"""Loss-curve plotting from training metrics CSVs.

The analog of the reference's loss-curve plotting in scripts/Finetune
(reference: SURVEY.md §2.9). Reads one or more metrics CSVs written by
--metrics_csv (core/logging.py MetricsLogger) and writes a PNG with
loss + EMA curves (and LR on a twin axis), one series per file. Falls
back to a text summary when matplotlib is unavailable.

Tolerates BOTH CSV schemas: the pre-telemetry columns
(timestamp,epoch,step,loss,avg_loss,lr,step_time_ms[,host_wait_ms],
hbm_mb) and the current one with grad_norm/tok_s/mfu — rows are read by
column NAME and missing columns default, so old runs keep plotting.

Usage:
  python tools/plot_loss.py out/metrics.csv [more.csv ...] \
      [--out loss_curve.png] [--title "..."]
"""

import argparse
import csv
import os
import sys


def read_metrics(path):
    steps, loss, avg, lr = [], [], [], []
    with open(path, newline="") as f:
        for row in csv.DictReader(f):
            # parse the whole row first, append only on full success — a
            # truncated tail row (killed training mid-write) must not
            # leave the series desynchronized or crash on float(None)
            try:
                s = int(row["step"])
                lo = float(row["loss"])
                av = float(row.get("avg_loss") or lo)
                r = float(row.get("lr") or 0.0)
            except (KeyError, ValueError, TypeError):
                continue
            steps.append(s)
            loss.append(lo)
            avg.append(av)
            lr.append(r)
    return steps, loss, avg, lr


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("csvs", nargs="+")
    ap.add_argument("--out", default="loss_curve.png")
    ap.add_argument("--title", default="training loss")
    args = ap.parse_args(argv)

    series = []
    for path in args.csvs:
        steps, loss, avg, lr = read_metrics(path)
        if not steps:
            print(f"warning: no rows in {path}", file=sys.stderr)
            continue
        name = os.path.splitext(os.path.basename(path))[0]
        series.append((name, steps, loss, avg, lr))
        print(f"{name}: {len(steps)} rows, loss {loss[0]:.4f} -> "
              f"{loss[-1]:.4f} (ema {avg[-1]:.4f})")
    if not series:
        return 1

    try:
        import matplotlib
        matplotlib.use("Agg")
        import matplotlib.pyplot as plt
    except Exception as e:
        print(f"matplotlib unavailable ({e}); text summary only",
              file=sys.stderr)
        return 0

    fig, ax = plt.subplots(figsize=(8, 4.5))
    ax2 = ax.twinx()
    for name, steps, loss, avg, lr in series:
        (line,) = ax.plot(steps, loss, alpha=0.3)
        ax.plot(steps, avg, color=line.get_color(), label=name)
        if any(lr):
            ax2.plot(steps, lr, color=line.get_color(), linestyle=":",
                     alpha=0.5)
    ax.set_xlabel("optimizer step")
    ax.set_ylabel("loss (faint: raw, solid: EMA)")
    ax2.set_ylabel("learning rate (dotted)")
    ax.set_title(args.title)
    ax.legend(loc="upper right")
    fig.tight_layout()
    fig.savefig(args.out, dpi=120)
    print(f"wrote {args.out}")
    return 0


if __name__ == "__main__":
    sys.exit(main())
