"""Merge and render per-host telemetry shards from a multi-host run.

Under multi-host every process writes its own host-stamped stream
(`core/telemetry.py`): the coordinator at `--telemetry_out`'s path, host
k at `<path>.host<k>` (DESIGN.md §14). This tool discovers the shard
set next to the given base path, validates every line against the shared
EVENT_SCHEMA, checks each shard's seq monotonicity and (host, seq)
uniqueness, merges the fleet timeline, and answers the pod questions the
single-stream report cannot: which host is slow (per-host step-time
percentiles), how far apart the fleet is (cross-host median skew, step
reach), and whether any host raised `straggler` or `hang` events.

Usage:
  python tools/fleet_report.py run.jsonl [--json]
  (run.jsonl.host1, run.jsonl.host2, ... are discovered automatically)
"""

from __future__ import annotations

import argparse
import glob
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from report_sections import (_fmt, add_format_flags,  # noqa: E402
                             checkpoint_lines,
                             checkpoint_summary, controller_entries,
                             controller_lines, controller_summary,
                             emit_output, goodput_lines, hang_entries,
                             hang_lines, load_events, memory_lines,
                             memory_summary, observability_lines,
                             observability_summary, percentile,
                             recovery_lines, recovery_summary,
                             serve_fleet_lines, serve_fleet_summary,
                             split_latest_run, straggler_entries,
                             straggler_lines)

from mobilefinetuner_tpu.core.telemetry import (controller_path,  # noqa: E402
                                                partial_goodput)


def discover_shards(base: str) -> dict:
    """{host_index: path} — the base path is host 0's stream (when it
    exists), `<base>.host<k>` the others. Hosts may be sparse (a dead
    worker that never wrote is itself a finding, reported as a gap)."""
    shards = {}
    if os.path.exists(base):
        shards[0] = base
    for p in glob.glob(glob.escape(base) + ".host*"):
        m = re.fullmatch(re.escape(base) + r"\.host(\d+)", p)
        if m:
            shards[int(m.group(1))] = p
    return shards


def shard_summary(host: int, events: list, n_invalid: int) -> dict:
    """Per-host rollup over one shard's validated events. A resumed
    shard whose LATEST run was killed scopes its stats/incidents to
    that run and withholds the prior run's clean run_end
    (telemetry_report's latest-run rule)."""
    truncated, latest = split_latest_run(events)
    scope = latest if truncated else events
    stats = [e for e in scope if e["event"] == "step_stats"]
    times = sorted(s["step_time_ms"] for s in stats)
    waits = [s["host_wait_ms"] for s in stats]
    seqs = [e["seq"] for e in events]
    # records are host-stamped since the fleet layer; pre-fleet shards
    # carry no host field (counted, not fatal)
    mismatched = sum(1 for e in events
                     if "host" in e and e["host"] != host)
    ends = [] if truncated else \
        [e for e in events if e["event"] == "run_end"]
    return {
        "host": host,
        "events": len(events),
        "invalid_lines": n_invalid,
        "seq_monotonic": all(a < b for a, b in zip(seqs, seqs[1:])),
        "host_stamp_mismatches": mismatched,
        "flushes": len(stats),
        "last_step": stats[-1]["step"] if stats else None,
        "step_time_ms": {
            "p50": percentile(times, 50),
            "p90": percentile(times, 90),
            "p99": percentile(times, 99),
        },
        "host_wait_frac": (sum(waits) / max(sum(times), 1e-9)
                           if stats else None),
        "stragglers": sum(1 for e in scope if e["event"] == "straggler"),
        "hangs": sum(1 for e in scope if e["event"] == "hang"),
        "anomalies": sum(1 for e in scope if e["event"] == "anomaly"),
        # snapshot/write split + coalesced-drop count (shared builder —
        # only the coordinator saves, but the rollup is per-shard so a
        # misconfigured worker writing checkpoints would show up)
        "checkpoints": checkpoint_summary(scope),
        # round-15 numerical-fault recovery rollup (shared builder):
        # skipped updates, rollbacks + steps lost, ckpt_verify failures
        "recovery": recovery_summary(scope),
        # round-16 memory-admission rollup (shared builder): mem_check
        # verdicts (est vs cap) + degradation-ladder decisions
        "memory": memory_summary(scope),
        # round-17 observability rollup (shared builder): span counts
        # by track + anomaly-triggered profile captures
        "observability": observability_summary(scope),
        "run_end": ({"steps": ends[-1]["steps"],
                     "wall_s": ends[-1]["wall_s"],
                     "exit": ends[-1]["exit"],
                     "goodput": ends[-1].get("goodput")}
                    if ends else None),
    }


def fleet_summary(shards: dict, controller=None) -> dict:
    """shards: {host: (events, n_invalid)} -> the merged fleet view.
    `controller`: the <base>.controller stream's validated events (the
    fleet controller's recovery timeline, DESIGN.md §18) — rendered
    next to the goodput buckets so recovery cost is a visible line."""
    per_host = {h: shard_summary(h, ev, bad)
                for h, (ev, bad) in sorted(shards.items())}
    # merged timeline: every shard's events ordered by wall time, ties
    # broken by (host, seq) — (host, seq) is the global event identity
    merged = sorted(
        (e for ev, _ in shards.values() for e in ev),
        key=lambda e: (e["t"], e.get("host", 0), e["seq"]))
    keys = [(e.get("host", 0), e["seq"]) for e in merged]
    dup_keys = len(keys) - len(set(keys))
    # incident lists follow each shard's latest-run scope (a prior
    # appended run's stragglers are not this post-mortem's)
    scoped = []
    for ev, _ in shards.values():
        trunc, latest = split_latest_run(ev)
        scoped.extend(latest if trunc else ev)
    scoped.sort(key=lambda e: (e["t"], e.get("host", 0), e["seq"]))
    # cross-host skew over the per-host MEDIAN step time: the headline
    # "is the fleet balanced" number
    medians = {h: s["step_time_ms"]["p50"] for h, s in per_host.items()
               if s["step_time_ms"]["p50"] is not None}
    skew = None
    if len(medians) >= 2:
        lo_h = min(medians, key=medians.get)
        hi_h = max(medians, key=medians.get)
        skew = {
            "fastest_host": lo_h, "fastest_ms": medians[lo_h],
            "slowest_host": hi_h, "slowest_ms": medians[hi_h],
            "abs_ms": round(medians[hi_h] - medians[lo_h], 3),
            "ratio": round(medians[hi_h] / max(medians[lo_h], 1e-9), 3),
        }
    reach = {h: s["last_step"] for h, s in per_host.items()}
    reached = [r for r in reach.values() if r is not None]
    # a host with a run_end is done; one without is crashed/running
    missing_end = sorted(h for h, s in per_host.items()
                         if s["run_end"] is None)
    # coordinator goodput when its run ENDED (None stays None — some
    # entry points carry no metered loop); only a run_end-less
    # coordinator shard gets the partial reconstruction
    goodput = None
    h0 = per_host.get(0)
    if h0 and h0["run_end"]:
        goodput = h0["run_end"]["goodput"]
    elif 0 in shards:
        # reconstruct over the LATEST run's slice of the coordinator
        # shard (a prior appended run's events are not this post-mortem)
        goodput = partial_goodput(split_latest_run(shards[0][0])[1])
    return {
        "hosts": len(per_host),
        "events": len(merged),
        "duplicate_host_seq_keys": dup_keys,
        "per_host": per_host,
        "skew": skew,
        "step_reach": {"min": min(reached) if reached else None,
                       "max": max(reached) if reached else None},
        # shared builders (telemetry_report) — the two reports render
        # these events identically by construction
        "stragglers": straggler_entries(scoped),
        "hangs": hang_entries(scoped),
        "hosts_missing_run_end": missing_end,
        "goodput": goodput,
        "controller": controller_summary(
            controller_entries(controller or [])),
        # round-22 serve-fleet section (shared builder): router decision
        # histogram + exact cross-shard rid accounting + per-replica
        # SLO rows; None unless host 0 is a serve_router stream
        "serve_fleet": serve_fleet_summary(
            {h: split_latest_run(ev)[1] for h, (ev, _) in
             shards.items()}),
    }


def print_fleet(s: dict):
    print(f"fleet: {s['hosts']} host shard(s), {s['events']} events"
          + (f"  [{s['duplicate_host_seq_keys']} DUPLICATE (host,seq)]"
             if s["duplicate_host_seq_keys"] else ""))
    for h, ph in s["per_host"].items():
        t = ph["step_time_ms"]
        flags = []
        if not ph["seq_monotonic"]:
            flags.append("SEQ NOT MONOTONIC")
        if ph["invalid_lines"]:
            flags.append(f"{ph['invalid_lines']} invalid lines")
        if ph["checkpoints"]["dropped"]:
            flags.append(f"{ph['checkpoints']['dropped']} ckpt snapshot(s) "
                         f"coalesced away")
        if ph["host_stamp_mismatches"]:
            flags.append(f"{ph['host_stamp_mismatches']} host-stamp "
                         f"mismatches")
        end = ph["run_end"]
        end_s = (f"exit={end['exit']} after {end['steps']} steps"
                 if end else "NO run_end (crashed or running)")
        wf = ph["host_wait_frac"]
        print(f"  host {h}: {ph['events']} events, "
              f"{ph['flushes']} flushes through step "
              f"{ph['last_step'] if ph['last_step'] is not None else '-'}; "
              f"step_time p50/p90/p99 = {_fmt(t['p50'])}/"
              f"{_fmt(t['p90'])}/{_fmt(t['p99'])} ms; "
              f"host_wait {_fmt(100 * wf if wf is not None else None, 1)}%; "
              f"{end_s}"
              + (f"  [{'; '.join(flags)}]" if flags else ""))
    if s["skew"]:
        k = s["skew"]
        print(f"  skew: host {k['slowest_host']} median "
              f"{_fmt(k['slowest_ms'])} ms vs host {k['fastest_host']} "
              f"{_fmt(k['fastest_ms'])} ms "
              f"({k['ratio']}x, +{_fmt(k['abs_ms'])} ms)")
    r = s["step_reach"]
    if r["min"] is not None and r["min"] != r["max"]:
        print(f"  step reach: min {r['min']} / max {r['max']} "
              f"(a lagging shard means a stalled or dead host)")
    for line in straggler_lines(s["stragglers"]) + hang_lines(s["hangs"]):
        print(line)
    # fleet checkpoint + recovery rollup (coordinator writes the
    # checkpoints and drives skip/rollback; shared renderers)
    h0 = s["per_host"].get(0)
    if h0:
        for line in checkpoint_lines(h0["checkpoints"]):
            print(line)
        for line in memory_lines(h0.get("memory")):
            print(line)
        for line in recovery_lines(h0.get("recovery")):
            print(line)
        for line in observability_lines(h0.get("observability")):
            print(line)
    if s["hosts_missing_run_end"]:
        print(f"  hosts without run_end: {s['hosts_missing_run_end']}")
    for line in goodput_lines(s["goodput"]):  # one shared renderer
        print(line)
    for line in serve_fleet_lines(s.get("serve_fleet")):
        print(line)
    # the recovery timeline renders NEXT TO the goodput buckets: the
    # two together answer "where did the fleet's wall-clock go"
    for line in controller_lines(s.get("controller")):
        print(line)


def main(argv=None) -> int:
    from report_sections import add_registry_flags, resolve_stream
    ap = argparse.ArgumentParser()
    ap.add_argument("jsonl", nargs="?", default="",
                    help="coordinator stream (--telemetry_out "
                         "base path; .host<k> shards are "
                         "discovered next to it); or use --run to "
                         "resolve it from the run registry")
    add_format_flags(ap)
    add_registry_flags(ap)
    args = ap.parse_args(argv)
    base = resolve_stream(args)
    paths = discover_shards(base)
    if not paths:
        print(f"error: no telemetry shards at {base}",
              file=sys.stderr)
        return 1
    shards = {}
    for h, p in paths.items():
        try:
            shards[h] = load_events(p)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    if not any(ev for ev, _ in shards.values()):
        print(f"error: no valid telemetry events in {sorted(paths.values())}",
              file=sys.stderr)
        return 1
    controller = None
    cpath = controller_path(base)
    if os.path.exists(cpath):
        try:
            controller, _ = load_events(cpath)
        except OSError:
            controller = None
    emit_output(fleet_summary(shards, controller=controller), args,
                print_fleet)
    return 0


if __name__ == "__main__":
    sys.exit(main())
