"""Longitudinal regression observatory (round 23, DESIGN.md §28).

Every bench/e2e/eval artifact this repo has ever committed is a point
on some metric's timeline — but until now each round's numbers were
compared only against the immediately previous artifact (bench_compare,
two files at a time). This tool ingests ALL of them — BENCH_*/E2E_*/
MMLU_*/MULTICHIP_*/MULTIHOST_*/TORCH_WALLCLOCK_*/ENERGY_* JSONs, serve
artifacts, telemetry JSONL streams, and the run registry
(core/run_registry.py) — into one normalized metrics store
(platform x config x metric x run), then runs a NOISE-AWARE regression
sentinel over each series:

  direction   inferred per metric name (bench_compare conventions:
              tok_s-ish higher-better, _ms/_mb-ish lower-better,
              everything else informational — trended, never gated);
  band        rolling median + MAD over the series' PRIOR points
              (robust: one historical outlier cannot shift the center
              the way a mean would), with a relative floor when MAD~0
              so a flat history does not make the band infinitely
              tight;
  z           signed so POSITIVE is worse: (latest - median)/(1.4826
              * MAD) times -direction;
  platform    split into the series key, so a CPU schema-pin artifact
              (synthetic harness proofs, BENCH_SERVE CPU rows) never
              gates a TPU perf series and vice versa.

Only the LATEST point of a series can regress — history is context,
not a defendant. A regression needs z > --z AND a worse-percent floor
(--pct_floor) AND at least --min_n prior points: all three, or the
verdict is "ok" (an under-observed series cannot gate).

Outputs: a markdown trend report (per-metric sparkline table, shared
renderer in tools/report_sections.py), a machine-readable JSON verdict,
`trend` events through the telemetry stream (--telemetry_out) which
feed the live mft_trend_* gauges (core/metrics_http.py,
--metrics_port), and exit code 2 naming run+metric when the sentinel
fires.

Usage:
  python tools/observatory.py --backfill                # committed history
  python tools/observatory.py --backfill --report TREND.md --json
  python tools/observatory.py --backfill EXTRA.json --z 4
  python tools/observatory.py --selfcheck               # tier-1: every
      committed artifact must ingest and every trend event must
      validate against EVENT_SCHEMA
Exit codes: 0 = ok, 1 = load/usage error, 2 = regression.
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
sys.path.insert(0, __file__.rsplit("/", 1)[0])

from bench_compare import _flatten, direction, load_rows  # noqa: E402
from report_sections import trend_lines  # noqa: E402

from mobilefinetuner_tpu.core.telemetry import validate_event  # noqa: E402

#: committed-artifact globs the backfill sweep ingests (repo root).
#: BASELINE.json is metadata prose, not a measurement — excluded.
BACKFILL_GLOBS = ("BENCH_*.json", "E2E_*.json", "MMLU_*.json",
                  "MULTICHIP_*.json", "MULTIHOST_*.json",
                  "TORCH_WALLCLOCK_*.json", "ENERGY_*.json")

#: MAD-to-sigma scale for a normal distribution
MAD_SCALE = 1.4826

#: timeline slots for artifacts with no `_rNN` round in the name:
#: HEAD_ORDER = the current working tree's own captures (BENCH_SUITE),
#: CANDIDATE_ORDER = explicitly-passed artifacts and registry runs —
#: the run under test, judged against everything before it.
HEAD_ORDER = 1 << 30
CANDIDATE_ORDER = 1 << 31

_ROUND_RE = re.compile(r"_r(\d+)\b")


def round_of(name: str):
    """Round ordinal from an artifact filename (`_r(\\d+)`), or None —
    un-numbered artifacts (BENCH_SUITE.json, registry runs) order
    AFTER every numbered round: they are the current head."""
    m = _ROUND_RE.search(os.path.basename(name))
    return int(m.group(1)) if m else None


def run_label(path: str):
    """Short run name for the trend table: rNN when the filename
    carries a round, else the file stem."""
    r = round_of(path)
    if r is not None:
        return f"r{r:02d}"
    return os.path.splitext(os.path.basename(path))[0]


def platform_of(data: dict) -> str:
    """Artifact platform for the series split. Explicit `device` /
    `device_kind` / `platform` fields win; a `synthetic: true` artifact
    is a CPU harness proof (that is what synthetic means in this repo —
    eval_mmlu --synthetic provenance, round 3 verdict); else unknown.
    CPU schema pins must never gate TPU perf series."""
    for key in ("device", "device_kind", "platform"):
        v = data.get(key)
        if isinstance(v, str) and v:
            v = v.lower()
            if "tpu" in v or re.search(r"\bv[2-6][ep]?\b", v):
                return "tpu"
            if "cpu" in v or "x86" in v or "arm" in v:
                return "cpu"
            return v
    if data.get("synthetic") is True:
        return "cpu"
    return "unknown"


def config_of(path: str) -> str:
    """Fallback config key for flat (row-less) artifacts: the filename
    stem minus the round suffix, lowercased — E2E_PPL_GEMMA_r03.json
    and _r05.json must land in the SAME series."""
    stem = os.path.splitext(os.path.basename(path))[0]
    return _ROUND_RE.sub("", stem).lower()


def _telemetry_points(path: str) -> list:
    """(config, platform, {metric: value}) rows from a telemetry JSONL
    stream: the run_end wall_s, the last step flush's throughput
    numbers, and any registry `run` records' wall_s — the stream
    becomes trendable without re-running anything."""
    from report_sections import load_events
    events, _bad = load_events(path)
    if not events:
        return []
    manifest = next((e for e in events if e.get("event") == "run_start"),
                    {})
    kind = str(manifest.get("device_kind", "")).lower()
    platform = "tpu" if "tpu" in kind else ("cpu" if kind else "unknown")
    cfg = config_of(path)
    metrics = {}
    for e in events:
        if e.get("event") == "step":
            for k in ("tok_s", "step_time_ms", "mfu"):
                if isinstance(e.get(k), (int, float)) \
                        and not isinstance(e.get(k), bool):
                    metrics[k] = float(e[k])
        elif e.get("event") == "run_end":
            if isinstance(e.get("wall_s"), (int, float)):
                metrics["wall_s"] = float(e["wall_s"])
    return [(cfg, platform, metrics)] if metrics else []


def ingest_file(path: str, order: int = None) -> list:
    """Normalized store rows from one artifact:
    {source, run, round, order, platform, config, metric, value}.
    Every artifact shape this repo produces loads — config-keyed rows
    via bench_compare.load_rows, flat report dicts as a single
    filename-keyed row, telemetry JSONL via the event reader.
    `order` places the artifact on the timeline explicitly (the
    candidate-run slot, AFTER all committed history); default is the
    filename round, un-numbered artifacts right after the last
    round (the current head)."""
    out = []
    rnd = round_of(path)
    run = run_label(path)
    if order is None:
        order = rnd if rnd is not None else HEAD_ORDER

    def add(cfg, platform, metrics):
        for metric, value in sorted(metrics.items()):
            out.append({"source": path, "run": run, "round": rnd,
                        "order": order, "platform": platform,
                        "config": cfg, "metric": metric,
                        "value": value})

    if path.endswith(".jsonl"):
        for cfg, platform, metrics in _telemetry_points(path):
            add(cfg, platform, metrics)
        return out
    with open(path) as f:
        txt = f.read()
    try:
        data = json.loads(txt)
    except json.JSONDecodeError:
        data = None
    platform = platform_of(data) if isinstance(data, dict) else "unknown"
    rows = load_rows(path)
    if rows:
        for cfg, metrics in sorted(rows.items()):
            add(cfg, platform, metrics)
    elif isinstance(data, dict):
        flat = _flatten(data)
        if flat:
            add(config_of(path), platform, flat)
    return out


def ingest_registry(reg) -> list:
    """Store rows from the run registry: each finalized record's wall_s
    becomes a trendable metric keyed by (kind, tool, fingerprint), and
    each record's on-disk artifacts are ingested under its run_id."""
    out = []
    for rec in reg.records():
        cfg = f"{rec.get('kind', '?')}_{rec.get('tool', '?')}"
        if rec.get("config_fingerprint"):
            cfg += "_" + rec["config_fingerprint"]
        if isinstance(rec.get("wall_s"), (int, float)):
            out.append({"source": reg.path, "run": rec["run_id"],
                        "round": None, "order": CANDIDATE_ORDER,
                        "platform": rec.get("platform") or "unknown",
                        "config": cfg, "metric": "wall_s",
                        "value": float(rec["wall_s"])})
        for art in rec.get("artifacts") or []:
            if os.path.exists(art):
                for row in ingest_file(art, order=CANDIDATE_ORDER):
                    row["run"] = rec["run_id"]
                    out.append(row)
    return out


def build_series(store: list) -> list:
    """Fold store rows into per-(platform, config, metric) series,
    ordered by round (None = head, last) then source name. One value
    per run: a re-captured run overwrites its earlier point (the
    registry may ingest the same artifact bench_compare already
    swept)."""
    groups = {}
    for row in store:
        key = (row["platform"], row["config"], row["metric"])
        groups.setdefault(key, {})[(
            row["order"], row["run"], row["source"])] = row["value"]
    series = []
    for (platform, cfg, metric), pts in sorted(groups.items()):
        ordered = sorted(pts.items())
        series.append({
            "platform": platform, "config": cfg, "metric": metric,
            "runs": [k[1] for k, _v in ordered],
            "values": [v for _k, v in ordered],
        })
    return series


def sentinel(series: list, z_threshold: float = 4.0, min_n: int = 4,
             rel_floor: float = 0.05, pct_floor: float = 10.0) -> list:
    """Noise-aware verdict per series, judging only the LATEST point.
    The band is median + MAD over the PRIOR points; the scale gets a
    relative floor (rel_floor * |median|) so a flat history cannot
    make any nonzero delta look like infinite sigmas. Regression needs
    direction-awareness, n >= min_n prior points, z > z_threshold AND
    worse_pct > pct_floor."""
    out = []
    for s in series:
        vals = s["values"]
        prior, latest = vals[:-1], vals[-1]
        d = direction(s["metric"])
        n = len(prior)
        verdict = dict(s)
        verdict.update({
            "n": len(vals), "value": latest,
            "direction": {1: "higher", -1: "lower", 0: None}[d],
            "median": None, "mad": None, "z": None, "regressed": False,
        })
        if prior:
            med = sorted(prior)[len(prior) // 2]
            mad = sorted(abs(v - med) for v in prior)[len(prior) // 2]
            scale = max(MAD_SCALE * mad, rel_floor * abs(med), 1e-12)
            z_raw = (latest - med) / scale
            worse_z = -z_raw * d
            worse_pct = (-(latest - med) * d / abs(med) * 100.0
                         if med else 0.0)
            verdict["median"] = med
            verdict["mad"] = mad
            verdict["z"] = round(worse_z if d else abs(z_raw), 3)
            verdict["regressed"] = bool(
                d and n >= min_n and worse_z > z_threshold
                and worse_pct > pct_floor)
        out.append(verdict)
    return out


def trend_events(verdicts: list) -> list:
    """`trend` event payloads (EVENT_SCHEMA) from sentinel verdicts —
    what rides --telemetry_out and feeds the mft_trend_* gauges."""
    events = []
    for v in verdicts:
        events.append({
            "metric": v["metric"], "config": v["config"],
            "platform": v["platform"], "value": v["value"],
            "median": v["median"], "mad": v["mad"], "z": v["z"],
            "direction": v["direction"], "regressed": v["regressed"],
            "run": v["runs"][-1] if v["runs"] else "?", "n": v["n"],
        })
    return events


def render_report(verdicts: list, store: list) -> list:
    """Markdown trend report lines: coverage header, the shared
    sparkline table, and a named line per regression."""
    rounds = sorted({r["round"] for r in store if r["round"] is not None})
    runs = sorted({r["run"] for r in store})
    span = (f"r{rounds[0]:02d}->r{rounds[-1]:02d}" if rounds else "head")
    lines = [
        "# Longitudinal trend report",
        "",
        f"{len(store)} points, {len(verdicts)} series, "
        f"{len(runs)} runs, rounds {span} "
        f"(+{len([r for r in runs if not r.startswith('r')])} head/"
        f"registry runs)",
        "",
    ]
    lines += trend_lines(verdicts)
    regressions = [v for v in verdicts if v["regressed"]]
    lines.append("")
    if regressions:
        lines.append(f"## {len(regressions)} REGRESSION(S)")
        for v in regressions:
            lines.append(
                f"- run {v['runs'][-1]} [{v['platform']}] "
                f"{v['config']}.{v['metric']}: {v['value']:g} vs "
                f"median {v['median']:g} (z={v['z']:g}, "
                f"{v['direction']}-better)")
    else:
        lines.append("no regressions: every gated series is inside "
                     "its noise band")
    return lines


def selfcheck(root: str) -> int:
    """Tier-1 schema pin: every committed artifact must ingest without
    error and yield points, and every trend event the sentinel would
    emit must validate against EVENT_SCHEMA. Returns the number of
    problems (0 = pass)."""
    problems = 0
    store = []
    for pat in BACKFILL_GLOBS:
        for path in sorted(glob.glob(os.path.join(root, pat))):
            try:
                rows = ingest_file(path)
            except Exception as e:
                print(f"SELFCHECK FAIL {path}: {type(e).__name__}: {e}")
                problems += 1
                continue
            if not rows:
                print(f"SELFCHECK FAIL {path}: no numeric points "
                      f"ingested")
                problems += 1
            store.extend(rows)
    verdicts = sentinel(build_series(store))
    for ev in trend_events(verdicts):
        # envelope keys (seq/t) are stamped by Telemetry.emit; supply
        # a minimal envelope so the payload contract is what's checked
        err = validate_event({"event": "trend", "seq": 0, "t": 0.0,
                              **ev})
        if err:
            print(f"SELFCHECK FAIL trend event {ev['config']}."
                  f"{ev['metric']}: {err}")
            problems += 1
    if not problems:
        print(f"selfcheck ok: {len(store)} points, "
              f"{len(verdicts)} series, every trend event "
              f"schema-valid")
    return problems


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="run registry + longitudinal regression sentinel")
    ap.add_argument("paths", nargs="*",
                    help="extra artifacts to ingest (any repo shape; "
                         ".jsonl = telemetry stream)")
    ap.add_argument("--backfill", action="store_true",
                    help="sweep --root for every committed artifact "
                         "(BENCH_*/E2E_*/MMLU_*/... ) so history "
                         "starts at r01")
    ap.add_argument("--root", default=".",
                    help="backfill sweep root (default: .)")
    ap.add_argument("--registry", default="",
                    help="run registry stream to ingest (core/"
                         "run_registry.py); default $MFT_RUN_REGISTRY")
    ap.add_argument("--store", default="",
                    help="write the normalized metrics store (JSONL, "
                         "one point per line) here")
    ap.add_argument("--report", default="",
                    help="write the markdown trend report here "
                         "(default: stdout)")
    ap.add_argument("--json", action="store_true",
                    help="print the machine-readable verdict instead "
                         "of the markdown report")
    ap.add_argument("--z", type=float, default=4.0,
                    help="robust-z gate threshold (default 4)")
    ap.add_argument("--min_n", type=int, default=4,
                    help="minimum PRIOR points before a series can "
                         "gate (default 4)")
    ap.add_argument("--rel_floor", type=float, default=0.05,
                    help="noise-scale floor as a fraction of |median| "
                         "(default 0.05)")
    ap.add_argument("--pct_floor", type=float, default=10.0,
                    help="minimum worse-percent for a regression "
                         "(default 10)")
    ap.add_argument("--telemetry_out", default="",
                    help="emit one `trend` event per series into this "
                         "telemetry stream (core/telemetry.py)")
    ap.add_argument("--metrics_port", type=int, default=0,
                    help="serve mft_trend_* gauges on this OpenMetrics "
                         "port after the sweep (core/metrics_http.py); "
                         "0 = off")
    ap.add_argument("--metrics_addr", default="127.0.0.1")
    ap.add_argument("--selfcheck", action="store_true",
                    help="tier-1 pin: ingest every committed artifact, "
                         "schema-validate every trend event; exit "
                         "nonzero on any problem")
    args = ap.parse_args(argv)

    if args.selfcheck:
        return 1 if selfcheck(args.root) else 0

    store = []
    if args.backfill:
        for pat in BACKFILL_GLOBS:
            for path in sorted(glob.glob(os.path.join(args.root, pat))):
                try:
                    store.extend(ingest_file(path))
                except Exception as e:
                    print(f"error: {path}: {type(e).__name__}: {e}",
                          file=sys.stderr)
                    return 1
    for path in args.paths:
        try:
            # explicit paths are the candidate run: latest on every
            # series they touch, judged against committed history
            store.extend(ingest_file(path, order=CANDIDATE_ORDER))
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
    from mobilefinetuner_tpu.core.run_registry import registry_from
    reg = registry_from(args.registry)
    if reg is not None and os.path.exists(reg.path):
        store.extend(ingest_registry(reg))
    if not store:
        print("error: nothing ingested (pass --backfill, --registry, "
              "or artifact paths)", file=sys.stderr)
        return 1

    if args.store:
        tmp = args.store + ".tmp"
        with open(tmp, "w") as f:
            for row in store:
                f.write(json.dumps(row) + "\n")
        os.replace(tmp, args.store)

    verdicts = sentinel(build_series(store), z_threshold=args.z,
                        min_n=args.min_n, rel_floor=args.rel_floor,
                        pct_floor=args.pct_floor)
    regressions = [v for v in verdicts if v["regressed"]]
    events = trend_events(verdicts)

    if args.telemetry_out:
        from mobilefinetuner_tpu.core.telemetry import Telemetry
        with Telemetry(args.telemetry_out) as tel:
            for ev in events:
                tel.emit("trend", **ev)

    report = render_report(verdicts, store)
    if args.report:
        tmp = args.report + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(report) + "\n")
        os.replace(tmp, args.report)
    if args.json:
        print(json.dumps({
            "points": len(store), "series": len(verdicts),
            "threshold_z": args.z, "verdicts": verdicts,
            "regressions": [
                {"run": v["runs"][-1], "platform": v["platform"],
                 "config": v["config"], "metric": v["metric"],
                 "value": v["value"], "median": v["median"],
                 "z": v["z"]} for v in regressions],
        }, indent=1))
    elif not args.report:
        print("\n".join(report))
    else:
        for v in regressions:
            print(f"REGRESSION: run {v['runs'][-1]} "
                  f"{v['config']}.{v['metric']} z={v['z']:g}")

    if args.metrics_port:
        from mobilefinetuner_tpu.core.metrics_http import (MetricsRegistry,
                                                           MetricsServer)
        mreg = MetricsRegistry()
        for ev in events:
            mreg.observe({"event": "trend", **ev})
        server = MetricsServer(mreg, port=args.metrics_port,
                               addr=args.metrics_addr)
        print(f"serving mft_trend_* on "
              f"http://{args.metrics_addr}:{server.port}/metrics "
              f"(ctrl-c to stop)")
        try:
            import time
            while True:
                time.sleep(3600)
        except KeyboardInterrupt:
            pass
    return 2 if regressions else 0


if __name__ == "__main__":
    sys.exit(main())
