"""Two-process multi-host smoke worker (launched by tests/test_distributed.py
and usable standalone for N-process validation on CPU or a real pod slice).

Each process brings up the jax.distributed runtime against a shared
coordinator, builds the DCN-aware hybrid mesh, FSDP-shards a tiny GPT-2's
frozen params over it, and runs TWO LoRA optimizer steps on a seeded global
batch (every process computes the same batch; parallel/distributed.py feeds
each process's addressable shards). A second phase runs tiny GEMMA-3 (GQA,
local/global interleave, V-sharded tied embed, vocab-parallel chunked CE)
across the same process boundaries — the riskiest DCN composition: the
CE's vocab psums crossing the hybrid mesh with global-array feeding, with
an in-program HLO assertion that the V-sharded table is never
all-gathered. Prints `MULTIHOST_OK loss=<x> gemma_loss=<y>` — the launcher
asserts every process prints the same losses, which can only happen if the
cross-process collectives actually ran.

Fleet-observability wiring (DESIGN.md §14): with a telemetry base path
(5th positional arg), every process writes its own host-stamped shard —
the coordinator at the path itself, process k at `<path>.host<k>` — with
run_start, per-phase step_stats (measured step ms), and run_end, so a
real pod smoke leaves exactly the shard set `tools/fleet_report.py`
merges. `--write_shards <path>` simulates the same two-host shard set in
ONE process on CPU (no jax.distributed needed) with a known straggler
skew baked in — the cheap merge-path proof tests/test_scripts.py runs.

Elastic-fleet wiring (DESIGN.md §18): `--inject kill:<step>` /
`--inject hang:<step>` fault-injects the REAL worker loop (die hard /
emit a `hang` event then exit 113 like the watchdog's abort) so a pod
smoke can exercise tools/fleet_controller.py against actual collectives.
`--sim_worker` is the CPU-runnable simulated-fleet worker the tier-1
controller e2e drives instead: an independent single-process "training"
loop (deterministic fake loss, real per-host telemetry shard, real
ATOMIC safetensors checkpoint each step, automatic resume from that
checkpoint, SIGTERM drain via core/preempt.py, and the same --inject
faults) — no cross-process collectives, because this container's jax
CPU backend cannot run them; everything the controller observes (shard
tails, exit codes, resume-from-checkpoint step counters) is real.

Usage (one line per process):
  python tools/multihost_smoke.py <coordinator> <num_procs> <proc_id> \
      [ndev] [telemetry_out] [--inject kill:1]
  python tools/multihost_smoke.py --write_shards out.jsonl
  python tools/multihost_smoke.py --sim_worker --host 1 --hosts 2 \
      --steps 10 --telemetry base.jsonl --ckpt w1.safetensors \
      [--step_ms 30] [--inject kill:4]
"""

import argparse
import os
import sys
import time

import numpy as np


def write_simulated_shards(base: str, hosts: int = 2,
                           flushes: int = 5) -> list:
    """Two(+) per-host shards with a deterministic skew: host 0 steps at
    ~40 ms, the last host at ~3x that, plus the coordinator-side
    `straggler` event the cadence gather would have fired and a goodput-
    carrying run_end on every shard. Returns the shard paths. Every
    record passes EVENT_SCHEMA (tests/test_scripts.py re-validates via
    fleet_report)."""
    from mobilefinetuner_tpu.core.telemetry import Telemetry, shard_path
    paths = []
    for h in range(hosts):
        p = shard_path(base, h)
        paths.append(p)
        slow = 3.0 if h == hosts - 1 else 1.0
        step_ms = 40.0 * slow
        with Telemetry(p, host=h) as tel:
            tel.emit("run_start", jax_version="sim", mesh_shape=None,
                     process_count=hosts, process_index=h,
                     device_kind="sim-cpu", device_count=hosts,
                     config={"simulated": True, "steps": flushes})
            for i in range(flushes):
                tel.emit("step_stats", step=i + 1, loss=3.0 - 0.1 * i,
                         ema=3.0 - 0.05 * i, lr=1e-4, grad_norm=0.5,
                         step_time_ms=step_ms + (i % 2),
                         host_wait_ms=1.0, slept_ms=0.0,
                         tok_s=1000.0 / slow, mfu=None, param_norm=10.0,
                         update_ratio=1e-3, nonfinite_count=0,
                         hbm_mb=100.0, queue_depth=2,
                         host_step_ms={str(k): 40.0 * (3.0 if
                                       k == hosts - 1 else 1.0)
                                       for k in range(hosts)})
            if h == 0:
                # what the straggler cadence fires on the coordinator
                tel.emit("straggler", step=flushes, slow_host=hosts - 1,
                         host_ms=step_ms * 3.0, fleet_ms=40.0, ratio=3.0)
            tel.emit("run_end", steps=flushes,
                     wall_s=flushes * step_ms / 1000.0, exit="ok",
                     goodput={"total_s": flushes * step_ms / 1000.0,
                              "step_s": flushes * step_ms / 1000.0,
                              "productive_frac": 1.0})
    return paths


def parse_inject(spec: str):
    """'kill:<step>' / 'hang:<step>' -> (mode, step); ('', -1) when off."""
    if not spec:
        return "", -1
    mode, _, step = spec.partition(":")
    if mode not in ("kill", "hang") or not step.isdigit():
        raise SystemExit(f"--inject must be kill:<step> or hang:<step>, "
                         f"got {spec!r}")
    return mode, int(step)


def fire_inject(mode: str, tel, step: int, marker: str) -> None:
    """Fault injection, ONCE per checkpoint lineage (the marker file
    makes a restarted/resumed worker run clean — the fault simulates a
    host incident, not a deterministic poison step). kill = die hard
    mid-run (no flush: exactly the truncated-tail shard a dead host
    leaves). hang = what the watchdog's abort path produces: a durable
    `hang` event, a flushed newline-terminated shard, exit 113."""
    if marker:
        if os.path.exists(marker):
            return
        with open(marker, "w") as f:
            f.write(f"{mode}@{step}\n")
    if mode == "kill":
        os._exit(86)
    tel.emit("hang", step=step, stall_s=120.0, deadline_s=60.0,
             stacks_file=(tel.path + ".stacks") if tel.path else "",
             device_probe="timeout", action="abort")
    tel.flush_tail()
    os._exit(113)


def sim_worker(args) -> None:
    """One simulated fleet worker (see module docstring). Exit codes
    mirror the real training CLIs: 0 = complete, EXIT_PREEMPTED (75) =
    SIGTERM drain with a durable checkpoint, 113 = hang abort, other =
    crash. The checkpoint is written ATOMICALLY every step through the
    production safetensors writer, so a kill at ANY instant leaves a
    loadable recovery point — the property the controller's
    resume-from-checkpoint restart depends on."""
    from mobilefinetuner_tpu.core.preempt import (EXIT_PREEMPTED,
                                                  PreemptionGuard)
    from mobilefinetuner_tpu.core.telemetry import Telemetry, shard_path
    from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                       save_safetensors)
    mode, inject_step = parse_inject(args.inject)
    marker = (args.ckpt + ".injected") if args.ckpt else ""
    start = 0
    if args.ckpt and os.path.exists(args.ckpt):
        start = int(SafeTensorsReader(args.ckpt).load_all()["step"][0])
    guard = PreemptionGuard().install()
    tel = Telemetry(shard_path(args.telemetry, args.host), host=args.host)
    tel.emit("run_start", jax_version="sim", mesh_shape=None,
             process_count=args.hosts, process_index=args.host,
             device_kind="sim-cpu", device_count=args.hosts,
             config={"sim_worker": True, "steps": args.steps,
                     "start_step": start, "inject": args.inject})
    t0 = time.time()
    for step in range(start, args.steps):
        if mode and step == inject_step:
            fire_inject(mode, tel, step, marker)
        time.sleep(args.step_ms / 1000.0)
        loss = 3.0 - 0.02 * step  # deterministic in the ABSOLUTE step:
        # a resumed trajectory continues the uninterrupted one exactly
        tel.emit("step_stats", step=step + 1, loss=loss, ema=loss,
                 lr=1e-4, grad_norm=0.5, step_time_ms=args.step_ms,
                 host_wait_ms=0.0, slept_ms=0.0, tok_s=1000.0, mfu=None,
                 param_norm=10.0, update_ratio=1e-3, nonfinite_count=0,
                 hbm_mb=1.0, queue_depth=None, host_step_ms=None)
        if args.ckpt:
            save_safetensors(args.ckpt, {
                "step": np.asarray([step + 1], np.int32),
                "w": np.full((8,), float(step + 1), np.float32)})
        if guard.triggered:
            tel.emit("preempt", step=step + 1,
                     signal=guard.signal_name or "SIGTERM")
            tel.emit("run_end", steps=step + 1 - start,
                     wall_s=round(time.time() - t0, 3), exit="preempted",
                     goodput=None, reason="preempted")
            tel.close()
            print(f"SIM_WORKER_PREEMPTED host={args.host} "
                  f"step={step + 1}")
            sys.exit(EXIT_PREEMPTED)
    tel.emit("run_end", steps=args.steps - start,
             wall_s=round(time.time() - t0, 3), exit="ok", goodput=None)
    tel.close()
    guard.uninstall()
    print(f"SIM_WORKER_OK host={args.host} steps={args.steps}")


def build_parser() -> argparse.ArgumentParser:
    ap = argparse.ArgumentParser(
        prog="multihost_smoke",
        description="multi-host smoke worker / simulated fleet worker")
    ap.add_argument("pos", nargs="*",
                    help="real-worker positionals: coordinator "
                         "num_procs proc_id [ndev] [telemetry_out]")
    ap.add_argument("--write_shards", default="",
                    help="write a simulated 2-host shard set and exit")
    ap.add_argument("--sim_worker", action="store_true",
                    help="run ONE simulated fleet worker (CPU, no "
                         "collectives) for tools/fleet_controller.py")
    ap.add_argument("--host", type=int, default=0)
    ap.add_argument("--hosts", type=int, default=1)
    ap.add_argument("--steps", type=int, default=10)
    ap.add_argument("--telemetry", default="")
    ap.add_argument("--ckpt", default="",
                    help="sim worker checkpoint path (atomic "
                         "safetensors; auto-resumed when present)")
    ap.add_argument("--step_ms", type=float, default=30.0)
    ap.add_argument("--resume", action="store_true",
                    help="accepted for controller cmd-template compat; "
                         "the sim worker auto-resumes from --ckpt")
    ap.add_argument("--inject", default="",
                    help="kill:<step> | hang:<step> — fire ONCE per "
                         "checkpoint lineage (marker file)")
    return ap


def main():
    args = build_parser().parse_args()
    if args.write_shards:
        for p in write_simulated_shards(args.write_shards):
            print(f"SHARD {p}")
        print("SHARDS_OK")
        return
    if args.sim_worker:
        sim_worker(args)
        return
    if len(args.pos) < 3:
        raise SystemExit("usage: multihost_smoke.py <coordinator> "
                         "<num_procs> <proc_id> [ndev] [telemetry_out]")
    coordinator, num_procs, proc_id = (args.pos[0], int(args.pos[1]),
                                       int(args.pos[2]))
    ndev = int(args.pos[3]) if len(args.pos) > 3 else 4
    telemetry_out = args.pos[4] if len(args.pos) > 4 else ""
    inject_mode, inject_at = parse_inject(args.inject)

    from mobilefinetuner_tpu.parallel.host_devices import force_host_devices
    force_host_devices(ndev)

    from mobilefinetuner_tpu.parallel import distributed as dist
    started = dist.initialize(coordinator=coordinator,
                              num_processes=num_procs, process_id=proc_id)
    assert started, "distributed runtime did not start"

    import dataclasses

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == num_procs, jax.process_count()
    assert len(jax.devices()) == num_procs * ndev

    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                               trainable_mask)
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
    from mobilefinetuner_tpu.parallel.mesh import (batch_sharding,
                                                   shard_batch, shard_params)
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   init_optimizer,
                                                   make_train_step)

    config = dataclasses.replace(GPT2Config.tiny(vocab_size=512),
                                 n_embd=64, n_head=2, n_positions=32,
                                 n_layer=2)
    mesh = dist.make_hybrid_mesh(data=num_procs, fsdp=ndev)
    assert mesh.shape == {"data": num_procs, "fsdp": ndev}

    # fleet telemetry: EVERY process writes its host-stamped shard (the
    # per-host contract tools/fleet_report.py merges)
    from mobilefinetuner_tpu.core.telemetry import (Telemetry,
                                                    run_manifest,
                                                    shard_path)
    tel = Telemetry.for_process(telemetry_out)
    tel.emit("run_start", **run_manifest(
        {"smoke": True, "num_procs": num_procs, "ndev": ndev}, mesh))
    t_run0 = time.time()

    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    params = shard_params(params, mesh, min_size=0)
    lora = init_lora_gpt2(config, LoRASpec(rank=2, alpha=4.0),
                          jax.random.PRNGKey(1))
    lora = jax.tree.map(
        lambda x: dist.device_put_global(
            x, jax.sharding.NamedSharding(mesh,
                                          jax.sharding.PartitionSpec())),
        lora)
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=2, lr=1e-3, grad_accum_steps=2,
                     schedule="constant", warmup_ratio=0.0)
    opt = init_optimizer(lora, tc, mask)

    def loss_fn(lora_t, p, mb):
        logits = gpt2.forward(config, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              lora=lora_t)
        return lm_cross_entropy_sum(logits, mb["labels"])

    step_fn = make_train_step(loss_fn, tc, mask=mask, donate=False)

    rng = np.random.default_rng(7)  # same seed on every process
    B = 2 * num_procs * ndev
    ids = rng.integers(0, config.vocab_size, (2 * B, 32)).astype(np.int32)
    batch = {"input_ids": ids, "attention_mask": np.ones_like(ids),
             "labels": ids}
    batch = shard_batch(batch, mesh)
    assert batch["input_ids"].sharding.spec == \
        jax.sharding.PartitionSpec(("data", "fsdp"))

    # once-per-lineage marker for pod fault injection: keyed off the
    # telemetry shard (the real worker has no checkpoint path) so a
    # controller-relaunched worker runs clean instead of re-dying at
    # the same step forever. Without a telemetry path the fault
    # re-fires every launch — fine for a bare two-process smoke, but a
    # controller drive needs the shard path anyway.
    inject_marker = (shard_path(telemetry_out, jax.process_index())
                     + ".injected") if telemetry_out else ""
    with mesh:
        losses = []
        for step in range(2):
            if inject_mode and step == inject_at:
                # pod fault injection: this process dies mid-collective
                # (kill) or reports-then-aborts like the watchdog
                # (hang) — what the fleet controller recovers from
                fire_inject(inject_mode, tel, step, marker=inject_marker)
            t0 = time.perf_counter()
            lora, opt, metrics = step_fn(lora, params, opt, batch,
                                         jnp.int32(step))
            losses.append(float(metrics["loss"]))  # host sync (global)
            step_ms = (time.perf_counter() - t0) * 1000
            tel.emit("step_stats", step=step + 1, loss=losses[-1],
                     ema=losses[-1], lr=1e-3,
                     grad_norm=float(metrics["grad_norm"]),
                     step_time_ms=step_ms, host_wait_ms=0.0,
                     slept_ms=0.0, tok_s=batch["input_ids"].size
                     / max(step_ms / 1000, 1e-9), mfu=None,
                     param_norm=None, update_ratio=None,
                     nonfinite_count=None, hbm_mb=0.0, queue_depth=None,
                     host_step_ms=None)
    loss = losses[-1]
    assert np.isfinite(loss), losses
    # convergence, not just finiteness: the optimizer stepped on the same
    # fixed batch, so the global loss must DECREASE
    assert losses[1] < losses[0], losses

    # checkpoint-path validation: gather the cross-process FSDP-sharded
    # frozen tree to host (collective; every process calls it) and check
    # a leaf's global shape survives the round trip
    gathered = dist.gather_to_host(params)
    # single-process (N=1) standalone runs get the tree back unchanged;
    # multi-process must yield host numpy for every leaf
    qkv = np.asarray(gathered["blocks"]["attn"]["qkv_w"])
    assert qkv.shape == (config.n_layer, config.n_embd, 3 * config.n_embd)
    assert np.isfinite(qkv).all()
    lora_h = dist.gather_to_host(lora)
    if jax.process_count() > 1:
        assert isinstance(gathered["blocks"]["attn"]["qkv_w"], np.ndarray)
        # replicated trainables gather via the fully-replicated fast path
        assert all(isinstance(x, np.ndarray)
                   for x in jax.tree.leaves(lora_h))
    # ---- Gemma phase: vocab-parallel CE across REAL process boundaries
    # (round-5 verdict item 4). The tied 2048-row embed V-shards over the
    # per-process fsdp axis; the CE's max/sum-exp/gold psums cross the
    # hybrid mesh; the compiled HLO must carry no full-table all-gather.
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.lora.lora import init_lora_gemma3
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum

    gcfg = Gemma3TextConfig(
        vocab_size=2048, hidden_size=64, intermediate_size=128,
        num_hidden_layers=3, num_attention_heads=4,
        num_key_value_heads=2, head_dim=16, max_position_embeddings=64,
        sliding_window=16, query_pre_attn_scalar=16.0,
        sliding_window_pattern=3)
    gparams = gemma3.init_params(gcfg, jax.random.PRNGKey(3))
    gparams = shard_params(gparams, mesh, min_size=0)
    assert gparams["embed"].sharding.spec[0] == "fsdp", \
        gparams["embed"].sharding  # the risky bit: V-sharded tied table
    glora = init_lora_gemma3(gcfg, LoRASpec(rank=2, alpha=4.0, init="peft"),
                             jax.random.PRNGKey(4))
    glora = jax.tree.map(
        lambda x: dist.device_put_global(
            x, jax.sharding.NamedSharding(mesh,
                                          jax.sharding.PartitionSpec())),
        glora)
    gmask = trainable_mask(glora)
    gopt = init_optimizer(glora, tc, gmask)

    def gemma_loss_fn(lora_t, p, mb):
        hidden = gemma3.hidden_states(
            gcfg, p, mb["input_ids"], attention_mask=mb["attention_mask"],
            lora=lora_t)
        return chunked_lm_cross_entropy_sum(
            hidden, p["embed"], mb["labels"], num_chunks=4, mesh=mesh)

    gstep = make_train_step(gemma_loss_fn, tc, mask=gmask, donate=False)
    gids = rng.integers(0, gcfg.vocab_size, (2 * B, 32)).astype(np.int32)
    gbatch = shard_batch({"input_ids": gids,
                          "attention_mask": np.ones_like(gids),
                          "labels": gids}, mesh)
    with mesh:
        gcomp = gstep.lower(glora, gparams, gopt, gbatch,
                            jnp.int32(0)).compile()
        from mobilefinetuner_tpu.core.xla_stats import shaped_all_gathers
        bad = shaped_all_gathers(gcomp, (gcfg.vocab_size, gcfg.hidden_size))
        assert not bad, ("full-table all-gather across processes:\n"
                         + "\n".join(bad[:3]))
        glosses = []
        for step in range(2):
            glora, gopt, gm = gstep(glora, gparams, gopt, gbatch,
                                    jnp.int32(step))
            glosses.append(float(gm["loss"]))
    assert np.isfinite(glosses[-1]), glosses
    assert glosses[1] < glosses[0], glosses

    tel.emit("run_end", steps=4, wall_s=round(time.time() - t_run0, 3),
             exit="ok", goodput=None)
    tel.close()
    print(f"MULTIHOST_OK loss={loss:.6f} gemma_loss={glosses[-1]:.6f} "
          f"proc={jax.process_index()}/{jax.process_count()}")


if __name__ == "__main__":
    main()
