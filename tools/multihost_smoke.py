"""Two-process multi-host smoke worker (launched by tests/test_distributed.py
and usable standalone for N-process validation on CPU or a real pod slice).

Each process brings up the jax.distributed runtime against a shared
coordinator, builds the DCN-aware hybrid mesh, FSDP-shards a tiny GPT-2's
frozen params over it, and runs TWO LoRA optimizer steps on a seeded global
batch (every process computes the same batch; parallel/distributed.py feeds
each process's addressable shards). Prints `MULTIHOST_OK loss=<x>` — the
launcher asserts both processes print the same loss, which can only happen
if the cross-process collectives actually ran.

Usage (one line per process):
  python tools/multihost_smoke.py <coordinator> <num_procs> <proc_id> [ndev]
"""

import sys

import numpy as np


def main():
    coordinator, num_procs, proc_id = (sys.argv[1], int(sys.argv[2]),
                                       int(sys.argv[3]))
    ndev = int(sys.argv[4]) if len(sys.argv) > 4 else 4

    from mobilefinetuner_tpu.parallel.host_devices import force_host_devices
    force_host_devices(ndev)

    from mobilefinetuner_tpu.parallel import distributed as dist
    started = dist.initialize(coordinator=coordinator,
                              num_processes=num_procs, process_id=proc_id)
    assert started, "distributed runtime did not start"

    import dataclasses

    import jax
    import jax.numpy as jnp

    assert jax.process_count() == num_procs, jax.process_count()
    assert len(jax.devices()) == num_procs * ndev

    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                               trainable_mask)
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
    from mobilefinetuner_tpu.parallel.mesh import (batch_sharding,
                                                   shard_batch, shard_params)
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   init_optimizer,
                                                   make_train_step)

    config = dataclasses.replace(GPT2Config.tiny(vocab_size=512),
                                 n_embd=64, n_head=2, n_positions=32,
                                 n_layer=2)
    mesh = dist.make_hybrid_mesh(data=num_procs, fsdp=ndev)
    assert mesh.shape == {"data": num_procs, "fsdp": ndev}

    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    params = shard_params(params, mesh, min_size=0)
    lora = init_lora_gpt2(config, LoRASpec(rank=2, alpha=4.0),
                          jax.random.PRNGKey(1))
    lora = jax.tree.map(
        lambda x: dist.device_put_global(
            x, jax.sharding.NamedSharding(mesh,
                                          jax.sharding.PartitionSpec())),
        lora)
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=2, lr=1e-3, grad_accum_steps=2,
                     schedule="constant", warmup_ratio=0.0)
    opt = init_optimizer(lora, tc, mask)

    def loss_fn(lora_t, p, mb):
        logits = gpt2.forward(config, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              lora=lora_t)
        return lm_cross_entropy_sum(logits, mb["labels"])

    step_fn = make_train_step(loss_fn, tc, mask=mask, donate=False)

    rng = np.random.default_rng(7)  # same seed on every process
    B = 2 * num_procs * ndev
    ids = rng.integers(0, config.vocab_size, (2 * B, 32)).astype(np.int32)
    batch = {"input_ids": ids, "attention_mask": np.ones_like(ids),
             "labels": ids}
    batch = shard_batch(batch, mesh)
    assert batch["input_ids"].sharding.spec == \
        jax.sharding.PartitionSpec(("data", "fsdp"))

    with mesh:
        losses = []
        for step in range(2):
            lora, opt, metrics = step_fn(lora, params, opt, batch,
                                         jnp.int32(step))
            losses.append(float(metrics["loss"]))  # host sync (global)
    loss = losses[-1]
    assert np.isfinite(loss), losses
    # convergence, not just finiteness: the optimizer stepped on the same
    # fixed batch, so the global loss must DECREASE
    assert losses[1] < losses[0], losses

    # checkpoint-path validation: gather the cross-process FSDP-sharded
    # frozen tree to host (collective; every process calls it) and check
    # a leaf's global shape survives the round trip
    gathered = dist.gather_to_host(params)
    # single-process (N=1) standalone runs get the tree back unchanged;
    # multi-process must yield host numpy for every leaf
    qkv = np.asarray(gathered["blocks"]["attn"]["qkv_w"])
    assert qkv.shape == (config.n_layer, config.n_embd, 3 * config.n_embd)
    assert np.isfinite(qkv).all()
    lora_h = dist.gather_to_host(lora)
    if jax.process_count() > 1:
        assert isinstance(gathered["blocks"]["attn"]["qkv_w"], np.ndarray)
        # replicated trainables gather via the fully-replicated fast path
        assert all(isinstance(x, np.ndarray)
                   for x in jax.tree.leaves(lora_h))
    print(f"MULTIHOST_OK loss={loss:.6f} "
          f"proc={jax.process_index()}/{jax.process_count()}")


if __name__ == "__main__":
    main()
