"""PyTorch/PEFT mirror for the alignment harness.

Counterpart of align/dump.py (reference: pytorch_alignment/
gpt2_lora_finetune.py + gemma_lora_finetune.py and the npy comparison flow
of train_lora_gemma.cpp:620-920): loads the SAME checkpoint, the SAME
dumped batch, and the SAME adapter (via the dump's PEFT export), recomputes
every dumped tensor with HF transformers + PEFT + torch.optim.AdamW, and
reports max abs/rel errors per tensor plus the N-step loss-curve gap.

Usage:
  python tools/align_torch_mirror.py --dump_dir DUMP [--tol 2e-3]

The model dir, family, and hyperparameters come from DUMP/meta.json.
Prints one JSON report line; exit 0 iff every tensor is within --tol
relative error (relative to the torch reference's max |value|).
"""

import argparse
import json
import os
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def load_dump(d):
    meta = json.load(open(os.path.join(d, "meta.json")))
    arr = lambda n: np.load(os.path.join(d, n + ".npy"))
    batch = {k: arr("batch_" + k)
             for k in ("input_ids", "attention_mask", "labels")}
    return meta, batch, arr


def build_model(meta):
    import torch
    from peft import PeftModel
    from transformers import AutoModelForCausalLM
    torch.manual_seed(0)
    model = AutoModelForCausalLM.from_pretrained(
        meta["model_dir"], torch_dtype=torch.float32,
        attn_implementation="eager")
    model = PeftModel.from_pretrained(model, meta["peft_dir"],
                                      is_trainable=True)
    model.eval()  # deterministic: all dropout off (align runs use p=0)
    return model


def block_modules(model, family):
    """Ordered per-layer block modules + the module path templates used by
    the PEFT export (lora/peft_io.py mapping tables)."""
    pat = re.compile(r"\.transformer\.h\.(\d+)$" if family == "gpt2"
                     else r"\.model\.layers\.(\d+)$")
    blocks = {}
    for name, mod in model.named_modules():
        m = pat.search(name)
        if m:
            blocks[int(m.group(1))] = mod
    return [blocks[i] for i in range(len(blocks))]


def lora_param(params_by_name, family, target, layer, which):
    """The torch Parameter for our (target, layer) A/B leaf."""
    from mobilefinetuner_tpu.lora.peft_io import (GEMMA_PEFT_MODULES,
                                                  GPT2_PEFT_MODULES)
    modules = GPT2_PEFT_MODULES if family == "gpt2" else GEMMA_PEFT_MODULES
    path = ("base_model.model." + modules[target].format(layer)
            + f".lora_{which}.default.weight")
    return params_by_name[path]


def stacked_lora(params_by_name, family, target, which, n_layers,
                 grad=False):
    """[L, ...] array in OUR layout (A [L,in,r], B [L,r,out]) from the
    torch per-layer [r,in]/[out,r] parameters (or their grads)."""
    outs = []
    for i in range(n_layers):
        p = lora_param(params_by_name, family, target, i, which)
        t = p.grad if grad else p.detach()
        outs.append(t.numpy().T)
    return np.stack(outs)


def rel_err(ours, ref):
    ref = np.asarray(ref, np.float32)
    ours = np.asarray(ours, np.float32)
    denom = max(float(np.max(np.abs(ref))), 1e-8)
    return float(np.max(np.abs(ours - ref))) / denom


def main(argv=None):
    import torch
    ap = argparse.ArgumentParser()
    ap.add_argument("--dump_dir", required=True)
    ap.add_argument("--tol", type=float, default=2e-3)
    args = ap.parse_args(argv)

    meta, batch, arr = load_dump(args.dump_dir)
    meta.setdefault("peft_dir", os.path.join(args.dump_dir, "peft"))
    family, L = meta["family"], meta["n_layers"]
    model = build_model(meta)
    blocks = block_modules(model, family)
    assert len(blocks) == L, (len(blocks), L)
    params_by_name = dict(model.named_parameters())

    acts = {}
    hooks = [blocks[0].register_forward_pre_hook(
        lambda mod, a: acts.__setitem__("embed", a[0].detach().numpy()))]
    for i, blk in enumerate(blocks):
        hooks.append(blk.register_forward_hook(
            (lambda i: lambda mod, a, out:
             acts.__setitem__(i, out[0].detach().numpy()))(i)))

    ids = torch.tensor(batch["input_ids"], dtype=torch.long)
    am = torch.tensor(batch["attention_mask"], dtype=torch.long)
    labels = torch.tensor(batch["labels"], dtype=torch.long)

    out = model(input_ids=ids, attention_mask=am, labels=labels)
    for h in hooks:
        h.remove()

    report = {"tensors": {}}

    def cmp(name, ours_file_or_arr, ref):
        ours = (arr(ours_file_or_arr)
                if isinstance(ours_file_or_arr, str) else ours_file_or_arr)
        report["tensors"][name] = round(rel_err(ours, ref), 6)

    cmp("act_embed", "act_embed", acts["embed"])
    for i in range(L):
        cmp(f"act_layer_{i:02d}", f"act_layer_{i:02d}", acts[i])
    cmp("logits", "logits", out.logits.detach().numpy())
    cmp("loss", "loss", out.loss.detach().numpy())

    # ---- adapter grads of the mean loss
    out.loss.backward()
    grads_dir = os.path.join(args.dump_dir, "grads", "blocks")
    for target in meta["targets"]:
        for which in ("A", "B"):
            ours = np.load(os.path.join(grads_dir, target,
                                        which + ".npy"))
            ref = stacked_lora(params_by_name, family, target, which, L,
                               grad=True)
            cmp(f"grad.{target}.{which}", ours, ref)

    # ---- N optimizer steps on the same batch: post-step adapter + curve.
    # coupled mode = L2-into-gradient decay, which is torch.optim.Adam's
    # weight_decay semantics; decoupled = torch.optim.AdamW.
    lora_params = [p for n, p in params_by_name.items()
                   if "lora_" in n and p.requires_grad]
    opt_cls = (torch.optim.Adam if meta.get("coupled_weight_decay")
               else torch.optim.AdamW)
    opt = opt_cls(lora_params, lr=meta["lr"], betas=(0.9, 0.999),
                  eps=1e-8, weight_decay=meta["weight_decay"])
    losses = []
    for s in range(meta["steps"]):
        if s > 0:
            opt.zero_grad()
            loss = model(input_ids=ids, attention_mask=am,
                         labels=labels).loss
            loss.backward()
        else:
            loss = out.loss  # grads already computed above
        losses.append(float(loss.detach()))
        if meta["clip_grad_norm"]:
            torch.nn.utils.clip_grad_norm_(lora_params,
                                           meta["clip_grad_norm"])
        opt.step()
        if s == 0:
            post_dir = os.path.join(args.dump_dir, "adapter_post",
                                    "blocks")
            for target in meta["targets"]:
                for which in ("A", "B"):
                    ours = np.load(os.path.join(post_dir, target,
                                                which + ".npy"))
                    ref = stacked_lora(params_by_name, family, target,
                                       which, L)
                    cmp(f"post_step.{target}.{which}", ours, ref)

    ours_losses = arr("losses")
    report["loss_curve"] = {
        "ours": [round(float(x), 6) for x in ours_losses],
        "torch": [round(x, 6) for x in losses],
        "max_abs_diff": round(float(np.max(np.abs(
            ours_losses - np.asarray(losses, np.float32)))), 6),
    }
    worst = max(report["tensors"].items(), key=lambda kv: kv[1])
    report["worst"] = {"tensor": worst[0], "rel_err": worst[1]}
    report["tol"] = args.tol
    report["pass"] = bool(worst[1] < args.tol
                          and report["loss_curve"]["max_abs_diff"]
                          < args.tol * 10)
    print(json.dumps(report))
    return 0 if report["pass"] else 1


if __name__ == "__main__":
    sys.exit(main())
