"""graftlint CLI: run the repo-invariant static-analysis rules
(mobilefinetuner_tpu/core/static_checks.py, DESIGN.md §24) over source
trees and report findings.

The rules encode the invariants eighteen rounds of hardening bought:
no host syncs reachable from the step loop, donated buffers never read
after dispatch, no untraced Python branches in jitted code, f32
accumulation pinned on adapter math, emit-site/EVENT_SCHEMA agreement,
and lock discipline in the threaded host subsystems. Intentional
exceptions are visible, reasoned suppressions:

    # graftlint: disable=sync-hazard(flush boundary: one get per flush)

Usage:
  python tools/graft_lint.py mobilefinetuner_tpu/
  python tools/graft_lint.py mobilefinetuner_tpu/ tools/ --format json
  python tools/graft_lint.py --rules emit-schema,lock-discipline pkg/
  python tools/graft_lint.py --list-rules

Exit codes (bench_compare convention): 0 = clean, 2 = unsuppressed
findings, 1 = usage/engine error (bad path, syntax error, unknown rule).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from mobilefinetuner_tpu.core.static_checks import (  # noqa: E402
    RULES, LintError, run_lint)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="repo-invariant static analysis (graftlint)")
    ap.add_argument("paths", nargs="*", help="files or directories to lint")
    ap.add_argument("--rules", default=None,
                    help="comma-separated rule subset (default: all)")
    ap.add_argument("--format", choices=("text", "json"), default="text",
                    help="finding output format")
    ap.add_argument("--show-suppressed", action="store_true",
                    help="also print suppressed findings (text mode)")
    ap.add_argument("--list-rules", action="store_true",
                    help="print the shipped rule catalog and exit")
    args = ap.parse_args(argv)

    if args.list_rules:
        for name in sorted(RULES):
            print(f"{name}: {RULES[name].doc}")
        return 0
    if not args.paths:
        ap.print_usage(sys.stderr)
        print("error: no paths given (or use --list-rules)",
              file=sys.stderr)
        return 1

    rules = None
    if args.rules:
        rules = [r.strip() for r in args.rules.split(",") if r.strip()]
    try:
        res = run_lint(args.paths, rules=rules)
    except LintError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1

    if args.format == "json":
        print(json.dumps(res.to_dict(), indent=1))
    else:
        for f in res.findings:
            print(f.render())
        if args.show_suppressed:
            for f in res.suppressed:
                print(f.render())
        print(f"graftlint: {res.files} file(s), "
              f"{len(res.rules)} rule(s), "
              f"{len(res.findings)} finding(s), "
              f"{len(res.suppressed)} suppressed")
    return 2 if res.findings else 0


if __name__ == "__main__":
    sys.exit(main())
