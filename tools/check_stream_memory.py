"""Peak-HBM proof for per-layer offload streaming (run on a real TPU).

Compiles the LoRA train loss+grad for a GPT-2-medium-shaped stack twice —
fully resident vs budget-0 streamed — and reports XLA's compiled memory
analysis. On TPU, host-placed arguments are billed to host memory and the
streamed program's device footprint is ~one layer of weights + activations;
this is the rebuild's analog of the reference's RSS benchmark for the
ParameterSharder (reference: scripts/Finetune/measure_rss.sh:22-42,
parameter_sharder.cpp:242-271 per-layer require()).

Prints one JSON line:
  {"ok": bool, "blocks_bytes": N, "resident": {...}, "streamed": {...}}

Used by tests/test_offload.py (subprocess, skipped when no TPU) and
runnable standalone:  python tools/check_stream_memory.py
"""

import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def main() -> int:
    if jax.devices()[0].platform == "cpu":
        print(json.dumps({"ok": False,
                          "reason": "cpu backend has no host/device "
                                    "memory-space accounting"}))
        return 2

    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gpt2
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
    from mobilefinetuner_tpu.parallel.mesh import (make_mesh,
                                                   replicated_sharding)
    from mobilefinetuner_tpu.parallel.offload import (OffloadConfig,
                                                      apply_placement,
                                                      plan_placement)

    config = GPT2Config(n_embd=512, n_layer=8, n_head=8, vocab_size=2048,
                        n_positions=64)
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    ocfg = OffloadConfig(enable=True, max_resident_bytes=0,
                         offload_dtype="float32", min_offload_size=1024)
    plan = plan_placement(params, ocfg)
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    shardings = jax.tree.map(lambda _: sh, params)
    placed = apply_placement(params, plan, shardings, ocfg)
    offload = (plan, shardings)

    ids = jnp.zeros((2, 32), jnp.int32)
    labels = jnp.zeros((2, 32), jnp.int32)
    spec = LoRASpec(rank=4, alpha=8.0, targets=["attn_qkv"], init="gpt2")
    lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(1))

    def make(off):
        def loss(lora_t, p):
            logits = gpt2.forward(config, p, ids, lora=lora_t, offload=off)
            s, w = lm_cross_entropy_sum(logits, labels)
            return s / w
        return jax.jit(jax.grad(loss))

    def stats(ma):
        return {"dev_args": int(ma.argument_size_in_bytes),
                "host_args": int(ma.host_argument_size_in_bytes),
                "temp": int(ma.temp_size_in_bytes),
                "output": int(ma.output_size_in_bytes)}

    res = stats(make(None).lower(lora, params).compile().memory_analysis())
    stm = stats(make(offload).lower(lora, placed).compile()
                .memory_analysis())

    blocks_bytes = sum(int(np.prod(x.shape)) * 4
                       for x in jax.tree.leaves(params["blocks"]))
    per_layer = blocks_bytes / config.n_layer
    dev_peak_res = res["dev_args"] + res["temp"]
    dev_peak_stm = stm["dev_args"] + stm["temp"]
    ok = (stm["dev_args"] < blocks_bytes / 10
          and stm["host_args"] > 0.8 * blocks_bytes
          and stm["temp"] < 3 * per_layer + 32 * 2 ** 20
          and dev_peak_stm < dev_peak_res / 2)
    print(json.dumps({"ok": bool(ok), "blocks_bytes": blocks_bytes,
                      "per_layer_bytes": int(per_layer),
                      "resident": res, "streamed": stm}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
