"""Host/device memory-boundedness proofs for the two streaming paths.

1. Peak-HBM proof for per-layer offload streaming (needs a real TPU):
   compiles the LoRA train loss+grad for a GPT-2-medium-shaped stack
   twice — fully resident vs budget-0 streamed — and reports XLA's
   compiled memory analysis. On TPU, host-placed arguments are billed to
   host memory and the streamed program's device footprint is ~one layer
   of weights + activations; this is the rebuild's analog of the
   reference's RSS benchmark for the ParameterSharder (reference:
   scripts/Finetune/measure_rss.sh:22-42, parameter_sharder.cpp:242-271
   per-layer require()).

2. Host-RAM proof for the async input pipeline (runs anywhere, CPU
   included): a streaming-mode dataset consumed through the bounded-queue
   background producer (data/prefetch.py) for hundreds of steps must keep
   the traced Python/numpy heap inside (resident token window) +
   (queue depth + lookahead) step batches + slack — i.e. the queue, not
   the epoch, bounds host memory.

Prints one JSON line:
  {"ok": bool, "queue": {...}, "blocks_bytes": N, "resident": {...},
   "streamed": {...}}    (offload keys replaced by "reason" off-TPU)

Used by tests/test_offload.py (subprocess, offload part skipped when no
TPU) and runnable standalone:  python tools/check_stream_memory.py
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax
import jax.numpy as jnp
import numpy as np


def check_queue_memory(steps: int = 120, warm_steps: int = 10,
                       depth: int = 4) -> dict:
    """Prove the prefetch queue keeps host memory bounded in streaming
    mode. An unbounded producer (or a queue that leaks consumed batches)
    would grow the heap by ~48 KB x `steps` here (~5.8 MB), ~2x past the
    asserted bound; the passing state is the resident window + at most
    depth+lookahead in-flight step batches (measured ~0.8 MB growth).
    Sized to stay cheap inside tests/test_offload's subprocess run."""
    import tempfile
    import tracemalloc
    import zlib

    from mobilefinetuner_tpu.cli.common import micro_batches
    from mobilefinetuner_tpu.data.prefetch import Prefetcher
    from mobilefinetuner_tpu.data.wikitext2 import (WT2Config,
                                                    WikiText2Dataset)

    B, S, accum = 8, 256, 2
    window_tokens = 20_000
    encode = lambda s: [zlib.crc32(w.encode()) % 50_000
                        for w in s.split()]
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as d:
        corpus = os.path.join(d, "wiki.train.tokens")
        with open(corpus, "w") as f:
            for _ in range(2000):
                n = int(rng.integers(8, 40))
                f.write(" ".join(f"w{rng.integers(0, 3000)}"
                                 for _ in range(n)) + "\n")
        cfg = WT2Config(seq_len=S, batch_size=B, seed=0, streaming=True,
                        window_tokens=window_tokens)
        ds = WikiText2Dataset(corpus, "train", cfg, encode, eos_id=1)
        stream = Prefetcher((b for _, b in micro_batches(ds, accum)),
                            depth=depth)
        try:
            tracemalloc.start()
            for _ in range(warm_steps):  # window populated, queue full
                next(stream)
            steady, _ = tracemalloc.get_traced_memory()
            tracemalloc.reset_peak()
            for _ in range(steps):
                next(stream)
            _, peak = tracemalloc.get_traced_memory()
        finally:
            stream.close()
            tracemalloc.stop()
    step_bytes = accum * B * S * 12  # i32 ids + f32 mask + i32 labels
    # window tokens resident as i32 + a re-tokenization list of python
    # ints in flight, + in-flight step batches, + fixed slack for
    # interpreter noise
    bound = (window_tokens * 40 + (depth + 4) * step_bytes
             + 2 * 2 ** 20)
    growth = peak - steady
    return {"ok": bool(growth < bound), "steps": steps,
            "steady_bytes": int(steady), "peak_growth_bytes": int(growth),
            "bound_bytes": int(bound), "step_bytes": step_bytes,
            "queue_depth": depth}


def check_rss_shed(steps: int = 60, depth: int = 4) -> dict:
    """Prove the producer's host-RSS guard (round 16, data/prefetch.py
    rss_limit_mb): under simulated memory pressure — an injected rss_fn
    reporting over-limit for a window of steps — the producer defers
    lookahead assembly (rss_sheds > 0, queue drains toward empty)
    instead of filling the bounded queue, then recovers to full depth
    when pressure clears, with the consumed batch sequence untouched.
    Pure host-side; runs anywhere, CPU included."""
    import itertools

    from mobilefinetuner_tpu.data.prefetch import Prefetcher

    def batches():
        for i in itertools.count():
            yield {"i": i, "payload": np.zeros(4096, np.int32)}

    # pressure window: over-limit between consumer step 15 and 35,
    # keyed off a shared cell the consumer advances
    seen = {"n": 0}
    limit = 100.0
    pressure = lambda: 999.0 if 15 <= seen["n"] < 35 else 0.0
    order = []
    depths_under_pressure = []
    with Prefetcher(batches(), depth=depth, rss_limit_mb=limit,
                    rss_fn=pressure) as stream:
        for _ in range(steps):
            b = next(stream)
            order.append(b["i"])
            seen["n"] += 1
            if 20 <= seen["n"] < 35:
                # settled pressure regime: the producer must be shed
                # (at most the one batch it held mid-build in flight)
                time.sleep(0.005)
                depths_under_pressure.append(stream.queue_depth())
        sheds = stream.rss_sheds
        # after pressure clears the producer must refill
        time.sleep(0.2)
        depth_after = stream.queue_depth()
    ok = (order == list(range(steps)) and sheds > 0
          and max(depths_under_pressure) <= 2
          and depth_after >= depth - 1)
    return {"ok": bool(ok), "sheds": int(sheds),
            "max_depth_under_pressure": max(depths_under_pressure),
            "depth_after_recovery": depth_after,
            "sequence_intact": order == list(range(steps))}


def main() -> int:
    queue = check_queue_memory()
    rss = check_rss_shed()
    if jax.devices()[0].platform == "cpu":
        # the offload half needs accelerator memory-space accounting; the
        # queue + rss halves have already run — surface their verdict in
        # the exit code (2 keeps test_offload's "no TPU" skip contract)
        print(json.dumps({"ok": False,
                          "reason": "cpu backend has no host/device "
                                    "memory-space accounting",
                          "queue": queue, "rss": rss}))
        return 2 if (queue["ok"] and rss["ok"]) else 1

    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gpt2
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
    from mobilefinetuner_tpu.parallel.mesh import (make_mesh,
                                                   replicated_sharding)
    from mobilefinetuner_tpu.parallel.offload import (OffloadConfig,
                                                      apply_placement,
                                                      plan_placement)

    config = GPT2Config(n_embd=512, n_layer=8, n_head=8, vocab_size=2048,
                        n_positions=64)
    params = gpt2.init_params(config, jax.random.PRNGKey(0))
    ocfg = OffloadConfig(enable=True, max_resident_bytes=0,
                         offload_dtype="float32", min_offload_size=1024)
    plan = plan_placement(params, ocfg)
    sh = replicated_sharding(make_mesh(1, 1, devices=jax.devices()[:1]))
    shardings = jax.tree.map(lambda _: sh, params)
    placed = apply_placement(params, plan, shardings, ocfg)
    offload = (plan, shardings)

    ids = jnp.zeros((2, 32), jnp.int32)
    labels = jnp.zeros((2, 32), jnp.int32)
    spec = LoRASpec(rank=4, alpha=8.0, targets=["attn_qkv"], init="gpt2")
    lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(1))

    def make(off):
        def loss(lora_t, p):
            logits = gpt2.forward(config, p, ids, lora=lora_t, offload=off)
            s, w = lm_cross_entropy_sum(logits, labels)
            return s / w
        return jax.jit(jax.grad(loss))

    def stats(ma):
        return {"dev_args": int(ma.argument_size_in_bytes),
                "host_args": int(ma.host_argument_size_in_bytes),
                "temp": int(ma.temp_size_in_bytes),
                "output": int(ma.output_size_in_bytes)}

    res = stats(make(None).lower(lora, params).compile().memory_analysis())
    stm = stats(make(offload).lower(lora, placed).compile()
                .memory_analysis())

    blocks_bytes = sum(int(np.prod(x.shape)) * 4
                       for x in jax.tree.leaves(params["blocks"]))
    per_layer = blocks_bytes / config.n_layer
    dev_peak_res = res["dev_args"] + res["temp"]
    dev_peak_stm = stm["dev_args"] + stm["temp"]
    ok = (stm["dev_args"] < blocks_bytes / 10
          and stm["host_args"] > 0.8 * blocks_bytes
          and stm["temp"] < 3 * per_layer + 32 * 2 ** 20
          and dev_peak_stm < dev_peak_res / 2
          and queue["ok"] and rss["ok"])
    print(json.dumps({"ok": bool(ok), "queue": queue, "rss": rss,
                      "blocks_bytes": blocks_bytes,
                      "per_layer_bytes": int(per_layer),
                      "resident": res, "streamed": stm}))
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
