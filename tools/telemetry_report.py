"""Render a run-telemetry JSONL stream into a human summary.

Reads the --telemetry_out stream (core/telemetry.py event taxonomy) and
prints what an operator asks after a run: how fast was it (step-time
percentiles, tokens/s, MFU trend), where did the time go (host-wait
fraction, throttle sleeps, compile), and was it healthy (anomalies,
nonfinite gradients, exit status). Every line is validated against the
shared EVENT_SCHEMA; invalid lines are counted, not fatal (a crashed
writer may leave one truncated tail line).

Usage:
  python tools/telemetry_report.py run.jsonl [--json]
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from mobilefinetuner_tpu.core.telemetry import (partial_goodput,
                                                validate_event)


def percentile(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return None
    i = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def load_events(path):
    """(events, n_invalid): valid events in file order."""
    events, bad = [], 0
    with open(path, "rb") as f:
        for raw in f:
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw)
            except (json.JSONDecodeError, UnicodeDecodeError):
                bad += 1
                continue
            if validate_event(rec) is None:
                events.append(rec)
            else:
                bad += 1
    return events, bad


def split_latest_run(events):
    """(truncated, latest_run_events): a resumed stream appends runs, so
    'is there any run_end' is the wrong truncation test — run 1 may have
    ended cleanly while the appended run 2 was SIGKILLed. The post-mortem
    subject is the LATEST run: truncated iff its run_start has no
    following run_end; the returned slice is that run's events (the whole
    stream when nothing is truncated)."""
    idx_start = max((i for i, e in enumerate(events)
                     if e.get("event") == "run_start"), default=-1)
    idx_end = max((i for i, e in enumerate(events)
                   if e.get("event") == "run_end"), default=-1)
    truncated = idx_start > idx_end
    return truncated, (events[idx_start:]
                       if truncated and idx_start >= 0 else events)


def summarize(events, n_invalid=0) -> dict:
    truncated, latest = split_latest_run(events)
    # a truncated stream's post-mortem subject is the LATEST run: stats
    # and incident lists over the whole file would attribute an earlier
    # appended run's stragglers/anomalies/percentiles to the killed run
    scope = latest if truncated else events
    by = {}
    for e in scope:
        by.setdefault(e["event"], []).append(e)
    runs_all = [e for e in events if e["event"] == "run_start"]
    stats = by.get("step_stats", [])
    times = sorted(s["step_time_ms"] for s in stats)
    waits = [s["host_wait_ms"] for s in stats]
    mfus = [s["mfu"] for s in stats if s.get("mfu") is not None]
    toks = [s["tok_s"] for s in stats]
    nonfinite = sum(s.get("nonfinite_count") or 0 for s in stats)
    runs = runs_all  # manifest/run count span the WHOLE stream
    ends = by.get("run_end", [])
    seqs = [e["seq"] for e in events]
    out = {
        "events": len(events),
        "invalid_lines": n_invalid,
        "seq_monotonic": all(a < b for a, b in zip(seqs, seqs[1:])),
        "runs": len(runs),
        "manifest": (lambda m: {
            "device_kind": m["device_kind"],
            "device_count": m["device_count"],
            "process_count": m["process_count"],
            "mesh_shape": m["mesh_shape"],
            "jax_version": m["jax_version"],
        })(runs[-1]) if runs else None,
        "compile": [{"step": c["step"], "wall_s": c["wall_s"],
                     "flops": c.get("flops"),
                     "peak_hbm_mb": c.get("peak_hbm_mb")}
                    for c in by.get("compile", [])],
        "step_stats": {
            "flushes": len(stats),
            "last_step": stats[-1]["step"] if stats else None,
            "step_time_ms": {
                "p50": percentile(times, 50),
                "p90": percentile(times, 90),
                "p99": percentile(times, 99),
            },
            # fraction of step time the loop sat blocked on the input
            # pipeline — the host/device breakdown
            "host_wait_frac": (sum(waits) / max(sum(times), 1e-9)
                               if stats else None),
            "tok_s": {"mean": sum(toks) / len(toks) if toks else None,
                      "last": toks[-1] if toks else None},
            "mfu": {"first": mfus[0] if mfus else None,
                    "last": mfus[-1] if mfus else None,
                    "mean": sum(mfus) / len(mfus) if mfus else None},
            "loss": {"first": stats[0]["loss"] if stats else None,
                     "last": stats[-1]["loss"] if stats else None,
                     "ema_last": stats[-1]["ema"] if stats else None},
            "nonfinite_grad_elements": nonfinite,
        },
        # throttle events mark DECISION CHANGES; the actual time slept
        # accumulates per flush interval in step_stats.slept_ms
        "throttle": {
            "decisions": len(by.get("throttle", [])),
            "total_sleep_ms": sum(s.get("slept_ms") or 0 for s in stats),
        },
        "anomalies": [{"step": a["step"], "kind": a["kind"],
                       "loss": a["loss"], "zscore": a.get("zscore")}
                      for a in by.get("anomaly", [])],
        "evals": [{"step": e["step"], "loss": e["loss"], "ppl": e["ppl"],
                   "macro_accuracy": e.get("macro_accuracy")}
                  for e in by.get("eval", [])],
        "checkpoints": checkpoint_summary(scope),
        "recovery": recovery_summary(scope),
        "memory": memory_summary(scope),
        "observability": observability_summary(scope),
        "requests": request_summary(scope),
        "tenants": tenant_summary(scope),
        "serve": serve_stats_summary(scope),
        "routing": route_summary(scope),
        "stragglers": straggler_entries(scope),
        "hangs": hang_entries(scope),
        # a killed LATEST run leaves no run_end after its run_start (a
        # prior appended run's clean run_end must not mask it): report
        # the truncation with the last step the stream DID see instead
        # of pretending nothing ran. A truncated stream's stale run_end
        # (from the earlier run) is withheld — rendering it as current
        # is exactly the post-mortem trap.
        "run_end": ({"steps": ends[-1]["steps"],
                     "wall_s": ends[-1]["wall_s"],
                     "exit": ends[-1]["exit"]}
                    if ends and not truncated else None),
        "truncated": truncated,
        "last_seen_step": max(
            (e.get("step") for e in latest
             if isinstance(e.get("step"), int)), default=None),
        # goodput: the writer-side buckets when the latest run ENDED
        # (None stays None — e.g. the eval CLIs have no metered loop;
        # that is not a truncation); a truncated run gets the partial
        # reconstruction over ITS OWN slice of the stream
        "goodput": (ends[-1].get("goodput") if ends and not truncated
                    else partial_goodput(latest)),
    }
    return out


def _fmt(v, nd=2):
    return "-" if v is None else f"{v:.{nd}f}"


def checkpoint_summary(events) -> dict:
    """Roll up `checkpoint`/`ckpt_dropped` events with the round-10
    snapshot/write split (io/async_ckpt.py): blocking_s is what the step
    loop actually stalled (wall_s — snapshot only under --async_save),
    write_s/bytes/mb_s the background write cost that overlapped compute,
    dropped the snapshots coalesced away by the depth-1 writer queue.
    ONE builder shared with tools/fleet_report.py. Pre-async streams
    (step/final/wall_s only) still summarize: the split fields are
    optional on read."""
    cks = [e for e in events if e.get("event") == "checkpoint"]
    mbs = [c["mb_s"] for c in cks if c.get("mb_s")]
    return {
        "count": len(cks),
        "async": sum(1 for c in cks if c.get("async")),
        "blocking_s": round(sum(c["wall_s"] for c in cks), 4),
        "write_s": round(sum(c.get("write_ms") or 0.0
                             for c in cks) / 1000.0, 4),
        "bytes": sum(c.get("bytes") or 0 for c in cks),
        "mb_s_mean": (round(sum(mbs) / len(mbs), 2) if mbs else None),
        "dropped": sum(1 for e in events
                       if e.get("event") == "ckpt_dropped"),
    }


def checkpoint_lines(ck) -> list:
    """Render a checkpoint_summary dict (shared with fleet_report)."""
    if not ck or not (ck["count"] or ck["dropped"]):
        return []
    line = (f"  checkpoints: {ck['count']} ({ck['async']} async), "
            f"blocking {ck['blocking_s']:.2f}s")
    if ck["write_s"]:
        line += (f", background write {ck['write_s']:.2f}s"
                 + (f" ({ck['bytes'] / 2**20:.1f} MB"
                    + (f" @ {ck['mb_s_mean']:.1f} MB/s" if ck["mb_s_mean"]
                       else "") + ")" if ck["bytes"] else ""))
    if ck["dropped"]:
        line += f", {ck['dropped']} snapshot(s) coalesced away"
    return [line]


def recovery_summary(events) -> dict:
    """Roll up the round-15 numerical-fault recovery events (DESIGN.md
    §20): skipped-update count (sum of step_stats.skipped — the
    in-jit guard's identity steps), every `rollback` decision with its
    steps-lost recovery cost, and the `ckpt_verify` verdicts (failures
    listed with the mismatch reason). None when the stream carries
    none of the three — ONE builder shared with tools/fleet_report.py
    like the checkpoint/straggler/hang entries."""
    stats = [e for e in events if e.get("event") == "step_stats"]
    skipped = sum(e.get("skipped") or 0 for e in stats)
    rollbacks = [{"step": e["step"], "reason": e["reason"],
                  "ok": e["ok"], "to_step": e.get("to_step"),
                  "steps_lost": e.get("steps_lost"),
                  "ckpt": e.get("ckpt"),
                  "budget_left": e.get("budget_left")}
                 for e in events if e.get("event") == "rollback"]
    verifies = [e for e in events if e.get("event") == "ckpt_verify"]
    failures = [{"path": e["path"], "reason": e.get("reason"),
                 "step": e.get("step")}
                for e in verifies if not e.get("ok")]
    if not (skipped or rollbacks or verifies):
        return None
    return {
        "skipped_steps": skipped,
        "rollbacks": rollbacks,
        "steps_lost": sum(r["steps_lost"] or 0 for r in rollbacks
                          if r["ok"]),
        "ckpt_verified": sum(1 for e in verifies if e.get("ok")),
        "ckpt_verify_failures": failures,
    }


def recovery_lines(r) -> list:
    """Render a recovery_summary (shared with fleet_report)."""
    if not r:
        return []
    lines = [f"  recovery: {r['skipped_steps']} skipped update(s), "
             f"{sum(1 for x in r['rollbacks'] if x['ok'])} rollback(s) "
             f"({r['steps_lost']} step(s) lost), "
             f"{r['ckpt_verified']} ckpt verification(s), "
             f"{len(r['ckpt_verify_failures'])} failure(s)"]
    for x in r["rollbacks"]:
        if x["ok"]:
            lines.append(
                f"    ROLLBACK ({x['reason']}) @ step {x['step']} -> "
                f"{x['to_step']} ({x['steps_lost']} lost, budget left "
                f"{x['budget_left']})")
        else:
            lines.append(
                f"    ROLLBACK WANTED ({x['reason']}) @ step "
                f"{x['step']} but not possible (no verified "
                f"checkpoint / budget exhausted)")
    for f in r["ckpt_verify_failures"]:
        lines.append(f"    CKPT REJECTED: {f['path']} ({f['reason']})")
    return lines


def memory_summary(events) -> dict:
    """Roll up the round-16 memory-admission events (DESIGN.md §21):
    every `mem_check` verdict (est vs cap, the cap_frac headroom
    number) and every `degrade` ladder decision. None when the stream
    carries neither — ONE builder shared with tools/fleet_report.py
    like the checkpoint/recovery sections."""
    checks = [e for e in events if e.get("event") == "mem_check"]
    degrades = [e for e in events if e.get("event") == "degrade"]
    if not (checks or degrades):
        return None
    last = checks[-1] if checks else None
    row = lambda c: {"phase": c.get("phase"), "est_mb": c.get("est_mb"),
                     "cap_mb": c.get("cap_mb"), "verdict": c["verdict"],
                     "cap_frac": c.get("cap_frac")}
    return {
        "checks": [row(c) for c in checks],
        "final": row(last) if last else None,
        "over": sum(1 for c in checks if c["verdict"] == "over"),
        "degrades": [{"step": d.get("step"), "rung": d["rung"],
                      "from": d.get("from"), "to": d.get("to"),
                      "est_mb": d.get("est_mb")} for d in degrades],
    }


def memory_lines(m) -> list:
    """Render a memory_summary (shared with fleet_report)."""
    if not m:
        return []
    bits = []
    f = m["final"]
    if f:
        bits.append(f"est {_fmt(f['est_mb'], 0)} MB vs cap "
                    f"{_fmt(f['cap_mb'], 0)} MB"
                    + (f" ({100 * f['cap_frac']:.0f}% of cap)"
                       if f.get("cap_frac") else "")
                    + f", verdict {f['verdict']}")
    if m["over"]:
        bits.append(f"{m['over']} over-capacity check(s)")
    if m["degrades"]:
        bits.append(f"{len(m['degrades'])} ladder rung(s)")
    lines = ["  memory: " + "; ".join(bits)]
    for d in m["degrades"]:
        lines.append(
            f"    DEGRADE {d['rung']}: {d['from']} -> {d['to']}"
            + (f" (est {d['est_mb']:.0f} MB over)"
               if d.get("est_mb") else "")
            + (f" @ step {d['step']}" if d.get("step") is not None
               else " @ preflight"))
    return lines


def observability_summary(events) -> dict:
    """Roll up the round-17 live-observability events (DESIGN.md §22):
    span count by track (the timeline's shape at a glance — the spans
    themselves belong in tools/trace_export.py, not a text report) and
    every anomaly-triggered `profile_capture` with its trigger and
    on-disk path. None when the stream carries neither — ONE builder
    shared with tools/fleet_report.py like the other sections."""
    spans = [e for e in events if e.get("event") == "span"]
    caps = [e for e in events if e.get("event") == "profile_capture"]
    if not (spans or caps):
        return None
    by_track = {}
    for s in spans:
        by_track[s["track"]] = by_track.get(s["track"], 0) + 1
    return {
        "spans": len(spans),
        "span_tracks": by_track,
        "profile_captures": [{"step": c["step"],
                              "trigger": c["trigger"],
                              "path": c["path"],
                              "budget_left": c.get("budget_left")}
                             for c in caps],
    }


def observability_lines(o) -> list:
    """Render an observability_summary (shared with fleet_report)."""
    if not o:
        return []
    lines = []
    if o["spans"]:
        tracks = ", ".join(f"{k} {v}" for k, v in
                           sorted(o["span_tracks"].items())[:6])
        more = len(o["span_tracks"]) - 6
        lines.append(f"  spans: {o['spans']} across "
                     f"{len(o['span_tracks'])} track(s) ({tracks}"
                     + (f", +{more} more" if more > 0 else "") + ")"
                     + " — export with tools/trace_export.py")
    for c in o["profile_captures"]:
        lines.append(f"  PROFILE CAPTURED @ step {c['step']} "
                     f"({c['trigger']}): {c['path']} "
                     f"(budget left {c['budget_left']})")
    return lines


def tenant_summary(events) -> dict:
    """Per-tenant roll-up for the multi-tenant training engine
    (multitenant/engine.py, DESIGN.md §23): one row per adapter job from
    its `tenant` lifecycle events plus the LAST step_stats `tenants`
    section — steps reached vs budget, final loss, cumulative tokens,
    host-wait attribution, lifecycle outcome, and the saved artifact.
    None when the stream carries no multi-tenant traffic."""
    tev = [e for e in events if e.get("event") == "tenant"]
    stats = [e for e in events if e.get("event") == "step_stats"
             and e.get("tenants")]
    if not tev and not stats:
        return None
    rows: dict = {}
    for e in tev:
        r = rows.setdefault(e["name"], {"name": e["name"]})
        r["status"] = e["phase"]
        r["slot"] = e["slot"]
        r["step"] = e["step"]
        r["job_steps"] = e.get("job_steps")
        if e.get("loss") is not None:
            r["loss"] = e["loss"]
        if e.get("tokens") is not None:
            r["tokens"] = e["tokens"]
        if e.get("phase") in ("save", "finish") and e.get("path"):
            r["path"] = e["path"]
    if stats:
        for name, t in stats[-1]["tenants"].items():
            r = rows.setdefault(name, {"name": name})
            r.setdefault("status", "active")
            for k in ("slot", "step", "loss", "tokens", "wait_ms"):
                if t.get(k) is not None:
                    r[k] = t[k]
    order = {"finish": 0, "cancel": 1}
    return {
        "jobs": len(rows),
        "finished": sum(1 for r in rows.values()
                        if r.get("status") == "finish"),
        "cancelled": sum(1 for r in rows.values()
                         if r.get("status") == "cancel"),
        "rows": sorted(rows.values(),
                       key=lambda r: (order.get(r.get("status"), 2),
                                      r["name"])),
    }


def tenant_lines(t) -> list:
    if not t:
        return []
    lines = [f"  tenants: {t['jobs']} job(s), {t['finished']} finished"
             + (f", {t['cancelled']} cancelled" if t["cancelled"]
                else "")]
    for r in t["rows"]:
        budget = (f"/{r['job_steps']}" if r.get("job_steps") is not None
                  else "")
        bits = [f"    {r['name']}: {r.get('status', '?')} @ step "
                f"{r.get('step', '?')}{budget}"]
        if r.get("loss") is not None:
            bits.append(f"loss {_fmt(r['loss'], 4)}")
        if r.get("tokens") is not None:
            bits.append(f"{r['tokens']} tok")
        if r.get("wait_ms"):
            bits.append(f"wait {_fmt(r['wait_ms'], 1)} ms")
        if r.get("path"):
            bits.append(f"-> {r['path']}")
        lines.append(", ".join(bits))
    return lines


def request_summary(events) -> dict:
    """Serving SLOs from the per-request `request` lifecycle events
    (serve/engine.py): TTFT/TPOT percentiles over FINISHED requests,
    sustained req/s over the stream's observed request span, and —
    round 14 — the failure-mode counters and rates (reject / timeout /
    error over submitted) a robustness policy is judged by. None when
    the stream carries no serving traffic."""
    reqs = [e for e in events if e.get("event") == "request"]
    if not reqs:
        return None
    fins = [e for e in reqs if e.get("phase") == "finish"]
    ttfts = sorted(e["ttft_ms"] for e in fins
                   if e.get("ttft_ms") is not None)
    tpots = sorted(e["tpot_ms"] for e in fins
                   if e.get("tpot_ms") is not None)
    pcts = lambda vals: {"p50": percentile(vals, 50),
                         "p95": percentile(vals, 95),
                         "p99": percentile(vals, 99)}
    span = (max(e["t"] for e in reqs) - min(e["t"] for e in reqs)
            if len(reqs) > 1 else 0.0)
    gen = sum(e.get("new_tokens") or 0 for e in fins)
    sub = sum(1 for e in reqs if e.get("phase") == "enqueue")
    n_phase = lambda p: sum(1 for e in reqs if e.get("phase") == p)
    rate = lambda n: round(n / sub, 4) if sub else None
    rejected, timeouts, errors = (n_phase("reject"), n_phase("timeout"),
                                  n_phase("error"))
    reasons = {}
    for e in reqs:
        if e.get("phase") in ("reject", "timeout", "error") \
                and e.get("reason"):
            reasons[e["reason"]] = reasons.get(e["reason"], 0) + 1
    return {
        "submitted": sub,
        "finished": len(fins),
        "cancelled": n_phase("cancel"),
        "rejected": rejected,
        "timeout": timeouts,
        "errors": errors,
        "reject_rate": rate(rejected),
        "timeout_rate": rate(timeouts),
        "error_rate": rate(errors),
        "fail_reasons": reasons,
        "ttft_ms": pcts(ttfts),
        "tpot_ms": pcts(tpots),
        "req_s": round(len(fins) / span, 3) if span > 0 else None,
        "gen_tok_s": round(gen / span, 1) if span > 0 else None,
    }


def request_lines(r) -> list:
    if not r:
        return []
    tt, tp = r["ttft_ms"], r["tpot_ms"]
    lines = [f"  requests: {r['finished']}/{r['submitted']} finished"
             + (f", {r['cancelled']} cancelled" if r["cancelled"] else "")
             + (f"; {r['req_s']:.2f} req/s"
                if r["req_s"] is not None else "")
             + (f", {r['gen_tok_s']:.0f} gen tok/s"
                if r["gen_tok_s"] is not None else "")]
    if tt["p50"] is not None:
        lines.append(f"    TTFT p50/p95/p99 = {_fmt(tt['p50'], 1)}/"
                     f"{_fmt(tt['p95'], 1)}/{_fmt(tt['p99'], 1)} ms")
    if tp["p50"] is not None:
        lines.append(f"    TPOT p50/p95/p99 = {_fmt(tp['p50'], 2)}/"
                     f"{_fmt(tp['p95'], 2)}/{_fmt(tp['p99'], 2)} ms")
    # pre-round-14 summaries (fleet_report fixtures) may lack the
    # failure counters; render the line only when something failed
    fails = [(k, r.get(k, 0), r.get(rk)) for k, rk in
             (("rejected", "reject_rate"), ("timeout", "timeout_rate"),
              ("errors", "error_rate"))]
    if any(n for _, n, _ in fails):
        pc = lambda v: f" ({100 * v:.1f}%)" if v else ""
        bits = [f"{k} {n}{pc(rt)}" for k, n, rt in fails if n]
        why = r.get("fail_reasons") or {}
        if why:
            bits.append("reasons: " + ", ".join(
                f"{k}={v}" for k, v in sorted(why.items())))
        lines.append("    " + "; ".join(bits))
    return lines


def serve_stats_summary(events) -> dict:
    """Roll up the cadenced `serve_stats` health snapshots
    (serve/engine.py health()): queue-depth peak, occupancy mean,
    free-page floor, latest rolling p95 step latency, and the final
    cumulative terminal-state counters. None when the stream carries
    none (pre-round-14 serve streams, training runs)."""
    ss = [e for e in events if e.get("event") == "serve_stats"]
    if not ss:
        return None
    last = ss[-1]
    return {
        "snapshots": len(ss),
        "queue_depth_max": max(e["queue_depth"] for e in ss),
        "queue_depth_last": last["queue_depth"],
        "occupancy_mean": round(
            sum(e["occupancy"] for e in ss) / len(ss), 4),
        "free_blocks_min": min(e["free_blocks"] for e in ss),
        "p95_step_ms_last": last["p95_step_ms"],
        # round-20 mesh shape [dp, tp]; absent on pre-sharding streams
        "mesh": last.get("mesh"),
        # round-21 shared-prefix reuse: cumulative hit rate + COW count
        # from the LAST snapshot; absent (None) on cache-off streams
        "prefix_hit_rate": last.get("prefix_hit_rate"),
        "cow_copies": last.get("cow_copies"),
        "counts": {k: last.get(k, 0) for k in
                   ("finished", "cancelled", "rejected", "timeout",
                    "error")},
    }


def serve_stats_lines(s) -> list:
    if not s:
        return []
    mesh = ""
    if s.get("mesh"):
        mesh = f", mesh {s['mesh'][0]}x{s['mesh'][1]}"
    reuse = ""
    if s.get("prefix_hit_rate") is not None:
        reuse = (f", prefix hit_rate {s['prefix_hit_rate']:.2f} "
                 f"({s.get('cow_copies') or 0} COW cop"
                 f"{'y' if (s.get('cow_copies') or 0) == 1 else 'ies'})")
    return [f"  serve health: {s['snapshots']} snapshot(s); queue max "
            f"{s['queue_depth_max']} (last {s['queue_depth_last']}), "
            f"occupancy mean {100 * s['occupancy_mean']:.0f}%, free "
            f"pages min {s['free_blocks_min']}, p95 step "
            f"{_fmt(s['p95_step_ms_last'], 1)} ms{mesh}{reuse}"]


def route_summary(events) -> dict:
    """Roll up the serve-router's `route` decision events (round 22,
    tools/serve_router.py): decision histogram by policy and by placed
    replica, reject count, distinct rids, and snapshot-staleness
    percentiles (scrape_age_ms — how old the metrics behind each
    decision were). None when the stream carries no routing traffic.
    ONE builder shared with tools/fleet_report.py; serve_fleet_summary
    wraps it with the cross-shard accounting."""
    rs = [e for e in events if e.get("event") == "route"]
    if not rs:
        return None
    by_policy, by_replica = {}, {}
    for e in rs:
        p = e.get("policy", "?")
        by_policy[p] = by_policy.get(p, 0) + 1
        if e.get("replica") is not None:
            k = str(e["replica"])
            by_replica[k] = by_replica.get(k, 0) + 1
    ages = sorted(e["scrape_age_ms"] for e in rs
                  if e.get("scrape_age_ms") is not None)
    return {
        "decisions": len(rs),
        "rids": len({e["rid"] for e in rs}),
        "by_policy": by_policy,
        "by_replica": by_replica,
        "rejects": by_policy.get("reject", 0),
        "scrape_age_ms": {"p50": percentile(ages, 50),
                          "p95": percentile(ages, 95),
                          "max": ages[-1] if ages else None},
    }


def route_lines(r) -> list:
    """Render a route_summary (shared with fleet_report)."""
    if not r:
        return []
    pol = ", ".join(f"{k} {v}"
                    for k, v in sorted(r["by_policy"].items()))
    spread = ", ".join(f"r{k}:{v}"
                       for k, v in sorted(r["by_replica"].items()))
    a = r["scrape_age_ms"]
    line = (f"  routing: {r['decisions']} decision(s) over "
            f"{r['rids']} rid(s) ({pol}); spread {spread or 'none'}")
    if a["p50"] is not None:
        line += (f"; snapshot age p50/p95/max = {_fmt(a['p50'], 1)}/"
                 f"{_fmt(a['p95'], 1)}/{_fmt(a['max'], 1)} ms")
    return [line]


def serve_fleet_summary(shards) -> dict:
    """The serve-fleet section (round 22): {host: events} with the
    router stream at host 0 and replica shards at host k. Router side:
    route_summary plus EXACT rid accounting — every placed rid must
    own at most one replica-side terminal (a duplicate means two
    replicas both think they finished the same request; a rid with
    none was settled router-side from the shard tail or the shutdown
    fallback, which is how a killed replica's orphans are supposed to
    land). Replica side: one row per shard via the SAME
    request_summary/serve_stats_summary builders the single-engine
    report renders. None when host 0 carries no route events (not a
    router session)."""
    routing = route_summary(shards.get(0, []))
    if routing is None:
        return None
    placed = {e["rid"] for e in shards.get(0, [])
              if e.get("event") == "route"
              and isinstance(e.get("rid"), int)
              and e.get("replica") is not None}
    terminal: dict = {}
    replicas = {}
    for h, evs in sorted(shards.items()):
        if h == 0:
            continue
        replicas[str(h)] = {
            "requests": request_summary(evs),
            "serve": serve_stats_summary(evs),
        }
        for e in evs:
            if e.get("event") == "request" \
                    and isinstance(e.get("rid"), int) \
                    and e.get("phase") in ("finish", "cancel", "reject",
                                           "timeout", "error"):
                terminal[e["rid"]] = terminal.get(e["rid"], 0) + 1
    settled = sum(1 for r in placed if terminal.get(r))
    return {
        "routing": routing,
        "replicas": replicas,
        "routed_rids": len(placed),
        "replica_settled_rids": settled,
        "router_settled_rids": len(placed) - settled,
        "duplicate_terminals": sum(1 for r in placed
                                   if terminal.get(r, 0) > 1),
    }


def serve_fleet_lines(f) -> list:
    """Render a serve_fleet_summary (shared with fleet_report)."""
    if not f:
        return []
    lines = route_lines(f["routing"])
    lines.append(
        f"  fleet accounting: {f['routed_rids']} placed, "
        f"{f['replica_settled_rids']} replica-settled, "
        f"{f['router_settled_rids']} router-settled"
        + (f", {f['duplicate_terminals']} DUPLICATE terminal(s)"
           if f["duplicate_terminals"] else ""))
    for k, r in sorted(f["replicas"].items(), key=lambda kv: int(kv[0])):
        req, sv = r["requests"], r["serve"]
        if not req:
            lines.append(f"    replica {k}: no request traffic")
            continue
        hit = ""
        if sv and sv.get("prefix_hit_rate") is not None:
            hit = f", prefix hit_rate {sv['prefix_hit_rate']:.2f}"
        lines.append(
            f"    replica {k}: {req['finished']}/{req['submitted']} "
            f"finished, TTFT p99 {_fmt(req['ttft_ms']['p99'], 1)} ms, "
            f"TPOT p50 {_fmt(req['tpot_ms']['p50'], 2)} ms{hit}")
    return lines


def controller_entries(events) -> list:
    """Summary dicts for `controller` events (the fleet controller's
    recovery timeline, tools/fleet_controller.py) — ONE builder shared
    with tools/fleet_report.py like the straggler/hang entries."""
    return [{"t": e["t"], "action": e["action"],
             "worker": e.get("worker"), "reason": e.get("reason"),
             "attempt": e.get("attempt"), "step": e.get("step"),
             "recovery_s": e.get("recovery_s")}
            for e in events if e.get("event") == "controller"]


def latest_controller_session(entries) -> list:
    """The controller stream appends across sessions (re-running with
    the same --telemetry base resumes the file). Scope to the LATEST
    session — the same rule the worker shards get from split_latest_run
    — so a resumed fleet's recovery accounting describes THIS run, not
    every run ever recorded. A session STARTS with a burst of `launch`
    events, so the latest session begins at the last launch whose
    predecessor is not itself a launch — robust even when an earlier
    session died without its stop/give_up terminator (a SIGKILLed
    controller writes no goodbye). Streams with no launch at all
    (hand-built fixtures) fall back to terminator slicing."""
    starts = [i for i, e in enumerate(entries)
              if e["action"] == "launch"
              and (i == 0 or entries[i - 1]["action"] != "launch")]
    if starts:
        return entries[starts[-1]:]
    ends = [i for i, e in enumerate(entries)
            if e["action"] in ("stop", "give_up")]
    if not ends:
        return entries
    last = ends[-1]
    if last == len(entries) - 1:  # closed session: back to the previous
        prev = ends[-2] if len(ends) > 1 else -1
        return entries[prev + 1:]
    return entries[last + 1:]     # live session after the last closed one


def controller_summary(entries) -> dict:
    """Roll up the recovery timeline (scoped to the LATEST controller
    session): restarts/shrinks/lost counts and the total recovery
    wall-clock (down-observed -> relaunched, summed over restart+shrink
    events) — the number that turns recovery cost into a visible line
    next to the goodput buckets instead of a mystery gap in step reach.
    None when no controller ran."""
    if not entries:
        return None
    entries = latest_controller_session(entries)
    return {
        "events": len(entries),
        "restarts": sum(1 for e in entries if e["action"] == "restart"),
        "shrinks": sum(1 for e in entries if e["action"] == "shrink"),
        "lost": sum(1 for e in entries if e["action"] == "lost"),
        "drains": sum(1 for e in entries if e["action"] == "drain"),
        "gave_up": any(e["action"] == "give_up" for e in entries),
        "recovery_s": round(sum(e["recovery_s"] or 0.0 for e in entries
                                if e["action"] in ("restart", "shrink")),
                            3),
        "entries": entries,
    }


def controller_lines(cs) -> list:
    """Render a controller_summary (shared with fleet_report)."""
    if not cs:
        return []
    head = (f"  controller: {cs['restarts']} restart(s), "
            f"{cs['shrinks']} shrink(s), {cs['lost']} lost, "
            f"recovery {cs['recovery_s']:.2f}s"
            + (", GAVE UP" if cs["gave_up"] else "")
            + (f", {cs['drains']} drain(s)" if cs["drains"] else ""))
    lines = [head]
    for e in cs["entries"]:
        if e["action"] not in ("restart", "shrink", "lost", "give_up",
                               "drain"):
            continue
        bits = [f"    {e['action'].upper()}"]
        if e["worker"] is not None:
            bits.append(f"worker {e['worker']}")
        if e["reason"]:
            bits.append(f"({e['reason']})")
        if e["step"] is not None:
            bits.append(f"@ step {e['step']}")
        if e["attempt"] is not None:
            bits.append(f"attempt {e['attempt']}")
        if e["recovery_s"] is not None:
            bits.append(f"recovered in {e['recovery_s']:.2f}s")
        lines.append(" ".join(bits))
    return lines


def straggler_entries(events) -> list:
    """Summary dicts for `straggler` events — ONE builder shared with
    tools/fleet_report.py (same rule as goodput_lines)."""
    return [{"step": e["step"], "slow_host": e["slow_host"],
             "host_ms": e["host_ms"], "fleet_ms": e["fleet_ms"],
             "ratio": e["ratio"]}
            for e in events if e.get("event") == "straggler"]


def hang_entries(events) -> list:
    """Summary dicts for `hang` events (host = the WRITER's envelope
    stamp: which process's watchdog fired)."""
    return [{"host": e.get("host", 0), "step": e["step"],
             "stall_s": e["stall_s"], "device_probe": e["device_probe"],
             "action": e["action"], "stacks_file": e["stacks_file"]}
            for e in events if e.get("event") == "hang"]


def straggler_lines(entries) -> list:
    return [f"  STRAGGLER @ step {e['step']}: host {e['slow_host']} at "
            f"{e['host_ms']:.1f} ms vs fleet {e['fleet_ms']:.1f} ms "
            f"({e['ratio']}x)" for e in entries]


def hang_lines(entries) -> list:
    return [f"  HANG on host {e['host']} @ step {e['step']}: stalled "
            f"{e['stall_s']:.1f}s, device probe {e['device_probe']}, "
            f"action {e['action']} (stacks: {e['stacks_file']})"
            for e in entries]


def goodput_lines(g) -> list:
    """Render a goodput dict — writer-side (GoodputMeter.summary) or
    reader-side (partial_goodput) — to report lines. ONE renderer,
    shared with tools/fleet_report.py, so the two reports cannot
    drift."""
    if not g:
        return []
    if g.get("partial"):
        return [f"  goodput (PARTIAL, reconstructed): compile "
                f"{g['compile_s']:.1f}s, checkpoint "
                f"{g['checkpoint_s']:.1f}s, governor sleep "
                f"{g['governor_sleep_s']:.1f}s, input-wait "
                f"{100 * g['input_wait_frac_of_step']:.1f}% of step "
                f"time over {g['observed_span_s']:.1f}s observed"]
    buckets = ", ".join(
        f"{k[:-2]} {v:.1f}s" for k, v in g.items()
        if k.endswith("_s") and k != "total_s" and v)
    return [f"  goodput: {100 * g['productive_frac']:.1f}% productive "
            f"of {g['total_s']:.1f}s ({buckets})"]


def print_summary(s: dict):
    m = s["manifest"] or {}
    print(f"telemetry: {s['events']} events"
          + (f" ({s['invalid_lines']} invalid lines skipped)"
             if s["invalid_lines"] else "")
          + ("" if s["seq_monotonic"] else "  [SEQ NOT MONOTONIC]"))
    if m:
        print(f"  device: {m['device_count']}x {m['device_kind']}, "
              f"{m['process_count']} process(es), mesh={m['mesh_shape']}, "
              f"jax {m['jax_version']}")
    for c in s["compile"]:
        fl = (f", {c['flops'] / 1e9:.2f} GFLOP/step"
              if c.get("flops") else "")
        hbm = (f", peak {c['peak_hbm_mb']:.0f} MB"
               if c.get("peak_hbm_mb") else "")
        print(f"  compile @ step {c['step']}: {c['wall_s']:.1f}s{fl}{hbm}")
    st = s["step_stats"]
    if st["flushes"]:
        t = st["step_time_ms"]
        print(f"  steps: {st['flushes']} flushes through step "
              f"{st['last_step']}; step_time p50/p90/p99 = "
              f"{_fmt(t['p50'])}/{_fmt(t['p90'])}/{_fmt(t['p99'])} ms; "
              f"host_wait {_fmt(100 * st['host_wait_frac'], 1)}%")
        print(f"  throughput: {_fmt(st['tok_s']['mean'], 0)} tok/s mean "
              f"({_fmt(st['tok_s']['last'], 0)} last); "
              f"mfu first/mean/last = {_fmt(st['mfu']['first'], 3)}/"
              f"{_fmt(st['mfu']['mean'], 3)}/{_fmt(st['mfu']['last'], 3)}")
        print(f"  loss: {_fmt(st['loss']['first'], 4)} -> "
              f"{_fmt(st['loss']['last'], 4)} "
              f"(ema {_fmt(st['loss']['ema_last'], 4)}); "
              f"nonfinite grad elements: {st['nonfinite_grad_elements']}")
    th = s["throttle"]
    if th["decisions"] or th["total_sleep_ms"]:
        print(f"  throttle: {th['decisions']} decision(s), "
              f"{th['total_sleep_ms']:.0f} ms total sleep")
    if s["anomalies"]:
        print(f"  ANOMALIES ({len(s['anomalies'])}):")
        for a in s["anomalies"]:
            z = f" z={a['zscore']}" if a.get("zscore") else ""
            print(f"    step {a['step']}: {a['kind']} "
                  f"loss={_fmt(a['loss'], 4)}{z}")
    for e in s["evals"]:
        if e.get("macro_accuracy") is not None:  # accuracy-shaped eval
            print(f"  eval @ step {e['step']}: "
                  f"macro_acc={e['macro_accuracy']:.4f}")
        else:
            print(f"  eval @ step {e['step']}: loss={_fmt(e['loss'], 4)} "
                  f"ppl={_fmt(e['ppl'])}")
    for line in checkpoint_lines(s["checkpoints"]):
        print(line)
    for line in memory_lines(s.get("memory")):
        print(line)
    for line in recovery_lines(s.get("recovery")):
        print(line)
    for line in observability_lines(s.get("observability")):
        print(line)
    for line in request_lines(s.get("requests")):
        print(line)
    for line in tenant_lines(s.get("tenants")):
        print(line)
    for line in serve_stats_lines(s.get("serve")):
        print(line)
    for line in route_lines(s.get("routing")):
        print(line)
    for line in straggler_lines(s.get("stragglers", [])) \
            + hang_lines(s.get("hangs", [])):
        print(line)
    g = s.get("goodput")
    if g and not g.get("partial"):
        for line in goodput_lines(g):
            print(line)
    if s["run_end"]:
        r = s["run_end"]
        print(f"  run_end: {r['steps']} steps in {r['wall_s']:.1f}s "
              f"(exit={r['exit']})")
    else:
        last = s.get("last_seen_step")
        print(f"  run TRUNCATED (no run_end — killed or still running); "
              f"last seen step: "
              f"{last if last is not None else 'none'}")
        if g and g.get("partial"):
            for line in goodput_lines(g):
                print(line)


def add_format_flags(ap: argparse.ArgumentParser) -> None:
    """--format {text,json} (+ the legacy --json alias), shared by both
    report tools so the output contract cannot drift between them."""
    ap.add_argument("--format", choices=["text", "json"], default="text",
                    help="'json' = machine-readable summary (the same "
                         "section builders the text report renders — "
                         "dashboards and CI consume the numbers humans "
                         "read)")
    ap.add_argument("--json", action="store_true",
                    help="alias for --format json (kept for existing "
                         "callers)")


def emit_output(summary: dict, args, text_printer) -> None:
    """ONE serializer for both report tools: the summary dict the
    section builders assembled is either json.dumps'd verbatim or
    handed to the tool's text printer — the JSON output IS the text
    report's input, so the two can never disagree."""
    try:
        if args.json or args.format == "json":
            print(json.dumps(summary, indent=1))
        else:
            text_printer(summary)
    except BrokenPipeError:  # `report run.jsonl | head` is a normal use
        pass


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("jsonl", help="telemetry stream (--telemetry_out)")
    add_format_flags(ap)
    args = ap.parse_args(argv)
    try:
        events, bad = load_events(args.jsonl)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not events:
        print(f"error: no valid telemetry events in {args.jsonl}",
              file=sys.stderr)
        return 1
    emit_output(summarize(events, bad), args, print_summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
