"""Render a run-telemetry JSONL stream into a human summary.

Reads the --telemetry_out stream (core/telemetry.py event taxonomy) and
prints what an operator asks after a run: how fast was it (step-time
percentiles, tokens/s, MFU trend), where did the time go (host-wait
fraction, throttle sleeps, compile), and was it healthy (anomalies,
nonfinite gradients, exit status). Every line is validated against the
shared EVENT_SCHEMA; invalid lines are counted, not fatal (a crashed
writer may leave one truncated tail line).

Usage:
  python tools/telemetry_report.py run.jsonl [--json]
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])
sys.path.insert(0, __file__.rsplit("/", 1)[0])

from mobilefinetuner_tpu.core.telemetry import (partial_goodput,  # noqa: F401
                                                validate_event)

# shared section builders live in report_sections.py (round 23) —
# re-exported here so existing `from telemetry_report import ...`
# callers keep working
from report_sections import (  # noqa: E402,F401
    percentile, load_events, split_latest_run, _fmt, checkpoint_summary,
    checkpoint_lines, recovery_summary, recovery_lines, memory_summary,
    memory_lines, observability_summary, observability_lines,
    tenant_summary, tenant_lines, request_summary, request_lines,
    serve_stats_summary, serve_stats_lines, route_summary, route_lines,
    serve_fleet_summary, serve_fleet_lines, controller_entries,
    latest_controller_session, controller_summary, controller_lines,
    straggler_entries, hang_entries, straggler_lines, hang_lines,
    goodput_lines, add_format_flags, emit_output)


def summarize(events, n_invalid=0) -> dict:
    truncated, latest = split_latest_run(events)
    # a truncated stream's post-mortem subject is the LATEST run: stats
    # and incident lists over the whole file would attribute an earlier
    # appended run's stragglers/anomalies/percentiles to the killed run
    scope = latest if truncated else events
    by = {}
    for e in scope:
        by.setdefault(e["event"], []).append(e)
    runs_all = [e for e in events if e["event"] == "run_start"]
    stats = by.get("step_stats", [])
    times = sorted(s["step_time_ms"] for s in stats)
    waits = [s["host_wait_ms"] for s in stats]
    mfus = [s["mfu"] for s in stats if s.get("mfu") is not None]
    toks = [s["tok_s"] for s in stats]
    nonfinite = sum(s.get("nonfinite_count") or 0 for s in stats)
    runs = runs_all  # manifest/run count span the WHOLE stream
    ends = by.get("run_end", [])
    seqs = [e["seq"] for e in events]
    out = {
        "events": len(events),
        "invalid_lines": n_invalid,
        "seq_monotonic": all(a < b for a, b in zip(seqs, seqs[1:])),
        "runs": len(runs),
        "manifest": (lambda m: {
            "device_kind": m["device_kind"],
            "device_count": m["device_count"],
            "process_count": m["process_count"],
            "mesh_shape": m["mesh_shape"],
            "jax_version": m["jax_version"],
        })(runs[-1]) if runs else None,
        "compile": [{"step": c["step"], "wall_s": c["wall_s"],
                     "flops": c.get("flops"),
                     "peak_hbm_mb": c.get("peak_hbm_mb")}
                    for c in by.get("compile", [])],
        "step_stats": {
            "flushes": len(stats),
            "last_step": stats[-1]["step"] if stats else None,
            "step_time_ms": {
                "p50": percentile(times, 50),
                "p90": percentile(times, 90),
                "p99": percentile(times, 99),
            },
            # fraction of step time the loop sat blocked on the input
            # pipeline — the host/device breakdown
            "host_wait_frac": (sum(waits) / max(sum(times), 1e-9)
                               if stats else None),
            "tok_s": {"mean": sum(toks) / len(toks) if toks else None,
                      "last": toks[-1] if toks else None},
            "mfu": {"first": mfus[0] if mfus else None,
                    "last": mfus[-1] if mfus else None,
                    "mean": sum(mfus) / len(mfus) if mfus else None},
            "loss": {"first": stats[0]["loss"] if stats else None,
                     "last": stats[-1]["loss"] if stats else None,
                     "ema_last": stats[-1]["ema"] if stats else None},
            "nonfinite_grad_elements": nonfinite,
        },
        # throttle events mark DECISION CHANGES; the actual time slept
        # accumulates per flush interval in step_stats.slept_ms
        "throttle": {
            "decisions": len(by.get("throttle", [])),
            "total_sleep_ms": sum(s.get("slept_ms") or 0 for s in stats),
        },
        "anomalies": [{"step": a["step"], "kind": a["kind"],
                       "loss": a["loss"], "zscore": a.get("zscore")}
                      for a in by.get("anomaly", [])],
        "evals": [{"step": e["step"], "loss": e["loss"], "ppl": e["ppl"],
                   "macro_accuracy": e.get("macro_accuracy")}
                  for e in by.get("eval", [])],
        "checkpoints": checkpoint_summary(scope),
        "recovery": recovery_summary(scope),
        "memory": memory_summary(scope),
        "observability": observability_summary(scope),
        "requests": request_summary(scope),
        "tenants": tenant_summary(scope),
        "serve": serve_stats_summary(scope),
        "routing": route_summary(scope),
        "stragglers": straggler_entries(scope),
        "hangs": hang_entries(scope),
        # a killed LATEST run leaves no run_end after its run_start (a
        # prior appended run's clean run_end must not mask it): report
        # the truncation with the last step the stream DID see instead
        # of pretending nothing ran. A truncated stream's stale run_end
        # (from the earlier run) is withheld — rendering it as current
        # is exactly the post-mortem trap.
        "run_end": ({"steps": ends[-1]["steps"],
                     "wall_s": ends[-1]["wall_s"],
                     "exit": ends[-1]["exit"]}
                    if ends and not truncated else None),
        "truncated": truncated,
        "last_seen_step": max(
            (e.get("step") for e in latest
             if isinstance(e.get("step"), int)), default=None),
        # goodput: the writer-side buckets when the latest run ENDED
        # (None stays None — e.g. the eval CLIs have no metered loop;
        # that is not a truncation); a truncated run gets the partial
        # reconstruction over ITS OWN slice of the stream
        "goodput": (ends[-1].get("goodput") if ends and not truncated
                    else partial_goodput(latest)),
    }
    return out


def print_summary(s: dict):
    m = s["manifest"] or {}
    print(f"telemetry: {s['events']} events"
          + (f" ({s['invalid_lines']} invalid lines skipped)"
             if s["invalid_lines"] else "")
          + ("" if s["seq_monotonic"] else "  [SEQ NOT MONOTONIC]"))
    if m:
        print(f"  device: {m['device_count']}x {m['device_kind']}, "
              f"{m['process_count']} process(es), mesh={m['mesh_shape']}, "
              f"jax {m['jax_version']}")
    for c in s["compile"]:
        fl = (f", {c['flops'] / 1e9:.2f} GFLOP/step"
              if c.get("flops") else "")
        hbm = (f", peak {c['peak_hbm_mb']:.0f} MB"
               if c.get("peak_hbm_mb") else "")
        print(f"  compile @ step {c['step']}: {c['wall_s']:.1f}s{fl}{hbm}")
    st = s["step_stats"]
    if st["flushes"]:
        t = st["step_time_ms"]
        print(f"  steps: {st['flushes']} flushes through step "
              f"{st['last_step']}; step_time p50/p90/p99 = "
              f"{_fmt(t['p50'])}/{_fmt(t['p90'])}/{_fmt(t['p99'])} ms; "
              f"host_wait {_fmt(100 * st['host_wait_frac'], 1)}%")
        print(f"  throughput: {_fmt(st['tok_s']['mean'], 0)} tok/s mean "
              f"({_fmt(st['tok_s']['last'], 0)} last); "
              f"mfu first/mean/last = {_fmt(st['mfu']['first'], 3)}/"
              f"{_fmt(st['mfu']['mean'], 3)}/{_fmt(st['mfu']['last'], 3)}")
        print(f"  loss: {_fmt(st['loss']['first'], 4)} -> "
              f"{_fmt(st['loss']['last'], 4)} "
              f"(ema {_fmt(st['loss']['ema_last'], 4)}); "
              f"nonfinite grad elements: {st['nonfinite_grad_elements']}")
    th = s["throttle"]
    if th["decisions"] or th["total_sleep_ms"]:
        print(f"  throttle: {th['decisions']} decision(s), "
              f"{th['total_sleep_ms']:.0f} ms total sleep")
    if s["anomalies"]:
        print(f"  ANOMALIES ({len(s['anomalies'])}):")
        for a in s["anomalies"]:
            z = f" z={a['zscore']}" if a.get("zscore") else ""
            print(f"    step {a['step']}: {a['kind']} "
                  f"loss={_fmt(a['loss'], 4)}{z}")
    for e in s["evals"]:
        if e.get("macro_accuracy") is not None:  # accuracy-shaped eval
            print(f"  eval @ step {e['step']}: "
                  f"macro_acc={e['macro_accuracy']:.4f}")
        else:
            print(f"  eval @ step {e['step']}: loss={_fmt(e['loss'], 4)} "
                  f"ppl={_fmt(e['ppl'])}")
    for line in checkpoint_lines(s["checkpoints"]):
        print(line)
    for line in memory_lines(s.get("memory")):
        print(line)
    for line in recovery_lines(s.get("recovery")):
        print(line)
    for line in observability_lines(s.get("observability")):
        print(line)
    for line in request_lines(s.get("requests")):
        print(line)
    for line in tenant_lines(s.get("tenants")):
        print(line)
    for line in serve_stats_lines(s.get("serve")):
        print(line)
    for line in route_lines(s.get("routing")):
        print(line)
    for line in straggler_lines(s.get("stragglers", [])) \
            + hang_lines(s.get("hangs", [])):
        print(line)
    g = s.get("goodput")
    if g and not g.get("partial"):
        for line in goodput_lines(g):
            print(line)
    if s["run_end"]:
        r = s["run_end"]
        print(f"  run_end: {r['steps']} steps in {r['wall_s']:.1f}s "
              f"(exit={r['exit']})")
    else:
        last = s.get("last_seen_step")
        print(f"  run TRUNCATED (no run_end — killed or still running); "
              f"last seen step: "
              f"{last if last is not None else 'none'}")
        if g and g.get("partial"):
            for line in goodput_lines(g):
                print(line)


def main(argv=None) -> int:
    from report_sections import add_registry_flags, resolve_stream
    ap = argparse.ArgumentParser()
    ap.add_argument("jsonl", nargs="?", default="",
                    help="telemetry stream (--telemetry_out); or use "
                         "--run to resolve it from the run registry")
    add_format_flags(ap)
    add_registry_flags(ap)
    args = ap.parse_args(argv)
    path = resolve_stream(args)
    try:
        events, bad = load_events(path)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not events:
        print(f"error: no valid telemetry events in {path}",
              file=sys.stderr)
        return 1
    emit_output(summarize(events, bad), args, print_summary)
    return 0


if __name__ == "__main__":
    sys.exit(main())
