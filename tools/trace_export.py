"""Telemetry stream(s) -> ONE Perfetto/Chrome-trace-event timeline.

A fleet run (or serve session) leaves per-host JSONL telemetry shards;
this tool converts a coordinator stream — plus every `.host<k>` shard
found next to it, plus optionally a `jax.profiler` device trace — into
a single trace-event JSON file that opens in ui.perfetto.dev (or
chrome://tracing): one PROCESS row per host, one THREAD row per track
("phase" for the goodput buckets, "ckpt" for the async writer thread,
"prefetch" for the producer, "req:<id>" per serve request), counter
tracks for loss/tok_s/queue depth, and instant markers for every
incident event (anomaly, straggler, hang, rollback, degrade, preempt,
profile_capture, over-capacity mem_check).

Clock discipline: `span` events carry a MONOTONIC t0 (time.perf_counter,
the envelope's `t_mono` clock). Each host's monotonic clock is placed
on the wall timeline via the median (t - t_mono) offset over its own
records — NTP steps move wall time, never a span's duration or its
position relative to its host's other spans. Streams that predate
`t_mono` still convert (instants and counters use wall `t`; they carry
no spans to place).

Reconciliation: with `--trace_spans` the goodput meter emits one span
per phase segment from the SAME transitions that charge the run_end
buckets, so per-phase span sums match `run_end.goodput` by
construction — the tool prints the check (and `phase_reconcile` is the
test's oracle).

Device-trace merge (`--profile DIR|FILE`): jax.profiler writes a
Chrome-trace `*.trace.json.gz` under its log dir; its events are
appended under their own process rows. Alignment is BEST-EFFORT (the
profiler's clock zero is its own): the profiler timeline is shifted so
its start coincides with the stream's first `profile_capture` event
when one exists, else with the stream's start.

Usage:
  python tools/trace_export.py run.jsonl -o trace.json
  python tools/trace_export.py run.jsonl --profile prof_dir -o all.json
"""

from __future__ import annotations

import argparse
import glob as globmod
import gzip
import json
import os
import statistics
import sys

_HERE = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(_HERE))  # repo root
sys.path.insert(0, _HERE)                   # sibling tools

from fleet_report import discover_shards          # noqa: E402
from telemetry_report import load_events          # noqa: E402

# incident events rendered as instant markers (name rule per event)
_INSTANT_EVENTS = ("anomaly", "straggler", "hang", "preempt", "rollback",
                   "degrade", "mem_check", "ckpt_verify",
                   "profile_capture", "throttle", "ckpt_dropped",
                   "route")

# step_stats fields rendered as counter tracks
_COUNTERS = ("loss", "tok_s", "queue_depth", "hbm_mb", "step_time_ms")


def latest_run(events):
    """Slice one shard's events to its LATEST run (from the last
    run_start onward; the whole stream when none). A resumed stream
    appends runs from different processes, whose perf_counter epochs
    share nothing — one median (t - t_mono) offset over both would
    misplace the minority run's spans by the epoch gap, and summing
    both runs' phase spans against the final run_end's buckets would
    report the by-construction identity as violated on a healthy
    resumed run. One timeline = one run (the same latest-run scoping
    rule the report tools apply to truncated streams)."""
    idx = max((i for i, e in enumerate(events)
               if e.get("event") == "run_start"), default=-1)
    return events[idx:] if idx > 0 else events


def mono_offset(events):
    """Median wall-minus-monotonic offset for one host's records: maps
    a span's monotonic t0 onto the wall timeline. None when the stream
    predates t_mono."""
    ds = [e["t"] - e["t_mono"] for e in events
          if isinstance(e.get("t_mono"), (int, float))
          and isinstance(e.get("t"), (int, float))]
    return statistics.median(ds) if ds else None


def _instant_name(e) -> str:
    ev = e["event"]
    if ev == "anomaly":
        return f"anomaly:{e.get('kind')}"
    if ev == "straggler":
        return f"straggler:host{e.get('slow_host')}"
    if ev == "mem_check":
        return f"mem_check:{e.get('verdict')}"
    if ev == "ckpt_verify":
        return ("ckpt_verify:ok" if e.get("ok")
                else "ckpt_verify:REJECTED")
    if ev == "rollback":
        return f"rollback:{e.get('reason')}"
    if ev == "degrade":
        return f"degrade:{e.get('rung')}"
    if ev == "profile_capture":
        return f"profile_capture:{e.get('trigger')}"
    if ev == "route":
        repl = e.get("replica")
        return (f"route:rid{e.get('rid')}->r{repl}" if repl is not None
                else f"route:rid{e.get('rid')}->REJECT")
    return ev


def _span_args(e) -> dict:
    skip = {"event", "seq", "t", "t_mono", "host", "name", "track",
            "t0", "dur_ms"}
    return {k: v for k, v in e.items() if k not in skip}


def host_trace_events(host, events, t_base):
    """One host's trace events (ts in us relative to t_base). Returns
    (trace_events, track_names_seen)."""
    out = []
    off = mono_offset(events)
    tracks = {}  # track name -> tid

    def tid_for(track):
        if track not in tracks:
            # stable, readable ordering: phase first, then the engine
            # threads, request tracks in arrival order after
            tracks[track] = len(tracks) + 1
        return tracks[track]

    spans = [e for e in events if e["event"] == "span"]
    have = {e.get("track", "") for e in spans}
    for e in spans:
        if off is None:
            continue  # no clock bridge: a pre-t_mono stream has no
            # spans anyway (same round introduced both)
        wall = e["t0"] + off
        out.append({
            "ph": "X", "pid": host, "tid": tid_for(e["track"]),
            "ts": round((wall - t_base) * 1e6, 3),
            "dur": round(e["dur_ms"] * 1000.0, 3),
            "name": e["name"], "cat": "span", "args": _span_args(e),
        })
    # requests: if the engine did not trace spans (trace_spans off),
    # synthesize queue/decode spans from the request lifecycle events
    # the stream always carries — wall-clock precision, same tracks
    if not any(t.startswith("req:") for t in have):
        reqs = {}
        for e in events:
            if e["event"] == "request":
                reqs.setdefault(e["id"], []).append(e)
        for rid, recs in sorted(reqs.items()):
            by_phase = {r["phase"]: r for r in recs}
            enq = by_phase.get("enqueue")
            admit = by_phase.get("admit")
            term = next((r for r in recs
                         if r["phase"] in ("finish", "cancel", "reject",
                                           "timeout", "error")), None)
            track = f"req:{rid}"
            if enq and admit:
                out.append({
                    "ph": "X", "pid": host, "tid": tid_for(track),
                    "ts": round((enq["t"] - t_base) * 1e6, 3),
                    "dur": round(max(admit["t"] - enq["t"], 0) * 1e6, 3),
                    "name": "queue", "cat": "request",
                    "args": {"id": rid}})
            if admit and term:
                out.append({
                    "ph": "X", "pid": host, "tid": tid_for(track),
                    "ts": round((admit["t"] - t_base) * 1e6, 3),
                    "dur": round(max(term["t"] - admit["t"], 0) * 1e6, 3),
                    "name": "decode", "cat": "request",
                    "args": {"id": rid, "outcome": term["phase"],
                             "new_tokens": term.get("new_tokens")}})
    # checkpoint writes: derive write spans from the checkpoint events
    # (emitted at write END with write_ms) when the writer wasn't traced
    if "ckpt" not in have:
        for e in events:
            if e["event"] == "checkpoint" and e.get("write_ms"):
                t_end = e["t"]
                out.append({
                    "ph": "X", "pid": host, "tid": tid_for("ckpt"),
                    "ts": round((t_end - e["write_ms"] / 1000.0
                                 - t_base) * 1e6, 3),
                    "dur": round(e["write_ms"] * 1000.0, 3),
                    "name": f"ckpt_write(step {e['step']})",
                    "cat": "checkpoint",
                    "args": {"step": e["step"], "bytes": e.get("bytes"),
                             "async": e.get("async")}})
    # instants: every incident event is a marker on its host's row
    for e in events:
        if e["event"] in _INSTANT_EVENTS:
            if e["event"] == "mem_check" and e.get("verdict") == "ok":
                continue  # a clean preflight is not an incident
            out.append({
                "ph": "i", "pid": host, "tid": tid_for("events"),
                "ts": round((e["t"] - t_base) * 1e6, 3), "s": "p",
                "name": _instant_name(e), "cat": e["event"],
                "args": {k: v for k, v in e.items()
                         if k not in ("event", "seq", "t", "t_mono",
                                      "host")}})
    # counters: the step_stats trend lines, drawable next to the spans
    for e in events:
        if e["event"] == "step_stats":
            ts = round((e["t"] - t_base) * 1e6, 3)
            for f in _COUNTERS:
                v = e.get(f)
                if isinstance(v, (int, float)):
                    out.append({"ph": "C", "pid": host, "tid": 0,
                                "ts": ts, "name": f,
                                "args": {f: round(float(v), 4)}})
        elif e["event"] == "serve_stats":
            ts = round((e["t"] - t_base) * 1e6, 3)
            for f in ("queue_depth", "active", "free_blocks"):
                v = e.get(f)
                if isinstance(v, (int, float)):
                    out.append({"ph": "C", "pid": host, "tid": 0,
                                "ts": ts, "name": f"serve_{f}",
                                "args": {f: round(float(v), 4)}})
    # metadata: name the process and thread rows
    meta = [{"ph": "M", "pid": host, "name": "process_name",
             "args": {"name": f"host {host}"
                      + (" (coordinator)" if host == 0 else "")}},
            {"ph": "M", "pid": host, "name": "process_sort_index",
             "args": {"sort_index": host}}]
    for track, tid in tracks.items():
        meta.append({"ph": "M", "pid": host, "tid": tid,
                     "name": "thread_name", "args": {"name": track}})
    return meta + out, set(tracks)


def find_profiler_trace(path):
    """Locate a jax.profiler Chrome trace: the path itself when it is a
    .json/.json.gz file, else the newest *.trace.json.gz under it."""
    if os.path.isfile(path):
        return path
    hits = sorted(globmod.glob(os.path.join(
        globmod.escape(path), "**", "*.trace.json.gz"), recursive=True),
        key=os.path.getmtime)
    return hits[-1] if hits else None


def load_profiler_events(path):
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rt", encoding="utf-8", errors="replace") as f:
        data = json.load(f)
    evs = data.get("traceEvents", data) or []
    return [e for e in evs if isinstance(e, dict)]


def merge_profiler(trace_events, prof_events, anchor_us):
    """Append the device trace under its own process rows (pids offset
    by 9000), shifted so its earliest timestamp lands at `anchor_us` —
    best-effort alignment (the profiler's epoch is its own)."""
    ts0 = min((e["ts"] for e in prof_events
               if isinstance(e.get("ts"), (int, float))), default=0.0)
    out = []
    for e in prof_events:
        e = dict(e)
        if isinstance(e.get("pid"), int):
            e["pid"] = 9000 + e["pid"]
        else:
            e["pid"] = 9000
        if isinstance(e.get("ts"), (int, float)):
            e["ts"] = round(e["ts"] - ts0 + anchor_us, 3)
        out.append(e)
    trace_events.extend(out)


def phase_sums(trace, pid: int = 0) -> dict:
    """Per-name sums (seconds) over ONE host's goodput-phase spans —
    the reconciliation oracle the acceptance test compares against
    that host's run_end.goodput. Scoped to a single pid: each host
    runs its own GoodputMeter, so summing phase spans across a fleet
    against one host's buckets would report the by-construction
    identity as violated on a perfectly healthy run."""
    sums = {}
    for e in trace["traceEvents"]:
        if e.get("ph") == "X" and e.get("cat") == "span" \
                and e.get("pid") == pid \
                and e.get("name") and "dur" in e:
            # phase spans carry bucket names; other span tracks carry
            # names outside the bucket set, so keying by name is safe
            sums[e["name"]] = sums.get(e["name"], 0.0) \
                + e["dur"] / 1e6
    return sums


def phase_reconcile(trace, goodput, pid: int = 0) -> dict:
    """{bucket: (span_sum_s, bucket_s, abs_delta_s)} for every goodput
    bucket the trace carries spans for, scoped to `pid`'s host."""
    sums = phase_sums(trace, pid=pid)
    out = {}
    for k, v in (goodput or {}).items():
        if not k.endswith("_s") or k == "total_s":
            continue
        b = k[:-2]
        if b in sums:
            out[b] = (round(sums[b], 4), v, round(abs(sums[b] - v), 4))
    return out


def router_reconcile(shards) -> dict | None:
    """Router-vs-replica span reconciliation for a serve-fleet stream
    (shard 0 = router, shard k = replica k). The merged timeline is
    only trustworthy across process rows if each process's spans —
    placed on the wall axis via that host's mono_offset — land where
    that SAME process's wall-stamped events say the instant occurred.
    Two anchors exist per routed rid, one on each side of the handoff:

      router side:   the `route` span's END (t0 + offset + dur) is the
                     ack instant the `route` EVENT stamps with wall t;
      replica side:  the rid-tagged `queue` span's START (t0 + offset)
                     is the submit instant the request phase=enqueue
                     EVENT stamps with wall t.

    |placed - stamped| per anchor bounds how far a span can be
    misplaced relative to any other process's row (events share one
    wall clock; queueing delay between route and enqueue is real time,
    not error, and is deliberately NOT measured here). Returns None
    when the stream carries no route events (not a router run); rids
    missing an anchor (replica killed pre-flush, tracer off) are
    counted, not matched — settlement handled them off-stream."""
    shards = {h: latest_run(evs) for h, evs in shards.items()}
    offs = {h: mono_offset(evs) for h, evs in shards.items()}
    routes = {}
    for e in shards.get(0, ()):
        if e.get("event") == "route" and isinstance(e.get("rid"), int) \
                and e.get("replica") is not None:
            routes[e["rid"]] = e  # last route per rid wins (failover)
    if not routes:
        return None
    gaps, unmatched = [], 0

    def anchor(host, rid, span_name, span_end, event_t):
        """Gap between a placed span edge and the wall stamp of the
        event emitted at the same instant. None when either half is
        missing on `host` for `rid`."""
        off = offs.get(host)
        if off is None:
            return None
        span = next((e for e in shards.get(host, ())
                     if e.get("event") == "span"
                     and e.get("name") == span_name
                     and e.get("rid") == rid), None)
        if span is None or event_t is None:
            return None
        placed = span["t0"] + off \
            + (span["dur_ms"] / 1000.0 if span_end else 0.0)
        return abs(placed - event_t)

    enq = {}  # (host, rid) -> wall t of the last enqueue event
    for h, evs in shards.items():
        if h == 0:
            continue
        for e in evs:
            if e.get("event") == "request" \
                    and e.get("phase") == "enqueue" \
                    and isinstance(e.get("rid"), int):
                enq[(h, e["rid"])] = e["t"]
    for rid, r in routes.items():
        pair = (anchor(0, rid, "route", True, r["t"]),
                anchor(r["replica"], rid, "queue", False,
                       enq.get((r["replica"], rid))))
        got = [g for g in pair if g is not None]
        if len(got) < 2:
            unmatched += 1
        gaps.extend(got)
    ts = [e["t"] for evs in shards.values() for e in evs
          if isinstance(e.get("t"), (int, float))]
    wall = (max(ts) - min(ts)) if ts else 0.0
    worst = max(gaps) if gaps else 0.0
    return {"rids": len(routes),
            "matched": len(routes) - unmatched,
            "unmatched": unmatched,
            "anchors": len(gaps),
            "max_gap_ms": round(worst * 1000.0, 3),
            "wall_s": round(wall, 3),
            "max_gap_frac": (worst / wall) if wall else 0.0}


def export(shards, profile=None, router=False) -> dict:
    """shards: {host: events}. Returns the trace-event JSON dict.
    Each shard is scoped to its latest run first (see latest_run).
    With router=True the process rows are named for the serve-fleet
    layout (host 0 is the router front door, host k replica k)."""
    shards = {h: latest_run(evs) for h, evs in shards.items()}
    all_events = [e for evs in shards.values() for e in evs]
    t_base = min((e["t"] for e in all_events
                  if isinstance(e.get("t"), (int, float))), default=0.0)
    trace_events = []
    for host, events in sorted(shards.items()):
        evs, _tracks = host_trace_events(host, events, t_base)
        trace_events.extend(evs)
    if router:
        for e in trace_events:
            if e.get("ph") == "M" and e.get("name") == "process_name":
                pid = e.get("pid")
                e["args"]["name"] = ("router" if pid == 0
                                     else f"replica {pid}")
    if profile:
        caps = [e for e in all_events
                if e["event"] == "profile_capture"]
        anchor = ((caps[0]["t"] - t_base) * 1e6) if caps else 0.0
        merge_profiler(trace_events, profile, anchor)
    return {"traceEvents": trace_events, "displayTimeUnit": "ms",
            "otherData": {"source": "mobilefinetuner_tpu trace_export",
                          "hosts": len(shards),
                          "t_base_unix": t_base}}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="telemetry stream(s) -> Perfetto trace-event JSON")
    ap.add_argument("jsonl", help="telemetry stream (--telemetry_out "
                                  "base path; .host<k> shards are "
                                  "discovered and merged)")
    ap.add_argument("-o", "--out", default="",
                    help="output file (default: <stream>.trace.json)")
    ap.add_argument("--profile", default="",
                    help="jax.profiler log dir (or trace.json[.gz]) to "
                         "merge as device-trace process rows")
    ap.add_argument("--router", action="store_true",
                    help="serve-fleet stream: name host 0 'router' and "
                         "host k 'replica k', and check the per-rid "
                         "route->enqueue clock gap across processes "
                         "(fails when it exceeds 1%% of wall)")
    args = ap.parse_args(argv)
    paths = discover_shards(args.jsonl)
    if not paths:
        print(f"error: no telemetry shards at {args.jsonl}",
              file=sys.stderr)
        return 1
    shards, n_bad = {}, 0
    for h, p in sorted(paths.items()):
        try:
            events, bad = load_events(p)
        except OSError as e:
            print(f"error: {e}", file=sys.stderr)
            return 1
        n_bad += bad
        if events:
            shards[h] = events
    if not shards:
        print(f"error: no valid telemetry events in "
              f"{sorted(paths.values())}", file=sys.stderr)
        return 1
    prof = None
    if args.profile:
        found = find_profiler_trace(args.profile)
        if found is None:
            print(f"error: no *.trace.json.gz under {args.profile}",
                  file=sys.stderr)
            return 1
        prof = load_profiler_events(found)
        print(f"device trace: {found} ({len(prof)} events)")
    trace = export(shards, profile=prof, router=args.router)
    out = args.out or (args.jsonl + ".trace.json")
    tmp = out + ".tmp"
    with open(tmp, "w") as f:
        json.dump(trace, f)
    os.replace(tmp, out)
    n_span = sum(1 for e in trace["traceEvents"] if e.get("ph") == "X")
    print(f"{out}: {len(trace['traceEvents'])} trace events "
          f"({n_span} spans) from {len(shards)} host shard(s)"
          + (f", {n_bad} invalid lines skipped" if n_bad else "")
          + " — open in ui.perfetto.dev")
    # reconciliation check: the COORDINATOR's phase-span sums vs its
    # run_end goodput buckets (the acceptance identity; per-host by
    # construction, so the comparison is scoped to pid 0)
    ends = [e for e in shards.get(0, []) if e["event"] == "run_end"
            and isinstance(e.get("goodput"), dict)]
    if ends:
        rec = phase_reconcile(trace, ends[-1]["goodput"], pid=0)
        if rec:
            total = ends[-1]["goodput"].get("total_s") or 0.0
            worst = max(d for _, _, d in rec.values())
            print(f"goodput reconciliation over {len(rec)} bucket(s): "
                  f"max |span_sum - bucket| = {worst:.4f}s"
                  + (f" ({100 * worst / total:.2f}% of total)"
                     if total else ""))
    if args.router:
        rr = router_reconcile(shards)
        if rr is None:
            print("error: --router but no route events in the stream",
                  file=sys.stderr)
            return 1
        print(f"router reconciliation: {rr['matched']}/{rr['rids']} "
              f"rids fully anchored"
              + (f" ({rr['unmatched']} settled off-stream)"
                 if rr["unmatched"] else "")
              + f", max span-placement gap over {rr['anchors']} "
              f"anchor(s) = {rr['max_gap_ms']}ms "
              f"({100 * rr['max_gap_frac']:.3f}% of {rr['wall_s']}s "
              f"wall)")
        if rr["max_gap_frac"] > 0.01:
            print("error: router/replica span reconciliation gap "
                  "exceeds 1% of wall clock", file=sys.stderr)
            return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
