"""Fleet controller: turn the observability sensors into automatic
recovery (ROADMAP item 4, DESIGN.md §18).

Rounds 8-10 built the sensors — per-host telemetry shards, straggler
attribution, the hang watchdog (`hang` events + exit 113), goodput
buckets, atomic async checkpoints. This supervisor closes the loop: it
launches one worker subprocess per host, tails the per-host shards
LIVE, and enacts policy:

  * **restart**: a worker that exits nonzero (crash, or the watchdog's
    113) — or whose shard shows a `hang` event while the process is
    still wedged (`--kill_on_hang`) — is relaunched with `{resume}`
    flags after exponential backoff, up to `--restart_budget` attempts.
    Training resumes from the last ATOMIC checkpoint (the round-10
    publication guarantee is what makes blind restart safe).
  * **shrink**: a worker whose budget is exhausted is declared LOST;
    with `--allow_shrink` the controller drains the survivors (SIGTERM
    → they exit EXIT_PREEMPTED with a final checkpoint) and relaunches
    the fleet at `hosts-1` — the `{hosts}` template field carries the
    new size, so a real launch can re-mesh (`--mesh_data`), and every
    relaunched worker `{resume}`s from its drain checkpoint.
  * **drain**: the controller's OWN SIGTERM/SIGINT forwards to every
    worker and waits for the preemption-drain exits — one signal
    cleanly parks the whole fleet.

Every decision is emitted as a `controller` telemetry event to
`<base>.controller` (its own stream — interleaving a second writer into
a worker shard would corrupt the (host, seq) merge key), which
`tools/fleet_report.py` renders next to the goodput buckets: recovery
cost becomes a visible line, not a mystery gap in step reach.

A clean worker exit is 0. EXIT_PREEMPTED (75) during a controller-
initiated drain counts as clean; OUTSIDE one (the platform preempted
the worker directly) it drained cleanly and is resumed after the base
backoff WITHOUT burning restart budget — the same verdict
`decide_worker` reaches replaying that shard. Everything else is a
failure that counts against the budget.

`--dry_run` replays a RECORDED shard set through the same decision
function and prints what the live policy would do — the cheap
contract-testable mode, and an operator's post-mortem tool.

Usage:
  python tools/fleet_controller.py --hosts 2 --telemetry run.jsonl \\
      --cmd "python tools/multihost_smoke.py --sim_worker --host {host} \\
             --hosts {hosts} --steps 20 --telemetry run.jsonl \\
             --ckpt w{host}.safetensors {resume}" \\
      --restart_budget 2 --backoff_s 0.5 --allow_shrink
  python tools/fleet_controller.py --telemetry run.jsonl --dry_run
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import subprocess
import sys
import time
from typing import Dict, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from telemetry_report import load_events, split_latest_run  # noqa: E402

from mobilefinetuner_tpu.core.preempt import (EXIT_PREEMPTED,  # noqa: E402
                                              PreemptionGuard)
from mobilefinetuner_tpu.core.telemetry import (Telemetry,  # noqa: E402
                                                controller_path,
                                                shard_path)


# --------------------------- shard tailing ----------------------------------

class ShardTail:
    """Incremental reader over one worker's telemetry shard: consumes
    only COMPLETE lines (a worker killed mid-write leaves a partial
    tail; we wait for the newline rather than mis-parse), tracking the
    facts the live policy needs — last observed step, hang-event
    count, and the latest run_end's exit NAME (round 16: an exit of
    MemoryAdmissionError marks an inadmissible CONFIG — the one class
    of nonzero exit a restart can never fix, so the policy gives up
    instead of burning the budget re-proving the same arithmetic;
    plain exit CODES carry the rest, and full run_end records remain
    the dry-run replay's input, not the live tail's)."""

    def __init__(self, path: str):
        self.path = path
        # start tailing at the CURRENT end of file: shards append across
        # controller sessions (Telemetry resumes seq), and replaying a
        # previous run's hang events into the live policy would SIGKILL
        # a freshly launched healthy worker (--dry_run is the tool that
        # reads history; the live tail reads only what happens now)
        try:
            self._off = os.path.getsize(path)
        except OSError:
            self._off = 0
        self.last_step: Optional[int] = None
        self.hangs = 0
        self.last_exit: Optional[str] = None  # latest run_end exit name

    def poll(self) -> None:
        try:
            size = os.path.getsize(self.path)
        except OSError:
            return  # not written yet
        if size <= self._off:
            return
        with open(self.path, "rb") as f:
            f.seek(self._off)
            buf = f.read(size - self._off)
        nl = buf.rfind(b"\n")
        if nl < 0:
            return
        self._off += nl + 1
        for raw in buf[:nl + 1].splitlines():
            try:
                rec = json.loads(raw)
            except (json.JSONDecodeError, UnicodeDecodeError):
                continue
            if not isinstance(rec, dict):
                continue
            self._see(rec)

    def _see(self, rec: dict) -> None:
        """Per-record dispatch — the override point for policy layers
        that tail richer shards (round 22: serve_router's ServeShardTail
        tracks per-rid terminal `request` events so a replica's death
        reroutes ONLY requests the shard never settled)."""
        ev = rec.get("event")
        if ev == "step_stats" and isinstance(rec.get("step"), int):
            self.last_step = rec["step"]
        elif ev == "hang":
            self.hangs += 1
        elif ev == "run_end":
            self.last_exit = rec.get("exit")


# --------------------------- decision function ------------------------------

def decide_worker(events) -> dict:
    """One worker shard -> the decision the live policy would take for
    it: the SHARED logic behind --dry_run (replay a recorded incident)
    and the operator's post-mortem reading. Scoped to the shard's
    LATEST run (telemetry_report's resume-append rule)."""
    truncated, latest = split_latest_run(events)
    stats = [e for e in latest if e.get("event") == "step_stats"]
    last_step = stats[-1]["step"] if stats else None
    hangs = [e for e in latest if e.get("event") == "hang"]
    ends = [e for e in latest if e.get("event") == "run_end"]
    if hangs and not ends:
        return {"decision": "restart", "reason": "hang",
                "step": hangs[-1].get("step", last_step)}
    if truncated or not ends:
        return {"decision": "restart", "reason": "crash",
                "step": last_step}
    end = ends[-1]
    if end.get("reason") == "preempted" or end.get("exit") == "preempted":
        return {"decision": "resume", "reason": "preempted",
                "step": last_step}
    if end.get("exit") == "MemoryAdmissionError":
        # inadmissible CONFIG (round-16 memory admission): the same
        # flags re-fail the same preflight on every launch — restarting
        # burns the budget proving arithmetic. The operator must change
        # the config (or let --on_oom_risk degrade walk the ladder).
        return {"decision": "give_up", "reason": "inadmissible_config",
                "step": last_step}
    if end.get("exit") != "ok":
        return {"decision": "restart",
                "reason": f"exit:{end.get('exit')}", "step": last_step}
    return {"decision": "none", "reason": "ok", "step": last_step}


def dry_run(base: str) -> int:
    """Replay a recorded shard set; print (don't enact) the decisions."""
    import fleet_report
    shards = fleet_report.discover_shards(base)
    if not shards:
        print(f"error: no telemetry shards at {base}", file=sys.stderr)
        return 1
    for host, path in sorted(shards.items()):
        events, bad = load_events(path)
        d = decide_worker(events)
        print(f"DRYRUN worker={host} decision={d['decision']} "
              f"reason={d['reason']} step={d['step']}"
              + (f" invalid_lines={bad}" if bad else ""))
    return 0


# --------------------------- the live controller ----------------------------

class _W:
    __slots__ = ("host", "proc", "attempts", "done", "lost", "tail",
                 "seen_hangs", "restarted", "relaunch_at", "down_t",
                 "down_reason", "pending_attempt", "backoff")

    def __init__(self, host: int, tail: ShardTail):
        self.host = host
        self.proc: Optional[subprocess.Popen] = None
        self.attempts = 0          # budgeted restarts consumed
        self.done = False
        self.lost = False
        self.tail = tail
        self.seen_hangs = 0        # hang events already acted on
        self.restarted = False     # next spawn passes {resume}
        # scheduled-relaunch state: handle_exit sets a DEADLINE instead
        # of sleeping the backoff inline — an inline sleep would stall
        # monitoring of every other worker (and the controller's own
        # SIGTERM) for the whole backoff
        self.relaunch_at: Optional[float] = None
        self.down_t = 0.0
        self.down_reason = ""
        self.pending_attempt: Optional[int] = None
        self.backoff = 0.0


class FleetController:
    def __init__(self, args):
        self.args = args
        self.tel = Telemetry(controller_path(args.telemetry), host=0)
        self.workers: Dict[int, _W] = {
            k: _W(k, ShardTail(shard_path(args.telemetry, k)))
            for k in range(args.hosts)}
        self.active_hosts = args.hosts
        self.guard = PreemptionGuard().install()
        self.t0 = time.time()

    # -- helpers --------------------------------------------------------------

    def record(self, action: str, worker=None, reason=None, attempt=None,
             backoff_s=None, step=None, recovery_s=None):
        self.tel.emit("controller", action=action, worker=worker,
                      reason=reason, attempt=attempt,
                      backoff_s=backoff_s, step=step,
                      recovery_s=recovery_s)
        bits = [f"controller: {action}"]
        if worker is not None:
            bits.append(f"worker={worker}")
        if reason:
            bits.append(f"reason={reason}")
        if step is not None:
            bits.append(f"step={step}")
        print("  ".join(bits), flush=True)

    def spawn(self, w: _W) -> None:
        cmd = self.args.cmd.format(
            host=w.host, hosts=self.active_hosts,
            resume=(self.args.resume_flags if w.restarted else ""))
        # own session: a terminal Ctrl-C must reach ONLY the controller
        # — if workers shared the foreground process group they would
        # get the SIGINT directly AND the controller's drain SIGTERM,
        # and a worker's PreemptionGuard treats the second signal as
        # "abort the drain" (losing the final checkpoint). All worker
        # signalling is explicit, from the drain/kill paths here.
        w.proc = subprocess.Popen(shlex.split(cmd),
                                  start_new_session=True)

    def alive(self):
        return [w for w in self.workers.values()
                if w.proc is not None and w.proc.poll() is None]

    def signal_all(self, sig) -> None:
        for w in self.alive():
            try:
                w.proc.send_signal(sig)
            except OSError:
                pass

    def wait_all(self, timeout_s: float) -> int:
        """Wait for every live worker; force-kill past the deadline.
        Marks clean completions (rc 0) done — an exit that lands during
        a drain window never reaches handle_exit, and a finished worker
        must not be respawned by a subsequent shrink relaunch. Returns
        the number of workers that had to be SIGKILLed (their drain
        checkpoint never landed)."""
        deadline = time.time() + timeout_s
        killed = 0
        for w in list(self.workers.values()):
            if w.proc is None:
                continue
            try:
                w.proc.wait(timeout=max(deadline - time.time(), 0.1))
            except subprocess.TimeoutExpired:
                w.proc.kill()
                w.proc.wait()
                killed += 1
            if w.proc.returncode == 0:
                w.done = True
                w.proc = None
        return killed

    # -- policy ---------------------------------------------------------------

    def handle_exit(self, w: _W, rc: int) -> None:
        reason = "hang" if rc == 113 else f"exit:{rc}"
        if w.seen_hangs < w.tail.hangs:
            reason = "hang"  # the shard names the incident
        if rc == 0:
            # (controller-initiated drains never reach here — shrink()/
            # drain() reap their exits via wait_all; an exit-75 HERE is
            # always an external preemption, handled below)
            w.done = True
            w.proc = None
            return
        w.proc = None
        w.seen_hangs = w.tail.hangs
        w.down_t = time.time()
        if rc == EXIT_PREEMPTED:
            # externally-preempted worker (the platform SIGTERMed it,
            # not us): it drained cleanly and its checkpoint is durable
            # — RESUME without burning restart budget, mirroring what
            # decide_worker says about the same shard. The base backoff
            # still applies (give the platform's disruption a beat).
            w.down_reason = "preempted"
            w.pending_attempt = None
            w.backoff = self.args.backoff_s
            self.record("down", worker=w.host, reason="preempted",
                        step=w.tail.last_step)
            w.relaunch_at = w.down_t + w.backoff
            return
        if w.tail.last_exit == "MemoryAdmissionError":
            # the shard names an INADMISSIBLE CONFIG (round-16 memory
            # admission): deterministic — every relaunch re-fails the
            # same preflight, so give up now with the budget intact
            # (mirrors decide_worker's 'give_up/inadmissible_config')
            self.record("down", worker=w.host,
                        reason="inadmissible_config",
                        step=w.tail.last_step)
            self.give_up(f"worker {w.host} config failed memory "
                         f"admission (MemoryAdmissionError) — a "
                         f"restart cannot fix it")
            return
        self.record("down", worker=w.host, reason=reason,
                    step=w.tail.last_step)
        w.attempts += 1
        if w.attempts <= self.args.restart_budget:
            # schedule, don't sleep: the poll loop relaunches when the
            # deadline passes, and keeps watching everyone meanwhile
            w.down_reason = reason
            w.pending_attempt = w.attempts
            w.backoff = self.args.backoff_s * (2 ** (w.attempts - 1))
            w.relaunch_at = w.down_t + w.backoff
            return
        # budget exhausted: the host is LOST
        w.lost = True
        self.record("lost", worker=w.host, reason=reason,
                    attempt=w.attempts, step=w.tail.last_step)
        if self.args.allow_shrink \
                and self.active_hosts - 1 >= self.args.min_hosts:
            self.shrink(lost=w, t_down=w.down_t)
        else:
            self.give_up(f"worker {w.host} lost, shrink unavailable")

    def maybe_relaunch(self, w: _W) -> None:
        """Fire a scheduled relaunch once its backoff deadline passes."""
        if w.relaunch_at is None or time.time() < w.relaunch_at:
            return
        w.relaunch_at = None
        w.restarted = True
        self.spawn(w)
        self.record("restart", worker=w.host, reason=w.down_reason,
                    attempt=w.pending_attempt,
                    backoff_s=round(w.backoff, 3),
                    step=w.tail.last_step,
                    recovery_s=round(time.time() - w.down_t, 3))

    def shrink(self, lost: _W, t_down: float) -> None:
        """Drain the survivors (SIGTERM -> preemption drain -> atomic
        checkpoint) and relaunch the fleet one host smaller, every
        worker resuming from its drain checkpoint. The shrunk size
        reaches the workers through the {hosts} template field. A
        survivor SIGKILLed for blowing the drain timeout is still
        relaunched (its last PERIODIC checkpoint is the best recovery
        point available) but the forced kill is recorded on the shrink
        event — the post-mortem must see that this host may replay
        steps since its drain save never landed."""
        self.signal_all(signal.SIGTERM)
        killed = self.wait_all(self.args.drain_timeout_s)
        self.active_hosts -= 1
        for w in self.workers.values():
            if w.lost or w.done:
                continue
            w.relaunch_at = None  # the shrink relaunch supersedes any
            w.restarted = True    # scheduled single-worker restart
            self.spawn(w)
        self.record("shrink", worker=lost.host,
                    reason=f"worker {lost.host} lost"
                           + (f"; {killed} survivor(s) force-killed "
                              f"mid-drain" if killed else ""),
                    step=lost.tail.last_step,
                    recovery_s=round(time.time() - t_down, 3))

    def give_up(self, reason: str) -> None:
        self.signal_all(signal.SIGTERM)
        self.wait_all(self.args.drain_timeout_s)
        self.record("give_up", reason=reason)
        self.tel.close()
        sys.exit(1)

    def drain(self) -> None:
        self.record("drain", reason=self.guard.signal_name or "SIGTERM")
        self.signal_all(signal.SIGTERM)
        killed = self.wait_all(self.args.drain_timeout_s)
        parked = crashed = 0
        for w in self.workers.values():
            if w.done:
                continue
            if w.proc is None:
                continue
            if w.proc.returncode == EXIT_PREEMPTED:
                w.done = True
                parked += 1
            else:
                # a worker that died with a CRASH code during the drain
                # window left no drain checkpoint either — the park is
                # not fully resumable for it, same as a forced kill
                crashed += 1
        if killed or crashed:
            # some worker's final checkpoint never landed (SIGKILLed
            # past the timeout, or crashed mid-drain): this park is NOT
            # fully resumable — say so in the event and the exit code
            self.record("stop", reason=f"drain_incomplete:{killed} "
                                       f"killed, {crashed} crashed, "
                                       f"{parked} parked")
            self.tel.close()
            sys.exit(1)
        self.record("stop", reason=f"drained:{parked} parked")
        self.tel.close()
        sys.exit(0)

    # -- main loop ------------------------------------------------------------

    def run(self) -> int:
        for w in self.workers.values():
            w.restarted = self.args.resume_first
            self.spawn(w)
            self.record("launch", worker=w.host)
        while True:
            if self.guard.triggered:
                self.drain()
            if self.args.max_wall_s \
                    and time.time() - self.t0 > self.args.max_wall_s:
                self.give_up("max_wall_s exceeded")
            pending = False
            for w in self.workers.values():
                if w.done or w.lost:
                    continue
                if w.proc is None:
                    if w.relaunch_at is not None:
                        pending = True
                        self.maybe_relaunch(w)
                    continue
                pending = True
                w.tail.poll()
                rc = w.proc.poll()
                if rc is not None:
                    w.tail.poll()  # drain the tail the exit flushed
                    self.handle_exit(w, rc)
                    continue
                if self.args.kill_on_hang \
                        and w.tail.hangs > w.seen_hangs:
                    # the shard reports a hang but the process is still
                    # wedged (watchdog mode 1, or a hang between report
                    # and abort): reclaim the host
                    try:
                        w.proc.kill()
                    except OSError:
                        pass
            if not pending:
                break
            time.sleep(self.args.poll_s)
        ok = all(w.done for w in self.workers.values() if not w.lost)
        self.record("stop", reason="complete" if ok else "incomplete")
        self.guard.uninstall()
        self.tel.close()
        return 0 if ok else 1


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="fleet_controller",
        description="elastic-fleet supervisor over per-host telemetry "
                    "shards (DESIGN.md §18)")
    ap.add_argument("--telemetry", required=True,
                    help="telemetry base path (worker shards at "
                         "<base>/<base>.host<k>; controller events at "
                         "<base>.controller)")
    ap.add_argument("--cmd", default="",
                    help="worker command template; {host}/{hosts}/"
                         "{resume} are substituted per spawn")
    ap.add_argument("--hosts", type=int, default=2)
    ap.add_argument("--restart_budget", type=int, default=2,
                    help="restarts per worker before it is declared "
                         "lost")
    ap.add_argument("--backoff_s", type=float, default=0.5,
                    help="restart backoff base (doubles per attempt)")
    ap.add_argument("--resume_flags", default="--resume",
                    help="what {resume} expands to on restarts")
    ap.add_argument("--resume_first", action="store_true",
                    help="pass {resume} on the FIRST launch too "
                         "(controller itself restarted mid-run)")
    ap.add_argument("--allow_shrink", action="store_true",
                    help="on a lost worker: drain survivors and "
                         "relaunch the fleet one host smaller")
    ap.add_argument("--min_hosts", type=int, default=1)
    ap.add_argument("--kill_on_hang", type=int, default=1,
                    help="SIGKILL a live worker whose shard reports a "
                         "hang event (watchdog mode 1 wedges)")
    ap.add_argument("--drain_timeout_s", type=float, default=30.0)
    ap.add_argument("--poll_s", type=float, default=0.05)
    ap.add_argument("--max_wall_s", type=float, default=0.0,
                    help="safety net: give up past this wall time "
                         "(0 = off)")
    ap.add_argument("--dry_run", action="store_true",
                    help="replay the recorded shard set at --telemetry "
                         "and print the decisions; no processes")
    args = ap.parse_args(argv)
    if args.dry_run:
        return dry_run(args.telemetry)
    if not args.cmd:
        ap.error("--cmd is required (unless --dry_run)")
    return FleetController(args).run()


if __name__ == "__main__":
    sys.exit(main())
