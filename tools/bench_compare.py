"""Diff two BENCH_*.json artifacts: the bench trajectory as a CI gate.

Every round's bench capture lands as rows keyed by a `config` string
(bench.py JSONL, BENCH_SERVE's {"rows": [...]}, BENCH_CKPT, the
driver's {"tail": "<jsonl>"} wrapper, and BENCH_SUITE's
{"suite": [...]} — all five shapes load here).
This tool matches rows by that key across two artifacts, prints the
per-metric % delta for every shared numeric metric, and — with
`--threshold P` — exits NONZERO when any direction-aware metric
regressed by more than P percent, so "did this PR slow the bench" is a
CI check instead of a human squinting at two JSON files.

Direction is inferred from the metric name (throughput-ish names are
higher-better, latency/memory-ish names lower-better, everything else
informational — reported, never gated):

  higher better:  *tok*_s*, *tokens_per_sec*, mfu*, req_s, mb_s
  lower better:   *_ms (incl. nested ttft_ms.p50 etc.), *_mb, *stall*,
                  *blocking*, *bytes*

Nested dicts one level deep (the serve rows' ttft_ms/tpot_ms
percentile dicts) are flattened to dotted keys.

Usage:
  python tools/bench_compare.py OLD.json NEW.json
  python tools/bench_compare.py OLD.json NEW.json --threshold 5
  python tools/bench_compare.py OLD.json NEW.json --json
  python tools/bench_compare.py --registry runs.jsonl --run OLD NEW
Exit codes: 0 = ok, 1 = usage/load error or no shared rows,
2 = regression beyond --threshold, 3 = a direction-aware metric
present in OLD is MISSING from NEW under a threshold (a deleted
metric must not read as "no regression" — distinct code so CI can
tell "got slower" from "stopped measuring").
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

_HIGHER = ("tokens_per_sec", "tok_s", "mfu", "req_s", "mb_s",
           "productive_frac", "requests", "hit_rate", "goodput")
_LOWER = ("_ms", "_mb", "stall", "blocking", "bytes", "elapsed_s",
          "retraces", "pages_per_req")
# relative-to-moving-target noise, plus router placement spread (how
# many requests each replica drew is topology weather, not a regression)
_SKIP = ("vs_baseline", "per_replica")


def direction(name: str) -> int:
    """+1 higher-better, -1 lower-better, 0 informational."""
    n = name.lower()
    if any(s in n for s in _SKIP):
        return 0
    if any(s in n for s in _HIGHER):
        return +1
    if any(s in n for s in _LOWER):
        return -1
    return 0


def _flatten(row: dict) -> dict:
    out = {}
    for k, v in row.items():
        if isinstance(v, dict):
            for kk, vv in v.items():
                if isinstance(vv, (int, float)) \
                        and not isinstance(vv, bool):
                    out[f"{k}.{kk}"] = float(vv)
        elif isinstance(v, (int, float)) and not isinstance(v, bool):
            out[k] = float(v)
    return out


def load_rows(path: str, key: str = "config") -> dict:
    """{config: flattened numeric row} from any of the artifact shapes
    this repo produces (rows list, bare list, driver tail wrapper,
    plain JSONL)."""
    with open(path) as f:
        txt = f.read()
    rows = []
    try:
        data = json.loads(txt)
        if isinstance(data, list):
            rows = data
        elif isinstance(data, dict) and isinstance(data.get("rows"), list):
            rows = data["rows"]
        elif isinstance(data, dict) and isinstance(data.get("suite"),
                                                   list):
            # bench.py's BENCH_SUITE.json artifact (round 18: the
            # multitenant step_time-vs-k rows ride it) — rows keyed by
            # config like every other shape
            rows = data["suite"]
        elif isinstance(data, dict) and isinstance(data.get("tail"), str):
            # the driver's bench capture: rc/cmd wrapper whose tail is
            # the benchmark's JSONL stdout
            rows = [json.loads(ln) for ln in data["tail"].splitlines()
                    if ln.strip().startswith("{")]
        elif isinstance(data, dict):
            rows = [data]
    except json.JSONDecodeError:
        # plain JSONL
        for ln in txt.splitlines():
            ln = ln.strip()
            if ln.startswith("{"):
                try:
                    rows.append(json.loads(ln))
                except json.JSONDecodeError:
                    continue
    out = {}
    for r in rows:
        if isinstance(r, dict) and isinstance(r.get(key), str):
            out[r[key]] = _flatten(r)
    return out


def compare(old: dict, new: dict, threshold: float = 0.0) -> dict:
    """Row-matched per-metric deltas. A REGRESSION is a direction-aware
    metric worse by more than `threshold` percent (threshold <= 0:
    nothing gates, everything reports). A direction-aware metric
    present in OLD but absent from NEW is a DROPPED metric — reported
    separately (and exit 3 under a threshold): deleting a metric must
    not read as "no regression"."""
    shared = sorted(set(old) & set(new))
    rows = []
    regressions = []
    dropped = []
    for cfg in shared:
        o, n = old[cfg], new[cfg]
        for metric in sorted(set(o) - set(n)):
            dropped.append({"config": cfg, "metric": metric,
                            "direction": {1: "higher", -1: "lower",
                                          0: None}[direction(metric)]})
        for metric in sorted(set(o) & set(n)):
            ov, nv = o[metric], n[metric]
            if ov == 0:
                continue  # % delta undefined; absolute-only metrics skip
            delta_pct = (nv - ov) / abs(ov) * 100.0
            d = direction(metric)
            worse_pct = -delta_pct * d if d else 0.0
            regressed = bool(d and threshold > 0
                             and worse_pct > threshold)
            rows.append({"config": cfg, "metric": metric,
                         "old": ov, "new": nv,
                         "delta_pct": round(delta_pct, 3),
                         "direction": {1: "higher", -1: "lower",
                                       0: None}[d],
                         "regressed": regressed})
            if regressed:
                regressions.append(rows[-1])
    gated_drops = [d for d in dropped if d["direction"]] \
        if threshold > 0 else []
    return {
        "shared_rows": shared,
        "only_old": sorted(set(old) - set(new)),
        "only_new": sorted(set(new) - set(old)),
        "threshold_pct": threshold,
        "metrics": rows,
        "regressions": regressions,
        "dropped": dropped,
        "gated_drops": gated_drops,
    }


def print_compare(c: dict) -> None:
    if c["only_old"]:
        print(f"rows only in OLD: {', '.join(c['only_old'])}")
    if c["only_new"]:
        print(f"rows only in NEW: {', '.join(c['only_new'])}")
    cur = None
    for m in c["metrics"]:
        if m["config"] != cur:
            cur = m["config"]
            print(f"{cur}:")
        arrow = {"higher": "^", "lower": "v", None: " "}[m["direction"]]
        flag = "  REGRESSED" if m["regressed"] else ""
        print(f"  {m['metric']:<28} {m['old']:>12.4g} -> "
              f"{m['new']:>12.4g}  {m['delta_pct']:>+8.2f}% "
              f"{arrow}{flag}")
    for d in c.get("dropped", []):
        gate = "  [gates: exit 3]" if d["direction"] \
            and c["threshold_pct"] > 0 else ""
        print(f"  {d['config']}: metric {d['metric']} present in OLD, "
              f"missing from NEW{gate}")
    if c["regressions"]:
        print(f"\n{len(c['regressions'])} metric(s) regressed beyond "
              f"{c['threshold_pct']:g}%")
    elif c.get("gated_drops"):
        print(f"\n{len(c['gated_drops'])} direction-aware metric(s) "
              f"dropped from NEW (a deleted metric cannot pass the "
              f"gate)")
    elif c["threshold_pct"] > 0:
        print(f"\nno regression beyond {c['threshold_pct']:g}% across "
              f"{len(c['shared_rows'])} shared row(s)")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="diff two BENCH_*.json artifacts by config row")
    ap.add_argument("old", nargs="?", default="")
    ap.add_argument("new", nargs="?", default="")
    ap.add_argument("--key", default="config",
                    help="row-matching key (default: config)")
    ap.add_argument("--threshold", type=float, default=0.0,
                    help="exit 2 when any direction-aware metric is "
                         "worse by more than this percent; exit 3 when "
                         "a direction-aware metric was dropped from "
                         "NEW (0 = report only)")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable comparison instead of text")
    ap.add_argument("--registry", default="",
                    help="run registry stream (core/run_registry.py); "
                         "default $MFT_RUN_REGISTRY")
    ap.add_argument("--run", nargs=2, metavar=("OLD", "NEW"),
                    default=None,
                    help="resolve OLD/NEW artifacts from the registry "
                         "by run id, id prefix, or git rev — after "
                         "resolution this IS a path invocation, so "
                         "output is byte-identical")
    args = ap.parse_args(argv)
    old_path, new_path = args.old, args.new
    if args.run:
        from mobilefinetuner_tpu.core.run_registry import registry_from
        reg = registry_from(args.registry)
        if reg is None:
            print("error: --run needs --registry or $MFT_RUN_REGISTRY",
                  file=sys.stderr)
            return 1
        resolved = []
        for token in args.run:
            p = reg.artifact_for(token, suffix=".json")
            if not p:
                print(f"error: --run {token!r}: no .json artifact "
                      f"resolved from registry {reg.path}",
                      file=sys.stderr)
                return 1
            resolved.append(p)
        old_path, new_path = resolved
    if not old_path or not new_path:
        ap.error("pass OLD NEW paths or --run OLD NEW")
    try:
        old = load_rows(old_path, key=args.key)
        new = load_rows(new_path, key=args.key)
    except OSError as e:
        print(f"error: {e}", file=sys.stderr)
        return 1
    if not old or not new:
        print(f"error: no keyed rows in "
              f"{old_path if not old else new_path}", file=sys.stderr)
        return 1
    c = compare(old, new, threshold=args.threshold)
    if not c["shared_rows"]:
        print("error: no shared rows to compare", file=sys.stderr)
        return 1
    if args.json:
        print(json.dumps(c, indent=1))
    else:
        print_compare(c)
    if c["regressions"]:
        return 2
    if c["gated_drops"]:
        return 3
    return 0


if __name__ == "__main__":
    sys.exit(main())
