"""Serve-fleet front door: metrics-driven routing over N engine
replicas (ROADMAP item 2's remainder, DESIGN.md §27).

One process per replica runs a single-threaded ServeEngine behind the
round-17 MetricsServer (`--serve_replica` mode below): /metrics and
/healthz as before, plus two JSON data-plane routes — POST /submit
queues a request for the engine's main loop, POST /collect drains the
terminal results the loop has produced. The ROUTER process supervises
those replicas with the r13 fleet-controller restart/backoff machinery,
scrapes every replica's /metrics + /healthz on a cadence, and places
each arriving request by policy:

  affinity      the request names an adapter and some healthy replica
                holds it resident — route least-loaded WITHIN those
                (LoRAFusion's job-level batching instinct: tenants keep
                hitting warm banks and warm prefix caches)
  least_loaded  no adapter (or nobody holds it): least queue_depth +
                active over every fresh, non-draining snapshot
  failover      the chosen replica refused or was unreachable (it died
                between scrape and forward, or is mid-drain) — walk the
                remaining candidates; also stamped on requests re-routed
                off a dead replica
  reject        no routable replica at all: the router answers 503

Every decision is a `route` telemetry event in the ROUTER's stream
(`<base>` — the coordinator shard; replicas write `<base>.host<k>`,
controller events `<base>.controller`), and every routed request gets a
fleet-wide `rid` that rides submit() into the replica's `request`
events and `req:<id>` span track, so `trace_export --router` can join
the router's queue/route spans to the replica-side lifecycle in one
Perfetto timeline.

Replica death is settled from the SHARD, not from memory: Telemetry
flushes per event, so a SIGKILLed replica's shard still names every
request it terminated. The router tails each shard live
(ServeShardTail); on an exit, inflight rids the shard settled are
delivered from the shard record, and ONLY the remainder is re-routed
to survivors — no request is lost and none can double-terminate.

The router serves its own MetricsRegistry: per-replica labeled gauges
(`mft_fleet_*{replica="k"}`) refreshed by the scrape, fleet-level
TTFT/TPOT/queue-wait histograms folded from collected results, and the
`route` decision counter from its own stream.

Usage:
  python tools/serve_router.py --telemetry /tmp/fleet.jsonl \\
      --replicas 2 --engine_json '{"model": "tiny-gpt2"}' --port 0
  # front door: POST /submit {"prompt": [...], "adapter": "tenant0"}
  #             POST /collect {} -> {"done": [...]}
  #             GET  /fleet -> supervision snapshot (pids, ports)
"""

from __future__ import annotations

import argparse
import json
import os
import shlex
import signal
import sys
import threading
import time
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, __file__.rsplit("/", 2)[0])
sys.path.insert(0, __file__.rsplit("/", 1)[0])

from fleet_controller import FleetController, ShardTail, _W  # noqa: E402

from mobilefinetuner_tpu.core.metrics_http import (MetricsRegistry,  # noqa: E402
                                                   MetricsServer)
from mobilefinetuner_tpu.core.preempt import EXIT_PREEMPTED  # noqa: E402
from mobilefinetuner_tpu.core.telemetry import (Telemetry,  # noqa: E402
                                                shard_path)
from mobilefinetuner_tpu.core.trace import Tracer  # noqa: E402

# lock-discipline declaration (core/static_checks.py, DESIGN.md §24).
# Three shared surfaces, one lock each:
#   ReplicaGateway  HTTP handler threads push submits / pop results;
#                   the engine's single main thread pumps between them
#   ScrapeCache     the scrape thread writes snapshots; handler threads
#                   (routing decisions) and the main loop read them
#   RouterCore      handler threads stamp rids and track inflight; the
#                   collector thread and the supervision loop resolve
GRAFT_SHARED_STATE = {
    "ReplicaGateway": {
        "lock": "_lock",
        "guarded": ["_inbox", "_outbox", "_draining"],
        "locked_helpers": [],
        "channels": [],
        "note": "submit/collect ride the MetricsServer handler threads; "
                "pump() is the engine main loop's only touchpoint",
    },
    "ScrapeCache": {
        "lock": "_lock",
        "guarded": ["_snap"],
        "locked_helpers": [],
        "channels": [],
        "note": "whole-snapshot copies in and out; readers never see a "
                "half-written replica entry",
    },
    "RouterCore": {
        "lock": "_lock",
        "guarded": ["_next_rid", "_inflight", "_results", "_closed",
                    "routed"],
        "locked_helpers": [],
        "channels": [],
        "note": "the rid counter and the inflight/results maps are the "
                "exact-accounting invariant: a rid moves inflight -> "
                "results exactly once, whichever thread settles it",
    },
}

_TERMINAL_PHASES = ("finish", "cancel", "reject", "timeout", "error")

DEFAULT_ENGINE_SPEC = {
    # tiny-gpt2 has n_positions=64: max_prompt + max_new must fit
    "model": "tiny-gpt2", "num_slots": 4, "block_T": 16,
    "num_blocks": 64, "max_prompt": 32, "max_new": 16, "adapters": 0,
    "dtype": "float32", "seed": 0, "max_queue": 0,
    "shed_policy": "reject", "on_step_error": "fail_active",
    # serve_stats on a cadence (the scrape's gauge source) and request
    # spans on (the --router timeline's replica half) by default
    "stats_every": 10, "trace_spans": True,
    "prefix_cache": False, "max_prompt_chunked": 0, "sampling": False,
}


# --------------------------- small plumbing ---------------------------------

def _http_json(method: str, url: str, payload=None, timeout: float = 5.0
               ) -> Tuple[int, dict]:
    """One JSON round trip; non-2xx responses return their code + body
    instead of raising (a draining replica's 503 carries information)."""
    data = json.dumps(payload).encode() if payload is not None else None
    req = urllib.request.Request(url, data=data, method=method)
    if data is not None:
        req.add_header("Content-Type", "application/json")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as r:
            body = r.read().decode()
            code = r.status
    except urllib.error.HTTPError as e:
        body = e.read().decode()
        code = e.code
    try:
        obj = json.loads(body) if body else {}
    except json.JSONDecodeError:
        obj = {}
    return code, obj if isinstance(obj, dict) else {}


def _http_text(url: str, timeout: float = 5.0) -> str:
    with urllib.request.urlopen(url, timeout=timeout) as r:
        return r.read().decode()


def port_file(base: str, host: int) -> str:
    """Where process `host` publishes its bound HTTP port (0 = the
    router's front door, k >= 1 a replica) — ports are ephemeral by
    default, so discovery rides the telemetry base path."""
    return f"{base}.port{host}"


def write_port_file(base: str, host: int, port: int) -> None:
    tmp = port_file(base, host) + ".tmp"
    with open(tmp, "w") as f:
        json.dump({"host": host, "port": port, "pid": os.getpid()}, f)
    os.replace(tmp, port_file(base, host))


def read_port_file(base: str, host: int) -> Optional[dict]:
    try:
        with open(port_file(base, host)) as f:
            obj = json.load(f)
        return obj if isinstance(obj, dict) and "port" in obj else None
    except (OSError, json.JSONDecodeError):
        return None


def parse_serve_gauges(text: str) -> Dict[str, float]:
    """The scrape's half of the round-17 exposition contract: pull the
    unlabeled `mft_serve_*` gauge samples out of an OpenMetrics body
    (the engine's loop vitals — queue depth, occupancy, free pages,
    p95 step ms, r21 cache counters)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        if not line or line.startswith("#"):
            continue
        name, _, val = line.rpartition(" ")
        if name.startswith("mft_serve_") and "{" not in name:
            try:
                out[name[len("mft_serve_"):]] = float(val)
            except ValueError:
                pass
    return out


# --------------------------- replica side -----------------------------------

class ReplicaGateway:
    """The replica's HTTP data plane. The engine stays single-threaded:
    handler threads only queue submits into `_inbox` and drain results
    from `_outbox`; the main loop's `pump()` moves everything between
    the lists and the engine."""

    def __init__(self):
        self._lock = threading.Lock()
        self._inbox: List[dict] = []
        self._outbox: List[dict] = []
        self._draining = False

    # -- HTTP routes (handler threads) ---------------------------------------

    def route_submit(self, payload) -> Tuple[int, dict]:
        if not isinstance(payload, dict) or "prompt" not in payload:
            return 400, {"accepted": False, "reason": "bad_request"}
        with self._lock:
            if self._draining:
                return 503, {"accepted": False, "draining": True,
                             "reason": "shutdown"}
            self._inbox.append(payload)
        return 200, {"accepted": True, "rid": payload.get("rid")}

    def route_collect(self, payload) -> Tuple[int, dict]:
        with self._lock:
            out, self._outbox = self._outbox, []
        return 200, {"done": out}

    # -- main-loop side -------------------------------------------------------

    def begin_drain(self) -> None:
        with self._lock:
            self._draining = True

    def outbox_size(self) -> int:
        with self._lock:
            return len(self._outbox)

    @staticmethod
    def summarize(req) -> dict:
        """The collect-row shape: everything the router needs to settle
        a rid and fold fleet SLO histograms, nothing engine-internal."""
        return {
            "rid": req.rid, "id": req.id, "state": req.state,
            "reason": req.reason, "adapter": req.adapter,
            "prompt_tokens": len(req.prompt),
            "new_tokens": len(req.tokens),
            "ttft_ms": req.ttft_ms, "tpot_ms": req.tpot_ms,
            "queue_ms": ((req.admit_t - req.enqueue_t) * 1000.0
                         if req.admit_t else None),
        }

    def push(self, reqs) -> None:
        rows = [self.summarize(r) for r in reqs if r.done]
        if rows:
            with self._lock:
                self._outbox.extend(rows)

    def pump(self, eng) -> bool:
        """One main-loop beat: drain the inbox into submit(), one
        step() when work is pending, terminal results to the outbox.
        Returns whether anything moved (the idle loop sleeps)."""
        with self._lock:
            batch, self._inbox = self._inbox, []
        term = []
        for p in batch:
            try:
                req = eng.submit(
                    p["prompt"],
                    max_new_tokens=int(p.get("max_new_tokens") or 0),
                    adapter=p.get("adapter"),
                    deadline_ms=p.get("deadline_ms"),
                    temperature=float(p.get("temperature") or 0.0),
                    top_k=int(p.get("top_k") or 0),
                    top_p=float(p.get("top_p") if p.get("top_p")
                                is not None else 1.0),
                    seed=int(p.get("seed") or 0),
                    rid=p.get("rid"))
                if req.done:   # submit-time reject (queue_full, ...)
                    term.append(req)
            except (ValueError, KeyError, RuntimeError) as e:
                # a malformed payload fails ONE request, not the
                # replica; no engine record exists, so synthesize the
                # settle row here
                with self._lock:
                    self._outbox.append({
                        "rid": p.get("rid"), "id": None,
                        "state": "error", "reason": type(e).__name__,
                        "adapter": p.get("adapter"), "prompt_tokens": 0,
                        "new_tokens": 0, "ttft_ms": None,
                        "tpot_ms": None, "queue_ms": None})
        moved = bool(batch)
        if not eng.idle:
            term.extend(eng.step())
            moved = True
        self.push(term)
        return moved


def replica_main(args) -> int:
    """`--serve_replica`: one engine process under the router's
    supervision. Builds the engine via serve_bench.build_engine (one
    construction path for bench and fleet), writes its shard at
    shard_path(base, host) with host=<k> envelope stamps, serves
    /metrics + /healthz + /submit + /collect on one ephemeral port
    published through the port file, and drains on SIGTERM exactly
    like a directly-driven engine (queue rejected reason=shutdown,
    in-flight decoded out, run_end{exit=preempted}, exit code 75)."""
    import serve_bench  # imports jax — replica processes only
    spec = dict(DEFAULT_ENGINE_SPEC)
    with open(args.engine_json) as f:
        spec.update(json.load(f))
    unknown = set(spec) - set(DEFAULT_ENGINE_SPEC)
    if unknown:
        raise SystemExit(f"unknown engine spec keys: {sorted(unknown)}")
    base, k = args.telemetry, args.host
    eng, names = serve_bench.build_engine(
        telemetry_out=shard_path(base, k), host=k, **spec)
    registry = MetricsRegistry()
    eng.telemetry.add_observer(registry.observe)
    gw = ReplicaGateway()

    def health():
        # engine.health() already leads with status=draining when
        # admissions are closed — metrics_http turns that into the 503
        # the router's scrape keys on; replica identity and the
        # resident-adapter set ride along for affinity scoring
        return {**eng.health(), "replica": k, "adapters": list(names)}

    server = MetricsServer(registry, port=args.port, addr=args.addr,
                           health_fn=health,
                           routes={"/submit": gw.route_submit,
                                   "/collect": gw.route_collect})
    write_port_file(base, k, server.port)
    guard = eng.install_preemption()
    try:
        while not guard.triggered:
            if not gw.pump(eng) and eng.idle:
                time.sleep(0.002)
        # drain: close the HTTP intake first (new submits 503), then
        # the engine path — queued remainder rejects, in-flight decodes
        # to completion; a second signal escalates out of drain()
        gw.begin_drain()
        gw.push(eng.begin_shutdown())
        try:
            gw.push(eng.drain())
        except KeyboardInterrupt:
            active = list(eng.active)
            for req in active:
                eng.cancel(req)
            gw.push(active)
        # linger briefly so the router's collector can pick up the
        # final rows over HTTP (the shard tail is the fallback if not)
        deadline = time.time() + args.linger_s
        while time.time() < deadline and gw.outbox_size():
            time.sleep(0.02)
    finally:
        server.close()
        eng.close()
        try:
            os.remove(port_file(base, k))
        except OSError:
            pass
    return EXIT_PREEMPTED if guard.triggered else 0


# --------------------------- router side ------------------------------------

class ServeShardTail(ShardTail):
    """The r13 shard tail, extended with the serve-fleet fact the
    death-settlement protocol needs: which rids this replica already
    TERMINATED (Telemetry flushes per event, so the shard is durable
    ground truth at SIGKILL — anything it settled must be delivered,
    never re-routed)."""

    def __init__(self, path: str):
        super().__init__(path)
        self.terminal: Dict[int, dict] = {}

    def _see(self, rec: dict) -> None:
        super()._see(rec)
        if rec.get("event") == "request" \
                and isinstance(rec.get("rid"), int) \
                and rec.get("phase") in _TERMINAL_PHASES:
            self.terminal[rec["rid"]] = rec


_PHASE_STATE = {"finish": "finished", "cancel": "cancelled",
                "reject": "rejected", "timeout": "timeout",
                "error": "error"}


def row_from_shard(rec: dict) -> dict:
    """Rebuild a collect-row from a shard `request` record (the
    death-settlement path: the replica died before /collect returned
    this result, but its flushed shard already has the terminal)."""
    return {
        "rid": rec.get("rid"), "id": rec.get("id"),
        "state": _PHASE_STATE.get(rec.get("phase"), "error"),
        "reason": rec.get("reason"), "adapter": rec.get("adapter"),
        "prompt_tokens": rec.get("prompt_tokens"),
        "new_tokens": rec.get("new_tokens") or 0,
        "ttft_ms": rec.get("ttft_ms"), "tpot_ms": rec.get("tpot_ms"),
        "queue_ms": rec.get("queue_ms"),
    }


class ScrapeCache:
    """Latest per-replica scrape snapshot, one lock. A snapshot is one
    dict: {t, port, status, draining, adapters, queue_depth, active,
    occupancy, free_blocks, p95_step_ms, ...} — routing reads a
    whole-cache copy and never blocks the scraper."""

    def __init__(self):
        self._lock = threading.Lock()
        self._snap: Dict[int, dict] = {}

    def put(self, replica: int, snap: dict) -> None:
        with self._lock:
            self._snap[replica] = snap

    def drop(self, replica: int) -> None:
        with self._lock:
            self._snap.pop(replica, None)

    def snapshot(self) -> Dict[int, dict]:
        with self._lock:
            return dict(self._snap)


class RouterCore:
    """Placement decisions + the exact-accounting rid ledger.

    A rid is stamped under the lock, lives in `_inflight` while some
    replica owns it, and moves to `_results` exactly once — settled by
    the collector thread (HTTP /collect), by the supervision loop
    (shard record of a dead replica), or by the router itself (reject).
    `deliver` ignores duplicates, so the shard-settlement path and a
    late /collect row can race without double-terminating."""

    def __init__(self, tel: Telemetry, tracer: Tracer,
                 registry: MetricsRegistry, cache: ScrapeCache,
                 max_age_s: float):
        self._lock = threading.Lock()
        self._next_rid = 0
        self._inflight: Dict[int, dict] = {}
        self._results: Dict[int, dict] = {}
        self._closed = False
        self.routed = 0
        self.tel = tel
        self.tracer = tracer
        self.registry = registry
        self.cache = cache
        self.max_age_s = max_age_s

    # -- intake state ---------------------------------------------------------

    def close_intake(self) -> None:
        with self._lock:
            self._closed = True

    @property
    def closed(self) -> bool:
        with self._lock:
            return self._closed

    def counts(self) -> dict:
        with self._lock:
            return {"routed": self.routed,
                    "inflight": len(self._inflight),
                    "results_pending": len(self._results)}

    # -- decision -------------------------------------------------------------

    def _candidates(self, now: float) -> List[Tuple[int, dict]]:
        return [(k, s) for k, s in sorted(self.cache.snapshot().items())
                if s.get("status") == "ok" and not s.get("draining")
                and now - s.get("t", 0.0) <= self.max_age_s]

    def _place(self, rid: int, payload: dict, forced_policy: str = ""
               ) -> Tuple[int, dict]:
        """Decide + forward. Returns the HTTP answer for /submit; on
        reject the rid is settled here (every stamped rid gets exactly
        one result, routable or not)."""
        t_in = time.perf_counter()
        now = time.time()
        cands = self._candidates(now)
        adapter = payload.get("adapter")
        pool, policy = cands, "least_loaded"
        if adapter is not None:
            aff = [(k, s) for k, s in cands
                   if adapter in (s.get("adapters") or ())]
            if aff:
                pool, policy = aff, "affinity"
        if forced_policy:
            policy = forced_policy
        # load = the replica's OWN report (queue + active at scrape
        # time) PLUS the requests this router placed there since — the
        # snapshot is stale by up to scrape_s, and without the inflight
        # term a burst between scrapes would all land on one replica
        with self._lock:
            owned: Dict[int, int] = {}
            for info in self._inflight.values():
                r = info.get("replica")
                owned[r] = owned.get(r, 0) + 1
        order = sorted(pool, key=lambda ks:
                       (ks[1].get("queue_depth") or 0)
                       + (ks[1].get("active") or 0)
                       + owned.get(ks[0], 0))
        t_decide = time.perf_counter()
        chosen, snap = None, None
        for k, s in order:
            try:
                code, obj = _http_json(
                    "POST", f"http://127.0.0.1:{s['port']}/submit",
                    dict(payload, rid=rid), timeout=5.0)
            except OSError:
                code, obj = 0, {}
            if code == 200 and obj.get("accepted"):
                chosen, snap = k, s
                break
            # refused or unreachable: the snapshot lied (death or drain
            # since the last scrape) — walk the rest as failover
            policy = "failover"
        if chosen is None:
            self.tel.emit("route", rid=rid, replica=None,
                          policy="reject", adapter=adapter,
                          queue_depth=None, occupancy=None,
                          scrape_age_ms=None, candidates=len(cands))
            self.deliver(rid, None, {
                "rid": rid, "id": None, "state": "rejected",
                "reason": "no_replica", "adapter": adapter,
                "prompt_tokens": len(payload.get("prompt") or []),
                "new_tokens": 0, "ttft_ms": None, "tpot_ms": None,
                "queue_ms": None})
            return 503, {"accepted": False, "rid": rid,
                         "reason": "no_replica"}
        t_ack = time.perf_counter()
        with self._lock:
            self._inflight[rid] = {"replica": chosen,
                                   "payload": payload, "t": now}
            self.routed += 1
        self.tel.emit("route", rid=rid, replica=chosen, policy=policy,
                      adapter=adapter,
                      queue_depth=snap.get("queue_depth"),
                      occupancy=snap.get("occupancy"),
                      scrape_age_ms=round(
                          (now - snap.get("t", now)) * 1000.0, 3),
                      candidates=len(cands))
        # the router half of the request timeline: ingress->decision
        # ("queue") and decision->forward-ack ("route") on the rid's
        # own track, reconciled against the replica's req:<id> spans
        # by trace_export --router
        self.tracer.emit_span("queue", f"req:{rid}", t_in,
                              (t_decide - t_in) * 1000.0,
                              rid=rid, replica=chosen)
        self.tracer.emit_span("route", f"req:{rid}", t_decide,
                              (t_ack - t_decide) * 1000.0,
                              rid=rid, replica=chosen, policy=policy)
        return 200, {"accepted": True, "rid": rid, "replica": chosen,
                     "policy": policy}

    # -- HTTP routes (handler threads) ---------------------------------------

    def route_submit(self, payload) -> Tuple[int, dict]:
        if not isinstance(payload, dict) or "prompt" not in payload:
            return 400, {"accepted": False, "reason": "bad_request"}
        with self._lock:
            if self._closed:
                return 503, {"accepted": False, "draining": True,
                             "reason": "shutdown"}
            rid = self._next_rid
            self._next_rid += 1
        return self._place(rid, payload)

    def route_collect(self, payload) -> Tuple[int, dict]:
        with self._lock:
            out = [self._results[r] for r in sorted(self._results)]
            self._results.clear()
            pending = len(self._inflight)
        return 200, {"done": out, "inflight": pending}

    # -- settlement -----------------------------------------------------------

    def deliver(self, rid, replica, row: dict) -> bool:
        """Settle one rid (idempotent: the first settle wins). Folds
        the fleet SLO histograms the router's /metrics exposes."""
        if not isinstance(rid, int):
            return False
        with self._lock:
            self._inflight.pop(rid, None)
            if rid in self._results:
                return False
            self._results[rid] = dict(row, rid=rid, replica=replica)
        self.registry.inc("mft_fleet_requests",
                          state=str(row.get("state")))
        if row.get("state") == "finished":
            self.registry.observe_hist("mft_fleet_ttft_ms",
                                       row.get("ttft_ms"))
            self.registry.observe_hist("mft_fleet_tpot_ms",
                                       row.get("tpot_ms"))
            self.registry.observe_hist("mft_fleet_queue_wait_ms",
                                       row.get("queue_ms"))
        return True

    def take_inflight(self, replica: int) -> Dict[int, dict]:
        """Pop every inflight rid owned by `replica` (its death is
        being settled); the caller delivers or re-routes each."""
        with self._lock:
            mine = {rid: info for rid, info in self._inflight.items()
                    if info.get("replica") == replica}
            for rid in mine:
                del self._inflight[rid]
        return mine

    def reroute(self, rid: int, payload: dict) -> None:
        """Re-place an orphaned rid on a survivor (policy=failover,
        SAME rid — the replica-side lifecycle restarts, the fleet-wide
        identity does not)."""
        self._place(rid, payload, forced_policy="failover")


class ServeRouter:
    """The router process: front-door HTTP + scrape/collect threads +
    the supervision loop (a FleetController with serve-aware shard
    tails and replica workers keyed 1..k)."""

    def __init__(self, args):
        self.args = args
        base = args.telemetry
        self.base = base
        # replica launch spec rides a FILE, not the cmd template — the
        # controller formats cmd with str.format, and JSON braces in
        # the template would be parsed as fields
        spec = dict(DEFAULT_ENGINE_SPEC)
        if args.engine_json:
            raw = (args.engine_json if args.engine_json.lstrip()
                   .startswith("{") else open(args.engine_json).read())
            spec.update(json.loads(raw))
        unknown = set(spec) - set(DEFAULT_ENGINE_SPEC)
        if unknown:
            raise SystemExit(
                f"unknown engine spec keys: {sorted(unknown)}")
        self.spec = spec
        self.spec_path = f"{base}.engcfg.json"
        with open(self.spec_path, "w") as f:
            json.dump(spec, f)
        cmd = (f"{shlex.quote(sys.executable)} "
               f"{shlex.quote(os.path.abspath(__file__))} "
               f"--serve_replica --host {{host}} "
               f"--telemetry {shlex.quote(base)} "
               f"--engine_json {shlex.quote(self.spec_path)} "
               f"--port 0 --linger_s {args.linger_s}")
        self.fc = FleetController(argparse.Namespace(
            telemetry=base, cmd=cmd, hosts=args.replicas,
            restart_budget=args.restart_budget,
            backoff_s=args.backoff_s, resume_flags="",
            resume_first=False, allow_shrink=False, min_hosts=1,
            kill_on_hang=0, drain_timeout_s=args.drain_timeout_s,
            poll_s=args.poll_s, max_wall_s=args.max_wall_s))
        # replicas are hosts 1..k (host 0 is the router's own shard);
        # re-key the controller's worker table accordingly, with the
        # serve-aware tail that tracks per-rid terminals
        self.fc.workers = {
            h: _W(h, ServeShardTail(shard_path(base, h)))
            for h in range(1, args.replicas + 1)}
        self.tel = Telemetry(base, host=0)
        self.tracer = Tracer(sink=self.tel.emit)
        self.registry = MetricsRegistry()
        self.tel.add_observer(self.registry.observe)
        self.cache = ScrapeCache()
        self.core = RouterCore(self.tel, self.tracer, self.registry,
                               self.cache, args.scrape_max_age_s)
        self._stop = threading.Event()
        self.server: Optional[MetricsServer] = None

    # -- scrape ---------------------------------------------------------------

    def scrape_once(self) -> None:
        for h, w in self.fc.workers.items():
            pf = read_port_file(self.base, h)
            if pf is None:
                self.cache.drop(h)
                self.registry.set_gauge("mft_fleet_up", 0,
                                        replica=str(h))
                continue
            port = pf["port"]
            try:
                code, hz = _http_json(
                    "GET", f"http://127.0.0.1:{port}/healthz",
                    timeout=self.args.scrape_timeout_s)
                gauges = parse_serve_gauges(_http_text(
                    f"http://127.0.0.1:{port}/metrics",
                    timeout=self.args.scrape_timeout_s))
            except OSError:
                self.cache.drop(h)
                self.registry.set_gauge("mft_fleet_up", 0,
                                        replica=str(h))
                continue
            snap = {
                "t": time.time(), "port": port,
                "status": hz.get("status", "ok" if code == 200
                                 else "unreachable"),
                "draining": bool(hz.get("draining")),
                "adapters": hz.get("adapters") or [],
                "queue_depth": hz.get("queue_depth"),
                "active": hz.get("active"),
                "occupancy": hz.get("occupancy"),
                "free_blocks": hz.get("free_blocks"),
                "p95_step_ms": hz.get("p95_step_ms"),
            }
            self.cache.put(h, snap)
            self.registry.set_gauge("mft_fleet_up",
                                    1 if snap["status"] == "ok" else 0,
                                    replica=str(h))
            for f in ("queue_depth", "active", "occupancy",
                      "free_blocks", "p95_step_ms", "prefix_hit_rate",
                      "cow_copies", "blocks_in_use", "pool_occupancy"):
                v = gauges.get(f)
                if v is None and f in snap:
                    v = snap[f]
                self.registry.set_gauge(f"mft_fleet_{f}", v,
                                        replica=str(h))

    def _scrape_loop(self) -> None:
        while not self._stop.is_set():
            self.scrape_once()
            self._stop.wait(self.args.scrape_s)

    # -- collect --------------------------------------------------------------

    def collect_once(self) -> int:
        settled = 0
        for h, snap in sorted(self.cache.snapshot().items()):
            try:
                _, obj = _http_json(
                    "POST",
                    f"http://127.0.0.1:{snap['port']}/collect", {},
                    timeout=self.args.scrape_timeout_s)
            except OSError:
                continue
            for row in obj.get("done") or []:
                if self.core.deliver(row.get("rid"), h, row):
                    settled += 1
        return settled

    def _collect_loop(self) -> None:
        while not self._stop.is_set():
            self.collect_once()
            self._stop.wait(self.args.collect_s)

    # -- supervision ----------------------------------------------------------

    def settle_replica_down(self, w: _W) -> None:
        """A replica process exited (crash, SIGKILL, drain): the shard
        is ground truth. Deliver every inflight rid the shard already
        terminated; re-route the rest to survivors under the SAME rid.
        Runs BEFORE handle_exit so the restart policy sees a settled
        ledger."""
        self.cache.drop(w.host)
        try:
            os.remove(port_file(self.base, w.host))
        except OSError:
            pass
        orphans = self.core.take_inflight(w.host)
        rerouted = delivered = 0
        for rid, info in sorted(orphans.items()):
            rec = w.tail.terminal.get(rid)
            if rec is not None:
                self.core.deliver(rid, w.host, row_from_shard(rec))
                delivered += 1
            else:
                self.core.reroute(rid, info["payload"])
                rerouted += 1
        if orphans:
            print(f"router: replica {w.host} down with "
                  f"{len(orphans)} inflight — {delivered} settled "
                  f"from shard, {rerouted} rerouted", flush=True)

    def health(self) -> dict:
        snaps = self.cache.snapshot()
        ready = sorted(k for k, s in snaps.items()
                       if s.get("status") == "ok"
                       and not s.get("draining"))
        status = ("draining" if self.core.closed
                  else "ok" if ready else "starting")
        return {"status": status, "replicas": self.args.replicas,
                "ready": ready, **self.core.counts()}

    def fleet_info(self, payload) -> Tuple[int, dict]:
        snaps = self.cache.snapshot()
        reps = {}
        for h, w in sorted(self.fc.workers.items()):
            s = snaps.get(h) or {}
            reps[str(h)] = {
                "pid": (w.proc.pid if w.proc is not None else None),
                "port": s.get("port"),
                "status": s.get("status"),
                "attempts": w.attempts,
                "terminal_seen": len(w.tail.terminal),
            }
        return 200, {"replicas": reps, **self.core.counts()}

    # -- lifecycle ------------------------------------------------------------

    def run(self) -> int:
        args = self.args
        self.tel.emit("run_start", jax_version="n/a", mesh_shape=None,
                      process_count=args.replicas + 1, process_index=0,
                      device_kind="router", device_count=0,
                      config={"replicas": args.replicas,
                              "engine": self.spec,
                              "scrape_s": args.scrape_s,
                              "scrape_max_age_s": args.scrape_max_age_s})
        self.server = MetricsServer(
            self.registry, port=args.port, addr=args.addr,
            health_fn=self.health,
            routes={"/submit": self.core.route_submit,
                    "/collect": self.core.route_collect,
                    "/fleet": self.fleet_info})
        write_port_file(self.base, 0, self.server.port)
        print(f"router: front door http://{self.server.addr}:"
              f"{self.server.port} (replicas {args.replicas})",
              flush=True)
        threads = [threading.Thread(target=self._scrape_loop,
                                    name="router-scrape", daemon=True),
                   threading.Thread(target=self._collect_loop,
                                    name="router-collect", daemon=True)]
        t0 = time.perf_counter()
        try:
            for w in self.fc.workers.values():
                self.fc.spawn(w)
                self.fc.record("launch", worker=w.host)
            for t in threads:
                t.start()
            while not self.fc.guard.triggered:
                if args.max_wall_s and \
                        time.perf_counter() - t0 > args.max_wall_s:
                    break
                for w in self.fc.workers.values():
                    if w.done or w.lost:
                        continue
                    if w.proc is None:
                        if w.relaunch_at is not None:
                            self.fc.maybe_relaunch(w)
                        continue
                    w.tail.poll()
                    rc = w.proc.poll()
                    if rc is not None:
                        w.tail.poll()  # the exit's flushed tail
                        self.settle_replica_down(w)
                        self.fc.handle_exit(w, rc)
                time.sleep(args.poll_s)
            return self.shutdown()
        finally:
            self._stop.set()
            self.tel.close()
            if self.server is not None:
                self.server.close()
            try:
                os.remove(port_file(self.base, 0))
            except OSError:
                pass

    def shutdown(self) -> int:
        """Drain the fleet: intake closed (front door answers 503),
        replicas SIGTERMed (their own drain contract finishes in-flight
        work), then every still-inflight rid settled from the flushed
        shards — exact accounting holds through shutdown."""
        self.core.close_intake()
        self.fc.record("drain",
                       reason=self.fc.guard.signal_name or "SIGTERM")
        self.fc.signal_all(signal.SIGTERM)
        self.fc.wait_all(self.args.drain_timeout_s)
        # one last HTTP sweep happens implicitly via the collector up
        # to _stop; the authoritative sweep is the shard tails
        for w in self.fc.workers.values():
            w.tail.poll()
            for rid, info in sorted(
                    self.core.take_inflight(w.host).items()):
                rec = w.tail.terminal.get(rid)
                self.core.deliver(
                    rid, w.host,
                    row_from_shard(rec) if rec is not None else {
                        "rid": rid, "id": None, "state": "cancelled",
                        "reason": "shutdown", "adapter":
                        info["payload"].get("adapter"),
                        "prompt_tokens": len(
                            info["payload"].get("prompt") or []),
                        "new_tokens": 0, "ttft_ms": None,
                        "tpot_ms": None, "queue_ms": None})
        counts = self.core.counts()
        self.tel.emit("run_end", steps=counts["routed"],
                      wall_s=round(time.time() - self.fc.t0, 3),
                      exit="preempted" if self.fc.guard.triggered
                      else "ok", goodput=None,
                      reason="preempted" if self.fc.guard.triggered
                      else None)
        self.fc.record("stop",
                       reason=f"drained: {counts['routed']} routed")
        self.fc.guard.uninstall()
        self.fc.tel.close()
        return 0


# --------------------------- entry point ------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="serve_router",
        description="metrics-driven router over N serve-engine "
                    "replicas (DESIGN.md §27)")
    ap.add_argument("--telemetry", required=True,
                    help="telemetry base: router stream at <base>, "
                         "replica shards at <base>.host<k>, controller "
                         "events at <base>.controller, port files at "
                         "<base>.port<k>")
    ap.add_argument("--replicas", type=int, default=2)
    ap.add_argument("--engine_json", default="",
                    help="replica engine spec: inline JSON or a path "
                         "(keys = serve_bench.build_engine args; "
                         "defaults are the tiny CPU engine)")
    ap.add_argument("--port", type=int, default=0,
                    help="front-door port (0 = ephemeral; the bound "
                         "port is published at <base>.port0)")
    ap.add_argument("--addr", default="127.0.0.1")
    ap.add_argument("--scrape_s", type=float, default=0.2,
                    help="replica /metrics + /healthz scrape cadence")
    ap.add_argument("--scrape_max_age_s", type=float, default=5.0,
                    help="snapshots older than this are not routable")
    ap.add_argument("--scrape_timeout_s", type=float, default=2.0)
    ap.add_argument("--collect_s", type=float, default=0.05,
                    help="replica /collect poll cadence")
    ap.add_argument("--restart_budget", type=int, default=3)
    ap.add_argument("--backoff_s", type=float, default=0.25)
    ap.add_argument("--drain_timeout_s", type=float, default=20.0)
    ap.add_argument("--poll_s", type=float, default=0.02)
    ap.add_argument("--max_wall_s", type=float, default=0.0,
                    help="safety net: drain past this wall time "
                         "(0 = run until SIGTERM)")
    ap.add_argument("--linger_s", type=float, default=0.5,
                    help="replica drain: wait this long for the final "
                         "outbox to be collected over HTTP before "
                         "exiting (the shard is the fallback)")
    # replica mode (spawned by the router; not for direct use)
    ap.add_argument("--serve_replica", action="store_true",
                    help=argparse.SUPPRESS)
    ap.add_argument("--host", type=int, default=0,
                    help=argparse.SUPPRESS)
    args = ap.parse_args(argv)
    if args.serve_replica:
        if args.host < 1:
            ap.error("--serve_replica needs --host >= 1")
        if not args.engine_json or not os.path.exists(args.engine_json):
            ap.error("--serve_replica needs --engine_json <path>")
        return replica_main(args)
    if args.replicas < 1:
        ap.error("--replicas must be >= 1")
    return ServeRouter(args).run()


if __name__ == "__main__":
    sys.exit(main())
