"""Compiled-artifact contract checker (DESIGN.md §24): lower the
representative train / decode / multitenant programs on CPU and pin
machine-readable contracts about WHAT THE COMPILER PRODUCED —

  - retrace count: N same-shape calls must share ONE executable (the
    zero-retrace-after-warmup invariant, measured the same way the
    serve/multitenant engines' trace_counts observables measure it);
  - collective census: named all-gather/all-reduce/reduce-scatter/
    collective-permute/all-to-all counts per program — a GSPMD
    regression that materializes a V-sharded embed all-gather (the r06
    incident) moves a pinned number here instead of a pod bill;
  - donation: the number of input->output alias entries in the compiled
    module header (a donating step whose aliasing silently vanished
    doubles its peak HBM);
  - named-scope spans: the embed/attention/mlp/loss/optimizer phase
    scopes must survive into compiled HLO metadata (the telemetry
    layer's semantic trace contract).

Contracts live in tools/compiled_contracts.json. `--update` regenerates
the file from the current build (run it when an intentional change
moves a number, and review the diff like any other pin).

Usage:
  python tools/check_compiled_contracts.py                 # check all
  python tools/check_compiled_contracts.py --programs train_gpt2_lora
  python tools/check_compiled_contracts.py --update        # re-pin
  python tools/check_compiled_contracts.py --format json

Exit codes (bench_compare convention): 0 = contracts hold, 2 = contract
violated, 1 = usage/build error.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

DEFAULT_CONTRACTS = os.path.join(REPO, "tools", "compiled_contracts.json")

# the phase scopes the telemetry layer pins (DESIGN.md §13)
TRAIN_SCOPES = ("embed", "attention", "mlp", "loss", "optimizer")


def _ensure_cpu_devices() -> None:
    """Force the 8-virtual-device CPU platform BEFORE jax initializes
    (same recipe as tests/conftest.py) so the fsdp program lowers at a
    real (2, 4) mesh and its collective census is nonzero."""
    from mobilefinetuner_tpu.parallel.host_devices import force_host_devices
    force_host_devices(8)


# ---------------------------------------------------------------------------
# program builders: each returns (hlo_text, retraces, required_scopes)
# retraces = executables traced across 3 same-shape calls (None when the
# program pins lowering-only contracts)
# ---------------------------------------------------------------------------

def _tiny_batch(cfg, rows, S, seed=0):
    import jax.numpy as jnp
    import numpy as np
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (rows, S)), jnp.int32)
    return {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
            "labels": ids}


def prog_train_gpt2_lora():
    """Single-device GPT-2 LoRA optimizer step, donate=True — the solo
    train path's executable."""
    import jax
    import jax.numpy as jnp
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                               trainable_mask)
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   init_optimizer,
                                                   make_train_step)
    cfg = GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    lora = init_lora_gpt2(cfg, LoRASpec(rank=2, alpha=4.0),
                          jax.random.PRNGKey(1))
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=8, lr=1e-3, warmup_ratio=0.0,
                     schedule="constant")
    traces = {"n": 0}

    def loss_fn(lo, p, mb):
        traces["n"] += 1  # runs exactly when jax (re)traces
        logits = gpt2.forward(cfg, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"], lora=lo)
        return lm_cross_entropy_sum(logits, mb["labels"])

    step = make_train_step(loss_fn, tc, mask=mask, donate=True)
    opt = init_optimizer(lora, tc, mask)
    batch = _tiny_batch(cfg, 2, 16)
    tr = lora
    for i in range(3):
        tr, opt, _ = step(tr, params, opt, batch, jnp.int32(i))
    retraces = traces["n"]
    text = step.lower(tr, params, opt, batch,
                      jnp.int32(3)).compile().as_text()
    return text, retraces, TRAIN_SCOPES


def prog_train_gpt2_fsdp():
    """GPT-2 full-FT step lowered at a (data=2, fsdp=4) mesh: the
    collective-census program (the r06 V-sharded-embed regression class
    fails HERE instead of on a pod)."""
    import jax
    import jax.numpy as jnp
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
    from mobilefinetuner_tpu.parallel.mesh import (make_mesh,
                                                   params_shardings,
                                                   replicated_sharding,
                                                   shard_batch)
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   init_optimizer,
                                                   make_train_step)
    cfg = GPT2Config.tiny()
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    fsdp_sh = params_shardings(params, mesh, min_size=2 ** 12)
    params = jax.device_put(params, fsdp_sh)

    def loss_fn(p, _unused, mb):
        logits = gpt2.forward(cfg, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"])
        return lm_cross_entropy_sum(logits, mb["labels"])

    tc = TrainConfig(total_steps=8, lr=1e-3, warmup_ratio=0.0,
                     schedule="constant")
    step = make_train_step(loss_fn, tc, donate=False)
    opt = init_optimizer(params, tc)
    repl = replicated_sharding(mesh)
    opt = jax.device_put(opt, jax.tree.map(lambda _: repl, opt))
    batch = _tiny_batch(cfg, 8, 32)
    with mesh:
        text = step.lower(params, None, opt, shard_batch(batch, mesh),
                          jnp.int32(0)).compile().as_text()
    return text, None, TRAIN_SCOPES


def prog_decode_gpt2_paged():
    """The serve loop's paged decode-step executable (block-table KV
    reads, pools donated) — zero collectives, one executable across
    steps with moving pos/tok/tbl DATA."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.models.generate import gpt2_decode_step_paged
    from mobilefinetuner_tpu.serve.paged_kv import init_pools
    cfg = GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    L, H = cfg.n_layer, cfg.n_head
    D = cfg.n_embd // cfg.n_head
    bT, NB = 8, 8
    # serve/engine.py KV pool layout: [NB, L, H, bT, D] per head-pool
    pool_k, pool_v = init_pools(NB, L, H, bT, D)
    traces = {"n": 0}

    def step_py(p, pk, pv, tok, pos, tbl):
        traces["n"] += 1
        logits, pk2, pv2 = gpt2_decode_step_paged(
            cfg, p, pk, pv, tok, pos, tbl, compute_dtype=jnp.float32,
            attn_impl="xla")
        return jnp.argmax(logits, -1).astype(jnp.int32), pk2, pv2

    step = jax.jit(step_py, donate_argnums=(1, 2))
    tbl = jnp.asarray(np.array([[1, 2], [3, 4]], np.int32))
    for i in range(3):
        tok = jnp.asarray([11 + i, 23 + i], jnp.int32)
        pos = jnp.asarray([i + 1, i + 2], jnp.int32)
        _, pool_k, pool_v = step(params, pool_k, pool_v, tok, pos, tbl)
    retraces = traces["n"]
    tok = jnp.asarray([1, 2], jnp.int32)
    pos = jnp.asarray([4, 5], jnp.int32)
    text = step.lower(params, pool_k, pool_v, tok, pos,
                      tbl).compile().as_text()
    return text, retraces, ()


def prog_decode_gpt2_paged_tp():
    """The SHARDED serve step (r20, serve/sharding.py): GPT-2 paged
    decode with a 2-tenant block-diagonal bank lowered at a (1, 4)
    ("dp", "tp") mesh — the GSPMD safety net the tensor-parallel serve
    plane stands on. The pinned census IS the perf contract: the
    partitioner may only pay activation-sized all-reduces (row-parallel
    matmul sums + head re-gathers); a regression that starts moving
    weight- or pool-sized tensors shows up as new collective entries
    here, not as a pod bill. Donation and zero-retrace are pinned
    exactly like the single-chip program's."""
    import dataclasses
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, assign_adapters,
                                               init_lora_gpt2,
                                               stack_adapters)
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.models.generate import gpt2_decode_step_paged
    from mobilefinetuner_tpu.serve.paged_kv import init_pools
    from mobilefinetuner_tpu.serve.sharding import ServeSharding
    # tiny() has 2 heads; tp=4 needs a head-aligned split
    cfg = dataclasses.replace(GPT2Config.tiny(), n_head=4)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    L, H = cfg.n_layer, cfg.n_head
    D = cfg.n_embd // cfg.n_head
    bT, NB = 8, 8
    sh = ServeSharding.build("gpt2", cfg, 1, 4)
    params = jax.device_put(params, sh.param_shardings(params))
    bank = stack_adapters([
        init_lora_gpt2(cfg, LoRASpec(rank=2, alpha=4.0),
                       jax.random.PRNGKey(i + 1)) for i in range(2)])
    bank = jax.device_put(bank, sh.bank_shardings(bank))
    pool_k, pool_v = init_pools(NB, L, H, bT, D)
    psh = sh.pool_sharding()
    pool_k = jax.device_put(pool_k, psh)
    pool_v = jax.device_put(pool_v, psh)
    traces = {"n": 0}

    def step_py(p, bk, pk, pv, tok, pos, tbl, aid):
        traces["n"] += 1
        lora = assign_adapters(bk, aid)
        logits, pk2, pv2 = gpt2_decode_step_paged(
            cfg, p, pk, pv, tok, pos, tbl, lora=lora,
            compute_dtype=jnp.float32, attn_impl="xla", shardings=sh)
        return jnp.argmax(logits, -1).astype(jnp.int32), pk2, pv2

    step = jax.jit(step_py, donate_argnums=(2, 3),
                   out_shardings=(sh.repl, psh, psh))
    dev = lambda a: jax.device_put(np.asarray(a), sh.repl)
    tbl = dev(np.array([[1, 2], [3, 4]], np.int32))
    aid = dev(np.array([0, 1], np.int32))
    for i in range(3):
        tok = dev(np.array([11 + i, 23 + i], np.int32))
        pos = dev(np.array([i + 1, i + 2], np.int32))
        _, pool_k, pool_v = step(params, bank, pool_k, pool_v, tok, pos,
                                 tbl, aid)
    retraces = traces["n"]
    tok = dev(np.array([1, 2], np.int32))
    pos = dev(np.array([4, 5], np.int32))
    text = step.lower(params, bank, pool_k, pool_v, tok, pos, tbl,
                      aid).compile().as_text()
    return text, retraces, ()


def prog_prefill_chunk_gpt2_tp():
    """The round-21 chunked-prefill executable (models/generate.py
    gpt2_prefill_chunk) at ONE static bucket width, lowered at a
    (1, 2) ("dp", "tp") mesh: W prompt rows scatter into the paged
    pools at data-driven start/n_tok offsets, so the whole bucket set
    costs one trace per width — never one per prompt length or chunk
    offset. Donation (pools aliased through) and the collective census
    are pinned exactly like the decode step's: chunk admission must
    pay only activation-sized all-reduces under tp, and a regression
    that re-traces per offset or drops the pool alias shows up here,
    not as a serving stall."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.models.generate import gpt2_prefill_chunk
    from mobilefinetuner_tpu.serve.paged_kv import init_pools
    from mobilefinetuner_tpu.serve.sharding import ServeSharding
    cfg = GPT2Config.tiny()            # 2 heads: tp=2 is head-aligned
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    L, H = cfg.n_layer, cfg.n_head
    D = cfg.n_embd // cfg.n_head
    bT, NB, W, M = 8, 8, 8, 4          # one bucket width W = block_T
    sh = ServeSharding.build("gpt2", cfg, 1, 2)
    params = jax.device_put(params, sh.param_shardings(params))
    pool_k, pool_v = init_pools(NB, L, H, bT, D)
    psh = sh.pool_sharding()
    pool_k = jax.device_put(pool_k, psh)
    pool_v = jax.device_put(pool_v, psh)
    traces = {"n": 0}

    def chunk_py(p, pk, pv, ids, start, n_tok, tbl):
        traces["n"] += 1
        logits, pk2, pv2 = gpt2_prefill_chunk(
            cfg, p, pk, pv, ids, start, n_tok, tbl,
            compute_dtype=jnp.float32, shardings=sh)
        return jnp.argmax(logits, -1).astype(jnp.int32), pk2, pv2

    chunk = jax.jit(chunk_py, donate_argnums=(1, 2),
                    out_shardings=(sh.repl, psh, psh))
    dev = lambda a: jax.device_put(np.asarray(a), sh.repl)
    tbl = dev(np.array([[1, 2, 3, 0]], np.int32))
    # three chunks of one walking admission: moving start, a full
    # chunk, then a partial tail — all DATA, one executable
    for i, (st, nt) in enumerate(((0, 8), (8, 8), (16, 3))):
        ids = dev((np.arange(W, dtype=np.int32) + 7 * i + 1)[None])
        _, pool_k, pool_v = chunk(params, pool_k, pool_v, ids,
                                  dev(np.int32(st)), dev(np.int32(nt)),
                                  tbl)
    retraces = traces["n"]
    ids = dev(np.full((1, W), 5, np.int32))
    text = chunk.lower(params, pool_k, pool_v, ids, dev(np.int32(0)),
                       dev(np.int32(W)), tbl).compile().as_text()
    return text, retraces, ()


def prog_multitenant_gpt2():
    """The k-tenant fused optimizer step (ids-routed bank, per-slot
    Adam) — the r18 engine's executable, donated, zero retraces across
    sched-data changes."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, assign_adapters,
                                               init_lora_gpt2,
                                               stack_adapters,
                                               trainable_mask)
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_rows
    from mobilefinetuner_tpu.optim.adam import init_multi_state
    from mobilefinetuner_tpu.train.trainer import (TrainConfig,
                                                   make_multi_train_step)
    cfg = GPT2Config.tiny()
    k = 2
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    adapters = [init_lora_gpt2(cfg, LoRASpec(rank=2, alpha=4.0),
                               jax.random.PRNGKey(i + 1))
                for i in range(k)]
    bank = stack_adapters(adapters)
    mask = trainable_mask(bank)
    tc = TrainConfig(total_steps=1, lr=0.0, warmup_ratio=0.0,
                     schedule="constant")
    traces = {"n": 0}

    def loss_rows(tr, frozen, mb):
        traces["n"] += 1
        routed = assign_adapters(tr, mb["adapter_ids"])
        logits = gpt2.forward(cfg, frozen, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              lora=routed)
        return lm_cross_entropy_rows(logits, mb["labels"])

    step = make_multi_train_step(loss_rows, tc, k, mask=mask)
    opt = init_multi_state(bank, tc.adam(), k, mask)
    batch = _tiny_batch(cfg, 4, 16)
    batch["adapter_ids"] = jnp.asarray([0, 1, 0, 1], jnp.int32)

    def sched(i):
        return {"step": jnp.asarray(np.full(k, i, np.int32)),
                "total": jnp.asarray(np.full(k, 8.0, np.float32)),
                "lr": jnp.asarray(np.full(k, 1e-3, np.float32)),
                "warmup_ratio": jnp.asarray(np.zeros(k, np.float32)),
                "active": jnp.asarray(np.ones(k, bool))}

    tr = bank
    for i in range(3):
        tr, opt, _ = step(tr, params, opt, batch, sched(i))
    retraces = traces["n"]
    text = step.lower(tr, params, opt, batch,
                      sched(3)).compile().as_text()
    return text, retraces, TRAIN_SCOPES


PROGRAMS = {
    "train_gpt2_lora": prog_train_gpt2_lora,
    "train_gpt2_fsdp": prog_train_gpt2_fsdp,
    "decode_gpt2_paged": prog_decode_gpt2_paged,
    "decode_gpt2_paged_tp": prog_decode_gpt2_paged_tp,
    "prefill_chunk_gpt2_tp": prog_prefill_chunk_gpt2_tp,
    "multitenant_gpt2": prog_multitenant_gpt2,
}


def build_contract(name: str) -> dict:
    from mobilefinetuner_tpu.core.static_checks import (
        hlo_collective_census, hlo_donated_inputs, missing_hlo_scopes)
    text, retraces, scopes = PROGRAMS[name]()
    missing = set(missing_hlo_scopes(text, scopes))
    present = [s for s in scopes if s not in missing]
    return {
        "retraces": retraces,
        "donated": hlo_donated_inputs(text),
        "collectives": hlo_collective_census(text),
        "scopes": present,
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="compiled-artifact contract checker (graftlint's "
                    "runtime half)")
    ap.add_argument("--contracts", default=DEFAULT_CONTRACTS,
                    help="pinned contract JSON (default: "
                         "tools/compiled_contracts.json)")
    ap.add_argument("--programs", default=None,
                    help="comma-separated subset (default: all)")
    ap.add_argument("--update", action="store_true",
                    help="regenerate the pinned contracts from the "
                         "current build")
    ap.add_argument("--format", choices=("text", "json"), default="text")
    args = ap.parse_args(argv)

    names = list(PROGRAMS)
    if args.programs:
        names = [n.strip() for n in args.programs.split(",") if n.strip()]
        unknown = [n for n in names if n not in PROGRAMS]
        if unknown:
            print(f"error: unknown program(s): {', '.join(unknown)} "
                  f"(have: {', '.join(PROGRAMS)})", file=sys.stderr)
            return 1

    _ensure_cpu_devices()
    try:
        built = {n: build_contract(n) for n in names}
    except Exception as e:  # noqa: BLE001 — build errors are exit 1
        print(f"error: building contracts failed: "
              f"{type(e).__name__}: {e}", file=sys.stderr)
        return 1

    if args.update:
        pinned = {}
        if os.path.exists(args.contracts):
            with open(args.contracts) as f:
                pinned = json.load(f).get("programs", {})
        pinned.update(built)
        doc = {"_comment": "pinned by tools/check_compiled_contracts.py "
                           "--update; review diffs like any other pin",
               "programs": {n: pinned[n] for n in sorted(pinned)}}
        with open(args.contracts, "w") as f:
            json.dump(doc, f, indent=1, sort_keys=True)
            f.write("\n")
        print(f"pinned {len(built)} program contract(s) -> "
              f"{args.contracts}")
        return 0

    if not os.path.exists(args.contracts):
        print(f"error: no pinned contracts at {args.contracts} "
              f"(run --update once)", file=sys.stderr)
        return 1
    with open(args.contracts) as f:
        pinned = json.load(f).get("programs", {})

    violations = []
    for n in names:
        want = pinned.get(n)
        if want is None:
            violations.append((n, "no pinned contract (run --update)"))
            continue
        got = built[n]
        for key in ("retraces", "donated", "collectives", "scopes"):
            if got[key] != want.get(key):
                violations.append(
                    (n, f"{key}: pinned {want.get(key)!r} != built "
                        f"{got[key]!r}"))

    if args.format == "json":
        print(json.dumps({
            "programs": built,
            "violations": [{"program": n, "detail": d}
                           for n, d in violations],
        }, indent=1, sort_keys=True))
    else:
        for n in names:
            c = built[n]
            col = ", ".join(f"{k}={v}" for k, v in
                            sorted(c["collectives"].items()) if v)
            print(f"{n}: retraces={c['retraces']} "
                  f"donated={c['donated']} "
                  f"collectives=[{col or 'none'}] "
                  f"scopes={','.join(c['scopes']) or '-'}")
        for n, d in violations:
            print(f"VIOLATION {n}: {d}")
        print(f"check_compiled_contracts: {len(names)} program(s), "
              f"{len(violations)} violation(s)")
    return 2 if violations else 0


if __name__ == "__main__":
    sys.exit(main())
