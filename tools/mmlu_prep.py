"""MMLU dataset prep: normalize any MMLU-shaped source into the Hendrycks
directory layout eval_mmlu consumes, or synthesize a full-taxonomy set.

The reference vendors the Hendrycks dataset + its evaluation scripts
(reference: data/mmlu/hendrycks_test/ — data.zip with data/{dev,val,test}/
<subject>_<split>.csv, categories.py taxonomy); this tool is the rebuild's
dataset-side counterpart: it produces <out>/{dev,val,test}/
<subject>_<split>.csv (headerless question,A,B,C,D,answer rows), validates
every row, and reports per-split/per-subject counts plus taxonomy coverage
against the official 57 subjects (eval/mmlu_categories.py).

Sources:
  --source PATH   a directory or .zip containing *_dev/_val/_test.csv
                  files anywhere in its tree (the Hendrycks archive's
                  data/ nesting is handled) — rows are parsed with the
                  same RFC-4180 subset the runner uses and re-emitted
                  normalized (answer upper-cased, exactly 6 columns);
  --synthetic N   no source needed (this environment has zero egress):
                  emit N items/subject for all 57 official subjects,
                  deterministic, answerable from the question text (the
                  correct choice repeats the question's key token), so a
                  capable model scores >chance and reports exercise every
                  category.

Usage:
  python tools/mmlu_prep.py --synthetic 8 --out /tmp/mmlu
  python tools/mmlu_prep.py --source ~/Downloads/data.zip --out ./mmlu
  python -m mobilefinetuner_tpu.cli.eval_mmlu --mmlu_root ./mmlu ...
"""

import argparse
import io
import json
import os
import re
import sys
import zipfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from mobilefinetuner_tpu.eval.mmlu import (MCQItem, parse_mmlu_text,
                                           read_mmlu_csv)
from mobilefinetuner_tpu.eval.mmlu_categories import SUBJECT_TOPICS

SPLITS = ("dev", "val", "test")

# subjects are written as "<subject>_<split>.csv" under --out: must be a
# single safe filename component (no separators, no leading dot)
_SAFE_SUBJECT = re.compile(r"[A-Za-z0-9][A-Za-z0-9 _\-]*$")


def csv_field(s: str) -> str:
    """RFC-4180 emit: quote when the field contains , " or newline."""
    if any(c in s for c in ',"\n'):
        return '"' + s.replace('"', '""') + '"'
    return s


def write_subject_csv(path: str, items):
    with open(path, "w", encoding="utf-8") as f:
        for it in items:
            f.write(",".join(csv_field(x) for x in
                             (it.question, it.A, it.B, it.C, it.D,
                              it.answer)) + "\n")


def split_of_filename(name: str):
    base = os.path.splitext(os.path.basename(name))[0]
    for sp in SPLITS:
        if base.endswith("_" + sp):
            return base[: -len(sp) - 1], sp
    return None, None


def collect_source(source: str):
    """{(subject, split): [MCQItem]} from a dir or zip of Hendrycks CSVs.
    Both branches go through the runner's own parser (parse_mmlu_text /
    read_mmlu_csv), so headered and headerless layouts are detected
    identically regardless of packaging."""
    out = {}

    def add(default_subject, split, items):
        # The parser fills per-row subjects for headered files that carry a
        # subject column; group by THAT instead of refiling everything under
        # the filename — a headered CSV's own subject labels must survive
        # normalization. The subject becomes an output filename component,
        # so cell content that could escape --out (separators, '..',
        # leading dots) is refiled under the filename-derived subject.
        for it in items:
            if not _SAFE_SUBJECT.match(it.subject or ""):
                it.subject = default_subject
            out.setdefault((it.subject, split), []).append(it)

    if zipfile.is_zipfile(source):
        with zipfile.ZipFile(source) as z:
            for name in z.namelist():
                subject, split = split_of_filename(name)
                if split and name.endswith(".csv"):
                    text = z.read(name).decode("utf-8", errors="replace")
                    add(subject, split,
                        parse_mmlu_text(text, subject, origin=name))
    else:
        for root, _, files in os.walk(source):
            for name in sorted(files):
                subject, split = split_of_filename(name)
                if split and name.endswith(".csv"):
                    add(subject, split,
                        read_mmlu_csv(os.path.join(root, name)))
    return out


def synthesize(n_per_subject: int, n_dev: int = 5):
    """Deterministic full-taxonomy synthetic set: the correct choice echoes
    a key token from the question, wrong choices echo other tokens."""
    out = {}
    subjects = sorted(SUBJECT_TOPICS)
    for si, subject in enumerate(subjects):
        for split, n in (("dev", n_dev), ("val", max(n_per_subject // 2, 1)),
                         ("test", n_per_subject)):
            items = []
            for i in range(n):
                key = f"{subject}_token_{i:03d}"
                wrong = [f"{subject}_alt_{(i + k) % (n + 7):03d}"
                         for k in (1, 2, 3)]
                gold = (si + i) % 4
                choices = wrong[:gold] + [key] + wrong[gold:]
                items.append(MCQItem(
                    subject=subject,
                    question=(f"In the study of {subject.replace('_', ' ')},"
                              f" which term matches the key \"{key}\"?"),
                    A=choices[0], B=choices[1], C=choices[2], D=choices[3],
                    answer="ABCD"[gold]))
            out[(subject, split)] = items
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--source", default="",
                    help="dir or .zip of Hendrycks-layout CSVs")
    ap.add_argument("--synthetic", type=int, default=0,
                    help="items/subject for a synthetic full-taxonomy set")
    ap.add_argument("--out", required=True, help="output mmlu_root")
    args = ap.parse_args(argv)
    if bool(args.source) == bool(args.synthetic):
        ap.error("exactly one of --source / --synthetic required")

    data = (synthesize(args.synthetic) if args.synthetic
            else collect_source(args.source))
    if not data:
        print(json.dumps({"error": "no MMLU CSVs found"}))
        return 1

    counts = {sp: {} for sp in SPLITS}
    bad = 0
    for (subject, split), items in sorted(data.items()):
        ok = [it for it in items if it.answer in "ABCD" and it.question]
        bad += len(items) - len(ok)
        if not ok:
            continue
        d = os.path.join(args.out, split)
        os.makedirs(d, exist_ok=True)
        write_subject_csv(os.path.join(d, f"{subject}_{split}.csv"), ok)
        counts[split][subject] = len(ok)

    official = set(SUBJECT_TOPICS)
    seen = {s for sp in counts.values() for s in sp}
    report = {
        "out": args.out,
        "splits": {sp: {"subjects": len(c), "items": sum(c.values())}
                   for sp, c in counts.items()},
        "dropped_rows": bad,
        "official_subjects_missing": sorted(official - seen),
        "unofficial_subjects": sorted(seen - official),
    }
    print(json.dumps(report))
    return 0


if __name__ == "__main__":
    sys.exit(main())
