"""Shared report-section builders (round 23, DESIGN.md §28).

One home for every section builder BOTH report tools render —
telemetry_report.py (single stream) and fleet_report.py (merged
multi-host shards) import from here, so a percentile convention or a
section's line format can never drift between them. Round 23 adds the
longitudinal trend section (sparkline + regression table) that
tools/observatory.py renders over the run registry's metric history.

Nothing here imports jax: these are pure JSONL-in, lines-out
formatters, safe for CI boxes with no accelerator runtime.
"""

from __future__ import annotations

import argparse
import json
import sys

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from mobilefinetuner_tpu.core.telemetry import validate_event  # noqa: E402


def percentile(sorted_vals, q):
    """Nearest-rank percentile over an already-sorted list."""
    if not sorted_vals:
        return None
    i = min(int(q / 100.0 * len(sorted_vals)), len(sorted_vals) - 1)
    return sorted_vals[i]


def load_events(path):
    """(events, n_invalid): valid events in file order."""
    events, bad = [], 0
    with open(path, "rb") as f:
        for raw in f:
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw)
            except (json.JSONDecodeError, UnicodeDecodeError):
                bad += 1
                continue
            if validate_event(rec) is None:
                events.append(rec)
            else:
                bad += 1
    return events, bad


def split_latest_run(events):
    """(truncated, latest_run_events): a resumed stream appends runs, so
    'is there any run_end' is the wrong truncation test — run 1 may have
    ended cleanly while the appended run 2 was SIGKILLed. The post-mortem
    subject is the LATEST run: truncated iff its run_start has no
    following run_end; the returned slice is that run's events (the whole
    stream when nothing is truncated)."""
    idx_start = max((i for i, e in enumerate(events)
                     if e.get("event") == "run_start"), default=-1)
    idx_end = max((i for i, e in enumerate(events)
                   if e.get("event") == "run_end"), default=-1)
    truncated = idx_start > idx_end
    return truncated, (events[idx_start:]
                       if truncated and idx_start >= 0 else events)


def _fmt(v, nd=2):
    return "-" if v is None else f"{v:.{nd}f}"


def checkpoint_summary(events) -> dict:
    """Roll up `checkpoint`/`ckpt_dropped` events with the round-10
    snapshot/write split (io/async_ckpt.py): blocking_s is what the step
    loop actually stalled (wall_s — snapshot only under --async_save),
    write_s/bytes/mb_s the background write cost that overlapped compute,
    dropped the snapshots coalesced away by the depth-1 writer queue.
    ONE builder shared with tools/fleet_report.py. Pre-async streams
    (step/final/wall_s only) still summarize: the split fields are
    optional on read."""
    cks = [e for e in events if e.get("event") == "checkpoint"]
    mbs = [c["mb_s"] for c in cks if c.get("mb_s")]
    return {
        "count": len(cks),
        "async": sum(1 for c in cks if c.get("async")),
        "blocking_s": round(sum(c["wall_s"] for c in cks), 4),
        "write_s": round(sum(c.get("write_ms") or 0.0
                             for c in cks) / 1000.0, 4),
        "bytes": sum(c.get("bytes") or 0 for c in cks),
        "mb_s_mean": (round(sum(mbs) / len(mbs), 2) if mbs else None),
        "dropped": sum(1 for e in events
                       if e.get("event") == "ckpt_dropped"),
    }


def checkpoint_lines(ck) -> list:
    """Render a checkpoint_summary dict (shared with fleet_report)."""
    if not ck or not (ck["count"] or ck["dropped"]):
        return []
    line = (f"  checkpoints: {ck['count']} ({ck['async']} async), "
            f"blocking {ck['blocking_s']:.2f}s")
    if ck["write_s"]:
        line += (f", background write {ck['write_s']:.2f}s"
                 + (f" ({ck['bytes'] / 2**20:.1f} MB"
                    + (f" @ {ck['mb_s_mean']:.1f} MB/s" if ck["mb_s_mean"]
                       else "") + ")" if ck["bytes"] else ""))
    if ck["dropped"]:
        line += f", {ck['dropped']} snapshot(s) coalesced away"
    return [line]


def recovery_summary(events) -> dict:
    """Roll up the round-15 numerical-fault recovery events (DESIGN.md
    §20): skipped-update count (sum of step_stats.skipped — the
    in-jit guard's identity steps), every `rollback` decision with its
    steps-lost recovery cost, and the `ckpt_verify` verdicts (failures
    listed with the mismatch reason). None when the stream carries
    none of the three — ONE builder shared with tools/fleet_report.py
    like the checkpoint/straggler/hang entries."""
    stats = [e for e in events if e.get("event") == "step_stats"]
    skipped = sum(e.get("skipped") or 0 for e in stats)
    rollbacks = [{"step": e["step"], "reason": e["reason"],
                  "ok": e["ok"], "to_step": e.get("to_step"),
                  "steps_lost": e.get("steps_lost"),
                  "ckpt": e.get("ckpt"),
                  "budget_left": e.get("budget_left")}
                 for e in events if e.get("event") == "rollback"]
    verifies = [e for e in events if e.get("event") == "ckpt_verify"]
    failures = [{"path": e["path"], "reason": e.get("reason"),
                 "step": e.get("step")}
                for e in verifies if not e.get("ok")]
    if not (skipped or rollbacks or verifies):
        return None
    return {
        "skipped_steps": skipped,
        "rollbacks": rollbacks,
        "steps_lost": sum(r["steps_lost"] or 0 for r in rollbacks
                          if r["ok"]),
        "ckpt_verified": sum(1 for e in verifies if e.get("ok")),
        "ckpt_verify_failures": failures,
    }


def recovery_lines(r) -> list:
    """Render a recovery_summary (shared with fleet_report)."""
    if not r:
        return []
    lines = [f"  recovery: {r['skipped_steps']} skipped update(s), "
             f"{sum(1 for x in r['rollbacks'] if x['ok'])} rollback(s) "
             f"({r['steps_lost']} step(s) lost), "
             f"{r['ckpt_verified']} ckpt verification(s), "
             f"{len(r['ckpt_verify_failures'])} failure(s)"]
    for x in r["rollbacks"]:
        if x["ok"]:
            lines.append(
                f"    ROLLBACK ({x['reason']}) @ step {x['step']} -> "
                f"{x['to_step']} ({x['steps_lost']} lost, budget left "
                f"{x['budget_left']})")
        else:
            lines.append(
                f"    ROLLBACK WANTED ({x['reason']}) @ step "
                f"{x['step']} but not possible (no verified "
                f"checkpoint / budget exhausted)")
    for f in r["ckpt_verify_failures"]:
        lines.append(f"    CKPT REJECTED: {f['path']} ({f['reason']})")
    return lines


def memory_summary(events) -> dict:
    """Roll up the round-16 memory-admission events (DESIGN.md §21):
    every `mem_check` verdict (est vs cap, the cap_frac headroom
    number) and every `degrade` ladder decision. None when the stream
    carries neither — ONE builder shared with tools/fleet_report.py
    like the checkpoint/recovery sections."""
    checks = [e for e in events if e.get("event") == "mem_check"]
    degrades = [e for e in events if e.get("event") == "degrade"]
    if not (checks or degrades):
        return None
    last = checks[-1] if checks else None
    row = lambda c: {"phase": c.get("phase"), "est_mb": c.get("est_mb"),
                     "cap_mb": c.get("cap_mb"), "verdict": c["verdict"],
                     "cap_frac": c.get("cap_frac")}
    return {
        "checks": [row(c) for c in checks],
        "final": row(last) if last else None,
        "over": sum(1 for c in checks if c["verdict"] == "over"),
        "degrades": [{"step": d.get("step"), "rung": d["rung"],
                      "from": d.get("from"), "to": d.get("to"),
                      "est_mb": d.get("est_mb")} for d in degrades],
    }


def memory_lines(m) -> list:
    """Render a memory_summary (shared with fleet_report)."""
    if not m:
        return []
    bits = []
    f = m["final"]
    if f:
        bits.append(f"est {_fmt(f['est_mb'], 0)} MB vs cap "
                    f"{_fmt(f['cap_mb'], 0)} MB"
                    + (f" ({100 * f['cap_frac']:.0f}% of cap)"
                       if f.get("cap_frac") else "")
                    + f", verdict {f['verdict']}")
    if m["over"]:
        bits.append(f"{m['over']} over-capacity check(s)")
    if m["degrades"]:
        bits.append(f"{len(m['degrades'])} ladder rung(s)")
    lines = ["  memory: " + "; ".join(bits)]
    for d in m["degrades"]:
        lines.append(
            f"    DEGRADE {d['rung']}: {d['from']} -> {d['to']}"
            + (f" (est {d['est_mb']:.0f} MB over)"
               if d.get("est_mb") else "")
            + (f" @ step {d['step']}" if d.get("step") is not None
               else " @ preflight"))
    return lines


def observability_summary(events) -> dict:
    """Roll up the round-17 live-observability events (DESIGN.md §22):
    span count by track (the timeline's shape at a glance — the spans
    themselves belong in tools/trace_export.py, not a text report) and
    every anomaly-triggered `profile_capture` with its trigger and
    on-disk path. None when the stream carries neither — ONE builder
    shared with tools/fleet_report.py like the other sections."""
    spans = [e for e in events if e.get("event") == "span"]
    caps = [e for e in events if e.get("event") == "profile_capture"]
    if not (spans or caps):
        return None
    by_track = {}
    for s in spans:
        by_track[s["track"]] = by_track.get(s["track"], 0) + 1
    return {
        "spans": len(spans),
        "span_tracks": by_track,
        "profile_captures": [{"step": c["step"],
                              "trigger": c["trigger"],
                              "path": c["path"],
                              "budget_left": c.get("budget_left")}
                             for c in caps],
    }


def observability_lines(o) -> list:
    """Render an observability_summary (shared with fleet_report)."""
    if not o:
        return []
    lines = []
    if o["spans"]:
        tracks = ", ".join(f"{k} {v}" for k, v in
                           sorted(o["span_tracks"].items())[:6])
        more = len(o["span_tracks"]) - 6
        lines.append(f"  spans: {o['spans']} across "
                     f"{len(o['span_tracks'])} track(s) ({tracks}"
                     + (f", +{more} more" if more > 0 else "") + ")"
                     + " — export with tools/trace_export.py")
    for c in o["profile_captures"]:
        lines.append(f"  PROFILE CAPTURED @ step {c['step']} "
                     f"({c['trigger']}): {c['path']} "
                     f"(budget left {c['budget_left']})")
    return lines


def tenant_summary(events) -> dict:
    """Per-tenant roll-up for the multi-tenant training engine
    (multitenant/engine.py, DESIGN.md §23): one row per adapter job from
    its `tenant` lifecycle events plus the LAST step_stats `tenants`
    section — steps reached vs budget, final loss, cumulative tokens,
    host-wait attribution, lifecycle outcome, and the saved artifact.
    None when the stream carries no multi-tenant traffic."""
    tev = [e for e in events if e.get("event") == "tenant"]
    stats = [e for e in events if e.get("event") == "step_stats"
             and e.get("tenants")]
    if not tev and not stats:
        return None
    rows: dict = {}
    for e in tev:
        r = rows.setdefault(e["name"], {"name": e["name"]})
        r["status"] = e["phase"]
        r["slot"] = e["slot"]
        r["step"] = e["step"]
        r["job_steps"] = e.get("job_steps")
        if e.get("loss") is not None:
            r["loss"] = e["loss"]
        if e.get("tokens") is not None:
            r["tokens"] = e["tokens"]
        if e.get("phase") in ("save", "finish") and e.get("path"):
            r["path"] = e["path"]
    if stats:
        for name, t in stats[-1]["tenants"].items():
            r = rows.setdefault(name, {"name": name})
            r.setdefault("status", "active")
            for k in ("slot", "step", "loss", "tokens", "wait_ms"):
                if t.get(k) is not None:
                    r[k] = t[k]
    order = {"finish": 0, "cancel": 1}
    return {
        "jobs": len(rows),
        "finished": sum(1 for r in rows.values()
                        if r.get("status") == "finish"),
        "cancelled": sum(1 for r in rows.values()
                         if r.get("status") == "cancel"),
        "rows": sorted(rows.values(),
                       key=lambda r: (order.get(r.get("status"), 2),
                                      r["name"])),
    }


def tenant_lines(t) -> list:
    if not t:
        return []
    lines = [f"  tenants: {t['jobs']} job(s), {t['finished']} finished"
             + (f", {t['cancelled']} cancelled" if t["cancelled"]
                else "")]
    for r in t["rows"]:
        budget = (f"/{r['job_steps']}" if r.get("job_steps") is not None
                  else "")
        bits = [f"    {r['name']}: {r.get('status', '?')} @ step "
                f"{r.get('step', '?')}{budget}"]
        if r.get("loss") is not None:
            bits.append(f"loss {_fmt(r['loss'], 4)}")
        if r.get("tokens") is not None:
            bits.append(f"{r['tokens']} tok")
        if r.get("wait_ms"):
            bits.append(f"wait {_fmt(r['wait_ms'], 1)} ms")
        if r.get("path"):
            bits.append(f"-> {r['path']}")
        lines.append(", ".join(bits))
    return lines


def request_summary(events) -> dict:
    """Serving SLOs from the per-request `request` lifecycle events
    (serve/engine.py): TTFT/TPOT percentiles over FINISHED requests,
    sustained req/s over the stream's observed request span, and —
    round 14 — the failure-mode counters and rates (reject / timeout /
    error over submitted) a robustness policy is judged by. None when
    the stream carries no serving traffic."""
    reqs = [e for e in events if e.get("event") == "request"]
    if not reqs:
        return None
    fins = [e for e in reqs if e.get("phase") == "finish"]
    ttfts = sorted(e["ttft_ms"] for e in fins
                   if e.get("ttft_ms") is not None)
    tpots = sorted(e["tpot_ms"] for e in fins
                   if e.get("tpot_ms") is not None)
    pcts = lambda vals: {"p50": percentile(vals, 50),
                         "p95": percentile(vals, 95),
                         "p99": percentile(vals, 99)}
    span = (max(e["t"] for e in reqs) - min(e["t"] for e in reqs)
            if len(reqs) > 1 else 0.0)
    gen = sum(e.get("new_tokens") or 0 for e in fins)
    sub = sum(1 for e in reqs if e.get("phase") == "enqueue")
    n_phase = lambda p: sum(1 for e in reqs if e.get("phase") == p)
    rate = lambda n: round(n / sub, 4) if sub else None
    rejected, timeouts, errors = (n_phase("reject"), n_phase("timeout"),
                                  n_phase("error"))
    reasons = {}
    for e in reqs:
        if e.get("phase") in ("reject", "timeout", "error") \
                and e.get("reason"):
            reasons[e["reason"]] = reasons.get(e["reason"], 0) + 1
    return {
        "submitted": sub,
        "finished": len(fins),
        "cancelled": n_phase("cancel"),
        "rejected": rejected,
        "timeout": timeouts,
        "errors": errors,
        "reject_rate": rate(rejected),
        "timeout_rate": rate(timeouts),
        "error_rate": rate(errors),
        "fail_reasons": reasons,
        "ttft_ms": pcts(ttfts),
        "tpot_ms": pcts(tpots),
        "req_s": round(len(fins) / span, 3) if span > 0 else None,
        "gen_tok_s": round(gen / span, 1) if span > 0 else None,
    }


def request_lines(r) -> list:
    if not r:
        return []
    tt, tp = r["ttft_ms"], r["tpot_ms"]
    lines = [f"  requests: {r['finished']}/{r['submitted']} finished"
             + (f", {r['cancelled']} cancelled" if r["cancelled"] else "")
             + (f"; {r['req_s']:.2f} req/s"
                if r["req_s"] is not None else "")
             + (f", {r['gen_tok_s']:.0f} gen tok/s"
                if r["gen_tok_s"] is not None else "")]
    if tt["p50"] is not None:
        lines.append(f"    TTFT p50/p95/p99 = {_fmt(tt['p50'], 1)}/"
                     f"{_fmt(tt['p95'], 1)}/{_fmt(tt['p99'], 1)} ms")
    if tp["p50"] is not None:
        lines.append(f"    TPOT p50/p95/p99 = {_fmt(tp['p50'], 2)}/"
                     f"{_fmt(tp['p95'], 2)}/{_fmt(tp['p99'], 2)} ms")
    # pre-round-14 summaries (fleet_report fixtures) may lack the
    # failure counters; render the line only when something failed
    fails = [(k, r.get(k, 0), r.get(rk)) for k, rk in
             (("rejected", "reject_rate"), ("timeout", "timeout_rate"),
              ("errors", "error_rate"))]
    if any(n for _, n, _ in fails):
        pc = lambda v: f" ({100 * v:.1f}%)" if v else ""
        bits = [f"{k} {n}{pc(rt)}" for k, n, rt in fails if n]
        why = r.get("fail_reasons") or {}
        if why:
            bits.append("reasons: " + ", ".join(
                f"{k}={v}" for k, v in sorted(why.items())))
        lines.append("    " + "; ".join(bits))
    return lines


def serve_stats_summary(events) -> dict:
    """Roll up the cadenced `serve_stats` health snapshots
    (serve/engine.py health()): queue-depth peak, occupancy mean,
    free-page floor, latest rolling p95 step latency, and the final
    cumulative terminal-state counters. None when the stream carries
    none (pre-round-14 serve streams, training runs)."""
    ss = [e for e in events if e.get("event") == "serve_stats"]
    if not ss:
        return None
    last = ss[-1]
    return {
        "snapshots": len(ss),
        "queue_depth_max": max(e["queue_depth"] for e in ss),
        "queue_depth_last": last["queue_depth"],
        "occupancy_mean": round(
            sum(e["occupancy"] for e in ss) / len(ss), 4),
        "free_blocks_min": min(e["free_blocks"] for e in ss),
        "p95_step_ms_last": last["p95_step_ms"],
        # round-20 mesh shape [dp, tp]; absent on pre-sharding streams
        "mesh": last.get("mesh"),
        # round-21 shared-prefix reuse: cumulative hit rate + COW count
        # from the LAST snapshot; absent (None) on cache-off streams
        "prefix_hit_rate": last.get("prefix_hit_rate"),
        "cow_copies": last.get("cow_copies"),
        "counts": {k: last.get(k, 0) for k in
                   ("finished", "cancelled", "rejected", "timeout",
                    "error")},
    }


def serve_stats_lines(s) -> list:
    if not s:
        return []
    mesh = ""
    if s.get("mesh"):
        mesh = f", mesh {s['mesh'][0]}x{s['mesh'][1]}"
    reuse = ""
    if s.get("prefix_hit_rate") is not None:
        reuse = (f", prefix hit_rate {s['prefix_hit_rate']:.2f} "
                 f"({s.get('cow_copies') or 0} COW cop"
                 f"{'y' if (s.get('cow_copies') or 0) == 1 else 'ies'})")
    return [f"  serve health: {s['snapshots']} snapshot(s); queue max "
            f"{s['queue_depth_max']} (last {s['queue_depth_last']}), "
            f"occupancy mean {100 * s['occupancy_mean']:.0f}%, free "
            f"pages min {s['free_blocks_min']}, p95 step "
            f"{_fmt(s['p95_step_ms_last'], 1)} ms{mesh}{reuse}"]


def route_summary(events) -> dict:
    """Roll up the serve-router's `route` decision events (round 22,
    tools/serve_router.py): decision histogram by policy and by placed
    replica, reject count, distinct rids, and snapshot-staleness
    percentiles (scrape_age_ms — how old the metrics behind each
    decision were). None when the stream carries no routing traffic.
    ONE builder shared with tools/fleet_report.py; serve_fleet_summary
    wraps it with the cross-shard accounting."""
    rs = [e for e in events if e.get("event") == "route"]
    if not rs:
        return None
    by_policy, by_replica = {}, {}
    for e in rs:
        p = e.get("policy", "?")
        by_policy[p] = by_policy.get(p, 0) + 1
        if e.get("replica") is not None:
            k = str(e["replica"])
            by_replica[k] = by_replica.get(k, 0) + 1
    ages = sorted(e["scrape_age_ms"] for e in rs
                  if e.get("scrape_age_ms") is not None)
    return {
        "decisions": len(rs),
        "rids": len({e["rid"] for e in rs}),
        "by_policy": by_policy,
        "by_replica": by_replica,
        "rejects": by_policy.get("reject", 0),
        "scrape_age_ms": {"p50": percentile(ages, 50),
                          "p95": percentile(ages, 95),
                          "max": ages[-1] if ages else None},
    }


def route_lines(r) -> list:
    """Render a route_summary (shared with fleet_report)."""
    if not r:
        return []
    pol = ", ".join(f"{k} {v}"
                    for k, v in sorted(r["by_policy"].items()))
    spread = ", ".join(f"r{k}:{v}"
                       for k, v in sorted(r["by_replica"].items()))
    a = r["scrape_age_ms"]
    line = (f"  routing: {r['decisions']} decision(s) over "
            f"{r['rids']} rid(s) ({pol}); spread {spread or 'none'}")
    if a["p50"] is not None:
        line += (f"; snapshot age p50/p95/max = {_fmt(a['p50'], 1)}/"
                 f"{_fmt(a['p95'], 1)}/{_fmt(a['max'], 1)} ms")
    return [line]


def serve_fleet_summary(shards) -> dict:
    """The serve-fleet section (round 22): {host: events} with the
    router stream at host 0 and replica shards at host k. Router side:
    route_summary plus EXACT rid accounting — every placed rid must
    own at most one replica-side terminal (a duplicate means two
    replicas both think they finished the same request; a rid with
    none was settled router-side from the shard tail or the shutdown
    fallback, which is how a killed replica's orphans are supposed to
    land). Replica side: one row per shard via the SAME
    request_summary/serve_stats_summary builders the single-engine
    report renders. None when host 0 carries no route events (not a
    router session)."""
    routing = route_summary(shards.get(0, []))
    if routing is None:
        return None
    placed = {e["rid"] for e in shards.get(0, [])
              if e.get("event") == "route"
              and isinstance(e.get("rid"), int)
              and e.get("replica") is not None}
    terminal: dict = {}
    replicas = {}
    for h, evs in sorted(shards.items()):
        if h == 0:
            continue
        replicas[str(h)] = {
            "requests": request_summary(evs),
            "serve": serve_stats_summary(evs),
        }
        for e in evs:
            if e.get("event") == "request" \
                    and isinstance(e.get("rid"), int) \
                    and e.get("phase") in ("finish", "cancel", "reject",
                                           "timeout", "error"):
                terminal[e["rid"]] = terminal.get(e["rid"], 0) + 1
    settled = sum(1 for r in placed if terminal.get(r))
    return {
        "routing": routing,
        "replicas": replicas,
        "routed_rids": len(placed),
        "replica_settled_rids": settled,
        "router_settled_rids": len(placed) - settled,
        "duplicate_terminals": sum(1 for r in placed
                                   if terminal.get(r, 0) > 1),
    }


def serve_fleet_lines(f) -> list:
    """Render a serve_fleet_summary (shared with fleet_report)."""
    if not f:
        return []
    lines = route_lines(f["routing"])
    lines.append(
        f"  fleet accounting: {f['routed_rids']} placed, "
        f"{f['replica_settled_rids']} replica-settled, "
        f"{f['router_settled_rids']} router-settled"
        + (f", {f['duplicate_terminals']} DUPLICATE terminal(s)"
           if f["duplicate_terminals"] else ""))
    for k, r in sorted(f["replicas"].items(), key=lambda kv: int(kv[0])):
        req, sv = r["requests"], r["serve"]
        if not req:
            lines.append(f"    replica {k}: no request traffic")
            continue
        hit = ""
        if sv and sv.get("prefix_hit_rate") is not None:
            hit = f", prefix hit_rate {sv['prefix_hit_rate']:.2f}"
        lines.append(
            f"    replica {k}: {req['finished']}/{req['submitted']} "
            f"finished, TTFT p99 {_fmt(req['ttft_ms']['p99'], 1)} ms, "
            f"TPOT p50 {_fmt(req['tpot_ms']['p50'], 2)} ms{hit}")
    return lines


def controller_entries(events) -> list:
    """Summary dicts for `controller` events (the fleet controller's
    recovery timeline, tools/fleet_controller.py) — ONE builder shared
    with tools/fleet_report.py like the straggler/hang entries."""
    return [{"t": e["t"], "action": e["action"],
             "worker": e.get("worker"), "reason": e.get("reason"),
             "attempt": e.get("attempt"), "step": e.get("step"),
             "recovery_s": e.get("recovery_s")}
            for e in events if e.get("event") == "controller"]


def latest_controller_session(entries) -> list:
    """The controller stream appends across sessions (re-running with
    the same --telemetry base resumes the file). Scope to the LATEST
    session — the same rule the worker shards get from split_latest_run
    — so a resumed fleet's recovery accounting describes THIS run, not
    every run ever recorded. A session STARTS with a burst of `launch`
    events, so the latest session begins at the last launch whose
    predecessor is not itself a launch — robust even when an earlier
    session died without its stop/give_up terminator (a SIGKILLed
    controller writes no goodbye). Streams with no launch at all
    (hand-built fixtures) fall back to terminator slicing."""
    starts = [i for i, e in enumerate(entries)
              if e["action"] == "launch"
              and (i == 0 or entries[i - 1]["action"] != "launch")]
    if starts:
        return entries[starts[-1]:]
    ends = [i for i, e in enumerate(entries)
            if e["action"] in ("stop", "give_up")]
    if not ends:
        return entries
    last = ends[-1]
    if last == len(entries) - 1:  # closed session: back to the previous
        prev = ends[-2] if len(ends) > 1 else -1
        return entries[prev + 1:]
    return entries[last + 1:]     # live session after the last closed one


def controller_summary(entries) -> dict:
    """Roll up the recovery timeline (scoped to the LATEST controller
    session): restarts/shrinks/lost counts and the total recovery
    wall-clock (down-observed -> relaunched, summed over restart+shrink
    events) — the number that turns recovery cost into a visible line
    next to the goodput buckets instead of a mystery gap in step reach.
    None when no controller ran."""
    if not entries:
        return None
    entries = latest_controller_session(entries)
    return {
        "events": len(entries),
        "restarts": sum(1 for e in entries if e["action"] == "restart"),
        "shrinks": sum(1 for e in entries if e["action"] == "shrink"),
        "lost": sum(1 for e in entries if e["action"] == "lost"),
        "drains": sum(1 for e in entries if e["action"] == "drain"),
        "gave_up": any(e["action"] == "give_up" for e in entries),
        "recovery_s": round(sum(e["recovery_s"] or 0.0 for e in entries
                                if e["action"] in ("restart", "shrink")),
                            3),
        "entries": entries,
    }


def controller_lines(cs) -> list:
    """Render a controller_summary (shared with fleet_report)."""
    if not cs:
        return []
    head = (f"  controller: {cs['restarts']} restart(s), "
            f"{cs['shrinks']} shrink(s), {cs['lost']} lost, "
            f"recovery {cs['recovery_s']:.2f}s"
            + (", GAVE UP" if cs["gave_up"] else "")
            + (f", {cs['drains']} drain(s)" if cs["drains"] else ""))
    lines = [head]
    for e in cs["entries"]:
        if e["action"] not in ("restart", "shrink", "lost", "give_up",
                               "drain"):
            continue
        bits = [f"    {e['action'].upper()}"]
        if e["worker"] is not None:
            bits.append(f"worker {e['worker']}")
        if e["reason"]:
            bits.append(f"({e['reason']})")
        if e["step"] is not None:
            bits.append(f"@ step {e['step']}")
        if e["attempt"] is not None:
            bits.append(f"attempt {e['attempt']}")
        if e["recovery_s"] is not None:
            bits.append(f"recovered in {e['recovery_s']:.2f}s")
        lines.append(" ".join(bits))
    return lines


def straggler_entries(events) -> list:
    """Summary dicts for `straggler` events — ONE builder shared with
    tools/fleet_report.py (same rule as goodput_lines)."""
    return [{"step": e["step"], "slow_host": e["slow_host"],
             "host_ms": e["host_ms"], "fleet_ms": e["fleet_ms"],
             "ratio": e["ratio"]}
            for e in events if e.get("event") == "straggler"]


def hang_entries(events) -> list:
    """Summary dicts for `hang` events (host = the WRITER's envelope
    stamp: which process's watchdog fired)."""
    return [{"host": e.get("host", 0), "step": e["step"],
             "stall_s": e["stall_s"], "device_probe": e["device_probe"],
             "action": e["action"], "stacks_file": e["stacks_file"]}
            for e in events if e.get("event") == "hang"]


def straggler_lines(entries) -> list:
    return [f"  STRAGGLER @ step {e['step']}: host {e['slow_host']} at "
            f"{e['host_ms']:.1f} ms vs fleet {e['fleet_ms']:.1f} ms "
            f"({e['ratio']}x)" for e in entries]


def hang_lines(entries) -> list:
    return [f"  HANG on host {e['host']} @ step {e['step']}: stalled "
            f"{e['stall_s']:.1f}s, device probe {e['device_probe']}, "
            f"action {e['action']} (stacks: {e['stacks_file']})"
            for e in entries]


def goodput_lines(g) -> list:
    """Render a goodput dict — writer-side (GoodputMeter.summary) or
    reader-side (partial_goodput) — to report lines. ONE renderer,
    shared with tools/fleet_report.py, so the two reports cannot
    drift."""
    if not g:
        return []
    if g.get("partial"):
        return [f"  goodput (PARTIAL, reconstructed): compile "
                f"{g['compile_s']:.1f}s, checkpoint "
                f"{g['checkpoint_s']:.1f}s, governor sleep "
                f"{g['governor_sleep_s']:.1f}s, input-wait "
                f"{100 * g['input_wait_frac_of_step']:.1f}% of step "
                f"time over {g['observed_span_s']:.1f}s observed"]
    buckets = ", ".join(
        f"{k[:-2]} {v:.1f}s" for k, v in g.items()
        if k.endswith("_s") and k != "total_s" and v)
    return [f"  goodput: {100 * g['productive_frac']:.1f}% productive "
            f"of {g['total_s']:.1f}s ({buckets})"]


def add_format_flags(ap: argparse.ArgumentParser) -> None:
    """--format {text,json} (+ the legacy --json alias), shared by both
    report tools so the output contract cannot drift between them."""
    ap.add_argument("--format", choices=["text", "json"], default="text",
                    help="'json' = machine-readable summary (the same "
                         "section builders the text report renders — "
                         "dashboards and CI consume the numbers humans "
                         "read)")
    ap.add_argument("--json", action="store_true",
                    help="alias for --format json (kept for existing "
                         "callers)")


def emit_output(summary: dict, args, text_printer) -> None:
    """ONE serializer for both report tools: the summary dict the
    section builders assembled is either json.dumps'd verbatim or
    handed to the tool's text printer — the JSON output IS the text
    report's input, so the two can never disagree."""
    try:
        if args.json or args.format == "json":
            print(json.dumps(summary, indent=1))
        else:
            text_printer(summary)
    except BrokenPipeError:  # `report run.jsonl | head` is a normal use
        pass


# -- run-registry resolution (round 23, DESIGN.md §28) ----------------------

def add_registry_flags(ap: argparse.ArgumentParser) -> None:
    """--registry/--run, shared by every report tool that can resolve
    its input from the run registry instead of a raw file path."""
    ap.add_argument("--registry", default="",
                    help="run registry stream (core/run_registry.py); "
                         "default $MFT_RUN_REGISTRY")
    ap.add_argument("--run", default="",
                    help="resolve the input path from the registry by "
                         "run id, unique id prefix, or git rev — "
                         "instead of passing a file path")


def resolve_stream(args, what: str = "telemetry stream",
                   suffix: str = ".jsonl") -> str:
    """The tool's input path: --run wins (registry artifact lookup —
    after resolution it IS a path invocation, so output stays
    byte-identical), else the positional. SystemExit with a named
    error when neither resolves."""
    token = getattr(args, "run", "")
    if token:
        from mobilefinetuner_tpu.core.run_registry import registry_from
        reg = registry_from(getattr(args, "registry", ""))
        if reg is None:
            raise SystemExit(
                "--run needs --registry or $MFT_RUN_REGISTRY")
        path = reg.artifact_for(token, suffix=suffix)
        if not path:
            raise SystemExit(
                f"--run {token!r}: no {what} artifact ({suffix}) "
                f"resolved from registry {reg.path}")
        return path
    path = getattr(args, "jsonl", None)
    if not path:
        raise SystemExit(f"pass a {what} path or --run <id>")
    return path


# -- longitudinal trend section (round 23, DESIGN.md §28) -------------------

#: eight-level unicode sparkline ramp (lowest..highest)
SPARK_RAMP = "▁▂▃▄▅▆▇█"


def sparkline(values) -> str:
    """One unicode sparkline over a numeric series (Nones skipped on
    scale, rendered as spaces in place) — the per-metric history cell
    of the observatory's trend table."""
    vals = [v for v in values if v is not None]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    out = []
    for v in values:
        if v is None:
            out.append(" ")
            continue
        i = int((v - lo) / span * (len(SPARK_RAMP) - 1))
        out.append(SPARK_RAMP[i])
    return "".join(out)


def trend_lines(series) -> list:
    """Markdown trend table over observatory series dicts (each one:
    metric/config/platform/values/runs/verdict fields — see
    tools/observatory.py). One row per (platform, config, metric),
    regressions flagged in the status column."""
    if not series:
        return []
    rows = ["| platform | config | metric | n | latest | median | z | trend | status |",
            "|---|---|---|---|---|---|---|---|---|"]
    for s in series:
        status = "**REGRESSED**" if s.get("regressed") else "ok"
        z = s.get("z")
        med = s.get("median")
        latest = s.get("value")
        rows.append(
            "| {platform} | {config} | {metric} | {n} | {latest} | "
            "{median} | {z} | `{spark}` | {status} |".format(
                platform=s.get("platform", "?"),
                config=s.get("config", "?"),
                metric=s.get("metric", "?"),
                n=s.get("n", 0),
                latest=_fmt(latest, 3) if latest is not None else "-",
                median=_fmt(med, 3) if med is not None else "-",
                z=_fmt(z, 2) if z is not None else "-",
                spark=sparkline(s.get("values", [])),
                status=status))
    return rows
