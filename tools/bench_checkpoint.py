"""Checkpoint save-path benchmark: sync stall vs async blocking time.

Measures what ISSUE 5 / DESIGN.md §15 claims: with snapshot-then-write
checkpointing (`io/async_ckpt.py`) the step loop's blocking cost at a
save step collapses to the batched device→host snapshot, while the
HF key-mapping + encode + atomic safetensors write moves to the
background writer. One JSON row per measured tree on stdout:

  {"config": "...", "tree_bytes": ..., "sync_stall_ms": ...,
   "async_blocking_ms": ..., "snapshot_ms": ..., "write_ms": ...,
   "mb_s": ..., "blocking_frac": ..., "byte_identical": true}

`sync_stall_ms` is the full old-path stall (snapshot + write, the
`--async_save 0` oracle); `async_blocking_ms` is what the loop pays
under `--async_save` (snapshot + enqueue — the acceptance bar is
async_blocking ≤ 25% of sync on the real trees); `write_ms` is the
background write as reported by the checkpointer's own telemetry
event, and `byte_identical` is checked file-against-file, so every row
self-certifies the parity claim it rides on.

Trees measured by default (the two checkpoint shapes the train CLIs
produce): the GPT-2-small full-FT tree (params + Adam m/v sidecar,
via the real save_gpt2/save_state writers) and the Gemma-3-270M LoRA
adapter (save_adapter + sidecar). CPU-runnable: `--size tiny` swaps in
the test configs (what tests/test_async_ckpt.py contract-tests).

Usage:
  python tools/bench_checkpoint.py                # real sizes
  python tools/bench_checkpoint.py --size tiny --repeats 2
"""

from __future__ import annotations

import argparse
import filecmp
import json
import os
import shutil
import sys
import tempfile
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np


def _device_tree(host_tree):
    """Place a host pytree on the default device so the snapshot
    measures a real D2H pull."""
    return jax.tree.map(
        lambda x: jax.device_put(jnp.asarray(x)).block_until_ready(),
        host_tree)


def _adam_like(params):
    """Adam m/v the same shape as params (what the .opt sidecar holds)."""
    return {"step": jnp.zeros((), jnp.int32),
            "m": jax.tree.map(jnp.zeros_like, params),
            "v": jax.tree.map(jnp.zeros_like, params)}


def build_gpt2_fullft(size: str):
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.io.checkpoints import save_gpt2
    from mobilefinetuner_tpu.optim.adam import AdamConfig, save_state
    from mobilefinetuner_tpu.models import gpt2
    cfg = GPT2Config.tiny() if size == "tiny" else GPT2Config.gpt2_small()
    params = _device_tree(gpt2.init_params(cfg, jax.random.PRNGKey(0)))
    opt = _device_tree(_adam_like(params))

    def write(path, params_h, opt_h):
        save_gpt2(path, params_h)
        save_state(path + ".opt", opt_h, AdamConfig())
        return [path, path + ".opt"]

    return f"gpt2s_fullft_{size}", (params, opt), write


def build_gemma_lora(size: str):
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gemma3
    from mobilefinetuner_tpu.lora.peft_io import save_adapter
    from mobilefinetuner_tpu.optim.adam import AdamConfig, save_state
    cfg = (Gemma3TextConfig.tiny() if size == "tiny"
           else Gemma3TextConfig.gemma3_270m())
    spec = LoRASpec(rank=8, alpha=16.0)
    lora = _device_tree(init_lora_gemma3(cfg, spec,
                                         jax.random.PRNGKey(1)))
    opt = _device_tree(_adam_like(lora))

    def write(path, lora_h, opt_h):
        save_adapter(path, lora_h, spec)
        save_state(path + ".opt", opt_h, AdamConfig())
        return [path, path + ".opt"]

    return f"gemma270m_lora_{size}", (lora, opt), write


def bench_tree(name, trees, write, out_dir, repeats: int) -> dict:
    """One row: run the sync oracle and the async pipeline through the
    REAL AsyncCheckpointer (the measured path is the shipped path), take
    the best-of-repeats for each side, verify byte parity."""
    from mobilefinetuner_tpu.io.async_ckpt import (AsyncCheckpointer,
                                                   timed_snapshot,
                                                   tree_bytes)
    events = []
    sink = lambda ev, **f: events.append((ev, f))
    sync_path = os.path.join(out_dir, f"{name}_sync.safetensors")
    async_path = os.path.join(out_dir, f"{name}_async.safetensors")

    sync_ms, async_ms, snap_ms, write_ms, mb_s, nbytes = \
        [], [], [], [], [], 0
    for _ in range(repeats):
        # sync oracle: blocking = snapshot + write
        ck = AsyncCheckpointer(enabled=False, event_sink=sink)
        t0 = time.perf_counter()
        host, sms = timed_snapshot(trees)
        ck.save(0, lambda: write(sync_path, *host), snapshot_ms=sms)
        sync_ms.append((time.perf_counter() - t0) * 1000.0)
        nbytes = tree_bytes(host)

        # async: blocking = snapshot + enqueue; write happens behind
        ck = AsyncCheckpointer(enabled=True, event_sink=sink)
        t0 = time.perf_counter()
        host, sms = timed_snapshot(trees)
        ck.save(0, lambda: write(async_path, *host), snapshot_ms=sms)
        async_ms.append((time.perf_counter() - t0) * 1000.0)
        snap_ms.append(sms)
        ck.close()  # drain so write_ms below covers a completed write
        ev = [f for e, f in events if e == "checkpoint"][-1]
        write_ms.append(ev["write_ms"])
        if ev["mb_s"]:
            mb_s.append(ev["mb_s"])

    identical = all(
        filecmp.cmp(sync_path + sfx, async_path + sfx, shallow=False)
        for sfx in ("", ".opt"))
    best_sync, best_async = min(sync_ms), min(async_ms)
    return {
        "config": name,
        "tree_bytes": nbytes,
        "sync_stall_ms": round(best_sync, 3),
        "async_blocking_ms": round(best_async, 3),
        "snapshot_ms": round(min(snap_ms), 3),
        "write_ms": round(min(write_ms), 3),
        "mb_s": round(max(mb_s), 2) if mb_s else None,
        "blocking_frac": round(best_async / best_sync, 4)
        if best_sync > 0 else None,
        "byte_identical": identical,
    }


def run_rows(size: str, repeats: int, out_dir=None) -> list:
    keep = out_dir is not None
    out_dir = out_dir or tempfile.mkdtemp(prefix="bench_ckpt_")
    os.makedirs(out_dir, exist_ok=True)
    rows = []
    try:
        for build in (build_gpt2_fullft, build_gemma_lora):
            name, trees, write = build(size)
            rows.append(bench_tree(name, trees, write, out_dir, repeats))
    finally:
        if not keep:
            shutil.rmtree(out_dir, ignore_errors=True)
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--size", choices=["real", "tiny"], default="real",
                    help="real = GPT-2s full FT + Gemma-270M LoRA; "
                         "tiny = test configs (CPU contract runs)")
    ap.add_argument("--repeats", type=int, default=3)
    ap.add_argument("--out_dir", default="",
                    help="keep the written checkpoint files here "
                         "(default: tempdir, removed)")
    ap.add_argument("--out", default="",
                    help="also write the rows to this JSON file")
    args = ap.parse_args(argv)
    rows = run_rows(args.size, args.repeats, args.out_dir or None)
    for r in rows:
        print(json.dumps(r))
    if args.out:
        with open(args.out, "w") as f:
            json.dump({"rows": rows}, f, indent=1)
    return 0


if __name__ == "__main__":
    sys.exit(main())
