"""End-to-end perplexity pipeline at full model scale (both families).

The correctness anchor for the rebuild is the reference's README numbers:
WikiText-2 PPL ~29.5 pretrained -> ~26.8 after one LoRA epoch
(reference: README.md:355-357). This environment has zero egress (no real
checkpoint or WikiText-2 download), so this tool proves the FULL pipeline
at the real size instead: it synthesizes a full-size HF-format checkpoint
(random weights, real key schemes/layouts — 124M GPT-2-small with its
50257 vocab, or 270M Gemma-3 with the full 262,144-entry tokenizer) plus
a WikiText-shaped synthetic corpus, then runs

  eval_ppl (baseline) -> gpt2_lora_finetune | train_lora_gemma
                      -> eval_ppl (adapter merged)

through the actual CLIs and records baseline/post PPLs + training
throughput as one JSON artifact. Against REAL data the exact same recipe
applies — point the flags at real dirs:

  python tools/e2e_ppl_pipeline.py \
      --model_dir /path/gpt2 --data_root /path/wikitext-2 \
      --train_steps 0 --epochs 1        # one epoch, reference protocol
  # expected with the real checkpoint: baseline ppl ~29.5 at S=1024,
  # post-LoRA ~26.8 (README.md:355-357)
  python tools/e2e_ppl_pipeline.py --family gemma \
      --model_dir /path/gemma-3-270m --data_root /path/wikitext-2

With synthetic data the assertion is structural: the pipeline runs at
full size end-to-end and LoRA training IMPROVES the eval PPL on held-out
synthetic text (the corpus is Zipfian with bigram structure, so there is
signal to learn).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def write_synthetic_gpt2(d: str, seed: int = 0):
    """Full-size GPT-2-small HF checkpoint dir with random weights: real
    config.json, model.safetensors in HF GPT2LMHeadModel keys (Conv1D
    [in, out] layout), and a 50257-entry byte-level vocab (256 byte tokens
    + filler + <|endoftext|>=50256; empty merges, so encoding is pure
    byte-level — ids are valid and the full vocab head is exercised)."""
    import jax
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.data.tokenizer_bpe import bytes_to_unicode
    from mobilefinetuner_tpu.io.checkpoints import gpt2_params_to_hf
    from mobilefinetuner_tpu.io.safetensors_io import save_safetensors
    from mobilefinetuner_tpu.models import gpt2

    os.makedirs(d, exist_ok=True)
    cfg = GPT2Config.gpt2_small()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(seed))
    sd = gpt2_params_to_hf(jax.device_get(params))
    save_safetensors(os.path.join(d, "model.safetensors"),
                     {k: np.asarray(v) for k, v in sd.items()})
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gpt2", "vocab_size": cfg.vocab_size,
                   "n_positions": cfg.n_positions, "n_embd": cfg.n_embd,
                   "n_layer": cfg.n_layer, "n_head": cfg.n_head,
                   "activation_function": "gelu_new"}, f)
    byte_tokens = list(bytes_to_unicode().values())
    vocab = {t: i for i, t in enumerate(byte_tokens)}
    for i in range(len(byte_tokens), cfg.vocab_size - 1):
        vocab[f"[unused{i}]"] = i
    vocab["<|endoftext|>"] = cfg.vocab_size - 1
    with open(os.path.join(d, "vocab.json"), "w") as f:
        json.dump(vocab, f)
    with open(os.path.join(d, "merges.txt"), "w") as f:
        f.write("#version: 0.2\n")
    return cfg


def write_synthetic_gemma270m(d: str, seed: int = 0):
    """Full-size Gemma-3-270M HF checkpoint dir with random weights: real
    config.json (gemma3_text), model.safetensors in HF Gemma3 keys
    ([out, in] linears), and a full 262,144-entry tokenizer.json — BPE
    trained on the synthetic corpus's vocabulary for realistic merges,
    padded with filler pieces to the real vocab size so the full 262k
    head + chunked CE run at true scale."""
    import jax
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.io.checkpoints import gemma3_params_to_hf
    from mobilefinetuner_tpu.io.safetensors_io import save_safetensors
    from mobilefinetuner_tpu.models import gemma3

    os.makedirs(d, exist_ok=True)
    cfg = Gemma3TextConfig.gemma3_270m()
    params = gemma3.init_params(cfg, jax.random.PRNGKey(seed))
    sd = gemma3_params_to_hf(jax.device_get(params))
    save_safetensors(os.path.join(d, "model.safetensors"),
                     {k: np.asarray(v) for k, v in sd.items()})
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gemma3_text",
                   "vocab_size": cfg.vocab_size,
                   "hidden_size": cfg.hidden_size,
                   "intermediate_size": cfg.intermediate_size,
                   "num_hidden_layers": cfg.num_hidden_layers,
                   "num_attention_heads": cfg.num_attention_heads,
                   "num_key_value_heads": cfg.num_key_value_heads,
                   "head_dim": cfg.head_dim,
                   "sliding_window": cfg.sliding_window,
                   "rope_theta": cfg.rope_theta,
                   "rope_local_base_freq": cfg.rope_local_base_freq,
                   "query_pre_attn_scalar": cfg.query_pre_attn_scalar},
                  f)

    # tokenizer: train a small real BPE on corpus-shaped text, then pad
    from tokenizers import Tokenizer, models, normalizers, trainers
    byte_tokens = [f"<0x{b:02X}>" for b in range(256)]
    tok = Tokenizer(models.BPE(unk_token="<unk>", byte_fallback=True))
    tok.normalizer = normalizers.Replace(" ", "▁")
    trainer = trainers.BpeTrainer(
        vocab_size=4000,
        special_tokens=["<pad>", "<eos>", "<bos>", "<unk>"] + byte_tokens,
        show_progress=False)
    corpus_words = [f"w{i:03d}" for i in range(400)]
    tok.train_from_iterator(
        (" ".join(corpus_words[i % 400] for i in range(j, j + 12))
         for j in range(3000)), trainer)
    spec = json.loads(tok.to_str())
    vocab = spec["model"]["vocab"]
    for i in range(len(vocab), cfg.vocab_size):
        vocab[f"<unused{i}>"] = i
    spec["model"]["vocab"] = vocab
    with open(os.path.join(d, "tokenizer.json"), "w") as f:
        json.dump(spec, f)
    return cfg


def write_synthetic_corpus(d: str, n_train_words: int = 120_000,
                           seed: int = 0):
    """WikiText-shaped splits with Zipfian unigrams + deterministic bigram
    continuation structure — learnable, so a short LoRA run measurably
    lowers held-out PPL."""
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    vocab = [f"w{i:03d}" for i in range(400)]
    p = 1.0 / np.arange(1, len(vocab) + 1)
    p /= p.sum()
    follow = rng.integers(0, len(vocab), len(vocab))  # bigram rule

    def gen(n_words, rng):
        words, w = [], int(rng.integers(len(vocab)))
        for _ in range(n_words):
            if rng.random() < 0.55:
                w = int(follow[w])        # predictable continuation
            else:
                w = int(rng.choice(len(vocab), p=p))
            words.append(vocab[w])
        lines, i = [], 0
        while i < len(words):
            ln = int(rng.integers(8, 24))
            lines.append(" " + " ".join(words[i:i + ln]) + " ")
            i += ln
        return "\n".join(lines) + "\n"

    for split, n in (("train", n_train_words),
                     ("valid", n_train_words // 10),
                     ("test", n_train_words // 10)):
        with open(os.path.join(d, f"wiki.{split}.tokens"), "w") as f:
            f.write(gen(n, np.random.default_rng(seed + hash(split) % 97)))
    return d


def run_eval(gpt2_dir, data_root, seq_len, batch_size, max_batches,
             lora_path="", merge=True, dtype="bfloat16"):
    from mobilefinetuner_tpu.cli import eval_ppl
    import contextlib
    import io
    buf = io.StringIO()
    argv = ["--pretrained_dir", gpt2_dir, "--data_root", data_root,
            "--split", "valid", "--seq_len", str(seq_len),
            "--batch_size", str(batch_size), "--dtype", dtype,
            "--log_every", "0"]
    if max_batches:
        argv += ["--max_batches", str(max_batches)]
    if lora_path:
        argv += ["--lora_path", lora_path] + \
            (["--lora_merge"] if merge else [])
    with contextlib.redirect_stdout(buf):
        rc = eval_ppl.main(argv)
    assert rc == 0
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "gemma"], default="gpt2")
    ap.add_argument("--gpt2_dir", "--model_dir", dest="model_dir",
                    default="",
                    help="real HF model dir; default: synthesize full size")
    ap.add_argument("--data_root", default="",
                    help="real WikiText-2 dir; default: synthesize")
    ap.add_argument("--work_dir", default="/tmp/e2e_ppl")
    ap.add_argument("--out", default="E2E_PPL.json")
    ap.add_argument("--train_steps", type=int, default=300)
    ap.add_argument("--epochs", type=int, default=0,
                    help="overrides train_steps when > 0 (real-data use)")
    ap.add_argument("--batch_size", type=int, default=0,
                    help="0 = family default (16 gpt2 / 8 gemma)")
    ap.add_argument("--seq_len", type=int, default=0,
                    help="0 = family default (128 gpt2 / 256 gemma, the "
                         "BASELINE configs)")
    ap.add_argument("--eval_seq_len", type=int, default=0)
    ap.add_argument("--eval_batches", type=int, default=30)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args(argv)

    gemma = args.family == "gemma"
    args.batch_size = args.batch_size or (8 if gemma else 16)
    args.seq_len = args.seq_len or (256 if gemma else 128)
    args.eval_seq_len = args.eval_seq_len or args.seq_len

    os.makedirs(args.work_dir, exist_ok=True)
    synthetic = not args.model_dir
    model_dir = args.model_dir or os.path.join(
        args.work_dir, "gemma270m" if gemma else "gpt2s")
    data_root = args.data_root or os.path.join(args.work_dir, "corpus")
    if synthetic:
        name = "270M Gemma-3" if gemma else "124M GPT-2-small"
        print(f"synthesizing {name} checkpoint + corpus...",
              file=sys.stderr)
        if gemma:
            write_synthetic_gemma270m(model_dir)
        else:
            write_synthetic_gpt2(model_dir)
    if not args.data_root:
        write_synthetic_corpus(data_root)

    base = run_eval(model_dir, data_root, args.eval_seq_len,
                    8, args.eval_batches, dtype=args.dtype)
    print(f"baseline: ppl={base['ppl']:.2f}", file=sys.stderr)

    common_argv = ["--data_dir", data_root,
                   "--batch_size", str(args.batch_size),
                   "--seq_len", str(args.seq_len),
                   "--lr", str(args.lr), "--dtype", args.dtype,
                   "--log_interval", "50"] + \
        (["--epochs", str(args.epochs)] if args.epochs
         else ["--steps", str(args.train_steps)])
    t0 = time.time()
    if gemma:
        from mobilefinetuner_tpu.cli import train_lora_gemma
        out_dir = os.path.join(args.work_dir, "gemma_out")
        rc = train_lora_gemma.main(
            ["--model_dir", model_dir, "--output_dir", out_dir,
             "--targets", "full"] + common_argv)
        adapter = os.path.join(out_dir, "gemma_lora.safetensors")
    else:
        from mobilefinetuner_tpu.cli import gpt2_lora_finetune
        adapter = os.path.join(args.work_dir, "adapter.safetensors")
        rc = gpt2_lora_finetune.main(
            ["--pretrained_dir", model_dir, "--lora_out", adapter,
             "--lora_targets",
             "attn_qkv,attn_proj,mlp_fc_in,mlp_fc_out"] + common_argv)
    train_s = time.time() - t0
    assert rc == 0

    post = run_eval(model_dir, data_root, args.eval_seq_len,
                    8, args.eval_batches, lora_path=adapter,
                    dtype=args.dtype)
    print(f"post-LoRA: ppl={post['ppl']:.2f}", file=sys.stderr)

    steps = args.train_steps if not args.epochs else None
    report = {
        "synthetic": synthetic,
        "model": "gemma3-270m" if gemma else "gpt2-small-124M",
        "baseline_ppl": round(base["ppl"], 3),
        "post_lora_ppl": round(post["ppl"], 3),
        "ppl_improvement": round(base["ppl"] - post["ppl"], 3),
        "train_steps": steps, "train_seconds": round(train_s, 1),
        "train_tokens_per_sec": (round(steps * args.batch_size
                                       * args.seq_len / train_s, 1)
                                 if steps else None),
        "eval_tokens": post["tokens"],
        "reference_anchor": {"baseline_ppl": 29.5, "post_lora_ppl": 26.8,
                             "source": "/root/reference/README.md:355-357",
                             "note": "real-checkpoint numbers; this run "
                                     "is synthetic unless --model_dir"},
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return 0 if post["ppl"] < base["ppl"] else 1


if __name__ == "__main__":
    sys.exit(main())
