"""End-to-end perplexity pipeline at full GPT-2-small scale.

The correctness anchor for the rebuild is the reference's README numbers:
WikiText-2 PPL ~29.5 pretrained -> ~26.8 after one LoRA epoch
(reference: README.md:355-357). This environment has zero egress (no real
checkpoint or WikiText-2 download), so this tool proves the FULL pipeline
at the real size instead: it synthesizes a 124M-parameter GPT-2-small
HF-format checkpoint (random weights, real key scheme/layouts, full 50257
vocab) plus a WikiText-shaped synthetic corpus, then runs

  eval_ppl (baseline) -> gpt2_lora_finetune (short run)
                      -> eval_ppl (adapter merged)

through the actual CLIs and records baseline/post PPLs + training
throughput as one JSON artifact. Against REAL data the exact same recipe
applies — point the flags at real dirs:

  python tools/e2e_ppl_pipeline.py \
      --gpt2_dir /path/gpt2 --data_root /path/wikitext-2 \
      --train_steps 0 --epochs 1        # one epoch, reference protocol
  # expected with the real checkpoint: baseline ppl ~29.5 at S=1024,
  # post-LoRA ~26.8 (README.md:355-357)

With synthetic data the assertion is structural: the pipeline runs at
full size end-to-end and LoRA training IMPROVES the eval PPL on held-out
synthetic text (the corpus is Zipfian with bigram structure, so there is
signal to learn).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def write_synthetic_gpt2(d: str, seed: int = 0):
    """Full-size GPT-2-small HF checkpoint dir with random weights: real
    config.json, model.safetensors in HF GPT2LMHeadModel keys (Conv1D
    [in, out] layout), and a 50257-entry byte-level vocab (256 byte tokens
    + filler + <|endoftext|>=50256; empty merges, so encoding is pure
    byte-level — ids are valid and the full vocab head is exercised)."""
    import jax
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.data.tokenizer_bpe import bytes_to_unicode
    from mobilefinetuner_tpu.io.checkpoints import gpt2_params_to_hf
    from mobilefinetuner_tpu.io.safetensors_io import save_safetensors
    from mobilefinetuner_tpu.models import gpt2

    os.makedirs(d, exist_ok=True)
    cfg = GPT2Config.gpt2_small()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(seed))
    sd = gpt2_params_to_hf(jax.device_get(params))
    save_safetensors(os.path.join(d, "model.safetensors"),
                     {k: np.asarray(v) for k, v in sd.items()})
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gpt2", "vocab_size": cfg.vocab_size,
                   "n_positions": cfg.n_positions, "n_embd": cfg.n_embd,
                   "n_layer": cfg.n_layer, "n_head": cfg.n_head,
                   "activation_function": "gelu_new"}, f)
    byte_tokens = list(bytes_to_unicode().values())
    vocab = {t: i for i, t in enumerate(byte_tokens)}
    for i in range(len(byte_tokens), cfg.vocab_size - 1):
        vocab[f"[unused{i}]"] = i
    vocab["<|endoftext|>"] = cfg.vocab_size - 1
    with open(os.path.join(d, "vocab.json"), "w") as f:
        json.dump(vocab, f)
    with open(os.path.join(d, "merges.txt"), "w") as f:
        f.write("#version: 0.2\n")
    return cfg


def write_synthetic_corpus(d: str, n_train_words: int = 120_000,
                           seed: int = 0):
    """WikiText-shaped splits with Zipfian unigrams + deterministic bigram
    continuation structure — learnable, so a short LoRA run measurably
    lowers held-out PPL."""
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    vocab = [f"w{i:03d}" for i in range(400)]
    p = 1.0 / np.arange(1, len(vocab) + 1)
    p /= p.sum()
    follow = rng.integers(0, len(vocab), len(vocab))  # bigram rule

    def gen(n_words, rng):
        words, w = [], int(rng.integers(len(vocab)))
        for _ in range(n_words):
            if rng.random() < 0.55:
                w = int(follow[w])        # predictable continuation
            else:
                w = int(rng.choice(len(vocab), p=p))
            words.append(vocab[w])
        lines, i = [], 0
        while i < len(words):
            ln = int(rng.integers(8, 24))
            lines.append(" " + " ".join(words[i:i + ln]) + " ")
            i += ln
        return "\n".join(lines) + "\n"

    for split, n in (("train", n_train_words),
                     ("valid", n_train_words // 10),
                     ("test", n_train_words // 10)):
        with open(os.path.join(d, f"wiki.{split}.tokens"), "w") as f:
            f.write(gen(n, np.random.default_rng(seed + hash(split) % 97)))
    return d


def run_eval(gpt2_dir, data_root, seq_len, batch_size, max_batches,
             lora_path="", merge=True, dtype="bfloat16"):
    from mobilefinetuner_tpu.cli import eval_ppl
    import contextlib
    import io
    buf = io.StringIO()
    argv = ["--pretrained_dir", gpt2_dir, "--data_root", data_root,
            "--split", "valid", "--seq_len", str(seq_len),
            "--batch_size", str(batch_size), "--dtype", dtype,
            "--log_every", "0"]
    if max_batches:
        argv += ["--max_batches", str(max_batches)]
    if lora_path:
        argv += ["--lora_path", lora_path] + \
            (["--lora_merge"] if merge else [])
    with contextlib.redirect_stdout(buf):
        rc = eval_ppl.main(argv)
    assert rc == 0
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--gpt2_dir", default="",
                    help="real HF GPT-2 dir; default: synthesize 124M")
    ap.add_argument("--data_root", default="",
                    help="real WikiText-2 dir; default: synthesize")
    ap.add_argument("--work_dir", default="/tmp/e2e_ppl")
    ap.add_argument("--out", default="E2E_PPL.json")
    ap.add_argument("--train_steps", type=int, default=300)
    ap.add_argument("--epochs", type=int, default=0,
                    help="overrides train_steps when > 0 (real-data use)")
    ap.add_argument("--batch_size", type=int, default=16)
    ap.add_argument("--seq_len", type=int, default=128)
    ap.add_argument("--eval_seq_len", type=int, default=128)
    ap.add_argument("--eval_batches", type=int, default=30)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--dtype", default="bfloat16")
    args = ap.parse_args(argv)

    os.makedirs(args.work_dir, exist_ok=True)
    synthetic = not args.gpt2_dir
    gpt2_dir = args.gpt2_dir or os.path.join(args.work_dir, "gpt2s")
    data_root = args.data_root or os.path.join(args.work_dir, "corpus")
    if synthetic:
        print("synthesizing 124M GPT-2-small checkpoint + corpus...",
              file=sys.stderr)
        write_synthetic_gpt2(gpt2_dir)
    if not args.data_root:
        write_synthetic_corpus(data_root)

    base = run_eval(gpt2_dir, data_root, args.eval_seq_len,
                    8, args.eval_batches, dtype=args.dtype)
    print(f"baseline: ppl={base['ppl']:.2f}", file=sys.stderr)

    from mobilefinetuner_tpu.cli import gpt2_lora_finetune
    adapter = os.path.join(args.work_dir, "adapter.safetensors")
    train_argv = ["--pretrained_dir", gpt2_dir, "--data_dir", data_root,
                  "--batch_size", str(args.batch_size),
                  "--seq_len", str(args.seq_len), "--lr", str(args.lr),
                  "--dtype", args.dtype, "--lora_out", adapter,
                  "--log_interval", "50",
                  "--lora_targets",
                  "attn_qkv,attn_proj,mlp_fc_in,mlp_fc_out"]
    train_argv += (["--epochs", str(args.epochs)] if args.epochs
                   else ["--steps", str(args.train_steps)])
    t0 = time.time()
    rc = gpt2_lora_finetune.main(train_argv)
    train_s = time.time() - t0
    assert rc == 0

    post = run_eval(gpt2_dir, data_root, args.eval_seq_len,
                    8, args.eval_batches, lora_path=adapter,
                    dtype=args.dtype)
    print(f"post-LoRA: ppl={post['ppl']:.2f}", file=sys.stderr)

    steps = args.train_steps if not args.epochs else None
    report = {
        "synthetic": synthetic,
        "model": "gpt2-small-124M",
        "baseline_ppl": round(base["ppl"], 3),
        "post_lora_ppl": round(post["ppl"], 3),
        "ppl_improvement": round(base["ppl"] - post["ppl"], 3),
        "train_steps": steps, "train_seconds": round(train_s, 1),
        "train_tokens_per_sec": (round(steps * args.batch_size
                                       * args.seq_len / train_s, 1)
                                 if steps else None),
        "eval_tokens": post["tokens"],
        "reference_anchor": {"baseline_ppl": 29.5, "post_lora_ppl": 26.8,
                             "source": "/root/reference/README.md:355-357",
                             "note": "real-checkpoint numbers; this run "
                                     "is synthetic unless --gpt2_dir"},
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    return 0 if post["ppl"] < base["ppl"] else 1


if __name__ == "__main__":
    sys.exit(main())
