"""End-to-end perplexity pipeline at full model scale (both families).

The correctness anchor for the rebuild is the reference's README numbers:
WikiText-2 PPL ~29.5 pretrained -> ~26.8 after one LoRA epoch
(reference: README.md:355-357). This environment has zero egress (no real
checkpoint or WikiText-2 download), so this tool proves the FULL pipeline
at the real size instead: it synthesizes a full-size HF-format checkpoint
(random weights, real key schemes/layouts — 124M GPT-2-small with its
50257 vocab, or 270M Gemma-3 with the full 262,144-entry tokenizer) plus
a WikiText-shaped synthetic corpus, then runs

  eval_ppl (baseline) -> gpt2_lora_finetune | train_lora_gemma
                      -> eval_ppl (adapter merged)

through the actual CLIs and records baseline/post PPLs + training
throughput as one JSON artifact. Against REAL data the exact same recipe
applies — point the flags at real dirs:

  python tools/e2e_ppl_pipeline.py \
      --model_dir /path/gpt2 --data_root /path/wikitext-2 \
      --train_steps 0 --epochs 1        # one epoch, reference protocol
  # expected with the real checkpoint: baseline ppl ~29.5 at S=1024,
  # post-LoRA ~26.8 (README.md:355-357)
  python tools/e2e_ppl_pipeline.py --family gemma \
      --model_dir /path/gemma-3-270m --data_root /path/wikitext-2

With synthetic data the assertions are:
  1. structural — the pipeline runs at full size end-to-end and LoRA
     training IMPROVES the eval PPL on held-out synthetic text (the corpus
     is Zipfian with bigram structure, so there is signal to learn);
  2. cross-framework — HF transformers (+PEFT, after merging the trained
     adapter) evaluates the SAME checkpoint on the SAME token stream and
     must produce the SAME perplexity (|mean-NLL diff| < --anchor_tol),
     both at baseline and post-LoRA. This is the driver's correctness
     anchor ("match pytorch_alignment PPL", BASELINE.md) made executable
     without egress: whatever weights are in the checkpoint, the two
     frameworks must agree on their perplexity — so with the real GPT-2
     weights the rebuild reproduces the reference's 29.5 -> 26.8 by
     construction (reference: pytorch_alignment/gpt2_lora_finetune.py,
     README.md:355-357).
"""

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def write_synthetic_gpt2(d: str, seed: int = 0):
    """Full-size GPT-2-small HF checkpoint dir with random weights: real
    config.json, model.safetensors in HF GPT2LMHeadModel keys (Conv1D
    [in, out] layout), and a 50257-entry byte-level vocab (256 byte tokens
    + filler + <|endoftext|>=50256; empty merges, so encoding is pure
    byte-level — ids are valid and the full vocab head is exercised)."""
    import jax
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.data.tokenizer_bpe import bytes_to_unicode
    from mobilefinetuner_tpu.io.checkpoints import gpt2_params_to_hf
    from mobilefinetuner_tpu.io.safetensors_io import save_safetensors
    from mobilefinetuner_tpu.models import gpt2

    os.makedirs(d, exist_ok=True)
    cfg = GPT2Config.gpt2_small()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(seed))
    sd = gpt2_params_to_hf(jax.device_get(params))
    save_safetensors(os.path.join(d, "model.safetensors"),
                     {k: np.asarray(v) for k, v in sd.items()})
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gpt2", "vocab_size": cfg.vocab_size,
                   "n_positions": cfg.n_positions, "n_embd": cfg.n_embd,
                   "n_layer": cfg.n_layer, "n_head": cfg.n_head,
                   "activation_function": "gelu_new"}, f)
    byte_tokens = list(bytes_to_unicode().values())
    vocab = {t: i for i, t in enumerate(byte_tokens)}
    for i in range(len(byte_tokens), cfg.vocab_size - 1):
        vocab[f"[unused{i}]"] = i
    vocab["<|endoftext|>"] = cfg.vocab_size - 1
    with open(os.path.join(d, "vocab.json"), "w") as f:
        json.dump(vocab, f)
    with open(os.path.join(d, "merges.txt"), "w") as f:
        f.write("#version: 0.2\n")
    return cfg


def write_synthetic_gemma270m(d: str, seed: int = 0):
    """Full-size Gemma-3-270M HF checkpoint dir with random weights: real
    config.json (gemma3_text), model.safetensors in HF Gemma3 keys
    ([out, in] linears), and a full 262,144-entry tokenizer.json — BPE
    trained on the synthetic corpus's vocabulary for realistic merges,
    padded with filler pieces to the real vocab size so the full 262k
    head + chunked CE run at true scale."""
    import jax
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.io.checkpoints import gemma3_params_to_hf
    from mobilefinetuner_tpu.io.safetensors_io import save_safetensors
    from mobilefinetuner_tpu.models import gemma3

    os.makedirs(d, exist_ok=True)
    cfg = Gemma3TextConfig.gemma3_270m()
    params = gemma3.init_params(cfg, jax.random.PRNGKey(seed))
    sd = gemma3_params_to_hf(jax.device_get(params))
    save_safetensors(os.path.join(d, "model.safetensors"),
                     {k: np.asarray(v) for k, v in sd.items()})
    with open(os.path.join(d, "config.json"), "w") as f:
        json.dump({"model_type": "gemma3_text",
                   "vocab_size": cfg.vocab_size,
                   "hidden_size": cfg.hidden_size,
                   "intermediate_size": cfg.intermediate_size,
                   "num_hidden_layers": cfg.num_hidden_layers,
                   "num_attention_heads": cfg.num_attention_heads,
                   "num_key_value_heads": cfg.num_key_value_heads,
                   "head_dim": cfg.head_dim,
                   "sliding_window": cfg.sliding_window,
                   "rope_theta": cfg.rope_theta,
                   "rope_local_base_freq": cfg.rope_local_base_freq,
                   "query_pre_attn_scalar": cfg.query_pre_attn_scalar},
                  f)

    # tokenizer: train a small real BPE on corpus-shaped text, then pad
    from tokenizers import Tokenizer, models, normalizers, trainers
    byte_tokens = [f"<0x{b:02X}>" for b in range(256)]
    tok = Tokenizer(models.BPE(unk_token="<unk>", byte_fallback=True))
    tok.normalizer = normalizers.Replace(" ", "▁")
    trainer = trainers.BpeTrainer(
        vocab_size=4000,
        special_tokens=["<pad>", "<eos>", "<bos>", "<unk>"] + byte_tokens,
        show_progress=False)
    corpus_words = [f"w{i:03d}" for i in range(400)]
    tok.train_from_iterator(
        (" ".join(corpus_words[i % 400] for i in range(j, j + 12))
         for j in range(3000)), trainer)
    spec = json.loads(tok.to_str())
    vocab = spec["model"]["vocab"]
    for i in range(len(vocab), cfg.vocab_size):
        vocab[f"<unused{i}>"] = i
    spec["model"]["vocab"] = vocab
    with open(os.path.join(d, "tokenizer.json"), "w") as f:
        json.dump(spec, f)
    return cfg


def write_synthetic_corpus(d: str, n_train_words: int = 120_000,
                           seed: int = 0):
    """WikiText-shaped splits with Zipfian unigrams + deterministic bigram
    continuation structure — learnable, so a short LoRA run measurably
    lowers held-out PPL."""
    os.makedirs(d, exist_ok=True)
    rng = np.random.default_rng(seed)
    vocab = [f"w{i:03d}" for i in range(400)]
    p = 1.0 / np.arange(1, len(vocab) + 1)
    p /= p.sum()
    follow = rng.integers(0, len(vocab), len(vocab))  # bigram rule

    def gen(n_words, rng):
        words, w = [], int(rng.integers(len(vocab)))
        for _ in range(n_words):
            if rng.random() < 0.55:
                w = int(follow[w])        # predictable continuation
            else:
                w = int(rng.choice(len(vocab), p=p))
            words.append(vocab[w])
        lines, i = [], 0
        while i < len(words):
            ln = int(rng.integers(8, 24))
            lines.append(" " + " ".join(words[i:i + ln]) + " ")
            i += ln
        return "\n".join(lines) + "\n"

    for split, n in (("train", n_train_words),
                     ("valid", n_train_words // 10),
                     ("test", n_train_words // 10)):
        with open(os.path.join(d, f"wiki.{split}.tokens"), "w") as f:
            f.write(gen(n, np.random.default_rng(seed + hash(split) % 97)))
    return d


def run_eval(gpt2_dir, data_root, seq_len, batch_size, max_batches,
             lora_path="", merge=True, dtype="bfloat16"):
    from mobilefinetuner_tpu.cli import eval_ppl
    import contextlib
    import io
    buf = io.StringIO()
    argv = ["--pretrained_dir", gpt2_dir, "--data_root", data_root,
            "--split", "valid", "--seq_len", str(seq_len),
            "--batch_size", str(batch_size), "--dtype", dtype,
            "--log_every", "0"]
    if max_batches:
        argv += ["--max_batches", str(max_batches)]
    if lora_path:
        argv += ["--lora_path", lora_path] + \
            (["--lora_merge"] if merge else [])
    with contextlib.redirect_stdout(buf):
        rc = eval_ppl.main(argv)
    assert rc == 0
    return json.loads(buf.getvalue().strip().splitlines()[-1])


def torch_eval_ppl(model_dir, data_root, seq_len, batch_size, max_batches,
                   family, adapter_path="", work_dir="/tmp"):
    """HF transformers (+PEFT, adapter merged) perplexity on the SAME token
    stream our eval_ppl consumes: batches come from OUR WikiText2Dataset +
    tokenizer, the NLL uses the same internal shift / ignore_index=-100 /
    token-weighted mean (ops/loss.py semantics; reference:
    pytorch_alignment/gpt2_lora_finetune.py evaluation loop)."""
    import torch
    from transformers import AutoModelForCausalLM
    from mobilefinetuner_tpu.cli.family import load_family
    from mobilefinetuner_tpu.data.wikitext2 import (WT2Config,
                                                    WikiText2Dataset)

    b = load_family(model_dir, family)
    if family == "gemma":
        encode = lambda s: b.tok.encode(s, add_bos=False)
        eos_id, pad_id = b.tok.eos_id, b.tok.pad_id
    else:
        encode, eos_id, pad_id = b.tok.encode, b.tok.eos_id, None
    seq_len = min(seq_len, b.max_len)
    cfg = WT2Config(seq_len=seq_len, batch_size=batch_size, stride=None,
                    shuffle=False, drop_last=False)
    ds = WikiText2Dataset(data_root, "valid", cfg, encode, eos_id,
                          pad_id=pad_id)

    model = AutoModelForCausalLM.from_pretrained(
        model_dir, torch_dtype=torch.float32, attn_implementation="eager")
    if adapter_path:
        from peft import PeftModel
        from mobilefinetuner_tpu.lora.peft_io import (export_peft,
                                                      load_adapter)
        tree, spec = load_adapter(adapter_path)
        peft_dir = os.path.join(work_dir, "peft_anchor")
        export_peft(peft_dir, tree, spec, family)
        model = PeftModel.from_pretrained(model, peft_dir)
        model = model.merge_and_unload()  # the --lora_merge analog
    model.eval()

    total, count = 0.0, 0
    with torch.no_grad():
        for n, batch in enumerate(ds.epoch(0)):
            ids = torch.tensor(np.asarray(batch["input_ids"]),
                               dtype=torch.long)
            am = torch.tensor(np.asarray(batch["attention_mask"]),
                              dtype=torch.long)
            labels = torch.tensor(np.asarray(batch["labels"]),
                                  dtype=torch.long)
            logits = model(input_ids=ids, attention_mask=am).logits.float()
            lg, lb = logits[:, :-1], labels[:, 1:]
            valid = lb != -100
            lse = torch.logsumexp(lg, dim=-1)
            gold = lg.gather(-1, torch.where(valid, lb, 0)
                             .unsqueeze(-1)).squeeze(-1)
            total += float(torch.where(valid, lse - gold,
                                       torch.zeros(())).sum())
            count += int(valid.sum())
            if max_batches and n + 1 >= max_batches:
                break
    mean = total / max(count, 1)
    return {"ppl": float(np.exp(min(mean, 700.0))), "nll": mean,
            "tokens": count}


def main(argv=None):
    ap = argparse.ArgumentParser()
    ap.add_argument("--family", choices=["gpt2", "gemma"], default="gpt2")
    ap.add_argument("--gpt2_dir", "--model_dir", dest="model_dir",
                    default="",
                    help="real HF model dir; default: synthesize full size")
    ap.add_argument("--data_root", default="",
                    help="real WikiText-2 dir; default: synthesize")
    ap.add_argument("--work_dir", default="/tmp/e2e_ppl")
    ap.add_argument("--out", default="E2E_PPL.json")
    ap.add_argument("--train_steps", type=int, default=300)
    ap.add_argument("--epochs", type=int, default=0,
                    help="overrides train_steps when > 0 (real-data use)")
    ap.add_argument("--batch_size", type=int, default=0,
                    help="0 = family default (16 gpt2 / 8 gemma)")
    ap.add_argument("--seq_len", type=int, default=0,
                    help="0 = family default (128 gpt2 / 256 gemma, the "
                         "BASELINE configs)")
    ap.add_argument("--eval_seq_len", type=int, default=0)
    ap.add_argument("--eval_batches", type=int, default=30)
    ap.add_argument("--lr", type=float, default=3e-4)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--torch_anchor", type=int, default=1,
                    help="1 = also evaluate the same checkpoint+data with "
                         "HF transformers(+PEFT) and assert PPL equality")
    ap.add_argument("--anchor_batches", type=int, default=0,
                    help="eval batches for the cross-framework anchor "
                         "(both frameworks use the same subset); 0 = "
                         "family default (6 gpt2 / 3 gemma — the torch "
                         "side runs full-vocab f32 logits on host CPU)")
    ap.add_argument("--anchor_batch_size", type=int, default=2)
    ap.add_argument("--anchor_tol", type=float, default=3e-3,
                    help="max |mean NLL diff| between frameworks")
    args = ap.parse_args(argv)

    gemma = args.family == "gemma"
    args.batch_size = args.batch_size or (8 if gemma else 16)
    args.seq_len = args.seq_len or (256 if gemma else 128)
    args.eval_seq_len = args.eval_seq_len or args.seq_len

    os.makedirs(args.work_dir, exist_ok=True)
    synthetic = not args.model_dir
    model_dir = args.model_dir or os.path.join(
        args.work_dir, "gemma270m" if gemma else "gpt2s")
    data_root = args.data_root or os.path.join(args.work_dir, "corpus")
    if synthetic:
        name = "270M Gemma-3" if gemma else "124M GPT-2-small"
        print(f"synthesizing {name} checkpoint + corpus...",
              file=sys.stderr)
        if gemma:
            write_synthetic_gemma270m(model_dir)
        else:
            write_synthetic_gpt2(model_dir)
    if not args.data_root:
        write_synthetic_corpus(data_root)

    base = run_eval(model_dir, data_root, args.eval_seq_len,
                    8, args.eval_batches, dtype=args.dtype)
    print(f"baseline: ppl={base['ppl']:.2f}", file=sys.stderr)

    common_argv = ["--data_dir", data_root,
                   "--batch_size", str(args.batch_size),
                   "--seq_len", str(args.seq_len),
                   "--lr", str(args.lr), "--dtype", args.dtype,
                   "--log_interval", "50"] + \
        (["--epochs", str(args.epochs)] if args.epochs
         else ["--steps", str(args.train_steps)])
    t0 = time.time()
    if gemma:
        from mobilefinetuner_tpu.cli import train_lora_gemma
        out_dir = os.path.join(args.work_dir, "gemma_out")
        rc = train_lora_gemma.main(
            ["--model_dir", model_dir, "--output_dir", out_dir,
             "--targets", "full"] + common_argv)
        adapter = os.path.join(out_dir, "gemma_lora.safetensors")
    else:
        from mobilefinetuner_tpu.cli import gpt2_lora_finetune
        adapter = os.path.join(args.work_dir, "adapter.safetensors")
        rc = gpt2_lora_finetune.main(
            ["--pretrained_dir", model_dir, "--lora_out", adapter,
             "--lora_targets",
             "attn_qkv,attn_proj,mlp_fc_in,mlp_fc_out"] + common_argv)
    train_s = time.time() - t0
    assert rc == 0

    post = run_eval(model_dir, data_root, args.eval_seq_len,
                    8, args.eval_batches, lora_path=adapter,
                    dtype=args.dtype)
    print(f"post-LoRA: ppl={post['ppl']:.2f}", file=sys.stderr)

    # ---- cross-framework anchor: same checkpoint, same token stream,
    # ours (f32, merged adapter) vs HF transformers+PEFT (f32, merged)
    anchor = None
    if args.torch_anchor:
        nb = args.anchor_batches or (3 if gemma else 6)
        bs = args.anchor_batch_size
        anchor = {"eval_batches": nb, "batch_size": bs,
                  "tol_nll": args.anchor_tol, "pairs": {}}
        ok = True
        for tag, lp in (("baseline", ""), ("post_lora", adapter)):
            ours = run_eval(model_dir, data_root, args.eval_seq_len, bs,
                            nb, lora_path=lp, dtype="float32")
            ref = torch_eval_ppl(model_dir, data_root, args.eval_seq_len,
                                 bs, nb, args.family, adapter_path=lp,
                                 work_dir=args.work_dir)
            assert ours["tokens"] == ref["tokens"], \
                (tag, ours["tokens"], ref["tokens"])
            diff = abs(ours["nll"] - ref["nll"])
            anchor["pairs"][tag] = {
                "ours_ppl": round(ours["ppl"], 4),
                "torch_ppl": round(ref["ppl"], 4),
                "nll_diff": round(diff, 6), "tokens": ref["tokens"]}
            ok = ok and diff < args.anchor_tol
            print(f"anchor[{tag}]: ours={ours['ppl']:.3f} "
                  f"torch={ref['ppl']:.3f} nll_diff={diff:.2e}",
                  file=sys.stderr)
        anchor["pass"] = bool(ok)

    steps = args.train_steps if not args.epochs else None
    report = {
        "synthetic": synthetic,
        "model": "gemma3-270m" if gemma else "gpt2-small-124M",
        "baseline_ppl": round(base["ppl"], 3),
        "post_lora_ppl": round(post["ppl"], 3),
        "ppl_improvement": round(base["ppl"] - post["ppl"], 3),
        "train_steps": steps, "train_seconds": round(train_s, 1),
        "train_tokens_per_sec": (round(steps * args.batch_size
                                       * args.seq_len / train_s, 1)
                                 if steps else None),
        "eval_tokens": post["tokens"],
        "cross_framework_anchor": anchor,
        "reference_anchor": {"baseline_ppl": 29.5, "post_lora_ppl": 26.8,
                             "source": "/root/reference/README.md:355-357",
                             "note": "real-checkpoint numbers; the "
                                     "cross_framework_anchor proves both "
                                     "frameworks agree on ANY checkpoint, "
                                     "so those follow with real weights"},
    }
    with open(args.out, "w") as f:
        json.dump(report, f, indent=1)
    print(json.dumps(report))
    improved = post["ppl"] < base["ppl"]
    anchored = anchor is None or anchor["pass"]
    return 0 if (improved and anchored) else 1


if __name__ == "__main__":
    sys.exit(main())
