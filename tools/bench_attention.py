"""Flash-vs-XLA attention benchmark on the real chip (fwd + bwd).

Measures the Pallas kernel against the XLA oracle across the fine-tuning
shapes (GPT-2 small head layout and Gemma-3 270M GQA layout) at
S ∈ {512, 1024, 2048}, causal and sliding-window, and checks numerics
while at it. The reference's analog is memory_efficient_attention vs
standard attention timing (core/memory_efficient_attention.cpp); ours must
also win on the BACKWARD, which the reference does not implement.

Sync note: on the tunneled TPU platform, block_until_ready does not wait —
every timing reads a scalar back to host instead.

RESOLUTION LIMIT (round 4): even with the in-graph serial chain, per-op
times bottom out at ~0.7 ms on the tunneled platform — S <= 512 rows
measure the dispatch floor, not the op (everything from S=128 B=8 to
S=512 B=8 reads ~0.7-0.8 ms). The flash-vs-XLA crossover at small S is
therefore tuned from END-TO-END train steps instead
(ops/attention.resolve_impl docstring has those numbers: flash +20% e2e
at GPT-2s S=512 while this harness reads ~parity). Trust rows here from
S >= 1024, where op time clears the floor.

Prints one JSON line per config; exit 0 iff all numerics agree. Every
row times the backward BOTH ways — the merged one-pass dK/dV+dQ kernel
('auto') and the forced split pair — and reports `merged_vs_split`;
`--sweep_blocks` adds the r6 block-size sweep rows at the long-S shapes.
"""

import functools
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np


CHAIN = 32  # iterations fused into ONE jitted program: the tunneled TPU
            # has ~6 ms per-dispatch latency, so per-op time must be
            # measured as a serial in-graph chain, not a Python loop


def timeit(fn, *args, iters=5, warmup=2):
    for _ in range(warmup):
        r = fn(*args)
        float(jax.tree.leaves(r)[0].sum())  # host sync (axon gotcha)
    t0 = time.perf_counter()
    for _ in range(iters):
        r = fn(*args)
    float(jax.tree.leaves(r)[0].sum())
    return (time.perf_counter() - t0) / iters / CHAIN * 1e3  # ms per op


def run(name, B, Hq, Hkv, S, D, window, dtype=jnp.bfloat16, dropout=0.0,
        block_q=512, block_k=512):
    from mobilefinetuner_tpu.ops.attention import dot_product_attention
    from mobilefinetuner_tpu.ops.flash_attention import flash_attention

    ks = jax.random.split(jax.random.PRNGKey(0), 4)
    q = jax.random.normal(ks[0], (B, Hq, S, D), dtype)
    k = jax.random.normal(ks[1], (B, Hkv, S, D), dtype)
    v = jax.random.normal(ks[2], (B, Hkv, S, D), dtype)
    do = jax.random.normal(ks[3], (B, Hq, S, D), dtype)
    drng = jax.random.PRNGKey(9) if dropout > 0.0 else None

    def make(impl, bwd_impl="auto"):
        f = flash_attention if impl == "flash" else dot_product_attention

        def att(q, k, v):
            extra = {"bwd_impl": bwd_impl, "block_q": block_q,
                     "block_k": block_k} if impl == "flash" else {}
            return f(q, k, v, is_causal=True, sliding_window=window,
                     attn_dropout=dropout, attn_dropout_rng=drng, **extra)

        @jax.jit
        def fwd(q, k, v):
            # serial chain: each iteration's output feeds the next query,
            # so XLA cannot overlap or CSE the calls
            def body(c, _):
                return att(c, k, v).astype(c.dtype), None
            out, _ = jax.lax.scan(body, q, None, length=CHAIN)
            return out

        @jax.jit
        def fwdbwd(q, k, v, do):
            def body(c, _):
                out, vjp = jax.vjp(att, c, k, v)
                dq, dk, dv = vjp(do)
                # fold all grads back into the carry to serialize
                return (out + 1e-3 * dq + 1e-6 * (dk.sum() + dv.sum())
                        ).astype(c.dtype), None
            out, _ = jax.lax.scan(body, q, None, length=CHAIN)
            return out
        return fwd, fwdbwd

    f_fwd, f_bwd = make("flash")            # 'auto' backward (merged)
    _, f_bwd_split = make("flash", "split")  # forced split pair
    x_fwd, x_bwd = make("xla")

    def one_bwd(f):
        @jax.jit
        def g(q, k, v, do):
            out, vjp = jax.vjp(
                lambda q, k, v: f(q, k, v, is_causal=True,
                                  sliding_window=window), q, k, v)
            return out, vjp(do)
        return g

    if dropout > 0.0:
        # the two impls draw different (hash vs jax.random) masks, so
        # cross-impl numerics are meaningless here; exact same-mask parity
        # is covered by tests/test_flash_attention.py's hash oracle
        rel, ok = None, True
    else:
        # numerics vs the oracle (fwd + all three grads), single call
        of, gf = one_bwd(flash_attention)(q, k, v, do)
        ox, gx = one_bwd(dot_product_attention)(q, k, v, do)
        errs = [float(jnp.max(jnp.abs(a.astype(jnp.float32)
                                      - b.astype(jnp.float32))))
                for a, b in zip((of, *gf), (ox, *gx))]
        scale_ref = [float(jnp.max(jnp.abs(b.astype(jnp.float32))))
                     for b in (ox, *gx)]
        rel = max(e / max(s, 1e-6) for e, s in zip(errs, scale_ref))
        ok = rel < 0.05  # bf16 tolerance

    r = {"config": name, "B": B, "Hq": Hq, "Hkv": Hkv, "S": S, "D": D,
         "window": window, "dropout": dropout,
         "block_q": block_q, "block_k": block_k,
         "flash_fwd_ms": round(timeit(f_fwd, q, k, v), 3),
         "xla_fwd_ms": round(timeit(x_fwd, q, k, v), 3),
         "flash_fwdbwd_ms": round(timeit(f_bwd, q, k, v, do), 3),
         # the merged-vs-split backward comparison (r6): fwdbwd with the
         # one-pass dK/dV+dQ kernel vs the FlashAttention-2 split pair
         "flash_fwdbwd_split_ms": round(timeit(f_bwd_split, q, k, v, do),
                                        3),
         "xla_fwdbwd_ms": round(timeit(x_bwd, q, k, v, do), 3),
         "max_rel_err": None if rel is None else round(rel, 5),
         "numerics_ok": ok}
    r["fwd_speedup"] = round(r["xla_fwd_ms"] / r["flash_fwd_ms"], 2)
    r["fwdbwd_speedup"] = round(r["xla_fwdbwd_ms"] / r["flash_fwdbwd_ms"],
                                2)
    r["merged_vs_split"] = round(
        r["flash_fwdbwd_split_ms"] / r["flash_fwdbwd_ms"], 2)
    print(json.dumps(r))
    return ok


def main():
    import argparse
    ap = argparse.ArgumentParser()
    ap.add_argument("--sweep_blocks", action="store_true",
                    help="block-size sweep rows for the merged backward "
                         "at the long-S GPT-2/Gemma shapes (r6 retune)")
    args = ap.parse_args()
    ok = True
    for S in (512, 1024, 2048):
        ok &= run(f"gpt2s_causal_S{S}", 8, 12, 12, S, 64, None)
    for S in (1024, 2048):
        ok &= run(f"gemma270m_global_S{S}", 4, 4, 1, S, 256, None)
        ok &= run(f"gemma270m_sliding512_S{S}", 4, 4, 1, S, 256, 512)
    # train-mode attention dropout (HF GPT-2 default attn_pdrop=0.1):
    # in-kernel hash dropout vs the XLA path's materialized-mask dropout
    for S in (1024, 2048):
        ok &= run(f"gpt2s_causal_dropout_S{S}", 8, 12, 12, S, 64, None,
                  dropout=0.1)
    if args.sweep_blocks:
        # the merged kernel's q-loop depth per program is S/BQ while its
        # dq-slab residency scales with S alone, so the r4/r5 512x512
        # verdict must be re-checked per impl (each row reports both
        # backward impls at the chosen blocks via merged_vs_split)
        for bq, bk in ((512, 512), (256, 512), (512, 256), (256, 256)):
            for S in (1024, 2048):
                ok &= run(f"sweep_gpt2s_S{S}_bq{bq}_bk{bk}", 8, 12, 12,
                          S, 64, None, block_q=bq, block_k=bk)
            ok &= run(f"sweep_gemma_S2048_bq{bq}_bk{bk}", 4, 4, 1,
                      2048, 256, None, block_q=bq, block_k=bk)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
