"""Native-vs-Python BPE throughput on cache-defeating text.

The per-word cache makes real-corpus encoding cheap either way (WikiText-2
has ~70k unique words over 2.4M tokens); the native engine's win is the
merge loop on UNCACHED words, so this benchmark generates unique
pseudo-words. Prints one JSON line.
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np


def main():
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tests"))
    from fixtures import train_tiny_gpt2_tokenizer
    from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
    import tempfile
    d = tempfile.mkdtemp()
    train_tiny_gpt2_tokenizer(d)

    rng = np.random.default_rng(0)
    words = [" w" + "".join(chr(97 + c) for c in rng.integers(0, 26, 14))
             for _ in range(20000)]
    text = "".join(words)

    results = {}
    for name, use_native in (("native", True), ("python", False)):
        tok = GPT2BPETokenizer.from_pretrained(d, use_native=use_native)
        if use_native and tok._native is None:
            results["native"] = None
            continue
        t0 = time.perf_counter()
        ids = tok.encode(text)
        dt = time.perf_counter() - t0
        results[name] = {"seconds": round(dt, 3),
                         "tokens_per_sec": round(len(ids) / dt, 1)}
    if results.get("native") and results.get("python"):
        results["speedup"] = round(
            results["native"]["tokens_per_sec"]
            / results["python"]["tokens_per_sec"], 2)
    print(json.dumps(results))


if __name__ == "__main__":
    main()
