"""Serving-SLO ground truth: seeded Poisson open-loop load over the
real serve engine (serve/engine.py).

Open-loop means arrivals do NOT wait for the service: request k arrives
at its scheduled time whether or not the engine is keeping up, so queue
buildup — the thing a closed-loop "send, wait, send" bench structurally
cannot show — lands in the TTFT tail exactly as it would in production.
The arrival schedule is seeded (exponential inter-arrival gaps), so a
row is reproducible end to end: same seed, same prompts, same adapter
routing, same admission order.

Every request's lifecycle rides the telemetry `request` events
(--telemetry_out), so tools/telemetry_report.py renders the same
TTFT/TPOT percentiles this tool prints — one measurement, two readers.

Usage:
  python tools/serve_bench.py                        # GPT-2 small, k=1
  python tools/serve_bench.py --gemma --adapters 8   # Gemma-270M, k=8
  python tools/serve_bench.py --out BENCH_SERVE_r11.json --rate 4 8
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])
sys.path.insert(0, __file__.rsplit("/", 1)[0])

import jax
import jax.numpy as jnp
import numpy as np

# one rank convention, two readers: the percentiles this tool prints
# must be the ones telemetry_report computes over the same stream
from telemetry_report import percentile


def rand_adapters(family, config, k: int, seed: int = 0):
    """k seeded random adapters (B pushed off zero so each tenant's
    outputs actually differ)."""
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                               init_lora_gpt2)
    init = init_lora_gpt2 if family == "gpt2" else init_lora_gemma3
    out = []
    for i in range(k):
        lora = init(config, LoRASpec(rank=4, alpha=8.0),
                    jax.random.PRNGKey(seed + i))
        leaves, td = jax.tree.flatten(lora)
        keys = jax.random.split(jax.random.PRNGKey(seed + 100 + i),
                                len(leaves))
        out.append(jax.tree.unflatten(td, [
            l if l.ndim == 0 else 0.02 * jax.random.normal(kk, l.shape)
            for l, kk in zip(leaves, keys)]))
    return out


def build_engine(model: str, num_slots: int, block_T: int,
                 num_blocks: int, max_prompt: int, max_new: int,
                 adapters: int, dtype: str, telemetry_out: str = "",
                 seed: int = 0):
    """model: gpt2s | gemma270m | tiny-gpt2 | tiny-gemma. The tiny
    modes are the CPU contract/smoke path (tests/test_serve.py)."""
    from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
    from mobilefinetuner_tpu.core.telemetry import Telemetry
    from mobilefinetuner_tpu.models import gemma3, gpt2
    from mobilefinetuner_tpu.serve import (AdapterBank, ServeConfig,
                                           ServeEngine)
    if model == "gpt2s":
        config, family = GPT2Config.gpt2_small(), "gpt2"
    elif model == "gemma270m":
        config, family = Gemma3TextConfig.gemma3_270m(), "gemma"
    elif model == "tiny-gpt2":
        config, family = GPT2Config.tiny(), "gpt2"
    elif model == "tiny-gemma":
        config, family = Gemma3TextConfig.tiny(), "gemma"
    else:
        raise SystemExit(f"unknown model {model!r}")
    mod = gpt2 if family == "gpt2" else gemma3
    params = mod.init_params(config, jax.random.PRNGKey(seed))
    bank = None
    names = []
    if adapters:
        trees = rand_adapters(family, config, adapters, seed)
        bank = AdapterBank(trees[0], capacity=adapters)
        names = [f"tenant{i}" for i in range(adapters)]
    cfg = ServeConfig(num_slots=num_slots, block_T=block_T,
                      num_blocks=num_blocks, max_prompt=max_prompt,
                      max_new_tokens=max_new, dtype=dtype)
    eng = ServeEngine(family, config, params, cfg, bank=bank,
                      telemetry=Telemetry(telemetry_out))
    if adapters:
        for n, t in zip(names, trees):
            eng.load_adapter(n, t)
    return eng, names


def run_load(engine, names, rate: float, n_requests: int, seed: int,
             prompt_lo: int, prompt_hi: int, max_new: int):
    """Drive one open-loop Poisson run; returns (finished requests,
    elapsed seconds). Deterministic given the seed: arrivals, prompt
    contents/lengths, and tenant routing all come from one rng."""
    rng = np.random.default_rng(seed)
    vocab = engine.config.vocab_size
    gaps = rng.exponential(1.0 / rate, n_requests)
    prompts = [list(rng.integers(1, vocab, int(n))) for n in
               rng.integers(prompt_lo, prompt_hi + 1, n_requests)]
    route = ([names[int(i)] for i in
              rng.integers(0, len(names), n_requests)]
             if names else [None] * n_requests)
    t0 = time.perf_counter()
    arrivals = t0 + np.cumsum(gaps)
    done, i = [], 0
    while i < n_requests or not engine.idle:
        now = time.perf_counter()
        while i < n_requests and arrivals[i] <= now:
            engine.submit(prompts[i], max_new_tokens=max_new,
                          adapter=route[i])
            i += 1
        if engine.idle:
            time.sleep(min(max(arrivals[i] - now, 0.0), 0.05))
            continue
        done.extend(engine.step())
    return sorted(done, key=lambda r: r.id), time.perf_counter() - t0


def row_from(config_name: str, engine, done, elapsed: float,
             rate: float, adapters: int) -> dict:
    ttfts = sorted(r.ttft_ms for r in done if r.ttft_ms is not None)
    tpots = sorted(r.tpot_ms for r in done if r.tpot_ms is not None)
    gen_tokens = sum(len(r.tokens) for r in done)
    pct = lambda v: {"p50": percentile(v, 50), "p95": percentile(v, 95),
                     "p99": percentile(v, 99)}
    return {
        "config": config_name,
        "offered_rps": rate,
        "requests": len(done),
        "elapsed_s": round(elapsed, 3),
        "req_s": round(len(done) / elapsed, 3) if elapsed > 0 else None,
        "gen_tok_s": (round(gen_tokens / elapsed, 1)
                      if elapsed > 0 else None),
        "ttft_ms": pct(ttfts),
        "tpot_ms": pct(tpots),
        "adapters_resident": adapters,
        "num_slots": engine.cfg.num_slots,
        "block_T": engine.cfg.block_T,
        "num_blocks": engine.cfg.num_blocks,
        "decode_steps": engine.decode_steps,
        "traces": dict(engine.trace_counts),
    }


def run_rows(model: str, rates, n_requests: int, adapters: int,
             num_slots: int = 8, block_T: int = 16, num_blocks: int = 256,
             max_prompt: int = 64, max_new: int = 32, dtype: str =
             "bfloat16", seed: int = 0, prompt_lo: int = 8,
             prompt_hi: int = 0, telemetry_out: str = "") -> list:
    """One engine, one warmup request, then one row per offered rate."""
    prompt_hi = prompt_hi or max_prompt
    eng, names = build_engine(model, num_slots, block_T, num_blocks,
                              max_prompt, max_new, adapters, dtype,
                              telemetry_out=telemetry_out, seed=seed)
    # warmup: compile prefill + step outside the measured window
    eng.submit([1] * prompt_lo, max_new_tokens=min(2, max_new),
               adapter=names[0] if names else None)
    eng.drain()
    warm_traces = eng.total_traces()
    rows = []
    for rate in rates:
        done, elapsed = run_load(eng, names, rate, n_requests, seed,
                                 prompt_lo, prompt_hi, max_new)
        name = f"{model}_serve_k{max(adapters, 1)}_r{rate:g}"
        row = row_from(name, eng, done, elapsed, rate, adapters)
        row["new_traces_after_warmup"] = eng.total_traces() - warm_traces
        rows.append(row)
        # percentiles may be None (e.g. max_new=1 leaves no post-first-
        # token cadence, so every tpot is None)
        fmt = lambda v, spec="0f": ("n/a" if v is None
                                    else f"{v:.{spec}}")
        print(f"{name}: {row['req_s']} req/s ({row['gen_tok_s']} tok/s), "
              f"TTFT p50/p99 = {fmt(row['ttft_ms']['p50'])}/"
              f"{fmt(row['ttft_ms']['p99'])} ms, TPOT p50 = "
              f"{fmt(row['tpot_ms']['p50'], '1f')} ms, "
              f"{row['new_traces_after_warmup']} retraces")
    eng.close()
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2s",
                    choices=["gpt2s", "gemma270m", "tiny-gpt2",
                             "tiny-gemma"])
    ap.add_argument("--gemma", action="store_true",
                    help="shorthand for --model gemma270m")
    ap.add_argument("--rate", type=float, nargs="*", default=[4.0],
                    help="offered load(s), requests/second")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--adapters", type=int, default=0,
                    help="resident LoRA tenants (0 = base only)")
    ap.add_argument("--num_slots", type=int, default=8)
    ap.add_argument("--block_T", type=int, default=16)
    ap.add_argument("--num_blocks", type=int, default=256)
    ap.add_argument("--max_prompt", type=int, default=64)
    ap.add_argument("--max_new", type=int, default=32)
    ap.add_argument("--prompt_lo", type=int, default=8)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--telemetry_out", default="")
    ap.add_argument("--out", default="",
                    help="append rows to this JSON artifact")
    args = ap.parse_args(argv)
    model = "gemma270m" if args.gemma else args.model
    rows = run_rows(model, args.rate, args.requests, args.adapters,
                    num_slots=args.num_slots, block_T=args.block_T,
                    num_blocks=args.num_blocks,
                    max_prompt=args.max_prompt, max_new=args.max_new,
                    dtype=args.dtype, seed=args.seed,
                    prompt_lo=args.prompt_lo,
                    telemetry_out=args.telemetry_out)
    if args.out:
        art = {"device": jax.devices()[0].device_kind,
               "jax": jax.__version__, "rows": []}
        if os.path.exists(args.out):
            with open(args.out) as f:
                art = json.load(f)
        art["rows"].extend(rows)
        tmp = args.out + ".tmp"
        with open(tmp, "w") as f:
            json.dump(art, f, indent=1)
        os.replace(tmp, args.out)
    return 0


if __name__ == "__main__":
    sys.exit(main())
