"""Serving-SLO ground truth: seeded Poisson open-loop load over the
real serve engine (serve/engine.py) — and, since round 14, the serve
FAULT-INJECTION harness (DESIGN.md §19).

Open-loop means arrivals do NOT wait for the service: request k arrives
at its scheduled time whether or not the engine is keeping up, so queue
buildup — the thing a closed-loop "send, wait, send" bench structurally
cannot show — lands in the TTFT tail exactly as it would in production.
The arrival schedule is seeded (exponential inter-arrival gaps), so a
row is reproducible end to end: same seed, same prompts, same adapter
routing, same admission order.

Every request's lifecycle rides the telemetry `request` events
(--telemetry_out), so tools/telemetry_report.py renders the same
TTFT/TPOT percentiles this tool prints — one measurement, two readers.

`--inject` drives the robustness layers end to end under load, the way
multihost_smoke's --inject proves the fleet controller:

  step_error:<n>        raise out of decode step n's dispatch — the
                        engine must fail only the in-flight requests
                        and keep serving the queue (crash containment)
  hang:<n>[:<s>]        wedge step n for <s> seconds — the attached
                        HangWatchdog (--watchdog) must fire a `hang`
                        event while the run completes
  slow_step:<n>:<ms>    one straggler step (latency-tail realism)
  adapter_load_fail     a tenant upload with a mismatched template —
                        the bank must refuse it without disturbing the
                        resident tenants

`--max_queue/--deadline_ms/--shed_policy` engage bounded admission and
per-request deadlines under the same load; SIGTERM during a run drains
gracefully (finish in-flight, reject the queue with reason=shutdown,
run_end{reason=preempted}); a second SIGTERM cancels in-flight.

Usage:
  python tools/serve_bench.py                        # GPT-2 small, k=1
  python tools/serve_bench.py --gemma --adapters 8   # Gemma-270M, k=8
  python tools/serve_bench.py --out BENCH_SERVE_r11.json --rate 4 8
  python tools/serve_bench.py --inject step_error:20 --max_queue 16 \
      --deadline_ms 2000 --stats_every 25            # fault harness
  python tools/serve_bench.py --prefix_cache 1 --prefix_pool 4 \
      --max_prompt_chunked 128 --sampling 1          # traffic scale
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])
sys.path.insert(0, __file__.rsplit("/", 1)[0])

import jax
import jax.numpy as jnp
import numpy as np

# one rank convention, two readers: the percentiles this tool prints
# must be the ones telemetry_report computes over the same stream
from telemetry_report import percentile


class InjectedStepError(RuntimeError):
    """The fault harness's synthetic step-dispatch failure — a distinct
    type so telemetry attributes the contained error to the injection
    (request{phase=error, reason=InjectedStepError}) and a real crash
    can never hide behind an injected one."""


def install_inject(engine, spec: str, hang_s: float = 2.0):
    """Arm one fault on the engine's step_hook seam (fires ONCE: the
    step counter does not advance on a contained failure, so an
    unlatched hook would re-fire forever). Returns the fired-latch
    list (empty until the fault triggers) so the caller can FAIL the
    run when an armed fault never fired — a spec naming a step the run
    never reaches must not silently pass as "containment proven".
    Spec grammar: step_error:<n> | hang:<n>[:<s>] | slow_step:<n>:<ms>
    | adapter_load_fail (handled by inject_adapter_load_fail — it
    needs the bank, not the step loop; returns None here)."""
    if not spec or spec == "adapter_load_fail":
        return None
    parts = spec.split(":")
    kind, fired = parts[0], []

    def once(step, n):
        if step == n and not fired:
            fired.append(step)
            return True
        return False

    if kind == "step_error":
        n = int(parts[1])

        def hook(step):
            if once(step, n):
                raise InjectedStepError(
                    f"injected step_error at decode step {n}")
    elif kind == "hang":
        n = int(parts[1])
        s = float(parts[2]) if len(parts) > 2 else hang_s

        def hook(step):
            if once(step, n):
                time.sleep(s)   # wedge: the watchdog's deadline expires
    elif kind == "slow_step":
        n, ms = int(parts[1]), float(parts[2])

        def hook(step):
            if once(step, n):
                time.sleep(ms / 1000.0)
    else:
        raise SystemExit(f"unknown --inject spec {spec!r}")
    engine.step_hook = hook
    return fired


def inject_adapter_load_fail(engine) -> str:
    """Offer the bank a structurally-wrong adapter (rank bumped) the
    way a corrupt tenant upload would: the load must be REFUSED with a
    named error, resident tenants undisturbed. Returns the error text
    (empty = the bank accepted it, which is the failure)."""
    import jax
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                               init_lora_gpt2)
    init = (init_lora_gpt2 if engine.family == "gpt2"
            else init_lora_gemma3)
    bad = init(engine.config, LoRASpec(rank=16, alpha=32.0),
               jax.random.PRNGKey(99))
    try:
        engine.load_adapter("corrupt_tenant", bad)
    except ValueError as e:
        return str(e)
    return ""


def rand_adapters(family, config, k: int, seed: int = 0):
    """k seeded random adapters (B pushed off zero so each tenant's
    outputs actually differ)."""
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                               init_lora_gpt2)
    init = init_lora_gpt2 if family == "gpt2" else init_lora_gemma3
    out = []
    for i in range(k):
        lora = init(config, LoRASpec(rank=4, alpha=8.0),
                    jax.random.PRNGKey(seed + i))
        leaves, td = jax.tree.flatten(lora)
        keys = jax.random.split(jax.random.PRNGKey(seed + 100 + i),
                                len(leaves))
        out.append(jax.tree.unflatten(td, [
            l if l.ndim == 0 else 0.02 * jax.random.normal(kk, l.shape)
            for l, kk in zip(leaves, keys)]))
    return out


def build_engine(model: str, num_slots: int, block_T: int,
                 num_blocks: int, max_prompt: int, max_new: int,
                 adapters: int, dtype: str, telemetry_out: str = "",
                 seed: int = 0, max_queue: int = 0,
                 shed_policy: str = "reject",
                 on_step_error: str = "fail_active",
                 stats_every: int = 0, watchdog=None,
                 hbm_cap_mb: int = 0, hbm_headroom: float = 0.1,
                 trace_spans: bool = False, metrics_port: int = 0,
                 metrics_addr: str = "127.0.0.1",
                 mesh_dp: int = 1, mesh_tp: int = 1,
                 prefix_cache: bool = False, max_prompt_chunked: int = 0,
                 sampling: bool = False, host: int = 0):
    """model: gpt2s | gemma270m | tiny-gpt2 | tiny-gemma. The tiny
    modes are the CPU contract/smoke path (tests/test_serve.py).

    `host` stamps the telemetry envelope (round 22): a router replica
    writes shard_path(base, k) with host=k so the fleet merge key
    (host, seq) stays collision-free across replicas.

    metrics_port > 0 serves the live OpenMetrics endpoint
    (core/metrics_http.py) over the engine's telemetry emit path, with
    /healthz riding engine.health(); the server lands on
    `engine.metrics_server` (run_rows closes it). Everything is
    host-side bookkeeping — a scrape can never cost a retrace
    (tests/test_observability.py pins it under live load)."""
    from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
    from mobilefinetuner_tpu.core.telemetry import Telemetry
    from mobilefinetuner_tpu.models import gemma3, gpt2
    from mobilefinetuner_tpu.serve import (AdapterBank, ServeConfig,
                                           ServeEngine)
    if model == "gpt2s":
        config, family = GPT2Config.gpt2_small(), "gpt2"
    elif model == "gemma270m":
        config, family = Gemma3TextConfig.gemma3_270m(), "gemma"
    elif model == "tiny-gpt2":
        config, family = GPT2Config.tiny(), "gpt2"
    elif model == "tiny-gemma":
        config, family = Gemma3TextConfig.tiny(), "gemma"
    else:
        raise SystemExit(f"unknown model {model!r}")
    mod = gpt2 if family == "gpt2" else gemma3
    params = mod.init_params(config, jax.random.PRNGKey(seed))
    bank = None
    names = []
    if adapters:
        trees = rand_adapters(family, config, adapters, seed)
        bank = AdapterBank(trees[0], capacity=adapters)
        names = [f"tenant{i}" for i in range(adapters)]
    cfg = ServeConfig(num_slots=num_slots, block_T=block_T,
                      num_blocks=num_blocks, max_prompt=max_prompt,
                      max_new_tokens=max_new, dtype=dtype,
                      max_queue=max_queue, shed_policy=shed_policy,
                      on_step_error=on_step_error,
                      stats_every=stats_every,
                      hbm_cap_mb=hbm_cap_mb, hbm_headroom=hbm_headroom,
                      trace_spans=trace_spans,
                      mesh_dp=mesh_dp, mesh_tp=mesh_tp,
                      prefix_cache=prefix_cache,
                      max_prompt_chunked=max_prompt_chunked,
                      sampling=sampling)
    tel = Telemetry(telemetry_out, host=host)
    registry = None
    if metrics_port > 0:
        # observer attached BEFORE the engine builds, so run_start and
        # the build-time mem_check land in the registry too
        from mobilefinetuner_tpu.core.metrics_http import MetricsRegistry
        registry = MetricsRegistry()
        tel.add_observer(registry.observe)
    eng = ServeEngine(family, config, params, cfg, bank=bank,
                      telemetry=tel, watchdog=watchdog)
    eng.metrics_server = None
    if registry is not None:
        from mobilefinetuner_tpu.core.metrics_http import MetricsServer
        eng.metrics_server = MetricsServer(
            registry, port=metrics_port, addr=metrics_addr,
            health_fn=eng.health)
        print(f"metrics endpoint: http://{eng.metrics_server.addr}:"
              f"{eng.metrics_server.port}/metrics (+ /healthz)")
    if adapters:
        for n, t in zip(names, trees):
            eng.load_adapter(n, t)
    return eng, names


def gen_schedule(vocab: int, block_T: int, rate: float,
                 n_requests: int, seed: int, prompt_lo: int,
                 prompt_hi: int, names, prefix_pool: int = 0,
                 prefix_frac: float = 0.7, sampling: bool = False):
    """The seeded open-loop workload, decoupled from the engine so the
    in-process path (run_load) and the HTTP router path
    (run_router_rows, round 22) drive the IDENTICAL schedule: same
    seed => same arrival gaps, prompt contents, tenant routing and
    sampling knobs — a router row and its single-engine baseline
    differ only in serving topology. Returns (gaps, prompts, route,
    samp) with pure-python ints (the prompts must survive json).

    prefix_pool > 0 makes the workload SHARED-PREFIX shaped (round 21):
    a seeded pool of that many full-block prefixes, and each request
    opens with a pool member with probability prefix_frac (its suffix
    stays per-request random) — the multi-turn/system-prompt traffic a
    prefix cache earns its keep on. The prefixes span whole pages (the
    cache's unit of reuse): as many whole blocks as fit under the
    shortest prompt, leaving at least one unique-suffix token."""
    rng = np.random.default_rng(seed)
    gaps = rng.exponential(1.0 / rate, n_requests)
    lens = rng.integers(prompt_lo, prompt_hi + 1, n_requests)
    if prefix_pool > 0:
        bT = block_T
        plen = max(bT, ((prompt_lo - 1) // bT) * bT)
        pool = [[int(v) for v in rng.integers(1, vocab, plen)]
                for _ in range(prefix_pool)]
        hit = rng.random(n_requests) < prefix_frac
        pick = rng.integers(0, prefix_pool, n_requests)
        prompts = [
            (pool[int(pick[i])] if hit[i] else
             [int(v) for v in rng.integers(1, vocab, plen)])
            + [int(v) for v in
               rng.integers(1, vocab, max(int(lens[i]) - plen, 1))]
            for i in range(n_requests)]
    else:
        prompts = [[int(v) for v in rng.integers(1, vocab, int(n))]
                   for n in lens]
    seeds = rng.integers(0, 2**31, n_requests)
    samp = (lambda i: {"temperature": 0.8, "top_k": 40, "top_p": 0.95,
                       "seed": int(seeds[i])}) if sampling \
        else (lambda i: {})
    route = ([names[int(i)] for i in
              rng.integers(0, len(names), n_requests)]
             if names else [None] * n_requests)
    return gaps, prompts, route, samp


def run_load(engine, names, rate: float, n_requests: int, seed: int,
             prompt_lo: int, prompt_hi: int, max_new: int,
             deadline_ms=None, prefix_pool: int = 0,
             prefix_frac: float = 0.7, sampling: bool = False):
    """Drive one open-loop Poisson run; returns (terminal requests,
    elapsed seconds). Deterministic given the seed: arrivals, prompt
    contents/lengths, and tenant routing all come from one rng.
    Drain-aware: when a SIGTERM flips the engine into draining, the
    unsubmitted remainder of the schedule is dropped (the clients went
    away with the pod) and the loop runs the in-flight requests out; a
    second signal (KeyboardInterrupt out of step()) cancels in-flight.
    Rejected-at-submit requests (bounded queue, shutdown) are included
    in the returned list — filter on `.state` for completions.

    The workload comes from gen_schedule (prefix_pool shapes it into
    shared-prefix traffic; sampling=True submits each request with a
    seeded per-request PRNG and a fixed softmax temperature, so a
    sampled row is as reproducible as a greedy one)."""
    gaps, prompts, route, samp = gen_schedule(
        engine.config.vocab_size, engine.cfg.block_T, rate,
        n_requests, seed, prompt_lo, prompt_hi, names,
        prefix_pool=prefix_pool, prefix_frac=prefix_frac,
        sampling=sampling)
    t0 = time.perf_counter()
    arrivals = t0 + np.cumsum(gaps)
    done, submitted, i = [], [], 0
    try:
        while i < n_requests or not engine.idle:
            now = time.perf_counter()
            if engine.draining:
                i = n_requests
            while i < n_requests and arrivals[i] <= now:
                submitted.append(
                    engine.submit(prompts[i], max_new_tokens=max_new,
                                  adapter=route[i],
                                  deadline_ms=deadline_ms, **samp(i)))
                i += 1
            if engine.idle:
                if i < n_requests:
                    time.sleep(min(max(arrivals[i] - now, 0.0), 0.05))
                continue
            done.extend(engine.step())
    except KeyboardInterrupt:
        # second signal mid-drain: the operator wants out NOW — cancel
        # what is still in flight (partial output stays on the request)
        for req in list(engine.active):
            engine.cancel(req)
        engine.begin_shutdown()
    # census over SUBMITTED ∪ step-returned, not just step-returned:
    # submit-time terminals (queue_full/shutdown rejects, and shed
    # victims — terminated inside a LATER request's submit) and the
    # KeyboardInterrupt cancels above never come back from step()
    by_id = {r.id: r for r in done}
    by_id.update({r.id: r for r in submitted if r.done})
    return (sorted(by_id.values(), key=lambda r: r.id),
            time.perf_counter() - t0)


def row_from(config_name: str, engine, done, elapsed: float,
             rate: float, adapters: int) -> dict:
    fin = [r for r in done if r.state == "finished"]
    ttfts = sorted(r.ttft_ms for r in fin if r.ttft_ms is not None)
    tpots = sorted(r.tpot_ms for r in fin if r.tpot_ms is not None)
    gen_tokens = sum(len(r.tokens) for r in done)
    pct = lambda v: {"p50": percentile(v, 50), "p95": percentile(v, 95),
                     "p99": percentile(v, 99)}
    chips = engine.cfg.mesh_dp * engine.cfg.mesh_tp
    gen_tok_s = round(gen_tokens / elapsed, 1) if elapsed > 0 else None
    return {
        "config": config_name,
        "offered_rps": rate,
        "requests": len(fin),
        "elapsed_s": round(elapsed, 3),
        "req_s": round(len(fin) / elapsed, 3) if elapsed > 0 else None,
        "gen_tok_s": gen_tok_s,
        # mesh shape + per-chip throughput: the "is tp paying for
        # itself" number bench_compare tracks across mesh rows
        "mesh": [engine.cfg.mesh_dp, engine.cfg.mesh_tp],
        "tok_s_per_chip": (round(gen_tok_s / chips, 1)
                           if gen_tok_s is not None else None),
        "ttft_ms": pct(ttfts),
        "tpot_ms": pct(tpots),
        # round 14: where the non-finishers went (the SLO denominator a
        # load-shed/deadline policy is judged by) + the loop vitals
        "terminal": {s: sum(1 for r in done if r.state == s)
                     for s in ("finished", "cancelled", "rejected",
                               "timeout", "error")},
        "health": engine.health(),
        "adapters_resident": adapters,
        "num_slots": engine.cfg.num_slots,
        "block_T": engine.cfg.block_T,
        "num_blocks": engine.cfg.num_blocks,
        "decode_steps": engine.decode_steps,
        "traces": dict(engine.trace_counts),
        # round 21: the prefix-reuse and sampling row shape. hit_rate /
        # cow are None-safe: a cache-off row carries nulls, so the
        # contract test can pin the schema either way
        "sampling": bool(engine.cfg.sampling),
        "prefix_cache": bool(engine.cfg.prefix_cache),
        "prefix_hit_rate": (engine.prefix.hit_rate
                            if engine.prefix is not None else None),
        "cow_copies": (engine.cow_copies
                       if engine.prefix is not None else None),
    }


def run_router_rows(model: str, rates, n_requests: int, adapters: int,
                    replicas: int, telemetry_out: str,
                    num_slots: int = 8, block_T: int = 16,
                    num_blocks: int = 256, max_prompt: int = 64,
                    max_new: int = 32, dtype: str = "bfloat16",
                    seed: int = 0, prompt_lo: int = 8,
                    prompt_hi: int = 0, max_queue: int = 0,
                    shed_policy: str = "reject", stats_every: int = 10,
                    prefix_cache: bool = False,
                    max_prompt_chunked: int = 0, sampling: bool = False,
                    prefix_pool: int = 0, prefix_frac: float = 0.7,
                    deadline_ms=None, scrape_s: float = 0.1,
                    collect_s: float = 0.02,
                    startup_timeout_s: float = 300.0,
                    settle_timeout_s: float = 600.0,
                    baseline=None) -> list:
    """Round 22: the same seeded open-loop Poisson load, driven over
    HTTP through tools/serve_router.py with `replicas` engine
    processes behind it. One router subprocess per call (one compile
    per replica, amortised across the rates); per rate, one FLEET row
    (goodput, TTFT/TPOT/queue-wait percentiles over ALL replicas,
    terminal census, routing-decision histogram from the router's own
    `route` events, per-replica prefix-cache hit rate) plus one row
    per replica — the load-imbalance and per-tenant-locality story a
    fleet-level mean hides. `baseline` maps rate -> single-engine TTFT
    p99 (run_rows over the identical gen_schedule workload); when
    given, the fleet row carries the p99 ratio bench_compare tracks.

    Exact accounting is the contract here, same as the kill-replica
    e2e: every rid the router acked MUST settle through /collect
    before the rate's row is built — a missing rid fails the bench."""
    import signal
    import subprocess
    import serve_router as sr              # sibling tool (no jax)
    from telemetry_report import load_events
    from mobilefinetuner_tpu.core.config import (GPT2Config,
                                                 Gemma3TextConfig)
    prompt_hi = prompt_hi or max_prompt
    vocab = {"gpt2s": GPT2Config.gpt2_small,
             "gemma270m": Gemma3TextConfig.gemma3_270m,
             "tiny-gpt2": GPT2Config.tiny,
             "tiny-gemma": Gemma3TextConfig.tiny}[model]().vocab_size
    names = [f"tenant{i}" for i in range(adapters)]
    spec = {"model": model, "num_slots": num_slots, "block_T": block_T,
            "num_blocks": num_blocks, "max_prompt": max_prompt,
            "max_new": max_new, "adapters": adapters, "dtype": dtype,
            "seed": seed, "max_queue": max_queue,
            "shed_policy": shed_policy, "stats_every": stats_every,
            "trace_spans": True, "prefix_cache": prefix_cache,
            "max_prompt_chunked": max_prompt_chunked,
            "sampling": sampling}
    base = telemetry_out
    proc = subprocess.Popen(
        [sys.executable, sr.__file__, "--telemetry", base,
         "--replicas", str(replicas),
         "--engine_json", json.dumps(spec),
         "--scrape_s", str(scrape_s), "--collect_s", str(collect_s)])
    url = None

    def collect(results):
        try:
            _, obj = sr._http_json("POST", url + "/collect", {},
                                   timeout=10.0)
        except OSError:
            return
        for r in obj.get("done", ()):
            if isinstance(r.get("rid"), int):
                results[r["rid"]] = r

    pct = lambda v: {"p50": percentile(v, 50), "p95": percentile(v, 95),
                     "p99": percentile(v, 99)}
    census = lambda rs: {s: sum(1 for r in rs if r["state"] == s)
                         for s in ("finished", "cancelled", "rejected",
                                   "timeout", "error")}
    rows = []
    try:
        deadline = time.time() + startup_timeout_s
        while True:
            if proc.poll() is not None:
                raise SystemExit(f"--router: router exited "
                                 f"rc={proc.returncode} during startup")
            if time.time() > deadline:
                raise SystemExit("--router: router never became ready")
            pf = sr.read_port_file(base, 0)
            if pf:
                try:
                    code, _ = sr._http_json(
                        "GET", f"http://127.0.0.1:{pf['port']}/healthz",
                        timeout=2.0)
                except OSError:
                    code = 0
                if code == 200:
                    url = f"http://127.0.0.1:{pf['port']}"
                    break
            time.sleep(0.2)
        # /healthz goes 200 at the FIRST ready replica; wait for the
        # whole fleet so the warmup below reaches every engine
        while time.time() < deadline:
            try:
                _, fl = sr._http_json("GET", url + "/fleet",
                                      timeout=2.0)
            except OSError:
                fl = {}
            if sum(1 for r in fl.get("replicas", {}).values()
                   if r.get("status") == "ok") >= replicas:
                break
            time.sleep(0.2)
        # warmup OUTSIDE the measured window: enough requests that the
        # inflight-aware placement touches every replica, so each
        # engine compiles prefill + step before a measured arrival
        warm, results = [], {}
        for _ in range(2 * replicas):
            code, obj = sr._http_json(
                "POST", url + "/submit",
                {"prompt": [1] * prompt_lo,
                 "max_new_tokens": min(2, max_new),
                 **({"adapter": names[0]} if names else {})},
                timeout=30.0)
            if isinstance(obj.get("rid"), int):
                warm.append(obj["rid"])
        deadline = time.time() + startup_timeout_s
        while not set(warm) <= set(results):
            if time.time() > deadline:
                raise SystemExit("--router: warmup never settled")
            collect(results)
            time.sleep(0.05)
        for rate in rates:
            gaps, prompts, route, samp = gen_schedule(
                vocab, block_T, rate, n_requests, seed, prompt_lo,
                prompt_hi, names, prefix_pool=prefix_pool,
                prefix_frac=prefix_frac, sampling=sampling)
            results, rids, i = {}, [], 0
            t0 = time.perf_counter()
            arrivals = t0 + np.cumsum(gaps)
            while i < n_requests:
                now = time.perf_counter()
                while i < n_requests and arrivals[i] <= now:
                    payload = {"prompt": prompts[i],
                               "max_new_tokens": max_new, **samp(i)}
                    if route[i]:
                        payload["adapter"] = route[i]
                    if deadline_ms:
                        payload["deadline_ms"] = deadline_ms
                    try:
                        _, obj = sr._http_json(
                            "POST", url + "/submit", payload,
                            timeout=30.0)
                    except OSError:
                        obj = {}
                    # a 503 reject still carries the rid (it settles
                    # through /collect as a rejected row — the census
                    # counts it, exactly like a direct-path reject)
                    if isinstance(obj.get("rid"), int):
                        rids.append(obj["rid"])
                    i += 1
                collect(results)
                if i < n_requests:
                    time.sleep(min(max(
                        arrivals[i] - time.perf_counter(), 0.0), 0.02))
            want = set(rids)
            deadline = time.time() + settle_timeout_s
            while not want <= set(results):
                if time.time() > deadline:
                    raise SystemExit(
                        f"--router: {len(want - set(results))} rids "
                        f"never settled — exact accounting violated")
                collect(results)
                time.sleep(0.03)
            elapsed = time.perf_counter() - t0
            res = [results[r] for r in sorted(want)]
            name = (f"router{replicas}_{model}_serve_"
                    f"k{max(adapters, 1)}_r{rate:g}")
            if max_prompt_chunked:
                name += f"_chunk{max_prompt_chunked}"
            if prefix_pool:
                name += (f"_prefix{prefix_pool}" if prefix_cache
                         else f"_prefix{prefix_pool}off")
            if sampling:
                name += "_sampled"
            fin = [r for r in res if r["state"] == "finished"]
            gen_tokens = sum(int(r.get("new_tokens") or 0) for r in res)
            # the routing-decision histogram comes from the router's
            # OWN stream (every decision is a route event), scoped to
            # this rate's rids; per-replica placement from the settle
            # rows (failover rids count where they actually landed)
            decisions = {}
            for e in load_events(base)[0]:
                if e["event"] == "route" and e.get("rid") in want:
                    p = e.get("policy", "?")
                    decisions[p] = decisions.get(p, 0) + 1
            per_replica = {}
            for r in res:
                if r.get("replica") is not None:
                    k = str(r["replica"])
                    per_replica[k] = per_replica.get(k, 0) + 1
            hit = {}
            for k in range(1, replicas + 1):
                p = sr.shard_path(base, k)
                ss = ([e for e in load_events(p)[0]
                       if e["event"] == "serve_stats"]
                      if os.path.exists(p) else [])
                hit[str(k)] = (ss[-1].get("prefix_hit_rate")
                               if ss else None)
            row = {
                "config": name, "offered_rps": rate,
                "replicas": replicas, "requests": len(fin),
                "elapsed_s": round(elapsed, 3),
                "req_s": (round(len(fin) / elapsed, 3)
                          if elapsed > 0 else None),
                "gen_tok_s": (round(gen_tokens / elapsed, 1)
                              if elapsed > 0 else None),
                "ttft_ms": pct(sorted(r["ttft_ms"] for r in fin
                                      if r["ttft_ms"] is not None)),
                "tpot_ms": pct(sorted(r["tpot_ms"] for r in fin
                                      if r["tpot_ms"] is not None)),
                "queue_ms": pct(sorted(r["queue_ms"] for r in fin
                                       if r["queue_ms"] is not None)),
                "terminal": census(res),
                "routing": decisions,
                "requests_per_replica": per_replica,
                "prefix_hit_rate": hit,
                "adapters_resident": adapters,
                "sampling": bool(sampling),
                "prefix_cache": bool(prefix_cache),
            }
            if baseline and baseline.get(rate) is not None:
                row["baseline_ttft_p99_ms"] = baseline[rate]
                if row["ttft_ms"]["p99"] is not None and baseline[rate]:
                    row["ttft_p99_vs_baseline"] = round(
                        row["ttft_ms"]["p99"] / baseline[rate], 3)
            rows.append(row)
            fmt = lambda v: "n/a" if v is None else f"{v:.0f}"
            print(f"{name}: {row['req_s']} req/s "
                  f"({row['gen_tok_s']} tok/s) over {replicas} "
                  f"replicas, TTFT p50/p99 = "
                  f"{fmt(row['ttft_ms']['p50'])}/"
                  f"{fmt(row['ttft_ms']['p99'])} ms, routing "
                  f"{decisions}, spread {per_replica}"
                  + (f", p99 vs 1-engine x"
                     f"{row.get('ttft_p99_vs_baseline')}"
                     if "ttft_p99_vs_baseline" in row else ""))
            for k in sorted(int(k) for k in per_replica):
                sub = [r for r in res if r.get("replica") == k]
                fin_k = [r for r in sub if r["state"] == "finished"]
                rows.append({
                    "config": f"{name}_replica{k}",
                    "offered_rps": rate, "replica": k,
                    "requests": len(fin_k),
                    "ttft_ms": pct(sorted(
                        r["ttft_ms"] for r in fin_k
                        if r["ttft_ms"] is not None)),
                    "tpot_ms": pct(sorted(
                        r["tpot_ms"] for r in fin_k
                        if r["tpot_ms"] is not None)),
                    "terminal": census(sub),
                    "prefix_hit_rate": hit.get(str(k)),
                })
    finally:
        if proc.poll() is None:
            proc.send_signal(signal.SIGTERM)
            try:
                proc.wait(timeout=60)
            except subprocess.TimeoutExpired:
                proc.kill()
                proc.wait()
    print(f"router stream: {base} (replay with "
          f"tools/trace_export.py {base} --router)")
    return rows


def run_rows(model: str, rates, n_requests: int, adapters: int,
             num_slots: int = 8, block_T: int = 16, num_blocks: int = 256,
             max_prompt: int = 64, max_new: int = 32, dtype: str =
             "bfloat16", seed: int = 0, prompt_lo: int = 8,
             prompt_hi: int = 0, telemetry_out: str = "",
             max_queue: int = 0, shed_policy: str = "reject",
             on_step_error: str = "fail_active", deadline_ms=None,
             stats_every: int = 0, inject: str = "", drain: bool = True,
             watchdog_mode: int = 0, watchdog_min_s: float = 60.0,
             hbm_cap_mb: int = 0, hbm_headroom: float = 0.1,
             trace_spans: bool = False, metrics_port: int = 0,
             metrics_addr: str = "127.0.0.1",
             mesh_dp: int = 1, mesh_tp: int = 1,
             prefix_cache: bool = False, max_prompt_chunked: int = 0,
             sampling: bool = False, prefix_pool: int = 0,
             prefix_frac: float = 0.7) -> list:
    """One engine, one warmup request, then one row per offered rate.
    `drain` arms the SIGTERM PreemptionGuard; `inject` fires its fault
    during the FIRST rate's run (the spec names an absolute decode
    step)."""
    from mobilefinetuner_tpu.core.telemetry import HangWatchdog
    prompt_hi = prompt_hi or max_prompt
    wd = None
    if watchdog_mode:
        wd = HangWatchdog(mult=10.0, min_deadline_s=watchdog_min_s,
                          grace_s=max(watchdog_min_s, 5.0),
                          abort=watchdog_mode == 2)
    eng, names = build_engine(model, num_slots, block_T, num_blocks,
                              max_prompt, max_new, adapters, dtype,
                              telemetry_out=telemetry_out, seed=seed,
                              max_queue=max_queue, shed_policy=shed_policy,
                              on_step_error=on_step_error,
                              stats_every=stats_every, watchdog=wd,
                              hbm_cap_mb=hbm_cap_mb,
                              hbm_headroom=hbm_headroom,
                              trace_spans=trace_spans,
                              metrics_port=metrics_port,
                              metrics_addr=metrics_addr,
                              mesh_dp=mesh_dp, mesh_tp=mesh_tp,
                              prefix_cache=prefix_cache,
                              max_prompt_chunked=max_prompt_chunked,
                              sampling=sampling)
    if wd is not None:
        wd.on_hang = lambda p: eng.telemetry.emit("hang", **p)
        wd.stacks_file = (eng.telemetry.path + ".stacks"
                          if eng.telemetry.path else wd.stacks_file)
        wd.start()
    if drain:
        eng.install_preemption()
    # warmup: compile prefill + step outside the measured window
    eng.submit([1] * prompt_lo, max_new_tokens=min(2, max_new),
               adapter=names[0] if names else None)
    eng.drain()
    # r21 warmup: the reuse/chunk executables compile LAZILY (one per
    # bucket width, plus the full-hit COW re-feed) — trace each here or
    # its first use lands in a measured row's TTFT tail
    if eng.prefix is not None:
        head = [7] * block_T
        eng.submit(head, max_new_tokens=1)
        eng.drain()                    # registers the head page
        eng.submit(list(head), max_new_tokens=1)
        eng.drain()                    # full hit -> COW re-feed program
        for w in eng.chunk_buckets:
            # a hit on the head page + an s-token suffix dispatches the
            # smallest bucket covering s; s caps at the widest suffix a
            # hit can leave, which is also the widest REACHABLE width
            s = min(w, (max_prompt_chunked or max_prompt) - block_T)
            if s > 0:
                eng.submit(head + [11] * s, max_new_tokens=1)
                eng.drain()
    if max_prompt_chunked:
        widest = eng.chunk_buckets[-1]
        for w in eng.chunk_buckets:
            # widest-until-covered walk: a (widest + w)-token prompt
            # ends its walk on bucket w
            n = widest + w
            if max_prompt < n <= max_prompt_chunked:
                eng.submit([13] * n, max_new_tokens=1)
                eng.drain()
    warm_traces = eng.total_traces()
    if inject == "adapter_load_fail":
        err = inject_adapter_load_fail(eng)
        if not err:
            # the harness MUST fail loudly when the injected fault is
            # not handled — a CI caller keys on the exit status
            if wd is not None:
                wd.stop()
            eng.close()
            raise SystemExit(
                "--inject adapter_load_fail: the bank ACCEPTED a "
                "structurally-wrong adapter — validation regressed")
        print(f"inject adapter_load_fail: REFUSED ({err[:60]}...)")
        fired = None
    else:
        fired = install_inject(eng, inject)
    rows = []
    try:
        for rate in rates:
            counts0 = dict(eng.counts)   # scope the row's census to
            # THIS run: health()'s counters are engine-lifetime
            pages0 = eng.alloc.pages_allocated
            ht0, lt0 = ((eng.prefix.hit_tokens, eng.prefix.lookup_tokens)
                        if eng.prefix is not None else (0, 0))
            done, elapsed = run_load(eng, names, rate, n_requests, seed,
                                     prompt_lo, prompt_hi, max_new,
                                     deadline_ms=deadline_ms,
                                     prefix_pool=prefix_pool,
                                     prefix_frac=prefix_frac,
                                     sampling=sampling)
            name = f"{model}_serve_k{max(adapters, 1)}_r{rate:g}"
            if mesh_dp * mesh_tp > 1:
                name += f"_mesh{mesh_dp}x{mesh_tp}"
            if max_prompt_chunked:
                name += f"_chunk{max_prompt_chunked}"
            # the workload suffix also records whether REUSE was on, so
            # a cache-on vs cache-off A/B lands as two bench_compare
            # rows instead of one colliding config key
            if prefix_pool:
                name += (f"_prefix{prefix_pool}" if prefix_cache
                         else f"_prefix{prefix_pool}off")
            if sampling:
                name += "_sampled"
            row = row_from(name, eng, done, elapsed, rate, adapters)
            if eng.prefix is not None:
                # scope the (token-weighted) hit rate to THIS row's
                # lookups — engine-lifetime includes the warmup's
                lt = eng.prefix.lookup_tokens - lt0
                row["prefix_hit_rate"] = (
                    round((eng.prefix.hit_tokens - ht0) / lt, 4)
                    if lt else None)
            nfin = max(row["requests"], 1)
            # pages ALLOCATED this row (prefix hits acquire, not alloc)
            # per finished request — the KV-cost-of-reuse observable
            row["kv_pages_per_req"] = round(
                (eng.alloc.pages_allocated - pages0) / nfin, 2)
            row["health"]["counts"] = {
                k: int(eng.counts.get(k, 0)) - counts0.get(k, 0)
                for k in row["health"]["counts"]}
            row["new_traces_after_warmup"] = \
                eng.total_traces() - warm_traces
            if inject:
                row["inject"] = inject
            rows.append(row)
            # percentiles may be None (e.g. max_new=1 leaves no post-
            # first-token cadence, so every tpot is None)
            fmt = lambda v, spec="0f": ("n/a" if v is None
                                        else f"{v:.{spec}}")
            term = row["terminal"]
            faults = ", ".join(f"{k} {v}" for k, v in term.items()
                               if k != "finished" and v)
            reuse = ""
            if row["prefix_hit_rate"] is not None:
                reuse = (f", hit_rate {row['prefix_hit_rate']:.2f} "
                         f"(cow {row['cow_copies']}, "
                         f"{row['kv_pages_per_req']:.1f} pages/req)")
            print(f"{name}: {row['req_s']} req/s "
                  f"({row['gen_tok_s']} tok/s), "
                  f"TTFT p50/p99 = {fmt(row['ttft_ms']['p50'])}/"
                  f"{fmt(row['ttft_ms']['p99'])} ms, TPOT p50 = "
                  f"{fmt(row['tpot_ms']['p50'], '1f')} ms, "
                  f"{row['new_traces_after_warmup']} retraces{reuse}"
                  + (f" [{faults}]" if faults else ""))
            if eng.draining:
                print(f"{name}: DRAINED (SIGTERM) — remaining rates "
                      f"skipped")
                break
    finally:
        if wd is not None:
            wd.stop()
        if getattr(eng, "metrics_server", None) is not None:
            eng.metrics_server.close()
        eng.close()
    if fired is not None and not fired:
        # the armed fault never triggered (step already consumed by
        # warmup, or past the run's reach): the robustness claim was
        # NOT exercised — fail the harness, don't report a clean row
        raise SystemExit(
            f"--inject {inject}: the armed fault never fired "
            f"(run ended at decode step "
            f"{rows[-1]['decode_steps'] if rows else 0}) — "
            f"nothing was proven")
    return rows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--model", default="gpt2s",
                    choices=["gpt2s", "gemma270m", "tiny-gpt2",
                             "tiny-gemma"])
    ap.add_argument("--gemma", action="store_true",
                    help="shorthand for --model gemma270m")
    ap.add_argument("--rate", type=float, nargs="*", default=[4.0],
                    help="offered load(s), requests/second")
    ap.add_argument("--requests", type=int, default=64)
    ap.add_argument("--adapters", type=int, default=0,
                    help="resident LoRA tenants (0 = base only)")
    ap.add_argument("--num_slots", type=int, default=8)
    ap.add_argument("--block_T", type=int, default=16)
    ap.add_argument("--num_blocks", type=int, default=256)
    ap.add_argument("--max_prompt", type=int, default=64)
    ap.add_argument("--max_new", type=int, default=32)
    ap.add_argument("--prompt_lo", type=int, default=8)
    ap.add_argument("--prompt_hi", type=int, default=0,
                    help="prompt-length ceiling for the workload "
                         "(0 = max_prompt); raise past max_prompt "
                         "with --max_prompt_chunked to offer the "
                         "long-prompt mix chunked admission absorbs")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--mesh", default="1,1",
                    help="serve the engine over a (dp, tp) device mesh "
                         "(serve/sharding.py): 'dp,tp', e.g. '1,4' = "
                         "4-way tensor parallel. Rows gain mesh + "
                         "tok_s_per_chip and a _mesh{dp}x{tp} config "
                         "suffix. On CPU (JAX_PLATFORMS=cpu) the "
                         "8-virtual-device platform is forced "
                         "automatically")
    # --- traffic-scale serving (round 21, DESIGN.md §26) --------------
    ap.add_argument("--prefix_cache", type=int, default=0, choices=[0, 1],
                    help="1 = shared-prefix KV reuse: hashed full-block "
                         "prompt prefixes map refcounted pages, finished "
                         "requests' pages park as a reclaimable cache")
    ap.add_argument("--prefix_pool", type=int, default=0,
                    help="shape the workload around N seeded shared "
                         "prefixes (each request opens with a pool "
                         "member with probability --prefix_frac); 0 = "
                         "fully random prompts. Rows gain a _prefixN "
                         "config suffix")
    ap.add_argument("--prefix_frac", type=float, default=0.7,
                    help="fraction of requests that open with a pool "
                         "prefix when --prefix_pool is set")
    ap.add_argument("--max_prompt_chunked", type=int, default=0,
                    help="TRUE prompt cap under chunked admission "
                         "(block_T multiple > max_prompt): longer "
                         "prompts prefill in static-bucket chunks "
                         "interleaved with decode steps. 0 = off "
                         "(prompts beyond max_prompt reject with "
                         "reason=prompt_too_long)")
    ap.add_argument("--sampling", type=int, default=0, choices=[0, 1],
                    help="1 = per-request temperature/top-k/top-p "
                         "sampling with seeded per-slot PRNG keys "
                         "(same seed => same tokens); rows gain a "
                         "_sampled config suffix")
    # --- serve-fleet routing (round 22, DESIGN.md §27) ----------------
    ap.add_argument("--router", type=int, default=0,
                    help="drive the SAME open-loop load over HTTP "
                         "through tools/serve_router.py with this "
                         "many engine replica processes (0 = direct "
                         "in-process engine). Emits one fleet row per "
                         "rate plus per-replica rows; --telemetry_out "
                         "becomes the router stream base — replay the "
                         "session with tools/trace_export.py --router")
    ap.add_argument("--router_baseline", type=int, default=0,
                    choices=[0, 1],
                    help="with --router: first run the identical "
                         "workload on ONE in-process engine and stamp "
                         "the fleet row with baseline_ttft_p99_ms + "
                         "the ttft_p99_vs_baseline ratio")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--telemetry_out", default="")
    ap.add_argument("--out", default="",
                    help="append rows to this JSON artifact")
    ap.add_argument("--run_registry", default="",
                    help="append-only run registry stream (core/"
                         "run_registry.py): one crash-safe record per "
                         "bench invocation; default $MFT_RUN_REGISTRY, "
                         "empty = off")
    # --- robustness / fault harness (round 14, DESIGN.md §19) ---------
    ap.add_argument("--max_queue", type=int, default=0,
                    help="bounded admission: cap the FCFS queue; "
                         "over-limit submits reject with "
                         "reason=queue_full (0 = unbounded)")
    ap.add_argument("--shed_policy", default="reject",
                    choices=["reject", "deadline"],
                    help="on a full queue: reject the newest arrival, "
                         "or shed the queued request closest to "
                         "blowing its deadline")
    ap.add_argument("--deadline_ms", type=float, default=0.0,
                    help="per-request end-to-end deadline; expired "
                         "queued requests never prefill, active ones "
                         "stop at the next step boundary (0 = none)")
    ap.add_argument("--on_step_error", default="fail_active",
                    choices=["fail_active", "raise"],
                    help="contain a step-dispatch exception (fail the "
                         "in-flight requests, keep serving) or re-raise "
                         "after containing")
    ap.add_argument("--hbm_cap_mb", type=int, default=0,
                    help="memory-admission capacity override for the "
                         "engine's build-time preflight (DESIGN.md "
                         "§21); an infeasible num_blocks/num_slots "
                         "is refused with the max feasible values "
                         "named. 0 = auto")
    ap.add_argument("--hbm_headroom", type=float, default=0.1,
                    help="admission margin for the build preflight")
    ap.add_argument("--stats_every", type=int, default=0,
                    help="emit a serve_stats health snapshot every N "
                         "decode steps (0 = off)")
    # --- live observability (round 17, DESIGN.md §22) -----------------
    ap.add_argument("--trace_spans", type=int, default=0, choices=[0, 1],
                    help="1 = emit per-request queue/prefill/decode "
                         "`span` events (track req:<id>) into the "
                         "telemetry stream; tools/trace_export.py "
                         "renders the session as one Perfetto timeline")
    ap.add_argument("--metrics_port", type=int, default=0,
                    help="serve a live OpenMetrics /metrics endpoint + "
                         "/healthz (engine.health()) on this port, fed "
                         "from the engine's telemetry emit path "
                         "(core/metrics_http.py); scraping can never "
                         "cost a retrace. 0 = off")
    ap.add_argument("--metrics_addr", default="127.0.0.1",
                    help="bind address for --metrics_port (loopback by "
                         "default)")
    ap.add_argument("--inject", default="",
                    help="fault harness: step_error:<n> | hang:<n>[:<s>]"
                         " | slow_step:<n>:<ms> | adapter_load_fail")
    ap.add_argument("--drain", type=int, default=1, choices=[0, 1],
                    help="arm SIGTERM graceful drain (finish in-flight, "
                         "reject queue with reason=shutdown, "
                         "run_end{reason=preempted}; second signal "
                         "cancels in-flight)")
    ap.add_argument("--watchdog", type=int, default=0, choices=[0, 1, 2],
                    help="hang watchdog over the serve loop: 1 = report "
                         "(`hang` event) and keep waiting, 2 = report "
                         "then abort (exit 113)")
    ap.add_argument("--watchdog_min_s", type=float, default=60.0,
                    help="watchdog deadline floor (and pre-first-step "
                         "grace) in seconds")
    args = ap.parse_args(argv)
    model = "gemma270m" if args.gemma else args.model
    if args.inject == "adapter_load_fail" and not args.adapters:
        raise SystemExit("--inject adapter_load_fail needs --adapters k")
    try:
        mesh_dp, mesh_tp = (int(v) for v in args.mesh.split(","))
    except ValueError:
        raise SystemExit(f"--mesh must be 'dp,tp', got {args.mesh!r}")
    if mesh_dp * mesh_tp > 1 \
            and os.environ.get("JAX_PLATFORMS", "") == "cpu":
        # the CPU contract path: virtual devices must exist before the
        # first backend init (tests/conftest.py does the same)
        from mobilefinetuner_tpu.parallel.host_devices import \
            force_host_devices
        force_host_devices(max(8, mesh_dp * mesh_tp))
    # run registry (core/run_registry.py, DESIGN.md §28): one
    # crash-safe record per bench invocation. Admission rejects and
    # fault-harness aborts finalize with the exception's name via the
    # handle's __exit__; a SIGKILL mid-run settles to "interrupted"
    # on the next registry open.
    import contextlib
    from mobilefinetuner_tpu.core.run_registry import RunRegistry
    _reg = RunRegistry.from_args(args)
    run_rec = _reg.begin(
        "serve", "serve_bench", config=vars(args),
        platform=jax.devices()[0].platform,
        mesh=({"data": mesh_dp, "model": mesh_tp}
              if mesh_dp * mesh_tp > 1 else None),
        artifacts=[p for p in (args.telemetry_out, args.out)
                   if p]) if _reg else None
    with run_rec if run_rec is not None else contextlib.nullcontext():
        if args.router > 0:
            if args.inject:
                raise SystemExit("--router composes with --inject only by "
                                 "killing replica processes (see the "
                                 "kill-one-replica e2e); drop --inject")
            if mesh_dp * mesh_tp > 1:
                raise SystemExit("--router replicas are single-host "
                                 "engines (data parallelism IS the "
                                 "replica set); drop --mesh")
            base = args.telemetry_out
            if not base:
                import tempfile
                base = os.path.join(
                    tempfile.mkdtemp(prefix="serve_fleet_"), "fleet.jsonl")
                print(f"--router: telemetry stream at {base} "
                      f"(pass --telemetry_out to choose)")
            baseline = None
            if args.router_baseline:
                brows = run_rows(
                    model, args.rate, args.requests, args.adapters,
                    num_slots=args.num_slots, block_T=args.block_T,
                    num_blocks=args.num_blocks, max_prompt=args.max_prompt,
                    max_new=args.max_new, dtype=args.dtype, seed=args.seed,
                    prompt_lo=args.prompt_lo, prompt_hi=args.prompt_hi,
                    max_queue=args.max_queue, shed_policy=args.shed_policy,
                    deadline_ms=args.deadline_ms or None,
                    prefix_cache=bool(args.prefix_cache),
                    max_prompt_chunked=args.max_prompt_chunked,
                    sampling=bool(args.sampling),
                    prefix_pool=args.prefix_pool,
                    prefix_frac=args.prefix_frac)
                baseline = {r["offered_rps"]: r["ttft_ms"]["p99"]
                            for r in brows}
                rows = brows
            else:
                rows = []
            rows = rows + run_router_rows(
                model, args.rate, args.requests, args.adapters,
                args.router, base, num_slots=args.num_slots,
                block_T=args.block_T, num_blocks=args.num_blocks,
                max_prompt=args.max_prompt, max_new=args.max_new,
                dtype=args.dtype, seed=args.seed,
                prompt_lo=args.prompt_lo, prompt_hi=args.prompt_hi,
                max_queue=args.max_queue, shed_policy=args.shed_policy,
                stats_every=args.stats_every or 10,
                prefix_cache=bool(args.prefix_cache),
                max_prompt_chunked=args.max_prompt_chunked,
                sampling=bool(args.sampling),
                prefix_pool=args.prefix_pool,
                prefix_frac=args.prefix_frac,
                deadline_ms=args.deadline_ms or None,
                baseline=baseline)
        else:
            rows = run_rows(model, args.rate, args.requests, args.adapters,
                            num_slots=args.num_slots, block_T=args.block_T,
                            num_blocks=args.num_blocks,
                            max_prompt=args.max_prompt, max_new=args.max_new,
                            dtype=args.dtype, seed=args.seed,
                            prompt_lo=args.prompt_lo, prompt_hi=args.prompt_hi,
                            telemetry_out=args.telemetry_out,
                            max_queue=args.max_queue,
                            shed_policy=args.shed_policy,
                            on_step_error=args.on_step_error,
                            deadline_ms=args.deadline_ms or None,
                            stats_every=args.stats_every, inject=args.inject,
                            drain=bool(args.drain),
                            watchdog_mode=args.watchdog,
                            watchdog_min_s=args.watchdog_min_s,
                            hbm_cap_mb=args.hbm_cap_mb,
                            hbm_headroom=args.hbm_headroom,
                            trace_spans=bool(args.trace_spans),
                            metrics_port=args.metrics_port,
                            metrics_addr=args.metrics_addr,
                            mesh_dp=mesh_dp, mesh_tp=mesh_tp,
                            prefix_cache=bool(args.prefix_cache),
                            max_prompt_chunked=args.max_prompt_chunked,
                            sampling=bool(args.sampling),
                            prefix_pool=args.prefix_pool,
                            prefix_frac=args.prefix_frac)
        if args.out:
            art = {"device": jax.devices()[0].device_kind,
                   "jax": jax.__version__, "rows": []}
            if os.path.exists(args.out):
                with open(args.out) as f:
                    art = json.load(f)
            art["rows"].extend(rows)
            tmp = args.out + ".tmp"
            with open(tmp, "w") as f:
                json.dump(art, f, indent=1)
            os.replace(tmp, args.out)
        return 0


if __name__ == "__main__":
    sys.exit(main())
