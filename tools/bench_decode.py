"""Decode-cost ground truth: e2e marginal ms/token + serving B-sweep.

Microbenches of the isolated decode attention are polluted on this
platform by per-op and per-call overheads (and a ~105 ms dispatch RTT),
so this tool measures what DESIGN.md §10 calls the pipelined-call delta:
jit the full generate program at two values of N, dispatch `pipeline`
calls back-to-back with one sync, and divide the wall-clock difference by
the extra decode steps. That isolates the device-side marginal cost of
one token-step (all layers, cache reads, head matmul, sampling) with
prefill and RTT subtracted structurally.

Serving-SLO columns (round 11): each row also reports
  TTFT  wall time of a max_new_tokens=1 call — prefill + first token +
        dispatch, the latency a request sees before its first byte;
  TPOT  = the marginal ms/token-step above — the streaming cadence.
`--adapters k` runs the same program with a k-adapter stacked bank
routed per row (lora.stack_adapters + assign_adapters), pricing exactly
what multi-tenant decode adds over the base model.

Usage:
  python tools/bench_decode.py                 # GPT-2 small
  python tools/bench_decode.py --gemma         # Gemma-3 270M
  python tools/bench_decode.py --adapters 8    # k=8 stacked-bank decode
  python tools/bench_decode.py --kernel        # + pallas kernel microbench
"""

from __future__ import annotations

import argparse
import json
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])   # repo root
sys.path.insert(0, __file__.rsplit("/", 1)[0])   # tools/ (serve_bench)

import jax
import jax.numpy as jnp
import numpy as np


def timed_window(f, pipeline, reps=3):
    """Best-of-`reps` wall seconds per call for a pipelined dispatch
    window. Min discards OS scheduler hiccups, which otherwise dominate
    single-call windows (pipeline=1 contract mode on shared CPU)."""
    best = float("inf")
    for _ in range(reps):
        t0 = time.perf_counter()
        outs = [f() for _ in range(pipeline)]
        np.asarray(outs[-1])
        best = min(best, time.perf_counter() - t0)
    return best / pipeline


def marginal_ms(make_f, n_lo, n_hi, pipeline=8):
    """Marginal device ms/token-step from pipelined deltas between two N.
    make_f(n) -> zero-arg dispatch returning the output array."""
    out = {}
    for n in (n_lo, n_hi):
        f = make_f(n)
        np.asarray(f())                             # compile
        out[n] = timed_window(f, pipeline)
    return (out[n_hi] - out[n_lo]) * 1000 / (n_hi - n_lo), out


def bench_model(gemma: bool, B: int, P: int, dtype, pipeline: int,
                adapters: int = 0, tiny: bool = False, n_pair=(16, 64),
                lora_impl: str = "auto"):
    """One decode row; returns the row dict (contract-tested by
    tests/test_bench_contract.py via tiny=True on CPU). lora_impl
    selects the models/lora_apply.py path for the stacked-bank decode
    (--adapters k): the fused-vs-naive TPOT delta is the r12 column."""
    from mobilefinetuner_tpu.models import gemma3, gpt2
    from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                     gemma3_generate,
                                                     gpt2_generate)
    if gemma:
        from mobilefinetuner_tpu.core.config import Gemma3TextConfig
        config = (Gemma3TextConfig.tiny() if tiny
                  else Gemma3TextConfig.gemma3_270m())
        params = gemma3.init_params(config, jax.random.PRNGKey(0))
        gen, name = gemma3_generate, "gemma270m"
    else:
        from mobilefinetuner_tpu.core.config import GPT2Config
        config = GPT2Config.tiny() if tiny else GPT2Config.gpt2_small()
        params = gpt2.init_params(config, jax.random.PRNGKey(0))
        gen, name = gpt2_generate, "gpt2s"
    if tiny:
        name += "_tiny"
    vocab = config.vocab_size
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, vocab, (B, P)), jnp.int32)
    mask = jnp.ones_like(ids)

    lora = None
    if adapters:
        from mobilefinetuner_tpu.lora.lora import (assign_adapters,
                                                   stack_adapters)
        from serve_bench import rand_adapters
        trees = rand_adapters("gemma" if gemma else "gpt2", config,
                              adapters)
        lora = assign_adapters(stack_adapters(trees),
                               [i % adapters for i in range(B)])

    n_lo, n_hi = n_pair

    def make_f(n):
        cfg = SampleConfig(max_new_tokens=n, greedy=True, eos_id=None)
        f = jax.jit(lambda p, l, i, m: gen(config, p, i, m, cfg, lora=l,
                                           compute_dtype=dtype,
                                           lora_impl=lora_impl))
        return lambda: f(params, lora, ids, mask)

    ms, walls = marginal_ms(make_f, n_lo, n_hi, pipeline=pipeline)
    # TTFT: one prefill + one sampled token, e2e (dispatch included)
    f1 = make_f(1)
    np.asarray(f1())                                # compile
    ttft_ms = timed_window(lambda: np.asarray(f1()), pipeline) * 1000
    sustained = B * n_hi / walls[n_hi]
    row = {
        "config": f"{name}_decode_B{B}"
                  + (f"_k{adapters}" if adapters else "")
                  + (f"_lora{lora_impl}" if lora_impl != "auto" else ""),
        "B": B, "P": P, "adapters": adapters,
        "lora_impl": lora_impl,
        "dtype": str(jnp.dtype(dtype)),
        "tpot_ms": round(ms, 4),                    # marginal ms/token
        "ttft_ms": round(ttft_ms, 3),
        "tok_s_asymptotic": round(B / ms * 1000, 1) if ms > 0 else None,
        "sustained_tok_s": round(sustained, 1),
        "wall_ms_lo": round(walls[n_lo] * 1e3, 3),
        "wall_ms_hi": round(walls[n_hi] * 1e3, 3),
    }
    asym = (f"{row['tok_s_asymptotic']:.0f} tok/s asymptotic"
            if row["tok_s_asymptotic"] is not None
            else "marginal below timer noise")  # tiny CPU contract mode
    print(f"{row['config']} P={P}: TPOT {ms:.3f} ms/token-step, "
          f"TTFT {ttft_ms:.1f} ms ({asym})  "
          f"[wall N={n_lo} {walls[n_lo]*1e3:.1f} ms, "
          f"N={n_hi} {walls[n_hi]*1e3:.1f}]")
    print(f"  sustained e2e (pipeline={pipeline}, N={n_hi}): "
          f"{sustained:,.0f} tok/s")
    return row


def bench_paged_mesh(gemma: bool, S: int, dtype, pipeline: int,
                     mesh, tiny: bool = False, adapters: int = 0,
                     n_pair=(16, 64)):
    """TPOT/TTFT of the PAGED serving step under a (dp, tp) mesh — one
    row per attention path (xla gather vs pallas kernel), so the
    auto-gate's decision under sharding is a benched number, not a
    guess: `pallas_eligible` records the verdict paged_eligible reaches
    with PER-SHARD head counts, and the two rows' tpot_ms settle
    whether it was right on this backend. Contract-tested in tiny CPU
    mode (tests/test_bench_contract.py)."""
    import dataclasses
    from mobilefinetuner_tpu.models import gemma3, gpt2
    from mobilefinetuner_tpu.models.generate import (
        gemma3_decode_step_paged, gpt2_decode_step_paged, gpt2_prefill,
        gemma3_prefill)
    from mobilefinetuner_tpu.ops.decode_attention import paged_eligible
    from mobilefinetuner_tpu.serve import init_pools
    from mobilefinetuner_tpu.serve.sharding import ServeSharding

    dp, tp = mesh
    if gemma:
        from mobilefinetuner_tpu.core.config import Gemma3TextConfig
        config = (Gemma3TextConfig.tiny() if tiny
                  else Gemma3TextConfig.gemma3_270m())
        mod, name = gemma3, "gemma270m"
        step_raw, prefill_raw = gemma3_decode_step_paged, gemma3_prefill
        L, KV, D = (config.num_hidden_layers,
                    config.num_key_value_heads, config.head_dim)
        nq = config.num_attention_heads
    else:
        from mobilefinetuner_tpu.core.config import GPT2Config
        config = GPT2Config.tiny() if tiny else GPT2Config.gpt2_small()
        if tiny and tp > config.n_head:
            # tiny GPT-2 has 2 heads; give the mesh enough to split
            config = dataclasses.replace(config, n_head=4)
        mod, name = gpt2, "gpt2s"
        step_raw, prefill_raw = gpt2_decode_step_paged, gpt2_prefill
        L, KV, D = config.n_layer, config.n_head, config.head_dim
        nq = config.n_head
    if tiny:
        name += "_tiny"
    family = "gemma" if gemma else "gpt2"
    params = mod.init_params(config, jax.random.PRNGKey(0))

    lora = None
    if adapters:
        from mobilefinetuner_tpu.lora.lora import (assign_adapters,
                                                   stack_adapters)
        from serve_bench import rand_adapters
        trees = rand_adapters(family, config, adapters)
        lora = assign_adapters(stack_adapters(trees),
                               [i % adapters for i in range(S)])

    n_lo, n_hi = n_pair
    bT = 8 if tiny else 16
    P = bT                                   # one prefilled page/slot
    M = -(-(P + n_hi + 1) // bT)             # pages per slot, worst case
    NB = S * M + 1
    sh = None
    if dp * tp > 1:
        sh = ServeSharding.build(family, config, dp, tp)
        params = jax.device_put(params, sh.param_shardings(params))
        dev = lambda a: jax.device_put(np.asarray(a), sh.repl)
        if lora is not None:
            lora = sh.put_repl(lora)
    else:
        dev = jnp.asarray
    pool_k, pool_v = init_pools(NB, L, KV, bT, D, jnp.dtype(dtype))
    if sh is not None:
        psh = sh.pool_sharding()
        pool_k = jax.device_put(pool_k, psh)
        pool_v = jax.device_put(pool_v, psh)
    rng = np.random.default_rng(0)
    tok = dev(rng.integers(0, config.vocab_size, S).astype(np.int32))
    pos = dev(np.full(S, P, np.int32))
    tbl = dev((1 + np.arange(S * M, dtype=np.int32)).reshape(S, M))
    elig = paged_eligible(KV, nq // KV, bT, D,
                          jnp.dtype(dtype).itemsize, tp=tp)

    def make_make_f(impl):
        def make_f(n):
            def run(params, lora, pk, pv, tok, pos, tbl):
                def body(carry, _):
                    tok, pos, pk, pv = carry
                    logits, pk, pv = step_raw(
                        config, params, pk, pv, tok, pos, tbl,
                        lora=lora, compute_dtype=dtype, attn_impl=impl,
                        shardings=sh)
                    nxt = jnp.argmax(logits, -1).astype(jnp.int32)
                    return (nxt, pos + 1, pk, pv), None
                (tok, *_), _ = jax.lax.scan(
                    body, (tok, pos, pk, pv), None, length=n)
                return tok
            f = jax.jit(run)
            return lambda: f(params, lora, pool_k, pool_v, tok, pos, tbl)
        return make_f

    # TTFT: one sharded prefill + first token, e2e
    ids = dev(rng.integers(1, config.vocab_size, (1, P)).astype(np.int32))
    mask = dev(np.ones((1, P), np.int32))
    pf = jax.jit(lambda p, i, m: prefill_raw(
        config, p, i, m, compute_dtype=dtype, shardings=sh)[0])
    np.asarray(pf(params, ids, mask))               # compile
    ttft_ms = timed_window(
        lambda: np.asarray(pf(params, ids, mask)), pipeline) * 1000

    rows = []
    for impl in ("xla", "pallas"):
        ms, walls = marginal_ms(make_make_f(impl), n_lo, n_hi,
                                pipeline=pipeline)
        row = {
            "config": f"{name}_paged_S{S}_mesh{dp}x{tp}_{impl}"
                      + (f"_k{adapters}" if adapters else ""),
            "B": S, "P": P, "adapters": adapters,
            "attn_impl": impl, "mesh": [dp, tp],
            "pallas_eligible": bool(elig),
            "dtype": str(jnp.dtype(dtype)),
            "tpot_ms": round(ms, 4),
            "ttft_ms": round(ttft_ms, 3),
            "tok_s_asymptotic": (round(S / ms * 1000, 1)
                                 if ms > 0 else None),
            "tok_s_per_chip": (round(S / ms * 1000 / (dp * tp), 1)
                               if ms > 0 else None),
            "wall_ms_lo": round(walls[n_lo] * 1e3, 3),
            "wall_ms_hi": round(walls[n_hi] * 1e3, 3),
        }
        rows.append(row)
        print(f"{row['config']}: TPOT {ms:.3f} ms/token-step, "
              f"TTFT {ttft_ms:.1f} ms, per-chip "
              f"{row['tok_s_per_chip'] or 'n/a'} tok/s "
              f"(pallas eligible per-shard: {elig})")
    return rows


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gemma", action="store_true")
    ap.add_argument("--P", type=int, default=0,
                    help="prompt length (default 128; 8 under --tiny)")
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--pipeline", type=int, default=8)
    ap.add_argument("--B", type=int, nargs="*", default=[8, 32])
    ap.add_argument("--adapters", type=int, default=0,
                    help="stacked-bank decode with k adapters routed "
                         "per batch row (0 = base model)")
    ap.add_argument("--lora_impl", choices=["auto", "naive", "fused"],
                    default="auto",
                    help="LoRA hot-path implementation for the decode "
                         "program (models/lora_apply.py; naive = the "
                         "parity oracle, fused = cost-model order + "
                         "Pallas epilogue at eligible sites)")
    ap.add_argument("--tiny", action="store_true",
                    help="tiny config (CPU contract mode)")
    ap.add_argument("--mesh", default="",
                    help="bench the PAGED serving decode step under a "
                         "(dp, tp) mesh instead of generate(): 'dp,tp' "
                         "(e.g. '1,4'); emits one row per attention "
                         "path (xla gather vs pallas kernel) with "
                         "mesh + tok_s_per_chip — the sharded "
                         "gather-vs-kernel decision, benched. '1,1' "
                         "benches the same step unsharded")
    ap.add_argument("--json", action="store_true", dest="json_out",
                    help="emit one JSON row per batch size")
    ap.add_argument("--kernel", action="store_true",
                    help="also run the pallas decode_attention microbench")
    args = ap.parse_args()
    if args.mesh:
        import os
        try:
            dp, tp = (int(v) for v in args.mesh.split(","))
        except ValueError:
            raise SystemExit(f"--mesh must be 'dp,tp', got {args.mesh!r}")
        if dp * tp > 1 and os.environ.get("JAX_PLATFORMS", "") == "cpu":
            from mobilefinetuner_tpu.parallel.host_devices import \
                force_host_devices
            force_host_devices(max(8, dp * tp))
    dtype = jnp.dtype(args.dtype)
    # tiny configs have n_positions=64: shrink P and the N pair so
    # P + n_hi fits (same values the contract test pins)
    P = args.P or (8 if args.tiny else 128)
    n_pair = (2, 4) if args.tiny else (16, 64)
    for b in args.B:
        if args.mesh:
            rows = bench_paged_mesh(args.gemma, b, dtype, args.pipeline,
                                    (dp, tp), tiny=args.tiny,
                                    adapters=args.adapters,
                                    n_pair=n_pair)
            if args.json_out:
                for row in rows:
                    print(json.dumps(row))
            continue
        row = bench_model(args.gemma, b, P, dtype, args.pipeline,
                          adapters=args.adapters, tiny=args.tiny,
                          n_pair=n_pair, lora_impl=args.lora_impl)
        if args.json_out:
            print(json.dumps(row))
    if args.kernel:
        kernel_microbench(args.gemma)


def kernel_microbench(gemma: bool):
    """ops/decode_attention.py vs the XLA einsum path, on-device loop
    (documents the per-call launch floor that benches the kernel out —
    DESIGN.md §10a)."""
    from mobilefinetuner_tpu.ops.decode_attention import (decode_attention,
                                                          decode_eligible,
                                                          xla_reference)
    B, T, L = 8, 192, 12
    KV, G, D = (1, 4, 256) if gemma else (12, 1, 64)
    dt = jnp.bfloat16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, KV, G, D), dt)
    kc = jax.random.normal(kk, (B, KV, T, D), dt)
    vc = jax.random.normal(kv, (B, KV, T, D), dt)
    ok = jnp.broadcast_to(jnp.arange(T)[None, :] < T - 16, (B, T))
    scale = D ** -0.5

    def run(name, fn):
        def step(qq, _):
            out = qq
            for _ in range(L):
                out = qq + fn(out, kc, vc, ok, scale).astype(qq.dtype) \
                    * 1e-6
            return out, None
        j = jax.jit(lambda qq: jax.lax.scan(step, qq, None, length=200)[0])
        np.asarray(j(q))
        t0 = time.perf_counter()
        np.asarray(j(q))
        dtp = (time.perf_counter() - t0) / 200
        bw = L * 2 * kc.size * kc.dtype.itemsize / dtp / 1e9
        print(f"  {name:8s}: {dtp*1e6:7.1f} us/{L}-layer step  "
              f"cache BW {bw:6.1f} GB/s")
        return fn(q, kc, vc, ok, scale)

    print(f"kernel microbench B={B} KV={KV} G={G} T={T} D={D} "
          f"eligible={decode_eligible(KV, T, D, 2, G)}")
    r1 = run("xla", xla_reference)
    r2 = run("pallas", decode_attention)
    print("  max|diff| =", float(jnp.max(jnp.abs(r1 - r2))))


if __name__ == "__main__":
    main()
