"""Decode-cost ground truth: e2e marginal ms/token + serving B-sweep.

Microbenches of the isolated decode attention are polluted on this
platform by per-op and per-call overheads (and a ~105 ms dispatch RTT),
so this tool measures what DESIGN.md §10 calls the pipelined-call delta:
jit the full generate program at two values of N, dispatch `pipeline`
calls back-to-back with one sync, and divide the wall-clock difference by
the extra decode steps. That isolates the device-side marginal cost of
one token-step (all layers, cache reads, head matmul, sampling) with
prefill and RTT subtracted structurally.

Also prints the serving regime: sustained generated-tokens/sec at each
batch size (weights are read once per token-STEP, so batch amortizes the
dominant weight stream; the B=8 marginal cost is byte-floor-bound,
DESIGN.md §10a).

Usage:
  python tools/bench_decode.py                 # GPT-2 small
  python tools/bench_decode.py --gemma         # Gemma-3 270M
  python tools/bench_decode.py --kernel        # + pallas kernel microbench
"""

from __future__ import annotations

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

import jax
import jax.numpy as jnp
import numpy as np


def marginal_ms(fn_n, params, ids, mask, n_lo, n_hi, pipeline=8):
    """Marginal device ms/token-step from pipelined deltas between two N."""
    out = {}
    for n in (n_lo, n_hi):
        f = fn_n(n)
        np.asarray(f(params, ids, mask))            # compile
        t0 = time.perf_counter()
        outs = [f(params, ids, mask) for _ in range(pipeline)]
        np.asarray(outs[-1])
        out[n] = (time.perf_counter() - t0) / pipeline
    return (out[n_hi] - out[n_lo]) * 1000 / (n_hi - n_lo), out


def bench_model(gemma: bool, B: int, P: int, dtype, pipeline: int):
    from mobilefinetuner_tpu.models import gemma3, gpt2
    from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                     gemma3_generate,
                                                     gpt2_generate)
    if gemma:
        from mobilefinetuner_tpu.core.config import Gemma3TextConfig
        config = Gemma3TextConfig.gemma3_270m()
        params = gemma3.init_params(config, jax.random.PRNGKey(0))
        gen = gemma3_generate
        vocab = config.vocab_size
    else:
        from mobilefinetuner_tpu.core.config import GPT2Config
        config = GPT2Config.gpt2_small()
        params = gpt2.init_params(config, jax.random.PRNGKey(0))
        gen = gpt2_generate
        vocab = config.vocab_size
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, vocab, (B, P)), jnp.int32)
    mask = jnp.ones_like(ids)

    def fn_n(n):
        cfg = SampleConfig(max_new_tokens=n, greedy=True, eos_id=None)
        return jax.jit(lambda p, i, m: gen(config, p, i, m, cfg,
                                           compute_dtype=dtype))

    ms, walls = marginal_ms(fn_n, params, ids, mask, 16, 64,
                            pipeline=pipeline)
    name = "gemma270m" if gemma else "gpt2s"
    print(f"{name} B={B} P={P}: marginal {ms / 1:.3f} ms/token-step "
          f"({B / ms * 1000:.0f} tok/s asymptotic)  "
          f"[wall N=16 {walls[16]*1e3:.1f} ms, N=64 {walls[64]*1e3:.1f}]")
    # sustained serving number at N=64 (same definition as bench.py)
    sustained = B * 64 / walls[64]
    print(f"  sustained e2e (pipeline={pipeline}, N=64): "
          f"{sustained:,.0f} tok/s")
    return ms, sustained


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--gemma", action="store_true")
    ap.add_argument("--P", type=int, default=128)
    ap.add_argument("--dtype", default="bfloat16")
    ap.add_argument("--pipeline", type=int, default=8)
    ap.add_argument("--B", type=int, nargs="*", default=[8, 32])
    ap.add_argument("--kernel", action="store_true",
                    help="also run the pallas decode_attention microbench")
    args = ap.parse_args()
    dtype = jnp.dtype(args.dtype)
    for b in args.B:
        bench_model(args.gemma, b, args.P, dtype, args.pipeline)
    if args.kernel:
        kernel_microbench(args.gemma)


def kernel_microbench(gemma: bool):
    """ops/decode_attention.py vs the XLA einsum path, on-device loop
    (documents the per-call launch floor that benches the kernel out —
    DESIGN.md §10a)."""
    from mobilefinetuner_tpu.ops.decode_attention import (decode_attention,
                                                          decode_eligible,
                                                          xla_reference)
    B, T, L = 8, 192, 12
    KV, G, D = (1, 4, 256) if gemma else (12, 1, 64)
    dt = jnp.bfloat16
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(0), 3)
    q = jax.random.normal(kq, (B, KV, G, D), dt)
    kc = jax.random.normal(kk, (B, KV, T, D), dt)
    vc = jax.random.normal(kv, (B, KV, T, D), dt)
    ok = jnp.broadcast_to(jnp.arange(T)[None, :] < T - 16, (B, T))
    scale = D ** -0.5

    def run(name, fn):
        def step(qq, _):
            out = qq
            for _ in range(L):
                out = qq + fn(out, kc, vc, ok, scale).astype(qq.dtype) \
                    * 1e-6
            return out, None
        j = jax.jit(lambda qq: jax.lax.scan(step, qq, None, length=200)[0])
        np.asarray(j(q))
        t0 = time.perf_counter()
        np.asarray(j(q))
        dtp = (time.perf_counter() - t0) / 200
        bw = L * 2 * kc.size * kc.dtype.itemsize / dtp / 1e9
        print(f"  {name:8s}: {dtp*1e6:7.1f} us/{L}-layer step  "
              f"cache BW {bw:6.1f} GB/s")
        return fn(q, kc, vc, ok, scale)

    print(f"kernel microbench B={B} KV={KV} G={G} T={T} D={D} "
          f"eligible={decode_eligible(KV, T, D, 2, G)}")
    r1 = run("xla", xla_reference)
    r2 = run("pallas", decode_attention)
    print("  max|diff| =", float(jnp.max(jnp.abs(r1 - r2))))


if __name__ == "__main__":
    main()
