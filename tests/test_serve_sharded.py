"""Sharded serving tests (serve/sharding.py, DESIGN.md §25).

The correctness anchor is TOKEN PARITY: a ServeEngine running its
decode step over a (dp, tp) device mesh — TP-partitioned attention
heads + MLP hidden, per-shard KV pool slices, block-diagonally placed
adapter banks — must be token-IDENTICAL to the single-chip engine for
the same request set, mixed base+adapter, through hot-swaps. And the
COMPILE-STABILITY invariant survives sharding: zero post-warmup
retraces at every mesh shape (the bank swap stays one traced
`at[slot].set` on NamedSharding-stable buffers).

Runs on the 8-virtual-device CPU platform conftest.py forces."""

import dataclasses
import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                           init_lora_gpt2)
from mobilefinetuner_tpu.models import gemma3, gpt2
from mobilefinetuner_tpu.ops.decode_attention import (paged_eligible,
                                                      pick_kvb,
                                                      shard_heads)
from mobilefinetuner_tpu.serve import (AdapterBank, ServeConfig,
                                       ServeEngine, ServeSharding,
                                       make_serve_mesh)

GPT2_CFG = dataclasses.replace(
    GPT2Config.tiny(vocab_size=211), n_embd=64, n_head=4, n_positions=64,
    n_layer=3, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)
# sliding_window (6) < prompt+gen so local layers actually truncate
GEMMA_CFG = dataclasses.replace(
    Gemma3TextConfig.tiny(vocab_size=199), hidden_size=48, head_dim=12,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    num_hidden_layers=4, sliding_window=6, sliding_window_pattern=3)

FAMS = {
    "gpt2": (GPT2_CFG, gpt2.init_params,
             lambda seed: init_lora_gpt2(GPT2_CFG, LoRASpec(rank=3,
                                                            alpha=6.0),
                                         jax.random.PRNGKey(seed))),
    "gemma": (GEMMA_CFG, gemma3.init_params,
              lambda seed: init_lora_gemma3(GEMMA_CFG,
                                            LoRASpec(rank=3, alpha=6.0),
                                            jax.random.PRNGKey(seed))),
}


def rand_lora(family, seed, scale=0.05):
    lora = FAMS[family][2](seed)
    leaves, td = jax.tree.flatten(lora)
    keys = jax.random.split(jax.random.PRNGKey(seed + 50), len(leaves))
    return jax.tree.unflatten(td, [
        l if l.ndim == 0 else scale * jax.random.normal(k, l.shape)
        for l, k in zip(leaves, keys)])


@pytest.fixture(scope="module")
def params():
    return {f: FAMS[f][1](FAMS[f][0], jax.random.PRNGKey(0))
            for f in FAMS}


def run_engine(family, params, mesh, attn_impl="auto"):
    """One full serve session: two adapters resident, a mixed
    base+adapter wave, then a hot-swap + second wave that must add
    ZERO traces. Returns (tokens by submit order, post-warmup traces,
    health snapshot)."""
    dp, tp = mesh
    cfg = ServeConfig(num_slots=4, block_T=8, num_blocks=32,
                      max_prompt=16, max_new_tokens=8,
                      attn_impl=attn_impl, mesh_dp=dp, mesh_tp=tp)
    bank = AdapterBank(rand_lora(family, 5), capacity=2)
    eng = ServeEngine(family, FAMS[family][0], params[family], cfg,
                      bank=bank)
    try:
        eng.load_adapter("a", rand_lora(family, 7))
        eng.load_adapter("b", rand_lora(family, 8))
        rng = np.random.default_rng(0)
        vocab = 211 if family == "gpt2" else 199
        reqs = []
        for i, ad in enumerate([None, "a", "b", None, "a"]):
            p = rng.integers(1, vocab, size=4 + 2 * i).tolist()
            reqs.append(eng.submit(p, adapter=ad))
        eng.drain()
        warm = eng.total_traces()
        # hot-swap "a" in place + a second wave: the swap is one traced
        # at[slot].set on sharding-stable buffers — no new executables
        eng.load_adapter("a", rand_lora(family, 9))
        for i, ad in enumerate([None, "b", "a"]):
            p = rng.integers(1, vocab, size=5 + i).tolist()
            reqs.append(eng.submit(p, adapter=ad))
        eng.drain()
        retraces = eng.total_traces() - warm
        health = eng.health()
    finally:
        eng.close()
    return [list(r.tokens) for r in reqs], retraces, health


@pytest.fixture(scope="module")
def baseline(params):
    """Single-chip (1, 1) engine outputs — what every mesh must match."""
    return {f: run_engine(f, params, (1, 1))[0] for f in FAMS}


# ------------------------- the parity acceptance -------------------------

@pytest.mark.parametrize("mesh", [
    (1, 2), (1, 4),
    # the dp > 1 cells ride the full acceptance matrix, not the
    # budgeted tier-1 run
    pytest.param((2, 2), marks=pytest.mark.slow)])
@pytest.mark.parametrize("family", ["gpt2", "gemma"])
def test_sharded_engine_token_parity(family, mesh, params, baseline):
    """Sharded decode == single-chip decode, token for token: mixed
    base+adapter routing, gemma's sliding-window layers engaged
    (window 6 < prompt+gen), a mid-session hot-swap — and ZERO
    post-warmup retraces at every mesh shape. The bank's block-diagonal
    layout is pure PLACEMENT (dense math), so parity is exact, not
    approximate."""
    got, retraces, health = run_engine(family, params, mesh)
    assert retraces == 0, f"{family} {mesh}: {retraces} post-warmup traces"
    assert health["mesh"] == list(mesh)
    for i, (g, want) in enumerate(zip(got, baseline[family])):
        assert g == want, f"{family} {mesh} req {i}: {g} != {want}"


def test_sharded_pallas_path_token_parity(params, baseline):
    """attn_impl=pallas under the mesh: sharded_paged_attend wraps the
    unchanged kernel in shard_map over per-shard pool slices (interpret
    mode on CPU) — still token-identical to the single-chip xla path."""
    got, retraces, _ = run_engine("gpt2", params, (1, 2),
                                  attn_impl="pallas")
    assert retraces == 0
    assert got == baseline["gpt2"]


# ------------------------- placement unit tests --------------------------

def test_shard_heads_axis_choice():
    """KV divisible -> pool shards; else GQA groups shard; else heads
    replicate. This is the ONE head-axis decision (ops + sharding +
    eligibility all consult it)."""
    assert shard_heads(8, 1, 1) == (8, 1)          # tp=1: identity
    assert shard_heads(8, 1, 4) == (2, 1)          # KV shards
    assert shard_heads(2, 2, 2) == (1, 2)          # KV wins when both fit
    assert shard_heads(2, 4, 4) == (2, 1)          # groups shard
    assert shard_heads(2, 2, 4) == (2, 2)          # neither: replicate
    assert shard_heads(8, 1, 0) == (8, 1)          # tp=None/0 tolerated


def test_vmem_gates_charge_per_shard_head_counts():
    """A shape whose GLOBAL K/V pages overflow the VMEM budget must
    still pass the gate at tp=4 when each shard streams only KV/tp
    heads — otherwise the Pallas path would falsely gate off exactly
    as tp grows (the regression this pins)."""
    KV, G, bT, D = 8, 1, 8, 16384
    assert not paged_eligible(KV, G, bT, D, itemsize=4)
    assert paged_eligible(KV, G, bT, D, itemsize=4, tp=4)
    # pick_kvb's divisor search runs over the LOCAL head count
    assert pick_kvb(8, T=128, D=512, itemsize=4) == 8
    assert pick_kvb(8, T=128, D=512, itemsize=4, tp=4) == 2
    # indivisible heads replicate: per-shard bill == global bill
    assert paged_eligible(3, 1, bT, 64, itemsize=4, tp=2) == \
        paged_eligible(3, 1, bT, 64, itemsize=4)


def test_serve_sharding_build_and_validation():
    sh = ServeSharding.build("gemma", GEMMA_CFG, 1, 2)
    assert (sh.kv_shards, sh.g_shards) == (2, 1)    # KV=2 shards at tp=2
    sh4 = ServeSharding.build("gemma", GEMMA_CFG, 1, 4)
    assert (sh4.kv_shards, sh4.g_shards) == (1, 1)  # nq=4, kv=2: replicate
    shg = ServeSharding.build("gpt2", GPT2_CFG, 1, 4)
    assert (shg.kv_shards, shg.g_shards) == (4, 1)  # MHA: KV == nq shards
    with pytest.raises(ValueError, match="query-head"):
        ServeSharding.build("gpt2", GPT2_CFG, 1, 3)  # 4 % 3 != 0
    with pytest.raises(ValueError, match="family"):
        ServeSharding.build("bert", GPT2_CFG, 1, 2)
    with pytest.raises(ValueError, match=">= 1"):
        make_serve_mesh(0, 2)


def test_serve_config_mesh_validation(params):
    with pytest.raises(ValueError, match="mesh"):
        ServeConfig(mesh_dp=0).validate()
    with pytest.raises(ValueError, match="num_slots"):
        ServeConfig(num_slots=3, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=8, mesh_dp=2, mesh_tp=1).validate()
    with pytest.raises(ValueError, match="query-head"):
        ServeEngine("gpt2", GPT2_CFG, params["gpt2"],
                    ServeConfig(num_slots=3, block_T=8, num_blocks=32,
                                max_prompt=16, max_new_tokens=8,
                                mesh_dp=1, mesh_tp=3))


def test_bank_block_diagonal_placement():
    """The stacked [k, ...] bank shards each factor on its MODEL axis
    only — B on d_out where the layer is column-parallel, A on d_in
    where it is row-parallel — and never on the adapter axis, so the
    hot-swap at[slot].set is shard-local (no resharding collective)."""
    sh = ServeSharding.build("gpt2", GPT2_CFG, 1, 2)
    bank = AdapterBank(rand_lora("gpt2", 3), capacity=2)
    shardings = sh.bank_shardings(bank.tree)
    flat = jax.tree_util.tree_flatten_with_path(shardings)[0]
    saw_sharded = 0
    for path, ns in flat:
        keys = [getattr(p, "key", getattr(p, "name", None)) for p in path]
        axes = [a for a in ns.spec if a is not None]
        assert len(axes) <= 1
        if axes:
            saw_sharded += 1
            # leading (adapter-slot) axis always replicated
            assert ns.spec[0] is None
    assert saw_sharded > 0
    # placing + swapping keeps the committed shardings stable
    bank.place(shardings, sh.put_repl)
    before = jax.tree.map(lambda a: a.sharding, bank.tree)
    bank.load("t", rand_lora("gpt2", 4))
    after = jax.tree.map(lambda a: a.sharding, bank.tree)
    assert jax.tree.all(jax.tree.map(lambda x, y: x == y, before, after))
