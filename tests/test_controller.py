"""Fleet controller e2e (DESIGN.md §18): the acceptance loop — kill a
simulated worker mid-run, the controller restarts it, training resumes
from the last ATOMIC checkpoint with the correct step counter, and the
merged telemetry carries the `controller` recovery timeline that
fleet_report renders next to the goodput buckets. Plus: the mesh-shrink
relaunch on a lost worker, the one-SIGTERM fleet drain, and the
--dry_run decision contract over recorded incident shards."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

from mobilefinetuner_tpu.core.preempt import EXIT_PREEMPTED
from mobilefinetuner_tpu.core.telemetry import (Telemetry, controller_path,
                                                validate_event)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

CONTROLLER = os.path.join(REPO, "tools", "fleet_controller.py")
SMOKE = os.path.join(REPO, "tools", "multihost_smoke.py")


def _env():
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    env["JAX_PLATFORMS"] = "cpu"
    return env


def read_events(path):
    out = []
    with open(path) as f:
        for line in f.read().splitlines():
            if line.strip():
                try:
                    out.append(json.loads(line))
                except json.JSONDecodeError:
                    pass  # a killed worker's truncated tail is expected
    return out


def _worker_cmd(tmp_path, steps, extra=""):
    return (f"{sys.executable} {SMOKE} --sim_worker --host {{host}} "
            f"--hosts {{hosts}} --steps {steps} "
            f"--telemetry {tmp_path}/run.jsonl "
            f"--ckpt {tmp_path}/w{{host}}.safetensors "
            f"--step_ms 25 {{resume}} {extra}")


def _run_controller(tmp_path, cmd, hosts=2, budget=2, extra=()):
    return subprocess.run(
        [sys.executable, CONTROLLER, "--hosts", str(hosts),
         "--telemetry", str(tmp_path / "run.jsonl"),
         "--restart_budget", str(budget), "--backoff_s", "0.1",
         "--max_wall_s", "120", "--cmd", cmd, *extra],
        capture_output=True, text=True, env=_env(), cwd=REPO,
        timeout=180)


# --------------------------- injected-failure e2e ---------------------------

def test_controller_restarts_killed_worker_e2e(tmp_path):
    """The acceptance criterion: worker 1 is hard-killed at step 4; the
    controller restarts it; the relaunched worker resumes from the
    atomic checkpoint at step 4 and completes steps 5..10 — the merged
    trajectory covers exactly 1..10 with no replays — and the
    controller stream records down+restart with recovery accounting."""
    # only worker 1 carries the fault: worker 0's marker pre-exists
    open(str(tmp_path / "w0.safetensors.injected"), "w").write("off")
    r = _run_controller(tmp_path,
                        _worker_cmd(tmp_path, 10, "--inject kill:4"))
    assert r.returncode == 0, (r.stdout, r.stderr)

    # worker 1's shard: two runs appended (crash + resumed), the merged
    # step sequence is exactly 1..10 — the step counter survived the
    # restart because the checkpoint carried it
    shard1 = read_events(str(tmp_path / "run.jsonl.host1"))
    assert [e["event"] for e in shard1].count("run_start") == 2
    steps = [e["step"] for e in shard1 if e["event"] == "step_stats"]
    assert steps == list(range(1, 11))
    assert shard1[-1]["event"] == "run_end" \
        and shard1[-1]["exit"] == "ok"
    second_start = [e for e in shard1 if e["event"] == "run_start"][1]
    assert second_start["config"]["start_step"] == 4  # resumed, not 0

    # the controller timeline: down + restart for worker 1 only, with
    # recovery accounting; every event schema-valid
    ctrl = read_events(controller_path(str(tmp_path / "run.jsonl")))
    for e in ctrl:
        assert validate_event(e) is None, (e, validate_event(e))
    acts = [(e["action"], e.get("worker")) for e in ctrl]
    assert ("down", 1) in acts and ("restart", 1) in acts
    assert ("down", 0) not in acts
    restart = next(e for e in ctrl if e["action"] == "restart")
    assert restart["reason"] == "exit:86"
    assert restart["attempt"] == 1 and restart["recovery_s"] > 0
    assert acts[-1] == ("stop", None)

    # fleet_report renders the recovery next to the goodput buckets
    import fleet_report
    from telemetry_report import load_events
    shards = {h: load_events(p) for h, p in
              fleet_report.discover_shards(
                  str(tmp_path / "run.jsonl")).items()}
    ctrl_events, _ = load_events(
        controller_path(str(tmp_path / "run.jsonl")))
    s = fleet_report.fleet_summary(shards, controller=ctrl_events)
    assert s["controller"]["restarts"] == 1
    assert s["controller"]["recovery_s"] > 0
    assert fleet_report.main([str(tmp_path / "run.jsonl")]) == 0

    # and the dry-run replay of the RESOLVED incident decides "none"
    import fleet_controller
    d = fleet_controller.decide_worker(shards[1][0])
    assert d["decision"] == "none" and d["reason"] == "ok"


def test_controller_restarts_hung_worker_exit113(tmp_path):
    """hang:<step> = the watchdog abort path: durable `hang` event,
    exit 113 — the controller restarts with reason=hang."""
    open(str(tmp_path / "w0.safetensors.injected"), "w").write("off")
    r = _run_controller(tmp_path,
                        _worker_cmd(tmp_path, 8, "--inject hang:3"))
    assert r.returncode == 0, (r.stdout, r.stderr)
    shard1 = read_events(str(tmp_path / "run.jsonl.host1"))
    assert any(e["event"] == "hang" for e in shard1)
    steps = [e["step"] for e in shard1 if e["event"] == "step_stats"]
    assert steps == list(range(1, 9))
    ctrl = read_events(controller_path(str(tmp_path / "run.jsonl")))
    restart = next(e for e in ctrl if e["action"] == "restart")
    assert restart["worker"] == 1 and restart["reason"] == "hang"


# --------------------------- shrink on lost worker --------------------------

def test_controller_shrinks_fleet_on_lost_worker(tmp_path):
    """Budget 0 + --allow_shrink: worker 0's kill makes it LOST; the
    controller drains worker 1 (preemption drain — its shard ends with
    run_end{reason=preempted} mid-fleet), relaunches it at hosts-1 with
    resume, and the survivor completes from its drain checkpoint."""
    open(str(tmp_path / "w1.safetensors.injected"), "w").write("off")
    r = _run_controller(tmp_path,
                        _worker_cmd(tmp_path, 10, "--inject kill:4"),
                        budget=0, extra=("--allow_shrink",))
    assert r.returncode == 0, (r.stdout, r.stderr)
    ctrl = read_events(controller_path(str(tmp_path / "run.jsonl")))
    acts = [e["action"] for e in ctrl]
    assert "lost" in acts and "shrink" in acts and "restart" not in acts
    shrink = next(e for e in ctrl if e["action"] == "shrink")
    assert shrink["worker"] == 0 and shrink["recovery_s"] > 0
    # the survivor: drained mid-fleet, then resumed to completion
    shard1 = read_events(str(tmp_path / "run.jsonl.host1"))
    ends = [e for e in shard1 if e["event"] == "run_end"]
    assert ends[0]["reason"] == "preempted"  # the shrink drain
    assert ends[-1]["exit"] == "ok"
    steps = [e["step"] for e in shard1 if e["event"] == "step_stats"]
    assert steps == list(range(1, 11))  # no replayed or lost steps


# --------------------------- fleet drain on SIGTERM -------------------------

def test_controller_sigterm_drains_whole_fleet(tmp_path):
    p = subprocess.Popen(
        [sys.executable, CONTROLLER, "--hosts", "2",
         "--telemetry", str(tmp_path / "run.jsonl"),
         "--max_wall_s", "120",
         "--cmd", _worker_cmd(tmp_path, 400)],
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True,
        env=_env(), cwd=REPO)
    try:
        deadline = time.time() + 60
        shard = str(tmp_path / "run.jsonl")
        while time.time() < deadline:
            if os.path.exists(shard) \
                    and "step_stats" in open(shard).read():
                break
            time.sleep(0.1)
        p.send_signal(signal.SIGTERM)
        out, _ = p.communicate(timeout=60)
    finally:
        if p.poll() is None:
            p.kill()
    assert p.returncode == 0, out
    # every worker drained with the resumable contract
    for h, path in ((0, shard), (1, shard + ".host1")):
        recs = read_events(path)
        end = recs[-1]
        assert end["event"] == "run_end" \
            and end["reason"] == "preempted", (h, end)
        assert any(e["event"] == "preempt" for e in recs)
    ctrl = read_events(controller_path(shard))
    acts = [e["action"] for e in ctrl]
    assert "drain" in acts and acts[-1] == "stop"


# --------------------------- dry-run decision contract ----------------------

def test_dry_run_decisions_over_recorded_incidents(tmp_path, capsys):
    """--dry_run replays a recorded shard set through the SAME decision
    function the live policy uses and prints one decision per worker:
    ok->none, truncated->restart(crash), hang->restart(hang),
    preempted->resume."""
    base = str(tmp_path / "inc.jsonl")
    manifest = dict(jax_version="sim", mesh_shape=None, process_count=4,
                    process_index=0, device_kind="sim-cpu",
                    device_count=4, config={})
    ss = dict(loss=3.0, ema=3.0, lr=1e-4, grad_norm=0.5,
              step_time_ms=10.0, host_wait_ms=0.0, slept_ms=0.0,
              tok_s=100.0, mfu=None, param_norm=None, update_ratio=None,
              nonfinite_count=None, hbm_mb=0.0, queue_depth=None,
              host_step_ms=None)
    # host 0: clean completion
    with Telemetry(base, host=0) as tel:
        tel.emit("run_start", **manifest)
        tel.emit("step_stats", step=6, **ss)
        tel.emit("run_end", steps=6, wall_s=1.0, exit="ok", goodput=None)
    # host 1: SIGKILLed (truncated — no run_end)
    with Telemetry(base + ".host1", host=1) as tel:
        tel.emit("run_start", **manifest)
        tel.emit("step_stats", step=4, **ss)
    # host 2: watchdog hang fired, process wedged (no run_end)
    with Telemetry(base + ".host2", host=2) as tel:
        tel.emit("run_start", **manifest)
        tel.emit("step_stats", step=5, **ss)
        tel.emit("hang", step=5, stall_s=120.0, deadline_s=60.0,
                 stacks_file="", device_probe="timeout", action="abort")
    # host 3: preemption-drained
    with Telemetry(base + ".host3", host=3) as tel:
        tel.emit("run_start", **manifest)
        tel.emit("step_stats", step=3, **ss)
        tel.emit("preempt", step=4, signal="SIGTERM")
        tel.emit("run_end", steps=4, wall_s=1.0, exit="preempted",
                 goodput=None, reason="preempted")
    import fleet_controller
    assert fleet_controller.main(["--telemetry", base, "--dry_run"]) == 0
    out = capsys.readouterr().out.splitlines()
    assert "DRYRUN worker=0 decision=none reason=ok step=6" in out[0]
    assert "DRYRUN worker=1 decision=restart reason=crash step=4" in out[1]
    assert "DRYRUN worker=2 decision=restart reason=hang step=5" in out[2]
    assert ("DRYRUN worker=3 decision=resume reason=preempted step=3"
            in out[3])


# --------------------------- review-fix regressions -------------------------

def test_preempted_worker_resumes_without_burning_budget(tmp_path,
                                                         monkeypatch):
    """A worker exit-75 OUTSIDE a controller drain (the platform
    preempted it directly) is a clean resume — scheduled relaunch,
    reason=preempted, restart budget untouched — matching what
    decide_worker says about the same shard."""
    import argparse
    import fleet_controller
    args = argparse.Namespace(
        telemetry=str(tmp_path / "r.jsonl"), cmd="true", hosts=1,
        restart_budget=1, backoff_s=0.01, resume_flags="--resume",
        resume_first=False, allow_shrink=False, min_hosts=1,
        kill_on_hang=1, drain_timeout_s=1.0, poll_s=0.01,
        max_wall_s=0.0)
    fc = fleet_controller.FleetController(args)
    fc.guard.uninstall()  # unit test: no signal handlers left behind
    spawned = []
    monkeypatch.setattr(fc, "spawn", lambda w: spawned.append(w.host))
    w = fc.workers[0]
    fc.handle_exit(w, EXIT_PREEMPTED)
    assert w.attempts == 0          # no budget burned
    assert not w.lost and not w.done
    assert w.relaunch_at is not None and w.down_reason == "preempted"
    time.sleep(0.02)
    fc.maybe_relaunch(w)
    assert spawned == [0] and w.restarted
    # a real crash afterwards still burns budget exactly once
    fc.handle_exit(w, 86)
    assert w.attempts == 1 and w.relaunch_at is not None
    fc.tel.close()
    ctrl = read_events(controller_path(str(tmp_path / "r.jsonl")))
    acts = [(e["action"], e.get("reason")) for e in ctrl]
    assert ("down", "preempted") in acts
    assert ("restart", "preempted") in acts
    restart = next(e for e in ctrl if e["action"] == "restart")
    assert restart["attempt"] is None  # unbudgeted resume


def test_shard_tail_ignores_preexisting_history(tmp_path):
    """The live tail starts at END of file: a previous session's hang
    events must not SIGKILL a freshly launched healthy worker (history
    belongs to --dry_run, not the live policy)."""
    import fleet_controller
    path = str(tmp_path / "old.jsonl")
    with Telemetry(path, host=0) as tel:
        tel.emit("step_stats", step=9, loss=3.0, ema=3.0, lr=1e-4,
                 grad_norm=0.5, step_time_ms=10.0, host_wait_ms=0.0,
                 slept_ms=0.0, tok_s=100.0, mfu=None, param_norm=None,
                 update_ratio=None, nonfinite_count=None, hbm_mb=0.0,
                 queue_depth=None, host_step_ms=None)
        tel.emit("hang", step=9, stall_s=120.0, deadline_s=60.0,
                 stacks_file="", device_probe="timeout", action="abort")
    tail = fleet_controller.ShardTail(path)
    tail.poll()
    assert tail.hangs == 0 and tail.last_step is None  # history skipped
    with Telemetry(path, host=0) as tel:  # the NEW session's events
        tel.emit("hang", step=12, stall_s=90.0, deadline_s=60.0,
                 stacks_file="", device_probe="ok", action="continue")
    tail.poll()
    assert tail.hangs == 1  # live events still observed


def test_controller_summary_scopes_to_latest_session():
    """Recovery accounting over an appended controller stream counts
    only the latest session — a prior run's restarts must not inflate
    this run's recovery line."""
    from telemetry_report import controller_entries, controller_summary
    mk = lambda seq, **kw: {"event": "controller", "seq": seq, "t": float(seq),
                            "action": kw.pop("action"),
                            "worker": kw.pop("worker", None),
                            "reason": kw.pop("reason", None),
                            "attempt": kw.pop("attempt", None),
                            "backoff_s": None,
                            "step": None,
                            "recovery_s": kw.pop("recovery_s", None)}
    events = [
        # session 1: two restarts, closed with stop
        mk(0, action="launch", worker=0),
        mk(1, action="restart", worker=0, recovery_s=5.0),
        mk(2, action="restart", worker=0, recovery_s=5.0),
        mk(3, action="stop"),
        # session 2 (latest): one restart
        mk(4, action="launch", worker=0),
        mk(5, action="restart", worker=0, recovery_s=1.25),
        mk(6, action="stop"),
    ]
    s = controller_summary(controller_entries(events))
    assert s["restarts"] == 1
    assert s["recovery_s"] == pytest.approx(1.25)
    # a live (unterminated) latest session scopes the same way
    s2 = controller_summary(controller_entries(events[:6]))
    assert s2["restarts"] == 1 and s2["recovery_s"] == pytest.approx(1.25)
    # a SIGKILLed session 1 (no stop/give_up ever written) must not
    # bleed into session 2 either: sessions are delimited by the
    # launch burst, not just terminators
    no_term = [e for e in events if e["seq"] != 3]
    s3 = controller_summary(controller_entries(no_term))
    assert s3["restarts"] == 1 and s3["recovery_s"] == pytest.approx(1.25)


# --------------------------- sim-kill fixture dry run -----------------------

def test_dry_run_contract_against_simulated_kill_shards(tmp_path):
    """The dry run replayed against REAL sim-worker output: run the kill
    fixture to its crash (no controller), then assert the dry-run
    decision is restart/crash with the last checkpointed step."""
    r = subprocess.run(
        [sys.executable, SMOKE, "--sim_worker", "--host", "0",
         "--hosts", "1", "--steps", "10",
         "--telemetry", str(tmp_path / "k.jsonl"),
         "--ckpt", str(tmp_path / "k.safetensors"),
         "--step_ms", "5", "--inject", "kill:3"],
        capture_output=True, text=True, env=_env(), cwd=REPO,
        timeout=60)
    assert r.returncode == 86  # the hard-kill exit
    import fleet_controller
    from telemetry_report import load_events
    events, _ = load_events(str(tmp_path / "k.jsonl"))
    d = fleet_controller.decide_worker(events)
    assert d == {"decision": "restart", "reason": "crash", "step": 3}
