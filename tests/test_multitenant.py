"""Multi-tenant LoRA training engine tests (multitenant/, DESIGN.md §23).

The correctness anchor is the K-VS-SOLO PARITY ORACLE: each adapter job
trained in the fused k-tenant step — stacked bank, ids-routed forward,
per-slot Adam/LR/clip — must match a solo single-adapter run on the same
data/seed to <= 1e-5, in per-step loss trajectory AND final saved
weights, for both model families. And the COMPILE-STABILITY invariant
(the r11 serve discipline applied to training): after warmup, tenant
admission, completion, slot refill, and early cancellation add ZERO new
traces — tenancy is data."""

import dataclasses
import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.core.telemetry import Telemetry, validate_event
from mobilefinetuner_tpu.lora import peft_io
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                           init_lora_gpt2, stack_adapters,
                                           trainable_mask, unstack_adapter)
from mobilefinetuner_tpu.models import gemma3, gpt2
from mobilefinetuner_tpu.multitenant import (EngineConfig, JobSpec,
                                             MultiTenantEngine, TenantMux,
                                             load_jobs_file, parse_jobs)
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

GPT2_CFG = dataclasses.replace(
    GPT2Config.tiny(vocab_size=211), n_embd=32, n_head=2, n_positions=64,
    n_layer=2, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)
GEMMA_CFG = dataclasses.replace(
    Gemma3TextConfig.tiny(vocab_size=199), hidden_size=48, head_dim=12,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    num_hidden_layers=2, sliding_window=6, sliding_window_pattern=3)
S = 32
B = 2


@pytest.fixture(scope="module")
def gpt2_params():
    return gpt2.init_params(GPT2_CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def gemma_params():
    return gemma3.init_params(GEMMA_CFG, jax.random.PRNGKey(1))


def stream_batches(seed, n, vocab=199, b=B, s=S):
    """n deterministic [b, s] step batches — the SAME list feeds the
    solo oracle and the engine (make_stream below), so per-tenant data
    order is identical by construction."""
    rng = np.random.default_rng(seed)
    out = []
    for _ in range(n):
        ids = rng.integers(1, vocab, (b, s)).astype(np.int32)
        out.append({"input_ids": ids,
                    "attention_mask": np.ones((b, s), np.float32),
                    "labels": ids.copy()})
    return out


def make_stream_factory(n=64, vocab=199):
    def make_stream(spec):
        return iter(stream_batches(spec.data_seed, n, vocab=vocab))
    return make_stream


def solo_train(family, config, params, job, schedule="cosine"):
    """The oracle: a solo single-adapter run with the CLI loss shape
    (full-logits CE), same init seed, same data stream, same hparams.
    Returns (per-step losses, final host adapter tree)."""
    fwd = gpt2.forward if family == "gpt2" else gemma3.forward
    spec = LoRASpec(rank=job.rank, alpha=job.alpha,
                    init="gpt2" if family == "gpt2" else "peft")
    lora = init_lora_gpt2(config, spec, jax.random.PRNGKey(job.seed)) \
        if family == "gpt2" else \
        init_lora_gemma3(config, spec, jax.random.PRNGKey(job.seed))
    mask = trainable_mask(lora)
    tc = TrainConfig(total_steps=job.steps, lr=job.lr,
                     warmup_ratio=job.warmup_ratio, schedule=schedule,
                     clip_grad_norm=1.0)

    def loss_fn(l, p, mb):
        logits = fwd(config, p, mb["input_ids"],
                     attention_mask=mb["attention_mask"], lora=l,
                     compute_dtype=jnp.float32)
        return lm_cross_entropy_sum(logits, mb["labels"])

    step = make_train_step(loss_fn, tc, mask=mask, donate=False)
    opt = init_optimizer(lora, tc, mask)
    batches = stream_batches(job.data_seed, job.steps)
    losses = []
    for s in range(job.steps):
        lora, opt, m = step(lora, params, opt, batches[s], jnp.int32(s))
        losses.append(float(m["loss"]))
    return losses, jax.device_get(lora)


def run_engine(family, config, params, jobs, slots=2, tmp_path=None,
               telemetry=None, flush_every=1, schedule="cosine",
               prefetch=0):
    cfg = EngineConfig(slots=slots, rows_per_tenant=B, seq_len=S,
                       flush_every=flush_every, schedule=schedule,
                       prefetch=prefetch,
                       out_dir=str(tmp_path) if tmp_path else "")
    eng = MultiTenantEngine(family, config, params, jobs,
                            make_stream_factory(), cfg,
                            telemetry=telemetry)
    return eng


# --------------------------- jobspec --------------------------------------

def test_jobspec_parse_and_validation(tmp_path):
    doc = {"family": "gpt2",
           "defaults": {"rank": 4, "steps": 10},
           "jobs": [{"name": "a", "lr": 1e-4, "seed": 1},
                    {"name": "b", "lr": 3e-4, "alpha": 32.0}]}
    fam, jobs = parse_jobs(doc)
    assert fam == "gpt2" and [j.name for j in jobs] == ["a", "b"]
    assert jobs[0].rank == 4 and jobs[1].steps == 10   # defaults merged
    assert jobs[1].alpha == 32.0                       # per-job override
    # JSON file round trip
    p = tmp_path / "jobs.json"
    p.write_text(json.dumps(doc))
    fam2, jobs2 = load_jobs_file(str(p))
    assert fam2 == fam and [j.name for j in jobs2] == ["a", "b"]
    # TOML round trip
    t = tmp_path / "jobs.toml"
    t.write_text('family = "gpt2"\n[defaults]\nrank = 4\n'
                 '[[jobs]]\nname = "a"\n[[jobs]]\nname = "b"\n')
    fam3, jobs3 = load_jobs_file(str(t))
    assert fam3 == "gpt2" and jobs3[1].rank == 4
    # the stacked-bank constraints raise NAMING the offender
    with pytest.raises(ValueError, match="rank"):
        parse_jobs({"jobs": [{"name": "a", "rank": 4},
                             {"name": "b", "rank": 8}]})
    with pytest.raises(ValueError, match="duplicate"):
        parse_jobs({"jobs": [{"name": "a"}, {"name": "a"}]})
    with pytest.raises(ValueError, match="unknown field"):
        parse_jobs({"jobs": [{"name": "a", "learning_rate": 1e-4}]})
    with pytest.raises(ValueError, match="family"):
        parse_jobs({"family": "bert", "jobs": [{"name": "a"}]})
    with pytest.raises(ValueError, match="non-empty"):
        parse_jobs({"jobs": []})
    # per-job save-path resolution
    assert jobs[0].resolved_save_path("/out") == "/out/a.safetensors"
    j = JobSpec(name="x", save_path="/tmp/custom.st")
    assert j.resolved_save_path("/out") == "/tmp/custom.st"


# --------------------------- the parity oracle -----------------------------

@pytest.mark.parametrize("family", ["gpt2", "gemma"])
def test_k_adapter_matches_solo_run(family, gpt2_params, gemma_params,
                                    tmp_path):
    """THE acceptance oracle: two tenants with different LR/alpha/
    warmup/seeds trained in ONE fused step match their solo runs on the
    same data/seed — per-step loss trajectory AND final saved adapter
    weights within 1e-5, both families."""
    config = GPT2_CFG if family == "gpt2" else GEMMA_CFG
    params = gpt2_params if family == "gpt2" else gemma_params
    # gemma's per-row-gather einsum order differs from the solo shared-A
    # contraction at the LSB, and early-step Adam (v ~ g^2) amplifies
    # grad LSB noise proportionally to lr — the gentler gemma LRs keep
    # the 5-step accumulated drift under the 1e-5 bar the oracle pins
    # (the TRAJECTORY parity below is lr-independent at 1e-5 for both)
    lr_a, lr_b = (1e-3, 3e-3) if family == "gpt2" else (3e-4, 1e-3)
    jobs = [JobSpec(name="a", lr=lr_a, alpha=16.0, steps=5, seed=1,
                    data_seed=101, warmup_ratio=0.2),
            JobSpec(name="b", lr=lr_b, alpha=32.0, steps=5, seed=2,
                    data_seed=102)]
    eng = run_engine(family, config, params, jobs, tmp_path=tmp_path)
    eng.admit_pending()
    hist = {"a": [], "b": []}
    for _ in range(5):
        eng.step()
        for n in hist:
            hist[n].append(eng.tenants[n].last_loss)
    eng.close()
    for job in jobs:
        solo_losses, solo_tree = solo_train(family, config, params, job)
        mt_losses = hist[job.name]
        for s, (a, b) in enumerate(zip(solo_losses, mt_losses)):
            assert abs(a - b) <= 1e-5, \
                (job.name, s, a, b, "loss trajectory diverged")
        saved, sspec = peft_io.load_adapter(
            str(tmp_path / f"{job.name}.safetensors"))
        assert sspec.rank == job.rank and sspec.alpha == job.alpha
        for tgt, entry in saved["blocks"].items():
            for leaf in ("A", "B"):
                got = np.asarray(entry[leaf])
                want = np.asarray(solo_tree["blocks"][tgt][leaf])
                assert np.max(np.abs(got - want)) <= 1e-5, \
                    (job.name, tgt, leaf, "final weights diverged")


# --------------------------- compile stability -----------------------------

def test_zero_retraces_across_join_leave_refill_cancel(gpt2_params,
                                                       tmp_path):
    """THE compile-stability acceptance: after warmup (first step + the
    first jitted slot write), job completion, pending-queue refill into
    the freed slot, AND early cancellation add ZERO new traces —
    tenancy changes are data (the r11 trace_counts pin, applied to the
    train side)."""
    jobs = [JobSpec(name="a", lr=1e-3, steps=6, seed=1, data_seed=11),
            JobSpec(name="b", lr=2e-3, steps=2, seed=2, data_seed=12),
            JobSpec(name="c", lr=3e-3, steps=3, seed=3, data_seed=13),
            JobSpec(name="d", lr=1e-3, steps=9, seed=4, data_seed=14)]
    eng = run_engine("gpt2", GPT2_CFG, gpt2_params, jobs, slots=2,
                     tmp_path=tmp_path, flush_every=4)
    eng.admit_pending()
    eng.step()                       # warmup: one step + one admit trace
    warm = eng.total_traces()
    assert warm >= 2                 # the step and the slot writer
    eng.step()                       # b finishes at 2 -> c refills slot 1
    assert eng.tenants["b"].status == "finished"
    assert eng.tenants["c"].status == "active"
    for _ in range(3):
        eng.step()                   # c finishes -> d refills
    assert eng.tenants["c"].status == "finished"
    eng.cancel("d")                  # early cancel mid-flight
    assert eng.tenants["d"].status == "cancelled"
    while eng._has_work():
        eng.step()
    assert eng.tenants["a"].status == "finished"
    assert eng.total_traces() - warm == 0, dict(eng.trace_counts)
    eng.close()
    # every finished tenant saved; the cancelled one did not
    assert (tmp_path / "a.safetensors").exists()
    assert (tmp_path / "b.safetensors").exists()
    assert (tmp_path / "c.safetensors").exists()
    assert not (tmp_path / "d.safetensors").exists()


# --------------------------- stack/unstack round trip ----------------------

def test_unstack_peft_roundtrip_byte_identical(tmp_path):
    """Satellite: an adapter sliced out of a stacked [k, ...] bank
    (lora.unstack_adapter) saves BYTE-IDENTICAL to the solo layout —
    native safetensors file AND the PEFT export directory — so every
    downstream consumer (serve, eval, HF PEFT) is agnostic to where the
    adapter trained."""
    spec = LoRASpec(rank=4, alpha=8.0)
    adapters = [init_lora_gpt2(GPT2_CFG, spec, jax.random.PRNGKey(i))
                for i in range(3)]
    stacked = jax.device_get(stack_adapters(adapters))
    for i, solo in enumerate(adapters):
        solo_path = str(tmp_path / f"solo{i}.safetensors")
        bank_path = str(tmp_path / f"bank{i}.safetensors")
        peft_io.save_adapter(solo_path, jax.device_get(solo), spec)
        peft_io.save_adapter(bank_path, unstack_adapter(stacked, i),
                             spec)
        assert open(solo_path, "rb").read() == \
            open(bank_path, "rb").read(), f"adapter {i} bytes differ"
        # PEFT export layout round trip too
        d_solo = str(tmp_path / f"peft_solo{i}")
        d_bank = str(tmp_path / f"peft_bank{i}")
        peft_io.export_peft(d_solo, jax.device_get(solo), spec, "gpt2")
        peft_io.export_peft(d_bank, unstack_adapter(stacked, i), spec,
                            "gpt2")
        fa = open(os.path.join(d_solo,
                               "adapter_model.safetensors"), "rb").read()
        fb = open(os.path.join(d_bank,
                               "adapter_model.safetensors"), "rb").read()
        assert fa == fb
    # index validation names the bank size
    with pytest.raises(ValueError, match="out of range"):
        unstack_adapter(stacked, 3)


# --------------------------- train -> serve handoff ------------------------

def test_train_serve_handoff_token_identical(gpt2_params, tmp_path):
    """Satellite e2e: train 2 tiny adapters in the multitenant engine,
    hot-load the saved files into serve's AdapterBank via load_file
    (manifest-VERIFIED — the r15 integrity contract), and serve both
    tenants in one engine: greedy outputs token-identical to
    batch-at-a-time generate() with the solo-trained weights."""
    from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                     gpt2_generate)
    from mobilefinetuner_tpu.serve import (AdapterBank, ServeConfig,
                                           ServeEngine)
    jobs = [JobSpec(name="t1", lr=5e-3, steps=4, seed=1, data_seed=21),
            JobSpec(name="t2", lr=8e-3, steps=4, seed=2, data_seed=22)]
    eng = run_engine("gpt2", GPT2_CFG, gpt2_params, jobs,
                     tmp_path=tmp_path)
    eng.run()
    eng.close()

    spec = LoRASpec(rank=8, alpha=16.0, init="gpt2")
    template = init_lora_gpt2(GPT2_CFG, spec, jax.random.PRNGKey(0))
    bank = AdapterBank(template, capacity=2)
    serve = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=8),
        bank=bank)
    # manifest-verified hot-load of the engine-trained artifacts
    bank.load_file("t1", str(tmp_path / "t1.safetensors"))
    bank.load_file("t2", str(tmp_path / "t2.safetensors"))
    rng = np.random.default_rng(5)
    prompts = [list(rng.integers(1, 200, n)) for n in (5, 9)]
    reqs = [serve.submit(p, max_new_tokens=6, adapter=a)
            for p, a in zip(prompts, ("t1", "t2"))]
    done = {r.id: r for r in serve.drain()}
    serve.close()
    for req, job in zip(reqs, jobs):
        _, solo_tree = solo_train("gpt2", GPT2_CFG, gpt2_params, job)
        ids = jnp.asarray([req.prompt], jnp.int32)
        cfg = SampleConfig(max_new_tokens=6, greedy=True, eos_id=None,
                           pad_id=0)
        want = np.asarray(gpt2_generate(
            GPT2_CFG, gpt2_params, ids, jnp.ones_like(ids), cfg,
            lora=jax.tree.map(jnp.asarray, solo_tree)))[0].tolist()
        assert done[req.id].tokens == want, \
            f"{job.name}: served tokens != solo-trained generate()"
    # a corrupted upload is refused BEFORE any slot mutates
    victim = str(tmp_path / "t1.safetensors")
    blob = bytearray(open(victim, "rb").read())
    blob[-1] ^= 0xFF
    open(victim, "wb").write(bytes(blob))
    from mobilefinetuner_tpu.io.safetensors_io import \
        CheckpointIntegrityError
    bank2 = AdapterBank(template, capacity=1)
    with pytest.raises(CheckpointIntegrityError):
        bank2.load_file("t1", victim)


# --------------------------- mux fairness ----------------------------------

def test_mux_slow_tenant_does_not_starve_others():
    """Satellite: a stalled tenant stream must not starve the other
    k-1 — their producers keep their own bounded queues full — and the
    step loop's wait is ATTRIBUTED to the slow tenant (host_wait per
    tenant), with per-tenant queues bounded at `depth`."""
    stall = threading.Event()

    def slow_gen():
        n = 0
        while True:
            if n > 0:
                stall.wait(10.0)     # items after the first: blocked
            n += 1
            yield {"x": n}

    def fast_gen():
        n = 0
        while True:
            n += 1
            yield {"x": n}

    mux = TenantMux(depth=2)
    mux.add("slow", slow_gen())
    mux.add("f1", fast_gen())
    mux.add("f2", fast_gen())
    try:
        # first pulls: everyone has item 1
        for n in ("slow", "f1", "f2"):
            mux.pull(n)
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline and (
                mux.queue_depth("f1") < 2 or mux.queue_depth("f2") < 2):
            time.sleep(0.01)
        # the fast tenants' producers filled their bounded queues while
        # the slow producer sat blocked — no starvation, no unbounded
        # growth
        assert mux.queue_depth("f1") == 2
        assert mux.queue_depth("f2") == 2
        t0 = time.perf_counter()
        threading.Timer(0.25, stall.set).start()
        mux.pull("slow")             # blocks ~250 ms on the stall
        blocked_ms = (time.perf_counter() - t0) * 1000
        mux.pull("f1")
        mux.pull("f2")
        waits = mux.take_waits()
        assert waits["slow"] >= 0.8 * blocked_ms > 50
        assert waits["f1"] < waits["slow"] / 10
        assert waits["f2"] < waits["slow"] / 10
        # the accumulators drained
        assert mux.take_waits() == {"slow": 0.0, "f1": 0.0, "f2": 0.0}
    finally:
        stall.set()
        mux.close()


def test_mux_exhausted_stream_names_the_tenant():
    mux = TenantMux(depth=0)
    mux.add("tiny", iter([{"x": 1}]))
    mux.pull("tiny")
    with pytest.raises(RuntimeError, match="tiny"):
        mux.pull("tiny")
    mux.close()


# --------------------------- telemetry -------------------------------------

def test_engine_telemetry_stream_and_report(gpt2_params, tmp_path):
    """The engine's stream is schema-valid end to end: run_start ->
    tenant{admit/save/finish/cancel} + step_stats with the per-tenant
    `tenants` section -> run_end; every per-tenant event carries the
    optional `tenant` attribution field; telemetry_report renders a
    tenants section from it."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import telemetry_report
    stream = str(tmp_path / "mt.jsonl")
    jobs = [JobSpec(name="a", lr=1e-3, steps=4, seed=1, data_seed=31,
                    save_every=2),
            JobSpec(name="b", lr=2e-3, steps=2, seed=2, data_seed=32),
            JobSpec(name="c", lr=2e-3, steps=9, seed=3, data_seed=33)]
    eng = run_engine("gpt2", GPT2_CFG, gpt2_params, jobs, slots=2,
                     tmp_path=tmp_path, telemetry=Telemetry(stream),
                     flush_every=2)
    eng.admit_pending()
    for _ in range(4):
        eng.step()
    eng.cancel("c")
    while eng._has_work():
        eng.step()
    eng.close()
    with open(stream) as f:
        recs = [json.loads(l) for l in f.read().splitlines() if l.strip()]
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    tev = [r for r in recs if r["event"] == "tenant"]
    by_phase = {}
    for r in tev:
        by_phase.setdefault((r["name"], r["phase"]), []).append(r)
        assert r["tenant"] == r["name"]      # the attribution field
    assert ("a", "admit") in by_phase and ("a", "finish") in by_phase
    assert ("a", "save") in by_phase         # save_every=2 periodic
    assert ("b", "finish") in by_phase
    assert ("c", "admit") in by_phase and ("c", "cancel") in by_phase
    fin_a = by_phase[("a", "finish")][0]
    assert fin_a["step"] == 4 and fin_a["path"].endswith("a.safetensors")
    # per-tenant step_stats sections
    stats = [r for r in recs if r["event"] == "step_stats"]
    assert stats and any(r.get("tenants") for r in stats)
    first = next(r for r in stats if r.get("tenants"))
    for name, t in first["tenants"].items():
        assert set(t) >= {"slot", "step", "loss", "tokens", "wait_ms"}
    # checkpoint events rode the shared async writer
    assert any(r["event"] == "checkpoint" for r in recs)
    # the report tool renders a tenants section (text + json share it)
    s = telemetry_report.summarize(recs)
    assert s["tenants"]["jobs"] == 3
    assert s["tenants"]["finished"] == 2 and s["tenants"]["cancelled"] == 1
    rows = {r["name"]: r for r in s["tenants"]["rows"]}
    assert rows["a"]["status"] == "finish" and rows["a"]["step"] == 4
    assert rows["c"]["status"] == "cancel"
    assert telemetry_report.main([stream]) == 0
    assert telemetry_report.main([stream, "--format", "json"]) == 0


# --------------------------- schedule identity -----------------------------

def test_multi_lr_schedule_matches_solo_schedule():
    """multi_lr_schedule is lr_schedule broadcast over slots — the
    identity the parity oracle rides on, pinned directly across
    schedule kinds, warmup, and budgets."""
    from mobilefinetuner_tpu.optim.schedule import (lr_schedule,
                                                    multi_lr_schedule)
    totals = np.array([10, 50, 1], np.float32)
    lrs = np.array([1e-3, 3e-4, 5e-2], np.float32)
    warm = np.array([0.2, 0.0, 0.5], np.float32)
    for kind in ("cosine", "linear", "constant"):
        for step in (0, 1, 5, 49):
            got = np.asarray(multi_lr_schedule(
                np.full(3, step, np.int32), totals, lrs, warm, kind))
            want = np.array([
                float(lr_schedule(step, int(t), float(l), float(w),
                                  kind))
                for t, l, w in zip(totals, lrs, warm)])
            np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-9)


# --------------------------- CLI e2e ---------------------------------------

def test_cli_train_multi_lora_e2e(tmp_path):
    """The jobs-file CLI end to end on the tiny fixture checkpoint +
    real WikiText data path: two jobs train to completion, both
    adapters land with manifests + lineage, and the telemetry stream
    validates."""
    from fixtures import write_tiny_gpt2_dir, write_wikitext_dir
    model_dir = str(tmp_path / "model")
    data_dir = write_wikitext_dir(str(tmp_path / "wt2"))
    write_tiny_gpt2_dir(model_dir)
    jobs_file = str(tmp_path / "jobs.json")
    with open(jobs_file, "w") as f:
        json.dump({"family": "gpt2",
                   "defaults": {"rank": 4, "steps": 3, "alpha": 8.0},
                   "jobs": [{"name": "alice", "lr": 1e-3, "seed": 1},
                            {"name": "bob", "lr": 3e-3, "seed": 2,
                             "data_seed": 9}]}, f)
    out_dir = str(tmp_path / "out")
    stream = str(tmp_path / "mt.jsonl")
    from mobilefinetuner_tpu.cli import train_multi_lora
    rc = train_multi_lora.main([
        "--jobs", jobs_file, "--pretrained_dir", model_dir,
        "--data_dir", data_dir, "--out_dir", out_dir, "--slots", "2",
        "--batch_size", "2", "--seq_len", "32", "--log_interval", "2",
        "--telemetry_out", stream])
    assert rc == 0
    for name in ("alice", "bob"):
        path = os.path.join(out_dir, f"{name}.safetensors")
        assert os.path.exists(path)
        assert os.path.exists(path + ".manifest.json")
        tree, spec = peft_io.load_adapter(path)
        assert spec.rank == 4
    with open(stream) as f:
        recs = [json.loads(l) for l in f.read().splitlines() if l.strip()]
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    fins = [r for r in recs if r["event"] == "tenant"
            and r["phase"] == "finish"]
    assert {r["name"] for r in fins} == {"alice", "bob"}
