"""Gemma-3 golden-logit parity vs HF transformers Gemma3ForCausalLM
(tiny random weights), covering GQA, q/k norms, dual-theta RoPE,
sliding/global layer interleave, sandwich norms, scaled embeddings, tied
head. (Reference analog: test_gemma_forward.cpp + the align-dump harness,
train_lora_gemma.cpp:620-920.)"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from mobilefinetuner_tpu.core.config import Gemma3TextConfig
from mobilefinetuner_tpu.io.checkpoints import gemma3_params_from_hf
from mobilefinetuner_tpu.models import gemma3


@pytest.fixture(scope="module")
def hf_tiny():
    from transformers import Gemma3TextConfig as HFCfg
    from transformers import Gemma3ForCausalLM
    torch.manual_seed(0)
    hf_cfg = HFCfg(
        vocab_size=199, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=128, rope_theta=1_000_000.0,
        rope_local_base_freq=10_000.0, sliding_window=8,
        query_pre_attn_scalar=8.0, rms_norm_eps=1e-6,
        layer_types=["sliding_attention", "sliding_attention",
                     "full_attention", "sliding_attention"],
        attention_dropout=0.0, tie_word_embeddings=True)
    model = Gemma3ForCausalLM(hf_cfg).eval()
    cfg = Gemma3TextConfig(
        vocab_size=199, hidden_size=32, intermediate_size=64,
        num_hidden_layers=4, num_attention_heads=4, num_key_value_heads=2,
        head_dim=8, max_position_embeddings=128, sliding_window=8,
        query_pre_attn_scalar=8.0,
        layer_types=["sliding_attention", "sliding_attention",
                     "full_attention", "sliding_attention"])
    sd = {k: v.detach().numpy() for k, v in model.model.state_dict().items()}
    params = gemma3_params_from_hf(sd, cfg)
    return hf_cfg, model, cfg, params


def test_logits_match_hf(hf_tiny):
    hf_cfg, model, cfg, params = hf_tiny
    rng = np.random.default_rng(0)
    # S=24 > sliding_window=8 so local masking actually matters
    ids = rng.integers(0, cfg.vocab_size, size=(2, 24))
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.numpy()
    ours = np.asarray(gemma3.forward(cfg, params, jnp.array(ids)))
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=1e-3)


def test_sliding_vs_global_layers_differ(hf_tiny):
    """Ablation: flipping a local layer to global must change logits
    (proves the per-layer mask/theta selection is live)."""
    _, _, cfg, params = hf_tiny
    import dataclasses
    cfg2 = dataclasses.replace(
        cfg, layer_types=["full_attention"] * 4)
    rng = np.random.default_rng(1)
    ids = jnp.array(rng.integers(0, cfg.vocab_size, size=(1, 24)))
    a = np.asarray(gemma3.forward(cfg, params, ids))
    b = np.asarray(gemma3.forward(cfg2, params, ids))
    assert np.abs(a - b).max() > 1e-4


def test_lora_zero_init_identity_and_grads(hf_tiny):
    import jax
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                               merge_gemma3, unmerge_gemma3)
    from mobilefinetuner_tpu.ops.loss import lm_cross_entropy
    _, _, cfg, params = hf_tiny
    spec = LoRASpec(rank=4, alpha=32.0, init="peft", targets=None)
    lora = init_lora_gemma3(cfg, spec, jax.random.PRNGKey(0))
    assert set(lora["blocks"]) == {"q_proj", "k_proj", "v_proj", "o_proj",
                                   "gate_proj", "up_proj", "down_proj"}
    rng = np.random.default_rng(2)
    ids = jnp.array(rng.integers(0, cfg.vocab_size, size=(2, 16)))
    base = gemma3.forward(cfg, params, ids)
    with_lora = gemma3.forward(cfg, params, ids, lora=lora)
    np.testing.assert_allclose(np.asarray(base), np.asarray(with_lora),
                               atol=1e-5)

    # every LoRA target receives gradient (the reference's GPT-2 qkv-LoRA
    # gets NO grad, SURVEY.md §2.12.1 — Gemma path and ours must)
    def loss_fn(lora):
        return lm_cross_entropy(
            gemma3.forward(cfg, params, ids, lora=lora), ids)
    grads = jax.grad(loss_fn)(lora)
    for name, entry in grads["blocks"].items():
        ga = np.abs(np.asarray(entry["A"])).sum()
        gb = np.abs(np.asarray(entry["B"])).sum()
        assert gb > 0, f"{name}.B got no gradient"
        # A's grad flows through B=0 at init, so dL/dA == 0 on the very
        # first step; after B moves it must be nonzero. Perturb B:
    lora2 = jax.tree.map(lambda x: x, lora)
    for entry in lora2["blocks"].values():
        entry["B"] = jnp.ones_like(entry["B"]) * 0.01
    grads2 = jax.grad(loss_fn)(lora2)
    for name, entry in grads2["blocks"].items():
        assert np.abs(np.asarray(entry["A"])).sum() > 0, \
            f"{name}.A got no gradient"

    # merge/unmerge round trip
    merged = merge_gemma3(params, lora2)
    dyn = gemma3.forward(cfg, params, ids, lora=lora2)
    stat = gemma3.forward(cfg, merged, ids)
    np.testing.assert_allclose(np.asarray(dyn), np.asarray(stat),
                               atol=2e-4)
    restored = unmerge_gemma3(merged, lora2)
    import jax as _jax
    for a, b in zip(_jax.tree.leaves(params), _jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)
