"""ops/decode_attention.py vs the XLA decode einsum path (the oracle).

The kernel is benched OUT of models/generate.py on the current platform
(a no-op pallas_call costs ~43 us there, so L per-layer calls exceed the
whole XLA attention cost — DESIGN.md §10a), but it is kept as tested
infrastructure to re-measure against future runtimes, like ops/fused_ce.
These tests pin its numerics to the exact einsum semantics generate.py
uses (storage-dtype operands, f32 accumulation, NEG_INF masking,
softmax-then-cast context weights)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.ops.decode_attention import (decode_attention,
                                                      decode_eligible,
                                                      pick_kvb,
                                                      xla_reference)


def make(B, KV, G, T, D, dtype, seed=0):
    kq, kk, kv, km = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(kq, (B, KV, G, D), dtype)
    kc = jax.random.normal(kk, (B, KV, T, D), dtype)
    vc = jax.random.normal(kv, (B, KV, T, D), dtype)
    # left-padding-style mask plus scattered invalid columns, but the
    # last column (the current token) always attendable — generate.py's
    # invariant that makes fully-masked rows impossible
    ok = jax.random.bernoulli(km, 0.7, (B, T)).at[:, -1].set(True)
    return q, kc, vc, ok


@pytest.mark.parametrize("shape,dtype", [
    ((2, 12, 1, 64, 64), jnp.float32),    # GPT-2 head layout
    ((2, 12, 1, 64, 64), jnp.bfloat16),
    ((2, 1, 4, 48, 256), jnp.float32),    # Gemma GQA layout
    ((3, 2, 2, 40, 32), jnp.bfloat16),    # multi-kv-head GQA
])
def test_matches_xla_reference(shape, dtype):
    B, KV, G, T, D = shape
    q, kc, vc, ok = make(B, KV, G, T, D, dtype)
    scale = D ** -0.5
    assert decode_eligible(KV, T, D, jnp.dtype(dtype).itemsize)
    got = decode_attention(q, kc, vc, ok, scale)
    want = xla_reference(q, kc, vc, ok, scale)
    assert got.dtype == jnp.float32
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=tol, rtol=tol)


def test_left_padding_mask_respected():
    """Masked-out columns contribute nothing: shuffling their K/V rows
    must not change the output."""
    B, KV, G, T, D = 2, 4, 1, 32, 64
    q, kc, vc, ok = make(B, KV, G, T, D, jnp.float32)
    ok = jnp.broadcast_to(jnp.arange(T)[None, :] >= 8, (B, T))
    base = decode_attention(q, kc, vc, ok, D ** -0.5)
    poisoned_k = kc.at[:, :, :8].set(1e6)
    poisoned_v = vc.at[:, :, :8].set(-1e6)
    got = decode_attention(q, poisoned_k, poisoned_v, ok, D ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               atol=1e-6)


def test_jittable_and_grad_free():
    B, KV, G, T, D = 2, 2, 2, 16, 32
    q, kc, vc, ok = make(B, KV, G, T, D, jnp.float32)
    f = jax.jit(lambda *a: decode_attention(*a, D ** -0.5))
    out = f(q, kc, vc, ok)
    assert out.shape == (B, KV, G, D)


def test_eligibility_gates():
    # sublane-misaligned T
    assert not decode_eligible(12, 190, 64, 2)
    # VMEM overflow: KV=1 cannot be subdivided further
    assert not decode_eligible(1, 32768, 256, 4)
    assert pick_kvb(1, 32768, 256, 4) is None
    # GPT-2 bench shape picks the whole-KV block (one fat DMA per batch)
    assert pick_kvb(12, 192, 64, 2) == 12
    # a long-cache shape falls back to fewer kv heads per program
    kvb = pick_kvb(12, 8192, 64, 4)
    assert kvb is not None and kvb < 12 and 12 % kvb == 0


def test_vmem_gate_charges_gqa_terms():
    """Regression for the G-blind budget: the old estimate charged only
    the K/V blocks plus a flat T*D*4 term, so a large-G GQA shape whose
    [KVB, G, D] q/ctx blocks and [G, T] score rows dominate VMEM passed
    the gate and would overflow at runtime. The gate must now count
    kvb*G*D*(itemsize+4) and G*T*4."""
    # KV=1, T=8192, D=64, bf16: K/V terms alone need ~6.3 MB — admitted
    # with or without a moderate G...
    assert pick_kvb(1, 8192, 64, 2) == 1
    assert pick_kvb(1, 8192, 64, 2, G=8) == 1
    # ...but at G=256 the [G, T] f32 score rows alone add 8 MB: the OLD
    # G-blind estimate still said kvb=1 (it cannot subdivide KV=1 and
    # charged nothing for G); the tightened gate must refuse.
    assert pick_kvb(1, 8192, 64, 2, G=256) is None
    assert not decode_eligible(1, 8192, 64, 2, G=256)
    # G must also shrink the picked block when KV is divisible: the
    # per-program q/ctx blocks scale with kvb*G
    big = pick_kvb(16, 2048, 256, 2)
    small = pick_kvb(16, 2048, 256, 2, G=64)
    assert big is not None and small is not None and small <= big
