"""ops/decode_attention.py vs the XLA decode einsum path (the oracle).

The kernel is benched OUT of models/generate.py on the current platform
(a no-op pallas_call costs ~43 us there, so L per-layer calls exceed the
whole XLA attention cost — DESIGN.md §10a), but it is kept as tested
infrastructure to re-measure against future runtimes, like ops/fused_ce.
These tests pin its numerics to the exact einsum semantics generate.py
uses (storage-dtype operands, f32 accumulation, NEG_INF masking,
softmax-then-cast context weights)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.ops.decode_attention import (decode_attention,
                                                      decode_eligible,
                                                      paged_attention,
                                                      paged_decode_attention,
                                                      paged_eligible,
                                                      pick_kvb,
                                                      xla_reference)


def make(B, KV, G, T, D, dtype, seed=0):
    kq, kk, kv, km = jax.random.split(jax.random.PRNGKey(seed), 4)
    q = jax.random.normal(kq, (B, KV, G, D), dtype)
    kc = jax.random.normal(kk, (B, KV, T, D), dtype)
    vc = jax.random.normal(kv, (B, KV, T, D), dtype)
    # left-padding-style mask plus scattered invalid columns, but the
    # last column (the current token) always attendable — generate.py's
    # invariant that makes fully-masked rows impossible
    ok = jax.random.bernoulli(km, 0.7, (B, T)).at[:, -1].set(True)
    return q, kc, vc, ok


@pytest.mark.parametrize("shape,dtype", [
    ((2, 12, 1, 64, 64), jnp.float32),    # GPT-2 head layout
    ((2, 12, 1, 64, 64), jnp.bfloat16),
    ((2, 1, 4, 48, 256), jnp.float32),    # Gemma GQA layout
    ((3, 2, 2, 40, 32), jnp.bfloat16),    # multi-kv-head GQA
])
def test_matches_xla_reference(shape, dtype):
    B, KV, G, T, D = shape
    q, kc, vc, ok = make(B, KV, G, T, D, dtype)
    scale = D ** -0.5
    assert decode_eligible(KV, T, D, jnp.dtype(dtype).itemsize)
    got = decode_attention(q, kc, vc, ok, scale)
    want = xla_reference(q, kc, vc, ok, scale)
    assert got.dtype == jnp.float32
    tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               atol=tol, rtol=tol)


def test_left_padding_mask_respected():
    """Masked-out columns contribute nothing: shuffling their K/V rows
    must not change the output."""
    B, KV, G, T, D = 2, 4, 1, 32, 64
    q, kc, vc, ok = make(B, KV, G, T, D, jnp.float32)
    ok = jnp.broadcast_to(jnp.arange(T)[None, :] >= 8, (B, T))
    base = decode_attention(q, kc, vc, ok, D ** -0.5)
    poisoned_k = kc.at[:, :, :8].set(1e6)
    poisoned_v = vc.at[:, :, :8].set(-1e6)
    got = decode_attention(q, poisoned_k, poisoned_v, ok, D ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               atol=1e-6)


def test_jittable_and_grad_free():
    B, KV, G, T, D = 2, 2, 2, 16, 32
    q, kc, vc, ok = make(B, KV, G, T, D, jnp.float32)
    f = jax.jit(lambda *a: decode_attention(*a, D ** -0.5))
    out = f(q, kc, vc, ok)
    assert out.shape == (B, KV, G, D)


def test_eligibility_gates():
    # sublane-misaligned T
    assert not decode_eligible(12, 190, 64, 2)
    # VMEM overflow: KV=1 cannot be subdivided further
    assert not decode_eligible(1, 32768, 256, 4)
    assert pick_kvb(1, 32768, 256, 4) is None
    # GPT-2 bench shape picks the whole-KV block (one fat DMA per batch)
    assert pick_kvb(12, 192, 64, 2) == 12
    # a long-cache shape falls back to fewer kv heads per program
    kvb = pick_kvb(12, 8192, 64, 4)
    assert kvb is not None and kvb < 12 and 12 % kvb == 0


# --------------------------- block-paged variants ----------------------------

def make_paged(S, KV, G, D, bT, M, NB, L, dtype, seed=0):
    rng = np.random.default_rng(seed)
    kq, kk, kv = jax.random.split(jax.random.PRNGKey(seed), 3)
    q = jax.random.normal(kq, (S, KV, G, D), dtype)
    pool_k = jax.random.normal(kk, (NB, L, KV, bT, D), dtype)
    pool_v = jax.random.normal(kv, (NB, L, KV, bT, D), dtype)
    # block tables over non-trash pages; ragged per-slot lengths, plus a
    # sliding-window hole on slot 0 so FULLY-masked pages occur
    tbl = jnp.asarray(rng.integers(1, NB, (S, M)), jnp.int32)
    lens = rng.integers(1, M * bT + 1, S)
    cols = np.arange(M * bT)
    ok = cols[None, :] < lens[:, None]
    ok[0, :max(int(lens[0]) - 3, 0)] = False       # window: only last 3
    return q, pool_k, pool_v, tbl, jnp.asarray(ok)


@pytest.mark.parametrize("shape,dtype", [
    ((3, 12, 1, 64, 8, 4, 9, 2), jnp.float32),    # GPT-2 head layout
    ((3, 12, 1, 64, 8, 4, 9, 2), jnp.bfloat16),
    ((2, 1, 4, 64, 16, 3, 7, 3), jnp.float32),    # Gemma GQA layout
    ((4, 2, 2, 32, 8, 5, 11, 2), jnp.bfloat16),
])
def test_paged_matches_gathered_contiguous(shape, dtype):
    """paged_attention == xla_reference over the gathered contiguous
    cache (the paged read is pure indexing, not new math), and the
    pallas paged kernel == paged_attention — both for every layer index,
    under ragged lengths and fully-masked window pages."""
    S, KV, G, D, bT, M, NB, L = shape
    q, pk, pv, tbl, ok = make_paged(S, KV, G, D, bT, M, NB, L, dtype)
    scale = D ** -0.5
    assert paged_eligible(KV, G, bT, D, jnp.dtype(dtype).itemsize)
    for layer in range(L):
        got = paged_attention(q, pk, pv, tbl, layer, ok, scale)
        kc = pk[tbl, layer].transpose(0, 2, 1, 3, 4) \
            .reshape(S, KV, M * bT, D)
        vc = pv[tbl, layer].transpose(0, 2, 1, 3, 4) \
            .reshape(S, KV, M * bT, D)
        want = xla_reference(q, kc, vc, ok, scale)
        np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                                   atol=1e-6, rtol=1e-6)
        kern = paged_decode_attention(q, pk, pv, tbl, layer, ok, scale)
        assert kern.dtype == jnp.float32
        tol = 2e-2 if dtype == jnp.bfloat16 else 1e-5
        np.testing.assert_allclose(np.asarray(kern), np.asarray(got),
                                   atol=tol, rtol=tol)


def test_paged_trash_pages_never_leak():
    """Columns routed to the trash page (idle padding in a block table)
    must contribute nothing even when the trash page holds garbage."""
    S, KV, G, D, bT, M, NB, L = 2, 2, 1, 16, 8, 3, 6, 1
    q, pk, pv, tbl, _ = make_paged(S, KV, G, D, bT, M, NB, L, jnp.float32)
    tbl = tbl.at[:, 2].set(0)                     # last page -> trash
    ok = jnp.asarray(np.arange(M * bT)[None, :] < 2 * bT)
    ok = jnp.broadcast_to(ok, (S, M * bT))
    base = paged_attention(q, pk, pv, tbl, 0, ok, D ** -0.5)
    poisoned_k = pk.at[0].set(1e6)
    poisoned_v = pv.at[0].set(-1e6)
    got = paged_attention(q, poisoned_k, poisoned_v, tbl, 0, ok,
                          D ** -0.5)
    np.testing.assert_allclose(np.asarray(got), np.asarray(base),
                               atol=1e-6)
    kern = paged_decode_attention(q, poisoned_k, poisoned_v, tbl, 0, ok,
                                  D ** -0.5)
    np.testing.assert_allclose(np.asarray(kern), np.asarray(base),
                               atol=1e-5)


def test_paged_validation():
    S, KV, G, D, bT, M, NB, L = 2, 2, 1, 16, 8, 2, 5, 1
    q, pk, pv, tbl, ok = make_paged(S, KV, G, D, bT, M, NB, L,
                                    jnp.float32)
    with pytest.raises(ValueError, match="dtype"):
        paged_decode_attention(q.astype(jnp.bfloat16), pk, pv, tbl, 0,
                               ok, 1.0)
    assert not paged_eligible(KV, G, bT=12, D=D, itemsize=4)  # misaligned
    assert not paged_eligible(KV=1, G=4, bT=512, D=4096, itemsize=4)


def test_vmem_gate_charges_gqa_terms():
    """Regression for the G-blind budget: the old estimate charged only
    the K/V blocks plus a flat T*D*4 term, so a large-G GQA shape whose
    [KVB, G, D] q/ctx blocks and [G, T] score rows dominate VMEM passed
    the gate and would overflow at runtime. The gate must now count
    kvb*G*D*(itemsize+4) and G*T*4."""
    # KV=1, T=8192, D=64, bf16: K/V terms alone need ~6.3 MB — admitted
    # with or without a moderate G...
    assert pick_kvb(1, 8192, 64, 2) == 1
    assert pick_kvb(1, 8192, 64, 2, G=8) == 1
    # ...but at G=256 the [G, T] f32 score rows alone add 8 MB: the OLD
    # G-blind estimate still said kvb=1 (it cannot subdivide KV=1 and
    # charged nothing for G); the tightened gate must refuse.
    assert pick_kvb(1, 8192, 64, 2, G=256) is None
    assert not decode_eligible(1, 8192, 64, 2, G=256)
    # G must also shrink the picked block when KV is divisible: the
    # per-program q/ctx blocks scale with kvb*G
    big = pick_kvb(16, 2048, 256, 2)
    small = pick_kvb(16, 2048, 256, 2, G=64)
    assert big is not None and small is not None and small <= big
