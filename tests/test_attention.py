"""Attention oracle tests vs torch.scaled_dot_product_attention: causal,
GQA, sliding window, padding masks, differentiability.
(Reference analogs: test_qkt_softmax_grad.cpp, test_repeat_kv_softmax_grad.cpp,
test_attention_single_layer_backward.cpp.)"""

import jax
import jax.numpy as jnp
import numpy as np
import torch

from mobilefinetuner_tpu.ops.attention import (causal_mask,
                                               dot_product_attention)


def _rand(shape, seed):
    return np.random.default_rng(seed).normal(size=shape).astype(np.float32)


def test_causal_matches_torch_sdpa():
    B, H, S, D = 2, 3, 16, 8
    q, k, v = (_rand((B, H, S, D), i) for i in range(3))
    ours = dot_product_attention(jnp.array(q), jnp.array(k), jnp.array(v))
    ref = torch.nn.functional.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v), is_causal=True)
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-5)


def test_gqa_matches_repeated_kv():
    B, Hq, Hkv, S, D = 2, 8, 2, 12, 4
    q = _rand((B, Hq, S, D), 0)
    k = _rand((B, Hkv, S, D), 1)
    v = _rand((B, Hkv, S, D), 2)
    ours = dot_product_attention(jnp.array(q), jnp.array(k), jnp.array(v))
    # oracle: materialize repeated KV heads (the reference's repeat_kv_heads,
    # core/ops.cpp:2072) then plain MHA
    rep = Hq // Hkv
    kr = np.repeat(k, rep, axis=1)
    vr = np.repeat(v, rep, axis=1)
    ref = torch.nn.functional.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(kr), torch.tensor(vr), is_causal=True)
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-5)


def test_sliding_window_mask():
    m = np.asarray(causal_mask(6, 6, sliding_window=3))
    for i in range(6):
        for j in range(6):
            expect = j <= i and j > i - 3
            assert m[i, j] == expect, (i, j)


def test_sliding_window_attention_matches_masked_torch():
    B, H, S, D, W = 1, 2, 10, 4, 4
    q, k, v = (_rand((B, H, S, D), i + 10) for i in range(3))
    ours = dot_product_attention(jnp.array(q), jnp.array(k), jnp.array(v),
                                 sliding_window=W)
    mask = np.asarray(causal_mask(S, S, sliding_window=W))
    ref = torch.nn.functional.scaled_dot_product_attention(
        torch.tensor(q), torch.tensor(k), torch.tensor(v),
        attn_mask=torch.tensor(mask))
    np.testing.assert_allclose(np.asarray(ours), ref.numpy(), atol=1e-5)


def test_padding_mask():
    B, H, S, D = 2, 2, 8, 4
    q, k, v = (_rand((B, H, S, D), i + 20) for i in range(3))
    pad = np.ones((B, S), dtype=np.float32)
    pad[1, 5:] = 0.0
    ours = dot_product_attention(jnp.array(q), jnp.array(k), jnp.array(v),
                                 padding_mask=jnp.array(pad))
    # valid-token rows of the padded batch must equal the unpadded result on
    # a truncated sequence
    ours_trunc = dot_product_attention(
        jnp.array(q[1:, :, :5]), jnp.array(k[1:, :, :5]),
        jnp.array(v[1:, :, :5]))
    np.testing.assert_allclose(np.asarray(ours)[1, :, :5],
                               np.asarray(ours_trunc)[0], atol=1e-5)


def test_differentiable_and_finite_grads():
    # The reference's memory-efficient attention is forward-only (SURVEY.md
    # §2.12.1); ours must have correct finite grads on every path.
    B, H, S, D = 1, 2, 6, 4
    q, k, v = (jnp.array(_rand((B, H, S, D), i + 30)) for i in range(3))

    def f(q, k, v):
        return dot_product_attention(q, k, v, sliding_window=3).sum()

    grads = jax.grad(f, argnums=(0, 1, 2))(q, k, v)
    for g in grads:
        assert np.isfinite(np.asarray(g)).all()
        assert float(jnp.abs(g).sum()) > 0
