"""KV-cached generation tests (models/generate.py, cli/generate.py).

The correctness anchor: a greedy KV-cached rollout must match the naive
no-cache rollout (full forward re-run per emitted token, argmax) token for
token — for both model families, including ragged left-padded batches
(per-sample mask-derived positions), Gemma's sliding-window/global layer
mix, eos early-stop, and merged-LoRA weights. The reference has no active
generation path to anchor to (SURVEY.md §2.10: KV cache only in excluded
legacy code), so the no-cache rollout IS the oracle.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.models import gemma3, gpt2
from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                 gemma3_generate,
                                                 gpt2_generate, left_pad)

GPT2_CFG = dataclasses.replace(
    GPT2Config.tiny(vocab_size=211), n_embd=64, n_head=4, n_positions=64,
    n_layer=3, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)
# 4 layers: local, local, global, local (sliding_window < prompt+gen so the
# window actually truncates attention)
GEMMA_CFG = dataclasses.replace(
    Gemma3TextConfig.tiny(vocab_size=199), hidden_size=48, head_dim=12,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    num_hidden_layers=4, sliding_window=6, sliding_window_pattern=3)


@pytest.fixture(scope="module")
def gpt2_params():
    return gpt2.init_params(GPT2_CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def gemma_params():
    return gemma3.init_params(GEMMA_CFG, jax.random.PRNGKey(1))


def naive_rollout(fwd, ids, mask, n_new):
    """Oracle: re-run the full forward for every emitted token (no cache),
    greedy argmax, appending to the right of the left-padded batch."""
    ids = np.asarray(ids).copy()
    mask = np.asarray(mask).copy()
    out = []
    for _ in range(n_new):
        logits = np.asarray(fwd(jnp.asarray(ids), jnp.asarray(mask)))
        nxt = logits[:, -1].argmax(-1).astype(np.int32)
        out.append(nxt)
        ids = np.concatenate([ids, nxt[:, None]], axis=1)
        mask = np.concatenate(
            [mask, np.ones((ids.shape[0], 1), mask.dtype)], axis=1)
    return np.stack(out, axis=1)  # [B, n_new]


def test_gpt2_greedy_matches_naive_rollout(gpt2_params):
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(1, 200, n)) for n in (5, 9, 2)]
    ids, mask = left_pad(prompts, pad_id=0)
    n_new = 8
    cfg = SampleConfig(max_new_tokens=n_new, greedy=True, eos_id=None)

    def fwd(i, m):
        return gpt2.forward(GPT2_CFG, gpt2_params, i, attention_mask=m)

    want = naive_rollout(fwd, ids, mask, n_new)
    got = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params,
                                   jnp.asarray(ids), jnp.asarray(mask),
                                   cfg))
    np.testing.assert_array_equal(got, want)


def test_gemma3_greedy_matches_naive_rollout(gemma_params):
    rng = np.random.default_rng(1)
    prompts = [list(rng.integers(3, 190, n)) for n in (7, 3, 11)]
    ids, mask = left_pad(prompts, pad_id=0)
    n_new = 9  # > sliding_window - prompt overlap: the window engages
    cfg = SampleConfig(max_new_tokens=n_new, greedy=True, eos_id=None)

    def fwd(i, m):
        return gemma3.forward(GEMMA_CFG, gemma_params, i, attention_mask=m)

    want = naive_rollout(fwd, ids, mask, n_new)
    got = np.asarray(gemma3_generate(GEMMA_CFG, gemma_params,
                                     jnp.asarray(ids), jnp.asarray(mask),
                                     cfg))
    np.testing.assert_array_equal(got, want)


def test_gpt2_generate_is_jittable(gpt2_params):
    ids, mask = left_pad([[1, 2, 3], [4, 5, 6, 7]], pad_id=0)
    cfg = SampleConfig(max_new_tokens=4, greedy=True)
    fn = jax.jit(lambda i, m: gpt2_generate(GPT2_CFG, gpt2_params, i, m,
                                            cfg))
    out = np.asarray(fn(jnp.asarray(ids), jnp.asarray(mask)))
    assert out.shape == (2, 4)
    assert (out >= 0).all() and (out < GPT2_CFG.vocab_size).all()


def test_eos_stops_row(gpt2_params):
    """Declare the first greedily-emitted token to BE eos: the row must
    then emit exactly that token and pad out the rest."""
    ids, mask = left_pad([[1, 2, 3]], pad_id=0)
    free = SampleConfig(max_new_tokens=5, greedy=True, eos_id=None)
    rollout = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params,
                                       jnp.asarray(ids), jnp.asarray(mask),
                                       free))
    eos = int(rollout[0, 0])
    pad = (eos + 1) % GPT2_CFG.vocab_size
    cfg = SampleConfig(max_new_tokens=5, greedy=True, eos_id=eos,
                       pad_id=pad)
    out = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params, jnp.asarray(ids),
                                   jnp.asarray(mask), cfg))
    assert out[0, 0] == eos
    assert (out[0, 1:] == pad).all()


def test_gpt2_rejects_overlong_generation(gpt2_params):
    """prompt + max_new_tokens beyond n_positions must fail loudly (a
    clamped wpe gather would silently degrade output)."""
    ids, mask = left_pad([list(range(1, 61))], pad_id=0)  # P=60
    cfg = SampleConfig(max_new_tokens=10, greedy=True)    # 70 > 64
    with pytest.raises(ValueError, match="n_positions"):
        gpt2_generate(GPT2_CFG, gpt2_params, jnp.asarray(ids),
                      jnp.asarray(mask), cfg)


def test_single_token_generation(gpt2_params):
    """max_new_tokens=1: the token comes straight from prefill (the decode
    scan runs zero steps)."""
    ids, mask = left_pad([[1, 2, 3], [4, 5, 6]], pad_id=0)
    cfg = SampleConfig(max_new_tokens=1, greedy=True, eos_id=None)

    def fwd(i, m):
        return gpt2.forward(GPT2_CFG, gpt2_params, i, attention_mask=m)

    want = naive_rollout(fwd, ids, mask, 1)
    got = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params, jnp.asarray(ids),
                                   jnp.asarray(mask), cfg))
    np.testing.assert_array_equal(got, want)


def test_sampling_is_seeded_and_in_range(gpt2_params):
    ids, mask = left_pad([[1, 2, 3, 4]], pad_id=0)
    cfg = SampleConfig(max_new_tokens=6, temperature=0.9, top_k=20,
                      top_p=0.9, eos_id=None)
    a = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params, jnp.asarray(ids),
                                 jnp.asarray(mask), cfg,
                                 rng=jax.random.PRNGKey(3)))
    b = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params, jnp.asarray(ids),
                                 jnp.asarray(mask), cfg,
                                 rng=jax.random.PRNGKey(3)))
    c = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params, jnp.asarray(ids),
                                 jnp.asarray(mask), cfg,
                                 rng=jax.random.PRNGKey(4)))
    np.testing.assert_array_equal(a, b)
    assert (a >= 0).all() and (a < GPT2_CFG.vocab_size).all()
    assert not np.array_equal(a, c) or a.size < 4  # seeds differ


def test_lora_merged_generation_differs_and_runs(gpt2_params):
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                               merge_gpt2)
    spec = LoRASpec(rank=2, alpha=16.0)
    lora = init_lora_gpt2(GPT2_CFG, spec, jax.random.PRNGKey(9))
    # push B away from zero so the adapter actually changes logits
    lora = jax.tree.map(
        lambda x: x + 0.05 if x.ndim and x.shape[-1] else x, lora)
    merged = merge_gpt2(gpt2_params, lora)
    ids, mask = left_pad([[1, 2, 3, 4, 5]], pad_id=0)
    cfg = SampleConfig(max_new_tokens=6, greedy=True, eos_id=None)
    base = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params,
                                    jnp.asarray(ids), jnp.asarray(mask),
                                    cfg))
    tuned = np.asarray(gpt2_generate(GPT2_CFG, merged, jnp.asarray(ids),
                                     jnp.asarray(mask), cfg))
    assert base.shape == tuned.shape == (1, 6)
    assert not np.array_equal(base, tuned)


def test_generate_cli_end_to_end(tmp_path):
    """Drive the CLI against a tiny on-disk GPT-2 checkpoint."""
    import os
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(__file__)))
    from fixtures import write_tiny_gpt2_dir
    d = str(tmp_path / "model")
    os.makedirs(d)
    write_tiny_gpt2_dir(d)
    from mobilefinetuner_tpu.cli.generate import main
    import io
    from contextlib import redirect_stdout
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["--pretrained_dir", d, "--prompt", "hello world",
                   "--max_new_tokens", "4", "--greedy", "--json"])
    assert rc == 0
    import json
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    rec = json.loads(lines[-1])
    assert rec["prompt"] == "hello world"
    assert len(rec["ids"]) <= 4 and isinstance(rec["text"], str)

    # --lora_dynamic path: train nothing, just save a random adapter and
    # serve it unmerged through the CLI
    import jax as jax_mod
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gpt2
    from mobilefinetuner_tpu.lora.peft_io import save_adapter
    cfg2 = GPT2Config.from_pretrained(d)
    spec = LoRASpec(rank=2, alpha=4.0, targets=["attn_qkv"])
    lora = init_lora_gpt2(cfg2, spec, jax_mod.random.PRNGKey(0))
    apath = str(tmp_path / "a.safetensors")
    save_adapter(apath, lora, spec)
    buf = io.StringIO()
    with redirect_stdout(buf):
        rc = main(["--pretrained_dir", d, "--prompt", "hello world",
                   "--max_new_tokens", "4", "--greedy", "--json",
                   "--lora_path", apath, "--lora_dynamic"])
    assert rc == 0
    rec = json.loads([ln for ln in buf.getvalue().splitlines()
                      if ln.strip()][-1])
    assert isinstance(rec["text"], str)


def test_zero_new_tokens_returns_empty(gpt2_params, gemma_params):
    """max_new_tokens=0 returns [B, 0] — no silent extra token from the
    prefill sample."""
    ids = jnp.array([[1, 2, 3], [4, 5, 6]], jnp.int32)
    mask = jnp.ones_like(ids)
    cfg = SampleConfig(max_new_tokens=0, greedy=True)
    assert gpt2_generate(GPT2_CFG, gpt2_params, ids, mask,
                         cfg).shape == (2, 0)
    assert gemma3_generate(GEMMA_CFG, gemma_params, ids, mask,
                           cfg).shape == (2, 0)


def test_dynamic_lora_generation_matches_merged(gpt2_params, gemma_params):
    """Dynamic (unmerged) LoRA generation must emit the same greedy tokens
    as generating from the merged weights — every adapter site in BOTH
    decode loops (incl. prefill) applies the identical delta."""
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gemma3,
                                               init_lora_gpt2, merge_gemma3,
                                               merge_gpt2)
    ids, mask = left_pad([[1, 2, 3, 4, 5], [7, 8, 9]], pad_id=0)
    ids, mask = jnp.asarray(ids), jnp.asarray(mask)
    cfg = SampleConfig(max_new_tokens=6, greedy=True, eos_id=None)

    spec = LoRASpec(rank=2, alpha=16.0,
                    targets=["attn_qkv", "attn_proj", "mlp_fc_in",
                             "mlp_fc_out"])
    lora = init_lora_gpt2(GPT2_CFG, spec, jax.random.PRNGKey(9))
    lora = jax.tree.map(
        lambda x: x + 0.05 if x.ndim and x.shape[-1] else x, lora)
    merged = np.asarray(gpt2_generate(
        GPT2_CFG, merge_gpt2(gpt2_params, lora), ids, mask, cfg))
    dynamic = np.asarray(gpt2_generate(
        GPT2_CFG, gpt2_params, ids, mask, cfg, lora=lora))
    base = np.asarray(gpt2_generate(GPT2_CFG, gpt2_params, ids, mask, cfg))
    np.testing.assert_array_equal(dynamic, merged)
    assert not np.array_equal(dynamic, base)  # the adapter engaged

    gspec = LoRASpec(rank=2, alpha=16.0, targets="full")
    glora = init_lora_gemma3(GEMMA_CFG, gspec, jax.random.PRNGKey(10))
    glora = jax.tree.map(
        lambda x: x + 0.05 if x.ndim and x.shape[-1] else x, glora)
    gmerged = np.asarray(gemma3_generate(
        GEMMA_CFG, merge_gemma3(gemma_params, glora), ids, mask, cfg))
    gdynamic = np.asarray(gemma3_generate(
        GEMMA_CFG, gemma_params, ids, mask, cfg, lora=glora))
    gbase = np.asarray(gemma3_generate(GEMMA_CFG, gemma_params, ids, mask,
                                       cfg))
    np.testing.assert_array_equal(gdynamic, gmerged)
    assert not np.array_equal(gdynamic, gbase)


def test_dynamic_lora_split_qkv_generation(gpt2_params):
    """Split-QKV adapters (column-sliced on the fused c_attn) apply in the
    decode loop too: dynamic == merged."""
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                               merge_gpt2)
    spec = LoRASpec(rank=2, alpha=16.0,
                    targets=["attn_q", "attn_v", "attn_proj"])
    lora = init_lora_gpt2(GPT2_CFG, spec, jax.random.PRNGKey(11))
    lora = jax.tree.map(
        lambda x: x + 0.05 if x.ndim and x.shape[-1] else x, lora)
    ids, mask = left_pad([[3, 1, 4, 1, 5]], pad_id=0)
    ids, mask = jnp.asarray(ids), jnp.asarray(mask)
    cfg = SampleConfig(max_new_tokens=5, greedy=True, eos_id=None)
    merged = np.asarray(gpt2_generate(
        GPT2_CFG, merge_gpt2(gpt2_params, lora), ids, mask, cfg))
    dynamic = np.asarray(gpt2_generate(
        GPT2_CFG, gpt2_params, ids, mask, cfg, lora=lora))
    np.testing.assert_array_equal(dynamic, merged)


def test_gemma3_chunked_prefill_matches_whole(gemma_params):
    """Windowed prefill (prefill_chunk) must be token-identical to the
    whole-prompt forward — including ragged left-padded prompts, a
    window size that does NOT divide the prompt (internal re-pad), and
    sliding-window layers whose span crosses window boundaries."""
    rng = np.random.default_rng(7)
    prompts = [list(rng.integers(3, 190, n)) for n in (19, 11, 23)]
    ids, mask = left_pad(prompts, pad_id=0)         # P = 23
    cfg = SampleConfig(max_new_tokens=7, greedy=True, eos_id=None)
    want = np.asarray(gemma3_generate(
        GEMMA_CFG, gemma_params, jnp.asarray(ids), jnp.asarray(mask), cfg))
    for W in (8, 5, 16):                            # 23 % W != 0 for all
        got = np.asarray(gemma3_generate(
            GEMMA_CFG, gemma_params, jnp.asarray(ids), jnp.asarray(mask),
            cfg, prefill_chunk=W))
        np.testing.assert_array_equal(got, want, err_msg=f"W={W}")
    # a chunk larger than P falls back to the whole-prompt path
    got = np.asarray(gemma3_generate(
        GEMMA_CFG, gemma_params, jnp.asarray(ids), jnp.asarray(mask), cfg,
        prefill_chunk=64))
    np.testing.assert_array_equal(got, want)


def test_gemma3_chunked_prefill_with_dynamic_lora(gemma_params):
    """The windowed prefill applies dynamic LoRA at every site, same as
    the whole-prompt path — including MULTI-adapter trees, whose per-row
    routing must survive the [B, W, in] window activations."""
    from mobilefinetuner_tpu.lora.lora import (LoRASpec, assign_adapters,
                                               init_lora_gemma3,
                                               stack_adapters)

    def rand_lora(seed):
        lora = init_lora_gemma3(GEMMA_CFG, LoRASpec(rank=3, alpha=6.0),
                                jax.random.PRNGKey(seed))
        leaves, treedef = jax.tree.flatten(lora)
        keys = jax.random.split(jax.random.PRNGKey(seed + 50), len(leaves))
        return jax.tree.unflatten(treedef, [
            l if l.ndim == 0 else 0.05 * jax.random.normal(k, l.shape)
            for l, k in zip(leaves, keys)])

    lora = rand_lora(5)
    rng = np.random.default_rng(8)
    ids = jnp.asarray(rng.integers(3, 190, (2, 12)), jnp.int32)
    mask = jnp.ones_like(ids)
    cfg = SampleConfig(max_new_tokens=5, greedy=True, eos_id=None)
    want = np.asarray(gemma3_generate(GEMMA_CFG, gemma_params, ids, mask,
                                      cfg, lora=lora))
    got = np.asarray(gemma3_generate(GEMMA_CFG, gemma_params, ids, mask,
                                     cfg, lora=lora, prefill_chunk=4))
    np.testing.assert_array_equal(got, want)
    # multi-adapter x chunked prefill: routed rows == single-adapter runs
    multi = assign_adapters(stack_adapters([lora, rand_lora(9)]), [1, 0])
    got_m = np.asarray(gemma3_generate(GEMMA_CFG, gemma_params, ids, mask,
                                       cfg, lora=multi, prefill_chunk=4))
    want_a1 = np.asarray(gemma3_generate(
        GEMMA_CFG, gemma_params, ids[:1], mask[:1], cfg,
        lora=rand_lora(9), prefill_chunk=4))
    np.testing.assert_array_equal(got_m[0], want_a1[0])
    np.testing.assert_array_equal(got_m[1], want[1])


def test_gemma3_prefill_chunk_validation(gemma_params):
    ids = jnp.ones((1, 8), jnp.int32)
    mask = jnp.ones_like(ids)
    cfg = SampleConfig(max_new_tokens=2, greedy=True)
    with pytest.raises(ValueError):
        gemma3_generate(GEMMA_CFG, gemma_params, ids, mask, cfg,
                        prefill_chunk=-8)
    with pytest.raises(ValueError):
        gemma3_generate(GEMMA_CFG, gemma_params, ids, mask, cfg,
                        prefill_chunk=0)
