"""Tokenizer parity vs the HF `tokenizers` Rust library as oracle.
(Reference analogs: core/test_tokenizer_bpe.cpp HF-parity cases,
core/test_tokenizer_gemma.cpp.) With zero egress we can't use the real
GPT-2/Gemma vocab files, so we TRAIN small tokenizers of the same
construction with the oracle library, save them in the same file formats,
and require byte-identical encodes/decodes."""

import numpy as np
import pytest

CORPUS = [
    "The quick brown fox jumps over the lazy dog.",
    "Hello, world! It's a fine day — isn't it?",
    "In 1984, George Orwell wrote about   surveillance states.",
    "Tokenization: bytes, unicode (naïve café), and CJK 日本語のテキスト.",
    "def main():\n    print('hello')\n",
    "Prices rose 3.5% to $1,234.56 yesterday.",
    "  leading spaces and\ttabs\tmatter  ",
] * 50

TRICKY = [
    "Hello, world!",
    "it's isn't we're I'll you've they'd I'm",
    "multiple   spaces\nand\nnewlines\n\n",
    "numbers 123 45.67 and mixed a1b2",
    "unicode: naïve café résumé — über 日本語",
    "   ",
    "",
    "a",
    "don't stop 'til midnight '99",
]


@pytest.fixture(scope="module")
def gpt2_files(tmp_path_factory):
    from tokenizers import Tokenizer, models, pre_tokenizers, decoders, \
        trainers
    d = tmp_path_factory.mktemp("gpt2tok")
    tok = Tokenizer(models.BPE(unk_token=None))
    tok.pre_tokenizer = pre_tokenizers.ByteLevel(add_prefix_space=False)
    tok.decoder = decoders.ByteLevel()
    trainer = trainers.BpeTrainer(
        vocab_size=600, special_tokens=["<|endoftext|>"],
        initial_alphabet=pre_tokenizers.ByteLevel.alphabet(),
        show_progress=False)
    tok.train_from_iterator(CORPUS, trainer)
    tok.model.save(str(d))
    return str(d), tok


@pytest.mark.parametrize("use_native", [True, False])
def test_gpt2_bpe_matches_oracle(gpt2_files, use_native):
    from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
    d, oracle = gpt2_files
    ours = GPT2BPETokenizer.from_pretrained(d, use_native=use_native)
    for text in TRICKY + CORPUS[:7]:
        expect = oracle.encode(text).ids
        got = ours.encode(text)
        assert got == expect, (text, got, expect)


def test_gpt2_bpe_decode_roundtrip(gpt2_files):
    from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
    d, _ = gpt2_files
    ours = GPT2BPETokenizer.from_pretrained(d)
    for text in TRICKY:
        assert ours.decode(ours.encode(text)) == text


def test_gpt2_special_ids(gpt2_files):
    from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
    d, _ = gpt2_files
    ours = GPT2BPETokenizer.from_pretrained(d)
    # GPT-2 convention: eos==bos==pad==unk (tokenizer_bpe.h:29-33)
    assert ours.eos_id == ours.bos_id == ours.pad_id == ours.unk_id
    assert ours.eos_id == ours.encoder["<|endoftext|>"]


@pytest.fixture(scope="module")
def gemma_file(tmp_path_factory):
    from tokenizers import Tokenizer, models, normalizers, trainers
    d = tmp_path_factory.mktemp("gemmatok")
    byte_tokens = [f"<0x{b:02X}>" for b in range(256)]
    tok = Tokenizer(models.BPE(unk_token="<unk>", byte_fallback=True))
    tok.normalizer = normalizers.Replace(" ", "▁")
    trainer = trainers.BpeTrainer(
        vocab_size=700,
        special_tokens=["<pad>", "<eos>", "<bos>", "<unk>"] + byte_tokens,
        show_progress=False)
    tok.train_from_iterator(CORPUS, trainer)
    path = str(d / "tokenizer.json")
    tok.save(path)
    return path, tok



def make_gemma(path, backend):
    """Construct GemmaTokenizer on the requested BPE backend; the oracle
    suite runs BOTH so the pure-Python reference keeps direct HF-oracle
    coverage even on machines where the native engine builds."""
    import os
    from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
    if backend == "python":
        prior = os.environ.get("MFT_NO_NATIVE_GEMMA_BPE")
        os.environ["MFT_NO_NATIVE_GEMMA_BPE"] = "1"
        try:
            t = GemmaTokenizer(path)
        finally:  # restore a user-preset kill switch, don't clobber it
            if prior is None:
                del os.environ["MFT_NO_NATIVE_GEMMA_BPE"]
            else:
                os.environ["MFT_NO_NATIVE_GEMMA_BPE"] = prior
        assert t._native is None
        return t
    return GemmaTokenizer(path)


@pytest.mark.parametrize("backend", ["native", "python"])
def test_gemma_bpe_matches_oracle(gemma_file, backend):
    path, oracle = gemma_file
    ours = make_gemma(path, backend)
    for text in TRICKY + CORPUS[:7]:
        expect = oracle.encode(text).ids
        got = ours.encode(text, add_bos=False)
        assert got == expect, (text, got, expect)


@pytest.mark.parametrize("backend", ["native", "python"])
def test_gemma_byte_fallback(gemma_file, backend):
    path, oracle = gemma_file
    ours = make_gemma(path, backend)
    # char far outside the training corpus -> byte-fallback tokens
    text = "☃ unseen 𝄞"
    got = ours.encode(text, add_bos=False)
    expect = oracle.encode(text).ids
    assert got == expect
    assert ours.decode(got) == text.replace(" ", " ")


def test_gemma_metaspace_first_after_special_token(tmp_path):
    """Metaspace prepend_scheme='first' must NOT prepend the space marker to
    text that follows a special token ('<bos>user' -> [bos, 'user'], not
    [bos, '▁user']) — HF tokenizers parity."""
    from tokenizers import Tokenizer, models, pre_tokenizers, trainers
    from tokenizers.processors import TemplateProcessing  # noqa: F401
    from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
    tok = Tokenizer(models.BPE(unk_token="<unk>", byte_fallback=True))
    tok.pre_tokenizer = pre_tokenizers.Metaspace(
        replacement="▁", prepend_scheme="first")
    byte_tokens = [f"<0x{b:02X}>" for b in range(256)]
    trainer = trainers.BpeTrainer(
        vocab_size=700,
        special_tokens=["<pad>", "<eos>", "<bos>", "<unk>"] + byte_tokens,
        show_progress=False)
    tok.train_from_iterator(CORPUS, trainer)
    path = str(tmp_path / "tokenizer.json")
    tok.save(path)
    oracle = Tokenizer.from_file(path)
    ours = GemmaTokenizer(path)
    for text in ["<bos>user", "hi<eos>there", "<bos> spaced", "plain text",
                 "<bos><eos>tail"]:
        expect = oracle.encode(text).ids
        got = ours.encode(text, add_bos=False)
        assert got == expect, (text, got, expect)


def test_gemma_add_bos_and_special_ids(gemma_file):
    from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
    path, _ = gemma_file
    ours = GemmaTokenizer(path)
    assert ours.pad_id == 0 and ours.eos_id == 1 and ours.bos_id == 2 \
        and ours.unk_id == 3
    ids = ours.encode("hello")
    assert ids[0] == ours.bos_id  # add_bos defaults True
    assert ours.encode("hello", add_bos=False) == ids[1:]
