"""Native Gemma BPE engine parity (native/fast_gemma_bpe).

The native heap-merge engine must match the Python reference
(data/tokenizer_gemma.py _bpe_heap + vocab/byte-fallback lookup) id-for-id
— the Python side is itself HF-oracle-tested (test_tokenizers.py), so
transitively the native path is HF-aligned. Reference analog:
core/test_tokenizer_gemma.cpp parity cases.
"""

import os
import shutil

import numpy as np
import pytest

from tests.fixtures import WIKI_LINES, train_tiny_gemma_tokenizer

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no g++ in environment")


def make_tok(tmp_path_factory, native: bool):
    from mobilefinetuner_tpu.data.tokenizer_gemma import GemmaTokenizer
    d = str(tmp_path_factory.mktemp("gtok"))
    path = os.path.join(d, "tokenizer.json")
    train_tiny_gemma_tokenizer(path)
    if native:
        return GemmaTokenizer(path)
    os.environ["MFT_NO_NATIVE_GEMMA_BPE"] = "1"
    try:
        return GemmaTokenizer(path)
    finally:
        del os.environ["MFT_NO_NATIVE_GEMMA_BPE"]


@pytest.fixture(scope="module")
def tok_pair(tmp_path_factory):
    native = make_tok(tmp_path_factory, True)
    if native._native is None:
        pytest.skip("native Gemma BPE library failed to build")
    python = make_tok(tmp_path_factory, False)
    assert python._native is None
    return native, python


def test_native_library_builds():
    if os.environ.get("MFT_NO_NATIVE_GEMMA_BPE") == "1":
        pytest.skip("disabled by env")
    from mobilefinetuner_tpu.native.fast_gemma_bpe import load_library
    assert load_library() is not None


def test_corpus_parity(tok_pair):
    native, python = tok_pair
    text = "\n".join(WIKI_LINES)
    assert native.encode(text) == python.encode(text)


@pytest.mark.parametrize("text", [
    "",
    " ",
    "hello world",
    "  double  spaces  ",
    "newlines\nare\nreal\n\ntokens",
    "unicode: émigré Σigma 中文 🙂",
    "tabs\tand\rcarriage",
    "<eos> special <pad> tokens <bos>",
    "a" * 500,
    "word " * 200,
])
def test_case_parity(tok_pair, text):
    native, python = tok_pair
    assert native.encode(text) == python.encode(text)
    assert native.encode(text, add_bos=False) == \
        python.encode(text, add_bos=False)


def test_fuzz_parity(tok_pair):
    native, python = tok_pair
    rng = np.random.default_rng(0)
    alphabet = list("abcdefgh ABZ.\n\t字émo🙂") + ["<eos>", "▁"]
    for _ in range(200):
        n = int(rng.integers(0, 40))
        s = "".join(rng.choice(alphabet) for _ in range(n))
        assert native.encode(s) == python.encode(s), repr(s)


def test_byte_fallback_parity(tok_pair):
    """Characters outside the tiny training corpus exercise the <0xXX>
    byte-fallback path in both engines."""
    native, python = tok_pair
    for s in ["ß", "ß鬼🙃", "mix ß end", "\x00\x01"]:
        assert native.encode(s) == python.encode(s), repr(s)


def test_decode_roundtrip_unchanged(tok_pair):
    """decode stays pure-Python; native encode must feed it identically."""
    native, python = tok_pair
    s = "hello ß world\nnext"
    assert native.decode(native.encode(s)) == \
        python.decode(python.encode(s))
