"""Optimizer + train-step tests: Adam parity vs torch, grad-accum
equivalence, 10-step loss decrease, optimizer-state round-trip.
(Reference analogs: test_10step_convergence.cpp, test_optimizer_pipeline.cpp,
grad-accum A/B tests in scripts/Finetune.)"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import torch

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                           trainable_mask)
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
from mobilefinetuner_tpu.optim.adam import (AdamConfig, adam_update,
                                            init_state, load_state,
                                            save_state)
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

CFG = GPT2Config.tiny()


def _torch_adam_parity(coupled: bool, wd: float):
    rng = np.random.default_rng(0)
    p0 = rng.normal(size=(5, 3)).astype(np.float32)
    grads_seq = [rng.normal(size=(5, 3)).astype(np.float32)
                 for _ in range(4)]

    tp = torch.tensor(p0, requires_grad=True)
    if coupled:
        opt = torch.optim.Adam([tp], lr=1e-2, weight_decay=wd)
    else:
        opt = torch.optim.AdamW([tp], lr=1e-2, weight_decay=wd)

    cfg = AdamConfig(lr=1e-2, weight_decay=wd, coupled_weight_decay=coupled)
    jp = {"w": jnp.array(p0)}
    state = init_state(jp, cfg)
    for g in grads_seq:
        tp.grad = torch.tensor(g)
        opt.step()
        jp, state = adam_update({"w": jnp.array(g)}, state, jp, cfg,
                                jnp.float32(1e-2))
    np.testing.assert_allclose(np.asarray(jp["w"]), tp.detach().numpy(),
                               atol=1e-6)


def test_adam_matches_torch_adam_l2():
    _torch_adam_parity(coupled=True, wd=0.01)


def test_adamw_matches_torch_adamw():
    _torch_adam_parity(coupled=False, wd=0.01)


def test_adam_no_decay():
    _torch_adam_parity(coupled=False, wd=0.0)


def _make_problem():
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    spec = LoRASpec(rank=4, alpha=8.0)
    lora = init_lora_gpt2(CFG, spec, jax.random.PRNGKey(1))
    rng = np.random.default_rng(0)
    ids = jnp.array(rng.integers(0, CFG.vocab_size, size=(4, 16)))
    batch = {"input_ids": ids,
             "attention_mask": jnp.ones_like(ids),
             "labels": ids}
    return params, lora, batch


def _loss_fn(lora, params, mb):
    logits = gpt2.forward(CFG, params, mb["input_ids"],
                          attention_mask=mb["attention_mask"], lora=lora)
    return lm_cross_entropy_sum(logits, mb["labels"])


def test_10step_loss_decreases():
    params, lora, batch = _make_problem()
    tc = TrainConfig(total_steps=10, lr=5e-3, warmup_ratio=0.0,
                     schedule="constant", clip_grad_norm=1.0,
                     grad_accum_steps=1)
    mask = trainable_mask(lora)
    step_fn = make_train_step(_loss_fn, tc, mask=mask, donate=False)
    opt = init_optimizer(lora, tc, mask)
    losses = []
    for s in range(10):
        lora, opt, m = step_fn(lora, params, opt, batch, jnp.int32(s))
        losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] - 0.05, losses
    assert np.isfinite(losses).all()


def test_grad_accum_equivalence():
    """accum=2 over two half-batches == accum=1 over the full batch —
    EXACT even with unequal valid-token counts per micro-batch, because the
    step accumulates (sum_nll, count) and divides once."""
    params, lora, batch = _make_problem()
    labels = np.asarray(batch["labels"]).copy()
    labels[0, :10] = -100  # first micro-batch has far fewer valid tokens
    labels[1, :4] = -100
    batch = dict(batch, labels=jnp.array(labels))
    tc1 = TrainConfig(total_steps=5, lr=1e-3, warmup_ratio=0.0,
                      schedule="constant", clip_grad_norm=0.0,
                      grad_accum_steps=1)
    tc2 = dataclasses.replace(tc1, grad_accum_steps=2)
    mask = trainable_mask(lora)

    s1 = make_train_step(_loss_fn, tc1, mask=mask, donate=False)
    s2 = make_train_step(_loss_fn, tc2, mask=mask, donate=False)
    o1 = init_optimizer(lora, tc1, mask)
    o2 = init_optimizer(lora, tc2, mask)
    l1, _, m1 = s1(lora, params, o1, batch, jnp.int32(0))
    l2, _, m2 = s2(lora, params, o2, batch, jnp.int32(0))
    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-5
    for a, b in zip(jax.tree.leaves(l1), jax.tree.leaves(l2)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-6)


def test_scale_leaf_not_updated():
    params, lora, batch = _make_problem()
    tc = TrainConfig(total_steps=3, lr=1e-2, warmup_ratio=0.0,
                     schedule="constant", weight_decay=0.1)
    mask = trainable_mask(lora)
    step_fn = make_train_step(_loss_fn, tc, mask=mask, donate=False)
    opt = init_optimizer(lora, tc, mask)
    before = {k: float(v["scale"]) for k, v in lora["blocks"].items()}
    lora2, _, _ = step_fn(lora, params, opt, batch, jnp.int32(0))
    for k, v in lora2["blocks"].items():
        assert float(v["scale"]) == before[k]


def test_optimizer_state_roundtrip(tmp_path):
    params, lora, batch = _make_problem()
    tc = TrainConfig(total_steps=5, lr=1e-3)
    mask = trainable_mask(lora)
    step_fn = make_train_step(_loss_fn, tc, mask=mask, donate=False)
    opt = init_optimizer(lora, tc, mask)
    lora, opt, _ = step_fn(lora, params, opt, batch, jnp.int32(0))
    path = str(tmp_path / "opt.safetensors")
    save_state(path, opt, tc.adam())
    opt2, cfg2 = load_state(path, jax.tree.map(jnp.zeros_like, opt))
    assert cfg2.lr == tc.adam().lr
    assert int(opt2["step"]) == int(opt["step"])
    for a, b in zip(jax.tree.leaves(opt), jax.tree.leaves(opt2)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_lr_schedule_shapes():
    from mobilefinetuner_tpu.optim.schedule import lr_schedule
    # warmup ramps, cosine decays to floor
    lrs = [float(lr_schedule(s, 100, 1.0, warmup_ratio=0.1, kind="cosine"))
           for s in range(100)]
    assert lrs[0] < lrs[5] < lrs[9]
    assert abs(lrs[10] - 1.0) < 0.02
    assert lrs[99] < 0.15 and lrs[99] >= 0.1 - 1e-6
    lin = [float(lr_schedule(s, 100, 1.0, warmup_ratio=0.0, kind="linear"))
           for s in (0, 50, 99)]
    assert lin[0] > lin[1] > lin[2]
