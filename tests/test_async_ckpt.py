"""Async overlapped checkpointing tests (io/async_ckpt.py, DESIGN.md §15):
the snapshot is the step loop's only blocking work and survives donated
buffers, the background writer coalesces under backpressure and surfaces
its failures, every writer publishes atomically (a SIGKILL mid-write can
never corrupt the checkpoint --resume_from loads), and the sync oracle
(--async_save 0) produces byte-identical files to the async pipeline for
both model families, end to end through the real CLIs."""

import filecmp
import json
import os
import signal
import subprocess
import sys
import textwrap
import threading
import time

import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import (write_tiny_gemma3_dir, write_tiny_gpt2_dir,
                      write_wikitext_dir)

from mobilefinetuner_tpu.io.async_ckpt import (AsyncCheckpointer, snapshot,
                                               submit, timed_snapshot,
                                               tree_bytes)
from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                   atomic_publish,
                                                   save_safetensors)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2ckpt")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def gemma_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gemmackpt")
    write_tiny_gemma3_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2")))


# --------------------------- snapshot ---------------------------------------

def test_snapshot_returns_plain_numpy():
    import jax
    import jax.numpy as jnp
    tree = {"a": jax.device_put(jnp.arange(8, dtype=jnp.float32)),
            "b": {"c": jax.device_put(jnp.ones((2, 3)))},
            "host": np.arange(4)}  # numpy passes through untouched
    host = snapshot(tree)
    for leaf in [host["a"], host["b"]["c"], host["host"]]:
        assert isinstance(leaf, np.ndarray)
    np.testing.assert_array_equal(host["a"], np.arange(8, dtype=np.float32))
    # idempotent on an already-host tree (multi-host gathered case)
    again = snapshot(host)
    np.testing.assert_array_equal(again["a"], host["a"])
    assert tree_bytes(host) == host["a"].nbytes + host["b"]["c"].nbytes \
        + host["host"].nbytes


def test_timed_snapshot_reports_blocking_ms():
    import jax.numpy as jnp
    host, ms = timed_snapshot({"w": jnp.zeros((16, 16))})
    assert isinstance(host["w"], np.ndarray) and ms >= 0.0


def test_snapshot_immune_to_donated_updates():
    """The donation-hazard regression (ISSUE 5): snapshot at step k, then
    dispatch k+1..k+3 with DONATED input buffers — the loop's real train
    step donates the trainable/optimizer trees, so an un-awaited D2H
    copy would race the donated buffers' reuse and snapshot garbage.
    snapshot() must return step-k values no matter what the loop
    dispatches afterwards."""
    import jax
    import jax.numpy as jnp

    step = jax.jit(lambda p: jax.tree.map(lambda x: x + 1.0, p),
                   donate_argnums=0)
    params = {"w": jax.device_put(jnp.zeros(4096, jnp.float32)),
              "b": jax.device_put(jnp.zeros((8, 8), jnp.float32))}
    for _ in range(2):  # reach "step k"
        params = step(params)
    snap = snapshot(params)
    for _ in range(3):  # k+1..k+3 donate (and may reuse) the old buffers
        params = step(params)
    jax.block_until_ready(params)
    np.testing.assert_array_equal(snap["w"],
                                  np.full(4096, 2.0, np.float32))
    np.testing.assert_array_equal(snap["b"],
                                  np.full((8, 8), 2.0, np.float32))
    # and the loop really kept running past the snapshot
    np.testing.assert_array_equal(np.asarray(params["w"])[:4],
                                  np.full(4, 5.0, np.float32))


# --------------------------- writer semantics -------------------------------

def _sink(events):
    return lambda ev, **f: events.append({"event": ev, **f})


def test_sync_oracle_runs_inline(tmp_path):
    events = []
    ck = AsyncCheckpointer(enabled=False, event_sink=_sink(events))
    p = str(tmp_path / "sync.safetensors")

    def write():
        save_safetensors(p, {"x": np.arange(4, dtype=np.float32)})
        return [p]

    ck.save(3, write, snapshot_ms=1.5)
    assert os.path.exists(p)  # inline: durable the moment save returns
    ck.close()
    (ev,) = events
    assert ev["event"] == "checkpoint" and ev["async"] is False
    # sync blocking cost = snapshot + write
    assert ev["wall_s"] >= ev["write_ms"] / 1000.0
    assert ev["bytes"] == os.path.getsize(p) and ev["step"] == 3


def test_async_write_lands_with_split_telemetry(tmp_path):
    events = []
    ck = AsyncCheckpointer(enabled=True, event_sink=_sink(events))
    p = str(tmp_path / "async.safetensors")
    ck.save(7, lambda: (save_safetensors(
        p, {"x": np.ones(8, np.float32)}), [p])[1], snapshot_ms=2.0)
    ck.close()
    assert os.path.exists(p) and ck.written == 1
    (ev,) = events
    assert ev["event"] == "checkpoint" and ev["async"] is True
    # async blocking cost = the snapshot ONLY; the write overlapped
    assert ev["snapshot_ms"] == 2.0 and ev["wall_s"] == 0.002
    assert ev["write_ms"] > 0 and ev["bytes"] == os.path.getsize(p)


def test_depth1_queue_coalesces_to_newest(tmp_path):
    """Backpressure: a save landing while one is pending supersedes it —
    the stale snapshot is dropped with a ckpt_dropped event, the queue
    never grows beyond one whole-tree host copy."""
    events, written = [], []
    ck = AsyncCheckpointer(enabled=True, event_sink=_sink(events))
    gate = threading.Event()

    def slow_write(step):
        def write():
            gate.wait(30.0)
            written.append(step)
            return []
        return write

    ck.save(1, slow_write(1))           # picked up by the writer
    time.sleep(0.05)                    # let it start (blocked on gate)
    ck.save(2, slow_write(2))           # pending
    ck.save(3, slow_write(3))           # supersedes 2
    gate.set()
    ck.close()
    assert written == [1, 3] and ck.dropped == 1
    drops = [e for e in events if e["event"] == "ckpt_dropped"]
    assert drops == [{"event": "ckpt_dropped", "step": 2,
                      "superseded_by": 3}]
    # final=True drains: both surviving checkpoints completed
    assert [e["step"] for e in events
            if e["event"] == "checkpoint"] == [1, 3]


def test_background_write_error_surfaces(tmp_path):
    ck = AsyncCheckpointer(enabled=True)

    def boom():
        raise IOError("disk full")

    ck.save(1, boom)
    with pytest.raises(RuntimeError, match="background checkpoint"):
        ck.drain()
    # errors don't wedge the writer: each failed write is re-raised at
    # the next drain, and exception-path cleanup can swallow them
    ck.save(2, boom)
    with pytest.raises(RuntimeError):
        ck.drain()
    ck.close(raise_errors=False)  # exception-path cleanup swallows


def test_close_stops_writer_thread_even_on_write_error():
    """Regression: close(raise_errors=True) must stop/join the writer
    thread in a finally — a failed write that re-raises at close must
    not leak a parked ckpt-writer thread per run."""
    ck = AsyncCheckpointer(enabled=True)
    ck.save(1, lambda: (_ for _ in ()).throw(IOError("disk full")))
    with pytest.raises(RuntimeError):
        ck.close()
    assert ck._thread is None
    assert not [t for t in threading.enumerate()
                if t.name == "ckpt-writer"]


def test_submit_without_checkpointer_writes_inline(tmp_path):
    p = str(tmp_path / "direct.safetensors")
    submit(None, 0, lambda: (save_safetensors(
        p, {"x": np.zeros(2, np.float32)}), [p])[1])
    assert os.path.exists(p)


# --------------------------- atomic publication -----------------------------

def test_atomic_publish_success_and_abort(tmp_path):
    p = str(tmp_path / "f.bin")
    with atomic_publish(p) as tmp:
        with open(tmp, "wb") as f:
            f.write(b"v1")
    assert open(p, "rb").read() == b"v1"
    # a failure mid-write leaves the published bytes untouched and no tmp
    with pytest.raises(RuntimeError):
        with atomic_publish(p) as tmp:
            with open(tmp, "wb") as f:
                f.write(b"garbage")
            raise RuntimeError("writer died")
    assert open(p, "rb").read() == b"v1"
    assert os.listdir(tmp_path) == ["f.bin"]  # tmp cleaned up


_KILL_CHILD = textwrap.dedent("""
    import sys, time
    import numpy as np
    import mobilefinetuner_tpu.io.safetensors_io as sio

    path = sys.argv[1]
    orig = sio._write_safetensors

    def slow(p, tensors, *args, **kwargs):
        orig(p, tensors, *args, **kwargs)  # tmp fully written...
        print("TMP_DONE", flush=True)
        time.sleep(60)  # ...killed before fsync + atomic rename

    sio._write_safetensors = slow
    sio.save_safetensors(path, {"x": np.full(1024, 2.0, np.float32)})
""")


def test_sigkill_mid_write_leaves_previous_checkpoint_loadable(tmp_path):
    """The crash-safety contract: a checkpoint v1 exists; a writer is
    SIGKILLed while overwriting it (after the tmp bytes, before the
    rename — the widest window a real crash can hit); v1 must still load
    byte-for-byte, and the stale tmp must not break later saves."""
    p = str(tmp_path / "ckpt.safetensors")
    v1 = {"x": np.full(1024, 1.0, np.float32)}
    save_safetensors(p, v1)
    golden = open(p, "rb").read()

    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    child = subprocess.Popen([sys.executable, "-c", _KILL_CHILD, p],
                             stdout=subprocess.PIPE, env=env, text=True)
    try:
        assert child.stdout.readline().strip() == "TMP_DONE"
        os.kill(child.pid, signal.SIGKILL)
    finally:
        child.wait(timeout=30)

    assert open(p, "rb").read() == golden  # prior checkpoint untouched
    np.testing.assert_array_equal(
        SafeTensorsReader(p).load_all()["x"], v1["x"])
    # the orphaned .tmp.<childpid> is inert: the next save (different
    # pid) publishes cleanly over the same destination
    assert any(f.startswith("ckpt.safetensors.tmp.")
               for f in os.listdir(tmp_path))
    save_safetensors(p, {"x": np.full(1024, 3.0, np.float32)})
    assert SafeTensorsReader(p).load_all()["x"][0] == 3.0


# --------------------------- CLI parity e2e ---------------------------------

def test_gpt2_lora_sync_async_byte_identical(gpt2_dir, wiki_dir, tmp_path):
    """--async_save 1 vs 0 (oracle) must produce byte-identical adapter
    AND optimizer-sidecar files for the same seeded run."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    outs = {}
    for mode in (0, 1):
        out = str(tmp_path / f"a{mode}.safetensors")
        rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
                   "--steps", "3", "--batch_size", "2", "--seq_len", "32",
                   "--lora_out", out, "--async_save", str(mode)])
        assert rc == 0
        outs[mode] = out
    for sfx in ("", ".opt"):
        assert filecmp.cmp(outs[0] + sfx, outs[1] + sfx,
                           shallow=False), sfx


def test_gemma_fullft_sync_async_byte_identical(gemma_dir, wiki_dir,
                                                tmp_path):
    from mobilefinetuner_tpu.cli.gemma_full_finetune import main
    outs = {}
    for mode in (0, 1):
        out = str(tmp_path / f"g{mode}.safetensors")
        rc = main(["--model_dir", gemma_dir, "--data_dir", wiki_dir,
                   "--steps", "2", "--batch_size", "2", "--seq_len", "32",
                   "--loss_chunks", "2", "--output_path", out,
                   "--async_save", str(mode)])
        assert rc == 0
        outs[mode] = out
    for sfx in ("", ".opt"):
        assert filecmp.cmp(outs[0] + sfx, outs[1] + sfx,
                           shallow=False), sfx


def test_periodic_async_saves_emit_split_telemetry(gpt2_dir, wiki_dir,
                                                   tmp_path):
    """End to end through run_training: --save_every under the default
    --async_save produces loadable periodic checkpoints and checkpoint
    events carrying the round-10 snapshot/write split, all valid against
    EVENT_SCHEMA; the final event is a drained final=True save."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    from mobilefinetuner_tpu.core.telemetry import validate_event
    out = str(tmp_path / "a.safetensors")
    stream = str(tmp_path / "run.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "4", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", out, "--save_every", "2",
               "--telemetry_out", stream])
    assert rc == 0
    assert os.path.exists(str(tmp_path / "a_step2.safetensors"))
    assert os.path.exists(out) and os.path.exists(out + ".opt")
    events = [json.loads(l) for l in open(stream).read().splitlines()]
    cks = [e for e in events if e["event"] == "checkpoint"]
    assert len(cks) == 2  # step-2 periodic + final (fast writes: 0 drops)
    for e in cks:
        assert validate_event(e) is None
        assert e["async"] is True and e["bytes"] > 0
        assert e["write_ms"] > 0 and e["snapshot_ms"] >= 0
        # under async the blocking cost is the snapshot, not the write
        # (wall_s is rounded to 4 decimals — compare at that granularity)
        assert abs(e["wall_s"] - e["snapshot_ms"] / 1000.0) < 1e-4
    assert cks[-1]["final"] is True
    assert not [e for e in events if e["event"] == "ckpt_dropped"]
