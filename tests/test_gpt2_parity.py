"""GPT-2 golden-logit parity vs HF transformers (torch CPU).

The reference's backbone correctness strategy is golden-file alignment vs a
fixed HF forward (SURVEY.md §4.2, graph/save_pt_gold.py +
test_gpt2_forward.cpp). With zero egress we go one better: build a tiny
RANDOM-weight HF GPT2LMHeadModel in-process, export its state dict through
our safetensors round-trip + key mapping, and require logit agreement.
"""

import numpy as np
import pytest
import torch

import jax.numpy as jnp

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.io.checkpoints import gpt2_params_from_hf
from mobilefinetuner_tpu.models import gpt2


@pytest.fixture(scope="module")
def hf_tiny():
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel
    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=97, n_positions=32, n_embd=16, n_layer=3,
                      n_head=2, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    model = GPT2LMHeadModel(hf_cfg).eval()
    return hf_cfg, model


def _our_params(model, cfg: GPT2Config):
    sd = {k: v.detach().numpy() for k, v in
          model.transformer.state_dict().items()
          if not k.endswith(".attn.bias") and ".attn.masked_bias" not in k}
    return gpt2_params_from_hf(sd, cfg)


def test_logits_match_hf(hf_tiny):
    hf_cfg, model = hf_tiny
    cfg = GPT2Config(vocab_size=hf_cfg.vocab_size,
                     n_positions=hf_cfg.n_positions, n_embd=hf_cfg.n_embd,
                     n_layer=hf_cfg.n_layer, n_head=hf_cfg.n_head)
    params = _our_params(model, cfg)

    rng = np.random.default_rng(0)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 20))
    with torch.no_grad():
        ref = model(torch.tensor(ids)).logits.numpy()
    ours = np.asarray(gpt2.forward(cfg, params, jnp.array(ids)))
    np.testing.assert_allclose(ours, ref, atol=2e-4, rtol=1e-4)


def test_padding_mask_matches_hf(hf_tiny):
    hf_cfg, model = hf_tiny
    cfg = GPT2Config(vocab_size=hf_cfg.vocab_size,
                     n_positions=hf_cfg.n_positions, n_embd=hf_cfg.n_embd,
                     n_layer=hf_cfg.n_layer, n_head=hf_cfg.n_head)
    params = _our_params(model, cfg)
    rng = np.random.default_rng(1)
    ids = rng.integers(0, cfg.vocab_size, size=(2, 12))
    mask = np.ones((2, 12), dtype=np.int64)
    mask[1, 8:] = 0
    with torch.no_grad():
        ref = model(torch.tensor(ids),
                    attention_mask=torch.tensor(mask)).logits.numpy()
    ours = np.asarray(gpt2.forward(cfg, params, jnp.array(ids),
                                   attention_mask=jnp.array(mask)))
    # compare only non-padded positions (HF's padded positions differ by
    # position-embedding handling conventions)
    np.testing.assert_allclose(ours[0], ref[0], atol=2e-4, rtol=1e-4)
    np.testing.assert_allclose(ours[1, :8], ref[1, :8], atol=2e-4, rtol=1e-4)


def test_safetensors_roundtrip(tmp_path, hf_tiny):
    from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                       save_safetensors)
    hf_cfg, model = hf_tiny
    sd = {k: v.detach().numpy()
          for k, v in model.transformer.state_dict().items()
          if not k.endswith(".attn.bias")}
    path = str(tmp_path / "m.safetensors")
    save_safetensors(path, sd, metadata={"format": "pt"})
    back = SafeTensorsReader(path).load_all()
    assert set(back) == set(sd)
    for k in sd:
        np.testing.assert_array_equal(back[k], sd[k])
    # cross-check against the official safetensors library
    from safetensors.numpy import load_file
    official = load_file(path)
    for k in sd:
        np.testing.assert_array_equal(official[k], sd[k])


def test_bf16_roundtrip(tmp_path):
    from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                       save_safetensors)
    rng = np.random.default_rng(0)
    x = rng.normal(size=(5, 7)).astype(np.float32)
    path = str(tmp_path / "b.safetensors")
    save_safetensors(path, {"x": x}, bf16_keys={"x"})
    back = SafeTensorsReader(path).load("x")
    # bf16 quantization error <= 2^-8 relative
    np.testing.assert_allclose(back, x, rtol=1 / 256)
    ref = torch.tensor(x).to(torch.bfloat16).float().numpy()
    np.testing.assert_array_equal(back, ref)
