"""Round-16 resource-exhaustion robustness (DESIGN.md §21): the HBM
admission preflight (core/memory_guard.py), the remat -> accum_x2 ->
offload degradation ladder in cli/common.run_training, the
RESOURCE_EXHAUSTED-at-dispatch retry, the serve engine's build-time
refusal naming max feasible num_blocks/num_slots, the prefetch
host-RSS shed guard, and the report tools' memory section."""

import json
import os
import sys

import jax
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import write_tiny_gpt2_dir, write_wikitext_dir

from mobilefinetuner_tpu.core import memory_guard as mg
from mobilefinetuner_tpu.core.telemetry import validate_event


def read_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f.read().splitlines() if l.strip()]


def assert_stream_valid(evs):
    for e in evs:
        assert validate_event(e) is None, (e, validate_event(e))
    seqs = [e["seq"] for e in evs]
    assert all(a < b for a, b in zip(seqs, seqs[1:]))


# --------------------------- unit: capacity + verdicts ----------------------

class _Dev:
    def __init__(self, kind="cpu", limit=0):
        self.device_kind = kind
        self._limit = limit

    def memory_stats(self):
        return {"bytes_limit": self._limit} if self._limit else {}


def test_device_capacity_sources_in_precedence_order():
    """--hbm_cap_mb override > memory_stats bytes_limit > device-kind
    table > unknown (None — admission never refuses on a guess)."""
    cap, src = mg.device_capacity_mb(override_mb=123, device=_Dev())
    assert (cap, src) == (123.0, "flag")
    cap, src = mg.device_capacity_mb(device=_Dev(limit=4 * 2 ** 30))
    assert (cap, src) == (4096.0, "memory_stats")
    cap, src = mg.device_capacity_mb(device=_Dev(kind="TPU v5 lite"))
    assert (cap, src) == (16 * 1024.0, "device_table")
    # longest-substring-first: "v5p" must not match the "v5 lite" row
    cap, src = mg.device_capacity_mb(device=_Dev(kind="TPU v5p"))
    assert (cap, src) == (95 * 1024.0, "device_table")
    cap, src = mg.device_capacity_mb(device=_Dev(kind="weird accel"))
    assert (cap, src) == (None, "unknown")


def test_analytic_check_verdicts_and_headroom():
    over = mg.analytic_check(95.0, cap_mb=100, headroom=0.1)
    assert over.verdict == "over" and over.cap_frac == 0.95
    ok = mg.analytic_check(89.0, cap_mb=100, headroom=0.1)
    assert ok.verdict == "ok"
    unk = mg.analytic_check(89.0, cap_mb=0, headroom=0.1,
                            phase="serve_build")
    # no flag cap: falls back to the real device; on CPU (empty
    # memory_stats, kind not in the table) that is unknown
    if jax.local_devices()[0].platform == "cpu":
        assert unk.verdict == "unknown" and unk.cap_mb is None
    # the event payload carries the schema's required trio
    ev = over.event()
    assert ev["verdict"] == "over" and ev["est_mb"] == 95.0
    assert ev["cap_mb"] == 100.0 and ev["phase"] == "serve_build"


def test_is_resource_exhausted_matches_status_text():
    assert mg.is_resource_exhausted(
        RuntimeError("RESOURCE_EXHAUSTED: out of memory allocating"))
    assert not mg.is_resource_exhausted(ValueError("shape mismatch"))


def test_host_rss_mb_reads_this_process():
    rss = mg.host_rss_mb()
    if rss is None:
        pytest.skip("no /proc/self/statm on this platform")
    assert 1.0 < rss < 10 * 1024 * 1024


def test_parse_train_inject_hbm_pressure_grammar():
    from mobilefinetuner_tpu.cli.common import parse_train_inject
    assert parse_train_inject("hbm_pressure:64") == \
        ("hbm_pressure", None, 64)
    with pytest.raises(SystemExit):
        parse_train_inject("hbm_pressure")
    with pytest.raises(SystemExit):
        parse_train_inject("hbm_meltdown:1")


# --------------------------- unit: prefetch RSS shed ------------------------

def test_prefetch_rss_shed_guard():
    """The producer defers lookahead under injected pressure (sheds
    counted, queue drains), recovers to full depth after, and the
    consumed sequence is untouched — the tool's proof run in-process
    (tools/check_stream_memory.check_rss_shed)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    from check_stream_memory import check_rss_shed
    r = check_rss_shed()
    assert r["ok"], r
    assert r["sheds"] > 0 and r["sequence_intact"]
    assert r["max_depth_under_pressure"] <= 2


def test_prefetch_unreadable_rss_disables_guard():
    """A sensor that cannot answer must never block the pipeline."""
    from mobilefinetuner_tpu.data.prefetch import Prefetcher
    with Prefetcher(iter(range(20)), depth=2, rss_limit_mb=1,
                    rss_fn=lambda: None) as s:
        assert list(s) == list(range(20))
        assert s.rss_sheds == 0


# --------------------------- serve build admission --------------------------

def test_serve_infeasible_config_refused_naming_max_feasible():
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.serve.engine import ServeConfig, ServeEngine
    cfg = GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    scfg = ServeConfig(num_slots=2, num_blocks=4096, max_prompt=16,
                       max_new_tokens=16, hbm_cap_mb=8)
    with pytest.raises(mg.MemoryAdmissionError) as ei:
        ServeEngine("gpt2", cfg, params, scfg)
    msg = str(ei.value)
    assert "num_blocks=" in msg and "num_slots=" in msg
    max_blocks = int(msg.split("num_blocks=")[1].split()[0])
    assert 0 < max_blocks < 4096
    # the refusal happened BEFORE any pool allocation
    assert ei.value.check.verdict == "over"


def test_serve_feasible_config_emits_mem_check_and_hbm_stats(tmp_path):
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.core.telemetry import Telemetry
    from mobilefinetuner_tpu.models import gpt2
    from mobilefinetuner_tpu.serve.engine import ServeConfig, ServeEngine
    cfg = GPT2Config.tiny()
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    stream = str(tmp_path / "serve.jsonl")
    eng = ServeEngine(
        "gpt2", cfg, params,
        ServeConfig(num_slots=2, num_blocks=64, max_prompt=16,
                    max_new_tokens=16, hbm_cap_mb=1000, stats_every=1),
        telemetry=Telemetry(stream))
    h = eng.health()
    assert h["pool_mb"] == pytest.approx(eng.pool_mb)
    assert "hbm_mb" in h  # None on backends without memory_stats
    eng.emit_stats()
    eng.close()
    evs = read_events(stream)
    assert_stream_valid(evs)
    mc = [e for e in evs if e["event"] == "mem_check"]
    assert len(mc) == 1 and mc[0]["verdict"] == "ok"
    ss = [e for e in evs if e["event"] == "serve_stats"]
    assert ss and ss[0]["pool_mb"] == pytest.approx(eng.pool_mb)


# --------------------------- e2e: preflight + ladder ------------------------
# Calibrated on the tiny GPT-2 fixture at B=8, S=64: compiled peak is
# ~8.5 MB naive, ~3.7 MB with remat, ~1.5 MB with remat + accum_x2 —
# so cap 3 MB (threshold 2.7) forces exactly the remat AND accum rungs,
# and cap 1 MB (threshold 0.9) exhausts the whole ladder.

@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2ckpt")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2")))


@pytest.fixture(scope="module")
def big_wiki_dir(tmp_path_factory, wiki_dir):
    """The stock fixture corpus x4: a 6-step run consumes 48 chunks,
    and under drop_last the per-epoch chunk count depends on batch
    size — the naive (b=8) and degraded (b=4) streams must BOTH stay
    inside epoch 0 or their row sequences diverge at the boundary and
    the loss-parity oracle compares different data."""
    d = str(tmp_path_factory.mktemp("wt2big"))
    for split in ("train", "valid", "test"):
        with open(os.path.join(wiki_dir, f"wiki.{split}.tokens")) as f:
            txt = f.read()
        with open(os.path.join(d, f"wiki.{split}.tokens"), "w") as f:
            f.write(txt * 4)
    return d


def _base_argv(gpt2_dir, wiki_dir, tmp_path, name, steps=6):
    return ["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
            "--steps", str(steps), "--seq_len", "64",
            "--lora_out", str(tmp_path / f"{name}.safetensors"),
            "--telemetry_out", str(tmp_path / f"{name}.jsonl")]


def test_e2e_on_oom_risk_fail_raises_before_data_loading(
        gpt2_dir, wiki_dir, tmp_path):
    """Acceptance: an over-capacity config under --on_oom_risk fail
    dies with the named error immediately after compile — the stream
    is run_start, compile, mem_check{verdict=over}, run_end; no
    stream/step/checkpoint activity ever started."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    with pytest.raises(mg.MemoryAdmissionError):
        main(_base_argv(gpt2_dir, wiki_dir, tmp_path, "fail")
             + ["--batch_size", "8", "--hbm_cap_mb", "3",
                "--on_oom_risk", "fail"])
    evs = read_events(str(tmp_path / "fail.jsonl"))
    assert_stream_valid(evs)
    assert [e["event"] for e in evs] == \
        ["run_start", "compile", "mem_check", "run_end"]
    assert evs[2]["verdict"] == "over"
    assert evs[-1]["exit"] == "MemoryAdmissionError"


@pytest.fixture(scope="module")
def degrade_run(gpt2_dir, big_wiki_dir, tmp_path_factory):
    """ONE degraded run + its directly-degraded oracle, shared by the
    ladder acceptance test and the report-rendering test (each CLI run
    costs 1-3 tiny compiles; tier-1 rides a wall-clock budget)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    tmp_path = tmp_path_factory.mktemp("degrun")
    rc = main(_base_argv(gpt2_dir, big_wiki_dir, tmp_path, "deg")
              + ["--batch_size", "8", "--hbm_cap_mb", "3",
                 "--on_oom_risk", "degrade"])
    assert rc == 0
    rc = main(_base_argv(gpt2_dir, big_wiki_dir, tmp_path, "direct")
              + ["--batch_size", "4", "--grad_accum_steps", "2",
                 "--remat"])
    assert rc == 0
    return (read_events(str(tmp_path / "deg.jsonl")),
            read_events(str(tmp_path / "direct.jsonl")))


def test_e2e_degrade_ladder_walks_remat_then_accum_with_loss_parity(
        degrade_run):
    """THE acceptance e2e: with --hbm_cap_mb below the naive estimate
    the run emits mem_check{verdict=over}, walks degrade rungs
    (remat -> accum_x2) — each rung RECOMPILES (one compile event per
    attempt) and re-preflights — completes with run_end{exit=ok} in
    one schema-valid stream, and the final loss matches (<=1e-5) a run
    launched directly at the degraded config. The run finishing at all
    pins the donation/AOT sharding invariants: a drifted output
    sharding would reject its own donated outputs at step 2."""
    evs, direct = degrade_run
    assert_stream_valid(evs)
    mcs = [e for e in evs if e["event"] == "mem_check"]
    assert [m["verdict"] for m in mcs] == ["over", "over", "ok"]
    rungs = [e for e in evs if e["event"] == "degrade"]
    assert [r["rung"] for r in rungs] == ["remat", "accum_x2"]
    assert rungs[0]["from"] == "remat=off" and rungs[0]["to"] == "remat=on"
    assert rungs[1]["from"] == "accum=1" and rungs[1]["to"] == "accum=2"
    # each rung recompiled: 1 + len(rungs) compile events, est strictly
    # decreasing down the ladder
    compiles = [e for e in evs if e["event"] == "compile"]
    assert len(compiles) == 1 + len(rungs)
    ests = [m["est_mb"] for m in mcs]
    assert ests[0] > ests[1] > ests[2]
    ends = [e for e in evs if e["event"] == "run_end"]
    assert len(ends) == 1 and ends[0]["exit"] == "ok"
    deg_losses = [e["loss"] for e in evs if e["event"] == "step_stats"]
    # the oracle: launched DIRECTLY at the degraded config (remat on,
    # half micro-batch, doubled accum — same global batch)
    direct_losses = [e["loss"] for e in direct
                     if e["event"] == "step_stats"]
    assert len(deg_losses) == len(direct_losses) == 6
    np.testing.assert_allclose(deg_losses, direct_losses, atol=1e-5)


def test_e2e_ladder_exhausted_raises_with_attempted_rungs(
        gpt2_dir, wiki_dir, tmp_path):
    """When the LAST rung still does not fit, the named error carries
    the full attempted ladder and the stream records every rung. The
    run starts AT --remat, so this also pins the skip rule: a rung
    already enabled is skipped, not re-applied — the ladder goes
    straight to accum_x2 (then offload, via the CLI's builder)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    registry = str(tmp_path / "exh_runs.jsonl")
    with pytest.raises(mg.MemoryAdmissionError) as ei:
        main(_base_argv(gpt2_dir, wiki_dir, tmp_path, "exh", steps=2)
             + ["--batch_size", "8", "--remat", "--hbm_cap_mb", "1",
                "--on_oom_risk", "degrade", "--run_registry", registry])
    assert "remat" not in ei.value.ladder      # already on: skipped
    assert "accum_x2" in ei.value.ladder and "offload" in ei.value.ladder
    evs = read_events(str(tmp_path / "exh.jsonl"))
    assert_stream_valid(evs)
    assert [e["rung"] for e in evs if e["event"] == "degrade"] == \
        ["accum_x2", "offload"]
    assert all(m["verdict"] == "over" for m in evs
               if m["event"] == "mem_check")
    assert evs[-1]["event"] == "run_end" \
        and evs[-1]["exit"] == "MemoryAdmissionError"
    # the admission reject still leaves exactly ONE finalized registry
    # record, carrying the exception name (DESIGN.md §28)
    from mobilefinetuner_tpu.core.run_registry import RunRegistry
    (rec,) = RunRegistry(registry).records()
    assert rec["status"] == "MemoryAdmissionError"


def test_e2e_dispatch_oom_retries_next_rung_lineage_untouched(
        gpt2_dir, wiki_dir, tmp_path):
    """Acceptance: an injected RESOURCE_EXHAUSTED at dispatch is
    retried at the next rung IN PROCESS — mem_check{phase=dispatch} +
    a degrade event land in the stream, the run completes, checkpoint
    lineage stays verifiable, and the rollback machinery is never
    falsely triggered."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    from mobilefinetuner_tpu.io.checkpoints import resolve_checkpoint
    out = str(tmp_path / "oom.safetensors")
    rc = main(_base_argv(gpt2_dir, wiki_dir, tmp_path, "oom", steps=4)
              + ["--batch_size", "8", "--save_every", "2",
                 "--inject", "hbm_pressure:8"])
    assert rc == 0
    evs = read_events(str(tmp_path / "oom.jsonl"))
    assert_stream_valid(evs)
    dispatch = [e for e in evs if e["event"] == "mem_check"
                and e.get("phase") == "dispatch"]
    assert len(dispatch) == 1 and dispatch[0]["verdict"] == "over"
    rungs = [e for e in evs if e["event"] == "degrade"]
    assert rungs and rungs[0]["rung"] == "remat" \
        and rungs[0]["step"] == 0
    assert not [e for e in evs if e["event"] == "rollback"]
    ends = [e for e in evs if e["event"] == "run_end"]
    assert len(ends) == 1 and ends[0]["exit"] == "ok"
    # every step trained exactly once despite the retry
    stats = [e for e in evs if e["event"] == "step_stats"]
    assert stats[-1]["step"] == 4
    # the lineage the run wrote verifies clean end to end
    resolved, step, verdicts = resolve_checkpoint(out, verify=True)
    assert resolved == out and all(v["ok"] for v in verdicts)


# --------------------------- e2e: eval preflight ----------------------------

def test_eval_ppl_preflight_fail_and_warn(gpt2_dir, wiki_dir, tmp_path):
    """Satellite: the compiled eval fn gets the same preflight — fail
    raises the named error before the data loop (stream ends with a
    schema-valid run_end), warn proceeds and completes."""
    from mobilefinetuner_tpu.cli.eval_ppl import main
    # B=32 puts the compiled eval peak (~7 MB logits+activations)
    # decisively over the 1 MB cap; the valid split's real batches are
    # short (drop_last=False) and ride the jit-cache fallback
    argv = ["--pretrained_dir", gpt2_dir, "--data_root", wiki_dir,
            "--split", "valid", "--batch_size", "32", "--seq_len", "64",
            "--max_batches", "2"]
    telem = str(tmp_path / "evalfail.jsonl")
    with pytest.raises(mg.MemoryAdmissionError):
        main(argv + ["--hbm_cap_mb", "1", "--on_oom_risk", "fail",
                     "--telemetry_out", telem])
    evs = read_events(telem)
    assert_stream_valid(evs)
    mcs = [e for e in evs if e["event"] == "mem_check"]
    assert mcs and mcs[0]["verdict"] == "over"
    assert evs[-1]["event"] == "run_end" \
        and evs[-1]["exit"] == "MemoryAdmissionError"
    assert not [e for e in evs if e["event"] == "eval"]

    telem2 = str(tmp_path / "evalwarn.jsonl")
    rc = main(argv + ["--hbm_cap_mb", "1", "--on_oom_risk", "warn",
                      "--telemetry_out", telem2])
    assert rc == 0
    evs = read_events(telem2)
    assert [e for e in evs if e["event"] == "mem_check"]
    assert evs[-1]["event"] == "run_end" and evs[-1]["exit"] == "ok"


# --------------------------- report rendering -------------------------------

def test_reports_render_memory_section(degrade_run):
    """Both report tools render est-vs-cap + ladder decisions from the
    ONE shared builder (telemetry_report.memory_summary)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import fleet_report
    import telemetry_report
    events, _direct = degrade_run
    assert all(telemetry_report.validate_event(e) is None
               for e in events)
    s = telemetry_report.summarize(events)
    m = s["memory"]
    assert m and m["over"] == 2 and len(m["degrades"]) == 2
    assert m["final"]["verdict"] == "ok"
    assert m["final"]["cap_frac"] == pytest.approx(
        m["final"]["est_mb"] / m["final"]["cap_mb"], abs=5e-3)
    lines = telemetry_report.memory_lines(m)
    assert any("DEGRADE remat" in l for l in lines)
    assert any("DEGRADE accum_x2" in l for l in lines)
    # fleet_report: the same builder feeds the per-host rollup
    fs = fleet_report.fleet_summary({0: (events, 0)})
    assert fs["per_host"][0]["memory"]["over"] == 2
    # a memory-less stream renders nothing
    assert telemetry_report.memory_summary(
        [e for e in events if e["event"] == "run_end"]) is None


def test_preflight_eval_compile_names_compile_oom(tmp_path):
    """A RESOURCE_EXHAUSTED from the eval compile ITSELF must land as
    mem_check{verdict=over, phase=compile} + a schema-valid run_end +
    the named error — not an unnamed crash with a truncated stream —
    while any other compile exception passes through untouched."""
    from types import SimpleNamespace

    from mobilefinetuner_tpu.cli.common import preflight_eval_compile
    from mobilefinetuner_tpu.core.telemetry import Telemetry
    args = SimpleNamespace(hbm_cap_mb=8, hbm_headroom=0.1,
                           on_oom_risk="fail")

    def boom():
        raise RuntimeError("RESOURCE_EXHAUSTED: out of memory "
                           "allocating 123 bytes")

    tel = Telemetry(str(tmp_path / "e.jsonl"))
    with pytest.raises(mg.MemoryAdmissionError):
        preflight_eval_compile(boom, args, tel, what="test step")
    evs = read_events(str(tmp_path / "e.jsonl"))
    assert [e["event"] for e in evs] == ["mem_check", "run_end"]
    assert evs[0]["verdict"] == "over" and evs[0]["phase"] == "compile"
    assert evs[-1]["exit"] == "MemoryAdmissionError"

    def other():
        raise ValueError("not an OOM")

    tel2 = Telemetry(str(tmp_path / "e2.jsonl"))
    with pytest.raises(ValueError):
        preflight_eval_compile(other, args, tel2, what="test step")
    assert read_events(str(tmp_path / "e2.jsonl")) == []


def test_fleet_controller_gives_up_on_inadmissible_config(tmp_path):
    """The r13 controller must read run_end{exit=MemoryAdmissionError}
    as an INADMISSIBLE CONFIG — give up with the restart budget
    intact, never re-launch a config that deterministically re-fails
    the same preflight (both the dry-run decision function and the
    live ShardTail carry the verdict)."""
    sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                    "tools"))
    import fleet_controller as fc
    evs = [{"event": "run_start", "seq": 0, "t": 1.0},
           {"event": "run_end", "exit": "MemoryAdmissionError",
            "steps": 0, "wall_s": 0.1, "goodput": None, "seq": 1,
            "t": 2.0}]
    d = fc.decide_worker(evs)
    assert d["decision"] == "give_up"
    assert d["reason"] == "inadmissible_config"
    # a plain crash still restarts (the new branch must not widen)
    evs[1]["exit"] = "ValueError"
    assert fc.decide_worker(evs)["decision"] == "restart"
    # the live tail tracks the latest run_end exit name
    p = str(tmp_path / "w.jsonl")
    tail = fc.ShardTail(p)
    with open(p, "w") as f:
        for e in evs[:1] + [dict(evs[1], exit="MemoryAdmissionError")]:
            f.write(json.dumps(e) + "\n")
    tail.poll()
    assert tail.last_exit == "MemoryAdmissionError"


# --------------------------- partial memory_stats() dicts -------------------
# Some backends return PARTIAL dicts (bytes_in_use without bytes_limit,
# or vice versa), None, or raise outright. Round 23 routes every
# memory_stats read through xla_stats.memory_stat so no consumer
# KeyErrors on those platforms.

class _WeirdDev:
    device_kind = "weird accel"
    platform = "weird"

    def __init__(self, stats):
        self._stats = stats

    def memory_stats(self):
        if isinstance(self._stats, Exception):
            raise self._stats
        return self._stats


def test_memory_stat_guards_every_degenerate_shape():
    from mobilefinetuner_tpu.core.xla_stats import memory_stat
    assert memory_stat(_WeirdDev({"bytes_in_use": 7}), "bytes_in_use") == 7
    # partial dict: the missing key is default, not a KeyError
    assert memory_stat(_WeirdDev({"bytes_in_use": 7}), "bytes_limit") is None
    assert memory_stat(_WeirdDev({"bytes_in_use": 7}), "bytes_limit",
                       default=0) == 0
    assert memory_stat(_WeirdDev(None), "bytes_in_use") is None
    assert memory_stat(_WeirdDev("not a dict"), "bytes_in_use") is None
    assert memory_stat(_WeirdDev(RuntimeError("no stats")),
                       "bytes_in_use") is None
    # a bool is not a byte count even though bool subclasses int
    assert memory_stat(_WeirdDev({"bytes_in_use": True}),
                       "bytes_in_use") is None
    assert memory_stat(_WeirdDev({"bytes_in_use": "123"}),
                       "bytes_in_use") is None


def test_live_hbm_mb_survives_partial_stats_dicts():
    from mobilefinetuner_tpu.core.xla_stats import live_hbm_mb
    # bytes_in_use present WITHOUT bytes_limit: still reported
    devs = [_WeirdDev({"bytes_in_use": 300 * 2 ** 20}),
            _WeirdDev({"bytes_limit": 16 * 2 ** 30})]  # in_use missing
    assert live_hbm_mb(devices=devs) == pytest.approx(300.0)
    # nothing reports: None (not 0.0), and no exception
    assert live_hbm_mb(devices=[_WeirdDev(RuntimeError("boom")),
                                _WeirdDev({})]) is None


def test_device_capacity_falls_through_partial_stats_to_table():
    # bytes_in_use present but bytes_limit ABSENT: the capacity probe
    # must fall through (to the device table / unknown), not KeyError
    dev = _WeirdDev({"bytes_in_use": 123})
    cap, src = mg.device_capacity_mb(device=dev)
    assert (cap, src) == (None, "unknown")
    dev = _WeirdDev({"bytes_in_use": 123})
    dev.device_kind = "TPU v4"
    cap, src = mg.device_capacity_mb(device=dev)
    assert src == "device_table" and cap == 32 * 1024.0
    dev = _WeirdDev(RuntimeError("no stats"))
    dev.device_kind = "TPU v4"
    cap, src = mg.device_capacity_mb(device=dev)
    assert src == "device_table"
