"""graftlint engine tests (DESIGN.md §24): per-rule seeded true-positive
AND clean-negative fixture snippets, suppression-grammar parsing, the
JSON/exit-code CLI contract, the tier-1 gate (the whole package + tools
lint clean), and the compiled-artifact contract checker's tiny CPU run.

Fixture projects are written under tmp as `mobilefinetuner_tpu/<...>`
so the engine's suffix-matched module configuration (STEP_LOOP_MODULES,
THREADED_MODULES, ...) applies to them exactly as to the real tree."""

import json
import os
import subprocess
import sys

import pytest

from mobilefinetuner_tpu.core.static_checks import (
    RULES, Finding, LintError, Project, assert_dots_accumulate_f32,
    collect_emit_sites, hlo_collective_census, hlo_donated_inputs,
    jaxpr_contains, missing_hlo_scopes, parse_suppressions, run_lint)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))

import graft_lint  # noqa: E402


# ---------------------------------------------------------------------------
# fixture harness
# ---------------------------------------------------------------------------

_CASE = [0]


def lint_snippet(tmp_path, relpath, source, rules=None):
    """Write `source` at an ISOLATED tmp/<caseN>/<relpath> and lint the
    fixture package (isolation: earlier snippets in the same test must
    not leak into later lints)."""
    _CASE[0] += 1
    root = tmp_path / f"case{_CASE[0]}"
    full = root / relpath
    full.parent.mkdir(parents=True, exist_ok=True)
    full.write_text(source)
    return run_lint([str(root / relpath.split("/")[0])], rules=rules)


def names(res):
    return [f.rule for f in res.findings]


# ---------------------------------------------------------------------------
# per-rule true positive + clean negative
# ---------------------------------------------------------------------------

def test_sync_hazard_positive_and_negative(tmp_path):
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/train/trainer.py", """
def loop(x):
    return float(x)
""", rules=["sync-hazard"])
    assert names(res) == ["sync-hazard"]
    assert res.findings[0].line == 3
    # host-dataflow negative: device_get'd values may be converted
    # freely, and a module OUTSIDE the step-loop set is never flagged
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/train/trainer.py", """
import jax

def flush(buffered):
    fetched = jax.device_get(buffered)  # graftlint: disable=sync-hazard(the one flush get)
    return [float(m) for m in fetched]
""", rules=["sync-hazard"])
    assert not res.findings and len(res.suppressed) == 1
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/io/somewhere.py",
                       "def f(x):\n    return float(x)\n",
                       rules=["sync-hazard"])
    assert not res.findings


def test_sync_hazard_self_assignment_is_not_laundered(tmp_path):
    # `x = np.asarray(x)` must still flag: the name being defined by
    # the very statement is not evidence the argument was host data
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/serve/engine.py", """
import numpy as np

def step(nxt):
    nxt = np.asarray(nxt)
    return nxt
""", rules=["sync-hazard"])
    assert names(res) == ["sync-hazard"]


def test_donation_hazard_positive_and_negative(tmp_path):
    src_bad = """
from mobilefinetuner_tpu.train.trainer import make_train_step

def run(loss_fn, tc, frozen, batch, i):
    step = make_train_step(loss_fn, tc)
    tr, opt = init()
    out = step(tr, frozen, opt, batch, i)
    return tr  # read after donation
"""
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/cli/common.py",
                       src_bad, rules=["donation-hazard"])
    assert names(res) == ["donation-hazard"]
    src_ok = src_bad.replace("out = step(", "tr, opt, m = step(") \
                    .replace("return tr  # read after donation",
                             "return tr  # rebound by the dispatch")
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/cli/common.py",
                       src_ok, rules=["donation-hazard"])
    assert not res.findings
    # donate=False builders do not donate
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/cli/common.py",
                       src_bad.replace("make_train_step(loss_fn, tc)",
                                       "make_train_step(loss_fn, tc, "
                                       "donate=False)"),
                       rules=["donation-hazard"])
    assert not res.findings


def test_donation_hazard_sees_jit_and_lower_compile_chains(tmp_path):
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/cli/common.py", """
import jax

def run(f, a, b, batch):
    step = jax.jit(f, donate_argnums=(0,))
    compiled = step.lower(a, b, batch).compile()
    out = compiled(a, b, batch)
    return a
""", rules=["donation-hazard"])
    assert names(res) == ["donation-hazard"]


def test_donation_hazard_tracks_self_attribute_steps(tmp_path):
    # the engines' real dispatch pattern: the jitted step lives on
    # self (bound in a builder method, dispatched from another), the
    # donated args are self attributes, and donate_argnums is the
    # conditional `(...) if donate else ()` CPU opt-out spelling
    src_bad = """
import jax

class Engine:
    def build(self, step_py, donate):
        self._step = jax.jit(step_py,
                             donate_argnums=(0, 1) if donate else ())

    def step(self, tok):
        nxt, pk, pv = self._step(
            self.pool_k, self.pool_v, tok)
        return self.pool_k  # read after donation
"""
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/serve/engine.py",
                       src_bad, rules=["donation-hazard"])
    assert names(res) == ["donation-hazard"]
    assert "self.pool_k" in res.findings[0].message
    # rebinding the attributes from the dispatch output clears them —
    # whether on the dispatch's own statement or a later one
    src_ok = src_bad.replace(
        "nxt, pk, pv = self._step(",
        "nxt, self.pool_k, self.pool_v = self._step(").replace(
        "return self.pool_k  # read after donation",
        "return self.pool_k  # rebound by the dispatch")
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/serve/engine.py",
                       src_ok, rules=["donation-hazard"])
    assert not res.findings
    src_ok2 = src_bad.replace(
        "return self.pool_k  # read after donation",
        "self.pool_k, self.pool_v = pk, pv\n        return self.pool_k")
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/serve/engine.py",
                       src_ok2, rules=["donation-hazard"])
    assert not res.findings


def test_untraced_branch_positive_and_negative(tmp_path):
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/ops/foo.py", """
import jax

@jax.jit
def f(x):
    if x > 0:
        return x
    return -x
""", rules=["untraced-branch"])
    assert names(res) == ["untraced-branch"]
    # negatives: is-None / dict-membership / static attrs / static args
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/ops/foo.py", """
import jax
from functools import partial

@partial(jax.jit, static_argnames=("mode",))
def f(x, y, mode="a"):
    if x is None:
        return y
    if "k" in y:
        return x
    if x.shape[0] > 2:
        return x
    if mode == "b":
        return x
    return x + 1
""", rules=["untraced-branch"])
    assert not res.findings


def test_dtype_accum_positive_and_negative(tmp_path):
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/ops/foo.py", """
import jax.numpy as jnp

def f(a, b):
    return jnp.einsum("ij,jk->ik", a, b)
""", rules=["dtype-accum"])
    assert names(res) == ["dtype-accum"]
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/ops/foo.py", """
import jax.numpy as jnp

def f(a, b):
    return jnp.einsum("ij,jk->ik", a, b,
                      preferred_element_type=jnp.float32)
""", rules=["dtype-accum"])
    assert not res.findings
    # outside models//ops/ the rule does not apply (host-side math)
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/cli/common.py",
                       "import jax.numpy as jnp\n"
                       "def f(a, b):\n"
                       "    return jnp.matmul(a, b)\n",
                       rules=["dtype-accum"])
    assert not res.findings


def test_emit_schema_positive_and_negative(tmp_path):
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/foo.py", """
def f(tel):
    tel.emit("bogus_event", step=1)
""", rules=["emit-schema"])
    assert names(res) == ["emit-schema"]
    assert "bogus_event" in res.findings[0].message
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/foo.py", """
def f(tel, sink):
    tel.emit("run_start", config={})
    sink(event="step_stats", step=1)
""", rules=["emit-schema"])
    assert not res.findings


def test_emit_fields_positive_and_negative(tmp_path):
    # a literal-kwarg emit site that silently drops a required schema
    # field is the dead-taxonomy bug in miniature: the round-23 `run`
    # record contract (DESIGN.md §28) only holds if every field is
    # carried explicitly (None included)
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/foo.py", """
def f(tel):
    tel.emit("trend", metric="tok_s", config="c", platform="tpu",
             value=1.0, median=1.0, mad=0.0, z=0.0,
             direction="higher", regressed=False, run="r01")
""", rules=["emit-fields"])
    assert names(res) == ["emit-fields"]
    assert "n" in res.findings[0].message.split("field(s)")[1]
    # full field set: clean
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/foo.py", """
def f(tel):
    tel.emit("trend", metric="tok_s", config="c", platform="tpu",
             value=1.0, median=None, mad=None, z=None,
             direction=None, regressed=False, run="r01", n=1)
""", rules=["emit-fields"])
    assert not res.findings


def test_emit_fields_skips_splats_and_unknown_events(tmp_path):
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/foo.py", """
def f(tel, payload):
    tel.emit("run", **payload)       # runtime validate_event's job
    tel.emit("bogus_event", step=1)  # emit-schema's job, not ours
    tel.emit(name, step=1)           # dynamic event name: unknowable
""", rules=["emit-fields"])
    assert not res.findings


def test_serve_taxonomy_positive_and_negative(tmp_path):
    from mobilefinetuner_tpu.core.telemetry import (REQUEST_PHASES,
                                                    REQUEST_REASONS)
    lines = ["def f(emit):"]
    for p in REQUEST_PHASES:
        lines.append(f'    emit(phase="{p}")')
    for r in sorted(REQUEST_REASONS):
        lines.append(f'    emit(reason="{r}")')
    clean = "\n".join(lines) + "\n"
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/serve/engine.py",
                       clean, rules=["serve-taxonomy"])
    assert not res.findings
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/serve/engine.py",
                       clean + '\ndef g(emit):\n'
                               '    emit(phase="warp_speed")\n',
                       rules=["serve-taxonomy"])
    assert names(res) == ["serve-taxonomy"]
    assert "warp_speed" in res.findings[0].message


def test_lock_discipline_positive_and_negative(tmp_path):
    base = """
import threading

GRAFT_SHARED_STATE = {{
    "Box": {{"lock": "_lock", "guarded": ["_val"],
             "locked_helpers": ["_bump"], "channels": []}},
}}


class Box:
    def __init__(self):
        self._lock = threading.Lock()
        self._val = 0

    def _bump(self):
        self._val += 1

    def set(self, v):
        {set_body}

    def get(self):
        {get_body}
"""
    ok = base.format(
        set_body="with self._lock:\n            self._val = v",
        get_body="with self._lock:\n            return self._val")
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/io/async_ckpt.py",
                       ok, rules=["lock-discipline"])
    assert not res.findings
    # guarded access outside the lock + locked helper called unlocked
    bad = base.format(set_body="self._val = v",
                      get_body="self._bump()\n        return 0")
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/io/async_ckpt.py",
                       bad, rules=["lock-discipline"])
    assert sorted(names(res)) == ["lock-discipline", "lock-discipline"]
    msgs = " ".join(f.message for f in res.findings)
    assert "_val" in msgs and "_bump" in msgs
    # a threaded module with NO declaration is itself a finding
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/data/prefetch.py",
                       "x = 1\n", rules=["lock-discipline"])
    assert names(res) == ["lock-discipline"]
    assert "GRAFT_SHARED_STATE" in res.findings[0].message


def test_no_jax_import_positive_and_negative(tmp_path):
    # policy "never": even a lazy in-function import fails metrics_http
    res = lint_snippet(tmp_path,
                       "mobilefinetuner_tpu/core/metrics_http.py",
                       "def f():\n    import jax\n    return jax\n",
                       rules=["no-jax-import"])
    assert names(res) == ["no-jax-import"]
    # policy "toplevel": trace.py may import jax lazily, not at module
    # level
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/trace.py",
                       "def f():\n    import jax\n    return jax\n",
                       rules=["no-jax-import"])
    assert not res.findings
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/trace.py",
                       "from jax import profiler\n",
                       rules=["no-jax-import"])
    assert names(res) == ["no-jax-import"]
    # "toplevel" means import-time execution, not lexical depth: the
    # `try: import jax` idiom still runs at module level
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/core/trace.py", """
try:
    import jax
except ImportError:
    jax = None
""", rules=["no-jax-import"])
    assert names(res) == ["no-jax-import"]


# ---------------------------------------------------------------------------
# suppression grammar
# ---------------------------------------------------------------------------

def test_suppression_same_line_standalone_and_comma_reasons():
    src = ("x = float(y)  # graftlint: disable=sync-hazard(why, with a comma)\n"
           "# graftlint: disable=dtype-accum(covers the NEXT line)\n"
           "z = 1\n")
    table, bad = parse_suppressions(src, "f.py")
    assert not bad
    assert table[1] == {"sync-hazard": "why, with a comma"}
    assert table[3] == {"dtype-accum": "covers the NEXT line"}


def test_suppression_requires_reason_and_known_rule():
    table, bad = parse_suppressions(
        "x = 1  # graftlint: disable=sync-hazard\n", "f.py")
    assert not table.get(1) and len(bad) == 1
    assert bad[0].rule == "bad-suppression"
    table, bad = parse_suppressions(
        "x = 1  # graftlint: disable=not-a-rule(reason)\n", "f.py")
    assert not table.get(1) and len(bad) == 1
    assert "unknown rule" in bad[0].message


def test_reasonless_suppression_is_a_finding_not_an_exemption(tmp_path):
    res = lint_snippet(tmp_path, "mobilefinetuner_tpu/train/trainer.py", """
def loop(x):
    return float(x)  # graftlint: disable=sync-hazard
""", rules=["sync-hazard"])
    assert sorted(names(res)) == ["bad-suppression", "sync-hazard"]


# ---------------------------------------------------------------------------
# CLI contract: JSON shape + bench_compare-style exit codes
# ---------------------------------------------------------------------------

def test_graft_lint_json_output_and_exit_codes(tmp_path, capsys):
    pkg = tmp_path / "mobilefinetuner_tpu" / "ops"
    pkg.mkdir(parents=True)
    (pkg / "foo.py").write_text(
        "import jax.numpy as jnp\n"
        "def f(a, b):\n"
        "    return jnp.matmul(a, b)\n")
    rc = graft_lint.main([str(tmp_path / "mobilefinetuner_tpu"),
                          "--format", "json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 2
    assert out["counts"] == {"findings": 1, "suppressed": 0}
    f = out["findings"][0]
    assert {"rule", "path", "line", "col", "message", "suppressed",
            "reason"} <= set(f)
    assert f["rule"] == "dtype-accum"
    assert f["path"].endswith("ops/foo.py")
    # clean tree -> 0
    (pkg / "foo.py").write_text("x = 1\n")
    assert graft_lint.main([str(tmp_path / "mobilefinetuner_tpu"),
                            "--format", "json"]) == 0
    capsys.readouterr()
    # engine errors -> 1 (bad path, unknown rule, syntax error)
    assert graft_lint.main([str(tmp_path / "nope")]) == 1
    assert graft_lint.main([str(tmp_path / "mobilefinetuner_tpu"),
                            "--rules", "made-up"]) == 1
    (pkg / "foo.py").write_text("def broken(:\n")
    assert graft_lint.main([str(tmp_path / "mobilefinetuner_tpu")]) == 1
    capsys.readouterr()


def test_graft_lint_cli_subprocess_smoke():
    """The real entry point, end to end: `--list-rules` exits 0 and
    names every shipped rule (the CLI imports only the stdlib half, so
    this stays fast)."""
    out = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graft_lint.py"),
         "--list-rules"], capture_output=True, text=True, timeout=60)
    assert out.returncode == 0
    for rule in RULES:
        assert rule in out.stdout


# ---------------------------------------------------------------------------
# the tier-1 gate: the whole repo lints clean
# ---------------------------------------------------------------------------

def test_package_and_tools_lint_clean():
    """THE enforcement test (the CI satellite): zero unsuppressed
    findings over mobilefinetuner_tpu/ + tools/ with every shipped
    rule. A new module that breaks an invariant — or suppresses one
    without a reason — fails tier-1 here."""
    res = run_lint([os.path.join(REPO, "mobilefinetuner_tpu"),
                    os.path.join(REPO, "tools")])
    assert not res.findings, "\n" + "\n".join(
        f.render() for f in res.findings)
    # the suppression inventory is intentional, reasoned, and small —
    # every entry names its rule and carries prose
    assert all(f.reason for f in res.suppressed)
    assert len(res.suppressed) < 40, "suppressions are creeping: " \
        "fix findings instead of papering over them"


def test_threaded_modules_all_declare_shared_state():
    """Every threaded host subsystem carries a GRAFT_SHARED_STATE
    declaration (the lock-discipline rule's input, and the reader's
    map of the module's cross-thread contract)."""
    from mobilefinetuner_tpu.core.static_checks import THREADED_MODULES
    # the r22 serve router lives in tools/, so the scan covers both
    # roots (run_lint's tier-1 gate above already does)
    proj = Project([os.path.join(REPO, "mobilefinetuner_tpu"),
                    os.path.join(REPO, "tools")])
    declared = {m.relpath for m in proj.modules
                if "GRAFT_SHARED_STATE" in m.source}
    for suffix in THREADED_MODULES:
        assert any(p.endswith(suffix) for p in declared), suffix


# ---------------------------------------------------------------------------
# compiled-artifact helpers (unit level, synthetic HLO)
# ---------------------------------------------------------------------------

_HLO = '''HloModule jit_step, input_output_alias={ {0}: (0, {}, may-alias), {1}: (2, {}, may-alias) }, entry_computation_layout={()->f32[]}

ENTRY main {
  %ag = f32[8]{0} all-gather(f32[2]{0} %p0), replica_groups={}, metadata={op_name="jit(step)/jit(main)/transpose(jvp(embed))/gather"}
  %ar.1 = f32[8]{0} all-reduce-start(f32[8]{0} %ag), metadata={op_name="jit(step)/mlp/add"}
  %ar.2 = f32[8]{0} all-reduce-done(f32[8]{0} %ar.1)
  %r = f32[] dot(f32[8]{0} %ar.2, f32[8]{0} %ag), metadata={op_name="jit(step)/loss/dot"}
}
'''


def test_hlo_census_donation_and_scope_helpers():
    census = hlo_collective_census(_HLO)
    assert census["all-gather"] == 1
    assert census["all-reduce"] == 1  # -start counted once, -done not
    assert census["reduce-scatter"] == 0
    assert hlo_donated_inputs(_HLO) == 2
    assert missing_hlo_scopes(_HLO, ["embed", "mlp", "loss"]) == []
    # "emb" must NOT match inside "embed" (component-delimited match)
    assert missing_hlo_scopes(_HLO, ["emb", "optimizer"]) == \
        ["emb", "optimizer"]


def test_jaxpr_helpers_find_dots_and_pallas():
    import jax.numpy as jnp

    def good(a, b):
        return jnp.einsum("ij,jk->ik", a, b,
                          preferred_element_type=jnp.float32)

    def bad(a, b):
        return a @ b  # follows input dtype

    a = jnp.zeros((4, 4), jnp.bfloat16)
    assert_dots_accumulate_f32(good, a, a)
    with pytest.raises(AssertionError):
        assert_dots_accumulate_f32(bad, a, a)
    assert not jaxpr_contains(good, "pallas_call", a, a)
    assert jaxpr_contains(good, "dot_general", a, a)


def test_collect_emit_sites_sees_both_spellings(tmp_path):
    full = tmp_path / "mobilefinetuner_tpu" / "m.py"
    full.parent.mkdir(parents=True)
    full.write_text("tel.emit('run_start', config={})\n"
                    "sink(event='checkpoint', step=1)\n")
    found = collect_emit_sites(
        Project([str(tmp_path / "mobilefinetuner_tpu")]).modules)
    assert set(found) == {"run_start", "checkpoint"}


def test_finding_render_and_lint_error():
    f = Finding("sync-hazard", "a/b.py", 3, 7, "boom",
                suppressed=True, reason="why")
    assert f.render() == "a/b.py:3:7: sync-hazard: boom  [suppressed: why]"
    with pytest.raises(LintError):
        run_lint([os.path.join(REPO, "mobilefinetuner_tpu")],
                 rules=["no-such-rule"])


# ---------------------------------------------------------------------------
# compiled-artifact contract checker: tiny CPU run + violation exit
# ---------------------------------------------------------------------------

def test_check_compiled_contracts_cpu(tmp_path, capsys):
    """The pinned contracts hold on this container (retraces, donation,
    collective census, scopes for train/decode/multitenant programs),
    and a tampered pin exits 2 naming the drifted key."""
    import check_compiled_contracts as ccc
    assert ccc.main(["--programs",
                     "train_gpt2_lora,decode_gpt2_paged,"
                     "multitenant_gpt2"]) == 0
    capsys.readouterr()
    with open(os.path.join(REPO, "tools", "compiled_contracts.json")) as f:
        doc = json.load(f)
    for prog in ("train_gpt2_lora", "train_gpt2_fsdp",
                 "decode_gpt2_paged", "multitenant_gpt2"):
        c = doc["programs"][prog]
        assert set(c) == {"retraces", "donated", "collectives", "scopes"}
    # one executable across 3 same-shape calls, pinned
    assert doc["programs"]["train_gpt2_lora"]["retraces"] == 1
    assert doc["programs"]["train_gpt2_lora"]["donated"] > 0
    assert doc["programs"]["decode_gpt2_paged"]["donated"] == 2  # pools
    # tamper: a surprise all-gather in the solo train program must fail
    doc["programs"]["train_gpt2_lora"]["collectives"]["all-gather"] = 3
    tampered = tmp_path / "contracts.json"
    tampered.write_text(json.dumps(doc))
    rc = ccc.main(["--contracts", str(tampered),
                   "--programs", "train_gpt2_lora"])
    out = capsys.readouterr().out
    assert rc == 2
    assert "VIOLATION" in out and "collectives" in out
