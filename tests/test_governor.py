"""Step governor tests — mirror the reference's energy-function shell tests
(reference: scripts/benchmark/test_energy_function.sh: schedule parsing and
throttle behavior driven by --pm_manual_batt/--pm_manual_temp mocked
telemetry)."""

import pytest

from mobilefinetuner_tpu.system.governor import (GovernorConfig, MAX_SLEEP_MS,
                                                 StepGovernor, StepSleep,
                                                 parse_schedule)


def test_parse_schedule_ranges():
    s = parse_schedule("0-99:300,100-199:150,200-:50")
    assert s == [StepSleep(0, 99, 300.0), StepSleep(100, 199, 150.0),
                 StepSleep(200, None, 50.0)]


def test_parse_schedule_single_step_and_whitespace():
    s = parse_schedule(" 5 : 25 , 10 - 20 : 75 ")
    assert s == [StepSleep(5, 5, 25.0), StepSleep(10, 20, 75.0)]


def test_parse_schedule_rejects_garbage():
    with pytest.raises(ValueError):
        parse_schedule("abc:10")
    assert parse_schedule("") == []


def test_disabled_governor_never_sleeps():
    gov = StepGovernor(GovernorConfig(enable=False, schedule="0-:1000"))
    assert gov.suggest_sleep_ms(0) == 0.0


def test_schedule_overrides_telemetry():
    cfg = GovernorConfig(enable=True, schedule="0-99:300,100-199:150,200-:50",
                         manual_battery=5.0, manual_temp=90.0)
    gov = StepGovernor(cfg)
    assert gov.suggest_sleep_ms(0) == 300.0
    assert gov.suggest_sleep_ms(99) == 300.0
    assert gov.suggest_sleep_ms(100) == 150.0
    assert gov.suggest_sleep_ms(250) == 50.0


def test_telemetry_policy_healthy_fast():
    cfg = GovernorConfig(enable=True, manual_battery=80.0, manual_temp=30.0,
                         freq_batt_high=10.0, freq_temp_high=10.0)
    gov = StepGovernor(cfg)
    assert gov.suggest_sleep_ms(0) == pytest.approx(100.0)  # 1000/10


def test_telemetry_low_battery_throttles():
    cfg = GovernorConfig(enable=True, manual_battery=10.0, manual_temp=30.0,
                         battery_threshold=20.0, freq_batt_low=1.0)
    gov = StepGovernor(cfg)
    assert gov.suggest_sleep_ms(0) == pytest.approx(1000.0)


def test_telemetry_hot_takes_min_frequency():
    # battery fine (f=10), temp hot (f=0.5) -> min wins -> 2000 ms
    cfg = GovernorConfig(enable=True, manual_battery=80.0, manual_temp=55.0,
                         temp_threshold=40.0, freq_temp_low=0.5)
    gov = StepGovernor(cfg)
    assert gov.suggest_sleep_ms(0) == pytest.approx(2000.0)


def test_sleep_clamped_to_max():
    cfg = GovernorConfig(enable=True, manual_temp=99.0, freq_temp_low=0.01)
    gov = StepGovernor(cfg)
    assert gov.suggest_sleep_ms(0) == MAX_SLEEP_MS
    gov2 = StepGovernor(GovernorConfig(enable=True, schedule="0-:99999"))
    assert gov2.suggest_sleep_ms(0) == MAX_SLEEP_MS


def test_check_interval_caches_between_checks():
    """Telemetry is only re-read every check_interval_steps
    (power_monitor.cpp:72-96)."""
    reads = []

    def batt():
        reads.append(1)
        return 80.0

    cfg = GovernorConfig(enable=True, check_interval_steps=10)
    gov = StepGovernor(cfg, battery_fn=batt)
    for step in range(10):
        gov.suggest_sleep_ms(step)
    assert len(reads) == 1
    gov.suggest_sleep_ms(10)
    assert len(reads) == 2


def test_manual_injection_forces_recheck():
    cfg = GovernorConfig(enable=True, check_interval_steps=100,
                         manual_battery=80.0)
    gov = StepGovernor(cfg)
    fast = gov.suggest_sleep_ms(0)
    gov.set_manual_telemetry(battery=5.0)
    slow = gov.suggest_sleep_ms(1)
    assert slow > fast


def test_throttle_sleeps(monkeypatch):
    slept = []
    import mobilefinetuner_tpu.system.governor as G
    monkeypatch.setattr(G.time, "sleep", lambda s: slept.append(s))
    gov = StepGovernor(GovernorConfig(enable=True, schedule="0-:100"))
    gov.throttle(0)
    assert slept == [pytest.approx(0.1)]


def test_throttle_emits_telemetry_event(monkeypatch):
    """Every sleeping throttle() reports {step, sleep_ms, battery, temp,
    source} through event_sink — the run-telemetry `throttle` event, so
    duty-cycle decisions stop being invisible step-time stretches."""
    import mobilefinetuner_tpu.system.governor as G
    monkeypatch.setattr(G.time, "sleep", lambda s: None)
    events = []
    cfg = GovernorConfig(enable=True, schedule="0-4:250",
                         manual_battery=77.0, manual_temp=31.0)
    gov = StepGovernor(cfg, event_sink=events.append)
    gov.throttle(2)
    assert events == [{"step": 2, "sleep_ms": 250.0, "battery": 77.0,
                       "temp": 31.0, "source": "schedule"}]
    # same decision on later steps: NO new event (the stream must not
    # grow per-step on a steady duty cycle)...
    gov.throttle(3)
    gov.throttle(4)
    assert len(events) == 1
    # ...but a CHANGED decision emits again: past the schedule range the
    # telemetry policy takes over (healthy sensors -> 100 ms)
    gov.throttle(5)
    assert len(events) == 2
    assert events[1]["sleep_ms"] == pytest.approx(100.0)
    assert events[1]["source"] == "telemetry"
    # uncovered step under the telemetry policy -> source "telemetry"
    cfg2 = GovernorConfig(enable=True, check_interval_steps=1,
                          manual_battery=5.0, battery_threshold=20.0,
                          freq_batt_low=1.0)
    events2 = []
    gov2 = StepGovernor(cfg2, event_sink=events2.append)
    gov2.throttle(0)
    assert events2[0]["source"] == "telemetry"
    assert events2[0]["sleep_ms"] == pytest.approx(1000.0)
    assert events2[0]["battery"] == 5.0
    # a zero-sleep step emits nothing
    gov3 = StepGovernor(GovernorConfig(enable=False),
                        event_sink=events2.append)
    gov3.throttle(0)
    assert len(events2) == 1


def test_throttle_event_validates_against_telemetry_schema(monkeypatch):
    from mobilefinetuner_tpu.core.telemetry import validate_event
    import mobilefinetuner_tpu.system.governor as G
    monkeypatch.setattr(G.time, "sleep", lambda s: None)
    recs = []
    gov = StepGovernor(
        GovernorConfig(enable=True, schedule="0-:100"),
        event_sink=lambda p: recs.append(
            {"event": "throttle", "seq": 0, "t": 0.0, **p}))
    gov.throttle(3)
    assert recs and validate_event(recs[0]) is None
