"""Native C++ BPE engine parity + build machinery.

The native merge engine (native/fast_bpe.cpp) must match the Python
reference (data/tokenizer_bpe.py _bpe + vocab lookup) token-for-token —
the Python side is itself HF-oracle-tested (test_tokenizers.py), so
transitively the native path is HF-aligned too. Reference analog:
core/test_tokenizer_bpe.cpp parity cases against the C++ tokenizer.
"""

import os
import shutil

import numpy as np
import pytest

from tests.fixtures import WIKI_LINES, train_tiny_gpt2_tokenizer

pytestmark = pytest.mark.skipif(shutil.which("g++") is None,
                                reason="no g++ in environment")


@pytest.fixture(scope="module")
def tok_pair(tmp_path_factory):
    """(native-enabled, python-only) tokenizers over the same tiny vocab."""
    from mobilefinetuner_tpu.data.tokenizer_bpe import GPT2BPETokenizer
    d = str(tmp_path_factory.mktemp("tok"))
    train_tiny_gpt2_tokenizer(d)
    native = GPT2BPETokenizer.from_pretrained(d)
    if native._native is None:
        pytest.skip("native BPE library failed to build")
    python = GPT2BPETokenizer.from_pretrained(d, use_native=False)
    return native, python


def test_native_library_builds():
    if os.environ.get("MFT_NO_NATIVE_BPE") == "1":
        pytest.skip("native BPE disabled by env")
    from mobilefinetuner_tpu.native.fast_bpe import load_library
    assert load_library() is not None
    assert os.path.exists(os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "..",
        "mobilefinetuner_tpu", "native", "libfast_bpe.so"))


def test_native_matches_python_on_corpus(tok_pair):
    native, python = tok_pair
    text = "\n".join(WIKI_LINES)
    assert native.encode(text) == python.encode(text)


def test_native_matches_python_on_hard_cases(tok_pair):
    native, python = tok_pair
    cases = [
        "hello world", "  double  spaces  ", "don't stop",
        "Prices rose 3.5% to $1,234.56!", "naïve café über",
        "emoji 🙂 and 中文 bytes", "a", "", "\n\n\t",
        "CamelCaseWords and snake_case_words",
        "<|endoftext|> special <|endoftext|>",
        "x" * 300,  # long single word: deep merge recursion
    ]
    for c in cases:
        assert native.encode(c) == python.encode(c), c


def test_native_matches_python_on_random_bytes(tok_pair):
    native, python = tok_pair
    rng = np.random.default_rng(0)
    for _ in range(50):
        raw = bytes(rng.integers(0, 256, rng.integers(1, 64)))
        text = raw.decode("utf-8", errors="replace")
        assert native.encode(text) == python.encode(text)


def test_env_var_disables_native(tmp_path, monkeypatch):
    monkeypatch.setenv("MFT_NO_NATIVE_BPE", "1")
    from mobilefinetuner_tpu.native import fast_bpe
    # the env check runs before the shared cache lookup (native/build.py),
    # so no cache reset is needed
    assert fast_bpe.load_library() is None


def test_native_is_faster_on_uncached_words(tok_pair):
    """The point of the native path: the merge loop on fresh words. Not a
    strict benchmark — asserts only a sane ratio to catch pathological
    regressions (full numbers: tools/bench_tokenizer.py)."""
    import time
    native, python = tok_pair
    rng = np.random.default_rng(1)
    # unique pseudo-words defeat the per-word cache
    words = [" w" + "".join(chr(97 + c) for c in rng.integers(0, 26, 12))
             for _ in range(3000)]
    text = "".join(words)

    t0 = time.perf_counter()
    out_n = native.encode(text)
    t_native = time.perf_counter() - t0
    t0 = time.perf_counter()
    out_p = python.encode(text)
    t_python = time.perf_counter() - t0
    assert out_n == out_p
    assert t_native < t_python * 1.5, (t_native, t_python)