"""bench.py driver contract: exactly ONE JSON metric line on stdout
(printed right after the headline row, so a tail timeout cannot lose
it), per-row atomic BENCH_SUITE.json flushes, and a failed headline
reporting value 0 without aborting the rest of the run.

The heavy bench functions are stubbed — this pins the harness plumbing
the round scoring depends on, not the measurements."""

import contextlib
import io
import json
import os

import pytest


@pytest.fixture
def bench(monkeypatch, tmp_path):
    import bench as b
    monkeypatch.chdir(tmp_path)

    def fake_bench(dtype, steps, **kw):
        return {"dt": 1.0, "loss": 1.23, "peak_bytes": 2 ** 30,
                "flops": 10 ** 12, "tokens": 1000,
                "loss_tokens_seen": 24576}

    monkeypatch.setattr(b, "bench_gpt2_lora", fake_bench)
    return b


def run_main(b):
    buf = io.StringIO()
    with contextlib.redirect_stdout(buf):
        rc = b.main()
    lines = [ln for ln in buf.getvalue().splitlines() if ln.strip()]
    return rc, lines


def test_single_stdout_line_and_suite_artifact(bench):
    rc, lines = run_main(bench)
    assert rc == 0
    # exactly one stdout line, the driver metric schema
    assert len(lines) == 1, lines
    m = json.loads(lines[0])
    assert m["metric"] == "gpt2s_lora_train_tokens_per_sec_per_chip"
    assert m["unit"] == "tokens/sec/chip"
    assert m["value"] > 0 and m["vs_baseline"] is not None
    # the incremental flush left a valid artifact with the headline row
    with open("BENCH_SUITE.json") as f:
        suite = json.load(f)
    assert suite["suite"][0]["config"].startswith("gpt2s_lora_bf16")
    assert suite["suite"][0]["loss"] == 1.23
    # atomic-replace leaves no temp file behind
    assert not os.path.exists("BENCH_SUITE.json.tmp")


def test_input_pipeline_row_shape_and_tiny_e2e(bench):
    """The input-pipeline rows carry the host/device breakdown: run the
    REAL bench_input_pipeline (tiny model, CPU) prefetch off vs on and
    check the pipe_finish row schema — tokens/s plus host_wait_frac, the
    number the round scoring reads for the overlap claim."""
    import jax.numpy as jnp
    for prefetch in (0, 2):
        r = bench.bench_input_pipeline(jnp.float32, steps=3, size="tiny",
                                       B=2, S=32, prefetch=prefetch,
                                       warmup=1)
        assert r["tokens"] == 2 * 2 * 32  # B * accum * S
        assert r["host_wait_ms"] >= 0 and r["dt"] > 0
        row = bench.pipe_finish(f"pipe{prefetch}", r, "float32", 3)
        assert row["tokens_per_sec_per_chip"] > 0
        assert 0.0 <= row["host_wait_frac"] <= 1.0
        assert row["host_wait_ms_per_step"] >= 0
        assert "loss" in row and "peak_hbm_mb" in row
    # no leaked producer threads after the rows complete
    import threading
    assert not [t for t in threading.enumerate()
                if t.name == "batch-producer"]


def test_bench_and_telemetry_share_the_flops_estimator():
    """bench.py's MFU column and the in-loop telemetry MFU must use the
    SAME transformer_flops function — identity, not equality, so the
    estimators cannot drift apart."""
    import bench as b
    from mobilefinetuner_tpu.core import telemetry
    assert b.transformer_flops is telemetry.transformer_flops


def test_failed_headline_reports_zero_and_exits_nonzero(bench,
                                                        monkeypatch):
    def boom(dtype, steps, **kw):
        raise RuntimeError("compile service hiccup")

    monkeypatch.setattr(bench, "bench_gpt2_lora", boom)
    rc, lines = run_main(bench)
    assert rc == 1
    assert len(lines) == 1
    m = json.loads(lines[0])
    assert m["value"] == 0.0 and "hiccup" in m["error"]
    # the error row still landed in the artifact (run() records, not
    # raises — off-TPU there are no further rows, but the suite file
    # must exist and be valid JSON either way)
    with open("BENCH_SUITE.json") as f:
        suite = json.load(f)
    assert "error" in suite["suite"][0]


def test_bench_decode_row_contract():
    """tools/bench_decode.py rows (round 11): TPOT (= the marginal
    ms/token the tool always measured), TTFT (max_new_tokens=1 e2e
    wall), and the --adapters k stacked-bank mode — schema pinned on the
    tiny CPU config, base vs k=2 both. Round 12 adds the lora_impl
    column: every row names the models/lora_apply.py path it ran, and a
    forced non-auto impl lands in the config name."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import jax.numpy as jnp
    import bench_decode as bd
    for k, li in ((0, "auto"), (2, "fused")):
        row = bd.bench_model(False, B=2, P=8, dtype=jnp.float32,
                             pipeline=1, adapters=k, tiny=True,
                             n_pair=(2, 4), lora_impl=li)
        assert row["adapters"] == k
        assert row["lora_impl"] == li
        assert row["config"].endswith("_k2") == (k == 2 and li == "auto")
        assert ("_lorafused" in row["config"]) == (li == "fused")
        for key in ("ttft_ms", "sustained_tok_s", "wall_ms_lo",
                    "wall_ms_hi"):
            assert isinstance(row[key], (int, float)) and row[key] > 0, key
        assert isinstance(row["tpot_ms"], (int, float))  # marginal: may
        # jitter near 0 on CPU at tiny sizes, but must be present/finite
        assert row["wall_ms_hi"] >= row["wall_ms_lo"] * 0.5


def test_bench_lora_impl_rows_tiny_cpu(monkeypatch):
    """bench.py's r12 lorafused-vs-loranaive row pairs: the REAL
    bench_gpt2_lora in tiny CPU mode, both impls — finish() carries the
    lora_impl column and the pair's losses agree (the bench rows
    measure speed over an identical compute graph contract)."""
    import bench as b
    import jax.numpy as jnp
    monkeypatch.setattr(b, "LOSS_MARK_TOKENS", 512)  # 4 steps at B2 S64
    rows = {}
    for li in ("naive", "fused"):
        r = b.bench_gpt2_lora(B=2, S=64, dtype=jnp.float32, steps=2,
                              size="tiny", lora_impl=li)
        assert r["lora_impl"] == li
        row = b.finish(f"gpt2s_tiny_lora{li}", r, "float32", 2)
        assert row["lora_impl"] == li
        assert row["tokens_per_sec_per_chip"] > 0
        rows[li] = row
    # parity contract: same seeded stream, same graph semantics
    assert abs(rows["naive"]["loss"] - rows["fused"]["loss"]) < 1e-3
    # non-LoRA rows carry no lora_impl key (schema unchanged for them)
    fake = {"dt": 1.0, "loss": 1.0, "peak_bytes": 0, "flops": 1,
            "tokens": 10}
    assert "lora_impl" not in b.finish("x", fake, "float32", 1)


def test_bench_multitenant_rows_tiny_cpu(monkeypatch):
    """bench.py's r18 multitenant rows (k adapter jobs through ONE
    fused step, DESIGN.md §23): the REAL bench_multitenant in tiny CPU
    mode at k=1 and k=2 — mt_finish carries the k / step_time_ms /
    step_time_vs_k1 columns the step-time-vs-k claim is read from, and
    aggregate tokens count every tenant's rows. The loss column rides
    the shared loss-mark/eval-probe protocol like every other row
    (loss_tokens_seen says how far the probe trained)."""
    import bench as b
    import jax.numpy as jnp
    monkeypatch.setattr(b, "LOSS_MARK_TOKENS", 256)  # tiny CPU marks
    r1 = b.bench_multitenant(jnp.float32, steps=2, k=1, model="gpt2",
                             size="tiny", B_per=2, S=32)
    assert r1["loss_tokens_seen"] >= 256
    assert r1["k"] == 1 and r1["tokens"] == 1 * 2 * 32
    row1 = b.mt_finish("gpt2s_tiny_multitenant_k1", r1, "float32", 2)
    assert row1["k"] == 1
    assert row1["step_time_ms"] > 0
    assert row1["step_time_vs_k1"] == 1.0          # the reference row
    assert row1["tokens_per_sec_per_chip"] > 0
    r2 = b.bench_multitenant(jnp.float32, steps=2, k=2, model="gpt2",
                             size="tiny", B_per=2, S=32,
                             ref_step_ms=row1["step_time_ms"])
    assert r2["k"] == 2 and r2["tokens"] == 2 * 2 * 32
    row2 = b.mt_finish("gpt2s_tiny_multitenant_k2", r2, "float32", 2)
    assert row2["k"] == 2
    assert row2["step_time_ms"] > 0
    assert row2["step_time_vs_k1"] > 0             # ratio vs the k=1 row
    assert isinstance(row2["loss"], float)
    assert "peak_hbm_mb" in row2 and "mfu" in row2


def test_bench_compare_reads_suite_artifact(tmp_path):
    """Satellite (r18): tools/bench_compare.py recognizes bench.py's
    BENCH_SUITE {"suite": [...]} artifact shape — the multitenant
    step_time-vs-k rows ride it — including the --threshold regression
    gate over the new step_time_ms (lower-better) metric."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import bench_compare as bc
    row = {"config": "gpt2s_multitenant_k8_bf16", "k": 8,
           "tokens_per_sec_per_chip": 1000.0, "step_time_ms": 10.0,
           "step_time_vs_k1": 1.05}
    old = str(tmp_path / "old.json")
    new = str(tmp_path / "new.json")
    with open(old, "w") as f:
        json.dump({"suite": [row], "peak_flops_assumed": {}}, f)
    with open(new, "w") as f:
        json.dump({"suite": [dict(row, step_time_ms=20.0)]}, f)
    rows = bc.load_rows(old)
    assert "gpt2s_multitenant_k8_bf16" in rows
    assert rows["gpt2s_multitenant_k8_bf16"]["step_time_ms"] == 10.0
    # step_time_ms is direction-aware (lower better): 2x = regression
    assert bc.main([old, new, "--threshold", "5"]) == 2
    assert bc.main([old, old, "--threshold", "5"]) == 0


def test_serve_bench_row_contract(tmp_path):
    """tools/serve_bench.py rows: the BENCH_SERVE schema the round
    scoring reads — offered vs sustained req/s, TTFT/TPOT percentiles,
    resident-adapter count, and the compile-stability counter."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_bench as sb
    rows = sb.run_rows("tiny-gpt2", [100.0], n_requests=4, adapters=2,
                       num_slots=2, block_T=8, num_blocks=32,
                       max_prompt=16, max_new=4, dtype="float32",
                       seed=0, prompt_lo=2)
    (row,) = rows
    assert row["requests"] == 4 and row["adapters_resident"] == 2
    assert row["req_s"] > 0 and row["gen_tok_s"] > 0
    for p in ("p50", "p95", "p99"):
        assert row["ttft_ms"][p] > 0
        assert row["tpot_ms"][p] > 0
    assert row["new_traces_after_warmup"] == 0
    assert set(row["traces"]) == {"prefill", "write_prefill",
                                  "decode_step"}


def test_serve_bench_mesh_rows_tiny_cpu(tmp_path):
    """serve_bench --mesh rows (round 20): the per-mesh schema the
    tp-scaling claim is read from — mesh column, per-chip throughput,
    the mesh shape in the config name — still compile-stable and
    bench_compare-loadable."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import bench_compare as bc
    import serve_bench as sb
    rows = sb.run_rows("tiny-gpt2", [100.0], n_requests=4, adapters=2,
                       num_slots=2, block_T=8, num_blocks=32,
                       max_prompt=16, max_new=4, dtype="float32",
                       seed=0, prompt_lo=2, mesh_dp=1, mesh_tp=2)
    (row,) = rows
    assert row["mesh"] == [1, 2]
    assert "_mesh1x2" in row["config"]
    assert row["gen_tok_s"] > 0
    assert row["tok_s_per_chip"] == round(row["gen_tok_s"] / 2, 1)
    assert row["new_traces_after_warmup"] == 0
    suite = str(tmp_path / "suite.json")
    with open(suite, "w") as f:
        json.dump({"suite": rows}, f)
    assert row["config"] in bc.load_rows(suite)


@pytest.mark.slow
def test_serve_bench_prefix_rows_and_ttft_gate(tmp_path):
    """serve_bench --prefix_cache/--prefix_pool rows (round 21): the
    reuse columns the cache claim is read from — prefix_hit_rate,
    cow_copies, kv_pages_per_req, the _prefixN config suffix — and
    bench_compare's direction map over the NEW row shape: TTFT p99
    still gates lower-better, hit_rate gates higher-better, pages/req
    lower-better."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import bench_compare as bc
    import serve_bench as sb
    rows = sb.run_rows("tiny-gpt2", [100.0], n_requests=5, adapters=0,
                       num_slots=2, block_T=8, num_blocks=64,
                       max_prompt=16, max_new=4, dtype="float32",
                       seed=0, prompt_lo=10, prompt_hi=24,
                       prefix_cache=True, max_prompt_chunked=32,
                       prefix_pool=2, prefix_frac=0.8)
    (row,) = rows
    assert row["config"].endswith("_prefix2")
    assert row["prefix_cache"] is True and row["sampling"] is False
    assert 0.0 <= row["prefix_hit_rate"] <= 1.0
    assert isinstance(row["cow_copies"], int) and row["cow_copies"] >= 0
    assert row["kv_pages_per_req"] > 0
    assert row["requests"] == 5 and row["terminal"]["finished"] == 5
    # the direction map over the new columns: the TTFT p99 gate still
    # fires on the new row shape, and reuse regressions gate too
    assert bc.direction("ttft_ms.p99") == -1
    assert bc.direction("prefix_hit_rate") == +1
    assert bc.direction("kv_pages_per_req") == -1
    assert bc.direction("cow_copies") == 0          # informational
    old_p = str(tmp_path / "old.json")
    new_p = str(tmp_path / "new.json")
    with open(old_p, "w") as f:
        json.dump({"rows": rows}, f)
    worse = json.loads(json.dumps(row))
    worse["ttft_ms"]["p99"] = (row["ttft_ms"]["p99"] or 1.0) * 3.0
    with open(new_p, "w") as f:
        json.dump({"rows": [worse]}, f)
    assert bc.main([old_p, new_p, "--threshold", "10"]) == 2
    assert bc.main([old_p, old_p, "--threshold", "10"]) == 0


@pytest.mark.slow
def test_serve_bench_sampled_rows_tiny_cpu():
    """serve_bench --sampling rows (round 21): the _sampled config
    suffix, the sampling marker column, and a complete sampled run —
    every request terminal-finished with latency percentiles present
    (sampled decode rides the same compiled step, so the row schema is
    the greedy schema plus the marker)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_bench as sb
    rows = sb.run_rows("tiny-gpt2", [100.0], n_requests=4, adapters=0,
                       num_slots=2, block_T=8, num_blocks=32,
                       max_prompt=16, max_new=4, dtype="float32",
                       seed=0, prompt_lo=2, sampling=True)
    (row,) = rows
    assert row["config"].endswith("_sampled")
    assert row["sampling"] is True and row["prefix_cache"] is False
    assert row["prefix_hit_rate"] is None and row["cow_copies"] is None
    assert row["requests"] == 4 and row["terminal"]["finished"] == 4
    for p in ("p50", "p95", "p99"):
        assert row["ttft_ms"][p] > 0
        assert row["tpot_ms"][p] > 0
    assert row["new_traces_after_warmup"] == 0


@pytest.mark.slow
def test_bench_decode_mesh_rows_tiny_cpu():
    """bench_decode --mesh rows (round 20): one row per attention path
    (xla gather vs pallas kernel) per mesh, so the sharded auto-gate's
    decision is a benched number — pallas_eligible pins the per-shard
    verdict, both rows carry TPOT/TTFT/per-chip columns."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import jax.numpy as jnp
    import bench_decode as bd
    rows = bd.bench_paged_mesh(False, S=2, dtype=jnp.float32, pipeline=1,
                               mesh=(1, 2), tiny=True, adapters=2,
                               n_pair=(2, 4))
    assert [r["attn_impl"] for r in rows] == ["xla", "pallas"]
    for r in rows:
        assert r["mesh"] == [1, 2] and r["adapters"] == 2
        assert "_mesh1x2_" in r["config"]
        assert isinstance(r["pallas_eligible"], bool)
        for key in ("ttft_ms", "tok_s_asymptotic", "tok_s_per_chip",
                    "wall_ms_lo", "wall_ms_hi"):
            assert isinstance(r[key], (int, float)) and r[key] > 0, key
        assert isinstance(r["tpot_ms"], (int, float))
        assert r["tok_s_per_chip"] == pytest.approx(
            r["tok_s_asymptotic"] / 2, abs=0.06)  # column rounds to .1


def test_bench_checkpoint_rows_contract(tmp_path):
    """tools/bench_checkpoint.py (round 10): each row self-certifies the
    async-save claim it rides on — sync oracle stall vs async blocking
    time through the REAL AsyncCheckpointer plus file-against-file byte
    parity. Tiny trees on CPU pin the schema and the invariants; the
    ≤25% acceptance bar is read off the real-size BENCH_CKPT artifact."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import bench_checkpoint as bc
    rows = bc.run_rows("tiny", repeats=2, out_dir=str(tmp_path))
    assert [r["config"] for r in rows] == ["gpt2s_fullft_tiny",
                                           "gemma270m_lora_tiny"]
    for r in rows:
        for k in ("tree_bytes", "sync_stall_ms", "async_blocking_ms",
                  "snapshot_ms", "write_ms", "blocking_frac"):
            assert isinstance(r[k], (int, float)) and r[k] >= 0, k
        assert r["byte_identical"] is True
        # the async path may never block LONGER than the sync oracle
        # (the sync stall includes the same snapshot plus the write)
        assert r["async_blocking_ms"] <= r["sync_stall_ms"], r
        assert 0.0 <= r["blocking_frac"] <= 1.0
    # the checked-in real-size rows must satisfy the acceptance bar:
    # blocking ≤ 25% of the sync stall, byte-identical files
    art = os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "BENCH_CKPT_r10.json")
    with open(art) as f:
        real = json.load(f)["rows"]
    assert {r["config"] for r in real} == {"gpt2s_fullft_real",
                                           "gemma270m_lora_real"}
    for r in real:
        assert r["blocking_frac"] <= 0.25 and r["byte_identical"], r


def test_serve_bench_registry_record_normal_and_reject(tmp_path):
    """Round 23 (DESIGN.md §28): a serve_bench invocation leaves
    exactly ONE finalized registry record — status "ok" on a normal
    run, the exception's name when the build-time memory admission
    refuses the config (the registry-scoped `with` finalizes on every
    exit path)."""
    import sys
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_bench as sb
    from mobilefinetuner_tpu.core.memory_guard import MemoryAdmissionError
    from mobilefinetuner_tpu.core.run_registry import RunRegistry

    registry = str(tmp_path / "runs.jsonl")
    out = str(tmp_path / "BENCH_SERVE.json")
    rc = sb.main(["--model", "tiny-gpt2", "--rate", "100",
                  "--requests", "3", "--num_slots", "2",
                  "--block_T", "8", "--num_blocks", "32",
                  "--max_prompt", "16", "--max_new", "4",
                  "--dtype", "float32", "--prompt_lo", "2",
                  "--out", out, "--run_registry", registry])
    assert rc == 0
    (rec,) = RunRegistry(registry).records()
    assert rec["status"] == "ok" and rec["kind"] == "serve"
    assert out in rec["artifacts"]

    reject_reg = str(tmp_path / "reject_runs.jsonl")
    with pytest.raises(MemoryAdmissionError):
        # 4096 blocks of float32 KV ≈ 16 MB — over the 1 MB flag cap,
        # so the build preflight refuses before any engine exists
        sb.main(["--model", "tiny-gpt2", "--rate", "100",
                 "--requests", "3", "--num_slots", "2",
                 "--block_T", "8", "--num_blocks", "4096",
                 "--max_prompt", "16", "--max_new", "4",
                 "--dtype", "float32", "--prompt_lo", "2",
                 "--hbm_cap_mb", "1",
                 "--run_registry", reject_reg])
    (rec,) = RunRegistry(reject_reg).records()
    assert rec["status"] == "MemoryAdmissionError"
