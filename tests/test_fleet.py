"""Fleet observability tests (DESIGN.md §14): per-host telemetry shards
and the fleet_report merge, straggler attribution, the hang watchdog
state machine (unit + injected-stall CPU e2e), goodput wall-clock
accounting (the buckets-sum-to-wall-clock acceptance), the spike
detector's crash/resume re-seed, and the static emit-site/EVENT_SCHEMA
drift guard."""

import glob
import json
import os
import re
import sys
import time

import numpy as np
import pytest

from mobilefinetuner_tpu.core.telemetry import (EVENT_SCHEMA, GoodputMeter,
                                                HangWatchdog, SpikeConfig,
                                                SpikeDetector, Telemetry,
                                                partial_goodput, shard_path,
                                                validate_event)

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import write_tiny_gpt2_dir, write_wikitext_dir

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def read_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f.read().splitlines() if l.strip()]


# --------------------------- shard naming / host stamp ----------------------

def test_shard_path_contract():
    assert shard_path("run.jsonl", 0) == "run.jsonl"
    assert shard_path("run.jsonl", 3) == "run.jsonl.host3"
    assert shard_path("", 2) == ""  # disabled stays disabled


def test_host_stamp_lands_on_every_record_and_validates(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path, host=2) as tel:
        tel.emit("eval", step=1, loss=1.0, ppl=2.0, tokens=3)
        tel.emit("run_end", steps=1, wall_s=0.1, exit="ok", goodput=None)
    recs = read_events(path)
    assert [r["host"] for r in recs] == [2, 2]
    for r in recs:
        assert validate_event(r) is None, validate_event(r)
    # envelope check: a bad host stamp is rejected
    assert validate_event({**recs[0], "host": -1}) is not None
    assert validate_event({**recs[0], "host": "h2"}) is not None
    # pre-fleet records (no host) still validate
    del recs[0]["host"]
    assert validate_event(recs[0]) is None


def test_telemetry_resume_flags_and_trailing_step_stats(tmp_path):
    path = str(tmp_path / "t.jsonl")
    tel = Telemetry(path)
    assert not tel.resumed and tel.trailing_step_stats == []
    for i in range(3):
        tel.emit("step_stats", step=i + 1, loss=3.0 - i * 0.1, ema=3.0,
                 lr=1e-4, grad_norm=0.5, step_time_ms=10.0,
                 host_wait_ms=0.0, slept_ms=0.0, tok_s=100.0, mfu=None,
                 param_norm=None, update_ratio=None, nonfinite_count=None,
                 hbm_mb=0.0, queue_depth=None, host_step_ms=None)
    tel.emit("eval", step=3, loss=1.0, ppl=2.0, tokens=1)
    tel.close()
    tel2 = Telemetry(path)
    assert tel2.resumed
    assert [r["step"] for r in tel2.trailing_step_stats] == [1, 2, 3]
    assert tel2.trailing_step_stats[-1]["loss"] == pytest.approx(2.8)
    tel2.close()


# --------------------------- spike-detector resume seed ---------------------

def test_spike_seed_arms_detector_without_rewarmup():
    """Regression (crash/resume): a resumed run's detector must NOT
    re-enter warmup — a spike on the first post-resume step fires."""
    cfg = SpikeConfig(zscore=5.0, beta=0.9, warmup=10)
    rng = np.random.default_rng(0)
    history = [3.0 + 0.02 * float(rng.normal()) for _ in range(30)]
    # an unseeded fresh detector misses the immediate spike (warming up)
    fresh = SpikeDetector(cfg)
    assert fresh.update(30.0) is None
    # the seeded one is armed at once
    det = SpikeDetector(SpikeConfig(zscore=5.0, beta=0.9, warmup=10))
    fed = det.seed(history, count_hint=500)
    assert fed == 30 and det.count >= 500
    anom = det.update(30.0)
    assert anom is not None and anom["kind"] == "loss_spike"
    # and a normal post-resume loss does not fire
    assert det.update(3.0) is None


def test_spike_seed_skips_nonfinite_and_null_and_uses_count_hint():
    det = SpikeDetector(SpikeConfig(zscore=5.0, warmup=10))
    fed = det.seed([None, float("nan"), float("inf"), 3.0, 3.1],
                   count_hint=50)
    assert fed == 2
    assert det.count == 50  # step hint bridges a sparse flush cadence
    assert det.mean is not None and not det._nonfinite


# --------------------------- goodput meter ----------------------------------

def test_goodput_buckets_sum_to_total_by_construction():
    m = GoodputMeter()
    time.sleep(0.02)            # init
    m.enter("step")
    time.sleep(0.04)
    m.enter("eval")
    time.sleep(0.01)
    m.enter("step")
    s = m.summary()
    parts = sum(v for k, v in s.items()
                if k.endswith("_s") and k != "total_s")
    assert parts == pytest.approx(s["total_s"], abs=1e-6)
    assert s["init_s"] >= 0.015 and s["step_s"] >= 0.035
    assert s["eval_s"] >= 0.005
    assert 0.0 <= s["productive_frac"] <= 1.0


def test_goodput_meter_rejects_unknown_phase():
    with pytest.raises(AssertionError):
        GoodputMeter().enter("coffee_break")


def test_partial_goodput_reconstruction():
    events = [
        {"event": "run_start", "seq": 0, "t": 100.0},
        {"event": "compile", "seq": 1, "t": 102.5, "step": 0,
         "wall_s": 2.5, "flops": None, "peak_hbm_mb": None},
        {"event": "step_stats", "seq": 2, "t": 103.0, "step": 2,
         "step_time_ms": 100.0, "host_wait_ms": 10.0, "slept_ms": 50.0},
        {"event": "step_stats", "seq": 3, "t": 104.0, "step": 4,
         "step_time_ms": 100.0, "host_wait_ms": 30.0, "slept_ms": 150.0},
        {"event": "checkpoint", "seq": 4, "t": 105.0, "step": 4,
         "final": False, "wall_s": 0.5},
    ]
    g = partial_goodput(events)
    assert g["partial"] is True
    assert g["compile_s"] == pytest.approx(2.5)
    assert g["checkpoint_s"] == pytest.approx(0.5)
    assert g["governor_sleep_s"] == pytest.approx(0.2)
    assert g["input_wait_frac_of_step"] == pytest.approx(0.2)
    assert g["observed_span_s"] == pytest.approx(5.0)


# --------------------------- hang watchdog (unit) ---------------------------

def test_watchdog_fires_on_stall_dumps_stacks_and_probes(tmp_path):
    stacks = str(tmp_path / "stall.stacks")
    events = []
    wd = HangWatchdog(mult=2.0, min_deadline_s=0.15, grace_s=0.15,
                      on_hang=events.append, stacks_file=stacks,
                      probe_fn=lambda: None, probe_timeout_s=1.0)
    wd.start()
    for i in range(5):
        wd.pet(i, 0.01)
        time.sleep(0.01)
    time.sleep(0.8)  # stall >> deadline (max(2 x 10ms, 0.15) = 0.15s)
    wd.stop()
    assert wd.fired >= 1
    p = events[0]
    assert p["step"] == 4                 # last COMPLETED step
    assert p["action"] == "continue"
    assert p["device_probe"] == "ok"
    assert p["stall_s"] >= p["deadline_s"]
    assert os.path.exists(stacks)
    dump = open(stacks).read()
    assert "hang-watchdog" in dump or "Thread" in dump  # faulthandler dump
    # continue-mode backs the deadline off 2x per fire: a 0.8 s stall at
    # a 0.15 s deadline fires O(log), not 5+ times
    assert wd.fired <= 3


def test_watchdog_clean_cadence_never_fires():
    fired = []
    wd = HangWatchdog(mult=10.0, min_deadline_s=0.6, grace_s=0.6,
                      on_hang=fired.append)
    wd.start()
    for i in range(25):
        wd.pet(i, 0.02)
        time.sleep(0.02)
    wd.stop()
    assert wd.fired == 0 and not fired


def test_watchdog_probe_timeout_and_abort_fn(tmp_path):
    aborted = []
    events = []
    wd = HangWatchdog(mult=2.0, min_deadline_s=0.1, grace_s=0.1,
                      on_hang=events.append, abort=True,
                      stacks_file=str(tmp_path / "a.stacks"),
                      probe_fn=lambda: time.sleep(5.0),
                      probe_timeout_s=0.1, abort_fn=aborted.append)
    wd.start()
    time.sleep(0.6)  # never petted: grace deadline expires
    wd.stop()
    assert wd.fired == 1  # abort path fires exactly once
    assert events[0]["device_probe"] == "timeout"
    assert events[0]["action"] == "abort"
    assert aborted == [113]


def test_watchdog_touch_defers_deadline():
    """eval/checkpoint pauses the loop KNOWS about reset the idle clock
    without a completed step — no false positive."""
    fired = []
    wd = HangWatchdog(mult=2.0, min_deadline_s=0.5, grace_s=0.5,
                      on_hang=fired.append)
    wd.start()
    wd.pet(0, 0.01)
    for _ in range(6):          # a 0.6 s pause touched every 0.1 s
        time.sleep(0.1)
        wd.touch()
    wd.stop()
    assert wd.fired == 0 and not fired


def test_watchdog_suspend_covers_pause_longer_than_deadline():
    """The real eval/checkpoint contract: the pause may EXCEED any
    step-derived deadline, so the loop suspends the clock instead of
    racing it with touches — no fire mid-pause, re-armed after."""
    fired = []
    wd = HangWatchdog(mult=2.0, min_deadline_s=0.2, grace_s=0.2,
                      on_hang=fired.append)
    wd.start()
    wd.pet(0, 0.01)
    wd.suspend()
    time.sleep(0.8)             # 4x the deadline, clock stopped
    wd.resume()
    time.sleep(0.05)
    wd.pet(1, 0.01)
    wd.stop()
    assert wd.fired == 0 and not fired


def test_pre_fleet_records_still_validate():
    """Round-8 streams lack host_step_ms/goodput (and the host stamp):
    readers must accept their absence — but a PRESENT optional field is
    still type-checked."""
    old_ss = dict(event="step_stats", seq=5, t=1.0, step=1, loss=3.2,
                  ema=3.3, lr=1e-4, grad_norm=0.5, step_time_ms=10.0,
                  host_wait_ms=0.1, slept_ms=0.0, tok_s=1.0, mfu=None,
                  param_norm=None, update_ratio=None,
                  nonfinite_count=None, hbm_mb=1.0, queue_depth=None)
    assert validate_event(old_ss) is None
    old_end = dict(event="run_end", seq=6, t=1.0, steps=4, wall_s=1.0,
                   exit="ok")
    assert validate_event(old_end) is None
    assert validate_event({**old_ss, "host_step_ms": "fast"}) is not None
    assert validate_event({**old_end, "goodput": 3}) is not None


# --------------------------- CPU e2e fixtures -------------------------------

@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2fleet")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2fleet")))


@pytest.fixture(scope="module")
def clean_run(gpt2_dir, wiki_dir, tmp_path_factory):
    """ONE 20-step tiny CPU train shared by the goodput-sum, watchdog
    zero-false-positive, and straggler-cadence assertions: telemetry on,
    watchdog armed tight (5 s floor — far above tiny CPU step times),
    straggler cadence 5, an in-loop eval, a checkpoint save, and two
    governor-scheduled sleeps."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    tmp = tmp_path_factory.mktemp("cleanrun")
    stream = str(tmp / "run.jsonl")
    t0 = time.time()
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "20", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp / "a.safetensors"),
               "--telemetry_out", stream, "--log_interval", "5",
               "--eval_interval", "10", "--eval_batches", "2",
               "--pm_schedule", "0-1:40",
               "--straggler_cadence", "5",
               "--watchdog", "1", "--watchdog_mult", "50",
               "--watchdog_min_s", "5"])
    assert rc == 0
    return {"stream": stream, "recs": read_events(stream),
            "wall_s": time.time() - t0}


def test_clean_run_schema_and_zero_watchdog_false_positives(clean_run):
    recs = clean_run["recs"]
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    kinds = [r["event"] for r in recs]
    assert "hang" not in kinds  # 20 quick steps: no false positive
    assert not os.path.exists(clean_run["stream"] + ".stacks")
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"


def test_goodput_buckets_sum_to_wall_clock_within_1pct(clean_run):
    """The acceptance criterion: run_end.goodput buckets account for the
    run's whole wall-clock."""
    end = clean_run["recs"][-1]
    assert end["event"] == "run_end" and end["exit"] == "ok"
    g = end["goodput"]
    assert g and not g.get("partial")
    parts = sum(v for k, v in g.items()
                if k.endswith("_s") and k != "total_s")
    assert parts == pytest.approx(g["total_s"], abs=1e-3)
    # meter total vs the independently measured run_end wall_s
    assert abs(g["total_s"] - end["wall_s"]) \
        <= max(0.01 * end["wall_s"], 0.05)
    # every exercised phase left a footprint
    assert g["compile_s"] > 0
    assert g["step_s"] > 0
    assert g["eval_s"] > 0          # --eval_interval 10 ran twice
    assert g["checkpoint_s"] > 0    # final save
    assert g["governor_sleep_s"] >= 0.06  # two scheduled 40 ms sleeps
    assert 0.0 < g["productive_frac"] < 1.0
    # the governor's own run-total sleep counter rides run_end as an
    # independently-clocked cross-check of the meter's bucket
    assert end["governor_slept_ms"] >= 60
    assert g["governor_sleep_s"] * 1000 >= end["governor_slept_ms"] - 10


def test_straggler_cadence_single_host(clean_run):
    """--straggler_cadence 5 on one host: step_stats carries the
    {host: ms} map with this host's measured time, and no straggler
    fires (nothing to be slower than)."""
    recs = clean_run["recs"]
    assert "straggler" not in [r["event"] for r in recs]
    maps = [r["host_step_ms"] for r in recs
            if r["event"] == "step_stats" and r["host_step_ms"]]
    assert maps, "no step_stats carried a host_step_ms snapshot"
    assert set(maps[-1]) == {"0"}
    assert maps[-1]["0"] > 0


def test_watchdog_e2e_injected_stall(gpt2_dir, wiki_dir, tmp_path,
                                     monkeypatch):
    """Satellite: an injected mid-run stall deterministically produces a
    `hang` event + a stack-dump file, and the run still completes."""
    from mobilefinetuner_tpu.cli import common
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    orig = common.StepGovernor.throttle

    def stalling(self, step):
        if step == 5:
            time.sleep(3.0)  # >> the 0.8 s deadline floor
        return orig(self, step)

    monkeypatch.setattr(common.StepGovernor, "throttle", stalling)
    stream = str(tmp_path / "stall.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "8", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--telemetry_out", stream, "--log_interval", "1",
               "--watchdog", "1", "--watchdog_mult", "2",
               "--watchdog_min_s", "0.8"])
    assert rc == 0  # continue-mode: the run survives the stall
    recs = read_events(stream)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    hangs = [r for r in recs if r["event"] == "hang"]
    assert hangs, "injected stall did not raise a hang event"
    h = hangs[0]
    assert h["step"] == 5               # the stall began after step 5
    assert h["action"] == "continue"
    assert h["stall_s"] >= h["deadline_s"]
    assert h["device_probe"] == "ok"    # CPU device still responsive
    assert h["last_seq"] >= 0           # tail position for post-mortems
    assert os.path.exists(h["stacks_file"])
    assert "stalling" in open(h["stacks_file"]).read()  # the guilty frame
    assert recs[-1]["event"] == "run_end" and recs[-1]["exit"] == "ok"


def test_watchdog_kill_switch(gpt2_dir, wiki_dir, tmp_path, monkeypatch):
    """--watchdog 0: the same stall produces NO hang event."""
    from mobilefinetuner_tpu.cli import common
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    orig = common.StepGovernor.throttle

    def stalling(self, step):
        if step == 2:
            time.sleep(1.2)
        return orig(self, step)

    monkeypatch.setattr(common.StepGovernor, "throttle", stalling)
    stream = str(tmp_path / "off.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "4", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--telemetry_out", stream,
               "--watchdog", "0", "--watchdog_min_s", "0.3"])
    assert rc == 0
    assert "hang" not in [r["event"] for r in read_events(stream)]


# --------------------------- spike re-seed e2e ------------------------------

def test_spike_detector_reseeds_across_resume_e2e(gpt2_dir, wiki_dir,
                                                  tmp_path, monkeypatch):
    """The resumed run's detector sees the first run's step_stats tail:
    with warmup far above either run's step count, a fresh detector
    could never arm — the seeded one must still count the history."""
    from mobilefinetuner_tpu.cli import common
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    seeded = {}
    orig_seed = common.SpikeDetector.seed

    def spy(self, losses, count_hint=0):
        fed = orig_seed(self, losses, count_hint)
        seeded["fed"] = fed
        seeded["count"] = self.count
        return fed

    monkeypatch.setattr(common.SpikeDetector, "seed", spy)
    stream = str(tmp_path / "run.jsonl")
    adapter = str(tmp_path / "a.safetensors")
    base = ["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
            "--batch_size", "2", "--seq_len", "32", "--lora_out", adapter,
            "--telemetry_out", stream, "--log_interval", "2"]
    assert main(base + ["--steps", "6"]) == 0
    assert "fed" not in seeded  # first run: nothing to seed from
    assert main(base + ["--steps", "8", "--resume_from", adapter]) == 0
    assert seeded["fed"] >= 1   # flushed losses were replayed
    assert seeded["count"] >= 6  # count_hint bridged to the resumed step


# --------------------------- fleet report merge -----------------------------

def test_fleet_report_merges_simulated_shards(tmp_path):
    import fleet_report
    import multihost_smoke
    from telemetry_report import load_events
    base = str(tmp_path / "fleet.jsonl")
    paths = multihost_smoke.write_simulated_shards(base)
    assert paths == [base, base + ".host1"]
    shards = fleet_report.discover_shards(base)
    assert set(shards) == {0, 1}
    loaded = {h: load_events(p) for h, p in shards.items()}
    # every simulated record passes the shared schema
    assert all(bad == 0 for _, bad in loaded.values())
    s = fleet_report.fleet_summary(loaded)
    assert s["hosts"] == 2 and s["duplicate_host_seq_keys"] == 0
    for h in (0, 1):
        ph = s["per_host"][h]
        assert ph["seq_monotonic"] and ph["host_stamp_mismatches"] == 0
        assert ph["flushes"] == 5
        assert ph["run_end"]["exit"] == "ok"
        assert ph["step_time_ms"]["p50"] is not None
    # the baked-in 3x skew is attributed to host 1
    assert s["skew"]["slowest_host"] == 1
    assert s["skew"]["ratio"] == pytest.approx(3.0, rel=0.05)
    assert len(s["stragglers"]) == 1 \
        and s["stragglers"][0]["slow_host"] == 1
    assert s["goodput"]["productive_frac"] == pytest.approx(1.0)
    # the CLI renders both modes
    assert fleet_report.main([base]) == 0
    assert fleet_report.main([base, "--json"]) == 0


def test_fleet_report_flags_missing_run_end(tmp_path):
    base = str(tmp_path / "part.jsonl")
    with Telemetry(base, host=0) as tel:
        tel.emit("run_start", jax_version="x", mesh_shape=None,
                 process_count=2, process_index=0, device_kind="cpu",
                 device_count=2, config={})
    with Telemetry(base + ".host1", host=1) as tel:
        tel.emit("run_start", jax_version="x", mesh_shape=None,
                 process_count=2, process_index=1, device_kind="cpu",
                 device_count=2, config={})
        tel.emit("run_end", steps=0, wall_s=0.1, exit="ok", goodput=None)
    import fleet_report
    from telemetry_report import load_events
    s = fleet_report.fleet_summary(
        {h: load_events(p)
         for h, p in fleet_report.discover_shards(base).items()})
    assert s["hosts_missing_run_end"] == [0]
    assert fleet_report.main([base]) == 0


# --------------------------- truncated-stream report ------------------------

def test_telemetry_report_handles_truncated_stream(tmp_path, capsys):
    """Satellite: a killed run (no run_end) must render, say truncated,
    carry the last-seen step, and include partial goodput buckets."""
    import telemetry_report
    path = str(tmp_path / "killed.jsonl")
    with Telemetry(path) as tel:
        tel.emit("run_start", jax_version="x", mesh_shape=None,
                 process_count=1, process_index=0, device_kind="cpu",
                 device_count=1, config={"steps": 100})
        tel.emit("compile", step=0, wall_s=1.5, flops=None,
                 peak_hbm_mb=None)
        for i in (2, 4):
            tel.emit("step_stats", step=i, loss=3.0, ema=3.0, lr=1e-4,
                     grad_norm=0.5, step_time_ms=10.0, host_wait_ms=1.0,
                     slept_ms=25.0, tok_s=100.0, mfu=None,
                     param_norm=None, update_ratio=None,
                     nonfinite_count=None, hbm_mb=0.0, queue_depth=None,
                     host_step_ms=None)
    assert telemetry_report.main([path]) == 0
    out = capsys.readouterr().out
    assert "TRUNCATED" in out and "last seen step: 4" in out
    assert "PARTIAL" in out
    events, bad = telemetry_report.load_events(path)
    s = telemetry_report.summarize(events, bad)
    assert s["truncated"] and s["last_seen_step"] == 4
    assert s["goodput"]["partial"] is True
    assert s["goodput"]["compile_s"] == pytest.approx(1.5)
    assert s["goodput"]["governor_sleep_s"] == pytest.approx(0.05)


def test_report_resumed_stream_with_killed_second_run_is_truncated(
        tmp_path):
    """A resumed stream appends runs: run 1's clean run_end must NOT
    mask run 2 being SIGKILLed — truncation is judged on the LATEST
    run, and the stale run_end is withheld."""
    import telemetry_report
    path = str(tmp_path / "resumed.jsonl")
    manifest = dict(jax_version="x", mesh_shape=None, process_count=1,
                    process_index=0, device_kind="cpu", device_count=1,
                    config={})
    with Telemetry(path) as tel:
        tel.emit("run_start", **manifest)
        tel.emit("run_end", steps=4, wall_s=1.0, exit="ok", goodput=None)
    with Telemetry(path) as tel:  # the resumed run — killed, no run_end
        tel.emit("run_start", **manifest)
        tel.emit("step_stats", step=7, loss=3.0, ema=3.0, lr=1e-4,
                 grad_norm=0.5, step_time_ms=10.0, host_wait_ms=1.0,
                 slept_ms=0.0, tok_s=100.0, mfu=None, param_norm=None,
                 update_ratio=None, nonfinite_count=None, hbm_mb=0.0,
                 queue_depth=None, host_step_ms=None)
    events, bad = telemetry_report.load_events(path)
    s = telemetry_report.summarize(events, bad)
    assert s["truncated"] is True
    assert s["run_end"] is None      # run 1's exit=ok is not current
    assert s["last_seen_step"] == 7  # from the latest run's slice
    assert s["goodput"]["partial"] is True
    assert telemetry_report.main([path]) == 0
    # the fleet view inherits the rule (shard 0 = this stream)
    import fleet_report
    fs = fleet_report.fleet_summary({0: (events, bad)})
    assert fs["per_host"][0]["run_end"] is None
    assert fs["hosts_missing_run_end"] == [0]


# --------------------------- static emit-site schema guard ------------------

def test_every_emitted_event_name_is_in_schema():
    """Satellite (migrated r19): the hand-rolled source-regex scan is
    now graftlint's `emit-schema` rule (core/static_checks.py) — AST
    emit-site collection vs EVENT_SCHEMA in BOTH directions (no unknown
    event ships, no dead taxonomy survives). This wrapper pins the rule
    green over package + tools; tools/graft_lint.py runs the same rule
    from the CLI/tier-1 gate."""
    from mobilefinetuner_tpu.core.static_checks import (collect_emit_sites,
                                                        Project, run_lint)
    res = run_lint([os.path.join(REPO, "mobilefinetuner_tpu"),
                    os.path.join(REPO, "tools")], rules=["emit-schema"])
    bad = res.findings + res.suppressed  # this rule is never suppressed
    assert not bad, [f.render() for f in bad]
    # the rule's collector must still SEE the emit sites (an empty scan
    # would pass both directions vacuously if EVENT_SCHEMA were empty)
    found = collect_emit_sites(
        Project([os.path.join(REPO, "mobilefinetuner_tpu")]).all_modules())
    assert set(found) >= {"run_start", "run_end", "step_stats", "request"}
    assert set(found) <= set(EVENT_SCHEMA)


def test_request_phases_and_reasons_pinned_both_directions():
    """Round-14 satellite (migrated r19): the closed-set scan of the
    serve layer's `phase=`/`reason=` literals vs REQUEST_PHASES /
    REQUEST_REASONS is now graftlint's `serve-taxonomy` rule
    (core/static_checks.py) — same both-direction semantics (the error
    phase's exception-type reasons stay exempt: only lowercase_snake
    literals match). This wrapper pins the rule green."""
    from mobilefinetuner_tpu.core.static_checks import run_lint
    res = run_lint([os.path.join(REPO, "mobilefinetuner_tpu"),
                    os.path.join(REPO, "tools")],
                   rules=["serve-taxonomy"])
    bad = res.findings + res.suppressed  # this rule is never suppressed
    assert not bad, [f.render() for f in bad]
