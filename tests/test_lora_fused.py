"""Pallas LoRA epilogue kernels vs the XLA oracle (DESIGN.md §17):
ops/lora_fused.lora_epilogue (projection sites) and the fused-CE
head-adapter variant (ops/fused_ce.fused_ce_rows_lora) — forward values,
gradients through every differentiable operand, eligibility gates, and
the chunked-CE integration. Interpret mode on CPU (ops/pallas_util)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.models.lora_apply import maybe_lora
from mobilefinetuner_tpu.ops.fused_ce import (fused_ce_lora_eligible,
                                              fused_ce_nll_sum,
                                              fused_ce_rows_lora,
                                              head_bottleneck,
                                              pick_block_v)
from mobilefinetuner_tpu.ops.lora_fused import (lora_epilogue_add,
                                                lora_epilogue_eligible,
                                                pick_tiles)
from mobilefinetuner_tpu.ops.loss import (_token_nll,
                                          chunked_lm_cross_entropy_sum)


# ------------------------------ eligibility ----------------------------------

def test_epilogue_eligibility_gates():
    # aligned train-shaped site fits
    assert pick_tiles(4096, 640, 2) is not None
    assert lora_epilogue_eligible(4096, 640, 8, 2)
    # rows must be sublane-aligned, lanes tile-aligned, rank <= the pad
    assert not lora_epilogue_eligible(4095, 640, 8, 2)
    assert not lora_epilogue_eligible(4096, 100, 8, 2)
    assert not lora_epilogue_eligible(4096, 640, 256, 2)
    # tiny aligned CPU-test shape is eligible (interpret-mode coverage)
    assert lora_epilogue_eligible(16, 128, 4, 4)


def test_fused_ce_lora_eligibility_adds_rank_terms():
    # the adapter slabs shrink (or keep) the viable vocab tile
    base = pick_block_v(262144, R=512, H=640)
    with_lora = pick_block_v(262144, R=512, H=640, r_pad=128)
    assert base is not None and with_lora is not None
    assert with_lora <= base
    assert fused_ce_lora_eligible(512, 262144, 640, 8)
    assert not fused_ce_lora_eligible(512, 262144, 640, 256)  # r > pad
    assert not fused_ce_lora_eligible(511, 262144, 640, 8)    # rows


# --------------------------- projection epilogue -----------------------------

@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 2e-2)])
def test_lora_epilogue_matches_oracle_with_grads(dtype, tol):
    rng = np.random.default_rng(0)
    N, d_out, r = 16, 128, 4
    y = jnp.asarray(rng.normal(size=(2, 8, d_out)), dtype)
    xa = jnp.asarray(rng.normal(size=(2, 8, r)), dtype)
    B = jnp.asarray(rng.normal(size=(r, d_out)) * 0.1, dtype)
    scale = jnp.float32(2.0)

    def kernel_fn(ops):
        yy, xx, bb = ops
        return jnp.sum(lora_epilogue_add(yy, xx, bb, scale)
                       .astype(jnp.float32) ** 2)

    def oracle_fn(ops):
        yy, xx, bb = ops
        out = yy.astype(jnp.float32) + 2.0 * (
            xx.astype(jnp.float32) @ bb.astype(jnp.float32))
        return jnp.sum(out.astype(dtype).astype(jnp.float32) ** 2)

    vk, gk = jax.value_and_grad(kernel_fn)((y, xa, B))
    vo, go = jax.value_and_grad(oracle_fn)((y, xa, B))
    np.testing.assert_allclose(float(vk), float(vo), rtol=tol)
    for a, b in zip(jax.tree.leaves(gk), jax.tree.leaves(go)):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=tol, atol=tol * 10)


def test_maybe_lora_fused_engages_the_kernel_at_aligned_shapes():
    """At an eligible site, impl='fused' routes through pallas_call;
    impl='naive' never does (the oracle stays pure XLA)."""
    entry = {"A": jnp.zeros((128, 4)), "B": jnp.zeros((4, 128)),
             "scale": jnp.float32(1.0)}
    x = jnp.zeros((2, 8, 128))
    y = jnp.zeros((2, 8, 128))

    # migrated r19: the rendered-string grep is now the shared
    # structural-pin API (core/static_checks.jaxpr_contains walks
    # sub-jaxprs, so the kernel inside the custom_vjp call jaxpr counts)
    from mobilefinetuner_tpu.core.static_checks import jaxpr_contains

    def engages(impl):
        return jaxpr_contains(
            lambda yy, xx: maybe_lora(yy, xx, entry, impl=impl),
            "pallas_call", y, x)

    assert engages("fused")
    assert not engages("naive")
    # ineligible site (d_out not lane-aligned): fused falls back to XLA
    entry_bad = {"A": jnp.zeros((128, 4)), "B": jnp.zeros((4, 100)),
                 "scale": jnp.float32(1.0)}
    assert not jaxpr_contains(
        lambda yy, xx: maybe_lora(yy, xx, entry_bad, impl="fused"),
        "pallas_call", jnp.zeros((2, 8, 100)), x)


# ------------------------------ fused-CE lora --------------------------------

def _ce_case(dtype=jnp.float32, R=16, V=256, H=96, r=4, seed=0):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(R, H)), dtype)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.05, dtype)
    A = jnp.asarray(rng.normal(size=(H, r)) * 0.1, dtype)
    B = jnp.asarray(rng.normal(size=(r, V)) * 0.1, dtype)
    lab = jnp.asarray(rng.integers(0, V, (R,)), jnp.int32)
    return h, w, A, B, lab


@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 2e-5),
                                       (jnp.bfloat16, 2e-2)])
def test_fused_ce_rows_lora_matches_oracle(dtype, tol):
    h, w, A, B, lab = _ce_case(dtype)
    entry = {"A": A, "B": B, "scale": jnp.float32(2.0)}
    xa, bt = head_bottleneck(h, entry)
    lse, gold = jax.jit(fused_ce_rows_lora)(h, w, lab, xa, bt)
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32).T
              + 2.0 * (h.astype(jnp.float32) @ A.astype(jnp.float32))
              @ B.astype(jnp.float32))
    lse_o = jax.nn.logsumexp(logits, axis=-1)
    gold_o = jnp.take_along_axis(logits, lab[:, None], -1)[:, 0]
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_o),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(gold), np.asarray(gold_o),
                               rtol=tol, atol=tol)


def test_fused_ce_lora_grads_match_xla_oracle():
    """Gradients through hidden, W, A, AND B of the full nll chain —
    the dh/dxa and dw/dbt kernel outputs composed with the outside
    A/B/scale chain must equal plain XLA autodiff."""
    h, w, A, B, lab = _ce_case()
    hidden = h.reshape(2, 8, -1)
    labels = lab.reshape(2, 8)

    def loss_kernel(ops):
        hh, ww, AA, BB = ops
        s, _ = fused_ce_nll_sum(hh, ww, labels, -100,
                                lora_head={"A": AA, "B": BB,
                                           "scale": jnp.float32(2.0)})
        return s

    def loss_oracle(ops):
        hh, ww, AA, BB = ops
        logits = jnp.einsum("bch,vh->bcv", hh, ww) \
            + 2.0 * jnp.einsum("bch,hr->bcr", hh, AA) @ BB
        nll, _ = _token_nll(logits, labels, -100)
        return nll.sum()

    gk = jax.grad(loss_kernel)((hidden, w, A, B))
    go = jax.grad(loss_oracle)((hidden, w, A, B))
    for a, b, name in zip(jax.tree.leaves(gk), jax.tree.leaves(go),
                          "hwAB"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-5, err_msg=name)


def test_chunked_ce_lora_head_xla_and_kernel_match_full_logits():
    """The chunked-CE integration: lora_head through the XLA chunk path
    (lora_impl=naive) and through the kernel (lora_impl=fused, eligible)
    both equal the full-logits oracle — the [B, S, V] delta never needs
    to exist."""
    h, w, A, B, lab = _ce_case(R=32)
    hidden = h.reshape(2, 16, -1)
    labels = lab.reshape(2, 16)
    entry = {"A": A, "B": B, "scale": jnp.float32(2.0)}
    logits = jnp.einsum("bch,vh->bcv", hidden, w) \
        + 2.0 * jnp.einsum("bch,hr->bcr", hidden, A) @ B
    nll, valid = _token_nll(logits[:, :-1], labels[:, 1:], -100)
    want = float(nll.sum())
    for impl in ("naive", "fused"):
        s, c = chunked_lm_cross_entropy_sum(
            hidden, w, labels, num_chunks=2, lora_head=entry,
            lora_impl=impl)
        np.testing.assert_allclose(float(s), want, rtol=3e-5,
                                   err_msg=impl)
        assert int(c) == int(valid.sum())


def test_chunked_ce_lora_head_applies_branch_dropout():
    """--lora_dropout must reach the lm_head adapter riding the chunked
    CE (the per-layer sites get it inside the models; silently training
    the head adapter without it is the regression this pins). The branch
    mask is the models' full-logits convention — inverted dropout over
    the FULL hidden under fold_in(rng, 2000) — so the chunked loss (and
    its adapter grads) must equal the full-logits oracle bit-for-mask,
    through BOTH the XLA chunk path and the fused kernel."""
    from mobilefinetuner_tpu.ops.dropout import inverted_dropout
    h, w, A, B, lab = _ce_case(R=32)
    hidden = h.reshape(2, 16, -1)
    labels = lab.reshape(2, 16)
    p, rng = 0.5, jax.random.PRNGKey(11)

    def oracle(entry):
        hb = inverted_dropout(hidden, p, jax.random.fold_in(rng, 2000))
        logits = jnp.einsum("bch,vh->bcv", hidden, w) \
            + 2.0 * jnp.einsum("bch,hr->bcr", hb, entry["A"]) @ entry["B"]
        nll, _ = _token_nll(logits[:, :-1], labels[:, 1:], -100)
        return nll.sum()

    def chunked(entry, impl):
        s, _ = chunked_lm_cross_entropy_sum(
            hidden, w, labels, num_chunks=2, lora_head=entry,
            lora_impl=impl, lora_dropout=p, dropout_rng=rng)
        return s

    entry = {"A": A, "B": B, "scale": jnp.float32(2.0)}
    want, gw = jax.value_and_grad(oracle)(entry)
    for impl in ("naive", "fused"):
        got, gg = jax.value_and_grad(
            lambda e: chunked(e, impl))(entry)
        np.testing.assert_allclose(float(got), float(want), rtol=3e-5,
                                   err_msg=impl)
        for a, b, name in zip(jax.tree.leaves(gg), jax.tree.leaves(gw),
                              ("A", "B", "scale")):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=3e-5, atol=3e-5,
                                       err_msg=f"{impl}:{name}")
    # dropout demonstrably engaged: the no-dropout loss differs
    s0, _ = chunked_lm_cross_entropy_sum(
        hidden, w, labels, num_chunks=2, lora_head=entry,
        lora_impl="naive")
    assert abs(float(s0) - float(want)) > 1e-3


def test_use_fused_ce_dispatch_with_lora():
    from mobilefinetuner_tpu.ops.loss import _use_fused_ce
    # auto + head adapter: kernel only under lora_impl=fused + eligible
    assert _use_fused_ce("auto", 512, 262144, 640, 2, lora_r=8,
                         lora_impl="fused")
    assert not _use_fused_ce("auto", 512, 262144, 640, 2, lora_r=8,
                             lora_impl="naive")
    assert not _use_fused_ce("auto", 512, 262144, 640, 2, lora_r=8,
                             lora_impl="auto")
    # base path unchanged: auto stays XLA
    assert not _use_fused_ce("auto", 512, 262144, 640, 2)
    # forcing at an ineligible lora shape is loud
    with pytest.raises(ValueError, match="lora_r"):
        _use_fused_ce(True, 512, 262144, 640, 2, lora_r=256)
