"""Multi-chip (virtual 8-device CPU mesh) hardening tests: sharding-spec
assertions and semantics under the ("data", "fsdp") mesh.

SURVEY.md §4 calls for sharding-spec assertions the reference has no
analog for (it is single-device): full-FT Adam m/v must be FSDP-sharded
with the params (ZeRO optimizer-state partitioning), the frozen tree's
specs must follow the largest-divisible-axis rule, and gradient
accumulation must equal the large-batch step under the mesh.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                           trainable_mask)
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
from mobilefinetuner_tpu.parallel.mesh import (batch_sharding, make_mesh,
                                               params_shardings,
                                               replicated_sharding,
                                               shard_batch)
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

CFG = dataclasses.replace(GPT2Config.tiny(vocab_size=1024), n_embd=128,
                          n_head=4, n_positions=64, n_layer=2)


@pytest.fixture(scope="module")
def mesh():
    return make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])


def make_batch(n, S=32, seed=0):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (n, S)), jnp.int32)
    return {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
            "labels": ids}


def full_ft_loss(params_t, _unused, mb):
    logits = gpt2.forward(CFG, params_t, mb["input_ids"],
                          attention_mask=mb["attention_mask"])
    return lm_cross_entropy_sum(logits, mb["labels"])


def test_frozen_tree_sharding_specs(mesh):
    """The FSDP placement rule, asserted leaf by leaf: big weights shard
    their largest fsdp-divisible axis; small leaves replicate."""
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    sh = params_shardings(params, mesh, min_size=2 ** 12)
    blocks = sh["blocks"]
    # [L=2, 128, 384] qkv: axis 2 is largest and divisible by fsdp=4
    assert blocks["attn"]["qkv_w"].spec == P(None, None, "fsdp")
    # [2, 128, 512] fc: axis 2
    assert blocks["mlp"]["fc_w"].spec == P(None, None, "fsdp")
    # [2, 512, 128] proj: axis 1
    assert blocks["mlp"]["proj_w"].spec == P(None, "fsdp", None)
    # [1024, 128] wte: axis 0
    assert sh["wte"].spec == P("fsdp", None)
    # small leaves (LN, biases) replicate
    assert blocks["ln_1"]["g"].spec == P()
    assert sh["ln_f"]["g"].spec == P()


def test_full_ft_adam_state_is_fsdp_sharded(mesh):
    """ZeRO optimizer-state partitioning: Adam m/v inherit the params'
    FSDP shardings, and one full-FT step preserves them."""
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    sh = params_shardings(params, mesh, min_size=2 ** 12)
    params = jax.device_put(params, sh)
    tc = TrainConfig(total_steps=4, lr=1e-3, schedule="constant",
                     warmup_ratio=0.0)
    opt = init_optimizer(params, tc, None)

    def spec_of(x):
        return x.sharding.spec if isinstance(x.sharding, NamedSharding) \
            else None

    for key in ("m", "v"):
        specs_p = jax.tree.map(spec_of, params)
        specs_o = jax.tree.map(spec_of, opt[key])
        assert specs_o == specs_p, key
    # the big leaves really are partitioned, not replicated
    assert opt["m"]["blocks"]["attn"]["qkv_w"].sharding.spec == \
        P(None, None, "fsdp")

    step_fn = make_train_step(full_ft_loss, tc, mask=None, donate=False)
    batch = shard_batch(make_batch(8), mesh)
    with mesh:
        params2, opt2, metrics = step_fn(params, None, opt, batch,
                                         jnp.int32(0))
    assert np.isfinite(float(metrics["loss"]))
    assert params2["blocks"]["attn"]["qkv_w"].sharding.spec == \
        P(None, None, "fsdp")
    assert opt2["v"]["blocks"]["attn"]["qkv_w"].sharding.spec == \
        P(None, None, "fsdp")
    # and the update actually happened
    assert not np.allclose(np.asarray(params2["ln_f"]["g"]),
                           np.asarray(params["ln_f"]["g"]))


def test_grad_accum_equals_large_batch_under_mesh(mesh):
    """accum=4 over micro-batches == one big batch, ON the mesh (the
    trainer's exact token-weighted accumulation, trainer.py contract)."""
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    lora = init_lora_gpt2(CFG, LoRASpec(rank=4, alpha=8.0),
                          jax.random.PRNGKey(1))
    # Randomize B away from its zero init: with B=0 the B-gradients are
    # borderline-zero and Adam's sign-normalized first step would amplify
    # accumulation-order rounding into +/-lr disagreements — the property
    # under test is accumulation equivalence, not that edge case.
    key = jax.random.PRNGKey(2)
    leaves, treedef = jax.tree.flatten(lora)
    keys = jax.random.split(key, len(leaves))
    lora = jax.tree.unflatten(treedef, [
        l if l.ndim == 0 else 0.02 * jax.random.normal(k, l.shape)
        for l, k in zip(leaves, keys)])
    mask = trainable_mask(lora)
    fsdp_sh = params_shardings(params, mesh, min_size=2 ** 12)
    repl = replicated_sharding(mesh)
    params = jax.device_put(params, fsdp_sh)
    lora = jax.device_put(lora, jax.tree.map(lambda _: repl, lora))

    def loss_fn(lora_t, p, mb):
        logits = gpt2.forward(CFG, p, mb["input_ids"],
                              attention_mask=mb["attention_mask"],
                              lora=lora_t)
        return lm_cross_entropy_sum(logits, mb["labels"])

    batch = make_batch(16, seed=3)
    results = []
    for accum in (1, 4):
        tc = TrainConfig(total_steps=4, lr=1e-3, schedule="constant",
                         warmup_ratio=0.0, grad_accum_steps=accum)
        step_fn = make_train_step(loss_fn, tc, mask=mask, donate=False)
        opt = init_optimizer(lora, tc, mask)
        opt = jax.device_put(opt, jax.tree.map(lambda _: repl, opt))
        with mesh:
            lora2, _, m = step_fn(lora, params, opt,
                                  shard_batch(batch, mesh), jnp.int32(0))
        results.append((jax.device_get(lora2), float(m["loss"])))
    (l1, loss1), (l4, loss4) = results
    assert loss1 == pytest.approx(loss4, rel=1e-5)
    # accumulation-order rounding passes through Adam's rsqrt; tolerance
    # covers that while still catching any semantic (scale/bias) error
    for a, b in zip(jax.tree.leaves(l1), jax.tree.leaves(l4)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-3, atol=1e-5)


GEMMA_CFG = None  # built lazily: Gemma3TextConfig import kept local


def _gemma_cfg():
    global GEMMA_CFG
    if GEMMA_CFG is None:
        from mobilefinetuner_tpu.core.config import Gemma3TextConfig
        GEMMA_CFG = Gemma3TextConfig(
            vocab_size=2048, hidden_size=64, intermediate_size=128,
            num_hidden_layers=3, num_attention_heads=4,
            num_key_value_heads=2, head_dim=16,
            max_position_embeddings=64, sliding_window=16,
            query_pre_attn_scalar=16.0, sliding_window_pattern=3)
    return GEMMA_CFG


def test_gemma_lora_mesh_train_step_vocab_parallel(mesh):
    """The driver-demanded pod config (SURVEY §2.11) at tiny shapes:
    Gemma LoRA training under the mesh with the tied large-vocab embed
    FSDP-sharded and the chunked CE run vocab-parallel. Asserts
    (a) the compiled HLO has NO full-table all-gather of the V-sharded
    embed, (b) the sharded step's loss equals the unsharded oracle, and
    (c) the loss decreases over 3 steps."""
    from mobilefinetuner_tpu.lora.lora import init_lora_gemma3
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
    cfg = _gemma_cfg()
    params_h = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    lora_h = init_lora_gemma3(cfg, LoRASpec(rank=4, alpha=8.0, init="peft"),
                              jax.random.PRNGKey(1))
    mask = trainable_mask(lora_h)
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    batch_h = {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
               "labels": ids}

    sh = params_shardings(params_h, mesh, min_size=2 ** 10)
    assert sh["embed"].spec == P("fsdp", None)  # V-sharded, the risky bit
    params = jax.device_put(params_h, sh)
    repl = replicated_sharding(mesh)
    lora = jax.device_put(lora_h, jax.tree.map(lambda _: repl, lora_h))
    tc = TrainConfig(total_steps=4, lr=1e-2, schedule="constant",
                     warmup_ratio=0.0)
    opt = jax.device_put(init_optimizer(lora_h, tc, mask),
                         jax.tree.map(lambda _: repl,
                                      init_optimizer(lora_h, tc, mask)))
    batch = shard_batch(batch_h, mesh)

    def loss_fn(lora_t, p, mb, ce_mesh):
        hidden = gemma3.hidden_states(
            cfg, p, mb["input_ids"], attention_mask=mb["attention_mask"],
            lora=lora_t)
        return chunked_lm_cross_entropy_sum(
            hidden, p["embed"], mb["labels"], num_chunks=4, mesh=ce_mesh)

    import functools
    step_fn = make_train_step(functools.partial(loss_fn, ce_mesh=mesh), tc,
                              mask=mask, donate=False)
    with mesh:
        compiled = step_fn.lower(lora, params, opt, batch,
                                 jnp.int32(0)).compile()
        # (a) the V-sharded table is never all-gathered — neither for the
        # CE chunks nor for the embedding lookup
        from mobilefinetuner_tpu.core.xla_stats import shaped_all_gathers
        bad = shaped_all_gathers(compiled, (cfg.vocab_size, cfg.hidden_size))
        assert not bad, "\n".join(bad[:3])
        losses = []
        l2, o2 = lora, opt
        for s in range(3):
            l2, o2, m = step_fn(l2, params, o2, batch, jnp.int32(s))
            losses.append(float(m["loss"]))
    # (b) sharded == unsharded oracle at step 0 (sum/count contract)
    s_ref, c_ref = jax.jit(
        lambda l, p, mb: loss_fn(l, p, mb, None))(lora_h, params_h, batch_h)
    tok = float(c_ref)
    assert losses[0] == pytest.approx(float(s_ref) / tok, rel=1e-5)
    # (c) trains
    assert losses[-1] < losses[0], losses


def test_gemma_sp_vocab_parallel_ce_compose(mesh):
    """Sequence parallelism + vocab-parallel CE COMPOSE (round-5 verdict
    item 2): ring attention shards S over "fsdp" while the chunked CE
    gathers each hidden chunk over that same axis and keeps the V-sharded
    tied table un-gathered. Asserts (a) NO full-table all-gather in the
    compiled HLO, (b) the SP step's loss equals the batch-parallel mesh
    step AND the unsharded oracle, (c) it trains."""
    import functools
    from mobilefinetuner_tpu.lora.lora import init_lora_gemma3
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
    cfg = _gemma_cfg()
    fsdp = mesh.shape["fsdp"]
    params_h = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    lora_h = init_lora_gemma3(cfg, LoRASpec(rank=4, alpha=8.0, init="peft"),
                              jax.random.PRNGKey(1))
    mask = trainable_mask(lora_h)
    rng = np.random.default_rng(11)
    S = 32
    assert S % fsdp == 0  # ring attention shards S over fsdp
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, S)), jnp.int32)
    batch_h = {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
               "labels": ids}
    sh = params_shardings(params_h, mesh, min_size=2 ** 10)
    assert sh["embed"].spec == P("fsdp", None)
    params = jax.device_put(params_h, sh)
    repl = replicated_sharding(mesh)
    lora = jax.device_put(lora_h, jax.tree.map(lambda _: repl, lora_h))
    tc = TrainConfig(total_steps=4, lr=1e-2, schedule="constant",
                     warmup_ratio=0.0)
    opt = jax.device_put(init_optimizer(lora_h, tc, mask),
                         jax.tree.map(lambda _: repl,
                                      init_optimizer(lora_h, tc, mask)))

    def loss_fn(lora_t, p, mb, ce_mesh, cp_mesh, sp):
        hidden = gemma3.hidden_states(
            cfg, p, mb["input_ids"], attention_mask=mb["attention_mask"],
            lora=lora_t, cp_mesh=cp_mesh)
        return chunked_lm_cross_entropy_sum(
            hidden, p["embed"], mb["labels"], num_chunks=4, mesh=ce_mesh,
            sequence_parallel=sp)

    sp_batch = shard_batch(batch_h, mesh, sequence_parallel=True)
    sp_step = make_train_step(
        functools.partial(loss_fn, ce_mesh=mesh, cp_mesh=mesh, sp=True),
        tc, mask=mask, donate=False)
    with mesh:
        compiled = sp_step.lower(lora, params, opt, sp_batch,
                                 jnp.int32(0)).compile()
        # (a) the V-sharded table is never all-gathered, even with the
        # sequence riding the same axis
        from mobilefinetuner_tpu.core.xla_stats import shaped_all_gathers
        bad = shaped_all_gathers(compiled, (cfg.vocab_size, cfg.hidden_size))
        assert not bad, "\n".join(bad[:3])
        losses = []
        l2, o2 = lora, opt
        for s in range(3):
            l2, o2, m = sp_step(l2, params, o2, sp_batch, jnp.int32(s))
            losses.append(float(m["loss"]))
        # (b) batch-parallel mesh steps on the same data agree — run TWO
        # so the post-step-1 loss compares as well: step 1's loss is
        # evaluated at weights produced by step 0's GRADIENT, so any
        # SP/BP divergence in the seq-shard all-gather backward (the
        # psum_scatter transpose) shows up here, not just in the
        # forward-only step-0 number.
        bp_step = make_train_step(
            functools.partial(loss_fn, ce_mesh=mesh, cp_mesh=None,
                              sp=False), tc, mask=mask, donate=False)
        bp_losses = []
        bl, bo = lora, opt
        for s in range(2):
            bl, bo, bp_m = bp_step(bl, params, bo,
                                   shard_batch(batch_h, mesh),
                                   jnp.int32(s))
            bp_losses.append(float(bp_m["loss"]))
    # unsharded oracle (sum/count contract)
    s_ref, c_ref = jax.jit(lambda l, p, mb: loss_fn(
        l, p, mb, ce_mesh=None, cp_mesh=None, sp=False))(
        lora_h, params_h, batch_h)
    oracle = float(s_ref) / float(c_ref)
    assert losses[0] == pytest.approx(oracle, rel=1e-4)
    assert losses[0] == pytest.approx(bp_losses[0], rel=1e-4)
    # post-step-1 agreement pins the SP backward path
    assert losses[1] == pytest.approx(bp_losses[1], rel=1e-4)
    # (c) trains
    assert losses[-1] < losses[0], losses


def test_vp_embed_lookup_matches_plain_lookup(mesh):
    """The Megatron-style sequence-parallel embedding lookup
    (ops/loss.vp_embed_lookup — all-gather the tiny ids, local-shard
    masked take, psum_scatter back to the sequence shard) must equal the
    plain table[ids] in values AND in the table's gradient (the full-FT
    tied-embed path), without ever materializing the table."""
    from mobilefinetuner_tpu.ops.loss import vp_embed_lookup
    V, H, B, S = 64, 16, 4, 32
    table = jax.random.normal(jax.random.PRNGKey(0), (V, H), jnp.float32)
    ids = jax.random.randint(jax.random.PRNGKey(1), (B, S), 0, V)

    got = jax.jit(lambda t, i: vp_embed_lookup(t, i, mesh))(table, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(table[ids]),
                               atol=1e-6, rtol=1e-6)

    # gradient w.r.t. the (trainable, V-sharded) table: scatter-add parity
    cot = jax.random.normal(jax.random.PRNGKey(2), (B, S, H), jnp.float32)
    g_vp = jax.grad(lambda t: jnp.sum(
        vp_embed_lookup(t, ids, mesh) * cot))(table)
    g_ref = jax.grad(lambda t: jnp.sum(t[ids] * cot))(table)
    np.testing.assert_allclose(np.asarray(g_vp), np.asarray(g_ref),
                               atol=1e-5, rtol=1e-5)


def test_gemma_sp_chunk_misalignment_falls_back_loudly(mesh):
    """When the scan chunk cannot split over the sequence axis the CE
    must warn and fall back, not silently misshard (ops/loss.py)."""
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
    cfg = _gemma_cfg()
    params = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    rng = np.random.default_rng(12)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    hidden = jnp.zeros((8, 32, cfg.hidden_size), jnp.float32)
    with pytest.warns(UserWarning, match="sequence-parallel chunk"):
        # num_chunks=31 -> chunk=1, not divisible by fsdp=4
        chunked_lm_cross_entropy_sum(hidden, params["embed"], ids,
                                     num_chunks=31, mesh=mesh,
                                     sequence_parallel=True)


def test_gemma_full_ft_mesh_adam_state_sharded(mesh):
    """Gemma full FT under the mesh: the TRAINABLE tied embed keeps its
    V-sharding through the step, Adam m/v inherit it (ZeRO), and the
    vocab-parallel CE also avoids gathering the table when its GRADIENT
    flows (the reduce-scatter path)."""
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
    cfg = _gemma_cfg()
    params = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    sh = params_shardings(params, mesh, min_size=2 ** 10)
    params = jax.device_put(params, sh)
    tc = TrainConfig(total_steps=2, lr=1e-3, schedule="constant",
                     warmup_ratio=0.0)
    opt = init_optimizer(params, tc, None)
    assert opt["m"]["embed"].sharding.spec == P("fsdp", None)
    rng = np.random.default_rng(9)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (8, 32)), jnp.int32)
    batch = shard_batch({"input_ids": ids,
                         "attention_mask": jnp.ones_like(ids),
                         "labels": ids}, mesh)

    def loss_fn(p, _unused, mb):
        hidden = gemma3.hidden_states(
            cfg, p, mb["input_ids"], attention_mask=mb["attention_mask"])
        return chunked_lm_cross_entropy_sum(
            hidden, p["embed"], mb["labels"], num_chunks=4, mesh=mesh)

    step_fn = make_train_step(loss_fn, tc, mask=None, donate=False)
    with mesh:
        compiled = step_fn.lower(params, None, opt, batch,
                                 jnp.int32(0)).compile()
        from mobilefinetuner_tpu.core.xla_stats import shaped_all_gathers
        bad = shaped_all_gathers(compiled, (cfg.vocab_size, cfg.hidden_size))
        assert not bad, "\n".join(bad[:3])
        p2, o2, m = step_fn(params, None, opt, batch, jnp.int32(0))
    assert np.isfinite(float(m["loss"]))
    # GSPMD may normalize away the trailing None — compare the sharded dim
    assert p2["embed"].sharding.spec[0] == "fsdp", p2["embed"].sharding
    assert o2["v"]["embed"].sharding.spec[0] == "fsdp", \
        o2["v"]["embed"].sharding
    # the tied embed actually updated (gradient flowed through BOTH the
    # lookup and the lm-head path)
    assert not np.allclose(np.asarray(jax.device_get(p2["embed"])),
                           np.asarray(jax.device_get(params["embed"])))


def test_train_lora_gemma_cli_multichip(tmp_path):
    """train_lora_gemma end-to-end on the virtual mesh (--mesh_fsdp 4):
    the reference's most complete CLI (train_lora_gemma.cpp:352-969)
    under FSDP."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import write_tiny_gemma3_dir, write_wikitext_dir
    from mobilefinetuner_tpu.cli.train_lora_gemma import main
    gemma_dir = str(tmp_path / "gemma")
    write_tiny_gemma3_dir(gemma_dir)
    wiki = write_wikitext_dir(str(tmp_path / "wiki"))
    out_dir = str(tmp_path / "out")
    rc = main(["--model_dir", gemma_dir, "--data_dir", wiki,
               "--max_steps", "2", "--batch", "8", "--seq_len", "32",
               "--targets", "light", "--loss_chunks", "2",
               "--mesh_data", "1", "--mesh_fsdp", "4",
               "--output_dir", out_dir])
    assert rc == 0
    import os.path
    assert os.path.exists(os.path.join(out_dir, "gemma_lora.safetensors"))


def test_full_ft_cli_multichip(tmp_path):
    """gpt2_full_finetune end-to-end on the virtual mesh: the ZeRO payoff
    path (sharded params + Adam state) through the real CLI."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import write_tiny_gpt2_dir, write_wikitext_dir
    from mobilefinetuner_tpu.cli.gpt2_full_finetune import main
    gpt2_dir = str(tmp_path / "gpt2")
    write_tiny_gpt2_dir(gpt2_dir)
    wiki = write_wikitext_dir(str(tmp_path / "wiki"))
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki,
               "--steps", "2", "--batch_size", "8", "--seq_len", "32",
               "--mesh_data", "1", "--mesh_fsdp", "4",
               "--output_path", str(tmp_path / "full.safetensors")])
    assert rc == 0
    assert (tmp_path / "full.safetensors").exists()