"""Round-17 live-observability contracts (DESIGN.md §22): span tracing
with monotonic stamps, the Perfetto exporter's goodput reconciliation,
anomaly-triggered profiler capture (budget/cooldown state machine + the
slow-step e2e), and the OpenMetrics /metrics endpoint — scraped LIVE
during a serve run with zero added retraces, and structurally pinned to
never touch jax (the zero-sync invariant extended to the scraper)."""

import json
import os
import re
import socket
import sys
import threading
import time
import urllib.request

import pytest

from mobilefinetuner_tpu.core.telemetry import (GoodputMeter, Telemetry,
                                                validate_event)
from mobilefinetuner_tpu.core.trace import AutoProfiler, Tracer

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import write_tiny_gpt2_dir, write_wikitext_dir  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def read_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f.read().splitlines() if l.strip()]


def _free_port() -> int:
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


# --------------------------- span layer --------------------------------------

def test_tracer_emits_schema_valid_spans_and_noops_disabled(tmp_path):
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path) as tel:
        tr = Tracer(tel.emit)
        with tr.span("work", track="phase", step=3):
            time.sleep(0.005)
        tr.emit_span("write", "ckpt", time.perf_counter(), 12.5)
        off = Tracer(None)  # no sink: hard no-op
        assert not off.enabled
        off.emit_span("x", "y", 0.0, 1.0)
        with off.span("z"):
            pass
    recs = read_events(path)
    assert [r["event"] for r in recs] == ["span", "span"]
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    assert recs[0]["name"] == "work" and recs[0]["track"] == "phase"
    assert recs[0]["dur_ms"] >= 4.0
    assert recs[0]["step"] == 3  # extras ride along


def test_envelope_t_mono_monotonic_and_optional_on_read(tmp_path):
    """Round-17 satellite: every record carries a monotonic t_mono next
    to wall t (span alignment never jitters across NTP steps) — and
    records WITHOUT it (pre-round-17 streams) still validate."""
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path) as tel:
        tel.emit("eval", step=1, loss=1.0, ppl=2.0, tokens=3)
        tel.emit("eval", step=2, loss=1.0, ppl=2.0, tokens=3)
    recs = read_events(path)
    assert all(isinstance(r["t_mono"], float) for r in recs)
    assert recs[0]["t_mono"] < recs[1]["t_mono"]
    old = {k: v for k, v in recs[0].items() if k != "t_mono"}
    assert validate_event(old) is None          # old streams still parse
    assert validate_event({**recs[0], "t_mono": "x"}) is not None


def test_goodput_meter_spans_reconcile_with_buckets(tmp_path):
    """The acceptance identity, unit-sized: phase spans come from the
    SAME transitions that charge the buckets, so per-bucket span sums
    equal the summary's bucket totals."""
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path) as tel:
        m = GoodputMeter(tracer=Tracer(tel.emit))
        time.sleep(0.01)
        m.enter("compile")
        time.sleep(0.01)
        m.enter("step")
        time.sleep(0.01)
        m.enter("input_wait")
        time.sleep(0.005)
        m.enter("step")
        time.sleep(0.01)
        s = m.summary()
    sums = {}
    for r in read_events(path):
        assert r["event"] == "span" and r["track"] == "phase"
        sums[r["name"]] = sums.get(r["name"], 0.0) + r["dur_ms"] / 1e3
    for bucket, total in sums.items():
        assert abs(total - s[f"{bucket}_s"]) < 5e-3, (bucket, total, s)
    # every nonzero bucket has spans backing it
    for k, v in s.items():
        if k.endswith("_s") and k != "total_s" and v > 0:
            assert k[:-2] in sums


def test_telemetry_observers_see_records_and_close_is_hard_noop(tmp_path):
    seen = []
    tel = Telemetry(str(tmp_path / "t.jsonl"))
    tel.add_observer(seen.append)
    tel.add_observer(lambda r: 1 / 0)  # a broken observer is swallowed
    rec = tel.emit("eval", step=1, loss=1.0, ppl=2.0, tokens=3)
    assert rec is not None and seen and seen[0]["event"] == "eval"
    tel.close()
    assert tel.emit("eval", step=2, loss=1.0, ppl=2.0, tokens=3) is None
    assert len(seen) == 1  # closed stream: observers muted too
    # observers work WITHOUT a file (metrics without --telemetry_out)
    seen2 = []
    tel2 = Telemetry("")
    tel2.add_observer(seen2.append)
    assert tel2.emit("eval", step=1, loss=1.0, ppl=2.0,
                     tokens=3) is None  # not durably written...
    assert seen2 and seen2[0]["step"] == 1  # ...but observed


# --------------------------- auto profiler -----------------------------------

def test_autoprofiler_budget_cooldown_state_machine(tmp_path):
    starts, stops = [], []
    now = {"t": 0.0}
    path = str(tmp_path / "t.jsonl")
    with Telemetry(path) as tel:
        ap = AutoProfiler(str(tmp_path / "prof"), sink=tel.emit,
                          steps=2, cooldown_s=100.0, budget=2,
                          profiler_start=starts.append,
                          profiler_stop=lambda: stops.append(1),
                          clock=lambda: now["t"])
        assert ap.trigger("slow_step", 5)
        assert ap.active and len(starts) == 1
        assert not ap.trigger("slow_step", 6)   # already capturing
        assert not ap.tick(6)                   # 1 of 2
        assert ap.tick(7)                       # capture completes
        assert ap.captured == 1 and ap.budget == 1 and stops
        assert not ap.trigger("divergence", 8)  # cooldown holds
        now["t"] = 200.0
        assert ap.trigger("divergence", 9)      # cooldown elapsed
        ap.tick(10)
        assert ap.tick(11) and ap.budget == 0
        now["t"] = 999.0
        assert not ap.trigger("slow_step", 12)  # budget exhausted
        # hang path: bounded immediate capture needs no ticks (budget
        # gone here, so it refuses — fresh instance proves the path)
        ap2 = AutoProfiler(str(tmp_path / "prof2"), sink=tel.emit,
                           steps=2, cooldown_s=0.0, budget=1,
                           profiler_start=starts.append,
                           profiler_stop=lambda: stops.append(1))
        assert ap2.capture_now("hang", 42, hold_s=0.0)
        assert ap2.captured == 1
    caps = [r for r in read_events(path)
            if r["event"] == "profile_capture"]
    assert [c["trigger"] for c in caps] == ["slow_step", "divergence",
                                           "hang"]
    for c in caps:
        assert validate_event(c) is None, (c, validate_event(c))
        assert os.path.isdir(c["path"])
    assert caps[0]["step"] == 7 and caps[0]["budget_left"] == 1


def test_autoprofiler_close_stops_open_capture(tmp_path):
    starts, stops = [], []
    ap = AutoProfiler(str(tmp_path), steps=5,
                      profiler_start=starts.append,
                      profiler_stop=lambda: stops.append(1))
    ap.trigger("slow_step", 1)
    ap.close()
    assert stops and not ap.active
    ap.close()  # idempotent
    assert len(stops) == 1


def test_autoprofiler_swallows_profiler_failures(tmp_path):
    def boom(*a):
        raise RuntimeError("no profiler here")
    ap = AutoProfiler(str(tmp_path), profiler_start=boom)
    assert not ap.trigger("slow_step", 1)   # failure contained
    assert not ap.active and ap.captured == 0


# --------------------------- OpenMetrics endpoint ----------------------------

_SAMPLE_RE = re.compile(
    r"^([a-zA-Z_:][a-zA-Z0-9_:]*)(\{[^}]*\})?\s+(-?[0-9.eE+-]+|\+Inf|NaN)$")


def parse_openmetrics(text):
    """Mini OpenMetrics parser: the scrape contract the test enforces —
    TYPE-declared families, well-formed samples, `# EOF` framing.
    Returns (families, samples)."""
    assert text.endswith("# EOF\n"), text[-60:]
    families, samples = {}, {}
    for line in text.splitlines():
        if line == "# EOF":
            break
        if line.startswith("# TYPE "):
            _, _, name, typ = line.split(" ")
            assert typ in ("counter", "gauge", "histogram"), line
            families[name] = typ
        elif line.startswith("#"):
            continue
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"malformed sample line: {line!r}"
            v = float("inf") if m.group(3) == "+Inf" else float(m.group(3))
            samples[m.group(1) + (m.group(2) or "")] = v
            # every sample belongs to a declared family
            base = m.group(1)
            for suffix in ("_bucket", "_count", "_sum", "_total"):
                if base.endswith(suffix):
                    base = base[: -len(suffix)]
                    break
            assert base in families, f"undeclared family for {line!r}"
    return families, samples


def test_registry_renders_parseable_openmetrics_from_representative():
    """Feed one of every schema event through the registry: the render
    must parse, with counters/gauges/histograms all represented."""
    from test_telemetry import REPRESENTATIVE
    from mobilefinetuner_tpu.core.metrics_http import MetricsRegistry
    reg = MetricsRegistry()
    for ev, fields in REPRESENTATIVE.items():
        reg.observe(dict(event=ev, seq=0, t=1.0, **fields))
    reg.observe({"event": "not_a_real_event", "x": 1})  # ignored, safe
    fams, samples = parse_openmetrics(reg.render())
    assert fams["mft_steps"] == "counter"
    assert samples["mft_steps_total"] == 1.0
    assert fams["mft_loss"] == "gauge" and samples["mft_loss"] == 3.2
    assert fams["mft_step_time_ms"] == "histogram"
    assert samples["mft_step_time_ms_count"] == 1.0
    assert samples['mft_requests_total{phase="finish"}'] == 1.0
    assert samples['mft_anomalies_total{kind="loss_spike"}'] == 1.0
    assert samples['mft_runs_total{exit="ok"}'] == 1.0
    assert samples["mft_goodput_productive_frac"] == 0.83
    h = reg.health()
    assert h["status"] == "ok" and h["events_observed"] >= len(
        REPRESENTATIVE)


def test_metrics_server_serves_metrics_and_healthz():
    from mobilefinetuner_tpu.core.metrics_http import (MetricsRegistry,
                                                       MetricsServer)
    reg = MetricsRegistry()
    reg.observe({"event": "step_stats", "step": 3, "loss": 2.0,
                 "step_time_ms": 12.0, "tok_s": 100.0})
    srv = MetricsServer(reg, port=0)  # ephemeral bind: the test path
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            assert r.status == 200
            assert "openmetrics-text" in r.headers["Content-Type"]
            fams, samples = parse_openmetrics(r.read().decode())
        assert samples["mft_loss"] == 2.0
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200
            h = json.loads(r.read())
        assert h["status"] == "ok" and h["last_step"] == 3
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/nope", timeout=5)
        assert ei.value.code == 404
    finally:
        srv.close()


def test_healthz_503_when_engine_draining():
    """Round-22 satellite: a draining engine's /healthz flips to 503
    with draining:true in the body — the router's scrape keys replica
    eligibility on exactly this status code, so a draining replica
    stops taking placements without any router-side special casing.
    Fake engine: the contract is the (health_fn -> HTTP) mapping, not
    the engine."""
    from mobilefinetuner_tpu.core.metrics_http import (MetricsRegistry,
                                                       MetricsServer)
    state = {"draining": False}

    def health():
        return {"status": ("draining" if state["draining"] else "ok"),
                "draining": state["draining"], "queue_depth": 0}

    srv = MetricsServer(MetricsRegistry(), port=0, health_fn=health)
    try:
        base = f"http://127.0.0.1:{srv.port}"
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            assert r.status == 200
            assert json.loads(r.read())["draining"] is False
        state["draining"] = True
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(base + "/healthz", timeout=5)
        assert ei.value.code == 503
        body = json.loads(ei.value.read())
        # the full health payload rides the 503 — a scraper sees WHY
        assert body["status"] == "draining"
        assert body["draining"] is True
    finally:
        srv.close()


def test_serve_stats_cache_gauges_render_and_parse():
    """Round-22 satellite: the r21 cache vitals (prefix_hit_rate,
    cow_copies, blocks_in_use) surface as engine /metrics gauges, plus
    the derived pool-occupancy gauge — pinned through the mini parser
    so the router's affinity/least-loaded scoring has a stable scrape
    contract to read."""
    from mobilefinetuner_tpu.core.metrics_http import MetricsRegistry
    reg = MetricsRegistry()
    reg.observe({"event": "serve_stats", "seq": 0, "t": 1.0, "step": 5,
                 "queue_depth": 2, "active": 3, "occupancy": 0.75,
                 "free_blocks": 40, "p95_step_ms": 12.0, "finished": 7,
                 "cancelled": 0, "rejected": 1, "timeout": 0,
                 "error": 0, "prefix_hit_rate": 0.42, "cow_copies": 3,
                 "blocks_in_use": 24})
    fams, samples = parse_openmetrics(reg.render())
    for name in ("mft_serve_prefix_hit_rate", "mft_serve_cow_copies",
                 "mft_serve_blocks_in_use", "mft_serve_pool_occupancy"):
        assert fams[name] == "gauge", name
    assert samples["mft_serve_prefix_hit_rate"] == 0.42
    assert samples["mft_serve_cow_copies"] == 3.0
    assert samples["mft_serve_blocks_in_use"] == 24.0
    # 24 live of 64 allocatable (parked cache pages count as free)
    assert samples["mft_serve_pool_occupancy"] == 0.375
    # a cache-off snapshot (None vitals) must not poison the render
    reg.observe({"event": "serve_stats", "seq": 1, "t": 2.0, "step": 6,
                 "queue_depth": 0, "active": 0, "occupancy": 0.0,
                 "free_blocks": 64, "p95_step_ms": None, "finished": 7,
                 "cancelled": 0, "rejected": 1, "timeout": 0,
                 "error": 0, "prefix_hit_rate": None, "cow_copies": None,
                 "blocks_in_use": None})
    parse_openmetrics(reg.render())


def test_route_events_and_fleet_registry_helpers_render():
    """Round-22: `route` decisions land as a (policy, replica)-labeled
    counter + a scrape-age histogram, and the public set_gauge /
    observe_hist / inc helpers (the router's per-replica gauges and
    fleet SLO histograms) render through the same parser."""
    from mobilefinetuner_tpu.core.metrics_http import MetricsRegistry
    reg = MetricsRegistry()
    reg.observe({"event": "route", "seq": 0, "t": 1.0, "rid": 7,
                 "replica": 1, "policy": "affinity",
                 "adapter": "tenant0", "queue_depth": 2,
                 "occupancy": 0.5, "scrape_age_ms": 35.0,
                 "candidates": 2})
    reg.observe({"event": "route", "seq": 1, "t": 2.0, "rid": 8,
                 "replica": None, "policy": "reject", "adapter": None,
                 "queue_depth": None, "occupancy": None,
                 "scrape_age_ms": None, "candidates": 0})
    reg.set_gauge("mft_fleet_queue_depth", 3, replica="1")
    reg.set_gauge("mft_fleet_queue_depth", 1, replica="2")
    reg.observe_hist("mft_fleet_ttft_ms", 12.5)
    reg.inc("mft_fleet_requests", state="finished")
    fams, samples = parse_openmetrics(reg.render())
    assert fams["mft_route_decisions"] == "counter"
    assert samples[
        'mft_route_decisions_total{policy="affinity",replica="1"}'] == 1.0
    assert samples[
        'mft_route_decisions_total{policy="reject",replica="None"}'] == 1.0
    assert fams["mft_route_scrape_age_ms"] == "histogram"
    assert samples["mft_route_scrape_age_ms_count"] == 1.0
    assert samples['mft_fleet_queue_depth{replica="1"}'] == 3.0
    assert samples['mft_fleet_queue_depth{replica="2"}'] == 1.0
    assert samples["mft_fleet_ttft_ms_count"] == 1.0
    assert samples['mft_fleet_requests_total{state="finished"}'] == 1.0


def test_observability_modules_never_import_jax_at_module_level():
    """The zero-sync pin, structurally (migrated r19): graftlint's
    `no-jax-import` rule — metrics_http must not import jax AT ALL
    (policy "never"), trace.py/telemetry.py must keep module level
    jax-free (policy "toplevel"; AutoProfiler binds jax.profiler lazily
    inside the capture functions only). The rule is AST-based, so a
    lazy in-function import in metrics_http fails it too."""
    from mobilefinetuner_tpu.core.static_checks import (NO_JAX_MODULES,
                                                        run_lint)
    res = run_lint([os.path.join(REPO, "mobilefinetuner_tpu")],
                   rules=["no-jax-import"])
    bad = res.findings + res.suppressed  # this rule is never suppressed
    assert not bad, [f.render() for f in bad]
    # the policy table still covers the three observability modules
    assert {s.rsplit("/", 1)[-1] for s in NO_JAX_MODULES} >= {
        "metrics_http.py", "trace.py", "telemetry.py"}


# --------------------------- train e2e ---------------------------------------

@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2obs")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2obs")))


def test_train_e2e_spans_export_and_goodput_reconcile(gpt2_dir, wiki_dir,
                                                      tmp_path):
    """Acceptance: a traced tiny train run exports to ONE Perfetto
    trace whose phase-span sums reconcile with run_end's goodput
    buckets to <1% of total, with ckpt-writer and prefetch-producer
    tracks present; both report tools render the stream and the shared
    --format json serializer returns the same sections."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    stream = str(tmp_path / "run.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "4", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--telemetry_out", stream, "--trace_spans", "1",
               "--save_every", "2", "--eval_interval", "4",
               "--eval_batches", "1", "--log_interval", "2"])
    assert rc == 0
    recs = read_events(stream)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    spans = [r for r in recs if r["event"] == "span"]
    tracks = {s["track"] for s in spans}
    assert "phase" in tracks and "ckpt" in tracks \
        and "prefetch" in tracks, tracks
    goodput = [r for r in recs if r["event"] == "run_end"][-1]["goodput"]

    import trace_export
    out = str(tmp_path / "trace.json")
    assert trace_export.main([stream, "-o", out]) == 0
    trace = json.load(open(out))
    assert trace["traceEvents"], "empty trace"
    rec_check = trace_export.phase_reconcile(trace, goodput)
    assert rec_check, "no phase spans reconciled"
    total = goodput["total_s"]
    for bucket, (span_s, bucket_s, delta) in rec_check.items():
        assert delta <= max(0.01 * total, 0.005), \
            (bucket, span_s, bucket_s, total)
    # every trace event is structurally drawable
    for e in trace["traceEvents"]:
        assert e["ph"] in ("X", "i", "C", "M")
        if e["ph"] == "X":
            assert e["dur"] >= 0 and isinstance(e["name"], str)
    # report tools: text renders the span/track rollup, json carries it
    import telemetry_report
    events, bad = telemetry_report.load_events(stream)
    s = telemetry_report.summarize(events, bad)
    obs = s["observability"]
    assert obs["spans"] == len(spans)
    assert set(obs["span_tracks"]) == tracks
    assert telemetry_report.main([stream, "--format", "json"]) == 0
    assert telemetry_report.main([stream]) == 0


def test_train_e2e_auto_profile_slow_step_captures_once(gpt2_dir,
                                                        wiki_dir,
                                                        tmp_path):
    """Satellite e2e: an injected slow step trips the flight recorder
    exactly once — the capture lands on disk with a profile_capture
    event pointing at it — and the cooldown holds through the later
    slow steps (budget intact for a future incident)."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    stream = str(tmp_path / "run.jsonl")
    prof_dir = str(tmp_path / "profiles")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "10", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--telemetry_out", stream, "--log_interval", "1",
               "--inject", "slow_step:6:400:2",
               "--auto_profile", "1", "--auto_profile_dir", prof_dir,
               "--auto_profile_steps", "1",
               "--auto_profile_budget", "2",
               "--auto_profile_cooldown", "3600",
               "--auto_profile_slow_mult", "3"])
    assert rc == 0
    recs = read_events(stream)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    caps = [r for r in recs if r["event"] == "profile_capture"]
    assert len(caps) == 1, [c["step"] for c in caps]  # cooldown held
    cap = caps[0]
    assert cap["trigger"] == "slow_step" and cap["budget_left"] == 1
    assert os.path.isdir(cap["path"])
    # the capture actually wrote a device trace (jax.profiler output)
    dumped = [os.path.join(r, f) for r, _, fs in os.walk(cap["path"])
              for f in fs]
    assert dumped, "profiler capture directory is empty"
    assert recs[-1]["event"] == "run_end" and recs[-1]["exit"] == "ok"


# --------------------------- serve e2e ---------------------------------------

def test_serve_e2e_spans_and_live_metrics_scrape(tmp_path):
    """Acceptance: /metrics scraped CONCURRENTLY during a live tiny
    serve run returns parseable OpenMetrics with nonzero request
    histograms, the run's post-warmup retrace count stays ZERO while
    being scraped (trace_counts pin), per-request spans land on
    req:<id> tracks, and the exported trace carries them."""
    import serve_bench
    stream = str(tmp_path / "serve.jsonl")
    port = _free_port()
    eng, names = serve_bench.build_engine(
        "tiny-gpt2", num_slots=2, block_T=4, num_blocks=32,
        max_prompt=8, max_new=4, adapters=0, dtype="float32",
        telemetry_out=stream, stats_every=2, trace_spans=True,
        metrics_port=port)
    try:
        eng.submit([1, 2, 3], max_new_tokens=2)
        eng.drain()                       # warmup: compile both programs
        warm = eng.total_traces()
        base = f"http://127.0.0.1:{port}"
        scrapes, stop = [], threading.Event()

        def scraper():
            while not stop.is_set():
                with urllib.request.urlopen(base + "/metrics",
                                            timeout=5) as r:
                    scrapes.append((r.status, r.read().decode()))
                time.sleep(0.005)

        th = threading.Thread(target=scraper, daemon=True)
        th.start()
        done, elapsed = serve_bench.run_load(
            eng, names, rate=200.0, n_requests=10, seed=0,
            prompt_lo=3, prompt_hi=6, max_new=4)
        stop.set()
        th.join(timeout=5)
        assert eng.total_traces() == warm, \
            "scraping the metrics endpoint cost a retrace"
        assert scrapes and all(st == 200 for st, _ in scrapes)
        with urllib.request.urlopen(base + "/metrics", timeout=5) as r:
            final = r.read().decode()
        with urllib.request.urlopen(base + "/healthz", timeout=5) as r:
            hz = json.loads(r.read())
    finally:
        eng.metrics_server.close()
        eng.close()
    fams, samples = parse_openmetrics(final)
    assert fams["mft_ttft_ms"] == "histogram"
    assert samples["mft_ttft_ms_count"] > 0        # nonzero histograms
    assert samples["mft_tpot_ms_count"] > 0
    assert samples['mft_requests_total{phase="finish"}'] >= 10
    assert "queue_depth" in hz and "counts" in hz  # engine.health()
    # mid-run scrapes already carried data (live, not post-hoc)
    assert any("mft_requests_total" in body for _, body in scrapes)
    # spans: every admitted request got queue/prefill/decode on its track
    recs = read_events(stream)
    for r in recs:
        assert validate_event(r) is None, (r, validate_event(r))
    spans = [r for r in recs if r["event"] == "span"]
    req_tracks = {s["track"] for s in spans if s["track"].startswith("req:")}
    assert len(req_tracks) >= 10
    names_on_track = {s["name"] for s in spans
                      if s["track"] == sorted(req_tracks)[0]}
    assert {"queue", "prefill", "decode"} <= names_on_track
    # ONE command renders the serve session (request tracks included)
    import trace_export
    out = str(tmp_path / "serve.trace.json")
    assert trace_export.main([stream, "-o", out]) == 0
    trace = json.load(open(out))
    xs = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert any(e["name"] == "decode" for e in xs)
    assert any(e["name"] == "prefill" for e in xs)


def test_trace_export_synthesizes_request_spans_without_tracing():
    """A stream recorded WITHOUT --trace_spans still exports: request
    lifecycle spans are synthesized from the request events' wall
    stamps (queue = enqueue->admit, decode = admit->terminal)."""
    import trace_export
    t0 = 1000.0
    evs = []

    def ev(seq, event, dt, **f):
        evs.append({"event": event, "seq": seq, "t": t0 + dt,
                    "t_mono": 50.0 + dt, "host": 0, **f})

    req = dict(prompt_tokens=3, adapter=None, queue_ms=None,
               new_tokens=None, ttft_ms=None, tpot_ms=None, reason=None)
    ev(0, "request", 0.0, id=7, phase="enqueue", **req)
    ev(1, "request", 0.5, id=7, phase="admit", **req)
    ev(2, "request", 0.6, id=7, phase="first_token", **req)
    ev(3, "request", 2.0, id=7, phase="finish",
       **{**req, "new_tokens": 8})
    ev(4, "checkpoint", 3.0, step=2, final=False, wall_s=0.1,
       snapshot_ms=1.0, write_ms=500.0, bytes=1 << 20, mb_s=2.0,
       **{"async": True})
    for e in evs:
        assert validate_event(e) is None, (e, validate_event(e))
    trace = trace_export.export({0: evs})
    xs = {e["name"]: e for e in trace["traceEvents"] if e["ph"] == "X"}
    assert "queue" in xs and "decode" in xs
    assert xs["queue"]["dur"] == pytest.approx(0.5e6, rel=1e-6)
    assert xs["decode"]["dur"] == pytest.approx(1.5e6, rel=1e-6)
    assert xs["decode"]["args"]["outcome"] == "finish"
    # checkpoint write span derived from the write_ms on the event
    ck = next(e for e in trace["traceEvents"]
              if e["ph"] == "X" and e["name"].startswith("ckpt_write"))
    assert ck["dur"] == pytest.approx(500e3, rel=1e-6)


def test_trace_export_scopes_resumed_stream_to_latest_run():
    """A resumed stream appends runs whose perf_counter epochs share
    nothing: the exporter renders only the LATEST run, so one clock
    offset places every span and the reconciliation never mixes a
    prior run's phase spans into the final run_end's buckets."""
    import trace_export
    mk = lambda seq, dt, tm, ev, **f: {"event": ev, "seq": seq,
                                       "t": 1000.0 + dt,
                                       "t_mono": tm, "host": 0, **f}
    run_start = dict(jax_version="x", mesh_shape=None, process_count=1,
                     process_index=0, device_kind="cpu", device_count=1,
                     config={})
    evs = [
        mk(0, 0.0, 5000.0, "run_start", **run_start),
        mk(1, 1.0, 5001.0, "span", name="step", track="phase",
           t0=5000.0, dur_ms=1000.0),
        mk(2, 2.0, 5002.0, "run_end", steps=1, wall_s=2.0, exit="ok",
           goodput={"step_s": 1.0, "total_s": 2.0,
                    "productive_frac": 0.5}),
        # resumed run: fresh process, fresh (much smaller) mono epoch
        mk(3, 100.0, 7.0, "run_start", **run_start),
        mk(4, 103.0, 10.0, "span", name="step", track="phase",
           t0=7.0, dur_ms=3000.0),
        mk(5, 104.0, 11.0, "run_end", steps=2, wall_s=4.0, exit="ok",
           goodput={"step_s": 3.0, "total_s": 4.0,
                    "productive_frac": 0.75}),
    ]
    for e in evs:
        assert validate_event(e) is None, (e, validate_event(e))
    trace = trace_export.export({0: evs})
    spans = [e for e in trace["traceEvents"] if e["ph"] == "X"]
    assert len(spans) == 1  # the prior run's span is NOT on this timeline
    assert spans[0]["dur"] == pytest.approx(3000e3)
    rec = trace_export.phase_reconcile(
        trace, evs[-1]["goodput"], pid=0)
    assert rec["step"][2] == pytest.approx(0.0, abs=1e-6)


# --------------------------- bench_compare -----------------------------------

def test_bench_compare_rows_deltas_and_regression_gate(tmp_path):
    """Satellite contract: shared-row matching, per-metric % delta with
    direction awareness (nested percentile dicts flattened), threshold
    gating — on two synthetic rows."""
    import bench_compare
    old = tmp_path / "old.json"
    new = tmp_path / "new.json"
    old.write_text(json.dumps({"rows": [
        {"config": "a", "tokens_per_sec_per_chip": 100.0,
         "ttft_ms": {"p50": 50.0}, "peak_hbm_mb": 800.0},
        {"config": "gone", "tokens_per_sec_per_chip": 9.0},
    ]}))
    # the other artifact shape: plain JSONL rows (bench.py stdout)
    new.write_text(
        json.dumps({"config": "a", "tokens_per_sec_per_chip": 80.0,
                    "ttft_ms": {"p50": 40.0}, "peak_hbm_mb": 820.0})
        + "\n" + json.dumps({"config": "fresh",
                             "tokens_per_sec_per_chip": 1.0}) + "\n")
    o = bench_compare.load_rows(str(old))
    n = bench_compare.load_rows(str(new))
    assert set(o) == {"a", "gone"} and set(n) == {"a", "fresh"}
    assert o["a"]["ttft_ms.p50"] == 50.0  # nested dict flattened
    c = bench_compare.compare(o, n, threshold=5.0)
    assert c["shared_rows"] == ["a"]
    assert c["only_old"] == ["gone"] and c["only_new"] == ["fresh"]
    by = {m["metric"]: m for m in c["metrics"]}
    tok = by["tokens_per_sec_per_chip"]
    assert tok["delta_pct"] == pytest.approx(-20.0)
    assert tok["regressed"]                      # throughput down 20%
    assert not by["ttft_ms.p50"]["regressed"]    # latency IMPROVED
    assert by["peak_hbm_mb"]["delta_pct"] == pytest.approx(2.5)
    assert not by["peak_hbm_mb"]["regressed"]    # 2.5% < 5% threshold
    assert [m["metric"] for m in c["regressions"]] == \
        ["tokens_per_sec_per_chip"]
    # direction heuristics
    assert bench_compare.direction("tok_s") == 1
    assert bench_compare.direction("tpot_ms.p99") == -1
    assert bench_compare.direction("loss") == 0
    # no threshold -> nothing gates
    assert not bench_compare.compare(o, n, threshold=0.0)["regressions"]
