"""Round-22 serve-fleet router (tools/serve_router.py, DESIGN.md §27).

The unit half never imports jax — the router process itself doesn't
(replicas do, in their own processes), and these tests pin exactly the
jax-free surfaces: the scrape parser, the replica HTTP gateway, the
RouterCore placement/settlement ledger (against fake replica servers),
and the shard-tail death-settlement protocol.

The e2e half launches the REAL router with two tiny-gpt2 CPU replica
subprocesses, SIGKILLs one mid-Poisson-load, and proves the fleet
invariant the whole design hangs on: every stamped rid settles exactly
once — rerouted to the survivor or delivered from the dead replica's
flushed shard — while the controller restarts the victim and every
stream stays schema-valid.
"""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)
sys.path.insert(0, os.path.join(REPO, "tools"))

import serve_router as sr  # noqa: E402

from mobilefinetuner_tpu.core.metrics_http import (MetricsRegistry,  # noqa: E402
                                                   MetricsServer)
from mobilefinetuner_tpu.core.telemetry import (Telemetry,  # noqa: E402
                                                controller_path,
                                                shard_path,
                                                validate_event)
from mobilefinetuner_tpu.core.trace import Tracer  # noqa: E402


def read_stream(path):
    """Parsed records of one stream; a SIGKILL can truncate at most the
    final line mid-write, so one unparseable TAIL line is tolerated and
    anything else is a corruption failure."""
    recs, bad = [], 0
    with open(path) as f:
        lines = f.read().splitlines()
    for i, ln in enumerate(lines):
        if not ln.strip():
            continue
        try:
            recs.append(json.loads(ln))
        except json.JSONDecodeError:
            bad += 1
            assert i == len(lines) - 1, f"mid-stream corruption: {path}"
    assert bad <= 1, path
    return recs


# --------------------------- unit: scrape parser ----------------------------

def test_parse_serve_gauges_pulls_unlabeled_serve_samples():
    text = "\n".join([
        "# TYPE mft_serve_queue_depth gauge",
        "mft_serve_queue_depth 3",
        "mft_serve_occupancy 0.625",
        "mft_serve_pool_occupancy 0.375",
        'mft_serve_terminal_total{state="finished"} 7',  # labeled: not a vital
        "mft_train_step_ms 12.5",                        # wrong family
        "mft_serve_p95_step_ms bogus",                   # unparseable value
        "# EOF"])
    assert sr.parse_serve_gauges(text) == {
        "queue_depth": 3.0, "occupancy": 0.625, "pool_occupancy": 0.375}


# --------------------------- unit: replica gateway --------------------------

class _FakeReq:
    """Just the attributes ReplicaGateway.summarize reads."""

    def __init__(self, rid):
        self.rid, self.id, self.state = rid, 3, "finished"
        self.reason, self.adapter = None, "tenant0"
        self.prompt, self.tokens = [1, 2, 3, 4], [9, 9]
        self.ttft_ms, self.tpot_ms = 5.0, 1.25
        self.enqueue_t, self.admit_t = 10.0, 10.002
        self.done = True


def test_replica_gateway_submit_collect_drain_contract():
    gw = sr.ReplicaGateway()
    code, obj = gw.route_submit({"prompt": [1, 2], "rid": 5})
    assert (code, obj["accepted"], obj["rid"]) == (200, True, 5)
    assert gw.route_submit("not a dict")[0] == 400
    assert gw.route_submit({"max_new_tokens": 4})[0] == 400
    gw.begin_drain()
    code, obj = gw.route_submit({"prompt": [1], "rid": 6})
    assert code == 503 and obj["draining"] is True
    # terminal results ride the outbox in the settle-row shape
    gw.push([_FakeReq(5)])
    assert gw.outbox_size() == 1
    code, obj = gw.route_collect({})
    row = obj["done"][0]
    assert row["rid"] == 5 and row["state"] == "finished"
    assert row["prompt_tokens"] == 4 and row["new_tokens"] == 2
    assert row["queue_ms"] == pytest.approx(2.0)
    assert gw.route_collect({})[1]["done"] == []  # collect drains


# --------------------------- unit: router core ------------------------------

def _core(tmp_path, cache=None):
    base = str(tmp_path / "router.jsonl")
    tel = Telemetry(base, host=0)
    core = sr.RouterCore(tel, Tracer(sink=tel.emit), MetricsRegistry(),
                         cache or sr.ScrapeCache(), max_age_s=5.0)
    return core, tel, base


def test_router_core_reject_settles_rid_exactly_once(tmp_path):
    core, tel, base = _core(tmp_path)
    code, obj = core.route_submit({"prompt": [1, 2, 3]})
    assert code == 503 and obj["rid"] == 0 \
        and obj["reason"] == "no_replica"
    # the reject already settled rid 0 — a late duplicate is a no-op
    assert core.deliver(0, None, {"state": "finished"}) is False
    assert core.deliver(None, None, {"state": "finished"}) is False
    code, obj = core.route_collect({})
    rows = obj["done"]
    assert len(rows) == 1 and rows[0]["state"] == "rejected" \
        and rows[0]["rid"] == 0 and rows[0]["replica"] is None
    assert core.route_collect({})[1]["done"] == []
    core.close_intake()
    code, obj = core.route_submit({"prompt": [1]})
    assert code == 503 and obj["reason"] == "shutdown" and "rid" not in obj
    assert core.counts() == {"routed": 0, "inflight": 0,
                             "results_pending": 0}
    tel.close()
    recs = read_stream(base)
    for r in recs:
        validate_event(r)
    routes = [r for r in recs if r["event"] == "route"]
    assert len(routes) == 1 and routes[0]["replica"] is None \
        and routes[0]["policy"] == "reject" and routes[0]["candidates"] == 0


def _fake_replica(accepted=True):
    """A replica's /submit data plane without an engine behind it."""
    calls = []

    def submit(payload):
        calls.append(payload)
        if accepted:
            return 200, {"accepted": True, "rid": payload.get("rid")}
        return 503, {"accepted": False, "draining": True}

    srv = MetricsServer(MetricsRegistry(), port=0,
                        routes={"/submit": submit})
    return srv, calls


def test_router_core_affinity_least_loaded_failover(tmp_path):
    s1, c1 = _fake_replica()
    s2, c2 = _fake_replica()
    cache = sr.ScrapeCache()
    now = time.time()
    cache.put(1, {"t": now, "port": s1.port, "status": "ok",
                  "draining": False, "adapters": ["tenant0"],
                  "queue_depth": 5, "active": 2})
    cache.put(2, {"t": now, "port": s2.port, "status": "ok",
                  "draining": False, "adapters": [],
                  "queue_depth": 0, "active": 0})
    core, tel, base = _core(tmp_path, cache)
    try:
        # resident adapter beats load: the busier replica 1 wins
        code, obj = core.route_submit({"prompt": [1], "adapter": "tenant0"})
        assert (code, obj["replica"], obj["policy"]) == (200, 1, "affinity")
        assert c1[-1]["rid"] == obj["rid"]  # the fleet rid rides submit
        # no adapter: least (queue + active + router-inflight) wins
        code, obj = core.route_submit({"prompt": [2]})
        assert (obj["replica"], obj["policy"]) == (2, "least_loaded")
        # preferred replica unreachable (died since the scrape): walk on
        s2.close()
        code, obj = core.route_submit({"prompt": [3]})
        assert (code, obj["replica"], obj["policy"]) == (200, 1, "failover")
        assert core.counts()["routed"] == 3 \
            and core.counts()["inflight"] == 3
        # a draining snapshot is not a candidate at all
        cache.put(1, {"t": now, "port": s1.port, "status": "draining",
                      "draining": True, "adapters": ["tenant0"],
                      "queue_depth": 0, "active": 0})
        cache.drop(2)
        code, obj = core.route_submit({"prompt": [4]})
        assert code == 503 and obj["reason"] == "no_replica"
    finally:
        s1.close()
        s2.close()
        tel.close()
    recs = read_stream(base)
    for r in recs:
        validate_event(r)
    assert [r["policy"] for r in recs if r["event"] == "route"] \
        == ["affinity", "least_loaded", "failover", "reject"]
    # the router half of each routed rid's timeline: queue + route spans
    spans = [r for r in recs if r["event"] == "span"]
    assert {(s["name"], s["track"]) for s in spans} == {
        ("queue", "req:0"), ("route", "req:0"),
        ("queue", "req:1"), ("route", "req:1"),
        ("queue", "req:2"), ("route", "req:2")}
    assert all(isinstance(s["rid"], int) for s in spans)


def test_take_inflight_and_reroute_keep_the_rid(tmp_path):
    s1, c1 = _fake_replica()
    cache = sr.ScrapeCache()
    cache.put(1, {"t": time.time(), "port": s1.port, "status": "ok",
                  "draining": False, "adapters": [], "queue_depth": 0,
                  "active": 0})
    core, tel, base = _core(tmp_path, cache)
    try:
        code, obj = core.route_submit({"prompt": [1, 2]})
        rid = obj["rid"]
        orphans = core.take_inflight(1)
        assert list(orphans) == [rid] and core.counts()["inflight"] == 0
        assert core.take_inflight(1) == {}  # pop semantics
        core.reroute(rid, orphans[rid]["payload"])
        assert c1[-1]["rid"] == rid  # SAME fleet identity, new placement
        assert core.counts()["inflight"] == 1
    finally:
        s1.close()
        tel.close()
    routes = [r for r in read_stream(base) if r["event"] == "route"]
    assert [r["policy"] for r in routes] == ["least_loaded", "failover"]
    assert routes[0]["rid"] == routes[1]["rid"] == 0


# --------------------------- unit: shard settlement -------------------------

def test_serve_shard_tail_terminals_and_row_from_shard(tmp_path):
    path = str(tmp_path / "s.jsonl.host1")
    tail = sr.ServeShardTail(path)  # tail from byte 0: file not yet born
    recs = [
        {"event": "request", "rid": 7, "id": 3, "phase": "enqueue"},
        {"event": "request", "rid": 7, "id": 3, "phase": "finish",
         "reason": None, "adapter": "tenant1", "prompt_tokens": 6,
         "new_tokens": 4, "ttft_ms": 8.0, "tpot_ms": 2.0,
         "queue_ms": 1.5},
        {"event": "request", "rid": 9, "id": 4, "phase": "timeout",
         "reason": "deadline", "new_tokens": None},
        {"event": "request", "id": 5, "phase": "finish"},  # no rid: local
        {"event": "serve_stats", "step": 1},
    ]
    with open(path, "w") as f:
        f.write("".join(json.dumps(r) + "\n" for r in recs))
    tail.poll()
    assert sorted(tail.terminal) == [7, 9]
    row = sr.row_from_shard(tail.terminal[7])
    assert row == {"rid": 7, "id": 3, "state": "finished",
                   "reason": None, "adapter": "tenant1",
                   "prompt_tokens": 6, "new_tokens": 4, "ttft_ms": 8.0,
                   "tpot_ms": 2.0, "queue_ms": 1.5}
    assert sr.row_from_shard(tail.terminal[9])["state"] == "timeout"
    assert sr.row_from_shard(tail.terminal[9])["new_tokens"] == 0


# --------------------------- e2e: kill one replica --------------------------

def _wait(pred, timeout_s, what, proc=None, log=None):
    deadline = time.time() + timeout_s
    while time.time() < deadline:
        v = pred()
        if v:
            return v
        if proc is not None and proc.poll() is not None:
            tail = open(log).read()[-3000:] if log else ""
            raise AssertionError(f"router died waiting for {what}\n{tail}")
        time.sleep(0.05)
    tail = open(log).read()[-3000:] if log else ""
    raise AssertionError(f"timeout waiting for {what}\n{tail}")


def test_kill_one_replica_mid_load_exact_accounting(tmp_path):
    """Satellite (d): two tiny CPU replicas behind the real router; one
    is SIGKILLed mid-Poisson-load. Requests reroute to the survivor,
    the controller restarts the victim, and EVERY stamped rid settles
    exactly once — delivered from the dead replica's flushed shard or
    rerouted, never lost, never doubled. All four streams stay
    schema-valid through the crash."""
    base = str(tmp_path / "fleet.jsonl")
    log = str(tmp_path / "router.log")
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, os.path.join(REPO, "tools", "serve_router.py"),
         "--telemetry", base, "--replicas", "2",
         "--engine_json", json.dumps({"adapters": 2, "stats_every": 5,
                                      "max_new": 8}),
         "--scrape_s", "0.05", "--collect_s", "0.02",
         "--backoff_s", "0.2", "--restart_budget", "3"],
        env=env, cwd=REPO, stdout=open(log, "w"),
        stderr=subprocess.STDOUT, text=True)
    try:
        pf = _wait(lambda: sr.read_port_file(base, 0), 300.0,
                   "front door port file", proc, log)
        front = f"http://127.0.0.1:{pf['port']}"

        def fleet():
            try:
                _, obj = sr._http_json("GET", front + "/fleet",
                                       timeout=2.0)
            except OSError:
                return None
            reps = obj.get("replicas") or {}
            ok = [h for h, i in reps.items() if i.get("status") == "ok"]
            return obj if len(ok) == 2 else None

        info = _wait(fleet, 300.0, "both replicas healthy", proc, log)
        pids = {h: i["pid"] for h, i in info["replicas"].items()}

        # deterministic Poisson-ish arrivals, victim killed mid-stream
        import random
        rng = random.Random(0)
        n, kill_at, victim = 16, 6, "1"
        rids, kill_done = [], False
        for i in range(n):
            if i == kill_at:
                os.kill(pids[victim], signal.SIGKILL)
                kill_done = True
            code, obj = sr._http_json(
                "POST", front + "/submit",
                {"prompt": [1 + i % 7] * (4 + i % 5),
                 "max_new_tokens": 4, "adapter": f"tenant{i % 2}"},
                timeout=10.0)
            # a reject mid-crash-window is legal — but it still carries
            # the rid and settles like everything else
            assert code in (200, 503) and isinstance(obj.get("rid"), int)
            rids.append(obj["rid"])
            time.sleep(min(rng.expovariate(20.0), 0.2))
        assert kill_done and len(set(rids)) == n

        # collect until the ledger is empty: exactly one row per rid
        settled = {}

        def drain():
            _, obj = sr._http_json("POST", front + "/collect", {},
                                   timeout=5.0)
            for row in obj.get("done") or []:
                assert row["rid"] not in settled, "rid settled TWICE"
                settled[row["rid"]] = row
            return len(settled) == n or None

        _wait(drain, 240.0, "all rids settled", proc, log)
        assert sorted(settled) == sorted(rids)
        states = {r["state"] for r in settled.values()}
        assert states <= {"finished", "cancelled", "rejected",
                          "timeout", "error"}
        assert sum(r["state"] == "finished"
                   for r in settled.values()) >= n // 2
        # the controller saw the death and spent a restart attempt
        _wait(lambda: any(
            r.get("event") == "controller" and r.get("action") == "restart"
            and r.get("worker") == int(victim)
            for r in read_stream(controller_path(base))),
            60.0, "controller restart record", proc, log)
    finally:
        proc.send_signal(signal.SIGTERM)
        try:
            proc.wait(timeout=120)
        except subprocess.TimeoutExpired:
            proc.kill()
            proc.wait()
            pytest.fail("router did not drain on SIGTERM")

    # post-mortem: every stream schema-valid (one truncated tail line
    # allowed on the SIGKILLed shard), down+restart recorded, and the
    # routed rids visible in replica request events (rid propagation)
    streams = {0: base, 1: shard_path(base, 1), 2: shard_path(base, 2),
               "ctl": controller_path(base)}
    recs = {k: read_stream(p) for k, p in streams.items()}
    for evs in recs.values():
        for r in evs:
            validate_event(r)
    actions = [(r.get("action"), r.get("worker")) for r in recs["ctl"]
               if r.get("event") == "controller"]
    assert ("down", int(victim)) in actions
    assert ("restart", int(victim)) in actions
    routes = [r for r in recs[0] if r["event"] == "route"]
    assert {r["rid"] for r in routes} == set(rids)
    placed = [r for r in routes if r["replica"] is not None]
    assert {r["replica"] for r in placed} <= {1, 2}
    shard_rids = {r.get("rid") for k in (1, 2) for r in recs[k]
                  if r.get("event") == "request"}
    assert {r["rid"] for r in placed} <= shard_rids
    # survivor really absorbed load after the kill
    assert any(r["replica"] == 2 for r in placed)
