"""LoRA correctness: zero-impact at init, merge/unmerge idempotence, native
adapter round-trip, PEFT export verified against real HF PEFT.
(Reference analogs: test_lora_correctness.cpp, test_lora_roundtrip.cpp,
nn/test_lora_linear.cpp.)"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                           merge_gpt2, num_trainable,
                                           trainable_mask, unmerge_gpt2)
from mobilefinetuner_tpu.lora.peft_io import (export_peft, import_peft,
                                              load_adapter, save_adapter)
from mobilefinetuner_tpu.models import gpt2

CFG = GPT2Config.tiny()


@pytest.fixture(scope="module")
def setup():
    key = jax.random.PRNGKey(0)
    params = gpt2.init_params(CFG, key)
    spec = LoRASpec(rank=4, alpha=8.0,
                    targets=["attn_qkv", "attn_proj", "mlp_fc_in",
                             "mlp_fc_out"])
    lora = init_lora_gpt2(CFG, spec, jax.random.PRNGKey(1))
    ids = jnp.array(np.random.default_rng(0).integers(
        0, CFG.vocab_size, size=(2, 16)))
    return params, spec, lora, ids


def test_zero_init_lora_is_identity(setup):
    params, spec, lora, ids = setup
    base = gpt2.forward(CFG, params, ids)
    with_lora = gpt2.forward(CFG, params, ids, lora=lora)
    np.testing.assert_allclose(np.asarray(base), np.asarray(with_lora),
                               atol=1e-6)


def test_merge_matches_dynamic_lora(setup):
    params, spec, lora, ids = setup
    # make B nonzero so LoRA actually does something
    lora = jax.tree.map(lambda x: x, lora)
    key = jax.random.PRNGKey(7)
    for name, entry in lora["blocks"].items():
        key, sub = jax.random.split(key)
        entry["B"] = jax.random.normal(sub, entry["B"].shape) * 0.05
    dynamic = gpt2.forward(CFG, params, ids, lora=lora)
    merged = merge_gpt2(params, lora)
    static = gpt2.forward(CFG, merged, ids)
    np.testing.assert_allclose(np.asarray(dynamic), np.asarray(static),
                               atol=1e-4)
    # unmerge restores the base weights
    restored = unmerge_gpt2(merged, lora)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_adapter_roundtrip(tmp_path, setup):
    params, spec, lora, ids = setup
    path = str(tmp_path / "adapter.safetensors")
    save_adapter(path, lora, spec)
    back, spec2 = load_adapter(path)
    assert spec2.rank == spec.rank and spec2.alpha == spec.alpha
    for name in lora["blocks"]:
        np.testing.assert_array_equal(
            np.asarray(lora["blocks"][name]["A"], dtype=np.float32),
            np.asarray(back["blocks"][name]["A"]))
        np.testing.assert_array_equal(
            np.asarray(lora["blocks"][name]["B"], dtype=np.float32),
            np.asarray(back["blocks"][name]["B"]))


def test_trainable_mask_excludes_scale(setup):
    _, spec, lora, _ = setup
    mask = trainable_mask(lora)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    for path, val in flat:
        is_scale = getattr(path[-1], "key", None) == "scale"
        assert val != is_scale
    n = num_trainable(lora)
    E, r, L = CFG.n_embd, spec.rank, CFG.n_layer
    expect = L * r * (E + 3 * E) + L * r * (E + E) + \
        L * r * (E + 4 * E) + L * r * (4 * E + E)
    assert n == expect


def test_peft_export_loads_in_hf_peft(tmp_path):
    """Export our adapter, attach it to the matching HF GPT-2 via real PEFT,
    and require logit parity with our dynamic-LoRA forward."""
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel
    from peft import PeftModel

    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=97, n_positions=32, n_embd=16, n_layer=2,
                      n_head=2, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(vocab_size=97, n_positions=32, n_embd=16, n_layer=2,
                     n_head=2)
    from mobilefinetuner_tpu.io.checkpoints import gpt2_params_from_hf
    sd = {k: v.numpy() for k, v in model.transformer.state_dict().items()
          if not k.endswith(".attn.bias")}
    params = gpt2_params_from_hf(sd, cfg)

    spec = LoRASpec(rank=4, alpha=8.0, targets=["attn_qkv", "attn_proj"])
    lora = init_lora_gpt2(cfg, spec, jax.random.PRNGKey(3))
    for entry in lora["blocks"].values():
        entry["B"] = jax.random.normal(jax.random.PRNGKey(4),
                                       entry["B"].shape) * 0.1

    out_dir = str(tmp_path / "peft_adapter")
    export_peft(out_dir, lora, spec, family="gpt2")

    peft_model = PeftModel.from_pretrained(model, out_dir).eval()
    ids = np.random.default_rng(5).integers(0, 97, size=(2, 12))
    with torch.no_grad():
        ref = peft_model(torch.tensor(ids)).logits.numpy()
    ours = np.asarray(gpt2.forward(cfg, params, jnp.array(ids), lora=lora))
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=1e-3)

    # and the import path round-trips
    back, spec2 = import_peft(out_dir, family="gpt2")
    for name in lora["blocks"]:
        np.testing.assert_allclose(
            np.asarray(lora["blocks"][name]["A"]),
            np.asarray(back["blocks"][name]["A"]), atol=1e-6)
