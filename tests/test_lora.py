"""LoRA correctness: zero-impact at init, merge/unmerge idempotence, native
adapter round-trip, PEFT export verified against real HF PEFT.
(Reference analogs: test_lora_correctness.cpp, test_lora_roundtrip.cpp,
nn/test_lora_linear.cpp.)

Round 12 adds the lora_impl contract (DESIGN.md §17): the fused
(shape-aware order + Pallas epilogue) path value+grad parity-pinned to
the naive oracle across dtypes, both families, dropout on/off, and
single/stacked-adapter routing; the f32-accumulation numerics pin at
r=8 S=2048; the stack_adapters mismatch diagnostics; and zero retraces
when serve hot-swaps adapters under lora_impl=fused."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.lora.lora import (LoRASpec, assign_adapters,
                                           init_lora_gemma3,
                                           init_lora_gpt2, merge_gpt2,
                                           num_trainable, stack_adapters,
                                           trainable_mask, unmerge_gpt2)
from mobilefinetuner_tpu.lora.peft_io import (export_peft, import_peft,
                                              load_adapter, save_adapter)
from mobilefinetuner_tpu.models import gpt2

CFG = GPT2Config.tiny()


@pytest.fixture(scope="module")
def setup():
    key = jax.random.PRNGKey(0)
    params = gpt2.init_params(CFG, key)
    spec = LoRASpec(rank=4, alpha=8.0,
                    targets=["attn_qkv", "attn_proj", "mlp_fc_in",
                             "mlp_fc_out"])
    lora = init_lora_gpt2(CFG, spec, jax.random.PRNGKey(1))
    ids = jnp.array(np.random.default_rng(0).integers(
        0, CFG.vocab_size, size=(2, 16)))
    return params, spec, lora, ids


def test_zero_init_lora_is_identity(setup):
    params, spec, lora, ids = setup
    base = gpt2.forward(CFG, params, ids)
    with_lora = gpt2.forward(CFG, params, ids, lora=lora)
    np.testing.assert_allclose(np.asarray(base), np.asarray(with_lora),
                               atol=1e-6)


def test_merge_matches_dynamic_lora(setup):
    params, spec, lora, ids = setup
    # make B nonzero so LoRA actually does something
    lora = jax.tree.map(lambda x: x, lora)
    key = jax.random.PRNGKey(7)
    for name, entry in lora["blocks"].items():
        key, sub = jax.random.split(key)
        entry["B"] = jax.random.normal(sub, entry["B"].shape) * 0.05
    dynamic = gpt2.forward(CFG, params, ids, lora=lora)
    merged = merge_gpt2(params, lora)
    static = gpt2.forward(CFG, merged, ids)
    np.testing.assert_allclose(np.asarray(dynamic), np.asarray(static),
                               atol=1e-4)
    # unmerge restores the base weights
    restored = unmerge_gpt2(merged, lora)
    for a, b in zip(jax.tree.leaves(params), jax.tree.leaves(restored)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-5)


def test_adapter_roundtrip(tmp_path, setup):
    params, spec, lora, ids = setup
    path = str(tmp_path / "adapter.safetensors")
    save_adapter(path, lora, spec)
    back, spec2 = load_adapter(path)
    assert spec2.rank == spec.rank and spec2.alpha == spec.alpha
    for name in lora["blocks"]:
        np.testing.assert_array_equal(
            np.asarray(lora["blocks"][name]["A"], dtype=np.float32),
            np.asarray(back["blocks"][name]["A"]))
        np.testing.assert_array_equal(
            np.asarray(lora["blocks"][name]["B"], dtype=np.float32),
            np.asarray(back["blocks"][name]["B"]))


def test_trainable_mask_excludes_scale(setup):
    _, spec, lora, _ = setup
    mask = trainable_mask(lora)
    flat = jax.tree_util.tree_flatten_with_path(mask)[0]
    for path, val in flat:
        is_scale = getattr(path[-1], "key", None) == "scale"
        assert val != is_scale
    n = num_trainable(lora)
    E, r, L = CFG.n_embd, spec.rank, CFG.n_layer
    expect = L * r * (E + 3 * E) + L * r * (E + E) + \
        L * r * (E + 4 * E) + L * r * (4 * E + E)
    assert n == expect


def test_peft_export_loads_in_hf_peft(tmp_path):
    """Export our adapter, attach it to the matching HF GPT-2 via real PEFT,
    and require logit parity with our dynamic-LoRA forward."""
    from transformers import GPT2Config as HFConfig, GPT2LMHeadModel
    from peft import PeftModel

    torch.manual_seed(0)
    hf_cfg = HFConfig(vocab_size=97, n_positions=32, n_embd=16, n_layer=2,
                      n_head=2, resid_pdrop=0.0, embd_pdrop=0.0,
                      attn_pdrop=0.0)
    model = GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(vocab_size=97, n_positions=32, n_embd=16, n_layer=2,
                     n_head=2)
    from mobilefinetuner_tpu.io.checkpoints import gpt2_params_from_hf
    sd = {k: v.numpy() for k, v in model.transformer.state_dict().items()
          if not k.endswith(".attn.bias")}
    params = gpt2_params_from_hf(sd, cfg)

    spec = LoRASpec(rank=4, alpha=8.0, targets=["attn_qkv", "attn_proj"])
    lora = init_lora_gpt2(cfg, spec, jax.random.PRNGKey(3))
    for entry in lora["blocks"].values():
        entry["B"] = jax.random.normal(jax.random.PRNGKey(4),
                                       entry["B"].shape) * 0.1

    out_dir = str(tmp_path / "peft_adapter")
    export_peft(out_dir, lora, spec, family="gpt2")

    peft_model = PeftModel.from_pretrained(model, out_dir).eval()
    ids = np.random.default_rng(5).integers(0, 97, size=(2, 12))
    with torch.no_grad():
        ref = peft_model(torch.tensor(ids)).logits.numpy()
    ours = np.asarray(gpt2.forward(cfg, params, jnp.array(ids), lora=lora))
    np.testing.assert_allclose(ours, ref, atol=3e-4, rtol=1e-3)

    # and the import path round-trips
    back, spec2 = import_peft(out_dir, family="gpt2")
    for name in lora["blocks"]:
        np.testing.assert_allclose(
            np.asarray(lora["blocks"][name]["A"]),
            np.asarray(back["blocks"][name]["A"]), atol=1e-6)


# ------------------- round 12: lora_impl=auto|naive|fused --------------------

from mobilefinetuner_tpu.models import gemma3
from mobilefinetuner_tpu.models.lora_apply import (impl_summary, maybe_lora,
                                                   multi_order_costs,
                                                   order_costs, pick_order,
                                                   resolve_lora_impl,
                                                   resolve_multi_order)

GEMMA_TINY = Gemma3TextConfig.tiny()


def _rand_lora(init_fn, config, targets, seed, rank=4):
    """Adapter with REAL (nonzero) B so the delta path does work."""
    spec = LoRASpec(rank=rank, alpha=2.0 * rank, targets=targets)
    lora = init_fn(config, spec, jax.random.PRNGKey(seed))
    leaves, td = jax.tree.flatten(lora)
    keys = jax.random.split(jax.random.PRNGKey(seed + 99), len(leaves))
    return jax.tree.unflatten(td, [
        l if l.ndim == 0 else 0.05 * jax.random.normal(k, l.shape)
        for l, k in zip(leaves, keys)])


_FAMILY_CACHE = {}


def _family(name):
    """Per-family setup + the naive-grad magnitude scale, cached at
    module scope (re-init per matrix case would redo first-call jits)."""
    if name in _FAMILY_CACHE:
        return _FAMILY_CACHE[name]
    if name == "gpt2":
        params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
        lora = _rand_lora(init_lora_gpt2, CFG,
                          ["attn_qkv", "attn_proj", "mlp_fc_in",
                           "mlp_fc_out", "lm_head"], seed=3)
        ids = jnp.asarray(np.random.default_rng(1).integers(
            0, CFG.vocab_size, (2, 16)))
        fwd = lambda lo, **kw: gpt2.forward(CFG, params, ids, lora=lo,
                                            **kw)
    else:
        params = gemma3.init_params(GEMMA_TINY, jax.random.PRNGKey(0))
        lora = _rand_lora(init_lora_gemma3, GEMMA_TINY,
                          ["q_proj", "o_proj", "gate_proj", "down_proj",
                           "lm_head"], seed=4)
        ids = jnp.asarray(np.random.default_rng(2).integers(
            0, GEMMA_TINY.vocab_size, (2, 16)))
        fwd = lambda lo, **kw: gemma3.forward(GEMMA_TINY, params, ids,
                                              lora=lo, **kw)
    out0 = fwd(lora).astype(jnp.float32)
    ct = jax.random.normal(jax.random.PRNGKey(5), out0.shape)
    # one reference naive value+grad (f32, no dropout) fixes the scale
    # every matrix case's tolerances are relative to
    vn, gn = jax.value_and_grad(
        lambda lo: jnp.vdot(fwd(lo, lora_impl="naive")
                            .astype(jnp.float32), ct))(lora)
    gmax = max(float(jnp.abs(x).max()) for x in jax.tree.leaves(gn))
    _FAMILY_CACHE[name] = (lora, fwd, ct, abs(float(vn)), gmax)
    return _FAMILY_CACHE[name]


def _parity_case(family, dtype, tol, dropout):
    """ONE vjp through the DIFFERENCE naive(lora) - fused(lora): same
    dropout rng => identical masks, so the difference isolates the
    compute-graph change, and its value AND cotangents must vanish to
    tolerance (relative to the cached naive reference magnitudes)."""
    lora, fwd, ct, vscale, gmax = _family(family)
    drng = jax.random.PRNGKey(7) if dropout else None

    def run(lo, impl):
        out = fwd(lo, compute_dtype=dtype, lora_dropout=dropout,
                  dropout_rng=drng, lora_impl=impl).astype(jnp.float32)
        return jnp.vdot(out, ct)

    vd, gd = jax.value_and_grad(
        lambda lo: run(lo, "naive") - run(lo, "fused"))(lora)
    assert abs(float(vd)) <= tol * max(vscale, 1.0), float(vd)
    for leaf in jax.tree.leaves(gd):
        assert float(jnp.abs(leaf).max()) <= tol * max(gmax, 1.0)


@pytest.mark.parametrize("family", ["gpt2", "gemma"])
def test_lora_impl_parity_smoke(family):
    """Tier-1 slice of the matrix: fused == naive in value AND grads
    through the real model, both families, f32 (the full dtype×dropout
    matrix runs as test_lora_impl_parity_matrix, marked slow — CPU
    tier-1 carries a 870 s budget)."""
    _parity_case(family, jnp.float32, 1e-5, 0.0)


@pytest.mark.slow
@pytest.mark.parametrize("family", ["gpt2", "gemma"])
@pytest.mark.parametrize("dtype,tol", [(jnp.float32, 1e-5),
                                       (jnp.bfloat16, 2e-2)])
@pytest.mark.parametrize("dropout", [0.0, 0.1])
def test_lora_impl_parity_matrix(family, dtype, tol, dropout):
    """The full acceptance matrix: fused == naive in value AND grads,
    both families, fp32/bf16, dropout on/off — incl. the unstacked
    lm_head site (see _parity_case)."""
    _parity_case(family, dtype, tol, dropout)


@pytest.mark.parametrize("k", [1, 4])
def test_multi_adapter_impl_parity(k):
    """Stacked-[k,...] ids-routed path: the fused order (gather or
    dense, cost-model picked) matches the naive per-row gather in value
    and grads (one vjp through the difference, same discipline as the
    matrix above)."""
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    adapters = [_rand_lora(init_lora_gpt2, CFG,
                           ["attn_qkv", "attn_proj"], seed=10 + i)
                for i in range(k)]
    stacked = stack_adapters(adapters)
    ids = jnp.asarray(np.random.default_rng(3).integers(
        0, CFG.vocab_size, (4, 8)))
    row_ids = [i % k for i in range(4)]

    def run(st, impl):
        lo = assign_adapters(st, row_ids)
        out = gpt2.forward(CFG, params, ids, lora=lo,
                           lora_impl=impl).astype(jnp.float32)
        return jnp.sum(out * out) / out.size

    vn, gn = jax.value_and_grad(lambda st: run(st, "naive"))(stacked)
    vd, gd = jax.value_and_grad(
        lambda st: run(st, "naive") - run(st, "fused"))(stacked)
    gmax = max(float(jnp.abs(x).max()) for x in jax.tree.leaves(gn))
    assert abs(float(vd)) <= 1e-5 * max(abs(float(vn)), 1.0)
    for leaf in jax.tree.leaves(gd):
        assert float(jnp.abs(leaf).max()) <= 1e-5 * max(gmax, 1.0)


def test_naive_fp32_accum_r8_s2048():
    """Satellite: the naive path must carry preferred_element_type=f32
    on BOTH adapter matmuls (the old per-call bf16-accumulate chain is
    the regression this pins, structurally — CPU may emulate bf16
    matmuls in f32, so a purely numeric check could pass vacuously) and
    land near the f32 oracle at the r=8, S=2048 shape."""
    rng = np.random.default_rng(0)
    x32 = rng.normal(size=(1, 2048, 256)).astype(np.float32)
    A32 = (rng.normal(size=(256, 8)) * 0.1).astype(np.float32)
    B32 = (rng.normal(size=(8, 256)) * 0.1).astype(np.float32)
    entry16 = {"A": jnp.asarray(A32, jnp.bfloat16),
               "B": jnp.asarray(B32, jnp.bfloat16),
               "scale": jnp.float32(2.0)}

    def f(x):
        return maybe_lora(jnp.zeros(x.shape, jnp.bfloat16), x, entry16,
                          impl="naive")

    # migrated r19: the hand-rolled jaxpr grep is now the shared
    # structural-pin API (core/static_checks.assert_dots_accumulate_f32,
    # sub-jaxprs included) — the same helper graftlint's runtime half
    # leans on
    from mobilefinetuner_tpu.core.static_checks import (
        assert_dots_accumulate_f32)
    assert_dots_accumulate_f32(f, jnp.asarray(x32, jnp.bfloat16),
                               min_dots=2)
    # numeric sanity vs the exact f32 oracle
    got = np.asarray(f(jnp.asarray(x32, jnp.bfloat16)), np.float32)
    want = 2.0 * (x32 @ A32) @ B32
    err = np.abs(got - want).max() / np.abs(want).max()
    assert err < 2e-2, err  # bf16 INPUT rounding only, not accumulation


def test_pick_order_asserts_merged_never_wins():
    """The cost model picks (x@A)@B at every LoRA-rank shape; a rank
    above the harmonic mean of the dims trips the assertion instead of
    silently materializing [d_in, d_out]."""
    for n_tok, d_in, d_out, r in ((8, 640, 640, 8), (4096, 768, 2304, 8),
                                  (2048, 640, 262144, 64), (8, 64, 64, 16)):
        assert pick_order(n_tok, d_in, d_out, r) == "xA_B"
        c = order_costs(n_tok, d_in, d_out, r)
        assert c["xA_B"] < c["x_AB"]
    with pytest.raises(AssertionError, match="merge the adapter"):
        pick_order(16, 8, 8, 64)  # r >> harmonic mean of dims


def test_resolve_lora_impl_gates():
    """`auto` never selects an ineligible fused site: off-TPU always
    naive; on TPU fused only when the epilogue is shape-eligible AND the
    delta is memory-bound."""
    # big aligned site on TPU -> fused
    assert resolve_lora_impl(4096, 640, 640, 8, 2,
                             backend="tpu") == "fused"
    # off-TPU -> naive regardless
    assert resolve_lora_impl(4096, 640, 640, 8, 2,
                             backend="cpu") == "naive"
    # misaligned d_out -> ineligible -> naive
    assert resolve_lora_impl(4096, 640, 100, 8, 2,
                             backend="tpu") == "naive"
    # tiny delta (decode: one token per slot) -> naive
    assert resolve_lora_impl(8, 640, 640, 8, 2, backend="tpu") == "naive"
    s = impl_summary({"q_proj": (640, 640), "o_proj": (640, 100)},
                     4096, 8, "auto", 2, backend="tpu")
    assert s == "o_proj=naive,q_proj=fused"
    assert impl_summary({"q_proj": (640, 640)}, 4096, 8, "naive",
                        2) == "q_proj=naive"


def test_resolve_multi_order_decode_vs_train():
    """Dense all-k routing wins only where the per-row factor gather
    dominates (tiny n_tok, small k); the train shapes keep gather."""
    # train shape: huge n_tok -> gather
    assert resolve_multi_order(16, 16 * 2048, 640, 640, 8, 8, 2) == \
        "gather"
    # decode shape, k=2 resident adapters -> dense beats the gather
    c = multi_order_costs(8, 8, 640, 640, 8, 2, 2)
    assert resolve_multi_order(8, 8, 640, 640, 8, 2, 2) == \
        ("dense" if c["dense"] < c["gather"] else "gather")
    assert c["dense"] < c["gather"]


def test_multi_lora_auto_stays_gather_off_tpu():
    """The module contract: off-TPU `auto` is always naive — on the
    ids-routed path too. At a decode shape where the cost model picks
    dense, auto on this CPU backend must still emit the gather graph
    (== naive), while an explicit `fused` exercises the dense order."""
    assert jax.default_backend() != "tpu"
    k, d, r, rows = 2, 640, 8, 8
    key = jax.random.PRNGKey(0)
    entry = {"A": jax.random.normal(key, (k, d, r)),
             "B": jax.random.normal(key, (k, r, d)),
             "scale": jnp.ones((k,)), "ids": jnp.zeros((rows,), jnp.int32)}
    y = jnp.zeros((rows, 1, d))
    x = jnp.ones((rows, 1, d))
    jp = {impl: str(jax.make_jaxpr(
        lambda yy, xx: maybe_lora(yy, xx, entry, impl=impl))(y, x))
        for impl in ("auto", "naive", "fused")}
    assert resolve_multi_order(rows, rows, d, d, r, k, 4) == "dense"
    assert jp["auto"] == jp["naive"]
    assert jp["fused"] != jp["naive"]


def test_stack_adapters_names_index_path_and_shapes():
    """Satellite: a mismatched adapter names the offending index, leaf
    path, and BOTH shapes."""
    a0 = init_lora_gpt2(CFG, LoRASpec(rank=4, targets=["attn_proj"]),
                        jax.random.PRNGKey(0))
    a_rank = init_lora_gpt2(CFG, LoRASpec(rank=8, targets=["attn_proj"]),
                            jax.random.PRNGKey(1))
    with pytest.raises(ValueError) as ei:
        stack_adapters([a0, a0, a_rank])
    msg = str(ei.value)
    assert "adapter 2" in msg and "attn_proj" in msg and "A" in msg
    assert str((CFG.n_layer, CFG.n_embd, 4)) in msg
    assert str((CFG.n_layer, CFG.n_embd, 8)) in msg
    # different target sets -> structure error naming both sets
    a_tgt = init_lora_gpt2(CFG, LoRASpec(rank=4, targets=["attn_qkv"]),
                           jax.random.PRNGKey(2))
    with pytest.raises(ValueError, match="attn_qkv"):
        stack_adapters([a0, a_tgt])


def test_lm_head_target_unstacked_and_merge_refused():
    """lm_head is a single unstacked site: A [E, r], B [r, V]; applying
    it changes logits; merging is refused (tied embedding)."""
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    lora = _rand_lora(init_lora_gpt2, CFG, ["lm_head"], seed=6)
    e = lora["blocks"]["lm_head"]
    assert e["A"].shape == (CFG.n_embd, 4)
    assert e["B"].shape == (4, CFG.vocab_size)
    ids = jnp.asarray(np.random.default_rng(4).integers(
        0, CFG.vocab_size, (2, 8)))
    base = gpt2.forward(CFG, params, ids)
    with_head = gpt2.forward(CFG, params, ids, lora=lora)
    assert float(jnp.abs(with_head - base).max()) > 1e-4
    with pytest.raises(ValueError, match="lm_head"):
        merge_gpt2(params, lora)


def test_serve_hot_swap_zero_retrace_under_fused():
    """Satellite: lora_impl=fused threads through the serve engine as a
    STATIC config — adapter hot-swaps stay data, zero new traces after
    warmup (the r11 compile-stability invariant, now under the fused
    path)."""
    from mobilefinetuner_tpu.serve import AdapterBank, ServeConfig, \
        ServeEngine
    cfg = dataclasses.replace(CFG, n_positions=64)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    mk = lambda s: _rand_lora(init_lora_gpt2, cfg,
                              ["attn_qkv", "attn_proj"], seed=s)
    bank = AdapterBank(mk(1), capacity=2)
    eng = ServeEngine(
        "gpt2", cfg, params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=6, lora_impl="fused"),
        bank=bank)
    try:
        eng.load_adapter("t1", mk(2))
        rng = np.random.default_rng(0)
        eng.submit(list(rng.integers(1, 250, 5)), max_new_tokens=4,
                   adapter="t1")
        eng.submit(list(rng.integers(1, 250, 9)), max_new_tokens=4)
        eng.drain()
        warm = eng.total_traces()
        # hot-swap: evict + load a new tenant, serve through it
        eng.evict_adapter("t1")
        eng.load_adapter("t2", mk(3))
        eng.submit(list(rng.integers(1, 250, 7)), max_new_tokens=4,
                   adapter="t2")
        eng.submit(list(rng.integers(1, 250, 3)), max_new_tokens=4)
        eng.drain()
        assert eng.total_traces() - warm == 0
    finally:
        eng.close()
