"""scripts/ recipe guard: every --flag a shell script passes must exist on
the CLI it invokes (catches parser/script drift without running the
expensive recipes; the scripts themselves are smoke-run against tiny
fixtures during verification, not in CI)."""

import glob
import os
import re
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# script -> (CLI module(s) it drives, extra flags consumed by tools/)
CLI_OF = {
    "run_gpt2s_lora.sh": (["gpt2_lora_finetune", "eval_ppl"], set()),
    "run_gpt2s_full.sh": (["gpt2_full_finetune"], set()),
    "run_gpt2m_lora.sh": (["gpt2_lora_finetune"], set()),
    "run_gemma270m_lora.sh": (["train_lora_gemma", "eval_ppl"], set()),
    "run_gemma1b_lora_offload.sh": (["train_lora_gemma"], set()),
    "run_gemma1b_lora.sh": (["train_lora_gemma"], set()),
    # --dump_dir belongs to tools/align_torch_mirror.py
    "run_alignment_gpt2.sh": (["gpt2_lora_finetune"], {"--dump_dir"}),
    "energy_benchmark.sh": (["gpt2_lora_finetune"], set()),
    "run_gemma270m_full.sh": (["gemma_full_finetune"], set()),
    "run_pod_v5e64.sh": (["gpt2_full_finetune"], set()),
}


def test_every_cli_script_is_guarded():
    """Completeness: any scripts/*/*.sh that invokes a cli module must be
    registered in CLI_OF, or it silently escapes the flag-drift guard."""
    missing = []
    for sh in glob.glob(os.path.join(REPO, "scripts", "**", "*.sh"),
                        recursive=True):
        name = os.path.basename(sh)
        if "mobilefinetuner_tpu.cli." in open(sh).read() \
                and name not in CLI_OF:
            missing.append(name)
    assert not missing, f"scripts not registered in CLI_OF: {missing}"


def parser_flags(cli_name):
    import importlib
    mod = importlib.import_module(f"mobilefinetuner_tpu.cli.{cli_name}")
    p = mod.build_parser()
    flags = set()
    for a in p._actions:
        flags.update(a.option_strings)
    return flags


def script_flags(path):
    src = open(path).read()
    # strip full-line AND trailing comments; collect --words used as flags
    # (flags may contain digits/hyphens — match the full token)
    lines = []
    for ln in src.splitlines():
        if ln.lstrip().startswith("#"):
            continue
        lines.append(re.sub(r"\s#.*$", "", ln))
    return set(re.findall(r"(?<![\w-])(--[a-z0-9_-]+)", "\n".join(lines)))


@pytest.mark.parametrize("script", sorted(CLI_OF))
def test_script_flags_exist(script):
    paths = glob.glob(os.path.join(REPO, "scripts", "*", script))
    assert paths, f"{script} missing"
    used = script_flags(paths[0])
    clis, extra = CLI_OF[script]
    known = set(extra)
    for cli in clis:
        known |= parser_flags(cli)
    unknown = used - known
    assert not unknown, (f"{script} passes flags no target CLI accepts: "
                         f"{sorted(unknown)}")


def test_all_scripts_bash_parse():
    for sh in glob.glob(os.path.join(REPO, "scripts", "*", "*.sh")):
        subprocess.run(["bash", "-n", sh], check=True)


def test_multihost_smoke_shards_merge_through_fleet_report(tmp_path):
    """Fleet-observability recipe guard (DESIGN.md §14): the smoke tool's
    simulated two-host shard writer produces exactly the per-host layout
    (base + base.host1) that tools/fleet_report.py discovers and merges —
    per-host percentiles and the baked-in 3x skew attributed to host 1 —
    all as real subprocess invocations, like an operator would run."""
    import sys
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    base = str(tmp_path / "pod.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "multihost_smoke.py"),
         "--write_shards", base],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "SHARDS_OK" in r.stdout
    assert os.path.exists(base) and os.path.exists(base + ".host1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         base, "--json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    import json
    s = json.loads(r.stdout)
    assert s["hosts"] == 2
    assert s["per_host"]["0"]["seq_monotonic"] \
        and s["per_host"]["1"]["seq_monotonic"]
    assert s["per_host"]["1"]["step_time_ms"]["p50"] \
        > 2.5 * s["per_host"]["0"]["step_time_ms"]["p50"]
    assert s["skew"]["slowest_host"] == 1
    assert s["stragglers"] and s["stragglers"][0]["slow_host"] == 1
    # the human rendering names the straggler too
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         base], capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "STRAGGLER" in r.stdout and "skew" in r.stdout


def test_plot_loss_runs_on_metrics_csv(tmp_path):
    import sys
    p = tmp_path / "m.csv"
    p.write_text(
        "timestamp,epoch,step,loss,avg_loss,lr,step_time_ms,hbm_mb\n"
        "1,0,1,2.5,2.5,0.001,10,100\n"
        "1,0,2,2.4,2.45,0.001,10,100\n"
        "1,0,3\n")  # truncated tail row must be tolerated
    out = tmp_path / "c.png"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "plot_loss.py"),
         str(p), "--out", str(out)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert out.exists()
