"""scripts/ recipe guard: every --flag a shell script passes must exist on
the CLI it invokes (catches parser/script drift without running the
expensive recipes; the scripts themselves are smoke-run against tiny
fixtures during verification, not in CI)."""

import glob
import os
import re
import subprocess

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# script -> (CLI module(s) it drives, extra flags consumed by tools/)
CLI_OF = {
    "run_gpt2s_lora.sh": (["gpt2_lora_finetune", "eval_ppl"], set()),
    "run_gpt2s_full.sh": (["gpt2_full_finetune"], set()),
    "run_gpt2m_lora.sh": (["gpt2_lora_finetune"], set()),
    "run_gemma270m_lora.sh": (["train_lora_gemma", "eval_ppl"], set()),
    "run_gemma1b_lora_offload.sh": (["train_lora_gemma"], set()),
    "run_gemma1b_lora.sh": (["train_lora_gemma"], set()),
    # --dump_dir belongs to tools/align_torch_mirror.py
    "run_alignment_gpt2.sh": (["gpt2_lora_finetune"], {"--dump_dir"}),
    "energy_benchmark.sh": (["gpt2_lora_finetune"], set()),
    "run_gemma270m_full.sh": (["gemma_full_finetune"], set()),
    "run_pod_v5e64.sh": (["gpt2_full_finetune"], set()),
}


def test_every_cli_script_is_guarded():
    """Completeness: any scripts/*/*.sh that invokes a cli module must be
    registered in CLI_OF, or it silently escapes the flag-drift guard."""
    missing = []
    for sh in glob.glob(os.path.join(REPO, "scripts", "**", "*.sh"),
                        recursive=True):
        name = os.path.basename(sh)
        if "mobilefinetuner_tpu.cli." in open(sh).read() \
                and name not in CLI_OF:
            missing.append(name)
    assert not missing, f"scripts not registered in CLI_OF: {missing}"


def parser_flags(cli_name):
    import importlib
    mod = importlib.import_module(f"mobilefinetuner_tpu.cli.{cli_name}")
    p = mod.build_parser()
    flags = set()
    for a in p._actions:
        flags.update(a.option_strings)
    return flags


def script_flags(path):
    src = open(path).read()
    # strip full-line AND trailing comments; collect --words used as flags
    # (flags may contain digits/hyphens — match the full token)
    lines = []
    for ln in src.splitlines():
        if ln.lstrip().startswith("#"):
            continue
        lines.append(re.sub(r"\s#.*$", "", ln))
    return set(re.findall(r"(?<![\w-])(--[a-z0-9_-]+)", "\n".join(lines)))


@pytest.mark.parametrize("script", sorted(CLI_OF))
def test_script_flags_exist(script):
    paths = glob.glob(os.path.join(REPO, "scripts", "*", script))
    assert paths, f"{script} missing"
    used = script_flags(paths[0])
    clis, extra = CLI_OF[script]
    known = set(extra)
    for cli in clis:
        known |= parser_flags(cli)
    unknown = used - known
    assert not unknown, (f"{script} passes flags no target CLI accepts: "
                         f"{sorted(unknown)}")


def test_all_scripts_bash_parse():
    for sh in glob.glob(os.path.join(REPO, "scripts", "*", "*.sh")):
        subprocess.run(["bash", "-n", sh], check=True)


def test_multihost_smoke_shards_merge_through_fleet_report(tmp_path):
    """Fleet-observability recipe guard (DESIGN.md §14): the smoke tool's
    simulated two-host shard writer produces exactly the per-host layout
    (base + base.host1) that tools/fleet_report.py discovers and merges —
    per-host percentiles and the baked-in 3x skew attributed to host 1 —
    all as real subprocess invocations, like an operator would run."""
    import sys
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    base = str(tmp_path / "pod.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "multihost_smoke.py"),
         "--write_shards", base],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "SHARDS_OK" in r.stdout
    assert os.path.exists(base) and os.path.exists(base + ".host1")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         base, "--json"],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    import json
    s = json.loads(r.stdout)
    assert s["hosts"] == 2
    assert s["per_host"]["0"]["seq_monotonic"] \
        and s["per_host"]["1"]["seq_monotonic"]
    assert s["per_host"]["1"]["step_time_ms"]["p50"] \
        > 2.5 * s["per_host"]["0"]["step_time_ms"]["p50"]
    assert s["skew"]["slowest_host"] == 1
    assert s["stragglers"] and s["stragglers"][0]["slow_host"] == 1
    # the human rendering names the straggler too
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "fleet_report.py"),
         base], capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "STRAGGLER" in r.stdout and "skew" in r.stdout


def test_trace_export_converts_two_host_fleet_fixture(tmp_path):
    """Round-17 recipe guard: the simulated two-host shard set (the
    same fixture the fleet_report test merges) converts to ONE
    Perfetto-loadable trace-event file with a process row per host —
    real subprocess invocations, like an operator would run."""
    import json
    import sys
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    base = str(tmp_path / "pod.jsonl")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "multihost_smoke.py"),
         "--write_shards", base],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    out = str(tmp_path / "pod.trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         base, "-o", out],
        capture_output=True, text=True, cwd=REPO, env=env)
    assert r.returncode == 0, r.stderr
    assert "perfetto" in r.stdout
    trace = json.load(open(out))
    evs = trace["traceEvents"]
    assert evs
    pids = {e["pid"] for e in evs}
    assert pids == {0, 1}  # one process row per host
    proc_names = {e["args"]["name"] for e in evs
                  if e.get("ph") == "M" and e["name"] == "process_name"}
    assert proc_names == {"host 0 (coordinator)", "host 1"}
    for e in evs:
        assert e["ph"] in ("X", "i", "C", "M"), e


def test_serve_bench_router_smoke_and_trace_export_reconciles(tmp_path):
    """Round-22 recipe guard (DESIGN.md §27): `serve_bench --router 2`
    drives open-loop load through the real router + two tiny CPU
    replicas and lands fleet + per-replica rows; `trace_export --router`
    then merges the four streams into ONE timeline — router process row
    plus a row per replica — and the span-placement reconciliation gate
    (<1% of wall) passes. Real subprocess invocations, like an operator
    would run."""
    import json
    import sys
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    base = str(tmp_path / "fleet.jsonl")
    rows_out = str(tmp_path / "rows.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "serve_bench.py"),
         "--model", "tiny-gpt2", "--router", "2", "--rate", "8",
         "--requests", "10", "--adapters", "2", "--max_new", "8",
         "--max_prompt", "32", "--num_slots", "4", "--num_blocks", "64",
         "--dtype", "float32", "--telemetry_out", base,
         "--out", rows_out],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=420)
    assert r.returncode == 0, (r.stdout[-2000:], r.stderr[-2000:])
    rows = json.load(open(rows_out))["rows"]
    fleet = [x for x in rows if x.get("replicas") == 2]
    assert len(fleet) == 1 and fleet[0]["requests"] == 10
    assert fleet[0]["terminal"]["finished"] >= 5
    assert sum(fleet[0]["routing"].values()) >= 10  # every decision logged
    per_replica = [x for x in rows if "replica" in x]
    assert {x["replica"] for x in per_replica} == {1, 2}
    for k in (1, 2):  # replica shards really landed next to the base
        assert os.path.exists(f"{base}.host{k}")
    out = str(tmp_path / "fleet.trace.json")
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace_export.py"),
         base, "--router", "-o", out],
        capture_output=True, text=True, cwd=REPO, env=env, timeout=120)
    assert r.returncode == 0, (r.stdout, r.stderr)
    assert "router reconciliation" in r.stdout
    trace = json.load(open(out))
    proc_names = {e["args"]["name"] for e in trace["traceEvents"]
                  if e.get("ph") == "M" and e["name"] == "process_name"}
    assert proc_names == {"router", "replica 1", "replica 2"}
    assert any(e.get("ph") == "i" and e["name"].startswith("route:rid")
               for e in trace["traceEvents"])


def test_bench_compare_cli_gates_on_regression(tmp_path):
    """Round-17 recipe guard: bench_compare diffs two artifacts as a
    subprocess and exits nonzero past --threshold (the CI contract)."""
    import json
    import sys
    old = tmp_path / "BENCH_old.json"
    new = tmp_path / "BENCH_new.json"
    old.write_text(json.dumps({"rows": [
        {"config": "gpt2s_lora", "tokens_per_sec_per_chip": 100.0,
         "peak_hbm_mb": 500.0}]}))
    new.write_text(json.dumps({"rows": [
        {"config": "gpt2s_lora", "tokens_per_sec_per_chip": 60.0,
         "peak_hbm_mb": 480.0}]}))
    cmd = [sys.executable, os.path.join(REPO, "tools", "bench_compare.py"),
           str(old), str(new)]
    r = subprocess.run(cmd + ["--threshold", "5"],
                       capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 2, (r.stdout, r.stderr)
    assert "REGRESSED" in r.stdout
    # improvement-only diff passes the same gate
    r = subprocess.run(
        [cmd[0], cmd[1], str(new), str(old), "--threshold", "5"],
        capture_output=True, text=True, cwd=REPO)
    assert r.returncode == 0, (r.stdout, r.stderr)
    # --json is machine-readable
    r = subprocess.run(cmd + ["--json"], capture_output=True, text=True,
                       cwd=REPO)
    assert r.returncode == 0
    c = json.loads(r.stdout)
    assert c["shared_rows"] == ["gpt2s_lora"]
    assert not c["regressions"]  # no threshold -> report only


def test_report_tools_format_json_matches_legacy_alias(tmp_path):
    """Round-17 satellite: --format json on BOTH report tools goes
    through one shared serializer; the legacy --json alias emits the
    identical document."""
    import json
    import sys
    from mobilefinetuner_tpu.core.telemetry import Telemetry
    base = str(tmp_path / "run.jsonl")
    with Telemetry(base) as tel:
        tel.emit("run_start", jax_version="x", mesh_shape=None,
                 process_count=1, process_index=0, device_kind="cpu",
                 device_count=1, config={})
        tel.emit("step_stats", step=1, loss=3.0, ema=3.0, lr=1e-4,
                 grad_norm=0.5, step_time_ms=10.0, host_wait_ms=0.1,
                 slept_ms=0.0, tok_s=100.0, mfu=None, param_norm=1.0,
                 update_ratio=1e-3, nonfinite_count=0, skipped=0,
                 hbm_mb=None, queue_depth=0, host_step_ms=None)
        tel.emit("run_end", steps=1, wall_s=0.1, exit="ok",
                 goodput=None)
    env = dict(os.environ)
    env["PYTHONPATH"] = REPO + os.pathsep + env.get("PYTHONPATH", "")
    for tool in ("telemetry_report.py", "fleet_report.py"):
        path = os.path.join(REPO, "tools", tool)
        outs = {}
        for flag in (["--format", "json"], ["--json"]):
            r = subprocess.run([sys.executable, path, base] + flag,
                               capture_output=True, text=True, cwd=REPO,
                               env=env)
            assert r.returncode == 0, (tool, flag, r.stderr)
            outs[tuple(flag)] = json.loads(r.stdout)
        assert outs[("--format", "json")] == outs[("--json",)], tool


def test_plot_loss_runs_on_metrics_csv(tmp_path):
    import sys
    p = tmp_path / "m.csv"
    p.write_text(
        "timestamp,epoch,step,loss,avg_loss,lr,step_time_ms,hbm_mb\n"
        "1,0,1,2.5,2.5,0.001,10,100\n"
        "1,0,2,2.4,2.45,0.001,10,100\n"
        "1,0,3\n")  # truncated tail row must be tolerated
    out = tmp_path / "c.png"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "plot_loss.py"),
         str(p), "--out", str(out)], capture_output=True, text=True)
    assert r.returncode == 0, r.stderr
    assert out.exists()
