"""Native C++ safetensors engine parity tests (native/fast_safetensors).

The pure-Python implementation in io/safetensors_io.py is the behavioral
reference (itself HF-oracle-tested in tests/test_* I/O suites); the native
mmap reader and streamed writer must be indistinguishable from it:
identical entries/metadata/arrays both ways, including BF16, escapes in
names/metadata, zero-size tensors, and malformed-file rejection.
Skips cleanly when the toolchain can't build the library.
"""

import json
import os
import struct

import numpy as np
import pytest

from mobilefinetuner_tpu.io import safetensors_io as st
from mobilefinetuner_tpu.native import fast_safetensors as nst


def native_available():
    return (os.environ.get("MFT_NO_NATIVE_ST") != "1"
            and nst.load_library() is not None)


pytestmark = pytest.mark.skipif(not native_available(),
                                reason="native safetensors lib unavailable")


def sample_tensors():
    rng = np.random.default_rng(0)
    return {
        "wte": rng.normal(size=(17, 8)).astype(np.float32),
        "blocks.0.qkv_w": rng.normal(size=(8, 24)).astype(np.float32),
        "ids": rng.integers(-5, 5, (3, 2)).astype(np.int64),
        "flags": np.array([True, False, True]),
        "half": rng.normal(size=(4,)).astype(np.float16),
        "empty": np.zeros((0, 4), np.float32),
        "weird \"name\"\t\\x": rng.normal(size=(2,)).astype(np.float32),
    }


def python_write(path, tensors, metadata=None, bf16_keys=None):
    os.environ["MFT_NO_NATIVE_ST"] = "1"
    try:
        st.save_safetensors(path, tensors, metadata, bf16_keys)
    finally:
        del os.environ["MFT_NO_NATIVE_ST"]


def python_read_all(path):
    os.environ["MFT_NO_NATIVE_ST"] = "1"
    try:
        r = st.SafeTensorsReader(path)
        return r.entries, r.metadata, r.load_all()
    finally:
        del os.environ["MFT_NO_NATIVE_ST"]


META = {"format": "pt", "lora_rank": "8", "esc\"key": "va\\lue\n2"}


def test_native_reader_matches_python_reader(tmp_path):
    p = str(tmp_path / "t.safetensors")
    python_write(p, sample_tensors(), META, bf16_keys={"wte"})
    entries_py, meta_py, arrays_py = python_read_all(p)
    r = st.SafeTensorsReader(p)
    assert r._native is not None, "native backend not engaged"
    assert r.metadata == meta_py
    assert list(r.entries.keys()) == list(entries_py.keys())
    for k in entries_py:
        assert r.entries[k]["dtype"] == entries_py[k]["dtype"]
        assert list(r.entries[k]["shape"]) == list(entries_py[k]["shape"])
        a, b = r.load(k), arrays_py[k]
        assert a.dtype == b.dtype and a.shape == b.shape
        np.testing.assert_array_equal(a, b)


def test_native_writer_matches_python_writer(tmp_path):
    tensors = sample_tensors()
    p_nat = str(tmp_path / "nat.safetensors")
    p_py = str(tmp_path / "py.safetensors")
    st.save_safetensors(p_nat, tensors, META, bf16_keys={"wte"})
    python_write(p_py, tensors, META, bf16_keys={"wte"})
    _, meta_a, arrays_a = python_read_all(p_nat)
    _, meta_b, arrays_b = python_read_all(p_py)
    assert meta_a == meta_b
    assert list(arrays_a.keys()) == list(arrays_b.keys())
    for k in arrays_a:
        np.testing.assert_array_equal(arrays_a[k], arrays_b[k])


def test_native_writer_output_loads_in_hf_safetensors(tmp_path):
    """Oracle check: the native writer's file must parse in the official
    safetensors package (HF interchange is the whole point)."""
    safetensors = pytest.importorskip("safetensors.numpy")
    tensors = {k: v for k, v in sample_tensors().items()
               if "\"" not in k}  # HF forbids nothing, but keep it plain
    p = str(tmp_path / "hf.safetensors")
    st.save_safetensors(p, tensors, {"format": "pt"})
    loaded = safetensors.load_file(p)
    for k, v in tensors.items():
        np.testing.assert_array_equal(loaded[k], v)


def test_native_reader_reads_hf_safetensors(tmp_path):
    safetensors = pytest.importorskip("safetensors.numpy")
    rng = np.random.default_rng(3)
    tensors = {"a": rng.normal(size=(5, 3)).astype(np.float32),
               "b": rng.integers(0, 9, (4,)).astype(np.int32)}
    p = str(tmp_path / "hf_in.safetensors")
    safetensors.save_file(tensors, p, metadata={"src": "hf"})
    r = st.SafeTensorsReader(p)
    assert r._native is not None
    assert r.metadata == {"src": "hf"}
    for k, v in tensors.items():
        np.testing.assert_array_equal(r.load(k), v)


def test_unicode_escape_in_header(tmp_path):
    """\\u-escaped names (incl. a surrogate pair) must decode to the same
    UTF-8 the Python json module produces."""
    name = "emb/é€\U0001F600"
    arr = np.arange(4, dtype=np.float32)
    header = {name: {"dtype": "F32", "shape": [4],
                     "data_offsets": [0, 16]}}
    hjson = json.dumps(header).encode()  # ensure_ascii=True -> \u escapes
    assert b"\\u" in hjson
    hjson += b" " * (-len(hjson) % 8)
    p = str(tmp_path / "esc.safetensors")
    with open(p, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        f.write(arr.tobytes())
    r = st.SafeTensorsReader(p)
    assert r._native is not None
    assert list(r.entries.keys()) == [name]
    np.testing.assert_array_equal(r.load(name), arr)


@pytest.mark.parametrize("corrupt", ["short", "bad_json", "bad_offsets",
                                     "huge_header"])
def test_malformed_files_rejected(tmp_path, corrupt):
    p = str(tmp_path / "bad.safetensors")
    if corrupt == "short":
        data = b"\x01\x02"
    elif corrupt == "bad_json":
        h = b'{"a": [broken'
        data = struct.pack("<Q", len(h)) + h
    elif corrupt == "bad_offsets":
        h = json.dumps({"a": {"dtype": "F32", "shape": [4],
                              "data_offsets": [0, 999]}}).encode()
        data = struct.pack("<Q", len(h)) + h + b"\x00" * 16
    else:  # huge_header
        data = struct.pack("<Q", 1 << 40) + b"{}"
    with open(p, "wb") as f:
        f.write(data)
    with pytest.raises((ValueError, Exception)):
        st.SafeTensorsReader(p)


def test_nul_bytes_in_names_and_metadata_roundtrip(tmp_path):
    """JSON strings may contain \\u0000; both backends must round-trip
    them identically (the FFI is length-aware, not NUL-terminated)."""
    name = "a\x00b"
    meta = {"note": "x\x00y"}
    arr = np.arange(3, dtype=np.float32)
    p_py = str(tmp_path / "py.safetensors")
    p_nat = str(tmp_path / "nat.safetensors")
    python_write(p_py, {name: arr}, meta)
    st.save_safetensors(p_nat, {name: arr}, meta)  # native writer
    for p in (p_py, p_nat):
        r = st.SafeTensorsReader(p)           # native reader
        assert r._native is not None
        assert list(r.entries.keys()) == [name]
        assert r.metadata == meta
        np.testing.assert_array_equal(r.load(name), arr)


def test_missing_file_raises_filenotfound(tmp_path):
    """Exception-type parity with the Python backend: a missing path must
    raise FileNotFoundError regardless of which backend is active."""
    missing = str(tmp_path / "nope.safetensors")
    with pytest.raises(FileNotFoundError):
        st.SafeTensorsReader(missing)
    os.environ["MFT_NO_NATIVE_ST"] = "1"
    try:
        with pytest.raises(FileNotFoundError):
            st.SafeTensorsReader(missing)
    finally:
        del os.environ["MFT_NO_NATIVE_ST"]


def test_zero_copy_raw_window(tmp_path):
    """NativeReader.raw must be a read-only zero-copy view."""
    p = str(tmp_path / "zc.safetensors")
    arr = np.arange(8, dtype=np.float32)
    python_write(p, {"a": arr})
    r = nst.NativeReader(p)
    w = r.raw("a")
    assert not w.flags.writeable
    np.testing.assert_array_equal(w.view(np.float32), arr)
    r.close()


def test_checkpoint_roundtrip_through_native(tmp_path):
    """The io.checkpoints path (LoRA/full saves) keeps working end-to-end
    with the native backend engaged."""
    p = str(tmp_path / "rt.safetensors")
    tensors = {"x": np.float32(np.random.default_rng(1)
                               .normal(size=(64, 64)))}
    st.save_safetensors(p, tensors, {"k": "v"})
    r = st.SafeTensorsReader(p)
    np.testing.assert_array_equal(r.load("x"), tensors["x"])
    assert r.metadata == {"k": "v"}


def test_raw_view_survives_reader_gc(tmp_path):
    """A raw() view pins the reader's mmap: dropping the last explicit
    reader reference (GC would otherwise munmap) must not dangle the
    view's memory."""
    import gc
    p = str(tmp_path / "gc.safetensors")
    arr = np.arange(1024, dtype=np.float32)
    python_write(p, {"a": arr})
    r = nst.NativeReader(p)
    w = r.raw("a")
    del r
    gc.collect()
    np.testing.assert_array_equal(w.view(np.float32), arr)


def test_raw_after_close_raises(tmp_path):
    p = str(tmp_path / "closed.safetensors")
    python_write(p, {"a": np.arange(4, dtype=np.float32)})
    r = nst.NativeReader(p)
    r.close()
    with pytest.raises(ValueError):
        r.raw("a")


def test_malformed_files_raise_valueerror_both_backends(tmp_path):
    """API contract: malformed files raise ValueError regardless of which
    backend parses them (the Python fallback used to leak struct.error /
    json.JSONDecodeError)."""
    import os
    cases = {
        "trunc_len.safetensors": b"\x05\x00\x00",          # short prefix
        "bad_json.safetensors":
            (8).to_bytes(8, "little") + b"not-json",
        "not_object.safetensors":
            (4).to_bytes(8, "little") + b"1234",           # JSON number
        # corrupt prefix decoding to ~2^60: must raise ValueError, not
        # attempt the allocation and leak MemoryError
        "huge_len.safetensors": (1 << 60).to_bytes(8, "little") + b"{}",
    }
    paths = []
    for fname, blob in cases.items():
        p = str(tmp_path / fname)
        with open(p, "wb") as f:
            f.write(blob)
        paths.append(p)
    for p in paths:
        with pytest.raises(ValueError):
            st.SafeTensorsReader(p)
    os.environ["MFT_NO_NATIVE_ST"] = "1"
    try:
        for p in paths:
            with pytest.raises(ValueError):
                st.SafeTensorsReader(p)
    finally:
        del os.environ["MFT_NO_NATIVE_ST"]
