"""Serve-layer robustness (round 14, DESIGN.md §19): bounded admission
and load shedding, per-request deadlines, step-dispatch crash
containment, SIGTERM graceful drain, and the fault-injection harness —
the serve-side mirror of r13's injected-failure fleet tests.

Two invariants anchor everything here:

  TERMINAL ACCOUNTING — every request reaching a terminal state
  (finished | cancelled | rejected | timeout | error) emits exactly ONE
  terminal `request` phase and releases exactly the pages it allocated
  (`assert_terminal_accounting`, run after every fault e2e);

  COMPILE STABILITY — rejects, sheds, timeouts, containment, and drain
  are host-side bookkeeping: ≤2 post-warmup traces (0 expected) across
  every fault path, and surviving requests' greedy outputs stay
  token-identical to the batch-at-a-time generate() oracle.
"""

import dataclasses
import json
import os
import signal
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__))), "tools"))

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.core.preempt import PreemptionGuard
from mobilefinetuner_tpu.core.telemetry import (HangWatchdog, Telemetry,
                                                validate_event)
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.models.generate import SampleConfig, gpt2_generate
from mobilefinetuner_tpu.serve import Request, ServeConfig, ServeEngine

CFG = dataclasses.replace(
    GPT2Config.tiny(vocab_size=211), n_embd=64, n_head=4, n_positions=64,
    n_layer=2, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)


@pytest.fixture(scope="module")
def params():
    return gpt2.init_params(CFG, jax.random.PRNGKey(0))


def make_engine(params, tmp_path=None, stream="r.jsonl", **cfg_kw):
    kw = dict(num_slots=2, block_T=8, num_blocks=32, max_prompt=16,
              max_new_tokens=8)
    kw.update(cfg_kw)
    tel = Telemetry(str(tmp_path / stream)) if tmp_path is not None \
        else Telemetry("")
    return ServeEngine("gpt2", CFG, params, ServeConfig(**kw),
                       telemetry=tel)


def oracle(params, req):
    """Batch-at-a-time generate() with a contiguous cache — the serve
    loop's parity target (same convention as tests/test_serve.py)."""
    ids = jnp.asarray([req.prompt], jnp.int32)
    cfg = SampleConfig(max_new_tokens=req.max_new_tokens, greedy=True,
                      eos_id=None, pad_id=0)
    return np.asarray(gpt2_generate(CFG, params, ids, jnp.ones_like(ids),
                                    cfg))[0].tolist()


def read_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f.read().splitlines() if l.strip()]


def assert_terminal_accounting(recs, reqs, engine):
    """THE leak/accounting invariant: every request terminal, exactly
    one terminal `request` phase per id (matching its state), and the
    allocator holds zero pages. Round 21 extends it to REFCOUNTED
    pages: with every request terminal, every shared page's refcount
    must have returned to zero exactly once (`refcounts == {}` — a
    page still carrying a count is a leak, a count going negative
    raised at free time), and a prefix cache's registered/parked sets
    must agree with the allocator's."""
    terminal_phase = {"finished": "finish", "cancelled": "cancel",
                      "rejected": "reject", "timeout": "timeout",
                      "error": "error"}
    by_id = {}
    for r in recs:
        if r.get("event") == "request":
            by_id.setdefault(r["id"], []).append(r["phase"])
    for req in reqs:
        assert req.state in Request.TERMINAL, \
            f"req {req.id} non-terminal: {req.state}"
        assert not req.blocks, f"req {req.id} still holds pages"
        terms = [p for p in by_id.get(req.id, ())
                 if p in terminal_phase.values()]
        assert terms == [terminal_phase[req.state]], \
            f"req {req.id} ({req.state}): terminal phases {terms}"
    assert engine.alloc.in_use == 0, \
        f"allocator leaked {engine.alloc.in_use} pages"
    assert engine.alloc.refcounts == {}, \
        f"pages still refcounted: {engine.alloc.refcounts}"
    if engine.prefix is not None:
        engine.prefix.check_consistent()
    assert not engine.active and not engine.queue


# --------------------------- bounded admission -------------------------------

def test_queue_full_rejects_newest(params, tmp_path):
    eng = make_engine(params, tmp_path, num_slots=1, max_queue=2)
    a = eng.submit([1, 2, 3])
    eng.step()                                  # a -> active
    assert a.state == "active"
    q = [eng.submit([4, 5]), eng.submit([6, 7])]
    over = eng.submit([8, 9])                   # queue at cap: rejected
    assert over.state == "rejected" and over.reason == "queue_full"
    assert [r.state for r in q] == ["queued", "queued"]
    eng.cancel(a)
    for r in q:
        eng.cancel(r)
    eng.close()
    recs = read_events(eng.telemetry.path)
    ev = {(r["id"], r["phase"]): r for r in recs
          if r["event"] == "request"}
    assert ev[(over.id, "reject")]["reason"] == "queue_full"
    assert_terminal_accounting(recs, [a, over] + q, eng)


def test_shed_policy_drops_nearest_deadline(params, tmp_path):
    """shed_policy="deadline": a full queue sheds the queued request
    closest to blowing its own deadline, not the newest arrival;
    with no deadline-carrying queued request it degrades to
    reject-newest."""
    eng = make_engine(params, tmp_path, num_slots=1, max_queue=2,
                      shed_policy="deadline")
    a = eng.submit([1, 2, 3])
    eng.step()
    urgent = eng.submit([4, 5], deadline_ms=50.0)
    lax = eng.submit([5, 6], deadline_ms=60_000.0)
    newcomer = eng.submit([6, 7])               # sheds `urgent`
    assert urgent.state == "rejected" and urgent.reason == "shed"
    assert newcomer.state == "queued" and lax.state == "queued"
    # no deadline-carrying queued request left that is sheddable ->
    # the next over-limit arrival... `lax` still has one; drop it too
    newcomer2 = eng.submit([7, 8])
    assert lax.state == "rejected" and lax.reason == "shed"
    # queue now holds only deadline-less requests: reject the newest
    newcomer3 = eng.submit([8, 9])
    assert newcomer3.state == "rejected" and \
        newcomer3.reason == "queue_full"
    eng.cancel(a)
    eng.cancel(newcomer)
    eng.cancel(newcomer2)
    eng.close()
    assert_terminal_accounting(
        read_events(eng.telemetry.path),
        [a, urgent, lax, newcomer, newcomer2, newcomer3], eng)


# --------------------------- deadlines ---------------------------------------

def test_queued_deadline_times_out_without_prefill(params, tmp_path):
    """A queued request past its deadline is dropped BEFORE admission:
    no prefill trace, no pages, partial-output-free timeout."""
    eng = make_engine(params, tmp_path)
    req = eng.submit([1, 2, 3], deadline_ms=1.0)
    time.sleep(0.01)
    eng.step()
    assert req.state == "timeout" and req.reason == "deadline"
    assert req.tokens == [] and eng.trace_counts["prefill"] == 0
    eng.close()
    assert_terminal_accounting(read_events(eng.telemetry.path),
                               [req], eng)


def test_active_deadline_returns_partial_output(params, tmp_path):
    """An active request past its deadline is cancelled at the next
    step boundary: partial tokens kept, slot + pages released, the
    OTHER slot's request unaffected and still oracle-equal."""
    eng = make_engine(params, tmp_path)
    rng = np.random.default_rng(3)
    doomed = eng.submit(list(rng.integers(1, 200, 5)), max_new_tokens=8,
                        deadline_ms=60_000.0)
    healthy = eng.submit(list(rng.integers(1, 200, 7)), max_new_tokens=8)
    eng.step()                      # admit (first token) + one decode
    eng.step()
    assert doomed.state == "active" and len(doomed.tokens) == 3
    # force the deadline into the past at a known boundary — the
    # wall-clock version of "the budget ran out mid-generation",
    # without a timing-dependent sleep
    doomed.deadline_t = time.perf_counter() - 1e-3
    done = eng.step()
    assert doomed in done
    assert doomed.state == "timeout" and doomed.reason == "deadline"
    partial = list(doomed.tokens)
    assert len(partial) == 3        # output up to the boundary survives
    assert partial == oracle(params, doomed)[:3]
    eng.drain()
    assert healthy.state == "finished"
    assert healthy.tokens == oracle(params, healthy)
    eng.close()
    assert_terminal_accounting(read_events(eng.telemetry.path),
                               [doomed, healthy], eng)


# --------------------------- crash containment -------------------------------

def test_step_error_fails_active_queue_survives(params, tmp_path):
    """The containment acceptance: an exception out of the decode-step
    dispatch fails ONLY the in-flight requests (phase=error, reason =
    the exception type), the pool resets leak-free, the queue survives,
    and serving resumes — queued survivors finish oracle-equal with
    ZERO new traces."""
    eng = make_engine(params, tmp_path, num_slots=2, stats_every=3)
    rng = np.random.default_rng(11)
    warm = eng.submit(list(rng.integers(1, 200, 4)), max_new_tokens=2)
    eng.drain()
    traces0 = eng.total_traces()
    reqs = [eng.submit(list(rng.integers(1, 200, int(n))),
                       max_new_tokens=6) for n in (5, 9, 3, 7)]
    eng.step()                      # admit the first two
    inflight = [r for r in reqs if r.state == "active"]
    queued = [r for r in reqs if r.state == "queued"]
    assert len(inflight) == 2 and len(queued) == 2

    class BoomError(RuntimeError):
        pass

    def boom(step):
        eng.step_hook = None        # one-shot
        raise BoomError("injected")
    eng.step_hook = boom
    done = eng.step()
    assert sorted(r.id for r in done) == sorted(r.id for r in inflight)
    for r in inflight:
        assert r.state == "error" and r.reason == "BoomError"
        assert len(r.tokens) >= 1   # partial output survives the crash
    assert eng.alloc.in_use == 0    # pool reset clean
    assert [r.state for r in queued] == ["queued", "queued"]
    # serving resumes: the survivors prefill into the reset pool and
    # stay oracle-equal — the fault never reached the compiled programs
    eng.drain()
    for r in queued:
        assert r.state == "finished"
        assert r.tokens == oracle(params, r), f"req {r.id}"
    assert eng.total_traces() - traces0 == 0
    eng.close()
    recs = read_events(eng.telemetry.path)
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    assert any(r.get("event") == "serve_stats" for r in recs)
    assert_terminal_accounting(recs, [warm] + reqs, eng)


def test_on_step_error_raise_policy(params, tmp_path):
    """on_step_error="raise": containment still runs (actives failed,
    pool clean) but the exception propagates to the caller."""
    eng = make_engine(params, tmp_path, on_step_error="raise")
    req = eng.submit([1, 2, 3, 4])
    eng.step()

    def boom(step):
        eng.step_hook = None
        raise ValueError("injected dispatch failure")
    eng.step_hook = boom
    with pytest.raises(ValueError, match="injected"):
        eng.step()
    assert req.state == "error" and req.reason == "ValueError"
    assert eng.alloc.in_use == 0
    # the engine object is still serviceable after the raise
    ok = eng.submit([5, 6, 7])
    eng.drain()
    assert ok.state == "finished" and ok.tokens == oracle(params, ok)
    eng.close()
    assert_terminal_accounting(read_events(eng.telemetry.path),
                               [req, ok], eng)


def test_prefill_error_fails_one_request_not_neighbors(params, tmp_path):
    """A failed PREFILL kills one request; the other slot's in-flight
    request keeps its cache (no pool reset on the admission path) and
    finishes oracle-equal."""
    eng = make_engine(params, tmp_path)
    rng = np.random.default_rng(7)
    healthy = eng.submit(list(rng.integers(1, 200, 6)), max_new_tokens=6)
    eng.step()                      # healthy active
    victim = eng.submit(list(rng.integers(1, 200, 4)), max_new_tokens=6)
    real_prefill, calls = eng._prefill, []

    def flaky_prefill(*a, **k):
        if not calls:
            calls.append(1)
            raise RuntimeError("prefill died")
        return real_prefill(*a, **k)
    eng._prefill = flaky_prefill
    done = eng.step()
    assert victim in done
    assert victim.state == "error" and victim.reason == "RuntimeError"
    eng.drain()
    assert healthy.state == "finished"
    assert healthy.tokens == oracle(params, healthy)
    eng.close()
    assert_terminal_accounting(read_events(eng.telemetry.path),
                               [healthy, victim], eng)


# --------------------------- graceful drain ----------------------------------

def test_sigterm_drain(params, tmp_path):
    """SIGTERM at a step boundary: admissions stop, the queued
    remainder rejects with reason=shutdown, in-flight requests FINISH
    (oracle-equal), and the stream ends run_end{exit=preempted,
    reason=preempted} with a preempt event marking the drain."""
    eng = make_engine(params, tmp_path, num_slots=2)
    eng.install_preemption()
    rng = np.random.default_rng(5)
    reqs = [eng.submit(list(rng.integers(1, 200, int(n))),
                       max_new_tokens=6) for n in (4, 8, 5, 3)]
    eng.step()
    inflight = [r for r in reqs if r.state == "active"]
    queued = [r for r in reqs if r.state == "queued"]
    assert len(inflight) == 2 and len(queued) == 2
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(0.01)                # let the handler run
    assert eng.guard.triggered
    eng.drain()
    assert eng.draining
    for r in queued:
        assert r.state == "rejected" and r.reason == "shutdown"
    for r in inflight:
        assert r.state == "finished"
        assert r.tokens == oracle(params, r), f"req {r.id}"
    # post-drain submissions are turned away, not queued into a corpse
    late = eng.submit([9, 9, 9])
    assert late.state == "rejected" and late.reason == "shutdown"
    eng.close()
    recs = read_events(eng.telemetry.path)
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    assert any(r["event"] == "preempt" for r in recs)
    end = recs[-1]
    assert end["event"] == "run_end" and end["exit"] == "preempted" \
        and end["reason"] == "preempted"
    assert_terminal_accounting(recs, reqs + [late], eng)


def test_second_signal_cancels_inflight(params, tmp_path):
    """The escalation contract: a second SIGTERM mid-drain raises
    KeyboardInterrupt (the operator outranks a slow drain) — the
    caller cancels in-flight and still gets a terminal-complete,
    schema-valid stream."""
    eng = make_engine(params, tmp_path)
    guard = eng.install_preemption()
    req = eng.submit([1, 2, 3, 4], max_new_tokens=8)
    eng.step()
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(0.01)
    assert guard.triggered
    with pytest.raises(KeyboardInterrupt):
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(0.2)
    for r in list(eng.active):
        eng.cancel(r)
    assert req.state == "cancelled" and len(req.tokens) >= 1
    eng.close()
    recs = read_events(eng.telemetry.path)
    assert recs[-1]["exit"] == "preempted"
    assert_terminal_accounting(recs, [req], eng)


# --------------------------- lifecycle hygiene -------------------------------

def test_submit_after_close_raises_and_close_idempotent(params):
    eng = make_engine(params)
    eng.close()
    with pytest.raises(RuntimeError, match="closed"):
        eng.submit([1, 2])
    eng.close()                     # second close is a no-op


def test_exit_unwinds_as_error_with_exception_name(params, tmp_path):
    """__exit__ on an exception records run_end{exit=error,
    reason=<type>} — not a clean run_end wearing the type as exit."""
    eng = make_engine(params, tmp_path)
    with pytest.raises(ValueError):
        with eng:
            raise ValueError("user code blew up")
    recs = read_events(eng.telemetry.path)
    end = recs[-1]
    assert end["event"] == "run_end"
    assert end["exit"] == "error" and end["reason"] == "ValueError"
    # and the clean path still records exit=ok
    eng2 = make_engine(params, tmp_path, stream="r2.jsonl")
    with eng2:
        pass
    assert read_events(eng2.telemetry.path)[-1]["exit"] == "ok"


def test_health_and_serve_stats_cadence(params, tmp_path):
    eng = make_engine(params, tmp_path, stats_every=2)
    h = eng.health()
    assert h["queue_depth"] == 0 and h["active"] == 0
    assert h["blocks_in_use"] == 0 and h["p95_step_ms"] is None
    eng.submit([1, 2, 3], max_new_tokens=6)
    eng.step()
    h = eng.health()
    assert h["active"] == 1 and h["occupancy"] == 0.5
    assert h["blocks_in_use"] >= 1
    eng.drain()
    eng.close()
    recs = read_events(eng.telemetry.path)
    stats = [r for r in recs if r["event"] == "serve_stats"]
    # max_new=6 = prefill token + 5 decode steps; cadence 2 -> 2, 4
    assert [s["step"] for s in stats] == [2, 4]
    for s in stats:
        assert validate_event(s) is None
        assert s["p95_step_ms"] > 0
        assert s["active"] == 1     # mid-flight at both snapshots
    # the request finishes at decode step 5, after the last snapshot —
    # the cumulative counter lives in health()
    assert eng.health()["counts"]["finished"] == 1


# --------------------------- watchdog over the serve loop --------------------

def test_watchdog_fires_on_injected_hang(params, tmp_path):
    """--inject hang: a wedged step dispatch trips the engine-level
    HangWatchdog (a `hang` event lands in the SAME stream) while the
    run still completes — report-only mode, serve-side mirror of the
    r09 injected-stall test."""
    import serve_bench
    stream = str(tmp_path / "wd.jsonl")
    wd = HangWatchdog(mult=2.0, min_deadline_s=0.25, grace_s=5.0,
                      stacks_file=str(tmp_path / "stacks.txt"),
                      abort=False)
    eng = ServeEngine("gpt2", CFG, params,
                      ServeConfig(num_slots=2, block_T=8, num_blocks=32,
                                  max_prompt=16, max_new_tokens=8),
                      telemetry=Telemetry(stream), watchdog=wd)
    wd.on_hang = lambda p: eng.telemetry.emit("hang", **p)
    wd.start()
    try:
        warm = eng.submit([1, 2, 3], max_new_tokens=2)
        eng.drain()
        serve_bench.install_inject(
            eng, f"hang:{eng.decode_steps + 1}:1.2")
        req = eng.submit([4, 5, 6, 7], max_new_tokens=4)
        eng.drain()
    finally:
        wd.stop()
    assert wd.fired >= 1
    assert req.state == "finished" and req.tokens == oracle(params, req)
    eng.close()
    recs = read_events(eng.telemetry.path)
    hangs = [r for r in recs if r["event"] == "hang"]
    assert hangs and hangs[0]["action"] == "continue"
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    assert_terminal_accounting(recs, [warm, req], eng)


def test_write_failure_escalates_to_full_containment(params, tmp_path):
    """The prompt-page WRITE donates the pools (non-CPU backends): a
    failure there may have consumed every resident's cache, so —
    unlike a failed prefill — containment must escalate: the victim
    AND the in-flight requests fail, the pools rebuild, and serving
    resumes clean (uniform semantics on every backend, because the CPU
    tests are the only ones that run in CI)."""
    eng = make_engine(params, tmp_path)
    rng = np.random.default_rng(13)
    resident = eng.submit(list(rng.integers(1, 200, 6)), max_new_tokens=8)
    eng.step()                      # resident active, cache populated
    victim = eng.submit(list(rng.integers(1, 200, 4)), max_new_tokens=6)
    real_write, calls = eng._write, []

    def flaky_write(*a, **k):
        if not calls:
            calls.append(1)
            raise RuntimeError("write died post-donation")
        return real_write(*a, **k)
    eng._write = flaky_write
    eng.step()
    assert victim.state == "error" and victim.reason == "RuntimeError"
    assert resident.state == "error"    # cache suspect -> failed too
    assert eng.alloc.in_use == 0 and not eng._pools_at_risk
    fresh = eng.submit(list(rng.integers(1, 200, 5)), max_new_tokens=6)
    eng.drain()
    assert fresh.state == "finished"
    assert fresh.tokens == oracle(params, fresh)
    eng.close()
    assert_terminal_accounting(read_events(eng.telemetry.path),
                               [resident, victim, fresh], eng)


def test_cache_on_fault_matrix_refcounts_return_to_zero(params, tmp_path):
    """Round 21: the r14 fault matrix re-run with shared-prefix reuse
    and chunked admission engaged — step-error containment while two
    residents SHARE refcounted prefix pages, a cancel mid-chunk, and a
    queued deadline timeout. After every path: every shared page's
    refcount back to zero exactly once (refcounts == {}), the prefix
    cache consistent with the allocator, survivors oracle-equal, and
    zero new traces once every bucket + the COW program are warm."""
    eng = make_engine(params, tmp_path, num_slots=2, num_blocks=64,
                      prefix_cache=True, max_prompt_chunked=40)
    rng = np.random.default_rng(21)
    common = list(rng.integers(1, 200, 16))      # two full pages
    all_reqs = []

    def run(prompt, max_new=2, **kw):
        r = eng.submit(prompt, max_new_tokens=max_new, **kw)
        eng.drain()
        all_reqs.append(r)
        return r

    # warm EVERY executable: classic prefill+write+step, both chunk
    # buckets (8, 16), and the COW full-hit re-feed
    run(common[:8])                              # classic one-shot
    run(common + list(rng.integers(1, 200, 10)))  # chunked, bucket 16
    run(common + list(rng.integers(1, 200, 5)))   # prefix hit, bucket 8
    run(common)                                   # full hit -> COW
    assert eng.cow_copies >= 1
    traces0 = eng.total_traces()

    # --- step_error containment while prefix pages are SHARED --------
    rA = eng.submit(common + list(rng.integers(1, 200, 8)),
                    max_new_tokens=6)
    rB = eng.submit(common + list(rng.integers(1, 200, 8)),
                    max_new_tokens=6)
    all_reqs += [rA, rB]
    eng.step()                      # admit both; rA's final chunk
    eng.step()                      # rB's final chunk; rA decodes
    assert not rA.prefilling and not rB.prefilling
    shared = rA.blocks[:2]
    assert shared == rB.blocks[:2], "prefix pages not shared"
    assert all(eng.alloc.refcounts[b] == 2 for b in shared)

    class BoomError(RuntimeError):
        pass

    def boom(step):
        eng.step_hook = None
        raise BoomError("injected")
    eng.step_hook = boom
    done = eng.step()
    assert sorted(r.id for r in done) == sorted([rA.id, rB.id])
    for r in (rA, rB):
        assert r.state == "error" and r.reason == "BoomError"
    # containment rebuilt the pools: refcounts cleared ONCE, and the
    # cache flushed alongside (its contents no longer exist)
    assert eng.alloc.in_use == 0 and eng.alloc.refcounts == {}
    assert eng.alloc.parked_blocks == 0
    eng.prefix.check_consistent()

    # --- cancel mid-chunk --------------------------------------------
    midway = eng.submit(list(rng.integers(1, 200, 35)),
                        max_new_tokens=6)
    all_reqs.append(midway)
    eng.step()                      # first 16-wide chunk only
    assert midway.state == "active" and midway.prefilling
    assert 0 < midway.prefill_pos < len(midway.prompt)
    eng.cancel(midway)
    assert midway.state == "cancelled" and not midway.blocks
    assert eng.alloc.in_use == 0 and eng.alloc.refcounts == {}
    eng.prefix.check_consistent()

    # --- queued deadline timeout with the cache engaged --------------
    late = eng.submit(common + [3, 3, 3], deadline_ms=1.0)
    all_reqs.append(late)
    time.sleep(0.01)
    eng.step()
    assert late.state == "timeout" and late.reason == "deadline"
    assert eng.alloc.refcounts == {}

    # serving resumes post-flush: a fresh chunked admission finishes
    # oracle-equal on the SAME executables (no retrace paid anywhere)
    fresh = run(common + list(rng.integers(1, 200, 9)), max_new=6)
    assert fresh.state == "finished"
    assert fresh.tokens == oracle(params, fresh)
    assert eng.total_traces() - traces0 == 0, dict(eng.trace_counts)
    eng.close()
    recs = read_events(eng.telemetry.path)
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    assert_terminal_accounting(recs, all_reqs, eng)


def test_inject_never_fired_fails_the_harness(tmp_path):
    """An armed --inject fault that never fires (step out of the run's
    reach) must FAIL the harness run — CI keys on the exit status, and
    a no-op injection proving nothing must not read as a pass."""
    import serve_bench
    with pytest.raises(SystemExit, match="never fired"):
        serve_bench.run_rows(
            "tiny-gpt2", [200.0], n_requests=2, adapters=0, num_slots=2,
            block_T=8, num_blocks=32, max_prompt=16, max_new=4,
            dtype="float32", seed=0, prompt_lo=2,
            inject="step_error:100000", drain=False)


def test_run_load_census_includes_submit_time_terminals(params, tmp_path):
    """run_load's returned list must cover submit-time terminals too:
    queue_full rejects and SHED VICTIMS reach their terminal state
    inside a LATER request's submit() call and never come back from
    step() — the bench row's census has to union submitted with
    step-returned or it undercounts exactly the failures the harness
    exists to measure."""
    import serve_bench
    eng = make_engine(params, tmp_path, num_slots=1, max_queue=2,
                      shed_policy="deadline")
    done, _ = serve_bench.run_load(eng, [], rate=1e6, n_requests=8,
                                   seed=2, prompt_lo=2, prompt_hi=6,
                                   max_new=4, deadline_ms=60_000.0)
    assert len(done) == 8                    # every request accounted for
    assert all(r.done for r in done)
    by_state = {}
    for r in done:
        by_state[r.state] = by_state.get(r.state, 0) + 1
    # 8 near-simultaneous arrivals into 1 slot + a 2-deep queue MUST
    # overflow; with every request carrying a deadline the victims are
    # shed (reason=shed), not reject-newest
    assert by_state.get("rejected", 0) >= 1
    assert any(r.reason == "shed" for r in done)
    assert by_state.get("finished", 0) >= 1
    assert sum(by_state.values()) == 8
    row = serve_bench.row_from("census", eng, done, 1.0, 1e6, 0)
    assert row["terminal"]["rejected"] == by_state.get("rejected", 0)
    eng.close()
    assert_terminal_accounting(read_events(eng.telemetry.path), done, eng)


# --------------------------- the merged fault e2e ----------------------------

def test_injected_fault_poisson_e2e(params, tmp_path):
    """THE acceptance e2e: seeded Poisson open-loop load through the
    real engine (tools/serve_bench.py load generator) with an injected
    step_error, a bounded queue, per-request deadlines, and a SIGTERM
    drain — ONE stream, asserted schema-valid end to end, surviving
    greedy outputs oracle-identical, zero post-warmup retraces, and
    terminal accounting across every fault path."""
    import serve_bench
    stream = str(tmp_path / "e2e.jsonl")
    eng = ServeEngine(
        "gpt2", CFG, params,
        # max_queue ABOVE the offered burst: bounded admission is
        # configured (the production shape) but the cap/shed behavior
        # itself is pinned by its own deterministic tests — a
        # timing-dependent shed here would make the terminal census
        # nondeterministic
        ServeConfig(num_slots=2, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=8, max_queue=16,
                    shed_policy="deadline", stats_every=5),
        telemetry=Telemetry(stream))
    eng.install_preemption()
    all_reqs = []
    # warmup outside the measured window (r11 convention)
    warm = eng.submit([1, 1, 1], max_new_tokens=2)
    eng.drain()
    all_reqs.append(warm)
    traces0 = eng.total_traces()

    # phase A: Poisson load with a step_error injected mid-flight —
    # generous deadline so only the injection (never CI timing) decides
    # who fails
    serve_bench.install_inject(eng, f"step_error:{eng.decode_steps + 2}")
    done, _ = serve_bench.run_load(eng, [], rate=500.0, n_requests=10,
                                   seed=4, prompt_lo=2, prompt_hi=9,
                                   max_new=5, deadline_ms=120_000.0)
    all_reqs.extend(done)
    assert len(done) == 10
    errored = [r for r in done if r.state == "error"]
    finished = [r for r in done if r.state == "finished"]
    assert errored, "the injection never fired"
    assert all(r.reason == "InjectedStepError" for r in errored)
    assert finished, "containment killed the queue too"
    for r in finished:
        assert r.tokens == oracle(params, r), f"req {r.id}"

    # phase B: a deterministic deadline blow (queued, never prefills)
    late = eng.submit([2, 2, 2], deadline_ms=1.0)
    time.sleep(0.01)
    prefills = eng.trace_counts["prefill"]
    eng.step()
    assert late.state == "timeout" and late.reason == "deadline"
    assert eng.trace_counts["prefill"] == prefills
    all_reqs.append(late)

    # phase C: SIGTERM drain — in-flight finish, queue rejects
    rng = np.random.default_rng(9)
    tail = [eng.submit(list(rng.integers(1, 200, int(n))),
                       max_new_tokens=5) for n in (4, 6, 3, 7)]
    all_reqs.extend(tail)
    eng.step()
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(0.01)
    eng.drain()
    survivors = [r for r in tail if r.state == "finished"]
    shut = [r for r in tail if r.state == "rejected"]
    assert survivors and shut
    assert all(r.reason == "shutdown" for r in shut)
    for r in survivors:
        assert r.tokens == oracle(params, r), f"req {r.id}"

    # the compile-stability invariant held across EVERY fault path
    assert eng.total_traces() - traces0 <= 2
    assert eng.total_traces() - traces0 == 0    # the design target
    eng.close()

    recs = read_events(stream)
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    assert recs[0]["event"] == "run_start"
    end = recs[-1]
    assert end["event"] == "run_end" and end["exit"] == "preempted" \
        and end["reason"] == "preempted"
    assert any(r["event"] == "preempt" for r in recs)
    assert any(r["event"] == "serve_stats" for r in recs)
    assert_terminal_accounting(recs, all_reqs, eng)

    # the report renders the failure-mode rates from the same stream
    import telemetry_report
    s = telemetry_report.summarize(recs)
    rq = s["requests"]
    census = lambda st: sum(1 for r in all_reqs if r.state == st)
    assert rq["errors"] == census("error") == len(errored)
    assert rq["rejected"] == census("rejected") == len(shut)
    assert rq["timeout"] == census("timeout") == 1
    assert rq["error_rate"] > 0 and rq["reject_rate"] > 0 \
        and rq["timeout_rate"] > 0
    assert rq["fail_reasons"]["shutdown"] == len(shut)
    assert rq["fail_reasons"]["deadline"] == 1
    assert rq["fail_reasons"]["InjectedStepError"] == len(errored)
    assert s["serve"]["snapshots"] >= 1
    assert s["serve"]["counts"]["error"] == len(errored)
    assert telemetry_report.main([stream]) == 0
