"""Optimizer-state/master-weight host offload (optim/opt_offload.py):
the streamed per-leaf Adam update must be numerically identical to the
resident trainer's update, and the master round trip must preserve
shapes/values for checkpointing."""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.core.config import Gemma3TextConfig
from mobilefinetuner_tpu.models import gemma3
from mobilefinetuner_tpu.ops.loss import chunked_lm_cross_entropy_sum
from mobilefinetuner_tpu.optim.opt_offload import (OptOffloadSpec,
                                                   init_opt_offload,
                                                   make_offload_train_step,
                                                   master_to_params,
                                                   plan_opt_offload)
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

CFG = Gemma3TextConfig(
    vocab_size=512, hidden_size=32, intermediate_size=64,
    num_hidden_layers=3, num_attention_heads=4, num_key_value_heads=2,
    head_dim=8, max_position_embeddings=64, sliding_window=16,
    query_pre_attn_scalar=8.0, sliding_window_pattern=3)


def make_problem(seed=0):
    params = gemma3.init_params(CFG, jax.random.PRNGKey(seed))
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, CFG.vocab_size, (4, 32)), jnp.int32)
    batch = {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
             "labels": ids}
    return params, batch


def assert_tree_matches(got, want, exact=False):
    """Leaf-by-leaf comparison keyed by want's tree paths (got may be a
    plain nested dict from master_to_params)."""
    for path, w in jax.tree_util.tree_flatten_with_path(want)[0]:
        leaf = got
        for k in path:
            leaf = leaf[k.key]
        if exact:
            np.testing.assert_array_equal(np.asarray(leaf), np.asarray(w),
                                          err_msg=str(path))
        else:
            np.testing.assert_allclose(np.asarray(leaf), np.asarray(w),
                                       rtol=1e-5, atol=1e-6,
                                       err_msg=str(path))


def loss_fn(params_t, _unused, mb):
    hidden = gemma3.hidden_states(CFG, params_t, mb["input_ids"],
                                  attention_mask=mb["attention_mask"])
    return chunked_lm_cross_entropy_sum(hidden, params_t["embed"],
                                        mb["labels"], num_chunks=2)


def test_plan_chunks_2d_and_stacked():
    params, _ = make_problem()
    spec = OptOffloadSpec(min_stream_bytes=1 << 10, chunk_bytes=1 << 12)
    plan = plan_opt_offload(params, spec)
    # [L, ...] stacks stream with C = L
    assert plan["blocks"]["attn"]["q_w"] == CFG.num_hidden_layers
    # the [512, 32] embed row-chunks: C divides 512, chunk <= ~4 KB
    c = plan["embed"]
    assert c > 1 and 512 % c == 0 and (512 // c) * 32 * 4 <= (1 << 12)
    # tiny norms stay resident
    assert plan["final_norm"] == 0


def test_streamed_update_matches_resident_trainer():
    """3 steps of the offloaded step vs trainer.make_train_step on an f32
    compute copy: master weights, moments, loss, and grad_norm must agree
    (compute_dtype f32 makes the gradients bit-comparable)."""
    params, batch = make_problem()
    tc = TrainConfig(total_steps=4, lr=1e-3, grad_accum_steps=2,
                     schedule="constant", warmup_ratio=0.0,
                     weight_decay=0.01)
    spec = OptOffloadSpec(min_stream_bytes=1 << 10, chunk_bytes=1 << 12)
    plan = plan_opt_offload(params, spec)
    compute, opt = init_opt_offload(params, plan,
                                    compute_dtype=jnp.float32)
    step_off = make_offload_train_step(loss_fn, tc, plan,
                                       compute_dtype=jnp.float32,
                                       donate=False)

    ref_params = jax.tree.map(lambda x: jnp.asarray(x, jnp.float32), params)
    ref_opt = init_optimizer(ref_params, tc, None)
    step_ref = make_train_step(loss_fn, tc, mask=None, donate=False)

    for s in range(3):
        compute, opt, m_off = step_off(compute, None, opt, batch,
                                       jnp.int32(s))
        ref_params, ref_opt, m_ref = step_ref(ref_params, None, ref_opt,
                                              batch, jnp.int32(s))
        assert float(m_off["loss"]) == pytest.approx(
            float(m_ref["loss"]), rel=1e-6), s
        assert float(m_off["grad_norm"]) == pytest.approx(
            float(m_ref["grad_norm"]), rel=1e-5), s

    got = master_to_params(opt, plan, params)
    assert_tree_matches(got, ref_params)
    # the device compute copy tracks the master
    np.testing.assert_allclose(
        np.asarray(jax.device_get(compute["embed"])),
        np.asarray(got["embed"]), rtol=1e-5, atol=1e-6)
    # moments really moved
    assert float(jnp.abs(jax.device_get(
        opt["m"]["blocks"]["attn"]["q_w"])).max()) > 0


def test_streamed_state_lives_on_host():
    params, _ = make_problem()
    spec = OptOffloadSpec(min_stream_bytes=1 << 10, chunk_bytes=1 << 12)
    plan = plan_opt_offload(params, spec)
    compute, opt = init_opt_offload(params, plan)
    # on the CPU test backend the host tier falls back to the backend's
    # sole memory (its NAME varies across jax versions — see _shardings);
    # on TPU this is "pinned_host" vs "device"
    d = jax.devices()[0]
    if d.platform == "cpu":
        host_kind = device_kind = d.default_memory().kind
    else:
        host_kind, device_kind = "pinned_host", "device"
    assert opt["master"]["embed"].sharding.memory_kind == host_kind
    assert opt["v"]["blocks"]["mlp"]["gate_w"].sharding.memory_kind == \
        host_kind
    assert opt["master"]["final_norm"].sharding.memory_kind == device_kind
    assert compute["embed"].dtype == jnp.bfloat16
    assert compute["embed"].sharding.memory_kind == device_kind


def test_bf16_compute_trains_and_loss_decreases():
    """The real configuration (bf16 compute copy): loss decreases and the
    step count advances."""
    params, batch = make_problem(seed=1)
    tc = TrainConfig(total_steps=6, lr=5e-3, schedule="constant",
                     warmup_ratio=0.0)
    plan = plan_opt_offload(params, OptOffloadSpec(min_stream_bytes=1 << 10,
                                                   chunk_bytes=1 << 12))
    compute, opt = init_opt_offload(params, plan)
    step = make_offload_train_step(loss_fn, tc, plan, donate=False)
    losses = []
    for s in range(5):
        compute, opt, m = step(compute, None, opt, batch, jnp.int32(s))
        losses.append(float(m["loss"]))
    assert int(opt["step"]) == 5
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_resume_equals_uninterrupted():
    """Sidecar round trip: steps 0-1, save (master + {step, m, v}), reload
    into a FRESH state, steps 2-3 — the final master must match an
    uninterrupted 4-step run bit-for-bit (same batches, f32 compute so
    no nondeterministic rounding enters)."""
    from mobilefinetuner_tpu.optim.opt_offload import (resume_opt_sidecar,
                                                       save_opt_sidecar)
    import tempfile, os
    params, batch = make_problem(seed=2)
    tc = TrainConfig(total_steps=4, lr=1e-3, schedule="cosine",
                     warmup_ratio=0.25)
    spec = OptOffloadSpec(min_stream_bytes=1 << 10, chunk_bytes=1 << 12)
    plan = plan_opt_offload(params, spec)
    step = make_offload_train_step(loss_fn, tc, plan,
                                   compute_dtype=jnp.float32, donate=False)

    # uninterrupted
    compute, opt = init_opt_offload(params, plan, compute_dtype=jnp.float32)
    for s in range(4):
        compute, opt, _ = step(compute, None, opt, batch, jnp.int32(s))
    want = master_to_params(opt, plan, params)

    # interrupted at step 2: persist sidecar + master, rebuild, resume
    compute2, opt2 = init_opt_offload(params, plan,
                                      compute_dtype=jnp.float32)
    for s in range(2):
        compute2, opt2, _ = step(compute2, None, opt2, batch, jnp.int32(s))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ck.opt")
        save_opt_sidecar(path, opt2, tc.adam())
        master_mid = master_to_params(opt2, plan, params)
        compute3, opt3 = init_opt_offload(master_mid, plan,
                                          compute_dtype=jnp.float32)
        opt3 = resume_opt_sidecar(path, opt3)
    assert int(opt3["step"]) == 2
    for s in range(2, 4):
        compute3, opt3, _ = step(compute3, None, opt3, batch, jnp.int32(s))
    got = master_to_params(opt3, plan, params)
    assert_tree_matches(got, want, exact=True)


# ----------------------- 16-bit host tier (round 5) --------------------------

SPEC16 = OptOffloadSpec(min_stream_bytes=1 << 10, chunk_bytes=1 << 12,
                        state_dtype="bfloat16", master_dtype="bfloat16")


@pytest.mark.parametrize("state_dtype,master_dtype", [
    ("bfloat16", "bfloat16"),
    ("float16", "float32"),
])
def test_16bit_tier_dtypes_and_trains(state_dtype, master_dtype):
    """The 16-bit tier stores streamed m/v (and optionally master) in
    16-bit on the host, dequantizes on-chip, and still trains."""
    params, batch = make_problem(seed=3)
    spec = OptOffloadSpec(min_stream_bytes=1 << 10, chunk_bytes=1 << 12,
                          state_dtype=state_dtype,
                          master_dtype=master_dtype)
    plan = plan_opt_offload(params, spec)
    compute, opt = init_opt_offload(params, plan, spec=spec)
    assert opt["master"]["embed"].dtype == jnp.dtype(master_dtype)
    assert opt["m"]["blocks"]["attn"]["q_w"].dtype == jnp.dtype(state_dtype)
    assert opt["v"]["embed"].dtype == jnp.dtype(state_dtype)
    # resident (small) leaves always stay f32
    assert opt["master"]["final_norm"].dtype == jnp.float32
    assert opt["m"]["final_norm"].dtype == jnp.float32
    tc = TrainConfig(total_steps=6, lr=5e-3, schedule="constant",
                     warmup_ratio=0.0)
    step = make_offload_train_step(loss_fn, tc, plan, donate=False,
                                   spec=spec)
    losses = []
    for s in range(5):
        compute, opt, m = step(compute, None, opt, batch, jnp.int32(s))
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0], losses


def test_16bit_quality_tracks_f32_stream():
    """Quality guard: a short 16-bit-tier run lands within optimizer-noise
    distance of the f32 stream (same seed, same batches)."""
    params, batch = make_problem(seed=4)
    tc = TrainConfig(total_steps=5, lr=2e-3, schedule="constant",
                     warmup_ratio=0.0)
    finals = {}
    for name, spec in (
            ("f32", OptOffloadSpec(min_stream_bytes=1 << 10,
                                   chunk_bytes=1 << 12)),
            ("16bit", SPEC16)):
        plan = plan_opt_offload(params, spec)
        compute, opt = init_opt_offload(params, plan, spec=spec)
        step = make_offload_train_step(loss_fn, tc, plan, donate=False,
                                       spec=spec)
        for s in range(4):
            compute, opt, m = step(compute, None, opt, batch, jnp.int32(s))
        finals[name] = float(m["loss"])
    assert finals["16bit"] == pytest.approx(finals["f32"], rel=2e-2), finals


def test_16bit_resume_equals_uninterrupted():
    """The resume contract HOLDS on the 16-bit tier too: stochastic
    rounding is counter-based on (step, leaf, chunk), so an interrupted
    run replays the exact same quantization draws (opt_offload._sr_bfloat16)."""
    from mobilefinetuner_tpu.optim.opt_offload import (resume_opt_sidecar,
                                                       save_opt_sidecar)
    import tempfile, os
    params, batch = make_problem(seed=5)
    tc = TrainConfig(total_steps=4, lr=1e-3, schedule="cosine",
                     warmup_ratio=0.25)
    plan = plan_opt_offload(params, SPEC16)
    step = make_offload_train_step(loss_fn, tc, plan,
                                   compute_dtype=jnp.float32, donate=False,
                                   spec=SPEC16)
    compute, opt = init_opt_offload(params, plan, compute_dtype=jnp.float32,
                                    spec=SPEC16)
    for s in range(4):
        compute, opt, _ = step(compute, None, opt, batch, jnp.int32(s))
    want = master_to_params(opt, plan, params)

    compute2, opt2 = init_opt_offload(params, plan,
                                      compute_dtype=jnp.float32,
                                      spec=SPEC16)
    for s in range(2):
        compute2, opt2, _ = step(compute2, None, opt2, batch, jnp.int32(s))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ck.opt")
        save_opt_sidecar(path, opt2, tc.adam())
        master_mid = master_to_params(opt2, plan, params)
        compute3, opt3 = init_opt_offload(master_mid, plan,
                                          compute_dtype=jnp.float32,
                                          spec=SPEC16)
        opt3 = resume_opt_sidecar(path, opt3)
    assert opt3["m"]["embed"].dtype == jnp.bfloat16  # sidecar kept 16-bit
    for s in range(2, 4):
        compute3, opt3, _ = step(compute3, None, opt3, batch, jnp.int32(s))
    got = master_to_params(opt3, plan, params)
    assert_tree_matches(got, want, exact=True)


def test_resume_rejects_spec_mismatch():
    """A sidecar saved under one spec must NOT silently load under
    another (raw-f32 v reinterpreted as sqrt-encoded bf16 would corrupt
    every Adam denominator)."""
    from mobilefinetuner_tpu.optim.opt_offload import (resume_opt_sidecar,
                                                       save_opt_sidecar)
    import tempfile, os
    params, batch = make_problem(seed=6)
    tc = TrainConfig(total_steps=2, lr=1e-3, schedule="constant",
                     warmup_ratio=0.0)
    spec_f32 = OptOffloadSpec(min_stream_bytes=1 << 10,
                              chunk_bytes=1 << 12)
    plan = plan_opt_offload(params, spec_f32)
    compute, opt = init_opt_offload(params, plan, spec=spec_f32)
    step = make_offload_train_step(loss_fn, tc, plan, donate=False,
                                   spec=spec_f32)
    compute, opt, _ = step(compute, None, opt, batch, jnp.int32(0))
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ck.opt")
        save_opt_sidecar(path, opt, tc.adam())
        _, opt16 = init_opt_offload(params, plan, spec=SPEC16)
        with pytest.raises(ValueError, match="dtype mismatch"):
            resume_opt_sidecar(path, opt16)


def test_resume_missing_key_raises_informative_error():
    """A sidecar from an older/different offload layout (missing a
    template leaf) must raise a ValueError NAMING the missing tensor,
    not a bare KeyError from the safetensors reader."""
    from mobilefinetuner_tpu.io.safetensors_io import (SafeTensorsReader,
                                                       save_safetensors)
    from mobilefinetuner_tpu.optim.opt_offload import (resume_opt_sidecar,
                                                       save_opt_sidecar)
    import tempfile, os
    params, batch = make_problem(seed=7)
    tc = TrainConfig(total_steps=2, lr=1e-3, schedule="constant",
                     warmup_ratio=0.0)
    spec = OptOffloadSpec(min_stream_bytes=1 << 10, chunk_bytes=1 << 12)
    plan = plan_opt_offload(params, spec)
    compute, opt = init_opt_offload(params, plan, spec=spec)
    with tempfile.TemporaryDirectory() as d:
        path = os.path.join(d, "ck.opt")
        save_opt_sidecar(path, opt, tc.adam())
        # truncate: rewrite the sidecar without one m-leaf
        reader = SafeTensorsReader(path)
        kept = {k: reader.load(k) for k in reader.keys()
                if k != "m/embed"}
        assert len(kept) == len(reader.keys()) - 1  # the leaf existed
        trunc = os.path.join(d, "trunc.opt")
        save_safetensors(trunc, kept, metadata=reader.metadata)
        with pytest.raises(ValueError, match="m/embed"):
            resume_opt_sidecar(trunc, opt)


def test_sr_salt_has_no_4096_step_period():
    """Regression for the int32 salt overflow: the old
    step_no * 2**20 product wrapped mod 2**32, so steps s and s + 4096
    shared every per-element rounding draw. The lowbias32-mixed uint32
    salt must differ across 0/2048/4096 (and the draws with it)."""
    from mobilefinetuner_tpu.optim.opt_offload import (_sr_bfloat16,
                                                       _sr_salt)
    salts = {s: int(_sr_salt(jnp.int32(s), 0)) for s in
             (0, 2048, 4096, 8192)}
    assert len(set(salts.values())) == len(salts), salts
    # and the actual quantization draws decorrelate: mid-ulp values
    # round differently under different step salts
    x = jnp.full((4096,), 1.0 + 1 / 512, jnp.float32)  # halfway point
    draws = {s: np.asarray(_sr_bfloat16(x, _sr_salt(jnp.int32(s), 0)),
                           np.float32) for s in (0, 2048, 4096)}
    assert (draws[0] != draws[4096]).any()
    assert (draws[0] != draws[2048]).any()
    # chunk/leaf offsets stay disjoint from the step mixing
    assert int(_sr_salt(jnp.int32(3), 0)) != int(_sr_salt(jnp.int32(3), 1))


def test_sr_bfloat16_unbiased():
    """Stochastic rounding: every draw is one of the two bf16 neighbors,
    and the mean over many salts converges to the f32 value (the property
    that keeps tiny lr*update increments alive in expectation)."""
    from mobilefinetuner_tpu.optim.opt_offload import _sr_bfloat16
    x = jnp.asarray([1.0 + 1 / 512, -3.137e-3, 42.123, 1e-20], jnp.float32)
    lo = x.astype(jnp.bfloat16)
    draws = np.stack([np.asarray(_sr_bfloat16(x, jnp.int32(s)),
                                 np.float32) for s in range(512)])
    xf = np.asarray(x, np.float32)
    lof = np.asarray(lo, np.float32)
    for j in range(x.size):
        uniq = np.unique(draws[:, j])
        assert len(uniq) <= 2, uniq
        assert np.all((uniq >= min(lof[j], xf[j]) - abs(xf[j]) / 128)
                      & (uniq <= max(lof[j], xf[j]) + abs(xf[j]) / 128))
    # unbiasedness: the mean must be much closer to x than the worst-case
    # round-to-nearest error (bf16 ulp/2 ~ x/512)
    mean = draws.mean(0)
    for j in range(3):  # skip the subnormal-ish 1e-20
        assert abs(mean[j] - xf[j]) < abs(xf[j]) / 1500, (j, mean[j], xf[j])
