"""Fused CE head kernel (ops/fused_ce.py) vs the XLA oracle
(ops/loss.py _token_nll): forward statistics, gradients through both
hidden and the head table, ignore_index handling, and the chunked-CE
integration equivalence."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.ops.fused_ce import (fused_ce_eligible,
                                              fused_ce_nll_sum,
                                              fused_ce_rows, pick_block_v)
from mobilefinetuner_tpu.ops.loss import _token_nll


def make(R=64, V=512, H=96, seed=0, dtype=jnp.float32):
    rng = np.random.default_rng(seed)
    h = jnp.asarray(rng.normal(size=(R, H)), dtype)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.05, dtype)
    lab = jnp.asarray(rng.integers(0, V, (R,)), jnp.int32)
    return h, w, lab


def oracle(h, w, lab):
    logits = (h.astype(jnp.float32) @ w.astype(jnp.float32).T)
    lse = jax.nn.logsumexp(logits, axis=-1)
    gold = jnp.take_along_axis(logits, lab[:, None], axis=-1)[:, 0]
    return lse, gold


def test_eligibility():
    # Gemma shapes at the bench row sizes must be eligible, tiles must
    # divide V, and the VMEM budget must bind (bigger R -> smaller tile)
    bv_small = pick_block_v(262144, R=512, H=640)
    bv_big = pick_block_v(262144, R=1024, H=640)
    assert bv_small and 262144 % bv_small == 0
    assert bv_big and bv_big <= bv_small
    # [R, H] blocks that cannot fit VMEM at any tile -> ineligible (the
    # XLA path takes over)
    assert pick_block_v(262144, R=2048, H=1152) is None
    assert pick_block_v(512, R=64, H=96) == 512
    assert pick_block_v(500, R=64, H=96) is None
    assert fused_ce_eligible(64, 512, 96)
    assert not fused_ce_eligible(63, 512, 96)
    assert not fused_ce_eligible(64, 500, 96)


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
def test_forward_matches_oracle(dtype):
    h, w, lab = make(dtype=dtype)
    lse, gold = jax.jit(fused_ce_rows)(h, w, lab)
    lse_o, gold_o = oracle(h, w, lab)
    tol = 1e-5 if dtype == jnp.float32 else 2e-2
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_o),
                               rtol=tol, atol=tol)
    np.testing.assert_allclose(np.asarray(gold), np.asarray(gold_o),
                               rtol=tol, atol=tol)


def test_multi_tile_online_softmax():
    """V = 4 tiles: the running (m, s) rescale across tiles must equal the
    single-pass oracle."""
    h, w, lab = make(R=16, V=1024, H=64, seed=3)
    lse, gold = jax.jit(fused_ce_rows)(h, w, lab)
    lse_o, gold_o = oracle(h, w, lab)
    np.testing.assert_allclose(np.asarray(lse), np.asarray(lse_o),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gold), np.asarray(gold_o),
                               rtol=1e-5, atol=1e-5)


def test_gradients_match_oracle():
    h, w, lab = make(R=32, V=512, H=64, seed=1)

    def loss_fused(h, w):
        lse, gold = fused_ce_rows(h, w, lab)
        return (lse - gold).sum()

    def loss_ref(h, w):
        lse, gold = oracle(h, w, lab)
        return (lse - gold).sum()

    gf_h, gf_w = jax.grad(loss_fused, argnums=(0, 1))(h, w)
    gr_h, gr_w = jax.grad(loss_ref, argnums=(0, 1))(h, w)
    np.testing.assert_allclose(np.asarray(gf_h), np.asarray(gr_h),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gf_w), np.asarray(gr_w),
                               rtol=1e-4, atol=1e-5)


def test_nll_sum_ignore_index_matches_token_nll():
    rng = np.random.default_rng(5)
    B, C, H, V = 4, 16, 64, 512
    h = jnp.asarray(rng.normal(size=(B, C, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.05, jnp.float32)
    lab = rng.integers(0, V, (B, C))
    lab[0, :5] = -100
    lab[2, -3:] = -100
    lab = jnp.asarray(lab, jnp.int32)
    s, c = jax.jit(fused_ce_nll_sum,
                   static_argnums=3)(h, w, lab, -100)
    logits = jnp.einsum("bch,vh->bcv", h, w)
    nll, valid = _token_nll(logits, lab, -100)
    assert int(c) == int(valid.sum())
    np.testing.assert_allclose(float(s), float(nll.sum()), rtol=1e-5)


def test_chunked_ce_kernel_dispatch_matches_xla():
    """chunked_lm_cross_entropy with the kernel forced on equals the XLA
    path (value and gradient) on an eligible shape."""
    from mobilefinetuner_tpu.ops import loss as loss_mod
    rng = np.random.default_rng(7)
    B, S, H, V = 2, 33, 64, 512   # S-1 = 32 -> chunk 16, R = 32
    h = jnp.asarray(rng.normal(size=(B, S, H)), jnp.float32)
    w = jnp.asarray(rng.normal(size=(V, H)) * 0.05, jnp.float32)
    lab = jnp.asarray(rng.integers(0, V, (B, S)), jnp.int32)

    def f(use_kernel):
        def loss(h, w):
            return loss_mod.chunked_lm_cross_entropy(
                h, w, lab, num_chunks=2, use_fused_kernel=use_kernel)
        return jax.value_and_grad(loss, argnums=(0, 1))(h, w)

    (v_k, (gh_k, gw_k)) = f(True)
    (v_x, (gh_x, gw_x)) = f(False)
    np.testing.assert_allclose(float(v_k), float(v_x), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gh_k), np.asarray(gh_x),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gw_k), np.asarray(gw_x),
                               rtol=1e-4, atol=1e-5)
