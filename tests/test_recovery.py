"""Round-15 numerical-fault recovery + checkpoint-integrity lineage
(DESIGN.md §20): the in-jit skip-step guard, the divergence→rollback
loop in run_training, the per-tensor checksum manifest + lineage
fallback on every load path, the AsyncCheckpointer drain timeout, and
the fault-injection e2e that drives skip → rollback → in-process resume
through one schema-valid telemetry stream."""

import dataclasses
import json
import os
import sys
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
from fixtures import write_tiny_gpt2_dir, write_wikitext_dir

from mobilefinetuner_tpu.core.config import GPT2Config
from mobilefinetuner_tpu.io.checkpoints import (lineage_entries,
                                                lineage_step_for,
                                                record_checkpoint,
                                                resolve_checkpoint)
from mobilefinetuner_tpu.io.safetensors_io import (CheckpointIntegrityError,
                                                   SafeTensorsReader,
                                                   manifest_path,
                                                   save_safetensors,
                                                   verify_report)
from mobilefinetuner_tpu.lora.lora import (LoRASpec, init_lora_gpt2,
                                           trainable_mask)
from mobilefinetuner_tpu.models import gpt2
from mobilefinetuner_tpu.ops.loss import lm_cross_entropy_sum
from mobilefinetuner_tpu.train.trainer import (TrainConfig, init_optimizer,
                                               make_train_step)

CFG = GPT2Config.tiny()


def _bitflip(path, offset=-1):
    with open(path, "r+b") as f:
        f.seek(offset, os.SEEK_END)
        b = f.read(1)
        f.seek(offset, os.SEEK_END)
        f.write(bytes([b[0] ^ 0xFF]))


def read_events(path):
    with open(path) as f:
        return [json.loads(l) for l in f.read().splitlines() if l.strip()]


# --------------------------- in-jit skip-step -------------------------------

def _problem():
    params = gpt2.init_params(CFG, jax.random.PRNGKey(0))
    lora = init_lora_gpt2(CFG, LoRASpec(rank=4, alpha=8.0),
                          jax.random.PRNGKey(1))
    rng = np.random.default_rng(0)
    ids = jnp.array(rng.integers(0, CFG.vocab_size, size=(4, 16)))
    batch = {"input_ids": ids, "attention_mask": jnp.ones_like(ids),
             "labels": ids, "grad_scale": jnp.ones(4, jnp.float32)}
    return params, lora, batch


def _loss_fn(lora, params, mb):
    logits = gpt2.forward(CFG, params, mb["input_ids"],
                          attention_mask=mb["attention_mask"], lora=lora)
    return lm_cross_entropy_sum(logits, mb["labels"])


def test_skip_nonfinite_guard_is_identity_on_nan_grads():
    """NaN grads under the guard: params, Adam m/v AND Adam's step
    counter pass through bit-identical, the skipped/nonfinite metrics
    fire, and the loss metric stays what the forward computed."""
    params, lora, batch = _problem()
    tc = TrainConfig(total_steps=5, lr=1e-3, warmup_ratio=0.0,
                     schedule="constant", skip_nonfinite=True)
    mask = trainable_mask(lora)
    step_fn = make_train_step(_loss_fn, tc, mask=mask, donate=False)
    opt = init_optimizer(lora, tc, mask)
    lora1, opt1, m1 = step_fn(lora, params, opt, batch, jnp.int32(0))
    assert int(m1["skipped"]) == 0 and int(m1["nonfinite_count"]) == 0
    bad = dict(batch, grad_scale=jnp.full(4, np.nan, jnp.float32))
    lora2, opt2, m2 = step_fn(lora1, params, opt1, bad, jnp.int32(1))
    assert int(m2["skipped"]) == 1
    assert int(m2["nonfinite_count"]) > 0
    assert np.isfinite(float(m2["loss"]))  # loss itself was clean
    for a, b in zip(jax.tree.leaves(lora2), jax.tree.leaves(lora1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    for a, b in zip(jax.tree.leaves(opt2), jax.tree.leaves(opt1)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert int(opt2["step"]) == int(opt1["step"])  # no bias-corr drift


def test_skip_nonfinite_guard_is_free_on_clean_steps():
    """Zero-overhead contract: with finite grads the guarded step's
    outputs are BIT-identical to the unguarded step's."""
    params, lora, batch = _problem()
    tc = TrainConfig(total_steps=5, lr=1e-3, warmup_ratio=0.0,
                     schedule="constant", skip_nonfinite=True)
    tc0 = dataclasses.replace(tc, skip_nonfinite=False)
    mask = trainable_mask(lora)
    sg = make_train_step(_loss_fn, tc, mask=mask, donate=False)
    s0 = make_train_step(_loss_fn, tc0, mask=mask, donate=False)
    lg, og = lora, init_optimizer(lora, tc, mask)
    l0, o0 = lora, init_optimizer(lora, tc0, mask)
    for s in range(3):
        lg, og, mg = sg(lg, params, og, batch, jnp.int32(s))
        l0, o0, m0 = s0(l0, params, o0, batch, jnp.int32(s))
        assert float(mg["loss"]) == float(m0["loss"])
    for a, b in zip(jax.tree.leaves(lg), jax.tree.leaves(l0)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


# --------------------------- drain timeout ----------------------------------

def test_drain_timeout_names_the_inflight_step():
    """Satellite: a wedged background write makes drain(timeout) raise
    the NAMED CheckpointDrainTimeout identifying the in-flight step,
    and close(raise_errors=False) abandons the writer promptly instead
    of stalling shutdown on a 30 s join."""
    from mobilefinetuner_tpu.io.async_ckpt import (AsyncCheckpointer,
                                                   CheckpointDrainTimeout)
    release = threading.Event()
    ck = AsyncCheckpointer(enabled=True)

    def blocked_write():
        release.wait(10.0)
        return []

    ck.save(7, blocked_write)
    with pytest.raises(CheckpointDrainTimeout) as ei:
        ck.drain(timeout=0.1)
    assert ei.value.step == 7
    assert "step 7" in str(ei.value)
    t0 = time.perf_counter()
    ck.close(raise_errors=False, drain_timeout=0.1)
    assert time.perf_counter() - t0 < 5.0, "close stalled on wedged writer"
    release.set()


def test_drain_completes_without_timeout_error():
    from mobilefinetuner_tpu.io.async_ckpt import AsyncCheckpointer
    ck = AsyncCheckpointer(enabled=True)
    ck.save(1, lambda: [])
    ck.drain(timeout=10.0)  # finishes fine, no raise
    ck.close()


# --------------------------- divergence detector ----------------------------

def test_spike_detector_escalates_to_divergence():
    """Satellite: one-off excursions stay kind=loss_spike; a SUSTAINED
    level-shift (divergence_run consecutive spiking steps) escalates to
    kind=divergence — the rollback trigger — and transient spikes with
    clean steps between them never do."""
    from mobilefinetuner_tpu.core.telemetry import SpikeConfig, SpikeDetector
    det = SpikeDetector(SpikeConfig(zscore=4.0, beta=0.9, warmup=5,
                                    divergence_run=3))
    rng = np.random.default_rng(0)
    for _ in range(50):
        det.update(3.0 + 0.05 * rng.standard_normal())
    # transient: spike, recover, spike — never divergence
    kinds = []
    for loss in (9.0, 3.0, 9.0, 3.0):
        a = det.update(loss)
        if a:
            kinds.append(a["kind"])
    assert kinds == ["loss_spike", "loss_spike"]
    # sustained: three consecutive spiking steps escalate
    kinds = []
    for loss in (9.0, 9.0, 9.0):
        a = det.update(loss)
        if a:
            kinds.append(a["kind"])
    assert kinds == ["loss_spike", "loss_spike", "divergence"]


# --------------------------- manifest + lineage -----------------------------

def test_manifest_written_and_verifies(tmp_path):
    p = str(tmp_path / "t.safetensors")
    save_safetensors(p, {"x": np.arange(8, dtype=np.float32)})
    assert os.path.exists(manifest_path(p))
    assert verify_report(p) == ("ok", None)


def test_verify_catches_bitflip_truncation_missing_stale(tmp_path):
    t = {"x": np.arange(8, dtype=np.float32), "y": np.ones(3, np.int32)}
    p = str(tmp_path / "t.safetensors")
    # bit-flipped payload
    save_safetensors(p, t)
    _bitflip(p)
    status, reason = verify_report(p)
    assert status == "corrupt" and "mismatch" in reason
    # truncated file
    save_safetensors(p, t)
    data = open(p, "rb").read()
    open(p, "wb").write(data[:-5])
    assert verify_report(p)[0] == "corrupt"
    # missing manifest -> unverified (legacy), loadable only last-resort
    save_safetensors(p, t)
    os.unlink(manifest_path(p))
    assert verify_report(p) == ("unverified", "manifest_missing")
    # stale manifest (from a different tensor set)
    save_safetensors(p, {"z": np.zeros(2, np.float32)})
    save_safetensors(str(tmp_path / "other.safetensors"), t)
    os.replace(manifest_path(str(tmp_path / "other.safetensors")),
               manifest_path(p))
    status, reason = verify_report(p)
    assert status == "corrupt" and reason == "manifest_stale"


def _mk_lineage(d, steps, keep=0):
    final = os.path.join(d, "a.safetensors")
    paths = {}
    for s in steps:
        p = os.path.join(d, f"a_step{s}.safetensors")
        save_safetensors(p, {"x": np.full(4, s, np.float32)})
        save_safetensors(p + ".opt", {"step": np.int32(s)})
        record_checkpoint(final, s, [p, p + ".opt"], keep=keep)
        paths[s] = p
    return final, paths


def test_lineage_gc_retains_keep_newest(tmp_path):
    final, paths = _mk_lineage(str(tmp_path), [2, 4, 6, 8], keep=2)
    ents = lineage_entries(final)
    assert [e["step"] for e in ents] == [8, 6]
    assert not os.path.exists(paths[2]) and not os.path.exists(paths[4])
    assert not os.path.exists(manifest_path(paths[2]))
    # every retained entry is loadable + verified
    for e in ents:
        for f in e["files"]:
            assert verify_report(f) == ("ok", None)
    assert lineage_step_for(paths[8]) == 8


def test_lineage_fallback_on_corrupt_newest(tmp_path):
    """Acceptance: corrupted newest checkpoint (bit-flip) resolves to
    the previous lineage entry with ckpt_verify evidence — never a
    crash, never a silent load."""
    final, paths = _mk_lineage(str(tmp_path), [2, 4, 6])
    _bitflip(paths[6])
    r, step, events = resolve_checkpoint(paths[6])
    assert r == paths[4] and step == 4
    assert events[0]["ok"] is False and "mismatch" in events[0]["reason"]
    assert events[-1]["ok"] is True and events[-1]["path"] == paths[4]


def test_lineage_fallback_on_truncation_and_missing_manifest(tmp_path):
    final, paths = _mk_lineage(str(tmp_path), [2, 4, 6])
    # truncated newest
    data = open(paths[6], "rb").read()
    open(paths[6], "wb").write(data[: len(data) // 2])
    r, step, ev = resolve_checkpoint(None, lineage_base=final)
    assert r == paths[4] and step == 4
    assert any(not e["ok"] for e in ev)
    # missing manifest on the (new) newest: falls to the verified older
    os.unlink(manifest_path(paths[4]))
    r2, step2, ev2 = resolve_checkpoint(None, lineage_base=final)
    assert r2 == paths[2] and step2 == 2
    # ... but when NOTHING verifies, the unverified one is the last
    # resort (legacy pre-manifest checkpoints keep loading)
    os.unlink(manifest_path(paths[2]))
    os.unlink(manifest_path(paths[2] + ".opt"))
    os.unlink(manifest_path(paths[4] + ".opt"))
    r3, step3, ev3 = resolve_checkpoint(None, lineage_base=final)
    assert r3 == paths[4] and ev3[-1]["reason"] == "loaded_unverified"


def test_lineage_survives_interrupted_gc(tmp_path):
    """SIGKILL-during-GC contract: the pruned lineage publishes BEFORE
    any unlink, so both crash windows leave a loadable retained set —
    (a) lineage updated + pruned files still on disk (orphans), and
    (b) pruned files gone while the lineage already stopped naming
    them. A lineage entry whose files were lost anyway (external
    deletion) is skipped, not fatal."""
    final, paths = _mk_lineage(str(tmp_path), [2, 4, 6])
    # window (a): hand-publish a pruned lineage, leave "pruned" files
    entries = [{"step": e["step"],
                "files": [os.path.basename(f) for f in e["files"]]}
               for e in lineage_entries(final) if e["step"] > 2]
    with open(final + ".lineage.json", "w") as f:
        json.dump({"version": 1, "entries": entries}, f)
    r, step, _ = resolve_checkpoint(None, lineage_base=final)
    assert r == paths[6] and step == 6  # orphan at step 2 is invisible
    # window (b): a named file vanished before the next lineage rewrite
    os.unlink(paths[6])
    r2, step2, ev = resolve_checkpoint(None, lineage_base=final)
    assert r2 == paths[4] and step2 == 4
    assert any(e["reason"] and "missing_file" in e["reason"] for e in ev)


def test_resolve_verify_off_still_walks_lineage(tmp_path):
    """Regression: --verify_ckpt 0 means 'trust the newest file', NOT
    'disable rollback' — a lineage-only resolution (path=None, the
    rollback caller) must still return the newest existing entry, and
    max_step must still filter."""
    final, paths = _mk_lineage(str(tmp_path), [2, 4, 6])
    r, step, ev = resolve_checkpoint(None, verify=False,
                                     lineage_base=final)
    assert r == paths[6] and step == 6 and ev == []
    r2, step2, _ = resolve_checkpoint(None, verify=False,
                                      lineage_base=final, max_step=5)
    assert r2 == paths[4] and step2 == 4
    os.unlink(paths[6])  # a vanished newest entry is skipped, unverified
    r3, step3, _ = resolve_checkpoint(None, verify=False,
                                      lineage_base=final)
    assert r3 == paths[4] and step3 == 4


def test_spike_detector_stays_armed_after_count_hint_seed():
    """Regression: a rollback re-arms the detector with
    seed([], count_hint=step) — no losses to feed. The first observed
    loss afterwards must not reset the observation count into warmup,
    or a divergence recurring right after the rollback goes unseen."""
    from mobilefinetuner_tpu.core.telemetry import SpikeConfig, SpikeDetector
    det = SpikeDetector(SpikeConfig(zscore=4.0, beta=0.9, warmup=20,
                                    divergence_run=2))
    det.seed([], count_hint=50)
    rng = np.random.default_rng(1)
    for _ in range(6):  # enough to build variance, far below warmup
        det.update(3.0 + 0.05 * rng.standard_normal())
    assert det.count > 50  # never re-entered warmup
    kinds = [a["kind"] for a in (det.update(9.0), det.update(9.0)) if a]
    assert kinds == ["loss_spike", "divergence"]


def test_grad_scale_shards_batch_only_under_sequence_parallel():
    """Regression: the fault harness's [B] grad_scale row must take the
    batch-only spec under --sequence_parallel (the rank-2 S-sharding
    spec would reject a rank-1 leaf at placement)."""
    from mobilefinetuner_tpu.parallel.mesh import (make_batch_placer,
                                                   make_mesh)
    mesh = make_mesh(data=2, fsdp=4, devices=jax.devices()[:8])
    place = make_batch_placer(mesh, sequence_parallel=True)
    ids = np.zeros((4, 8), np.int32)
    batch = place({"input_ids": ids, "attention_mask": ids,
                   "labels": ids,
                   "grad_scale": np.ones(4, np.float32)})
    assert batch["grad_scale"].shape == (4,)


def test_resolve_raises_named_error_when_nothing_loadable(tmp_path):
    final, paths = _mk_lineage(str(tmp_path), [2])
    _bitflip(paths[2])
    with pytest.raises(CheckpointIntegrityError):
        resolve_checkpoint(paths[2])


# --------------------------- serve adapter verify ---------------------------

def test_adapter_bank_refuses_corrupt_file(tmp_path):
    """Satellite: AdapterBank.load_file verifies the checksum manifest
    BEFORE hot-swapping — a corrupt tenant adapter raises the NAMED
    CheckpointIntegrityError with the reason, and no slot changes."""
    from mobilefinetuner_tpu.lora import peft_io
    from mobilefinetuner_tpu.serve.adapters import AdapterBank
    spec = LoRASpec(rank=4, alpha=8.0)
    tree = init_lora_gpt2(CFG, spec, jax.random.PRNGKey(3))
    path = str(tmp_path / "tenant.safetensors")
    peft_io.save_adapter(path, tree, spec)
    bank = AdapterBank(tree, capacity=2)
    assert bank.load_file("good", path) == 0  # clean file loads
    _bitflip(path)
    before = [np.asarray(x).copy() for x in jax.tree.leaves(bank.tree)]
    with pytest.raises(CheckpointIntegrityError) as ei:
        bank.load_file("evil", path)
    assert "mismatch" in str(ei.value) or "manifest" in str(ei.value)
    assert "evil" not in bank.resident
    for a, b in zip(jax.tree.leaves(bank.tree), before):
        np.testing.assert_array_equal(np.asarray(a), b)
    # verify=False is the explicit trusted-artifact opt-out... but the
    # flipped payload now fails at parse or loads garbage knowingly —
    # just assert the named error is specific to verification
    missing = str(tmp_path / "gone.safetensors")
    with pytest.raises(CheckpointIntegrityError):
        bank.load_file("ghost", missing)


# --------------------------- report recovery section ------------------------

def test_report_renders_recovery_section(tmp_path):
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import telemetry_report
    from mobilefinetuner_tpu.core.telemetry import Telemetry
    path = str(tmp_path / "r.jsonl")
    with Telemetry(path) as tel:
        tel.emit("run_start", jax_version="0", mesh_shape=None,
                 process_count=1, process_index=0, device_kind="cpu",
                 device_count=1, config={})
        tel.emit("step_stats", step=1, loss=3.0, ema=3.0, lr=1e-4,
                 grad_norm=1.0, step_time_ms=1.0, host_wait_ms=0.0,
                 slept_ms=0.0, tok_s=10.0, mfu=None, param_norm=1.0,
                 update_ratio=1e-3, nonfinite_count=4, skipped=2,
                 hbm_mb=1.0, queue_depth=0, host_step_ms=None)
        tel.emit("ckpt_verify", path="/x/a_step6.safetensors", ok=False,
                 reason="checksum_mismatch:x", step=6, action="reject")
        tel.emit("ckpt_verify", path="/x/a_step4.safetensors", ok=True,
                 reason=None, step=4, action="load")
        tel.emit("rollback", step=8, reason="divergence", ok=True,
                 to_step=4, steps_lost=4, ckpt="/x/a_step4.safetensors",
                 data_offset=1, budget_left=0)
        tel.emit("run_end", steps=10, wall_s=1.0, exit="ok",
                 goodput=None, reason=None)
    events, bad = telemetry_report.load_events(path)
    assert bad == 0
    s = telemetry_report.summarize(events)
    r = s["recovery"]
    assert r["skipped_steps"] == 2
    assert r["steps_lost"] == 4
    assert len(r["rollbacks"]) == 1 and r["rollbacks"][0]["ok"]
    assert len(r["ckpt_verify_failures"]) == 1
    assert r["ckpt_verified"] == 1
    lines = telemetry_report.recovery_lines(r)
    joined = "\n".join(lines)
    assert "ROLLBACK (divergence)" in joined
    assert "CKPT REJECTED" in joined
    # a stream with none of the three renders nothing
    assert telemetry_report.recovery_summary(
        [e for e in events if e["event"] == "run_end"]) is None


# --------------------------- e2e fault injection ----------------------------

@pytest.fixture(scope="module")
def gpt2_dir(tmp_path_factory):
    d = tmp_path_factory.mktemp("gpt2ckpt")
    write_tiny_gpt2_dir(str(d))
    return str(d)


@pytest.fixture(scope="module")
def wiki_dir(tmp_path_factory):
    return write_wikitext_dir(str(tmp_path_factory.mktemp("wt2")))


def test_e2e_grad_nan_skip_rollback_resume(gpt2_dir, wiki_dir, tmp_path):
    """Acceptance e2e: --inject grad_nan mid-run skips the poisoned
    updates, rolls back at the skip-streak threshold to a VERIFIED
    lineage checkpoint, resumes in-process (no restart, no recompile),
    and ends with run_end{exit=ok} in ONE schema-valid stream with
    monotonic seq — and the final adapter is parity-pinned bit-exact
    against a clean run resumed from the same checkpoint over the same
    post-rollback batch sequence."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    from mobilefinetuner_tpu.core.telemetry import validate_event
    out = str(tmp_path / "a.safetensors")
    telem = str(tmp_path / "run.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "12", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", out, "--save_every", "2", "--keep_ckpts", "4",
               "--skip_nonfinite", "1", "--rollback_budget", "2",
               "--rollback_skip_streak", "3", "--rollback_data_offset", "0",
               "--inject", "grad_nan:5:3", "--telemetry_out", telem])
    assert rc == 0
    evs = read_events(telem)
    for e in evs:
        assert validate_event(e) is None, (e, validate_event(e))
    seqs = [e["seq"] for e in evs]
    assert all(a < b for a, b in zip(seqs, seqs[1:]))
    ends = [e for e in evs if e["event"] == "run_end"]
    assert len(ends) == 1 and ends[0]["exit"] == "ok"
    # the guard skipped the whole poison window
    skipped = sum(e.get("skipped") or 0 for e in evs
                  if e["event"] == "step_stats")
    assert skipped == 3
    rbs = [e for e in evs if e["event"] == "rollback"]
    assert len(rbs) == 1 and rbs[0]["ok"] is True
    assert rbs[0]["reason"] == "skip_streak"
    to_step = rbs[0]["to_step"]
    assert to_step < rbs[0]["step"]
    vfy = [e for e in evs if e["event"] == "ckpt_verify"]
    assert vfy and vfy[-1]["ok"] is True
    ckpt = rbs[0]["ckpt"]
    assert os.path.exists(ckpt)
    # loop_step metadata vs Adam's counter: the sidecar of the rollback
    # target records the LOOP step; Adam lags it by the skipped updates
    md = SafeTensorsReader(ckpt + ".opt").metadata
    assert int(md["loop_step"]) == to_step
    adam_step = int(SafeTensorsReader(ckpt + ".opt").load_all()["step"])
    assert adam_step <= to_step
    # post-rollback losses are finite and the stream shows recovery
    last_stats = [e for e in evs if e["event"] == "step_stats"][-1]
    assert last_stats["loss"] is not None
    # parity pin: a clean run resumed from the SAME checkpoint over the
    # same post-rollback batch sequence produces the SAME final adapter
    out_b = str(tmp_path / "b.safetensors")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "12", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", out_b, "--skip_nonfinite", "1",
               "--resume_from", ckpt])
    assert rc == 0
    a = SafeTensorsReader(out).load_all()
    b = SafeTensorsReader(out_b).load_all()
    assert set(a) == set(b)
    for k in a:
        np.testing.assert_array_equal(a[k], b[k], err_msg=k)


def test_e2e_skip_guard_zero_overhead_parity(gpt2_dir, wiki_dir, tmp_path):
    """Acceptance: a clean run with --skip_nonfinite enabled is
    byte-identical in loss trajectory (and final adapter) to one
    without the guard."""
    import csv as csv_mod
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    losses, adapters = [], []
    for i, flag in enumerate(("1", "0")):
        out = str(tmp_path / f"p{i}.safetensors")
        csvp = str(tmp_path / f"m{i}.csv")
        rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
                   "--steps", "4", "--batch_size", "2", "--seq_len", "32",
                   "--lora_out", out, "--skip_nonfinite", flag,
                   "--metrics_csv", csvp])
        assert rc == 0
        with open(csvp) as f:
            losses.append([float(r["loss"])
                           for r in csv_mod.DictReader(f)])
        adapters.append(SafeTensorsReader(out).load_all())
    assert losses[0] == losses[1]
    for k in adapters[0]:
        np.testing.assert_array_equal(adapters[0][k], adapters[1][k])


def test_e2e_failed_rollback_fires_once_per_episode(gpt2_dir, wiki_dir,
                                                    tmp_path):
    """A triggered rollback with NO checkpoint to roll back to emits
    ONE rollback{ok=false} for the whole bad episode (suppressed until
    a clean step), not one per step — the stream-sizing rule."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    telem = str(tmp_path / "nockpt.jsonl")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "10", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", str(tmp_path / "n.safetensors"),
               "--skip_nonfinite", "1", "--rollback_budget", "2",
               "--rollback_skip_streak", "2",
               "--inject", "grad_nan:2:6", "--telemetry_out", telem])
    assert rc == 0
    evs = read_events(telem)
    rbs = [e for e in evs if e["event"] == "rollback"]
    assert len(rbs) == 1 and rbs[0]["ok"] is False, rbs
    assert [e for e in evs if e["event"] == "run_end"][0]["exit"] == "ok"


def test_gemma_opt_offload_refuses_recovery_flags(tmp_path):
    """--skip_nonfinite/--rollback_budget must refuse loudly under
    --opt_offload (the offloaded update has no guarded path), never
    silently void the safety promise."""
    from fixtures import write_tiny_gemma3_dir
    from mobilefinetuner_tpu.cli.gemma_full_finetune import main
    gdir = str(tmp_path / "g")
    write_tiny_gemma3_dir(gdir)
    wdir = write_wikitext_dir(str(tmp_path / "w"))
    with pytest.raises(SystemExit, match="opt_offload"):
        main(["--model_dir", gdir, "--data_dir", wdir,
              "--steps", "1", "--batch_size", "2", "--seq_len", "32",
              "--opt_offload", "--skip_nonfinite", "1",
              "--output_path", str(tmp_path / "x.safetensors")])


def test_e2e_resume_from_corrupt_final_falls_back(gpt2_dir, wiki_dir,
                                                  tmp_path):
    """Acceptance: a corrupted newest checkpoint at --resume_from
    resolves to the previous lineage entry, emits ckpt_verify into the
    resumed run's stream, and the run completes — never a crash or a
    silent load of the corrupt file."""
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    out = str(tmp_path / "a.safetensors")
    main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
          "--steps", "6", "--batch_size", "2", "--seq_len", "32",
          "--lora_out", out, "--save_every", "2", "--keep_ckpts", "3"])
    _bitflip(out)  # corrupt the newest (final) checkpoint
    telem = str(tmp_path / "resume.jsonl")
    out2 = str(tmp_path / "b.safetensors")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki_dir,
               "--steps", "8", "--batch_size", "2", "--seq_len", "32",
               "--lora_out", out2, "--resume_from", out,
               "--telemetry_out", telem])
    assert rc == 0
    evs = read_events(telem)
    assert evs[0]["event"] == "run_start"  # verdicts never precede it
    vfy = [e for e in evs if e["event"] == "ckpt_verify"]
    assert vfy[0]["ok"] is False and out in vfy[0]["path"]
    accepted = [e for e in vfy if e["ok"]]
    assert accepted and accepted[0]["step"] == 4  # newest verified entry
    # the resumed run continued from step 4 to 8
    ends = [e for e in evs if e["event"] == "run_end"]
    assert ends[0]["exit"] == "ok" and ends[0]["steps"] == 4
