"""Pallas flash-attention kernel vs the XLA oracle (ops/attention.py) —
forward AND backward (the reference's kernel is forward-only, SURVEY.md
§2.12.1; ours must match the oracle's gradients too). Runs in Pallas
interpret mode on the CPU test mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.ops.attention import dot_product_attention
from mobilefinetuner_tpu.ops.flash_attention import flash_attention


def make_qkv(key, B=2, Hq=4, Hkv=2, S=128, D=64, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (B, Hq, S, D), dtype)
    k = jax.random.normal(k2, (B, Hkv, S, D), dtype)
    v = jax.random.normal(k3, (B, Hkv, S, D), dtype)
    return q, k, v


CASES = [
    dict(),                                   # causal, MHA-as-GQA
    dict(sliding_window=32),                  # local attention
    dict(Hkv=1),                              # extreme GQA (Gemma-270M)
    dict(scale=0.25),                         # explicit scale
    dict(D=128),
    # 64-blocks at S=256: 4x4 block grid — exercises qi>0 row offsets, the
    # multi-iteration online-softmax k-loop, and causal block skipping
    # (default 512-blocks would degenerate these to a single block)
    dict(S=256, block=64),
    dict(S=256, Hkv=1, sliding_window=64, block=64),
    dict(S=256, sliding_window=96, block=64),  # window not block-aligned
]


@pytest.mark.parametrize("case", CASES)
def test_forward_matches_oracle(case):
    case = dict(case)
    kw = {k: case.pop(k) for k in ("sliding_window", "scale")
          if k in case}
    bkw = {}
    if "block" in case:
        b = case.pop("block")
        bkw = dict(block_q=b, block_k=b)
    q, k, v = make_qkv(jax.random.PRNGKey(0), **case)
    ours = flash_attention(q, k, v, is_causal=True, **kw, **bkw)
    ref = dot_product_attention(q, k, v, is_causal=True, **kw)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_valid_blocks_covers_odd_lengths():
    """Raising the default block must not drop previously-supported S off
    the kernel, and every picked block must satisfy the Mosaic alignment
    rules (block_q % 8, block_k % 128 or whole-S static block)."""
    from mobilefinetuner_tpu.ops.flash_attention import _valid_blocks
    assert _valid_blocks(1280, 512, 512) == (256, 256)
    assert _valid_blocks(1024, 512, 512) == (512, 512)
    assert _valid_blocks(1664, 512, 512) == (128, 128)
    # short/odd S: whole-S single block (statically indexed)
    assert _valid_blocks(64, 512, 512) == (64, 64)
    assert _valid_blocks(192, 512, 512) == (192, 192)
    # not 8-aligned -> XLA fallback
    assert _valid_blocks(130, 512, 512) is None
    # 8-aligned but no 128-divisor and > 1024: whole-S block would blow
    # VMEM -> fallback (1288 % 8 == 0, 1288 % 128 != 0)
    assert _valid_blocks(1288, 512, 512) is None
    for S in (256, 512, 1024, 2048):
        bq, bk = _valid_blocks(S, 512, 512)
        assert bq % 8 == 0 and (bk % 128 == 0 or bk == S)


def test_forward_with_padding_mask():
    q, k, v = make_qkv(jax.random.PRNGKey(1))
    B, S = q.shape[0], q.shape[2]
    pad = np.ones((B, S), np.float32)
    pad[0, 100:] = 0.0
    pad[1, 64:] = 0.0
    pad = jnp.asarray(pad)
    # 64-blocks: padding boundary (100) falls inside a block AND whole
    # blocks (cols >= 128 for row < 64 via causal) are skipped
    ours = flash_attention(q, k, v, padding_mask=pad, block_q=64,
                           block_k=64)
    ref = dot_product_attention(q, k, v, padding_mask=pad)
    # compare only valid query rows (padded queries are don't-cares and the
    # ref puts uniform-softmax garbage there; ours puts zeros)
    np.testing.assert_allclose(np.asarray(ours)[0, :, :100],
                               np.asarray(ref)[0, :, :100],
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(ours)[1, :, :64],
                               np.asarray(ref)[1, :, :64],
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("case", [dict(), dict(sliding_window=32),
                                  dict(Hkv=1),
                                  # 64-blocks: exercise the qi>0 offsets,
                                  # the dKdV kernel's q-block loop bounds,
                                  # and GQA group-head accumulation
                                  dict(S=256, Hkv=2, block=64),
                                  dict(S=256, Hkv=1, sliding_window=64,
                                       block=64)])
def test_gradients_match_oracle(case):
    case = dict(case)
    kw = {k: case.pop(k) for k in ("sliding_window",) if k in case}
    bkw = {}
    if "block" in case:
        b = case.pop("block")
        bkw = dict(block_q=b, block_k=b)
    q, k, v = make_qkv(jax.random.PRNGKey(2), **case)

    def loss(fn, q, k, v):
        extra = bkw if fn is flash_attention else {}
        out = fn(q, k, v, is_causal=True, **kw, **extra)
        return jnp.sum(out * jnp.cos(out))  # nontrivial cotangent

    g_ours = jax.grad(functools.partial(loss, flash_attention),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(functools.partial(loss, dot_product_attention),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ours, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=name)


def test_gradients_with_padding_mask():
    q, k, v = make_qkv(jax.random.PRNGKey(3))
    B, S = q.shape[0], q.shape[2]
    pad = np.ones((B, S), np.float32)
    pad[:, 96:] = 0.0
    pad = jnp.asarray(pad)
    valid = pad.astype(bool)[:, None, :, None]

    def loss(fn, q, k, v):
        kw = {"block_q": 64, "block_k": 64} if fn is flash_attention else {}
        out = fn(q, k, v, is_causal=True, padding_mask=pad, **kw)
        return jnp.sum(jnp.where(valid, out, 0.0) ** 2)

    g_ours = jax.grad(functools.partial(loss, flash_attention),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(functools.partial(loss, dot_product_attention),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ours, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=name)


def test_unsupported_shapes_fall_back():
    # S=100 not a block multiple; D=8 unsupported -> XLA path, still correct
    q, k, v = make_qkv(jax.random.PRNGKey(4), S=100, D=8)
    ours = flash_attention(q, k, v, is_causal=True)
    ref = dot_product_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_attention_dispatcher_flash():
    from mobilefinetuner_tpu.ops.attention import attention
    q, k, v = make_qkv(jax.random.PRNGKey(5))
    out = attention(q, k, v, impl="flash", is_causal=True)
    ref = attention(q, k, v, impl="xla", is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gpt2_model_flash_matches_xla():
    """Whole-model parity: GPT-2 forward with attention_impl='flash' equals
    the XLA path (flash-eligible head_dim=64)."""
    import dataclasses
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.models import gpt2
    cfg = dataclasses.replace(GPT2Config.tiny(vocab_size=512),
                              n_embd=128, n_head=2, n_positions=128)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 512)
    ref = gpt2.forward(cfg, params, ids)
    cfg_f = dataclasses.replace(cfg, attention_impl="flash")
    out = gpt2.forward(cfg_f, params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_gemma3_model_flash_matches_xla():
    """Gemma: the flash path must reproduce the per-layer global/local mask
    interleave (lax.cond branch) including sliding-window layers."""
    import dataclasses
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.models import gemma3
    cfg = Gemma3TextConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=1,
        head_dim=64, max_position_embeddings=256, sliding_window=32,
        query_pre_attn_scalar=64.0, sliding_window_pattern=3)
    params = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 512)
    mask = jnp.ones((2, 128))
    ref = gemma3.forward(cfg, params, ids, attention_mask=mask)
    cfg_f = dataclasses.replace(cfg, attention_impl="flash")
    out = gemma3.forward(cfg_f, params, ids, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_flash_under_jit_and_scan():
    """The kernel must trace under jit (model stacks run it inside
    lax.scan)."""
    q, k, v = make_qkv(jax.random.PRNGKey(6))

    @jax.jit
    def f(q, k, v):
        return flash_attention(q, k, v, is_causal=True, sliding_window=64)

    ref = dot_product_attention(q, k, v, is_causal=True, sliding_window=64)
    np.testing.assert_allclose(np.asarray(f(q, k, v)), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)
