"""Pallas flash-attention kernel vs the XLA oracle (ops/attention.py) —
forward AND backward (the reference's kernel is forward-only, SURVEY.md
§2.12.1; ours must match the oracle's gradients too). Runs in Pallas
interpret mode on the CPU test mesh."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.ops.attention import dot_product_attention
from mobilefinetuner_tpu.ops.flash_attention import flash_attention


def make_qkv(key, B=2, Hq=4, Hkv=2, S=128, D=64, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (B, Hq, S, D), dtype)
    k = jax.random.normal(k2, (B, Hkv, S, D), dtype)
    v = jax.random.normal(k3, (B, Hkv, S, D), dtype)
    return q, k, v


CASES = [
    dict(),                                   # causal, MHA-as-GQA
    dict(sliding_window=32),                  # local attention
    dict(Hkv=1),                              # extreme GQA (Gemma-270M)
    dict(scale=0.25),                         # explicit scale
    dict(D=128),
    # 64-blocks at S=256: 4x4 block grid — exercises qi>0 row offsets, the
    # multi-iteration online-softmax k-loop, and causal block skipping
    # (default 512-blocks would degenerate these to a single block)
    dict(S=256, block=64),
    dict(S=256, Hkv=1, sliding_window=64, block=64),
    dict(S=256, sliding_window=96, block=64),  # window not block-aligned
]


@pytest.mark.parametrize("case", CASES)
def test_forward_matches_oracle(case):
    case = dict(case)
    kw = {k: case.pop(k) for k in ("sliding_window", "scale")
          if k in case}
    bkw = {}
    if "block" in case:
        b = case.pop("block")
        bkw = dict(block_q=b, block_k=b)
    q, k, v = make_qkv(jax.random.PRNGKey(0), **case)
    ours = flash_attention(q, k, v, is_causal=True, **kw, **bkw)
    ref = dot_product_attention(q, k, v, is_causal=True, **kw)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_valid_blocks_covers_odd_lengths():
    """Raising the default block must not drop previously-supported S off
    the kernel, and every picked block must satisfy the Mosaic alignment
    rules (block_q % 8, block_k % 128 or whole-S static block)."""
    from mobilefinetuner_tpu.ops.flash_attention import _valid_blocks
    assert _valid_blocks(1280, 512, 512) == (256, 256)
    assert _valid_blocks(1024, 512, 512) == (512, 512)
    assert _valid_blocks(1664, 512, 512) == (128, 128)
    # short/odd S: whole-S single block (statically indexed)
    assert _valid_blocks(64, 512, 512) == (64, 64)
    assert _valid_blocks(192, 512, 512) == (192, 192)
    # not 8-aligned -> XLA fallback
    assert _valid_blocks(130, 512, 512) is None
    # 8-aligned but no 128-divisor and > 1024: whole-S block would blow
    # VMEM -> fallback (1288 % 8 == 0, 1288 % 128 != 0)
    assert _valid_blocks(1288, 512, 512) is None
    for S in (256, 512, 1024, 2048):
        bq, bk = _valid_blocks(S, 512, 512)
        assert bq % 8 == 0 and (bk % 128 == 0 or bk == S)


def test_forward_with_padding_mask():
    q, k, v = make_qkv(jax.random.PRNGKey(1))
    B, S = q.shape[0], q.shape[2]
    pad = np.ones((B, S), np.float32)
    pad[0, 100:] = 0.0
    pad[1, 64:] = 0.0
    pad = jnp.asarray(pad)
    # 64-blocks: padding boundary (100) falls inside a block AND whole
    # blocks (cols >= 128 for row < 64 via causal) are skipped
    ours = flash_attention(q, k, v, padding_mask=pad, block_q=64,
                           block_k=64)
    ref = dot_product_attention(q, k, v, padding_mask=pad)
    # compare only valid query rows (padded queries are don't-cares and the
    # ref puts uniform-softmax garbage there; ours puts zeros)
    np.testing.assert_allclose(np.asarray(ours)[0, :, :100],
                               np.asarray(ref)[0, :, :100],
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(ours)[1, :, :64],
                               np.asarray(ref)[1, :, :64],
                               atol=2e-5, rtol=2e-5)


@pytest.mark.parametrize("bwd_impl", ["merged", "split"])
@pytest.mark.parametrize("case", [dict(), dict(sliding_window=32),
                                  dict(Hkv=1),
                                  # 64-blocks: exercise the qi>0 offsets,
                                  # the dKdV kernel's q-block loop bounds,
                                  # and GQA group-head accumulation
                                  dict(S=256, Hkv=2, block=64),
                                  dict(S=256, Hkv=1, sliding_window=64,
                                       block=64)])
def test_gradients_match_oracle(case, bwd_impl):
    case = dict(case)
    kw = {k: case.pop(k) for k in ("sliding_window",) if k in case}
    bkw = {"bwd_impl": bwd_impl}
    if "block" in case:
        b = case.pop("block")
        bkw.update(block_q=b, block_k=b)
    q, k, v = make_qkv(jax.random.PRNGKey(2), **case)

    def loss(fn, q, k, v):
        extra = bkw if fn is flash_attention else {}
        out = fn(q, k, v, is_causal=True, **kw, **extra)
        return jnp.sum(out * jnp.cos(out))  # nontrivial cotangent

    g_ours = jax.grad(functools.partial(loss, flash_attention),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(functools.partial(loss, dot_product_attention),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ours, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=name)


@pytest.mark.parametrize("bwd_impl", ["merged", "split"])
def test_gradients_with_padding_mask(bwd_impl):
    q, k, v = make_qkv(jax.random.PRNGKey(3))
    B, S = q.shape[0], q.shape[2]
    pad = np.ones((B, S), np.float32)
    pad[:, 96:] = 0.0
    pad = jnp.asarray(pad)
    valid = pad.astype(bool)[:, None, :, None]

    def loss(fn, q, k, v):
        kw = {"block_q": 64, "block_k": 64, "bwd_impl": bwd_impl} \
            if fn is flash_attention else {}
        out = fn(q, k, v, is_causal=True, padding_mask=pad, **kw)
        return jnp.sum(jnp.where(valid, out, 0.0) ** 2)

    g_ours = jax.grad(functools.partial(loss, flash_attention),
                      argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(functools.partial(loss, dot_product_attention),
                     argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ours, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=name)


def test_merged_backward_equals_split_exactly():
    """The merged one-pass kernel and the split pair must agree to
    float-exact tolerance (same tile math, same per-tile recomputation)
    — tighter than the oracle comparison — across GQA + window +
    multi-block, asymmetric blocks, and a whole-S static-block shape."""
    for case, kw in [(dict(S=256, Hkv=1), dict(block_q=64, block_k=64,
                                               sliding_window=96)),
                     (dict(S=256, Hkv=2), dict(block_q=64, block_k=128)),
                     (dict(S=192, Hkv=2), {})]:  # whole-S static block
        q, k, v = make_qkv(jax.random.PRNGKey(8), **case)

        def loss(bwd_impl, q, k, v):
            out = flash_attention(q, k, v, is_causal=True,
                                  bwd_impl=bwd_impl, **kw)
            return jnp.sum(out * jnp.cos(out))

        g_m = jax.grad(functools.partial(loss, "merged"),
                       argnums=(0, 1, 2))(q, k, v)
        g_s = jax.grad(functools.partial(loss, "split"),
                       argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_m, g_s, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6,
                                       err_msg=f"{case} {kw} {name}")


def test_partial_joint_vjp_merged_equals_split():
    """The ring-attention contract: flash_attention_partial's custom_vjp
    carries cotangents through BOTH (out, lse) — including the non-causal
    negative-band windows ring hops use — and the merged backward must
    reproduce the split pair's gradients exactly (the dlse cotangent
    folds into Δ before either kernel runs)."""
    from mobilefinetuner_tpu.ops.flash_attention import \
        flash_attention_partial
    q, k, v = make_qkv(jax.random.PRNGKey(9), S=256, Hkv=1)

    for causal, window in [(True, None), (True, 96), (False, -32)]:
        def loss(bwd_impl, q, k, v):
            out, lse = flash_attention_partial(
                q, k, v, is_causal=causal, sliding_window=window,
                block_q=64, block_k=64, bwd_impl=bwd_impl)
            return jnp.sum(out * jnp.sin(out)) + jnp.sum(jnp.tanh(lse))

        g_m = jax.grad(functools.partial(loss, "merged"),
                       argnums=(0, 1, 2))(q, k, v)
        g_s = jax.grad(functools.partial(loss, "split"),
                       argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_m, g_s, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=1e-6, rtol=1e-6,
                                       err_msg=f"{causal}/{window} {name}")


def test_resolve_bwd_impl_gate():
    """'auto' must pick merged for every shape the forward dispatches
    today, and fall back to split when the whole-S q/dO/dQ slabs cannot
    fit the VMEM accounting."""
    from mobilefinetuner_tpu.ops.flash_attention import (merged_bwd_fits,
                                                         resolve_bwd_impl)
    # the bench shapes: GPT-2 D=64 and Gemma D=256, bf16
    assert resolve_bwd_impl(512, 64, 512, 2) == "merged"
    assert resolve_bwd_impl(1024, 64, 512, 2) == "merged"
    assert resolve_bwd_impl(2048, 64, 512, 2) == "merged"
    assert resolve_bwd_impl(2048, 256, 512, 2) == "merged"
    # f32 at the largest Gemma shape exceeds the budget -> split
    assert resolve_bwd_impl(2048, 256, 512, 4) == "split"
    assert resolve_bwd_impl(8192, 256, 512, 4) == "split"
    assert not merged_bwd_fits(8192, 256, 512, 4)


def test_unsupported_shapes_fall_back():
    # S=100 not a block multiple; D=8 unsupported -> XLA path, still correct
    q, k, v = make_qkv(jax.random.PRNGKey(4), S=100, D=8)
    ours = flash_attention(q, k, v, is_causal=True)
    ref = dot_product_attention(q, k, v, is_causal=True)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_attention_dispatcher_flash():
    from mobilefinetuner_tpu.ops.attention import attention
    q, k, v = make_qkv(jax.random.PRNGKey(5))
    out = attention(q, k, v, impl="flash", is_causal=True)
    ref = attention(q, k, v, impl="xla", is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_gpt2_model_flash_matches_xla():
    """Whole-model parity: GPT-2 forward with attention_impl='flash' equals
    the XLA path (flash-eligible head_dim=64)."""
    import dataclasses
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.models import gpt2
    cfg = dataclasses.replace(GPT2Config.tiny(vocab_size=512),
                              n_embd=128, n_head=2, n_positions=128)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 512)
    ref = gpt2.forward(cfg, params, ids)
    cfg_f = dataclasses.replace(cfg, attention_impl="flash")
    out = gpt2.forward(cfg_f, params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_gemma3_model_flash_matches_xla():
    """Gemma: the flash path must reproduce the per-layer global/local mask
    interleave (lax.cond branch) including sliding-window layers."""
    import dataclasses
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.models import gemma3
    cfg = Gemma3TextConfig(
        vocab_size=512, hidden_size=64, intermediate_size=128,
        num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=1,
        head_dim=64, max_position_embeddings=256, sliding_window=32,
        query_pre_attn_scalar=64.0, sliding_window_pattern=3)
    params = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 512)
    mask = jnp.ones((2, 128))
    ref = gemma3.forward(cfg, params, ids, attention_mask=mask)
    cfg_f = dataclasses.replace(cfg, attention_impl="flash")
    out = gemma3.forward(cfg_f, params, ids, attention_mask=mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_flash_under_jit_and_scan():
    """The kernel must trace under jit (model stacks run it inside
    lax.scan)."""
    q, k, v = make_qkv(jax.random.PRNGKey(6))

    @jax.jit
    def f(q, k, v):
        return flash_attention(q, k, v, is_causal=True, sliding_window=64)

    ref = dot_product_attention(q, k, v, is_causal=True, sliding_window=64)
    np.testing.assert_allclose(np.asarray(f(q, k, v)), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


# ------------------------- in-kernel attention dropout ----------------------

def _np_keep_mask(seed, b, h, S, p_drop):
    """Exact numpy reimplementation of flash_attention._keep_mask over the
    full [S, S] grid (uint32 two's-complement arithmetic == the kernel's
    wrapping int32)."""
    rows = np.arange(S, dtype=np.uint32)[:, None] * np.uint32(1)
    cols = np.arange(S, dtype=np.uint32)[None, :] * np.uint32(1)
    with np.errstate(over="ignore"):
        x = (np.uint32(seed & 0xFFFFFFFF)
             ^ (np.uint32(b) * np.uint32(0x9E3779B9))
             ^ (np.uint32(h) * np.uint32(0x85EBCA6B)))
        z = (x + rows * np.uint32(0xC2B2AE35)
             + cols * np.uint32(0x27D4EB2F))
        z = z ^ (z >> np.uint32(16))
        z = z * np.uint32(0x7FEB352D)
        z = z ^ (z >> np.uint32(15))
        z = z * np.uint32(0x846CA68B)
        z = z ^ (z >> np.uint32(16))
    u24 = (z >> np.uint32(8)) & np.uint32(0xFFFFFF)
    thresh = np.uint32(round((1.0 - p_drop) * (1 << 24)))
    return u24 < thresh


def _masked_dropout_oracle(q, k, v, seed, p_drop, causal=True, window=None):
    """Dense reference applying the EXACT kernel keep-mask: out =
    dropout(softmax(s)) @ v with the hash-derived mask — jax throughout, so
    jax.grad of this is the gradient oracle too."""
    B, Hq, S, D = q.shape
    Hkv = k.shape[1]
    G = Hq // Hkv
    scale = 1.0 / (D ** 0.5)
    keep = np.stack([[_np_keep_mask(seed, b, h, S, p_drop)
                      for h in range(Hq)] for b in range(B)])
    qf = q.astype(jnp.float32)
    kf = k.astype(jnp.float32)
    qg = qf.reshape(B, Hkv, G, S, D)
    s = jnp.einsum("bhgqd,bhkd->bhgqk", qg, kf) * scale
    rows = np.arange(S)[:, None]
    cols = np.arange(S)[None, :]
    m = np.ones((S, S), bool)
    if causal:
        m &= cols <= rows
    if window is not None:
        m &= cols > rows - window
    s = jnp.where(jnp.asarray(m)[None, None, None], s, -1e30)
    p = jax.nn.softmax(s, axis=-1)
    keep_g = jnp.asarray(keep.reshape(B, Hkv, G, S, S))
    pd = jnp.where(keep_g, p, 0.0) / (1.0 - p_drop)
    out = jnp.einsum("bhgqk,bhkd->bhgqd", pd, v.astype(jnp.float32))
    return out.reshape(B, Hq, S, D)


def test_dropout_forward_matches_hash_oracle():
    """Kernel dropout == dense attention masked with the numpy-recomputed
    hash mask: EXACT parity (not statistical), p=0.1 and p=0.5, causal and
    sliding-window, multi-block."""
    import mobilefinetuner_tpu.ops.flash_attention as fa
    q, k, v = make_qkv(jax.random.PRNGKey(0), B=2, Hq=4, Hkv=2, S=128,
                       D=64)
    rng = jax.random.PRNGKey(42)
    seed = int(np.asarray(jax.lax.bitcast_convert_type(
        jax.random.bits(rng, (1,), jnp.uint32), jnp.int32))[0])
    for p_drop in (0.1, 0.5):
        for window in (None, 48):
            out = flash_attention(q, k, v, attn_dropout=p_drop,
                                  attn_dropout_rng=rng,
                                  sliding_window=window,
                                  block_q=64, block_k=64)
            ref = _masked_dropout_oracle(q, k, v, seed, p_drop,
                                         window=window)
            np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                       atol=2e-5, rtol=2e-5,
                                       err_msg=f"p={p_drop} w={window}")


@pytest.mark.parametrize("bwd_impl", ["merged", "split"])
def test_dropout_gradients_match_hash_oracle(bwd_impl):
    """Backward with dropout: dq/dk/dv vs jax.grad of the dense
    same-mask oracle — BOTH backward implementations must regenerate the
    exact forward mask."""
    q, k, v = make_qkv(jax.random.PRNGKey(1), B=1, Hq=2, Hkv=1, S=128,
                       D=64)
    rng = jax.random.PRNGKey(7)
    seed = int(np.asarray(jax.lax.bitcast_convert_type(
        jax.random.bits(rng, (1,), jnp.uint32), jnp.int32))[0])
    p_drop = 0.2

    def loss_kernel(q, k, v):
        out = flash_attention(q, k, v, attn_dropout=p_drop,
                              attn_dropout_rng=rng, block_q=64,
                              block_k=64, bwd_impl=bwd_impl)
        return jnp.sum(out * jnp.cos(out))

    def loss_ref(q, k, v):
        out = _masked_dropout_oracle(q, k, v, seed, p_drop)
        return jnp.sum(out * jnp.cos(out))

    g_k = jax.grad(loss_kernel, argnums=(0, 1, 2))(q, k, v)
    g_r = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_k, g_r, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=name)


def test_dropout_keep_rate_and_determinism():
    q, k, v = make_qkv(jax.random.PRNGKey(2), B=1, Hq=2, Hkv=2, S=128,
                       D=64)
    rng = jax.random.PRNGKey(3)
    a = flash_attention(q, k, v, attn_dropout=0.3, attn_dropout_rng=rng)
    b = flash_attention(q, k, v, attn_dropout=0.3, attn_dropout_rng=rng)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))  # same seed
    c = flash_attention(q, k, v, attn_dropout=0.3,
                        attn_dropout_rng=jax.random.PRNGKey(4))
    assert np.abs(np.asarray(a) - np.asarray(c)).max() > 1e-3  # new mask
    # empirical keep-rate of the raw hash, full grid
    keep = _np_keep_mask(123456789, 0, 0, 512, 0.3)
    rate = keep.mean()
    assert abs(rate - 0.7) < 0.01, rate


def test_dropout_zero_equals_no_dropout():
    q, k, v = make_qkv(jax.random.PRNGKey(5), S=128, D=64)
    base = flash_attention(q, k, v)
    z = flash_attention(q, k, v, attn_dropout=0.0,
                        attn_dropout_rng=jax.random.PRNGKey(0))
    np.testing.assert_array_equal(np.asarray(base), np.asarray(z))


def test_attention_dispatcher_keeps_flash_with_dropout():
    """Train-mode attention dropout no longer forces the XLA path: the
    'flash' impl with dropout runs the kernel (and the auto rule is purely
    shape-based)."""
    from mobilefinetuner_tpu.ops.attention import attention
    q, k, v = make_qkv(jax.random.PRNGKey(6), S=128, D=64)
    rng = jax.random.PRNGKey(1)
    out = attention(q, k, v, impl="flash", attn_dropout=0.25,
                    attn_dropout_rng=rng)
    # must differ from the dropout-free kernel result (mask engaged)
    base = attention(q, k, v, impl="flash")
    assert np.abs(np.asarray(out) - np.asarray(base)).max() > 1e-3


def test_gpt2_model_training_dropout_on_flash_path():
    """Model-level: a GPT-2 block with attn_pdrop>0 and impl='flash' in
    TRAIN mode (dropout_rng set) runs the kernel path end to end — fwd +
    LoRA grads finite, seeded-deterministic, and actually dropping."""
    import dataclasses
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gpt2
    from mobilefinetuner_tpu.models import gpt2
    cfg = dataclasses.replace(GPT2Config.tiny(vocab_size=128),
                              attention_impl="flash", attn_pdrop=0.25,
                              embd_pdrop=0.0, resid_pdrop=0.0)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    lora = init_lora_gpt2(cfg, LoRASpec(rank=2, alpha=4.0),
                          jax.random.PRNGKey(1))
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 128)
    rng = jax.random.PRNGKey(3)

    def loss(lora_t, rng):
        out = gpt2.forward(cfg, params, ids, lora=lora_t,
                           dropout_rng=rng)
        return (out.astype(jnp.float32) ** 2).mean()

    l1 = float(loss(lora, rng))
    l2 = float(loss(lora, rng))
    assert l1 == l2, "same rng must give the same dropout mask"
    l3 = float(loss(lora, jax.random.PRNGKey(9)))
    assert l3 != l1, "different rng must give a different mask"
    cfg_nd = dataclasses.replace(cfg, attn_pdrop=0.0)
    l_nd = float((gpt2.forward(cfg_nd, params, ids, lora=lora,
                               dropout_rng=rng).astype(jnp.float32) ** 2
                  ).mean())
    assert l_nd != l1, "dropout must actually perturb the output"
    g = jax.grad(loss)(lora, rng)
    assert all(np.isfinite(np.asarray(x)).all()
               for x in jax.tree.leaves(g))
