"""Async input pipeline (data/prefetch.py + cli/common.micro_batches):
determinism contract (byte-identical batch sequence vs the synchronous
path, across epoch boundaries, skip_steps resume, and mesh sharding),
bounded queue depth, clean shutdown (no leaked producer threads, consumer
exceptions propagate, producer exceptions surface in order)."""

import itertools
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.cli.common import evaluate, micro_batches
from mobilefinetuner_tpu.data.prefetch import Prefetcher
from mobilefinetuner_tpu.data.wikitext2 import WT2Config, WikiText2Dataset

EOS = 999


def _encode(line: str):
    return [abs(hash(w)) % 900 for w in line.split()]


@pytest.fixture(scope="module")
def corpus_file(tmp_path_factory):
    d = tmp_path_factory.mktemp("wt2pf")
    path = str(d / "wiki.train.tokens")
    rng = np.random.default_rng(0)
    with open(path, "w") as f:
        for _ in range(120):
            n = int(rng.integers(3, 30))
            f.write(" ".join(f"w{rng.integers(0, 500)}"
                             for _ in range(n)) + "\n")
    return path


def _mk(path, **kw):
    cfg = WT2Config(**{"seq_len": 32, "batch_size": 2, "seed": 7, **kw})
    return WikiText2Dataset(path, "train", cfg, _encode, eos_id=EOS)


def _producer_threads():
    return [t for t in threading.enumerate() if t.name == "batch-producer"]


def _take(ds_factory, n, accum=2, skip_steps=0, depth=0):
    """First n (epoch, batch) pairs through a depth-`depth` pipeline."""
    src = micro_batches(ds_factory(), accum, skip_steps=skip_steps)
    with Prefetcher(itertools.islice(src, n), depth=depth) as stream:
        return list(stream)


def _assert_same_stream(a, b):
    assert len(a) == len(b)
    for (ep_a, ba), (ep_b, bb) in zip(a, b):
        assert ep_a == ep_b
        assert set(ba) == set(bb)
        for k in ba:
            np.testing.assert_array_equal(ba[k], bb[k])


# --------------------------- determinism contract ---------------------------

def test_byte_identical_across_epoch_boundaries(corpus_file):
    """accum=2 over an odd number of per-epoch batches: accumulation
    carries across reshuffled epoch boundaries; the prefetched stream
    must reproduce the synchronous one byte for byte."""
    mk = lambda: _mk(corpus_file)
    nb = mk().num_batches()
    n = 2 * nb + 3  # several epoch crossings
    sync = _take(mk, n, depth=0)
    pref = _take(mk, n, depth=3)
    _assert_same_stream(sync, pref)
    assert sync[-1][0] >= 2  # really crossed epochs


def test_byte_identical_streaming_mode(corpus_file):
    """Streaming dataset (window refetch runs in the producer thread,
    mutating the dataset's resident window): prefetched == synchronous.
    (Streaming uses its own window-local shuffle, so the oracle is the
    streaming-mode sync path, not the in-RAM dataset.)"""
    mk = lambda: _mk(corpus_file, streaming=True, window_tokens=64)
    sync = _take(mk, 10, depth=0)
    pref = _take(mk, 10, depth=2)
    _assert_same_stream(sync, pref)


def test_byte_identical_skip_steps_resume(corpus_file):
    """A prefetched resume (skip_steps) continues the exact sequence of
    an uninterrupted prefetched run — and of an uninterrupted sync run."""
    mk = lambda: _mk(corpus_file)
    nb = mk().num_batches()
    skip = nb + 1  # resume point past an epoch boundary
    full = _take(mk, skip + 4, depth=2)
    resumed = _take(mk, 4, skip_steps=skip, depth=2)
    _assert_same_stream(full[skip:], resumed)
    resumed_sync = _take(mk, 4, skip_steps=skip, depth=0)
    _assert_same_stream(resumed_sync, resumed)


def test_byte_identical_mesh_sharded_placement(corpus_file):
    """Lookahead placement over a (2,4) mesh: the placed global arrays
    carry the same bytes, per shard, as synchronous shard_batch — the
    prefetcher changes WHEN placement happens, never what is placed."""
    from mobilefinetuner_tpu.parallel.mesh import (make_batch_placer,
                                                   make_mesh, shard_batch)
    mesh = make_mesh(data=2, fsdp=4)
    mk = lambda: _mk(corpus_file, batch_size=8)
    place = make_batch_placer(mesh)
    src = (b for _, b in micro_batches(mk(), 1))
    with Prefetcher(itertools.islice(src, 6), depth=2,
                    place_fn=place) as stream:
        placed = list(stream)
    sync = [shard_batch(b, mesh)
            for _, b in itertools.islice(micro_batches(mk(), 1), 6)]
    for pa, pb in zip(placed, sync):
        for k in pa:
            assert pa[k].sharding == pb[k].sharding
            np.testing.assert_array_equal(np.asarray(pa[k]),
                                          np.asarray(pb[k]))


# --------------------------- queue mechanics --------------------------------

def test_bounded_queue_depth():
    """The producer must never run more than depth + lookahead + 1 items
    ahead of the consumer (bounded host memory is the point of the
    queue)."""
    produced = [0]

    def counting_source():
        for i in range(1000):
            produced[0] += 1
            yield i

    depth, lookahead = 3, 1
    with Prefetcher(counting_source(), depth=depth,
                    lookahead=lookahead) as stream:
        got = [next(stream) for _ in range(5)]
        time.sleep(0.3)  # let the producer run as far ahead as it can
        assert got == list(range(5))
        # consumed + queue + lookahead buffer + one in the producer's hand
        assert produced[0] <= 5 + depth + lookahead + 2, produced[0]


def test_order_is_strict_and_complete():
    with Prefetcher(iter(range(257)), depth=2) as stream:
        assert list(stream) == list(range(257))


def test_kill_switch_is_threadless():
    before = len(_producer_threads())
    with Prefetcher(iter(range(10)), depth=0) as stream:
        assert len(_producer_threads()) == before  # no thread spawned
        assert list(stream) == list(range(10))


# --------------------------- shutdown ---------------------------------------

def test_consumer_exception_propagates_and_no_leaked_threads():
    """A consumer dying mid-epoch must not leak the producer thread, and
    its own exception must propagate unchanged."""
    def endless():
        i = 0
        while True:
            yield i
            i += 1

    with pytest.raises(RuntimeError, match="consumer died"):
        with Prefetcher(endless(), depth=2) as stream:
            next(stream)
            next(stream)
            raise RuntimeError("consumer died")
    deadline = time.time() + 5
    while _producer_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert not _producer_threads(), "producer thread leaked"


def test_producer_exception_surfaces_after_prior_items():
    """A generator that raises mid-epoch: everything produced before the
    raise is delivered first, then the SAME exception type/message
    surfaces at the consumer (synchronous-path error semantics)."""
    def bad_source():
        yield from range(4)
        raise ValueError("tokenizer exploded")

    stream = Prefetcher(bad_source(), depth=2)
    got = [next(stream) for _ in range(4)]
    assert got == [0, 1, 2, 3]
    with pytest.raises(ValueError, match="tokenizer exploded"):
        next(stream)
    assert not _producer_threads()


def test_close_unblocks_full_queue_producer():
    """close() while the producer is parked on a full queue must stop it
    promptly (the put is timeout-polled against the stop event)."""
    stream = Prefetcher(itertools.count(), depth=1)
    next(stream)
    time.sleep(0.05)  # producer now blocked on the full queue
    stream.close()
    deadline = time.time() + 5
    while _producer_threads() and time.time() < deadline:
        time.sleep(0.01)
    assert not _producer_threads()
    with pytest.raises(StopIteration):
        next(stream)  # closed stream is terminal


# --------------------------- evaluate() integration -------------------------

def test_evaluate_device_accumulation_matches_sync(corpus_file):
    """evaluate()'s on-device accumulators + prefetch produce the same
    totals as a hand-rolled synchronous float()-per-batch loop."""
    ds = _mk(corpus_file)

    def eval_step(tr, fr, b):
        return (jnp.sum(b["input_ids"]).astype(jnp.float32),
                jnp.int32(b["input_ids"].size))

    ref_total, ref_count, ref_n = 0.0, 0, 0
    for b in itertools.islice(_mk(corpus_file).epoch(0), 5):
        s, c = eval_step(None, None, b)
        ref_total += float(s)
        ref_count += int(c)
        ref_n += 1

    for depth in (0, 2):
        out = evaluate(eval_step, None, None, ds, 5, prefetch=depth)
        assert out["tokens"] == ref_count
        assert out["batches"] == ref_n
        assert out["loss"] == pytest.approx(ref_total / ref_count)
    assert not _producer_threads()
