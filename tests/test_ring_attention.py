"""Ring attention vs the single-device oracle, on the virtual 8-device
mesh: forward AND gradients, causal / sliding-window / GQA / padding —
the long-context sequence-parallel path (parallel/ring_attention.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from mobilefinetuner_tpu.ops.attention import dot_product_attention
from mobilefinetuner_tpu.parallel.mesh import make_mesh
from mobilefinetuner_tpu.parallel.ring_attention import ring_attention


def make_qkv(key, B=2, Hq=4, Hkv=2, S=64, D=16, dtype=jnp.float32):
    k1, k2, k3 = jax.random.split(key, 3)
    q = jax.random.normal(k1, (B, Hq, S, D), dtype)
    k = jax.random.normal(k2, (B, Hkv, S, D), dtype)
    v = jax.random.normal(k3, (B, Hkv, S, D), dtype)
    return q, k, v


CASES = [
    dict(n=4),
    dict(n=8),
    dict(n=4, sliding_window=24),
    dict(n=4, Hkv=1),                       # extreme GQA
    dict(n=2, Hkv=4, S=96, D=32),           # MHA, odd shard size 48
    dict(n=4, is_causal=False),             # bidirectional
    dict(n=4, sliding_window=17),           # (w-1) % Sq == 0: 1 hop not 2
    dict(n=4, sliding_window=1),            # self-only window: 0 hops
]


def test_ring_hops_boundaries():
    """Hop t's nearest cell sits (t-1)*Sq+1 rows back, so the hop count is
    max(0, (w-2)//Sq + 1): a window of exactly Sq+1 needs ONE hop (the
    old (w-1)//Sq+1 formula shipped a fully-masked second hop), and w=1
    (self only) needs zero."""
    from mobilefinetuner_tpu.parallel.ring_attention import _ring_hops
    Sq = 16
    assert _ring_hops(8, None, Sq) == 7
    assert _ring_hops(8, 1, Sq) == 0
    assert _ring_hops(8, 2, Sq) == 1
    assert _ring_hops(8, Sq, Sq) == 1
    assert _ring_hops(8, Sq + 1, Sq) == 1
    assert _ring_hops(8, Sq + 2, Sq) == 2
    assert _ring_hops(8, 2 * Sq + 1, Sq) == 2
    assert _ring_hops(2, 10 * Sq, Sq) == 1     # clamped to n-1


@pytest.mark.parametrize("case", CASES)
def test_forward_matches_oracle(case):
    case = dict(case)
    n = case.pop("n")
    kw = {k: case.pop(k) for k in ("sliding_window", "is_causal")
          if k in case}
    mesh = make_mesh(data=1, fsdp=n, devices=jax.devices()[:n])
    q, k, v = make_qkv(jax.random.PRNGKey(0), **case)
    ours = ring_attention(q, k, v, mesh, **kw)
    ref_kw = dict(is_causal=True)
    ref_kw.update(kw)
    ref = dot_product_attention(q, k, v, **ref_kw)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_forward_with_padding():
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    q, k, v = make_qkv(jax.random.PRNGKey(1))
    B, S = q.shape[0], q.shape[2]
    pad = np.ones((B, S), np.float32)
    pad[0, 50:] = 0.0
    pad = jnp.asarray(pad)
    ours = ring_attention(q, k, v, mesh, padding_mask=pad)
    ref = dot_product_attention(q, k, v, is_causal=True, padding_mask=pad)
    np.testing.assert_allclose(np.asarray(ours)[0, :, :50],
                               np.asarray(ref)[0, :, :50],
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(ours)[1], np.asarray(ref)[1],
                               atol=2e-5, rtol=2e-5)


def test_gradients_match_oracle():
    """Reverse-mode through the ring (scan + ppermute transpose)."""
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    q, k, v = make_qkv(jax.random.PRNGKey(2))

    def loss(fn, q, k, v):
        out = fn(q, k, v)
        return jnp.sum(out * jnp.cos(out))

    ring = lambda q, k, v: ring_attention(q, k, v, mesh,
                                          sliding_window=24)
    ref = lambda q, k, v: dot_product_attention(q, k, v, is_causal=True,
                                                sliding_window=24)
    g_ours = jax.grad(lambda *a: loss(ring, *a), argnums=(0, 1, 2))(q, k, v)
    g_ref = jax.grad(lambda *a: loss(ref, *a), argnums=(0, 1, 2))(q, k, v)
    for a, b, name in zip(g_ours, g_ref, "qkv"):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=5e-5, rtol=5e-5, err_msg=name)


def test_gpt2_model_context_parallel_matches_single():
    """Whole-model sequence parallelism: GPT-2 forward with cp_mesh (ring
    attention, activations S-sharded by propagation) == the single-device
    forward — long-context capability end to end."""
    import dataclasses
    from mobilefinetuner_tpu.core.config import GPT2Config
    from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gpt2
    from mobilefinetuner_tpu.models import gpt2
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    cfg = dataclasses.replace(GPT2Config.tiny(vocab_size=512),
                              n_positions=128)
    params = gpt2.init_params(cfg, jax.random.PRNGKey(0))
    lora = init_lora_gpt2(cfg, LoRASpec(rank=4, alpha=8.0),
                          jax.random.PRNGKey(1))
    ids = jax.random.randint(jax.random.PRNGKey(2), (2, 128), 0, 512)
    ref = gpt2.forward(cfg, params, ids, lora=lora)
    out = jax.jit(lambda p, l, i: gpt2.forward(cfg, p, i, lora=l,
                                               cp_mesh=mesh))(
        params, lora, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)

    # gradients through the sequence-parallel model reach the adapters
    def loss(l, cp):
        o = gpt2.forward(cfg, params, ids, lora=l,
                         cp_mesh=mesh if cp else None)
        return (o.astype(jnp.float32) ** 2).mean()

    g_cp = jax.jit(jax.grad(lambda l: loss(l, True)))(lora)
    g_ref = jax.grad(lambda l: loss(l, False))(lora)
    for a, b in zip(jax.tree.leaves(g_cp), jax.tree.leaves(g_ref)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-5, rtol=1e-4)


def test_gemma_model_context_parallel_matches_single():
    """Gemma under cp_mesh: the per-layer global/local interleave rides
    lax.cond into ring attention with the right window."""
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.models import gemma3
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    cfg = Gemma3TextConfig.tiny()
    params = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 64), 0,
                             cfg.vocab_size)
    mask = jnp.ones((2, 64))
    ref = gemma3.forward(cfg, params, ids, attention_mask=mask)
    out = jax.jit(lambda p, i: gemma3.forward(cfg, p, i,
                                              attention_mask=mask,
                                              cp_mesh=mesh))(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=1e-4, rtol=1e-4)


def test_sequence_parallel_cli_end_to_end(tmp_path):
    """--sequence_parallel through the real CLI: S sharded over the fsdp
    axis, ring attention in the compiled train step, loss decreases."""
    import os
    import sys
    sys.path.insert(0, os.path.dirname(__file__))
    from fixtures import write_tiny_gpt2_dir, write_wikitext_dir
    from mobilefinetuner_tpu.cli.gpt2_lora_finetune import main
    gpt2_dir = str(tmp_path / "gpt2")
    write_tiny_gpt2_dir(gpt2_dir)
    wiki = write_wikitext_dir(str(tmp_path / "wiki"))
    csv_path = str(tmp_path / "m.csv")
    rc = main(["--pretrained_dir", gpt2_dir, "--data_dir", wiki,
               "--steps", "6", "--batch_size", "2", "--seq_len", "32",
               "--lr", "5e-3", "--mesh_data", "1", "--mesh_fsdp", "4",
               "--sequence_parallel",
               "--lora_out", str(tmp_path / "a.safetensors"),
               "--metrics_csv", csv_path])
    assert rc == 0
    import csv as csv_mod
    rows = list(csv_mod.DictReader(open(csv_path)))
    assert float(rows[-1]["loss"]) < float(rows[0]["loss"])


def test_under_jit_with_sharded_inputs():
    """The production shape: inputs already sequence-sharded on the mesh,
    ring attention under jit keeps them sharded."""
    from jax.sharding import NamedSharding, PartitionSpec as P
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    q, k, v = make_qkv(jax.random.PRNGKey(3))
    sh = NamedSharding(mesh, P(None, None, "fsdp", None))
    q, k, v = (jax.device_put(x, sh) for x in (q, k, v))

    @jax.jit
    def f(q, k, v):
        return ring_attention(q, k, v, mesh)

    out = f(q, k, v)
    assert out.sharding.spec == P(None, None, "fsdp", None)
    ref = dot_product_attention(jax.device_get(q), jax.device_get(k),
                                jax.device_get(v), is_causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)

# ------------------------- flash-kernel ring body ---------------------------
# D >= 64 engages _ring_shard_flash (parallel/ring_attention.py): partial
# flash attention per hop with a static shifted-band mask, merged in
# (lse, out) space — per-device scores stay blockwise, never [Sq, Sk].

FLASH_CASES = [
    dict(n=8, S=512, D=64, Hq=4, Hkv=2),                  # GQA causal
    dict(n=8, S=512, D=64, Hq=4, Hkv=2, sliding_window=96),   # 2-hop band
    dict(n=4, S=256, D=64, Hq=2, Hkv=2, sliding_window=300),  # w > S/2
    dict(n=2, S=128, D=128, Hq=2, Hkv=1),                 # D=128, n=2
]


@pytest.mark.parametrize("case", FLASH_CASES)
def test_flash_ring_forward_matches_oracle(case):
    case = dict(case)
    n = case.pop("n")
    kw = ({"sliding_window": case.pop("sliding_window")}
          if "sliding_window" in case else {})
    from mobilefinetuner_tpu.ops.flash_attention import \
        flash_partial_eligible
    assert flash_partial_eligible(case["S"] // n, case["D"])
    mesh = make_mesh(data=1, fsdp=n, devices=jax.devices()[:n])
    q, k, v = make_qkv(jax.random.PRNGKey(0), **case)
    ours = ring_attention(q, k, v, mesh, **kw)
    ref = dot_product_attention(q, k, v, is_causal=True, **kw)
    np.testing.assert_allclose(np.asarray(ours), np.asarray(ref),
                               atol=2e-5, rtol=2e-5)


def test_flash_ring_with_padding():
    mesh = make_mesh(data=1, fsdp=8, devices=jax.devices()[:8])
    q, k, v = make_qkv(jax.random.PRNGKey(1), B=2, Hq=2, Hkv=1, S=512,
                       D=64)
    pad = np.ones((2, 512), np.float32)
    pad[0, 400:] = 0.0
    pad = jnp.asarray(pad)
    ours = ring_attention(q, k, v, mesh, padding_mask=pad)
    ref = dot_product_attention(q, k, v, is_causal=True, padding_mask=pad)
    np.testing.assert_allclose(np.asarray(ours)[0, :, :400],
                               np.asarray(ref)[0, :, :400],
                               atol=2e-5, rtol=2e-5)
    np.testing.assert_allclose(np.asarray(ours)[1], np.asarray(ref)[1],
                               atol=2e-5, rtol=2e-5)


def test_flash_ring_gradients_match_oracle():
    """Reverse-mode through the flash ring: the merge tree differentiates
    through BOTH out and lse of every hop's partial (the joint custom_vjp
    in ops/flash_attention.py)."""
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    q, k, v = make_qkv(jax.random.PRNGKey(2), B=1, Hq=2, Hkv=1, S=256,
                       D=64)

    def loss(fn, q, k, v):
        out = fn(q, k, v)
        return jnp.sum(out * jnp.cos(out))

    for kw in ({}, {"sliding_window": 96}):
        ring = lambda q, k, v: ring_attention(q, k, v, mesh, **kw)
        ref = lambda q, k, v: dot_product_attention(q, k, v,
                                                    is_causal=True, **kw)
        g_ours = jax.grad(lambda *a: loss(ring, *a),
                          argnums=(0, 1, 2))(q, k, v)
        g_ref = jax.grad(lambda *a: loss(ref, *a),
                         argnums=(0, 1, 2))(q, k, v)
        for a, b, name in zip(g_ours, g_ref, "qkv"):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       atol=5e-5, rtol=5e-5,
                                       err_msg=f"{name} {kw}")


def test_flash_ring_long_context_8k():
    """The regime ring attention exists for: S=8192 over 8 devices. The
    flash body's equivalence oracle here is the DENSE ring body at the
    same sharding (the full [S, S] single-device oracle would be the
    memory blow-up this path avoids); fwd + grad run and agree."""
    from functools import partial as _p
    from mobilefinetuner_tpu.parallel import ring_attention as ra
    mesh = make_mesh(data=1, fsdp=8, devices=jax.devices()[:8])
    q, k, v = make_qkv(jax.random.PRNGKey(3), B=1, Hq=2, Hkv=1, S=8192,
                       D=64, dtype=jnp.float32)
    out = ring_attention(q, k, v, mesh, sliding_window=1024)
    assert np.isfinite(np.asarray(out)).all()
    # dense-body reference at the same sharding
    from jax.sharding import PartitionSpec as P
    pad = jnp.ones((1, 8192), jnp.float32)
    from mobilefinetuner_tpu.core.compat import shard_map
    dense = shard_map(
        _p(ra._ring_shard, axis="fsdp", n=8, scale=1.0 / 8.0, causal=True,
           window=1024),
        mesh=mesh,
        in_specs=(P(None, None, "fsdp", None),) * 3 + (P(None, "fsdp"),),
        out_specs=P(None, None, "fsdp", None), check_vma=False,
    )(q, k, v, pad)
    np.testing.assert_allclose(np.asarray(out), np.asarray(dense),
                               atol=3e-5, rtol=3e-5)


def test_gemma_model_context_parallel_flash_body():
    """Full-size-shaped Gemma under cp_mesh with a FLASH-eligible shard
    (head_dim 64, Sq=128): the per-layer global/local lax.cond now selects
    between two flash-ring variants (shard_map + pallas inside cond
    branches) — the exact composition a real Gemma sequence-parallel run
    hits."""
    from mobilefinetuner_tpu.core.config import Gemma3TextConfig
    from mobilefinetuner_tpu.models import gemma3
    from mobilefinetuner_tpu.ops.flash_attention import \
        flash_partial_eligible
    mesh = make_mesh(data=1, fsdp=4, devices=jax.devices()[:4])
    cfg = Gemma3TextConfig(
        vocab_size=512, hidden_size=128, intermediate_size=256,
        num_hidden_layers=4, num_attention_heads=2, num_key_value_heads=1,
        head_dim=64, max_position_embeddings=1024, sliding_window=96,
        query_pre_attn_scalar=64.0, sliding_window_pattern=3)
    assert flash_partial_eligible(512 // 4, cfg.head_dim)
    params = gemma3.init_params(cfg, jax.random.PRNGKey(0))
    ids = jax.random.randint(jax.random.PRNGKey(1), (2, 512), 0, 512)
    mask = jnp.ones((2, 512))
    ref = gemma3.forward(cfg, params, ids, attention_mask=mask)
    out = jax.jit(lambda p, i: gemma3.forward(cfg, p, i,
                                              attention_mask=mask,
                                              cp_mesh=mesh))(params, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               atol=2e-4, rtol=2e-4)
