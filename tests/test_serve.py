"""Serving subsystem tests (serve/, DESIGN.md §16).

The correctness anchor is the ACCEPTANCE ORACLE: for any request set,
the continuous-batching loop's per-request greedy outputs — paged KV
pool, static slots, per-slot adapter routing — must be token-identical
to batch-at-a-time generate() with the same adapter per row (contiguous
cache). And the COMPILE-STABILITY invariant: after warmup the engine
holds exactly one prefill + one decode-step executable, reused across
every admission, eviction, and adapter hot-swap (<= 2 new traces
allowed, 0 expected)."""

import dataclasses
import json
import os
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from mobilefinetuner_tpu.core.config import GPT2Config, Gemma3TextConfig
from mobilefinetuner_tpu.core.telemetry import Telemetry, validate_event
from mobilefinetuner_tpu.lora.lora import LoRASpec, init_lora_gemma3
from mobilefinetuner_tpu.models import gemma3, gpt2
from mobilefinetuner_tpu.models.generate import (SampleConfig,
                                                 gemma3_generate,
                                                 gpt2_generate)
from mobilefinetuner_tpu.serve import (AdapterBank, BlockAllocator,
                                       OutOfBlocks, ServeConfig,
                                       ServeEngine, TRASH_BLOCK,
                                       blocks_for)

GPT2_CFG = dataclasses.replace(
    GPT2Config.tiny(vocab_size=211), n_embd=64, n_head=4, n_positions=64,
    n_layer=3, embd_pdrop=0.0, resid_pdrop=0.0, attn_pdrop=0.0)
# sliding_window (6) < prompt+gen so local layers actually truncate
GEMMA_CFG = dataclasses.replace(
    Gemma3TextConfig.tiny(vocab_size=199), hidden_size=48, head_dim=12,
    num_attention_heads=4, num_key_value_heads=2, intermediate_size=96,
    num_hidden_layers=4, sliding_window=6, sliding_window_pattern=3)


@pytest.fixture(scope="module")
def gpt2_params():
    return gpt2.init_params(GPT2_CFG, jax.random.PRNGKey(0))


@pytest.fixture(scope="module")
def gemma_params():
    return gemma3.init_params(GEMMA_CFG, jax.random.PRNGKey(1))


def oracle(family, params, req, lora=None, eos_id=None):
    """Batch-at-a-time generate() with a CONTIGUOUS cache, truncated the
    way the serve loop reports (eos inclusive)."""
    gen = gpt2_generate if family == "gpt2" else gemma3_generate
    config = GPT2_CFG if family == "gpt2" else GEMMA_CFG
    ids = jnp.asarray([req.prompt], jnp.int32)
    cfg = SampleConfig(max_new_tokens=req.max_new_tokens, greedy=True,
                       eos_id=eos_id, pad_id=0)
    row = np.asarray(gen(config, params, ids, jnp.ones_like(ids), cfg,
                         lora=lora))[0].tolist()
    if eos_id is not None and eos_id in row:
        row = row[:row.index(eos_id) + 1]
    return row


def rand_lora(seed, scale=0.05):
    lora = init_lora_gemma3(GEMMA_CFG, LoRASpec(rank=3, alpha=6.0),
                            jax.random.PRNGKey(seed))
    leaves, td = jax.tree.flatten(lora)
    keys = jax.random.split(jax.random.PRNGKey(seed + 50), len(leaves))
    return jax.tree.unflatten(td, [
        l if l.ndim == 0 else scale * jax.random.normal(k, l.shape)
        for l, k in zip(leaves, keys)])


# --------------------------- allocator + config ------------------------------

def test_block_allocator_lifecycle():
    a = BlockAllocator(8)                 # 7 allocatable, 0 reserved
    assert a.free_blocks == 7
    got = a.alloc(3)
    assert len(set(got)) == 3 and TRASH_BLOCK not in got
    b = a.append()
    assert b not in got and a.free_blocks == 3
    with pytest.raises(OutOfBlocks):
        a.alloc(4)
    a.free(got)
    assert a.free_blocks == 6
    with pytest.raises(ValueError):
        a.free(got[:1])                   # double free
    with pytest.raises(ValueError):
        a.free([TRASH_BLOCK])
    with pytest.raises(ValueError):
        BlockAllocator(1)
    assert blocks_for(0, 8) == 0 and blocks_for(1, 8) == 1
    assert blocks_for(8, 8) == 1 and blocks_for(9, 8) == 2


def test_serve_config_validation(gpt2_params):
    with pytest.raises(ValueError, match="multiple"):
        ServeConfig(max_prompt=12, block_T=8).validate()
    # a pool too small for even one worst-case request must fail fast
    # (regression: admission could never fire and drain() spun forever)
    with pytest.raises(ValueError, match="worst-case"):
        ServeConfig(num_blocks=4, block_T=16, max_prompt=64,
                    max_new_tokens=64).validate()
    with pytest.raises(ValueError, match="n_positions"):
        ServeEngine("gpt2", GPT2_CFG, gpt2_params,
                    ServeConfig(block_T=8, max_prompt=56,
                                max_new_tokens=32))
    with pytest.raises(ValueError, match="family"):
        ServeEngine("bert", GPT2_CFG, gpt2_params)


# --------------------------- the acceptance oracle ---------------------------

@pytest.fixture(scope="module")
def gpt2_engine(gpt2_params):
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=3, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=12))
    yield eng
    eng.close()


def test_gpt2_paged_serving_matches_contiguous_generate(gpt2_engine,
                                                        gpt2_params):
    """More requests than slots, ragged prompt lengths: every request's
    greedy tokens equal its own batch-at-a-time generate() run — the
    paged-pool cache is observationally identical to the contiguous
    cache, through continuous-batching admissions and evictions."""
    eng = gpt2_engine
    free0 = eng.alloc.free_blocks
    rng = np.random.default_rng(0)
    prompts = [list(rng.integers(1, 200, n)) for n in (5, 9, 2, 13, 7, 3)]
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    done = eng.drain()
    assert sorted(r.id for r in done) == [r.id for r in reqs]
    for r in done:
        assert r.tokens == oracle("gpt2", gpt2_params, r), f"req {r.id}"
        assert r.ttft_ms is not None and r.tpot_ms is not None
    # eviction returned every page; slots all idle
    assert eng.alloc.free_blocks == free0
    assert eng.idle and not eng.active
    # warmup state for the trace-stability test below
    assert eng.trace_counts["decode_step"] == 1
    assert eng.trace_counts["prefill"] == 1


def test_gpt2_eos_stops_request_early(gpt2_engine, gpt2_params):
    """Declare a request's own second greedy token to be eos: the serve
    loop must stop that request there (emitting the eos), freeing its
    slot, while others run to their cap."""
    eng = gpt2_engine
    rng = np.random.default_rng(3)
    probe = list(rng.integers(1, 200, 6))
    r0 = eng.submit(probe, max_new_tokens=6)
    eng.drain()
    eos = r0.tokens[1]
    eng.eos_id = eos
    try:
        reqs = [eng.submit(probe, max_new_tokens=6),
                eng.submit(list(rng.integers(1, 200, 4)),
                           max_new_tokens=6)]
        done = eng.drain()
        by_id = {r.id: r for r in done}
        want0 = oracle("gpt2", gpt2_params, reqs[0], eos_id=eos)
        assert by_id[reqs[0].id].tokens == want0
        assert by_id[reqs[0].id].tokens[-1] == eos
        assert len(by_id[reqs[0].id].tokens) == 2      # stopped early
        assert len(by_id[reqs[1].id].tokens) <= 6
    finally:
        eng.eos_id = None


def test_trace_stability_across_admissions_evictions_cancel(gpt2_engine):
    """THE compile-stability acceptance: after warmup, admissions with
    new prompt lengths, evictions, mid-flight cancels, and pool
    turnover add <= 2 traces (expected: 0). Shapes are static by
    construction; this pins that no code path smuggles in a dynamic
    one."""
    eng = gpt2_engine
    warm = eng.total_traces()
    rng = np.random.default_rng(7)
    reqs = [eng.submit(list(rng.integers(1, 200, int(n))),
                       max_new_tokens=int(m))
            for n, m in zip((1, 16, 4, 11, 8, 2, 6),
                            (12, 3, 7, 1, 5, 9, 2))]
    eng.step()
    eng.cancel(reqs[2])                   # queued cancel
    eng.step()
    active = eng.active
    if active:
        eng.cancel(active[0])             # mid-flight eviction
    eng.drain()
    assert eng.total_traces() - warm <= 2
    assert eng.total_traces() - warm == 0  # the design target
    assert eng.idle


def test_cancel_frees_pages_and_slot(gpt2_engine):
    eng = gpt2_engine
    free0 = eng.alloc.free_blocks
    r = eng.submit([1, 2, 3, 4, 5], max_new_tokens=10)
    eng.step()
    assert r.state == "active" and eng.alloc.free_blocks < free0
    eng.cancel(r)
    assert r.state == "cancelled"
    assert eng.alloc.free_blocks == free0 and eng.idle
    eng.cancel(r)                          # idempotent


def test_submit_validation(gpt2_engine):
    eng = gpt2_engine
    # round 21: an over-cap prompt is a POLICY reject the caller reads
    # off .state (reason=prompt_too_long), not a ValueError — prompts
    # in (max_prompt, true_cap] are valid chunked admissions when
    # max_prompt_chunked is set, and the closed-set reason taxonomy is
    # how a proxy tells "too long" from "queue full". On this engine
    # (chunking off) the true cap IS max_prompt: 16 queues, 17 rejects.
    at_cap = eng.submit(list(range(1, 17)))         # 16 == cap: queued
    assert at_cap.state == "queued"
    over = eng.submit(list(range(1, 18)))           # 17 > 16: rejected
    assert over.state == "rejected" and over.reason == "prompt_too_long"
    assert not over.blocks and over.tokens == []
    eng.cancel(at_cap)
    with pytest.raises(ValueError, match="max_new_tokens"):
        eng.submit([1, 2], max_new_tokens=99)
    with pytest.raises(ValueError, match="empty"):
        eng.submit([])
    with pytest.raises(RuntimeError, match="bank"):
        eng.submit([1, 2], adapter="nope")  # bankless engine
    # sampling knobs on a greedy engine are a CALLER error (the engine
    # compiled no sampling lanes), as is a nonsense distribution
    with pytest.raises(ValueError, match="sampling"):
        eng.submit([1, 2], temperature=0.7)


def test_prompt_too_long_boundary_with_chunking(gpt2_params):
    """Satellite 5 regression, both sides of the TRUE cap: with
    max_prompt_chunked set, prompts in (max_prompt, true_cap] route to
    chunked admission (NOT rejected — the pre-r21 hard ValueError is
    the bug this pins against), and reason=prompt_too_long fires only
    beyond the true cap."""
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=8, max_prompt_chunked=40))
    rng = np.random.default_rng(17)
    inside = eng.submit(list(rng.integers(1, 200, 40)),   # == true cap
                        max_new_tokens=4)
    assert inside.state == "queued"
    beyond = eng.submit(list(rng.integers(1, 200, 41)),   # cap + 1
                        max_new_tokens=4)
    assert beyond.state == "rejected"
    assert beyond.reason == "prompt_too_long"
    eng.drain()
    assert inside.state == "finished"
    assert inside.tokens == oracle("gpt2", gpt2_params, inside)
    eng.close()


def test_admission_backpressure_tiny_pool(gpt2_params):
    """A pool that fits ~one worst-case request at a time still serves
    everything correctly: admission waits for pages, requests queue,
    outputs stay oracle-equal."""
    # worst case = blocks_for(8 + 8 - 1, 8) = 2 pages; pool has 3
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=4, max_prompt=8,
                    max_new_tokens=8))
    rng = np.random.default_rng(11)
    prompts = [list(rng.integers(1, 200, n)) for n in (6, 8, 3)]
    reqs = [eng.submit(p, max_new_tokens=8) for p in prompts]
    seen_queued_while_active = False
    while not eng.idle:
        eng.step()
        if eng.queue and eng.active:
            seen_queued_while_active = True
    assert seen_queued_while_active       # backpressure actually engaged
    for r in reqs:
        assert r.state == "finished"
        assert r.tokens == oracle("gpt2", gpt2_params, r), f"req {r.id}"
    eng.close()


# --------------------------- multi-adapter + hot-swap ------------------------

@pytest.fixture(scope="module")
def gemma_engine(gemma_params):
    bank = AdapterBank(rand_lora(5), capacity=2)
    eng = ServeEngine(
        "gemma", GEMMA_CFG, gemma_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=32, max_prompt=24,
                    max_new_tokens=10),
        bank=bank)
    yield eng
    eng.close()


def test_gemma_multi_adapter_serving_matches_per_adapter_generate(
        gemma_engine, gemma_params):
    """Slots carrying different adapter ids in the SAME decode step must
    each produce their own adapter's tokens (and base-only requests the
    base model's) — sliding-window layers engaged (window 6 < len)."""
    eng = gemma_engine
    a1, a2 = rand_lora(5), rand_lora(9)
    eng.load_adapter("t1", a1)
    eng.load_adapter("t2", a2)
    rng = np.random.default_rng(1)
    prompts = [list(rng.integers(3, 190, n)) for n in (7, 18, 11, 4)]
    route = ["t1", "t2", None, "t1"]
    trees = {"t1": a1, "t2": a2, None: None}
    reqs = [eng.submit(p, max_new_tokens=9, adapter=a)
            for p, a in zip(prompts, route)]
    done = {r.id: r for r in eng.drain()}
    for req, aname in zip(reqs, route):
        want = oracle("gemma", gemma_params, req, lora=trees[aname])
        assert done[req.id].tokens == want, f"req {req.id} ({aname})"


def test_hot_swap_without_recompile(gemma_engine, gemma_params):
    """Evict a tenant, load a new adapter into the freed slot: requests
    routed to the new name get the NEW weights, base/base-slot rows are
    untouched, and the decode step is NOT retraced."""
    eng = gemma_engine
    warm = eng.total_traces()
    a3 = rand_lora(13)
    eng.evict_adapter("t2")
    slot = eng.load_adapter("t3", a3)
    assert slot == eng.bank.resident["t3"]
    rng = np.random.default_rng(2)
    req = eng.submit(list(rng.integers(3, 190, 12)), max_new_tokens=9,
                     adapter="t3")
    base = eng.submit(list(rng.integers(3, 190, 5)), max_new_tokens=9)
    done = {r.id: r for r in eng.drain()}
    assert done[req.id].tokens == oracle("gemma", gemma_params, req,
                                         lora=a3)
    assert done[base.id].tokens == oracle("gemma", gemma_params, base)
    assert eng.total_traces() - warm == 0


def test_tenancy_protocol_guards(gemma_engine):
    """The hot-swap protocol: in-use residents cannot be replaced or
    evicted; unknown residents cannot be routed to; a full bank refuses
    loads until an eviction frees a slot."""
    eng = gemma_engine
    for name, seed in (("t1", 5), ("t3", 13)):  # self-provision: the
        # module's earlier tests leave these resident, but the guards
        # must also hold when this test runs alone
        if name not in eng.bank.resident:
            eng.load_adapter(name, rand_lora(seed))
    r = eng.submit([3, 4, 5], max_new_tokens=9, adapter="t1")
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.load_adapter("t1", rand_lora(21))
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.evict_adapter("t1")
    with pytest.raises(KeyError, match="not resident"):
        eng.submit([3, 4], adapter="t2")   # evicted in the prior test
    with pytest.raises(OverflowError, match="full"):
        eng.load_adapter("t9", rand_lora(22))   # t1 + t3 fill capacity 2
    eng.cancel(r)
    eng.drain()
    # structure mismatches are refused before touching the bank
    bad = init_lora_gemma3(GEMMA_CFG, LoRASpec(rank=5, alpha=10.0),
                           jax.random.PRNGKey(0))
    eng.evict_adapter("t3")
    with pytest.raises(ValueError, match="rank|shape"):
        eng.load_adapter("t9", bad)


def test_queued_request_pins_its_adapter(gemma_params):
    """Satellite regression (round 14): submit() resolves the bank slot
    at enqueue, so the in-use guard must cover QUEUED requests too —
    otherwise evict/load while a request waits silently serves another
    tenant's weights at admission. Pin both directions: replacement AND
    eviction of a queued-referenced resident are refused, and a
    non-referenced resident still swaps freely while the queue is
    non-empty."""
    bank = AdapterBank(rand_lora(5), capacity=2)
    eng = ServeEngine("gemma", GEMMA_CFG, gemma_params,
                      ServeConfig(num_slots=1, block_T=8, num_blocks=32,
                                  max_prompt=24, max_new_tokens=10),
                      bank=bank)
    a1, a2 = rand_lora(6), rand_lora(7)
    eng.load_adapter("t1", a1)
    eng.load_adapter("t2", a2)
    # one active request occupies the single slot, one QUEUED request
    # references t2 — nothing active routes to t2
    active = eng.submit([3, 4, 5], max_new_tokens=9, adapter="t1")
    eng.step()
    assert active.state == "active"
    queued = eng.submit([6, 7, 8], max_new_tokens=9, adapter="t2")
    assert queued.state == "queued"
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.load_adapter("t2", rand_lora(8))   # replacement refused
    with pytest.raises(RuntimeError, match="in-flight"):
        eng.evict_adapter("t2")                # eviction refused
    done = {r.id: r for r in eng.drain()}
    # the queued tenant got ITS OWN weights, not a swapped-in stranger's
    assert done[queued.id].tokens == oracle("gemma", gemma_params,
                                            queued, lora=a2)
    # with the queue empty the same swap is legal again
    eng.evict_adapter("t2")
    eng.load_adapter("t3", rand_lora(9))
    eng.close()


# --------------------------- telemetry + e2e smoke ---------------------------

def test_enqueue_event_reports_tenant_slot(gemma_params, tmp_path):
    """enqueue/cancel events must attribute a request to its resident
    bank slot — aid resolves at submit, not admission (regression:
    every queued tenant reported adapter slot 0)."""
    stream = str(tmp_path / "t.jsonl")
    bank = AdapterBank(rand_lora(5), capacity=2)
    eng = ServeEngine("gemma", GEMMA_CFG, gemma_params,
                      ServeConfig(num_slots=1, block_T=8, num_blocks=32,
                                  max_prompt=24, max_new_tokens=10),
                      bank=bank, telemetry=Telemetry(stream))
    eng.load_adapter("a", rand_lora(6))
    eng.load_adapter("b", rand_lora(7))            # bank slot 1
    rb = eng.submit([3, 4, 5], max_new_tokens=2, adapter="b")
    r0 = eng.submit([6, 7], max_new_tokens=2)      # base-only
    assert rb.aid == eng.bank.slot("b") == 1
    eng.cancel(rb)
    eng.cancel(r0)
    eng.close()
    with open(stream) as f:
        recs = [json.loads(l) for l in f.read().splitlines()
                if l.strip()]
    ev = {(r["id"], r["phase"]): r for r in recs
          if r["event"] == "request"}
    assert ev[(rb.id, "enqueue")]["adapter"] == 1
    assert ev[(rb.id, "cancel")]["adapter"] == 1
    assert ev[(r0.id, "enqueue")]["adapter"] is None


def test_cpu_e2e_serve_bench_smoke(gpt2_params, tmp_path):
    """Satellite acceptance: a deterministic seeded arrival schedule
    through the REAL serve loop in-process (tools/serve_bench.py's
    engine + load generator), asserting (a) run_start..run_end
    telemetry with schema-valid per-request lifecycle events, (b)
    oracle-equal outputs, (c) the report tool's TTFT/TPOT/req_s
    section."""
    sys.path.insert(0, os.path.join(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))), "tools"))
    import serve_bench
    import telemetry_report
    stream = str(tmp_path / "serve.jsonl")
    eng = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=8),
        telemetry=Telemetry(stream))
    done, elapsed = serve_bench.run_load(
        eng, [], rate=200.0, n_requests=5, seed=4, prompt_lo=2,
        prompt_hi=9, max_new=6)
    row = serve_bench.row_from("tiny_smoke", eng, done, elapsed,
                               rate=200.0, adapters=0)
    eng.close()
    assert len(done) == 5 and row["req_s"] > 0
    assert row["ttft_ms"]["p50"] is not None
    assert row["tpot_ms"]["p99"] is not None
    for r in done:                         # oracle-equal outputs
        assert r.tokens == oracle("gpt2", gpt2_params, r), f"req {r.id}"
    # determinism: same seed -> same prompts -> same tokens
    eng2 = ServeEngine(
        "gpt2", GPT2_CFG, gpt2_params,
        ServeConfig(num_slots=2, block_T=8, num_blocks=32, max_prompt=16,
                    max_new_tokens=8))
    done2, _ = serve_bench.run_load(eng2, [], rate=200.0, n_requests=5,
                                    seed=4, prompt_lo=2, prompt_hi=9,
                                    max_new=6)
    eng2.close()
    assert [r.tokens for r in done2] == [r.tokens for r in done]

    with open(stream) as f:
        recs = [json.loads(l) for l in f.read().splitlines() if l.strip()]
    for rec in recs:
        assert validate_event(rec) is None, (rec, validate_event(rec))
    kinds = [r["event"] for r in recs]
    assert kinds[0] == "run_start" and kinds[-1] == "run_end"
    phases = {}
    for rec in recs:
        if rec["event"] == "request":
            phases.setdefault(rec["id"], []).append(rec["phase"])
    assert len(phases) == 5
    for seq in phases.values():
        assert seq == ["enqueue", "admit", "first_token", "finish"]
    fin = [r for r in recs if r.get("phase") == "finish"]
    assert all(r["ttft_ms"] > 0 and r["new_tokens"] == 6 for r in fin)
    assert all(r["tpot_ms"] is not None for r in fin)

    s = telemetry_report.summarize(recs)
    assert s["requests"]["finished"] == 5
    assert s["requests"]["ttft_ms"]["p50"] > 0
    assert s["requests"]["tpot_ms"]["p95"] is not None
    assert s["requests"]["req_s"] > 0
    assert telemetry_report.main([stream]) == 0
