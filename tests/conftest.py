"""Test harness setup: force an 8-device virtual CPU platform so mesh/FSDP
code paths run without a TPU pod (the analog of the reference's mocked
telemetry testing culture, SURVEY.md §4.6). The subtle platform-forcing
recipe lives in parallel/host_devices.py, shared with __graft_entry__."""

from mobilefinetuner_tpu.parallel.host_devices import force_host_devices

force_host_devices(8)

import jax  # noqa: E402

jax.config.update("jax_default_matmul_precision", "highest")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: excluded from the budgeted tier-1 run (-m 'not slow'); "
        "run explicitly for the full acceptance matrices")
