"""Test harness setup: force an 8-device virtual CPU platform so mesh/FSDP
code paths run without a TPU pod (the analog of the reference's mocked
telemetry testing culture, SURVEY.md §4.6).

Note: the TPU plugin may set jax_platforms programmatically at interpreter
start (shadowing the JAX_PLATFORMS env var), so we force cpu through
jax.config — env vars alone are not enough.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_default_matmul_precision", "highest")
