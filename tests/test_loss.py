"""lm_cross_entropy semantics: internal shift, ignore_index=-100,
token-weighted mean — vs torch.nn.functional.cross_entropy oracle.
(Reference test analog: core/test_lm_loss.cpp, test_ce_grad.cpp.)"""

import jax
import jax.numpy as jnp
import numpy as np
import torch
import torch.nn.functional as F

from mobilefinetuner_tpu.ops.loss import (chunked_lm_cross_entropy,
                                          lm_cross_entropy,
                                          lm_cross_entropy_with_count)


def _torch_ref(logits, labels, ignore_index=-100):
    lt = torch.tensor(logits)[:, :-1, :].reshape(-1, logits.shape[-1])
    lb = torch.tensor(labels)[:, 1:].reshape(-1)
    return F.cross_entropy(lt, lb, ignore_index=ignore_index).item()


def test_matches_torch_with_shift_and_ignore():
    rng = np.random.default_rng(0)
    B, S, V = 3, 17, 29
    logits = rng.normal(size=(B, S, V)).astype(np.float32)
    labels = rng.integers(0, V, size=(B, S)).astype(np.int64)
    labels[0, :5] = -100
    labels[2, -3:] = -100
    ours = float(lm_cross_entropy(jnp.array(logits), jnp.array(labels)))
    ref = _torch_ref(logits, labels)
    assert abs(ours - ref) < 1e-5, (ours, ref)


def test_all_ignored_is_finite():
    logits = jnp.ones((1, 4, 7))
    labels = jnp.full((1, 4), -100)
    assert float(lm_cross_entropy(logits, labels)) == 0.0


def test_count_matches_valid_tokens():
    rng = np.random.default_rng(1)
    B, S, V = 2, 9, 11
    logits = jnp.array(rng.normal(size=(B, S, V)), dtype=jnp.float32)
    labels = np.full((B, S), -100, dtype=np.int64)
    labels[0, 1:4] = 5
    loss, count = lm_cross_entropy_with_count(logits, jnp.array(labels))
    # labels[0, 1:4] -> shifted positions 0..2 are valid
    assert int(count) == 3
    assert np.isfinite(float(loss))


def test_chunked_matches_full():
    rng = np.random.default_rng(2)
    B, S, H, V = 2, 13, 8, 37
    hidden = rng.normal(size=(B, S, H)).astype(np.float32)
    w = rng.normal(size=(V, H)).astype(np.float32)
    labels = rng.integers(0, V, size=(B, S)).astype(np.int64)
    labels[1, :4] = -100
    logits = hidden @ w.T
    full = float(lm_cross_entropy(jnp.array(logits), jnp.array(labels)))
    for nc in (1, 3, 4):
        ch = float(chunked_lm_cross_entropy(jnp.array(hidden), jnp.array(w),
                                            jnp.array(labels), num_chunks=nc))
        assert abs(ch - full) < 1e-5, (nc, ch, full)


def test_chunked_grad_matches_full():
    rng = np.random.default_rng(3)
    B, S, H, V = 2, 8, 4, 19
    hidden = jnp.array(rng.normal(size=(B, S, H)), dtype=jnp.float32)
    w = jnp.array(rng.normal(size=(V, H)), dtype=jnp.float32)
    labels = jnp.array(rng.integers(0, V, size=(B, S)))

    g_full = jax.grad(
        lambda h, w: lm_cross_entropy(h @ w.T, labels))(hidden, w)
    g_ch = jax.grad(
        lambda h, w: chunked_lm_cross_entropy(h, w, labels, num_chunks=2)
    )(hidden, w)
    np.testing.assert_allclose(np.asarray(g_full), np.asarray(g_ch),
                               atol=1e-5)
